"""Multi-tenant QoS: per-tenant quotas, weighted-fair admission, and
tier-aware shed ranking for the serving stack.

One hot tenant at millions-of-users scale can legally starve every
other tenant through a FIFO-plus-deadline admission door while
``requests_lost == 0`` still reads green. This module is the isolation
layer (ROADMAP 3(b)): every request carries a tenant identity, and the
:class:`TenantRegistry` is the single bookkeeper the front-ends and the
fleet router consult before capacity policy even runs:

* **QoS tiers** — ``realtime`` / ``standard`` / ``batch``, ranked for
  shedding (batch sheds first, realtime last; ``admission.py`` breaks
  ties within a tier by deadline slack) and weighted for fairness
  (``tier_weights``, overridable per tenant).
* **token-bucket rate limits** — requests/s and tokens/s with burst
  capacity, per tenant. Rate tokens are consumed by the admission
  ATTEMPT (a rejected attempt still drew from the bucket — retry storms
  are themselves traffic).
* **concurrency caps + KV-block quotas** — in-flight request count and
  projected KV blocks held, charged at admission and released at
  terminal resolution. Fleet copies (hedges, failover re-dispatches)
  each count: two live copies really do hold two replicas' resources.
* **weighted-fair admission** — start-time fair queueing adapted to an
  admit-or-reject front door: each admission advances the tenant's
  virtual token counter by ``cost / weight`` (cost is the
  ``backlog_tokens()``-style prompt+grant estimate). Under contention a
  tenant whose counter leads the floor (the minimum over tenants with
  work in flight) by more than ``fair_share_horizon_tokens`` is turned
  away with a drain-time retry hint — so a flood from one tenant queues
  behind other tenants' traffic rather than ahead of it, while a lone
  tenant on an idle box is never throttled (work-conserving).
* **poison quarantine** — a tenant whose requests repeatedly get
  evicted as tick-poison suspects trips a per-tenant circuit
  (``poison_quarantine_threshold`` evictions inside a
  ``poison_quarantine_s`` window) instead of the whole replica eating
  the blast; its submissions fast-fail with the remaining window as the
  retry-after.
* **label-cardinality guard** — per-tenant metric labels are bounded at
  ``max_tenant_labels`` distinct values; overflow tenants fold into the
  ``"other"`` label so an adversarial tenant-id stream cannot grow the
  telemetry registry without bound. Internal per-tenant state is
  likewise bounded at ``max_tracked_tenants`` (idle tenants evicted
  least-recently-seen first).

Shared fleet-wide: ``FleetRouter`` installs ONE registry on every
replica (including replicas added by ``replace_replica`` /
``add_replica`` / the autoscaler), so concurrency, KV quotas, fairness
counters and quarantines hold across the whole fleet, not per replica.

Config: the ``"tenancy"`` section of the runtime JSON config
(``runtime/config.py:TenancySectionConfig``). Metrics:
``serving_tenant_*`` / ``fleet_tenant_*`` in the README catalog.
Single-threaded like the serving loop that drives it.
"""
from __future__ import annotations

import collections
import time
from typing import Any, Dict, List, Optional, Tuple

from deepspeed_tpu.serving.admission import retry_after_from_backlog
from deepspeed_tpu.utils.logging import logger

#: QoS tiers, ranked for the shed ladder: HIGHER rank sheds FIRST
#: (batch pays before standard pays before realtime).
TIER_REALTIME = "realtime"
TIER_STANDARD = "standard"
TIER_BATCH = "batch"
TIER_RANKS: Dict[str, int] = {TIER_REALTIME: 0, TIER_STANDARD: 1,
                              TIER_BATCH: 2}

#: fair-share weights per tier when a tenant doesn't set its own
#: (higher weight = larger share of contended admission)
DEFAULT_TIER_WEIGHTS: Dict[str, float] = {
    TIER_REALTIME: 8.0, TIER_STANDARD: 4.0, TIER_BATCH: 1.0}

#: tenant name untagged traffic resolves to (keeps the pre-tenancy API
#: back-compatible: a submit() with no tenant behaves as one shared
#: default tenant with no quotas unless the config says otherwise)
DEFAULT_TENANT = "default"

#: metric label that over-cap tenants fold into
OTHER_LABEL = "other"

#: tenancy-scoped rejection reasons (structured ``Overloaded.reason``
#: values; every one carries a tenant-scoped retry-after)
REASON_TENANT_RATE = "tenant_rate_limited"
REASON_TENANT_CONCURRENCY = "tenant_concurrency"
REASON_TENANT_KV = "tenant_kv_quota"
REASON_FAIR_SHARE = "tenant_fair_share"
REASON_TENANT_QUARANTINED = "tenant_quarantined"


class TokenBucket:
    """Deterministic token bucket (injectable timestamps — callers pass
    ``now``). ``rate <= 0`` means unlimited."""

    __slots__ = ("rate", "burst", "level", "t")

    def __init__(self, rate: float, burst: float):
        self.rate = float(rate)
        self.burst = max(float(burst), 1.0)
        self.level = self.burst
        self.t: Optional[float] = None

    def _refill(self, now: float) -> None:
        if self.t is not None and now > self.t:
            self.level = min(self.burst,
                             self.level + (now - self.t) * self.rate)
        self.t = now

    def peek(self, n: float, now: float) -> bool:
        """Would ``take(n)`` succeed right now?"""
        if self.rate <= 0:
            return True
        self._refill(now)
        return self.level >= n

    def take(self, n: float, now: float) -> bool:
        if self.rate <= 0:
            return True
        self._refill(now)
        if self.level < n:
            return False
        self.level -= n
        return True

    def retry_after(self, n: float, now: float) -> float:
        """Seconds until ``n`` tokens will be available (0 when they
        already are)."""
        if self.rate <= 0:
            return 0.0
        self._refill(now)
        deficit = min(n, self.burst) - self.level
        return max(0.0, deficit / self.rate)


class _TenantState:
    """Mutable per-tenant bookkeeping (quota charges, fairness counter,
    quarantine clock). Bounded by ``max_tracked_tenants`` via LRU
    eviction of idle tenants."""

    __slots__ = ("name", "req_bucket", "tok_bucket", "inflight",
                 "kv_blocks", "vtime", "poison_marks", "quarantined_until",
                 "last_seen")

    def __init__(self, name: str, req_bucket: TokenBucket,
                 tok_bucket: TokenBucket):
        self.name = name
        self.req_bucket = req_bucket
        self.tok_bucket = tok_bucket
        self.inflight = 0          # live request copies charged
        self.kv_blocks = 0         # projected KV blocks held
        self.vtime = 0.0           # fair-queueing virtual token counter
        self.poison_marks: collections.deque = collections.deque()
        self.quarantined_until = 0.0
        self.last_seen = 0.0


class TenantRegistry:
    """Per-tenant quota, fairness, and quarantine bookkeeper shared by
    the serving front-ends and the fleet router. ``config`` is a
    ``TenancySectionConfig``, a plain dict of its keys, or None
    (defaults: one unlimited ``standard``-tier tenant namespace);
    ``clock`` is injectable for deterministic tests."""

    def __init__(self, config=None, clock=time.monotonic):
        from deepspeed_tpu.runtime.config import (
            TenancySectionConfig,
            TenantQuotaConfig,
        )
        from deepspeed_tpu.runtime.config_utils import config_from_dict

        if config is None:
            config = TenancySectionConfig()
        elif isinstance(config, dict):
            config = config_from_dict(TenancySectionConfig, config,
                                      path="tenancy.")
        else:
            config.validate()
        self.cfg = config
        self.clock = clock
        # configured per-tenant quota specs (validated at parse time);
        # unknown tenants share one default-tier unlimited spec
        self._specs: Dict[str, Any] = {}
        for name, entry in sorted(config.tenants.items()):
            spec = entry if not isinstance(entry, dict) else \
                config_from_dict(TenantQuotaConfig, entry,
                                 path=f"tenancy.tenants.{name}.")
            self._specs[name] = spec
        self._default_spec = TenantQuotaConfig(tier=config.default_tier)
        self._states: Dict[str, _TenantState] = {}
        self._vlast = 0.0   # fairness floor holdover while idle
        # label-cardinality guard: configured tenants get their own
        # label first (they are the ones operators alert on); dynamic
        # tenants claim remaining slots first-seen, overflow folds into
        # OTHER_LABEL
        self._labels: Dict[str, str] = {}
        for name in [DEFAULT_TENANT] + sorted(self._specs):
            if len(self._labels) < config.max_tenant_labels:
                self._labels[name] = name

    @classmethod
    def ensure(cls, tenancy, clock=time.monotonic) -> "TenantRegistry":
        """Coerce None / dict / section config / registry to a registry
        (an existing registry passes through so it can be shared)."""
        if isinstance(tenancy, TenantRegistry):
            return tenancy
        return cls(tenancy, clock=clock)

    # ------------------------------------------------------------------ #
    # identity
    # ------------------------------------------------------------------ #
    def resolve(self, tenant: Optional[str]) -> str:
        """Canonical tenant name: untagged traffic maps to the default
        tenant (back-compat for every pre-tenancy caller)."""
        if tenant is None or tenant == "":
            return DEFAULT_TENANT
        return str(tenant)

    def spec(self, tenant: str):
        return self._specs.get(tenant, self._default_spec)

    def tier(self, tenant: str) -> str:
        return self.spec(tenant).tier

    def tier_rank(self, tenant: str) -> int:
        return TIER_RANKS[self.spec(tenant).tier]

    def weight(self, tenant: str) -> float:
        qcfg = self.spec(tenant)
        if qcfg.weight > 0:
            return qcfg.weight
        return self.cfg.tier_weights.get(
            qcfg.tier, DEFAULT_TIER_WEIGHTS[qcfg.tier])

    def label(self, tenant: str) -> str:
        """Metric label for ``tenant`` — bounded cardinality: past
        ``max_tenant_labels`` distinct values new tenants fold into
        ``"other"`` (the registry itself stays bounded regardless)."""
        tenant = self.resolve(tenant)
        lbl = self._labels.get(tenant)
        if lbl is not None:
            return lbl
        if len(self._labels) < self.cfg.max_tenant_labels:
            self._labels[tenant] = tenant
            return tenant
        return OTHER_LABEL

    def known_tenants(self) -> List[str]:
        """Tenants with live bookkeeping (configured or seen)."""
        return sorted(set(self._specs) | set(self._states)
                      | {DEFAULT_TENANT})

    # ------------------------------------------------------------------ #
    # state bookkeeping
    # ------------------------------------------------------------------ #
    def _state(self, tenant: str) -> _TenantState:
        st = self._states.get(tenant)
        if st is None:
            if len(self._states) >= self.cfg.max_tracked_tenants:
                self._evict_idle_state()
            qcfg = self.spec(tenant)
            st = _TenantState(
                tenant,
                TokenBucket(qcfg.requests_per_s,
                            qcfg.burst_requests or qcfg.requests_per_s),
                TokenBucket(qcfg.tokens_per_s,
                            qcfg.burst_tokens or qcfg.tokens_per_s))
            self._states[tenant] = st
        st.last_seen = self.clock()
        return st

    def _evict_idle_state(self) -> None:
        """Drop the least-recently-seen tenant with nothing in flight —
        the bound that keeps an adversarial tenant-id stream from
        growing registry memory. Tenants with live charges are never
        evicted (their count is bounded by the concurrency they hold)."""
        idle = [st for st in self._states.values()
                if st.inflight == 0 and st.kv_blocks == 0]
        if not idle:
            return
        victim = min(idle, key=lambda st: (st.last_seen, st.name))
        del self._states[victim.name]

    def _vfloor(self) -> float:
        """System virtual time: the minimum fairness counter over
        tenants with work in flight. With nothing in flight the floor
        holds at the last computed value (an idle system must not wind
        fairness history backward)."""
        active = [st.vtime for st in self._states.values()
                  if st.inflight > 0]
        if active:
            self._vlast = min(active)
        return self._vlast

    # ------------------------------------------------------------------ #
    # admission gates
    # ------------------------------------------------------------------ #
    def quarantine_remaining_s(self, tenant: str,
                               now: Optional[float] = None) -> float:
        st = self._states.get(tenant)
        if st is None:
            return 0.0
        if now is None:
            now = self.clock()
        return max(0.0, st.quarantined_until - now)

    def fleet_gate(self, tenant: str, cost_tokens: int,
                   token_seconds: float
                   ) -> Optional[Tuple[str, float, str]]:
        """Client-facing gate the FLEET applies once per submission:
        quarantine + rate buckets (debited here — replica-level
        re-dispatches of the same request must not re-draw). Returns
        ``(reason, retry_after_s, detail)`` or None (pass)."""
        return self._gate(tenant, cost_tokens, blocks=0,
                          token_seconds=token_seconds, contended=False,
                          charge_rate=True, resource_checks=False)

    def admission_gate(self, tenant: str, cost_tokens: int, blocks: int,
                       token_seconds: float, contended: bool,
                       charge_rate: bool = True
                       ) -> Optional[Tuple[str, float, str]]:
        """Replica-level gate the front-end applies before capacity
        policy: quarantine, rate buckets (skipped when the fleet already
        charged them — ``charge_rate=False``), concurrency cap, KV-block
        quota, and — only under ``contended`` capacity — the
        weighted-fair share check. Returns ``(reason, retry_after_s,
        detail)`` or None (pass)."""
        return self._gate(tenant, cost_tokens, blocks, token_seconds,
                          contended, charge_rate, resource_checks=True)

    def _gate(self, tenant: str, cost_tokens: int, blocks: int,
              token_seconds: float, contended: bool, charge_rate: bool,
              resource_checks: bool
              ) -> Optional[Tuple[str, float, str]]:
        now = self.clock()
        st = self._state(tenant)
        qcfg = self.spec(tenant)
        remaining = st.quarantined_until - now
        if remaining > 0:
            return (REASON_TENANT_QUARANTINED, remaining,
                    f"tenant {tenant!r} quarantined for poisoning ticks")
        if charge_rate:
            req_ok = st.req_bucket.peek(1, now)
            tok_ok = st.tok_bucket.peek(cost_tokens, now)
            if not (req_ok and tok_ok):
                retry = max(st.req_bucket.retry_after(1, now),
                            st.tok_bucket.retry_after(cost_tokens, now))
                which = "requests/s" if not req_ok else "tokens/s"
                return (REASON_TENANT_RATE, max(retry, 0.001),
                        f"tenant {tenant!r} over its {which} limit")
            st.req_bucket.take(1, now)
            st.tok_bucket.take(cost_tokens, now)
        if not resource_checks:
            return None
        if qcfg.max_concurrent > 0 and st.inflight >= qcfg.max_concurrent:
            retry = retry_after_from_backlog(cost_tokens, token_seconds)
            return (REASON_TENANT_CONCURRENCY, retry,
                    f"tenant {tenant!r} at its concurrency cap "
                    f"({st.inflight}/{qcfg.max_concurrent})")
        if qcfg.max_kv_blocks > 0 \
                and st.kv_blocks + blocks > qcfg.max_kv_blocks:
            retry = retry_after_from_backlog(
                max(cost_tokens, st.kv_blocks), token_seconds)
            return (REASON_TENANT_KV, retry,
                    f"tenant {tenant!r} over its KV-block quota "
                    f"({st.kv_blocks}+{blocks} > {qcfg.max_kv_blocks})")
        if contended:
            lead = max(0.0, st.vtime - self._vfloor())
            if lead > self.cfg.fair_share_horizon_tokens:
                excess = (lead - self.cfg.fair_share_horizon_tokens) \
                    * self.weight(tenant)
                retry = retry_after_from_backlog(
                    int(excess) + 1, token_seconds)
                return (REASON_FAIR_SHARE, retry,
                        f"tenant {tenant!r} over its fair share under "
                        f"contention (lead {lead:.0f} weighted tokens)")
        return None

    # ------------------------------------------------------------------ #
    # charges
    # ------------------------------------------------------------------ #
    def charge_admit(self, tenant: str, cost_tokens: int,
                     blocks: int) -> None:
        """Record an admitted copy: concurrency + KV charge, and the
        fairness counter advances by cost over weight (an idle tenant
        re-enters at the floor — fairness credit does not bank)."""
        st = self._state(tenant)
        if st.inflight == 0:
            st.vtime = max(st.vtime, self._vfloor())
        st.vtime += cost_tokens / self.weight(tenant)
        st.inflight += 1
        st.kv_blocks += blocks

    def transfer_inflight(self, tenant: str, blocks: int) -> None:
        """Re-home an already-admitted copy's charges into THIS registry
        (frontend adoption during fleet install / rolling restart) —
        no rate debit, no fairness advance: the work was already paid
        for where it was admitted."""
        st = self._state(tenant)
        st.inflight += 1
        st.kv_blocks += blocks

    def release(self, tenant: str, blocks: int) -> None:
        """A charged copy reached a terminal state: return its
        concurrency slot and KV-block charge."""
        st = self._states.get(tenant)
        if st is None:
            return
        st.inflight = max(0, st.inflight - 1)
        st.kv_blocks = max(0, st.kv_blocks - blocks)

    # ------------------------------------------------------------------ #
    # poison quarantine
    # ------------------------------------------------------------------ #
    def record_poison(self, tenant: str) -> bool:
        """A request of this tenant was evicted as a tick-poison
        suspect. ``poison_quarantine_threshold`` evictions inside a
        ``poison_quarantine_s`` window trip the per-tenant circuit;
        returns True exactly when the quarantine newly trips."""
        now = self.clock()
        st = self._state(tenant)
        window = self.cfg.poison_quarantine_s
        st.poison_marks.append(now)
        while st.poison_marks and st.poison_marks[0] < now - window:
            st.poison_marks.popleft()
        if len(st.poison_marks) >= self.cfg.poison_quarantine_threshold \
                and st.quarantined_until <= now:
            st.quarantined_until = now + self.cfg.poison_quarantine_s
            st.poison_marks.clear()
            logger.warning(
                f"tenancy: quarantining tenant {tenant!r} for "
                f"{self.cfg.poison_quarantine_s}s after repeated "
                "poison evictions")
            return True
        return False

    # ------------------------------------------------------------------ #
    # introspection
    # ------------------------------------------------------------------ #
    def snapshot(self) -> Dict[str, Dict[str, Any]]:
        """Per-tenant bookkeeping view (tests, bench, flight dumps)."""
        floor = self._vfloor()
        out: Dict[str, Dict[str, Any]] = {}
        for name, st in sorted(self._states.items()):
            out[name] = {
                "tier": self.tier(name),
                "inflight": st.inflight,
                "kv_blocks": st.kv_blocks,
                "vtime_lead": max(0.0, st.vtime - floor),
                "quarantine_remaining_s":
                    self.quarantine_remaining_s(name),
            }
        return out
