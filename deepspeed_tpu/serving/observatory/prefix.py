"""KV/prefix opportunity metering — pricing prefix reuse BEFORE it's built.

ROADMAP item 3a (prefix-cache-aware routing) only pays if real traffic
actually shares prompt prefixes at block granularity. This module
measures that opportunity on today's fleet, with no routing changes:

* :class:`PrefixMeter` — hashes every submitted prompt block-by-block
  (chained, so a block only matches when its whole prefix matched) and
  counts how many blocks a block-granular prefix cache WOULD have
  served from cache (``fleet_prefix_blocks_total{outcome}``).
* :func:`pool_stats` — over the live paged KV pools: how many allocated
  blocks hold identical chained prefixes (block-sharing potential if
  blocks were refcounted, vLLM-style) and how much of the allocated
  pool is tail fragmentation (partially-filled last blocks).
* :func:`decode_wire_stats` — folds ``FastGenEngine.collective_ledger``
  into fleet terms: decode-tick wire bytes, the denominator EQuARX-style
  wire compression (item 3d) must shrink.
"""
from __future__ import annotations

import collections
import zlib
from typing import Any, Dict, Iterable, List, Optional, Sequence

from deepspeed_tpu import telemetry
from deepspeed_tpu.utils.logging import logger


def _chain_hashes(tokens: Sequence[int], block_size: int) -> List[int]:
    """Chained per-block hashes of ``tokens``: hash[i] covers blocks
    0..i, so equal hash[i] means the ENTIRE prefix up to block i is
    equal — the lookup a block-granular prefix cache would perform.
    Only full blocks count (a partial tail block can't be shared)."""
    out: List[int] = []
    h = 0
    for start in range(0, len(tokens) - block_size + 1, block_size):
        block = tokens[start:start + block_size]
        h = zlib.crc32(repr(tuple(block)).encode(), h)
        out.append(h)
    return out


class PrefixMeter:
    """Would-be prefix-hit accounting over submitted prompts.

    ``observe_prompt`` is called once per fleet submission (failover and
    hedge re-dispatches are the SAME offered prompt, so the fleet hooks
    it at its front door only). A seen-set of chained block hashes,
    bounded LRU at ``max_tracked`` entries, stands in for the cache a
    real implementation would keep; ``hit_rate`` is then the fraction
    of offered full blocks that cache would have served."""

    def __init__(self, max_tracked: int = 65536):
        self.max_tracked = max(1, int(max_tracked))
        self._seen: "collections.OrderedDict[int, None]" = \
            collections.OrderedDict()
        self.hit_blocks = 0
        self.total_blocks = 0
        self.prompts = 0
        self._tm_blocks = telemetry.counter(
            "fleet_prefix_blocks_total",
            "full prompt blocks offered to the fleet, by whether a "
            "block-granular prefix cache would have served them "
            "(outcome=hit / miss) — the measured prefix-reuse "
            "opportunity that prices prefix-aware routing")
        self._tm_rate = telemetry.gauge(
            "fleet_prefix_hit_rate",
            "cumulative would-be prefix-cache hit rate over offered "
            "full prompt blocks")

    def observe_prompt(self, prompt: Sequence[int],
                       block_size: int) -> int:
        """Meter one offered prompt; returns the would-be hit count."""
        if block_size <= 0:
            return 0
        self.prompts += 1
        hits = 0
        for h in _chain_hashes(prompt, block_size):
            self.total_blocks += 1
            if h in self._seen:
                self._seen.move_to_end(h)
                hits += 1
                self._tm_blocks.inc(outcome="hit")
            else:
                self._seen[h] = None
                while len(self._seen) > self.max_tracked:
                    self._seen.popitem(last=False)
                self._tm_blocks.inc(outcome="miss")
        self.hit_blocks += hits
        if self.total_blocks:
            self._tm_rate.set(self.hit_blocks / self.total_blocks)
        return hits

    def hit_rate(self) -> Optional[float]:
        if self.total_blocks == 0:
            return None
        return self.hit_blocks / self.total_blocks

    def snapshot(self) -> Dict[str, Any]:
        rate = self.hit_rate()
        return {
            "prompts": self.prompts,
            "total_blocks": self.total_blocks,
            "hit_blocks": self.hit_blocks,
            "hit_rate": round(rate, 6) if rate is not None else None,
            "tracked_prefixes": len(self._seen),
        }


def pool_stats(engines: Iterable) -> Dict[str, Any]:
    """Sharing potential + fragmentation over the LIVE paged KV pools.

    * ``sharing_potential``: of the full prompt blocks currently held
      by live sequences, the fraction that duplicates another live
      sequence's chained prefix block — blocks a refcounted
      block-sharing pool would free today.
    * ``fragmentation``: of the token capacity in allocated blocks, the
      fraction sitting empty in partially-filled tail blocks.

    Publishes ``fleet_prefix_sharing_potential`` and
    ``fleet_kv_fragmentation`` gauges and returns the numbers."""
    seen: Dict[int, int] = {}
    total_full = 0
    dup_full = 0
    alloc_blocks = 0
    used_tokens = 0
    free_blocks = 0
    n_blocks = 0
    for eng in engines:
        bs = eng.block_size
        alloc = getattr(eng, "allocator", None)
        if alloc is not None:
            free_blocks += alloc.free_blocks
            n_blocks += max(0, alloc.n_blocks - 1)   # block 0 = trash
        for seq in eng.seqs.values():
            if seq.done:
                continue
            tokens = list(seq.prompt) + list(seq.generated)
            alloc_blocks += len(seq.blocks)
            used_tokens += len(tokens)
            for h in _chain_hashes(tokens, bs):
                total_full += 1
                count = seen.get(h, 0)
                if count:
                    dup_full += 1
                seen[h] = count + 1
    capacity_tokens = 0
    for eng in engines:
        # re-walk for capacity so a heterogeneous fleet (mixed block
        # sizes) prices each sequence against ITS engine's block size
        for seq in eng.seqs.values():
            if not seq.done:
                capacity_tokens += len(seq.blocks) * eng.block_size
    sharing = dup_full / total_full if total_full else 0.0
    frag = (1.0 - used_tokens / capacity_tokens) if capacity_tokens else 0.0
    telemetry.gauge(
        "fleet_prefix_sharing_potential",
        "fraction of live full prompt blocks duplicating another live "
        "sequence's chained prefix — blocks a refcounted sharing pool "
        "would free right now").set(sharing)
    telemetry.gauge(
        "fleet_kv_fragmentation",
        "fraction of allocated KV token capacity sitting empty in "
        "partially-filled tail blocks").set(frag)
    return {
        "live_full_blocks": total_full,
        "duplicate_blocks": dup_full,
        "sharing_potential": round(sharing, 6),
        "allocated_blocks": alloc_blocks,
        "fragmentation": round(frag, 6),
        "pool_blocks": n_blocks,
        "free_blocks": free_blocks,
    }


def decode_wire_stats(engines: Iterable) -> Dict[str, Any]:
    """Fold each engine's decode-tick collective ledger into fleet
    rows: total wire bytes one tick moves, by collective kind. Engines
    whose ledger can't lower (no compiled program on this backend)
    contribute zero rather than failing the report — single-replica
    serving legitimately ledgers empty."""
    total_bytes = 0
    by_kind: Dict[str, int] = {}
    ledgered = 0
    unledgered = 0
    for eng in engines:
        try:
            ledger = eng.collective_ledger()
        except Exception as exc:
            # a backend that can't lower the decode tick contributes
            # zero wire bytes — counted + logged, never fatal
            unledgered += 1
            logger.debug(f"decode-wire ledger unavailable: {exc}")
            continue
        ledgered += 1
        total_bytes += ledger.total_bytes()
        for kind, row in ledger.totals_by_kind().items():
            by_kind[kind] = by_kind.get(kind, 0) + int(row["bytes"])
    telemetry.gauge(
        "fleet_decode_wire_bytes_per_tick",
        "bytes the fleet's compiled decode-tick collectives move per "
        "tick, summed across replicas — the denominator decode-wire "
        "compression must shrink").set(total_bytes)
    return {
        "engines_ledgered": ledgered,
        "engines_unledgered": unledgered,
        "wire_bytes_per_tick": total_bytes,
        "by_kind": by_kind,
    }
