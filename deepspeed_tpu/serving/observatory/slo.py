"""SLO burn-rate engine over the serving fleet.

Declarative objectives (validated ``"slo"`` config section) evaluated
SRE-workbook style: each objective burns its error budget at

    burn = bad_fraction / (1 - target)

and an alert FIRES only while BOTH a fast and a slow sliding window
burn faster than ``burn_rate_threshold`` — the fast window makes the
alert responsive, the slow window keeps one bad tick from paging — and
CLEARS as soon as either window recovers. All quantile sources are the
telemetry histograms' sliding-window views (never process-lifetime
state), so a slow startup burst ages out of the verdict instead of
tainting it forever.

Observe-only by default: alert state is exported as gauges and the
``/slo`` endpoint, and only becomes a ``FleetAutoscaler`` scale-out
reason (``autoscale_on_burn``) or an admission-ladder shed hint
(``shed_on_burn``) when the operator opts in — the chaos acceptance
test pins that the default changes no decision.
"""
from __future__ import annotations

import dataclasses
import time
from typing import Any, Dict, List, Optional

from deepspeed_tpu import telemetry

#: histogram each objective metric reads (fleet-wide scope); per-tenant
#: TTFT reads the per-tenant histogram the frontends already export
_FLEET_TTFT = "fleet_ttft_seconds"
_TENANT_TTFT = "serving_tenant_ttft_seconds"
_DECODE_TOK = "fastgen_decode_token_seconds"


@dataclasses.dataclass
class SloAlert:
    """One objective's evaluated state at an instant."""
    name: str
    metric: str
    tenant: str
    target: float
    threshold_s: float
    firing: bool
    fast_burn: float
    slow_burn: float
    fast_window_s: float
    slow_window_s: float
    has_data: bool            # any observation inside the slow window
    since: Optional[float] = None   # clock stamp of the current firing

    def as_dict(self) -> Dict[str, Any]:
        return dataclasses.asdict(self)


class SloEngine:
    """Evaluates the configured objectives against a
    :class:`~.ledger.FleetObservatory` (TTFT + availability sources) and
    the process registry (decode-latency + per-tenant TTFT sources).

    ``tenancy`` maps objective tenant names through the cardinality
    guard so an objective on an over-cap tenant reads the same
    ``"other"`` series the frontends recorded. Single-threaded, driven
    by ``FleetRouter.run_tick``.
    """

    def __init__(self, config=None, observatory=None, tenancy=None,
                 clock=time.monotonic):
        from deepspeed_tpu.runtime.config import SloSectionConfig
        from deepspeed_tpu.runtime.config_utils import config_from_dict

        if config is None:
            config = SloSectionConfig()
        elif isinstance(config, dict):
            config = config_from_dict(SloSectionConfig, config, path="slo.")
        else:
            config.validate()
        self.cfg = config
        self.objectives = config.parsed_objectives()
        self.observatory = observatory
        self.tenancy = tenancy
        self.clock = clock
        self._alerts: Dict[str, SloAlert] = {}
        # the exact callable handed to the exposition layer — unregister
        # matches by identity, and each ``self.state`` access binds a
        # fresh method object, so the registered one must be kept
        self._registered_provider = None
        self._tm_burn = telemetry.gauge(
            "fleet_slo_burn_rate",
            "error-budget burn rate per objective and window (1.0 = "
            "burning exactly the budget; the alert threshold is "
            "slo.burn_rate_threshold)")
        self._tm_firing = telemetry.gauge(
            "fleet_slo_alert_firing",
            "1 while an objective's burn-rate alert fires (both windows "
            "over threshold), 0 otherwise")
        self._tm_transitions = telemetry.counter(
            "fleet_slo_alert_transitions_total",
            "burn-rate alert edges per objective (to=firing / to=clear) "
            "— a fired-and-cleared episode is exactly one of each")

    # ------------------------------------------------------------ burn
    def _tenant_label(self, tenant: str) -> str:
        if self.tenancy is not None:
            return self.tenancy.label(self.tenancy.resolve(tenant))
        return tenant

    def _bad_fraction(self, ocfg, window_s: float):
        """``(bad_fraction, has_data)`` for one objective over one
        window. No data burns nothing: an idle fleet is not an outage."""
        if ocfg.metric == "availability":
            if self.observatory is None:
                return 0.0, False
            avail = self.observatory.availability(
                window_s, tenant=ocfg.tenant or None)
            if avail is None:
                return 0.0, False
            return 1.0 - avail, True
        if ocfg.metric == "ttft_p99_s" and ocfg.tenant:
            hist = telemetry.get_registry().get(_TENANT_TTFT)
            if hist is None:
                return 0.0, False
            bad = hist.windowed_bad_fraction(
                ocfg.threshold_s, window_s=window_s,
                tenant=self._tenant_label(ocfg.tenant))
        elif ocfg.metric == "ttft_p99_s":
            if self.observatory is None:
                return 0.0, False
            bad = self.observatory.ttft_bad_fraction(
                ocfg.threshold_s, window_s=window_s)
        else:   # decode_token_p99_s
            hist = telemetry.get_registry().get(_DECODE_TOK)
            if hist is None:
                return 0.0, False
            bad = hist.windowed_bad_fraction(
                ocfg.threshold_s, window_s=window_s)
        if bad is None:
            return 0.0, False
        return bad[0], True

    def _burn(self, ocfg, window_s: float):
        bad, has_data = self._bad_fraction(ocfg, window_s)
        return bad / (1.0 - ocfg.target), has_data

    # ------------------------------------------------------------ drive
    def evaluate(self) -> List[SloAlert]:
        """One evaluation pass over every objective; exports gauges and
        counts firing/clear transitions. Cheap enough for every fleet
        tick (a handful of window merges per objective)."""
        if not self.cfg.enabled:
            return []
        out: List[SloAlert] = []
        for ocfg in self.objectives:
            fast, fast_data = self._burn(ocfg, self.cfg.fast_window_s)
            slow, slow_data = self._burn(ocfg, self.cfg.slow_window_s)
            firing = (fast > self.cfg.burn_rate_threshold
                      and slow > self.cfg.burn_rate_threshold)
            prev = self._alerts.get(ocfg.name)
            since = prev.since if prev is not None else None
            if firing and (prev is None or not prev.firing):
                since = self.clock()
                self._tm_transitions.inc(objective=ocfg.name, to="firing")
            elif not firing:
                if prev is not None and prev.firing:
                    self._tm_transitions.inc(objective=ocfg.name, to="clear")
                since = None
            alert = SloAlert(
                name=ocfg.name, metric=ocfg.metric, tenant=ocfg.tenant,
                target=ocfg.target, threshold_s=ocfg.threshold_s,
                firing=firing, fast_burn=round(fast, 6),
                slow_burn=round(slow, 6),
                fast_window_s=self.cfg.fast_window_s,
                slow_window_s=self.cfg.slow_window_s,
                has_data=fast_data or slow_data, since=since)
            self._alerts[ocfg.name] = alert
            self._tm_burn.set(alert.fast_burn, objective=ocfg.name,
                              window="fast")
            self._tm_burn.set(alert.slow_burn, objective=ocfg.name,
                              window="slow")
            self._tm_firing.set(1.0 if firing else 0.0, objective=ocfg.name)
            out.append(alert)
        return out

    # ------------------------------------------------------------ reads
    def alerts(self) -> List[SloAlert]:
        return [self._alerts[o.name] for o in self.objectives
                if o.name in self._alerts]

    def any_firing(self) -> bool:
        return any(a.firing for a in self._alerts.values())

    def worst_burn_rate(self) -> float:
        worst = 0.0
        for a in self._alerts.values():
            worst = max(worst, a.fast_burn, a.slow_burn)
        return worst

    # the two config-gated actions — both inert by default
    def wants_scale_out(self) -> bool:
        """True when a firing objective should become the autoscaler's
        ``slo_burn`` scale-out reason (requires ``autoscale_on_burn``)."""
        return self.cfg.autoscale_on_burn and self.any_firing()

    def shed_tighten(self) -> float:
        """Fractional tightening of the admission queue bound while any
        objective fires (0.0 unless ``shed_on_burn``)."""
        if self.cfg.shed_on_burn and self.any_firing():
            return self.cfg.shed_tighten_frac
        return 0.0

    def state(self) -> Dict[str, Any]:
        """JSON-ready engine state: the ``/slo`` endpoint's body and the
        fleet-report CLI's live source."""
        body: Dict[str, Any] = {
            "enabled": self.cfg.enabled,
            "objectives_configured": len(self.cfg.objectives),
            "burn_rate_threshold": self.cfg.burn_rate_threshold,
            "fast_window_s": self.cfg.fast_window_s,
            "slow_window_s": self.cfg.slow_window_s,
            "objectives": [dataclasses.asdict(o) for o in self.objectives],
            "alerts": [a.as_dict() for a in self.alerts()],
            "any_firing": self.any_firing(),
            "worst_burn_rate": round(self.worst_burn_rate(), 6),
            "actions": {
                "autoscale_on_burn": self.cfg.autoscale_on_burn,
                "shed_on_burn": self.cfg.shed_on_burn,
                "shed_tighten": self.shed_tighten(),
            },
        }
        if self.observatory is not None:
            body["goodput"] = self.observatory.snapshot()
            p99 = self.observatory.ttft_quantile(0.99)
            if p99 is not None:
                body["ttft_p99_s"] = round(p99, 6)
        return body

    # ------------------------------------------------------------ expose
    def register_endpoint(self) -> None:
        """Serve :meth:`state` at ``/slo`` on the exposition server
        (idempotent; last registrant wins process-wide, matching the
        one-exposition-server-per-process model)."""
        from deepspeed_tpu.telemetry import exposition

        self._registered_provider = self.state
        exposition.register_slo_provider(self._registered_provider)

    def close(self) -> None:
        if self._registered_provider is not None:
            from deepspeed_tpu.telemetry import exposition

            exposition.unregister_slo_provider(self._registered_provider)
            self._registered_provider = None
