"""Request-lifecycle ledger + goodput accounting for the serving fleet.

Every request that crosses the fleet door gets ONE
:class:`RequestLifecycle` record tracking what the per-uid trace
(telemetry/tracing) narrates, but structured: queue-wait, admission
verdict, prefill/decode token counts, every failover/hedge/migration
hop, tenant, terminal state. Terminal records land in a bounded ring
(``slo.ledger_size``) the SLO engine's availability objectives and the
``fleet-report`` CLI read.

Goodput accounting is the second half: the fleet computes tokens it
never delivers — a hedge loser's stream, a failover's prefill replay of
carried tokens, a shed or poison-evicted request's partial output. Each
computation quantum is counted exactly once, at the moment its fate is
known, into ``fleet_goodput_tokens_total`` (delivered) or
``fleet_wasted_tokens_total{reason}`` (discarded), and every count also
lands in ``fleet_computed_tokens_total`` — so

    goodput + wasted == computed

holds by construction, and the reconciliation is an invariant the bench
validator and the chaos tests can pin rather than a report-time hope.
One LOGICAL token may contribute several quanta (decoded on a lost
replica, then prefill-replayed on the next): that is precisely the
waste this ledger exists to make visible.
"""
from __future__ import annotations

import collections
import dataclasses
import time
from typing import Any, Dict, List, Optional

from deepspeed_tpu import telemetry

#: the closed set of waste attributions — the bench validator and the
#: metric catalog enumerate exactly these
WASTE_REASONS = ("hedge_lost", "failover_replay", "evicted", "shed")

#: sliding-window shape for the fleet TTFT histogram: 10 s intervals
#: over 10 min, so the SLO engine's slow window (default 300 s) always
#: fits inside what the ring retains
TTFT_WINDOW_S = 600.0
TTFT_WINDOW_INTERVALS = 60


@dataclasses.dataclass
class RequestLifecycle:
    """One request's structured lifecycle, fleet-door to terminal."""
    uid: int
    tenant: str = ""
    submit_t: float = 0.0
    verdict: str = ""              # admitted | the rejection reason
    queue_wait_s: Optional[float] = None   # submit to first service
    prefill_tokens: int = 0        # prompt length at the fleet door
    decode_tokens: int = 0         # tokens actually delivered
    hops: List[Dict[str, Any]] = dataclasses.field(default_factory=list)
    state: str = "active"
    reason: str = ""
    end_t: Optional[float] = None

    @property
    def hedged(self) -> bool:
        return any(h["kind"] == "hedge" for h in self.hops)

    @property
    def failovers(self) -> int:
        return sum(1 for h in self.hops if h["kind"] == "failover")

    def as_dict(self) -> Dict[str, Any]:
        d = dataclasses.asdict(self)
        d["hedged"] = self.hedged
        d["failovers"] = self.failovers
        return d


class FleetObservatory:
    """The fleet's lifecycle ledger + goodput accountant.

    Owned by a ``FleetRouter`` (one per fleet); frontends the router
    installs get a back-reference and call the ``note_*`` hooks. Every
    hook is cheap (dict update / counter inc) and None-tolerant at the
    call sites, so a standalone frontend without a fleet pays nothing.
    As single-threaded as the router that owns it. ``slo`` is the
    optionally attached :class:`~.slo.SloEngine` (the frontend's shed
    hint and the autoscaler's burn reason read it through here).
    """

    def __init__(self, clock=time.monotonic, ledger_size: int = 2048):
        self.clock = clock
        self._open: Dict[int, RequestLifecycle] = {}
        self._closed: collections.deque = collections.deque(
            maxlen=max(1, int(ledger_size)))
        self.slo = None
        # internal integers are the reconciliation source of truth (the
        # process-global counters below mirror them but can be shared
        # with another fleet in the same process or reset by tests)
        self.goodput_tokens = 0
        self.computed_tokens = 0
        self.wasted_tokens: Dict[str, int] = {r: 0 for r in WASTE_REASONS}
        self.terminal_counts: collections.Counter = collections.Counter()
        self._tm_goodput = telemetry.counter(
            "fleet_goodput_tokens_total",
            "tokens computed AND delivered to callers in a terminal "
            "record — the honest numerator for serving-efficiency wins")
        self._tm_wasted = telemetry.counter(
            "fleet_wasted_tokens_total",
            "tokens the fleet computed but never delivered, by reason "
            "(hedge_lost / failover_replay / evicted / shed)")
        self._tm_computed = telemetry.counter(
            "fleet_computed_tokens_total",
            "every token-computation quantum the fleet paid for; equals "
            "goodput + wasted by construction (the reconciliation "
            "invariant the bench validator pins)")
        self._tm_ttft = telemetry.histogram(
            "fleet_ttft_seconds",
            "fleet submit to first prefill progress on any replica "
            "(fleet-wide TTFT; sliding-window source for SLO burn rates)",
            window_s=TTFT_WINDOW_S, window_intervals=TTFT_WINDOW_INTERVALS)
        self._tm_ttft.set_window_clock(clock)

    # ------------------------------------------------------------ hooks
    def note_submit(self, uid: int, tenant: str, prompt_len: int,
                    t: float) -> None:
        self._open[uid] = RequestLifecycle(
            uid=uid, tenant=tenant, submit_t=t, prefill_tokens=prompt_len)

    def note_verdict(self, uid: int, verdict: str) -> None:
        rec = self._open.get(uid)
        if rec is not None:
            rec.verdict = verdict

    def note_hop(self, uid: int, kind: str, replica: str,
                 reason: str = "") -> None:
        """One placement event: kind ∈ dispatch | retry | hedge |
        failover | migration."""
        rec = self._open.get(uid)
        if rec is not None:
            rec.hops.append({"kind": kind, "replica": replica,
                             "reason": reason,
                             "t": round(self.clock(), 6)})

    def note_first_service(self, uid: int, wait_s: float) -> None:
        """First prefill progress on ANY replica: the fleet TTFT. Only
        the first copy to serve counts — a hedge or failover copy
        reaching prefill later is not a second first-token. The wait is
        measured from the FLEET door (this ledger's submit stamp), so
        retry backoff and re-dispatch queuing are inside it — ``wait_s``
        is the replica-relative wait, kept in the signature for callers
        that have it, and a request never ledgered at submit observes
        nothing (there is no fleet door to measure from)."""
        rec = self._open.get(uid)
        if rec is not None and rec.queue_wait_s is None:
            fleet_wait = max(0.0, self.clock() - rec.submit_t)
            rec.queue_wait_s = round(fleet_wait, 6)
            self._tm_ttft.observe(fleet_wait)

    def note_goodput(self, tokens: int) -> None:
        if tokens <= 0:
            return
        self.goodput_tokens += tokens
        self.computed_tokens += tokens
        self._tm_goodput.inc(tokens)
        self._tm_computed.inc(tokens)

    def note_waste(self, reason: str, tokens: int) -> None:
        if tokens <= 0:
            return
        if reason not in self.wasted_tokens:
            raise ValueError(f"unknown waste reason {reason!r} "
                             f"(expected one of {WASTE_REASONS})")
        self.wasted_tokens[reason] += tokens
        self.computed_tokens += tokens
        self._tm_wasted.inc(tokens, reason=reason)
        self._tm_computed.inc(tokens)

    def note_terminal(self, uid: int, state: str, reason: str,
                      delivered_tokens: int) -> None:
        rec = self._open.pop(uid, None)
        if rec is None:
            # terminal without a submit record (router built mid-flight,
            # or a test drove _record_result directly): still ledger it
            rec = RequestLifecycle(uid=uid, submit_t=self.clock())
        rec.state = state
        rec.reason = reason
        rec.decode_tokens = delivered_tokens
        rec.end_t = self.clock()
        self.terminal_counts[state] += 1
        self._closed.append(rec)

    # ------------------------------------------------------------ reads
    def record(self, uid: int) -> Optional[RequestLifecycle]:
        if uid in self._open:
            return self._open[uid]
        for rec in reversed(self._closed):
            if rec.uid == uid:
                return rec
        return None

    def records(self, window_s: Optional[float] = None
                ) -> List[RequestLifecycle]:
        """Terminal records, oldest first; ``window_s`` keeps only those
        that ended inside the last that-many seconds."""
        if window_s is None:
            return list(self._closed)
        cutoff = self.clock() - window_s
        return [r for r in self._closed
                if r.end_t is not None and r.end_t >= cutoff]

    def availability(self, window_s: float, tenant: Optional[str] = None
                     ) -> Optional[float]:
        """Fraction of terminal requests inside the window that
        completed (rejections and failures both spend error budget —
        the caller was turned away or hurt either way). None when the
        window holds no terminal record: no traffic is not an outage."""
        recs = self.records(window_s)
        if tenant is not None:
            recs = [r for r in recs if r.tenant == tenant]
        if not recs:
            return None
        ok = sum(1 for r in recs if r.state == "completed")
        return ok / len(recs)

    def ttft_quantile(self, q: float, window_s: Optional[float] = None
                      ) -> Optional[float]:
        return self._tm_ttft.windowed_quantile(q, window_s=window_s)

    def ttft_bad_fraction(self, threshold_s: float,
                          window_s: Optional[float] = None):
        return self._tm_ttft.windowed_bad_fraction(
            threshold_s, window_s=window_s)

    def goodput_fraction(self) -> Optional[float]:
        """goodput / computed, or None before any token was computed."""
        if self.computed_tokens == 0:
            return None
        return self.goodput_tokens / self.computed_tokens

    def reconciles(self) -> bool:
        """The ledger's own invariant — exact, not approximate."""
        return (self.goodput_tokens + sum(self.wasted_tokens.values())
                == self.computed_tokens)

    def snapshot(self) -> Dict[str, Any]:
        """JSON-ready state for ``/slo``, bench rows and fleet-report."""
        frac = self.goodput_fraction()
        return {
            "goodput_tokens": self.goodput_tokens,
            "wasted_tokens": dict(self.wasted_tokens),
            "computed_tokens": self.computed_tokens,
            "goodput_fraction": round(frac, 6) if frac is not None else None,
            "reconciles": self.reconciles(),
            "terminal_counts": dict(self.terminal_counts),
            "open_requests": len(self._open),
            "ledger_records": len(self._closed),
        }
