"""fleet-report: one rendered verdict over the fleet observatory.

Builds a JSON-ready report — SLO compliance + burn rates, per-tenant
TTFT p99s, the goodput/wasted breakdown (with its exact reconciliation
check), prefix-reuse opportunity, decode wire bytes — from either a
LIVE fleet (router + engine objects) or a BENCH result row (a v2.6
``slo`` block embedded by the fleet lanes). The CLI in ``__main__``
renders it dslint-shaped: exit 0 clean, 1 findings (a firing alert or a
reconciliation failure), 2 usage/malformed input.
"""
from __future__ import annotations

from typing import Any, Dict, List, Optional

from deepspeed_tpu import telemetry

_TENANT_TTFT = "serving_tenant_ttft_seconds"


def _verdict(alert: Dict[str, Any], fired: float, cleared: float) -> str:
    if alert.get("firing"):
        return "firing"
    if fired > 0 and cleared > 0:
        return "fired_and_cleared"
    if alert.get("has_data"):
        return "ok"
    return "no_data"


def _tenant_ttft_p99s() -> Dict[str, Optional[float]]:
    """Per-tenant TTFT p99 from the live registry: the sliding-window
    view when the window holds data, the lifetime view otherwise (a
    drained fleet's report should still name its tenants)."""
    hist = telemetry.get_registry().get(_TENANT_TTFT)
    if hist is None:
        return {}
    out: Dict[str, Optional[float]] = {}
    for key, _child in hist.labels_items():
        labels = dict(key)
        tenant = labels.get("tenant")
        if tenant is None:
            continue
        p99 = hist.windowed_quantile(0.99, tenant=tenant)
        if p99 is None:
            p99 = hist.quantile(0.99, tenant=tenant)
        out[tenant] = round(p99, 6) if p99 is not None else None
    return out


def _alert_verdicts(slo_engine) -> Dict[str, str]:
    trans = telemetry.get_registry().get("fleet_slo_alert_transitions_total")
    verdicts: Dict[str, str] = {}
    for alert in slo_engine.alerts():
        d = alert.as_dict()
        fired = cleared = 0.0
        if trans is not None:
            fired = trans.value(objective=alert.name, to="firing")
            cleared = trans.value(objective=alert.name, to="clear")
        verdicts[alert.name] = _verdict(d, fired, cleared)
    return verdicts


def slo_bench_block(router) -> Dict[str, Any]:
    """The v2.6 ``slo`` bench-entry block, from a live router: compact
    objective verdicts + the goodput reconciliation triple the schema
    validator re-checks on every validate."""
    obs = router.observatory
    engine = router.slo
    block: Dict[str, Any] = {
        "objectives": [
            {"name": o.name, "metric": o.metric, "tenant": o.tenant,
             "target": o.target, "threshold_s": o.threshold_s}
            for o in (engine.objectives if engine is not None else [])],
        "verdicts": _alert_verdicts(engine) if engine is not None else {},
        "worst_burn_rate": round(engine.worst_burn_rate(), 6)
        if engine is not None else 0.0,
        "goodput_tokens": obs.goodput_tokens,
        "wasted_tokens": dict(obs.wasted_tokens),
        "computed_tokens": obs.computed_tokens,
        "goodput_fraction": obs.goodput_fraction(),
    }
    rate = router.prefix.hit_rate() if router.prefix is not None else None
    block["prefix_hit_rate"] = round(rate, 6) if rate is not None else None
    return block


def build_report(router=None, bench_entry: Optional[Dict[str, Any]] = None,
                 entry_name: str = "", wire: Optional[Dict[str, Any]] = None
                 ) -> Dict[str, Any]:
    """Assemble the canonical report dict from a live ``FleetRouter``
    OR a bench entry carrying a v2.6 ``slo`` block (exactly one)."""
    if (router is None) == (bench_entry is None):
        raise ValueError("build_report needs exactly one of router / "
                         "bench_entry")
    if router is not None:
        obs = router.observatory
        engine = router.slo
        alerts = [a.as_dict() for a in engine.alerts()] \
            if engine is not None else []
        verdicts = _alert_verdicts(engine) if engine is not None else {}
        for a in alerts:
            a["verdict"] = verdicts.get(a["name"], "no_data")
        goodput = obs.snapshot()
        resolved = telemetry.get_registry().get("fleet_resolved_total")
        ledger_terminals = sum(obs.terminal_counts.values())
        counter_terminals = int(resolved.total()) if resolved is not None \
            else ledger_terminals
        report = {
            "source": "live",
            "slo": {
                "objectives": [
                    {"name": o.name, "metric": o.metric, "tenant": o.tenant,
                     "target": o.target, "threshold_s": o.threshold_s}
                    for o in (engine.objectives
                              if engine is not None else [])],
                "alerts": alerts,
                "any_firing": engine.any_firing()
                if engine is not None else False,
                "worst_burn_rate": round(engine.worst_burn_rate(), 6)
                if engine is not None else 0.0,
            },
            "tenants": {t: {"ttft_p99_s": p}
                        for t, p in _tenant_ttft_p99s().items()},
            "goodput": goodput,
            "reconciliation": {
                # two independent checks: the ledger's own token
                # invariant, and the lifecycle ring vs the fleet's
                # terminal-outcome counter (every terminal counted once)
                "tokens_ok": obs.reconciles(),
                "terminals_ok": ledger_terminals == counter_terminals,
                "ledger_terminals": ledger_terminals,
                "counter_terminals": counter_terminals,
            },
            "prefix": router.prefix.snapshot()
            if router.prefix is not None else {},
        }
        if wire is not None:
            report["wire"] = wire
        return report
    # ---- bench-row mode -------------------------------------------- #
    slo = bench_entry.get("slo")
    if not isinstance(slo, dict):
        raise ValueError(
            f"bench entry {entry_name or '<unnamed>'} carries no 'slo' "
            "block (fleet lanes embed one unless BENCH_SLO=0)")
    wasted = slo.get("wasted_tokens", {})
    goodput_tokens = slo.get("goodput_tokens", 0)
    computed = slo.get("computed_tokens", 0)
    alerts = [{"name": name, "verdict": verdict, "firing":
               verdict == "firing"}
              for name, verdict in sorted(slo.get("verdicts", {}).items())]
    tenants = {}
    for t, row in (bench_entry.get("tenants") or {}).items():
        if isinstance(row, dict) and "ttft_p99_s" in row:
            tenants[t] = {"ttft_p99_s": row["ttft_p99_s"]}
    report = {
        "source": f"bench:{entry_name}" if entry_name else "bench",
        "slo": {
            "objectives": slo.get("objectives", []),
            "alerts": alerts,
            "any_firing": any(a["firing"] for a in alerts),
            "worst_burn_rate": slo.get("worst_burn_rate", 0.0),
        },
        "tenants": tenants,
        "goodput": {
            "goodput_tokens": goodput_tokens,
            "wasted_tokens": dict(wasted),
            "computed_tokens": computed,
            "goodput_fraction": slo.get("goodput_fraction"),
        },
        "reconciliation": {
            "tokens_ok": goodput_tokens + sum(wasted.values()) == computed,
            "terminals_ok": True,   # the schema validator pinned it at
                                    # embed time (tenants block)
        },
        "prefix": {"hit_rate": slo.get("prefix_hit_rate")},
    }
    if "wire_bytes_per_tick" in slo:
        report["wire"] = {"wire_bytes_per_tick": slo["wire_bytes_per_tick"]}
    return report


def report_exit_code(report: Dict[str, Any]) -> int:
    """dslint-shaped: 1 when the report carries findings (a firing
    alert, or a reconciliation the fleet cannot prove), else 0."""
    rec = report.get("reconciliation", {})
    if not rec.get("tokens_ok", True) or not rec.get("terminals_ok", True):
        return 1
    if report.get("slo", {}).get("any_firing"):
        return 1
    return 0


def _fmt(v: Any) -> str:
    if v is None:
        return "-"
    if isinstance(v, float):
        return f"{v:.6g}"
    return str(v)


def render_report(report: Dict[str, Any], as_json: bool = False) -> str:
    if as_json:
        import json

        return json.dumps(report, indent=2, sort_keys=True)
    lines: List[str] = []
    lines.append(f"fleet-report ({report.get('source', '?')})")
    slo = report.get("slo", {})
    lines.append(f"  slo: {len(slo.get('objectives', []))} objective(s), "
                 f"worst burn rate {_fmt(slo.get('worst_burn_rate'))}, "
                 f"{'FIRING' if slo.get('any_firing') else 'not firing'}")
    for a in slo.get("alerts", []):
        burns = ""
        if "fast_burn" in a:
            burns = (f" fast={_fmt(a['fast_burn'])} "
                     f"slow={_fmt(a['slow_burn'])}")
        lines.append(f"    [{a.get('verdict', '?'):>17}] {a['name']}"
                     f"{burns}")
    tenants = report.get("tenants", {})
    if tenants:
        lines.append("  per-tenant TTFT p99:")
        for t in sorted(tenants):
            lines.append(f"    {t}: {_fmt(tenants[t].get('ttft_p99_s'))} s")
    g = report.get("goodput", {})
    lines.append(f"  goodput: {_fmt(g.get('goodput_tokens'))} tokens "
                 f"delivered of {_fmt(g.get('computed_tokens'))} computed "
                 f"(fraction {_fmt(g.get('goodput_fraction'))})")
    wasted = g.get("wasted_tokens", {})
    if wasted:
        parts = ", ".join(f"{k}={v}" for k, v in sorted(wasted.items()))
        lines.append(f"  wasted: {parts}")
    rec = report.get("reconciliation", {})
    lines.append(f"  reconciliation: tokens "
                 f"{'ok' if rec.get('tokens_ok') else 'BROKEN'}, terminals "
                 f"{'ok' if rec.get('terminals_ok') else 'BROKEN'}")
    prefix = report.get("prefix", {})
    if prefix:
        lines.append(f"  prefix opportunity: hit rate "
                     f"{_fmt(prefix.get('hit_rate'))}"
                     + (f" over {prefix['total_blocks']} blocks"
                        if prefix.get("total_blocks") else ""))
    wire = report.get("wire")
    if wire:
        lines.append(f"  decode wire: "
                     f"{_fmt(wire.get('wire_bytes_per_tick'))} bytes/tick")
    return "\n".join(lines)
