"""Fleet observatory: the serving-side sibling of the execution
observatory (``profiling/observatory``).

Four lenses over a running fleet (README "Fleet observatory"):

* :mod:`ledger` — per-request lifecycle records (queue-wait, admission
  verdict, hops, terminal state) in a bounded ring, plus goodput
  accounting: ``fleet_goodput_tokens_total`` vs
  ``fleet_wasted_tokens_total{reason}`` — tokens the fleet computed but
  never delivered, the honest denominator for every phase-2 win.
* :mod:`slo` — declarative objectives (TTFT p99, per-token decode
  latency, availability) evaluated with SRE-workbook multi-window
  burn-rate alerting over sliding-window quantiles; observe-only by
  default, optionally a scale-out reason and a shed hint.
* :mod:`prefix` — block-granularity prompt-prefix hashing measuring the
  would-be prefix-hit rate, block-sharing potential and KV-pool
  fragmentation (prices ROADMAP item 3a before any routing code), plus
  the decode-tick collective-ledger fold (wire bytes for item 3d).
* :mod:`report` — the ``fleet-report`` CLI's renderer: SLO compliance,
  burn rates, per-tenant p99s, goodput breakdown and prefix opportunity
  from a live fleet or a bench row.
"""
from deepspeed_tpu.serving.observatory.ledger import (
    WASTE_REASONS,
    FleetObservatory,
    RequestLifecycle,
)
from deepspeed_tpu.serving.observatory.prefix import (
    PrefixMeter,
    decode_wire_stats,
    pool_stats,
)
from deepspeed_tpu.serving.observatory.report import (
    build_report,
    render_report,
    report_exit_code,
    slo_bench_block,
)
from deepspeed_tpu.serving.observatory.slo import SloAlert, SloEngine

__all__ = [
    "FleetObservatory", "RequestLifecycle", "WASTE_REASONS",
    "SloAlert", "SloEngine",
    "PrefixMeter", "pool_stats", "decode_wire_stats",
    "build_report", "render_report", "report_exit_code", "slo_bench_block",
]
