"""``fleet-report`` — render the fleet observatory's verdict.

Sources, in order of preference:

* ``--url http://host:port`` — fetch a live fleet's ``/slo`` endpoint
  and render its body (the SLO engine's ``state()``).
* ``PATH`` — a bench results JSON (detected by its ``schema_version``
  key; pick an entry with ``--entry``, default: first entry carrying an
  ``slo`` block) or a previously dumped report/``/slo`` body.

Exit codes are dslint-shaped: 0 clean, 1 findings (a firing burn-rate
alert or a goodput reconciliation the fleet cannot prove), 2 usage or
malformed input.
"""
from __future__ import annotations

import argparse
import json
import sys
from typing import Any, Dict, List, Optional

from deepspeed_tpu.serving.observatory.report import (
    render_report,
    report_exit_code,
)


def _report_from_slo_state(state: Dict[str, Any],
                           source: str) -> Dict[str, Any]:
    """Shape a live ``/slo`` body (SloEngine.state()) into the report
    dict the renderer expects."""
    goodput = state.get("goodput", {})
    report: Dict[str, Any] = {
        "source": source,
        "slo": {
            "objectives": state.get("objectives", []),
            "alerts": state.get("alerts", []),
            "any_firing": state.get("any_firing", False),
            "worst_burn_rate": state.get("worst_burn_rate", 0.0),
        },
        "tenants": {},
        "goodput": goodput,
        "reconciliation": {
            "tokens_ok": goodput.get("reconciles", True),
            "terminals_ok": True,
        },
        "prefix": state.get("prefix", {}),
    }
    if "ttft_p99_s" in state:
        report["ttft_p99_s"] = state["ttft_p99_s"]
    return report


def _pick_bench_entry(result: Dict[str, Any], wanted: str):
    entries = result.get("entries")
    if not isinstance(entries, dict) or not entries:
        raise ValueError("bench results carry no entries")
    if wanted:
        if wanted not in entries:
            raise ValueError(
                f"no bench entry named {wanted!r} "
                f"(have: {', '.join(sorted(entries))})")
        return wanted, entries[wanted]
    for name, entry in entries.items():
        if isinstance(entry, dict) and isinstance(entry.get("slo"), dict):
            return name, entry
    raise ValueError(
        "no bench entry carries an 'slo' block — run a fleet lane "
        "without BENCH_SLO=0, or name an entry with --entry")


def _load(path: str) -> Dict[str, Any]:
    with open(path, "r", encoding="utf-8") as fh:
        payload = json.load(fh)
    if not isinstance(payload, dict):
        raise ValueError("expected a JSON object at the top level")
    return payload


def main(argv: Optional[List[str]] = None) -> int:
    parser = argparse.ArgumentParser(
        prog="fleet-report",
        description="Render the fleet observatory's verdict: SLO "
                    "compliance, burn rates, per-tenant TTFT p99s, "
                    "goodput/wasted breakdown, prefix opportunity.")
    parser.add_argument("path", nargs="?", default=None,
                        help="bench results JSON (schema_version file) or "
                             "a dumped report / /slo body")
    parser.add_argument("--url", default=None,
                        help="base URL of a live exposition server; "
                             "fetches <url>/slo")
    parser.add_argument("--entry", default="",
                        help="bench entry name to report on (default: "
                             "first entry with an slo block)")
    parser.add_argument("--json", action="store_true", dest="as_json",
                        help="emit the report as JSON instead of text")
    try:
        args = parser.parse_args(argv)
    except SystemExit as exc:        # argparse exits 2 on usage errors
        return int(exc.code or 0)
    if (args.path is None) == (args.url is None):
        parser.print_usage(sys.stderr)
        print("fleet-report: need exactly one of PATH or --url",
              file=sys.stderr)
        return 2

    try:
        if args.url is not None:
            import urllib.request

            url = args.url.rstrip("/") + "/slo"
            with urllib.request.urlopen(url, timeout=10) as resp:
                state = json.loads(resp.read().decode("utf-8"))
            report = _report_from_slo_state(state, source=url)
        else:
            payload = _load(args.path)
            if "schema_version" in payload:
                from deepspeed_tpu.bench.schema import validate_result

                errs = validate_result(payload)
                if errs:
                    for e in errs:
                        print(f"fleet-report: schema: {e}", file=sys.stderr)
                    return 2
                from deepspeed_tpu.serving.observatory.report import (
                    build_report,
                )

                name, entry = _pick_bench_entry(payload, args.entry)
                report = build_report(bench_entry=entry, entry_name=name)
            elif "alerts" in payload.get("slo", {}) \
                    or "reconciliation" in payload:
                report = payload           # an already-built report dump
            elif "objectives" in payload:  # a dumped /slo body
                report = _report_from_slo_state(
                    payload, source=f"file:{args.path}")
            else:
                raise ValueError(
                    "unrecognized input: neither bench results, a "
                    "report dump, nor an /slo body")
    except (OSError, ValueError, KeyError) as exc:
        print(f"fleet-report: {exc}", file=sys.stderr)
        return 2

    print(render_report(report, as_json=args.as_json))
    return report_exit_code(report)


if __name__ == "__main__":
    sys.exit(main())
