"""Serving health probes: liveness + readiness for load-balancer drains.

The split follows the k8s convention, mapped onto continuous-batching
reality:

* **liveness** (``/healthz``) — is the serving LOOP alive? Staleness of
  the tick heartbeat (stamped at every ``run_tick`` entry, including
  circuit-rejected ones) only signals death while requests are PENDING:
  a tick hung inside a device call stops stamping with work queued —
  the restart-me signal. An idle frontend (nothing active — the
  documented ``while fe.active_count(): fe.run_tick()`` loop parked) and
  a frontend that has never ticked both report alive; idleness is not
  death, or a traffic pause would restart healthy replicas.
* **readiness** (``/readyz``) — should this replica receive NEW traffic?
  True iff the circuit is closed AND the queue is below its admission
  cap. An open circuit or a full queue flips the replica unready so the
  balancer drains it while it recovers; requests already queued keep
  being served.

``HealthSurface`` registers both probes on the telemetry exposition
server (``telemetry.register_health_probe``) under a shared name, so
``/healthz``/``/readyz`` answer 200/503 with per-probe JSON detail.
Probes read only host-side scalars — safe from the HTTP thread.
"""
from __future__ import annotations

from typing import Any, Dict, Tuple

from deepspeed_tpu import telemetry
from deepspeed_tpu.serving.circuit import CLOSED


class HealthSurface:
    """Registers a frontend's liveness/readiness probes; ``close()``
    (or the frontend's) unregisters them."""

    def __init__(self, frontend, name: str = "serving"):
        self.frontend = frontend
        self.name = name
        telemetry.register_health_probe("live", name, self.liveness)
        telemetry.register_health_probe("ready", name, self.readiness)

    def liveness(self) -> Tuple[bool, Dict[str, Any]]:
        fe = self.frontend
        if fe.last_tick_t is None:
            return True, {"ticks": 0, "note": "loop not started"}
        age = fe.clock() - fe.last_tick_t
        timeout = fe.cfg.heartbeat_timeout_s
        if fe.active_count() == 0:
            return True, {"last_tick_age_s": round(age, 3),
                          "note": "idle (no active requests)"}
        return age <= timeout, {"last_tick_age_s": round(age, 3),
                                "timeout_s": timeout,
                                "active": fe.active_count()}

    def readiness(self) -> Tuple[bool, Dict[str, Any]]:
        fe = self.frontend
        circuit_ok = fe.breaker.state == CLOSED
        queue = fe.active_count()
        queue_ok = queue < fe.cfg.max_queue
        return circuit_ok and queue_ok, {
            "circuit": fe.breaker.state,
            "queue": queue,
            "max_queue": fe.cfg.max_queue,
        }

    def close(self) -> None:
        telemetry.unregister_health_probe("live", self.name)
        telemetry.unregister_health_probe("ready", self.name)
