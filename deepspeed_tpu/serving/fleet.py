"""FleetRouter: health-aware routing + failover over N serving replicas.

``ServingFrontend`` gives ONE engine admission control, shedding, and a
circuit breaker; this module is the layer the millions-of-users story
needs above it — a router that owns N replicas and extends the same hard
guarantees to the fleet:

* **scored routing** — each admission goes to the replica with the least
  projected wait: measured decode throughput (``est_token_seconds()``)
  times its token backlog, inflated by projected KV-pool pressure. A
  replica whose circuit is open inside its backoff window, whose last
  tick hung past the staleness deadline, or which is draining is not a
  candidate; a replica whose open window has expired is routable as a
  last-resort probe vehicle (the same rule the frontend applies).
* **failover + retries** — a replica that crashes (circuit opens) or
  hangs (tick blocked past ``heartbeat_stale_s``) loses its in-flight
  requests to the survivors: each is re-materialized (prompt + tokens
  generated so far — greedy decode continues bit-identically), cancelled
  on the sick replica (KV blocks released), and resubmitted elsewhere
  with exponential backoff + jitter and an excluded-replica set. Bounded
  attempts, then a structured terminal ``failed`` — never a raised
  exception, never two terminal states for one uid, never a leaked KV
  block on either replica.
* **hedged dispatch** (optional) — a request still running past the
  observed completion-latency percentile is duplicated onto a second
  replica; first completion wins and the loser is cancelled.
* **honest degradation** — when every candidate answers ``Overloaded``,
  the fleet verdict aggregates them: the dominant reason and the
  EARLIEST retry-after any replica offered.
* **draining + quorum probes** — ``drain()`` stops routing to a replica
  and migrates (or waits out) its in-flight work, enabling rolling
  restarts via ``replace_replica``; the fleet registers ``/healthz`` /
  ``/readyz`` probes on the exposition registry reporting quorum
  (ready iff ≥ ``min_ready_replicas`` replicas are routable).
* **autoscaling** (:class:`FleetAutoscaler`) — scale-out/in policy over
  the signals the frontends already export (queue depth per ready
  replica, KV-pool utilization, p99 completion latency), built on the
  same drain/migrate machinery: ``add_replica`` makes a new frontend
  routable immediately; scale-in drains the victim with migration and
  only closes it once quiesced, so a resize in either direction can
  never lose an admitted request.

Single-threaded like the frontends it owns: one loop calls ``submit`` /
``run_tick``; the health probes are the only cross-thread readers and
touch host scalars only. Chaos hooks: every replica tick passes through
the ``serving/hang`` and ``serving/tick`` fault points scoped by replica
name (``DSTPU_CHAOS="serving/tick@r1=fail:999"`` crashes one replica of
a fleet; ``serving/hang@r2=hang:0.2:3`` hangs another), which is how the
zero-loss tests in ``tests/unit/test_fleet.py`` prove the guarantees.

Config: the ``"fleet"`` section of the runtime JSON config
(``runtime/config.py:FleetSectionConfig``). Metrics: ``fleet_*`` in the
README "Observability" catalog.
"""
from __future__ import annotations

import collections
import random
import time
from typing import Any, Dict, List, Optional, Sequence, Union

from deepspeed_tpu import telemetry
from deepspeed_tpu.serving.admission import (
    Admitted,
    Overloaded,
    Rejected,
)
from deepspeed_tpu.serving.circuit import CLOSED, OPEN
from deepspeed_tpu.serving.frontend import (
    ACTIVE,
    COMPLETED,
    EXPIRED,
    FAILED,
    REJECTED,
    RequestResult,
    ServingFrontend,
)
from deepspeed_tpu.serving.observatory import (
    FleetObservatory,
    PrefixMeter,
    SloEngine,
)
from deepspeed_tpu.serving.tenancy import TenantRegistry
from deepspeed_tpu.utils.logging import logger

#: fleet-level rejection reason when no replica is even a candidate
REASON_NO_REPLICA = "no_ready_replica"


class _Replica:
    """Router-side view of one frontend (name, drain flag, hung flag)."""

    __slots__ = ("frontend", "name", "draining", "hung")

    def __init__(self, frontend: ServingFrontend):
        self.frontend = frontend
        self.name = frontend.name
        self.draining = False
        self.hung = False


class _FleetRequest:
    __slots__ = ("uid", "prompt", "deadline_s", "max_new_tokens",
                 "submit_t", "dispatch_t", "attempts", "excluded",
                 "replica", "hedge", "hedged", "next_retry_t", "carried",
                 "last_reason", "tenant")

    def __init__(self, uid: int, prompt: List[int],
                 deadline_s: Optional[float], max_new_tokens: int,
                 submit_t: float, tenant: str):
        self.uid = uid
        self.prompt = prompt          # current payload (grows on remat)
        self.deadline_s = deadline_s  # relative to submit_t; None = none
        self.max_new_tokens = max_new_tokens
        self.submit_t = submit_t
        self.dispatch_t = submit_t    # last (re)dispatch time (hedge clock)
        self.attempts = 0             # dispatches that were ADMITTED
        self.excluded: set = set()    # replica names already tried & lost
        self.replica: Optional[str] = None   # current primary copy
        self.hedge: Optional[str] = None     # current hedge copy
        self.hedged = False           # a hedge was ever spawned
        self.next_retry_t: Optional[float] = None
        self.carried: List[int] = []  # tokens folded into prompt by remat
        self.last_reason = ""         # why the last copy was lost
        self.tenant = tenant          # resolved tenant; rides every
        # dispatch, failover re-materialization and hedge copy


class FleetRouter:
    """Routes requests across N ``ServingFrontend`` replicas with
    health-aware failover. ``config`` is a ``FleetSectionConfig``, a
    plain dict of its keys, or None (defaults); ``clock`` and ``seed``
    are injectable for deterministic tests."""

    def __init__(self, replicas: Sequence[ServingFrontend], config=None,
                 clock=time.monotonic, register_health: bool = True,
                 health_name: str = "fleet", seed: int = 0, tenancy=None,
                 slo=None):
        from deepspeed_tpu.runtime.config import FleetSectionConfig
        from deepspeed_tpu.runtime.config_utils import config_from_dict

        if config is None:
            config = FleetSectionConfig()
        elif isinstance(config, dict):
            config = config_from_dict(FleetSectionConfig, config,
                                      path="fleet.")
        else:
            config.validate()
        if not replicas:
            raise ValueError("FleetRouter needs at least one replica")
        self.cfg = config
        self.clock = clock
        self._rng = random.Random(seed)
        self._replicas: List[_Replica] = [_Replica(fe) for fe in replicas]
        names = [r.name for r in self._replicas]
        if len(set(names)) != len(names):
            raise ValueError(f"replica names must be unique, got {names}")
        # ONE tenant registry for the whole fleet: per-tenant quotas,
        # fairness counters and quarantines must hold ACROSS replicas
        # (and through replace_replica / autoscaler resizes — every
        # install path below adopts the same registry). With no tenancy
        # given, the first replica's registry becomes the fleet's, so
        # pre-built frontends sharing one keep it.
        if tenancy is None:
            self.tenancy = self._replicas[0].frontend.tenancy
        else:
            self.tenancy = TenantRegistry.ensure(tenancy, clock=clock)
        # fleet observatory (serving/observatory): lifecycle ledger +
        # goodput accounting, the SLO burn-rate engine over it (``slo``
        # is an SloSectionConfig / dict / None — observe-only defaults),
        # and the prefix-opportunity meter at the fleet door
        self.slo = SloEngine(config=slo, tenancy=self.tenancy, clock=clock)
        self.observatory = FleetObservatory(
            clock=clock, ledger_size=self.slo.cfg.ledger_size)
        self.slo.observatory = self.observatory
        self.observatory.slo = self.slo
        self.prefix = PrefixMeter()
        for rep in self._replicas:
            rep.frontend.adopt_tenancy(self.tenancy)
            rep.frontend.observatory = self.observatory
        self._active: Dict[int, _FleetRequest] = {}
        # terminal records, insertion-ordered and bounded (same contract
        # as the frontend's result map — sustained overload must not grow
        # router memory without limit)
        self._results: Dict[int, RequestResult] = {}
        # completion-latency samples feeding the hedge threshold
        self._lat_samples: collections.deque = collections.deque(maxlen=256)
        self._setup_telemetry()
        self.health_name: Optional[str] = None
        if register_health:
            name = telemetry.unique_health_probe_name(health_name)
            self.health_name = name
            telemetry.register_health_probe("live", name, self.liveness)
            telemetry.register_health_probe("ready", name, self.readiness)
            # /slo rides the same opt-in as the health probes: a fleet
            # that registers endpoints registers all of them
            self.slo.register_endpoint()

    @classmethod
    def build(cls, engines: Sequence, serving_config=None, fleet_config=None,
              replica_prefix: str = "replica", tenancy_config=None,
              slo_config=None, **kw) -> "FleetRouter":
        """Convenience: wrap N engines in frontends named
        ``{prefix}-{i}`` (distinct names scope per-replica chaos and
        de-synchronize circuit jitter) and route over them. The replicas
        do NOT register their own health probes — ``/readyz`` AND-folds
        every registered probe, so a single dead replica would flip the
        endpoint unready even with quorum intact; the fleet's quorum
        probe is the readiness contract here. Callers composing their
        own frontends can still register per-replica probes when each
        replica is its own pod."""
        fes = [ServingFrontend(eng, config=serving_config,
                               register_health=False,
                               health_name=f"{replica_prefix}-{i}")
               for i, eng in enumerate(engines)]
        return cls(fes, config=fleet_config, tenancy=tenancy_config,
                   slo=slo_config, **kw)

    @classmethod
    def from_ds_config(cls, engines: Sequence, config,
                       **kw) -> "FleetRouter":
        """Build from a full runtime config (dict / JSON path /
        ``DeepSpeedTPUConfig``), using its ``"serving"``, ``"fleet"``,
        ``"tenancy"`` and ``"slo"`` sections — the deploy-file twin of
        :meth:`build` (mirrors ``ServingFrontend.from_ds_config``)."""
        from deepspeed_tpu.runtime.config import load_config

        full_cfg = load_config(config)
        kw.setdefault("serving_config", full_cfg.serving)
        kw.setdefault("fleet_config", full_cfg.fleet)
        kw.setdefault("tenancy_config", full_cfg.tenancy)
        kw.setdefault("slo_config", full_cfg.slo)
        return cls.build(engines, **kw)

    # ------------------------------------------------------------------ #
    def _setup_telemetry(self) -> None:
        self._tm_submitted = telemetry.counter(
            "fleet_submitted_total", "requests submitted to the fleet")
        self._tm_routed = telemetry.counter(
            "fleet_routed_total", "admissions placed, by replica")
        self._tm_reject = telemetry.counter(
            "fleet_rejected_total",
            "fleet-level rejections by reason (aggregated replica "
            "overloads, invalid requests, no_ready_replica)")
        self._tm_resolved = telemetry.counter(
            "fleet_resolved_total",
            "requests reaching a fleet terminal state, by outcome")
        self._tm_failover = telemetry.counter(
            "fleet_failovers_total",
            "in-flight copies lost to a sick/draining replica, by reason "
            "(replica_hung / circuit_open / drain / shed / failed)")
        self._tm_retries = telemetry.counter(
            "fleet_retries_total",
            "resubmissions of a lost request onto another replica")
        self._tm_hedges = telemetry.counter(
            "fleet_hedges_total",
            "hedged dispatches by outcome (spawned / won / lost)")
        self._tm_lost = telemetry.counter(
            "fleet_requests_lost_total",
            "in-flight requests force-failed at router shutdown (a clean "
            "drain leaves this at 0 — the chaos tests pin it)")
        # sliding window matches the fleet TTFT histogram (10 s × 60):
        # the hedge threshold reads the RECENT completion-latency
        # percentile from here, so a slow warmup ages out of the hedge
        # decision instead of inflating it for the process lifetime
        self._tm_request_s = telemetry.histogram(
            "fleet_request_seconds",
            "fleet submit() to fleet completion, wall seconds (windowed "
            "source for the hedge-threshold percentile and fleet latency "
            "SLOs)", window_s=600.0, window_intervals=60)
        self._tm_request_s.set_window_clock(self.clock)
        self._tm_ready = telemetry.gauge(
            "fleet_ready_replicas", "replicas currently routable")
        self._tm_active = telemetry.gauge(
            "fleet_active_requests", "fleet requests not yet terminal")
        # per-tenant fleet accounting: submitted == sum over terminal
        # outcomes, per tenant, fleet-wide (the reconciliation invariant
        # the chaos tests pin). Labels pass the cardinality guard.
        self._tm_t_submitted = telemetry.counter(
            "fleet_tenant_submitted_total",
            "requests submitted to the fleet, by tenant (duplicate-uid "
            "rejections excluded — they never get a terminal record)")
        self._tm_t_resolved = telemetry.counter(
            "fleet_tenant_resolved_total",
            "fleet terminal states by tenant and outcome — per tenant, "
            "its sum over outcomes equals fleet_tenant_submitted_total "
            "exactly (the multi-tenant reconciliation invariant)")

    # ------------------------------------------------------------------ #
    # introspection
    # ------------------------------------------------------------------ #
    def active_count(self) -> int:
        return len(self._active)

    def active_uids(self) -> List[int]:
        return sorted(self._active)

    def replicas(self) -> List[ServingFrontend]:
        return [rep.frontend for rep in self._replicas]

    def latency_quantile(self, q: float) -> Optional[float]:
        """``q``-quantile of observed fleet completion latencies (the
        hedge threshold's sample window), or None before any completion —
        the autoscaler's p99 signal."""
        if not self._lat_samples:
            return None
        ordered = sorted(self._lat_samples)
        idx = min(len(ordered) - 1, int(len(ordered) * q))
        return ordered[idx]

    def result(self, uid: int) -> RequestResult:
        """Fleet terminal record for ``uid``, or its live ``active`` view
        (tokens = carried + current copy's stream). Unknown uids raise
        KeyError."""
        r = self._active.get(uid)
        if r is not None:
            tokens = list(r.carried)
            rep = self._by_name(r.replica) if r.replica else None
            if rep is not None:
                res = self._copy_result(rep, uid)
                if res is not None:
                    tokens += res.tokens
            return RequestResult(uid, ACTIVE, tokens, tenant=r.tenant)
        return self._results[uid]

    def drop_result(self, uid: int) -> None:
        self._results.pop(uid, None)

    def _by_name(self, name: str) -> Optional[_Replica]:
        for rep in self._replicas:
            if rep.name == name:
                return rep
        return None

    def _copy_result(self, rep: _Replica, uid: int
                     ) -> Optional[RequestResult]:
        try:
            return rep.frontend.result(uid)
        except KeyError:
            # the frontend never saw (or already dropped) the uid — the
            # caller treats the copy as gone
            return None

    # ------------------------------------------------------------------ #
    # routing
    # ------------------------------------------------------------------ #
    def _routable(self, rep: _Replica, excluded=()) -> bool:
        if rep.name in excluded or rep.draining or rep.hung:
            return False
        fe = rep.frontend
        if fe.breaker.state != CLOSED:
            retry = fe.breaker.retry_after_s()
            # OPEN inside the window, or HALF_OPEN with the probe pending:
            # the frontend would reject anyway — don't waste the attempt
            if retry is None or retry > 0:
                return False
        return True

    def _score(self, rep: _Replica, prompt_len: int, max_new: int) -> float:
        """Projected seconds until this request would COMPLETE on the
        replica: (backlog + its own work) at the measured per-token rate,
        inflated by projected KV pressure (a near-full pool is about to
        preempt). Lower is better."""
        fe = rep.frontend
        est = fe.engine.est_token_seconds()
        tok_s = est if est is not None else fe.cfg.assumed_token_seconds
        wait_s = (fe.backlog_tokens() + prompt_len + max_new) * tok_s
        blocks = prompt_len // fe.engine.block_size + 1
        kv = fe.engine.kv_utilization(blocks)
        score = wait_s * (1.0 + kv)
        if fe.breaker.state != CLOSED:
            # expired-window probe vehicle: routable, but last resort
            score += 1e9
        return score

    def _candidates(self, prompt_len: int, max_new: int,
                    excluded=()) -> List[_Replica]:
        cands = [rep for rep in self._replicas
                 if self._routable(rep, excluded)]
        cands.sort(key=lambda rep: (self._score(rep, prompt_len, max_new),
                                    rep.name))
        return cands

    def _retry_hint_s(self) -> float:
        """Honest retry-after when NO replica is a candidate: the earliest
        probe window any open circuit offers, else one stale deadline."""
        hints = []
        for rep in self._replicas:
            retry = rep.frontend.breaker.retry_after_s()
            if retry is not None:
                hints.append(retry)
        return round(min(hints) if hints else self.cfg.heartbeat_stale_s, 3)

    def _try_dispatch(self, r: _FleetRequest
                      ) -> Union[Admitted, Overloaded, Rejected]:
        """Place ``r`` on the best candidate. On success ``r.replica`` /
        ``r.attempts`` / ``r.dispatch_t`` are updated; Overloaded /
        Rejected leave ``r`` unplaced for the caller to act on."""
        now = self.clock()
        deadline = None
        if r.deadline_s is not None:
            deadline = r.deadline_s - (now - r.submit_t)
        remaining = max(1, r.max_new_tokens - len(r.carried))
        overloads: List[Overloaded] = []
        rejected: Optional[Rejected] = None
        for rep in self._candidates(len(r.prompt), remaining, r.excluded):
            # charge_quota=False: the fleet door already debited this
            # tenant's rate buckets at submit() — a replica dispatch (or
            # a failover retry) must not charge the client twice. The
            # replica still enforces quarantine/concurrency/KV/fairness.
            res = rep.frontend.submit(r.uid, r.prompt, deadline_s=deadline,
                                      max_new_tokens=remaining,
                                      tenant=r.tenant, charge_quota=False)
            if isinstance(res, Admitted):
                r.replica = rep.name
                r.attempts += 1
                r.dispatch_t = now
                r.next_retry_t = None
                self._tm_routed.inc(replica=rep.name)
                self.observatory.note_hop(
                    r.uid, "dispatch" if r.attempts == 1 else "retry",
                    rep.name, reason=r.last_reason)
                if r.carried:
                    # this replica will re-prefill every carried token —
                    # compute the fleet already paid for once on the
                    # replica that lost the request
                    self.observatory.note_waste("failover_replay",
                                                len(r.carried))
                return res
            if isinstance(res, Rejected):
                # universal only when the PAYLOAD is invalid for EVERY
                # replica (empty, or over every engine's max_len — the
                # fleet is not required to be homogeneous). A
                # duplicate-uid rejection is replica-LOCAL (someone
                # submitted that uid to that frontend out of band) — try
                # the next candidate
                if not r.prompt or all(
                        len(r.prompt) >= rr.frontend.engine.max_len
                        for rr in self._replicas):
                    return res
                rejected = res
                continue
            overloads.append(res)
        if overloads:
            # one honest fleet verdict: the dominant reason, the EARLIEST
            # retry-after any replica offered
            reasons = collections.Counter(o.reason for o in overloads)
            return Overloaded(
                r.uid, reasons.most_common(1)[0][0],
                round(min(o.retry_after_s for o in overloads), 3), "fleet",
                detail=f"{len(overloads)} candidate replicas overloaded",
                tenant=r.tenant)
        if rejected is not None:
            # every candidate rejected replica-locally — surface the last
            return rejected
        return Overloaded(r.uid, REASON_NO_REPLICA, self._retry_hint_s(),
                          "fleet", detail="no routable replica",
                          tenant=r.tenant)

    # ------------------------------------------------------------------ #
    # admission
    # ------------------------------------------------------------------ #
    def submit(self, uid: int, prompt: Sequence[int],
               deadline_s: Optional[float] = None,
               max_new_tokens: Optional[int] = None,
               tenant: Optional[str] = None
               ) -> Union[Admitted, Overloaded, Rejected]:
        """Admit one request to the fleet. Same contract as the frontend:
        never raises for request-shaped problems; Overloaded/Rejected are
        also recorded as fleet terminal results for ``result(uid)``.

        ``tenant`` (default tenant when omitted) is debited HERE — the
        fleet door is the client-facing layer, so rate buckets are
        charged exactly once regardless of how many replicas a request
        later visits through failover or hedging."""
        prompt = list(prompt)
        tenant = self.tenancy.resolve(tenant)
        self._tm_submitted.inc()
        if uid in self._active:
            # duplicate of a live fleet uid: reject WITHOUT clobbering the
            # live request's lifecycle (mirror of the frontend rule).
            # Deliberately NOT counted in fleet_tenant_submitted_total:
            # the dup produces no terminal record, so counting it would
            # break the submitted == Σ resolved reconciliation.
            self._tm_reject.inc(reason="invalid")
            return Rejected(uid, detail=f"uid {uid} is still active")
        if max_new_tokens is None:
            # homogeneous-fleet assumption: the first replica's default
            # grant stands in for all (the router needs a concrete number
            # for remaining-token accounting across failovers)
            max_new_tokens = self._replicas[0].frontend.cfg \
                .default_max_new_tokens
        self._results.pop(uid, None)   # resubmission of a terminal uid
        self._tm_t_submitted.inc(tenant=self.tenancy.label(tenant))
        # lifecycle ledger opens at the fleet door; the prefix meter
        # prices each OFFERED prompt once (hedge/failover re-dispatches
        # are the same offer, so they are deliberately not re-metered)
        self.observatory.note_submit(uid, tenant, len(prompt), self.clock())
        block_size = getattr(self._replicas[0].frontend.engine,
                             "block_size", 0)
        if block_size:
            self.prefix.observe_prompt(prompt, block_size)
        # fleet-level tenant gate: quarantine + rate buckets (debited
        # once, here). Concurrency/KV/fairness are enforced per replica
        # at dispatch — the registry is fleet-shared, so those hold
        # fleet-wide too.
        gate = self.tenancy.fleet_gate(
            tenant, len(prompt) + max_new_tokens,
            self._replicas[0].frontend._token_seconds())
        if gate is not None:
            reason, retry, det = gate
            self._tm_reject.inc(reason=reason)
            self.observatory.note_verdict(uid, reason)
            self._record_result(RequestResult(uid, REJECTED, [], reason,
                                              det, tenant=tenant))
            self._refresh_gauges()
            return Overloaded(uid, reason, round(retry, 3), "fleet",
                              detail=det, tenant=tenant)
        r = _FleetRequest(uid, prompt, deadline_s, max_new_tokens,
                          self.clock(), tenant)
        verdict = self._try_dispatch(r)
        if isinstance(verdict, Admitted):
            self._active[uid] = r
            self.observatory.note_verdict(uid, "admitted")
        else:
            self._tm_reject.inc(reason=verdict.reason)
            self.observatory.note_verdict(uid, verdict.reason)
            self._record_result(RequestResult(
                uid, REJECTED, [], verdict.reason,
                getattr(verdict, "detail", ""), tenant=tenant))
        self._refresh_gauges()
        return verdict

    # ------------------------------------------------------------------ #
    # lifecycle
    # ------------------------------------------------------------------ #
    def _record_result(self, result: RequestResult) -> None:
        """Exactly-one-terminal guard: the FIRST terminal state for a uid
        wins; later resolution attempts are no-ops (a hedge completion
        racing a failover must not produce two verdicts)."""
        if result.uid in self._results:
            return
        self._active.pop(result.uid, None)
        self._results[result.uid] = result
        while len(self._results) > self.cfg.max_result_history:
            self._results.pop(next(iter(self._results)))
        self._tm_resolved.inc(outcome=result.state)
        self._tm_t_resolved.inc(tenant=self.tenancy.label(result.tenant),
                                outcome=result.state)
        # every token in a terminal result IS delivered to the caller —
        # partial expired/failed output included — so it is goodput; the
        # discarded copies were already attributed by note_waste at the
        # moment each copy lost
        self.observatory.note_goodput(len(result.tokens))
        self.observatory.note_terminal(result.uid, result.state,
                                       result.reason, len(result.tokens))

    def _cancel_copy(self, r: _FleetRequest, name: Optional[str],
                     reason: str) -> None:
        if name is None:
            return
        rep = self._by_name(name)
        if rep is not None:
            rep.frontend.cancel(r.uid, reason=reason)
            rep.frontend.drop_result(r.uid)

    def _resolve(self, r: _FleetRequest, state: str, tokens: List[int],
                 reason: str = "", detail: str = "",
                 discard_reason: str = "hedge_lost") -> None:
        """Fleet terminal resolution: cancel every remaining copy (KV
        blocks released on every replica) then record once. Any copy
        still generating when the request resolves is a discarded
        duplicate stream — its progress is waste (``discard_reason``;
        the default covers the common case of a losing hedge copy,
        shutdown passes ``evicted``)."""
        for name in (r.replica, r.hedge):
            if name is not None:
                rep = self._by_name(name)
                if rep is not None:
                    snap = rep.frontend.rematerialize(r.uid)
                    if snap is not None and snap["generated"]:
                        self.observatory.note_waste(
                            discard_reason, len(snap["generated"]))
            self._cancel_copy(r, name, reason=f"fleet_{state}")
        r.replica = r.hedge = None
        self._record_result(RequestResult(r.uid, state,
                                          tokens[:r.max_new_tokens],
                                          reason, detail, tenant=r.tenant))

    def _lose_copy(self, r: _FleetRequest, rep: _Replica, reason: str,
                   count_attempt: bool = True, backoff: bool = True,
                   tokens: Optional[List[int]] = None) -> None:
        """One copy of ``r`` is gone (sick replica, drain migration, or
        the replica itself shed/failed it). Re-materialize whatever it
        generated, cancel it there, and either let the surviving hedge
        copy carry on or schedule a resubmission — bounded attempts, then
        a structured terminal ``failed``. ``tokens`` supplies the copy's
        progress when the replica already resolved it (rematerialize only
        answers for ACTIVE uids)."""
        snap = rep.frontend.rematerialize(r.uid)
        self._cancel_copy(r, rep.name, reason=f"fleet_failover_{reason}")
        is_hedge = r.hedge == rep.name
        if is_hedge:
            r.hedge = None
        if r.replica == rep.name:
            r.replica = None
        r.excluded.add(rep.name)
        r.last_reason = reason
        self._tm_failover.inc(reason=reason)
        self.observatory.note_hop(
            r.uid, "migration" if reason == "drain" else "failover",
            rep.name, reason=reason)
        other = r.hedge if not is_hedge else r.replica
        if other is not None:
            # the surviving copy (same payload, greedy-deterministic
            # stream) carries on; don't fold the loser's tokens — the
            # survivor has its own copy of the same stream, so the
            # loser's progress is pure discarded computation
            lost_n = (len(snap["generated"]) if snap is not None
                      else len(tokens or []))
            if lost_n:
                self.observatory.note_waste(
                    {"shed": "shed", "failed": "evicted"}.get(
                        reason, "hedge_lost"), lost_n)
            if is_hedge:
                self._tm_hedges.inc(outcome="lost")
            else:
                r.replica, r.hedge = r.hedge, None
            return
        # no survivor: fold the lost copy's progress into the payload so
        # the next replica continues instead of restarting
        gen = snap["generated"] if snap is not None else (tokens or [])
        if gen:
            r.carried.extend(gen)
            r.prompt = list(r.prompt) + list(gen)
        if len(r.carried) >= r.max_new_tokens:
            # the lost copy had already generated the full grant
            self._resolve(r, COMPLETED, list(r.carried))
            return
        if not count_attempt:
            # drain migration is not a failure: hand the attempt back so
            # moving a request off a healthy replica can never exhaust
            # its failover budget
            r.attempts = max(0, r.attempts - 1)
        elif r.attempts >= self.cfg.max_attempts or all(
                rr.name in r.excluded for rr in self._replicas):
            # bounded: attempts spent, OR every replica in the fleet has
            # already lost a copy of this request — a fleet smaller than
            # max_attempts must still terminate, not spin on
            # no_ready_replica forever
            self._resolve(
                r, FAILED, list(r.carried), reason=reason,
                detail=f"{r.attempts} attempts exhausted "
                       f"(excluded: {sorted(r.excluded)})")
            return
        if count_attempt and backoff:
            ramp = min(self.cfg.retry_backoff_s * (2 ** (r.attempts - 1)),
                       self.cfg.retry_backoff_max_s)
            wait = ramp * (1.0 + self.cfg.retry_jitter_frac
                           * self._rng.random())
        else:
            wait = 0.0   # migration redispatches immediately
        r.next_retry_t = self.clock() + wait

    def _detect_failures(self) -> None:
        """Hang-vs-crash detection: a replica whose last tick blocked
        past ``heartbeat_stale_s`` is hung; a replica whose circuit is
        OPEN is crashed. Either way its in-flight fleet requests fail
        over to the survivors.

        Deliberately DURATION-based, not heartbeat-age-based: this
        router shares the replicas' thread, so while one replica's tick
        blocks, EVERY other replica's heartbeat ages — an age check here
        would flag healthy replicas for their sick neighbor's stall. The
        age signal (``last_tick_age_s()``) is for genuinely concurrent
        observers: the health-probe thread, or a router driving replicas
        on worker threads, sees age grow WHILE the tick is blocked."""
        stale = self.cfg.heartbeat_stale_s
        for rep in self._replicas:
            fe = rep.frontend
            was_hung = rep.hung
            rep.hung = fe.last_tick_duration_s > stale
            if rep.hung and not was_hung:
                logger.warning(
                    f"fleet: replica {rep.name} is hung (last tick "
                    f"{fe.last_tick_duration_s:.3f}s, stale deadline "
                    f"{stale}s) — failing over its in-flight requests")
            if rep.hung:
                self._failover_replica(rep, "replica_hung")
            elif fe.breaker.state == OPEN:
                self._failover_replica(rep, "circuit_open")

    def _hung_probe_due(self, rep: _Replica) -> bool:
        """Whether a hung replica has earned its next recovery probe:
        at least ``heartbeat_stale_s`` since its last tick ENDED (entry
        stamp + duration — the entry stamp alone would re-probe
        immediately after every blocked tick returns)."""
        fe = rep.frontend
        if fe.last_tick_t is None:
            return True
        since_end = fe.clock() - (fe.last_tick_t + fe.last_tick_duration_s)
        return since_end >= self.cfg.heartbeat_stale_s

    def _failover_replica(self, rep: _Replica, reason: str,
                          count_attempt: bool = True,
                          backoff: bool = True) -> None:
        for r in list(self._active.values()):
            if rep.name in (r.replica, r.hedge):
                self._lose_copy(r, rep, reason, count_attempt=count_attempt,
                                backoff=backoff)

    def _harvest(self) -> None:
        """Fold replica-level terminal states into fleet lifecycle:
        completion/expiry resolve the fleet request (first completion wins
        under hedging, the loser is cancelled); a copy the replica shed or
        failed (poison eviction) re-enters the failover path."""
        now = self.clock()
        for r in list(self._active.values()):
            for name in (r.replica, r.hedge):
                if name is None or r.uid not in self._active:
                    continue
                rep = self._by_name(name)
                res = self._copy_result(rep, r.uid) if rep else None
                if res is None:
                    # replica replaced/record dropped under us: lost copy
                    if rep is not None:
                        self._lose_copy(r, rep, "failed")
                    continue
                if res.state == ACTIVE:
                    continue
                if res.state == COMPLETED:
                    # hedge won/lost only means something while BOTH
                    # copies are in play (a promoted hedge completing
                    # solo is a failover rescue, not a race outcome)
                    if name == r.hedge and r.replica is not None:
                        self._tm_hedges.inc(outcome="won")
                    elif name == r.replica and r.hedge is not None:
                        self._tm_hedges.inc(outcome="lost")
                    if name == r.hedge:
                        r.hedge = None
                    if name == r.replica:
                        r.replica = None
                    rep.frontend.drop_result(r.uid)
                    self._lat_samples.append(now - r.submit_t)
                    self._tm_request_s.observe(now - r.submit_t)
                    self._resolve(r, COMPLETED, r.carried + res.tokens)
                elif res.state == EXPIRED:
                    # the deadline is request-global: the other copy is on
                    # the same clock — resolve unless it already finished
                    if name == r.hedge:
                        r.hedge = None
                    if name == r.replica:
                        r.replica = None
                    rep.frontend.drop_result(r.uid)
                    self._resolve(r, EXPIRED, r.carried + res.tokens,
                                  reason=res.reason or "deadline")
                else:
                    # shed / failed / rejected on the replica: that copy
                    # is lost — failover machinery decides retry/terminal
                    self._lose_copy(r, rep, res.state, tokens=res.tokens)

    def _hedge_threshold_s(self) -> float:
        # the windowed histogram quantile is the primary source (it ages
        # out a cold-start's slow completions; the ring buffer doesn't);
        # the ring remains the fallback for clocks the window can't serve
        wq = self._tm_request_s.windowed_quantile(self.cfg.hedge_percentile)
        if wq is not None:
            return max(self.cfg.hedge_min_s, wq)
        if not self._lat_samples:
            return self.cfg.hedge_min_s
        ordered = sorted(self._lat_samples)
        idx = min(len(ordered) - 1,
                  int(len(ordered) * self.cfg.hedge_percentile))
        return max(self.cfg.hedge_min_s, ordered[idx])

    def _hedge_scan(self) -> None:
        if not self.cfg.hedge_enabled:
            return
        now = self.clock()
        threshold = self._hedge_threshold_s()
        for r in list(self._active.values()):
            if r.replica is None or r.hedge is not None or r.hedged:
                continue
            if now - r.dispatch_t <= threshold:
                continue
            deadline = None
            if r.deadline_s is not None:
                deadline = r.deadline_s - (now - r.submit_t)
                if deadline <= 0:
                    continue   # expiry will resolve it; no point hedging
            remaining = max(1, r.max_new_tokens - len(r.carried))
            # the hedge goes to a replica OTHER than the primary (and not
            # one this request already lost)
            for rep in self._candidates(len(r.prompt), remaining,
                                        r.excluded | {r.replica}):
                res = rep.frontend.submit(r.uid, r.prompt,
                                          deadline_s=deadline,
                                          max_new_tokens=remaining,
                                          tenant=r.tenant,
                                          charge_quota=False)
                if isinstance(res, Admitted):
                    r.hedge = rep.name
                    r.hedged = True
                    self._tm_hedges.inc(outcome="spawned")
                    self._tm_routed.inc(replica=rep.name)
                    self.observatory.note_hop(r.uid, "hedge", rep.name)
                    if r.carried:
                        # the hedge copy re-prefills the carried tokens
                        # exactly as a failover re-dispatch would
                        self.observatory.note_waste("failover_replay",
                                                    len(r.carried))
                break   # one placement attempt per scan — no storms

    def _retry_due(self) -> None:
        now = self.clock()
        for r in list(self._active.values()):
            if r.replica is not None or r.hedge is not None:
                continue
            if r.deadline_s is not None \
                    and now - r.submit_t >= r.deadline_s:
                self._resolve(r, EXPIRED, list(r.carried),
                              reason="deadline",
                              detail="expired waiting for failover")
                continue
            if all(rr.name in r.excluded for rr in self._replicas):
                # belt-and-braces twin of the _lose_copy check: replica
                # replacement can shrink the name set under a waiting
                # request — an all-excluded request can never place
                self._resolve(r, FAILED, list(r.carried),
                              reason=r.last_reason or "failed",
                              detail=f"{r.attempts} attempts exhausted "
                                     f"(excluded: {sorted(r.excluded)})")
                continue
            if r.next_retry_t is not None and now < r.next_retry_t:
                continue
            verdict = self._try_dispatch(r)
            if isinstance(verdict, Admitted):
                self._tm_retries.inc()
            elif isinstance(verdict, Rejected):
                # re-materialized payload invalid (e.g. grew past the
                # target engine's max_len): structured terminal, bounded
                self._resolve(r, FAILED, list(r.carried),
                              reason=r.last_reason or "invalid",
                              detail=verdict.detail)
            else:
                # every candidate overloaded: wait out its retry-after
                # hint (capped — the fleet loop must keep polling faster
                # than coarse backlog estimates suggest)
                r.next_retry_t = now + min(verdict.retry_after_s,
                                           self.cfg.retry_backoff_max_s)

    def run_tick(self) -> int:
        """One fleet scheduling pass: detect hung/crashed replicas and
        fail their work over, place due retries and hedges, tick every
        replica (absorbing failures — the frontends never raise), and
        fold completions. Returns the number of replica ticks attempted.

        Placement runs BEFORE the ticks: an open circuit whose backoff
        window just expired admits exactly one half-open probe, and the
        fleet's own idle tick of that replica would otherwise consume it
        — with every replica sick, retries waiting on an expired window
        would starve forever behind empty probe ticks."""
        self._detect_failures()
        self._retry_due()
        self._hedge_scan()
        ticked = 0
        for rep in self._replicas:
            if rep.hung and not self._hung_probe_due(rep):
                # a hung replica's tick BLOCKS this shared thread: probing
                # it on every pass would stall the survivors the failover
                # just rescued work onto — probe at most once per stale
                # window instead
                continue
            rep.frontend.run_tick()
            ticked += 1
        self._harvest()
        self._detect_failures()   # a tick may have just opened a circuit
        self._retry_due()         # ...and its failed-over work can often
        self._refresh_gauges()    # re-place on a survivor immediately
        self.slo.evaluate()       # burn rates see this tick's terminals
        return ticked

    def run_until_drained(self, max_ticks: int = 10_000,
                          deadline_s: Optional[float] = None) -> int:
        """Fleet ticks until no fleet request is active (or ``max_ticks``
        / ``deadline_s``); returns passes consumed. Between passes where
        no replica holds work but requests wait on retry backoff, sleeps
        a hair under the real clock (an injected clock's owner advances
        time itself)."""
        passes = 0
        t0 = self.clock()
        while self._active and passes < max_ticks:
            if deadline_s is not None and self.clock() - t0 >= deadline_s:
                break
            self.run_tick()
            passes += 1
            if self._active and self.clock is time.monotonic and not any(
                    rep.frontend.active_count() for rep in self._replicas):
                time.sleep(0.002)
        return passes

    # ------------------------------------------------------------------ #
    # draining + rolling restart
    # ------------------------------------------------------------------ #
    def _resolve_replica(self, which: Union[int, str, ServingFrontend]
                         ) -> _Replica:
        if isinstance(which, int):
            return self._replicas[which]
        for rep in self._replicas:
            if rep.name == which or rep.frontend is which:
                return rep
        raise KeyError(f"no replica {which!r} in this fleet")

    def drain(self, which, migrate: Optional[bool] = None) -> None:
        """Stop routing NEW work to a replica. ``migrate=True`` (default
        from config) moves its in-flight fleet requests to the survivors
        immediately (re-materialized, no attempt penalty); ``False`` lets
        them finish in place. Either way the replica keeps ticking until
        quiesced — rolling restarts wait on :meth:`quiesced`."""
        rep = self._resolve_replica(which)
        rep.draining = True
        if migrate is None:
            migrate = self.cfg.migrate_on_drain
        if migrate:
            self._failover_replica(rep, "drain", count_attempt=False,
                                   backoff=False)
            self._retry_due()
        self._refresh_gauges()

    def undrain(self, which) -> None:
        rep = self._resolve_replica(which)
        rep.draining = False
        self._refresh_gauges()

    def quiesced(self, which) -> bool:
        """True when a (draining) replica holds no fleet request and its
        frontend has nothing active — safe to close/replace."""
        rep = self._resolve_replica(which)
        if rep.frontend.active_count():
            return False
        return all(rep.name not in (r.replica, r.hedge)
                   for r in self._active.values())

    def replace_replica(self, which, new_frontend: ServingFrontend
                        ) -> ServingFrontend:
        """Rolling-restart swap: migrate any remaining in-flight work off
        the old replica, close its frontend, and install the new one
        (immediately routable). Returns the closed frontend."""
        rep = self._resolve_replica(which)
        # validate BEFORE any side effect: a collision must fail cleanly,
        # not leave a closed frontend installed and routable
        if any(r.name == new_frontend.name
               for r in self._replicas if r is not rep):
            raise ValueError(
                f"replacement name {new_frontend.name!r} collides with a "
                "live replica")
        self._failover_replica(rep, "drain", count_attempt=False,
                               backoff=False)
        # per-tenant quotas survive the swap: the replacement joins the
        # fleet's shared registry (its own in-flight charges, if any,
        # transfer over)
        new_frontend.adopt_tenancy(self.tenancy)
        new_frontend.observatory = self.observatory
        old = rep.frontend
        old.close()
        rep.frontend = new_frontend
        rep.name = new_frontend.name
        rep.draining = False
        rep.hung = False
        self._retry_due()
        self._refresh_gauges()
        return old

    def add_replica(self, new_frontend: ServingFrontend) -> None:
        """Scale-out: install a new replica, immediately routable. Waiting
        retries re-place onto it in the same call — a scale-out triggered
        by ``no_ready_replica`` backpressure takes effect at once."""
        if any(r.name == new_frontend.name for r in self._replicas):
            raise ValueError(
                f"replica name {new_frontend.name!r} collides with a "
                "live replica")
        new_frontend.adopt_tenancy(self.tenancy)
        new_frontend.observatory = self.observatory
        self._replicas.append(_Replica(new_frontend))
        self._retry_due()
        self._refresh_gauges()

    def remove_replica(self, which) -> ServingFrontend:
        """Scale-in: migrate any in-flight work off the replica (no
        attempt penalty — shrinking the fleet is not a failure), close
        its frontend, and drop it from the routing set. The last replica
        cannot be removed. Returns the closed frontend; callers wanting
        a graceful shrink ``drain()`` first and wait on ``quiesced()``
        so the migration set is empty by the time this runs."""
        rep = self._resolve_replica(which)
        if len(self._replicas) == 1:
            raise ValueError("cannot remove the last replica of a fleet")
        self._failover_replica(rep, "drain", count_attempt=False,
                               backoff=False)
        # a removed name must not poison waiting requests' excluded sets:
        # the name may be reused by a future scale-out
        for r in self._active.values():
            r.excluded.discard(rep.name)
        self._replicas.remove(rep)
        rep.frontend.close()
        self._retry_due()
        self._refresh_gauges()
        return rep.frontend

    # ------------------------------------------------------------------ #
    # health quorum
    # ------------------------------------------------------------------ #
    def _replica_ready(self, rep: _Replica) -> bool:
        fe = rep.frontend
        return (not rep.draining and not rep.hung
                and fe.breaker.state == CLOSED
                and fe.active_count() < fe.cfg.max_queue)

    def ready_count(self) -> int:
        return sum(1 for rep in self._replicas if self._replica_ready(rep))

    def readiness(self):
        """Quorum readiness: ok iff ≥ ``min_ready_replicas`` replicas are
        routable — the load balancer's drain signal for the whole fleet."""
        detail: Dict[str, Any] = {}
        for rep in self._replicas:
            detail[rep.name] = {
                "ready": self._replica_ready(rep),
                "circuit": rep.frontend.breaker.state,
                "draining": rep.draining,
                "hung": rep.hung,
                "queue": rep.frontend.active_count(),
            }
        n = sum(1 for d in detail.values() if d["ready"])
        return n >= self.cfg.min_ready_replicas, {
            "ready_replicas": n,
            "min_ready_replicas": self.cfg.min_ready_replicas,
            "replicas": detail,
        }

    def liveness(self):
        """The fleet is live while ANY replica is not hung — all replicas
        wedged with work pending is the restart-the-pod signal."""
        hung = [rep.name for rep in self._replicas if rep.hung]
        return len(hung) < len(self._replicas), {
            "replicas": len(self._replicas), "hung": hung}

    def _refresh_gauges(self) -> None:
        self._tm_ready.set(self.ready_count())
        self._tm_active.set(len(self._active))

    # ------------------------------------------------------------------ #
    def close(self, close_replicas: bool = True) -> None:
        """Unregister fleet probes and force-fail any still-active fleet
        request (copies cancelled on their replicas — blocks released).
        Force-failed in-flight requests count as ``fleet_requests_lost``:
        a clean shutdown drains first."""
        for r in list(self._active.values()):
            self._tm_lost.inc()
            self._resolve(r, FAILED, list(r.carried), reason="shutdown",
                          discard_reason="evicted")
        self.slo.close()
        if self.health_name is not None:
            telemetry.unregister_health_probe("live", self.health_name)
            telemetry.unregister_health_probe("ready", self.health_name)
            self.health_name = None
        if close_replicas:
            for rep in self._replicas:
                rep.frontend.close()

    def __enter__(self) -> "FleetRouter":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


class FleetAutoscaler:
    """Scale-out/in policy over a :class:`FleetRouter`.

    Decisions run off three signals the fleet already measures — no new
    instrumentation on the hot path:

    * **queue depth**: mean active fleet requests per ready replica;
      above ``scale_out_queue_depth`` → out, below ``scale_in_queue_depth``
      (with more than the floor running) → in.
    * **KV pressure**: the max ``kv_utilization`` across ready replicas;
      above ``scale_out_kv_util`` → out (a near-full pool is about to
      preempt — adding a replica beats thrashing the one that's full).
    * **p99 latency**: the fleet completion-latency p99; above
      ``scale_out_p99_latency_s`` (when > 0 — 0 disables the signal) → out.

    Scale-out calls ``replica_factory(name) -> ServingFrontend`` and
    installs the result immediately. Scale-in is the zero-loss path:
    drain the least-loaded ready replica WITH migration, then keep
    watching ``quiesced()`` across ticks and only close+remove once its
    last in-flight copy is gone — an admitted request can never be lost
    to a shrink. One resize at a time, ``autoscale_cooldown_ticks``
    between decisions, bounded by ``autoscale_min/max_replicas``.

    Drive it with ``tick()`` after each ``router.run_tick()``; it is as
    single-threaded as the router it steers. Events:
    ``fleet_scale_events_total{direction,reason}``.
    """

    def __init__(self, router: FleetRouter, replica_factory,
                 config=None, replica_prefix: str = "scale"):
        self.router = router
        self.replica_factory = replica_factory
        self.cfg = config if config is not None else router.cfg
        self.cfg.validate()
        self.replica_prefix = replica_prefix
        self._cooldown = 0
        self._seq = 0
        self._victim: Optional[str] = None   # scale-in drain in flight
        self.events: List[Dict[str, str]] = []
        self._tm_scale = telemetry.counter(
            "fleet_scale_events_total",
            "autoscaler resize events by direction and triggering reason "
            "(queue_depth / kv_pressure / latency / slo_burn / idle)")

    # ------------------------------------------------------------ signals
    def signals(self) -> Dict[str, float]:
        """The decision inputs, as measured this instant."""
        router = self.router
        ready = max(1, router.ready_count())
        kv = 0.0
        for rep in router._replicas:
            if router._replica_ready(rep):
                kv = max(kv, rep.frontend.engine.kv_utilization(0))
        p99 = router.latency_quantile(0.99)
        return {
            "queue_depth": router.active_count() / ready,
            "kv_util": kv,
            "p99_latency_s": p99 if p99 is not None else 0.0,
        }

    def _decide(self, sig: Dict[str, float]):
        """(direction, reason) or None. Scale-out wins ties: shedding
        load is the failure mode that costs users, idle capacity only
        costs chips."""
        n = len(self.router._replicas)
        if n < self.cfg.autoscale_max_replicas:
            if sig["queue_depth"] > self.cfg.scale_out_queue_depth:
                return "out", "queue_depth"
            if sig["kv_util"] > self.cfg.scale_out_kv_util:
                return "out", "kv_pressure"
            if 0 < self.cfg.scale_out_p99_latency_s < sig["p99_latency_s"]:
                return "out", "latency"
            slo = getattr(self.router, "slo", None)
            if slo is not None and slo.wants_scale_out():
                # opt-in (slo.autoscale_on_burn): a firing burn alert on
                # a latency/availability objective is the leading signal
                # the lagging queue/kv thresholds confirm too late
                return "out", "slo_burn"
        if n > self.cfg.autoscale_min_replicas \
                and sig["queue_depth"] < self.cfg.scale_in_queue_depth:
            return "in", "idle"
        return None

    def _next_name(self) -> str:
        live = {rep.name for rep in self.router._replicas}
        while True:
            name = f"{self.replica_prefix}-{self._seq}"
            self._seq += 1
            if name not in live:
                return name

    def _record(self, direction: str, reason: str) -> None:
        self._tm_scale.inc(direction=direction, reason=reason)
        self.events.append({"direction": direction, "reason": reason})
        self._cooldown = self.cfg.autoscale_cooldown_ticks

    # ------------------------------------------------------------ driving
    def pending(self) -> bool:
        """A scale-in victim is still draining."""
        return self._victim is not None

    def tick(self) -> Optional[str]:
        """One policy pass. Returns the action taken ("out", "in",
        "in_pending") or None."""
        router = self.router
        if self._victim is not None:
            # finish the in-flight shrink before any new decision — and
            # before the cooldown clock, so a long drain can't stack a
            # second resize right behind the first
            if router._by_name(self._victim) is None:
                self._victim = None      # replaced/removed under us
            elif router.quiesced(self._victim):
                router.remove_replica(self._victim)
                logger.info(
                    f"fleet autoscaler: scale-in complete, removed "
                    f"{self._victim} ({len(router._replicas)} replicas)")
                self._victim = None
            else:
                return "in_pending"
        if self._cooldown > 0:
            self._cooldown -= 1
            return None
        decision = self._decide(self.signals())
        if decision is None:
            return None
        direction, reason = decision
        if direction == "out":
            name = self._next_name()
            fe = self.replica_factory(name)
            router.add_replica(fe)
            logger.info(
                f"fleet autoscaler: scale-out +{name} (reason={reason}, "
                f"{len(router._replicas)} replicas)")
        else:
            # least-loaded ready replica quiesces fastest and loses the
            # least migration work
            cands = [rep for rep in router._replicas
                     if router._replica_ready(rep)]
            if len(cands) <= self.cfg.autoscale_min_replicas:
                return None
            victim = min(cands,
                         key=lambda rep: (rep.frontend.active_count(),
                                          rep.name))
            self._victim = victim.name
            router.drain(victim.name, migrate=True)
            logger.info(
                f"fleet autoscaler: scale-in draining {victim.name} "
                f"(reason={reason})")
        self._record(direction, reason)
        return direction
