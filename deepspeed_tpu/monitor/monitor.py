"""Monitoring fan-out: TensorBoard / CSV / W&B.

Parity: reference ``monitor/monitor.py:30`` (``MonitorMaster`` fanning out to
``TensorBoardMonitor``, ``WandbMonitor``, ``csvMonitor``). Events are
``(tag, value, step)`` triples written from process 0 only (SPMD: every host has
identical values; writing once is the rank-0 gating analog).
"""
from __future__ import annotations

import csv
import os
from typing import Any, List, Optional, Tuple

import jax

from deepspeed_tpu.utils.logging import logger

Event = Tuple[str, Any, int]


class Monitor:
    def __init__(self, config):
        self.enabled = bool(getattr(config, "enabled", False))

    def write_events(self, events: List[Event]) -> None:
        raise NotImplementedError


class csvMonitor(Monitor):
    def __init__(self, config):
        super().__init__(config)
        self.output_path = getattr(config, "output_path", "") or "./csv_monitor"
        self.job_name = getattr(config, "job_name", "job")
        self._files = {}
        if self.enabled and jax.process_index() == 0:
            os.makedirs(os.path.join(self.output_path, self.job_name), exist_ok=True)

    def write_events(self, events: List[Event]) -> None:
        if not self.enabled or jax.process_index() != 0:
            return
        for tag, value, step in events:
            fname = os.path.join(self.output_path, self.job_name,
                                 tag.replace("/", "_") + ".csv")
            new = not os.path.exists(fname)
            with open(fname, "a", newline="") as f:
                w = csv.writer(f)
                if new:
                    w.writerow(["step", tag])
                w.writerow([step, float(value)])


class TensorBoardMonitor(Monitor):
    def __init__(self, config):
        super().__init__(config)
        self.writer = None
        if self.enabled and jax.process_index() == 0:
            try:
                from torch.utils.tensorboard import SummaryWriter

                path = os.path.join(getattr(config, "output_path", "") or "./runs",
                                    getattr(config, "job_name", "job"))
                self.writer = SummaryWriter(log_dir=path)
            except Exception as e:  # tensorboard optional
                logger.warning(f"tensorboard unavailable: {e}")
                self.enabled = False

    def write_events(self, events: List[Event]) -> None:
        if self.writer is None:
            return
        for tag, value, step in events:
            self.writer.add_scalar(tag, float(value), step)
        self.writer.flush()


class WandbMonitor(Monitor):
    def __init__(self, config):
        super().__init__(config)
        self.run = None
        if self.enabled and jax.process_index() == 0:
            try:
                import wandb

                self.run = wandb.init(
                    project=getattr(config, "project", None) or "deepspeed_tpu",
                    group=getattr(config, "group", None),
                    name=getattr(config, "job_name", None))
            except Exception as e:
                logger.warning(f"wandb unavailable: {e}")
                self.enabled = False

    def write_events(self, events: List[Event]) -> None:
        if self.run is None:
            return
        import wandb

        for tag, value, step in events:
            wandb.log({tag: float(value)}, step=step)


class CometMonitor(Monitor):
    """Comet ML backend (reference ``monitor/comet.py``)."""

    def __init__(self, config):
        super().__init__(config)
        self.experiment = None
        if self.enabled and jax.process_index() == 0:
            try:
                import comet_ml

                self.experiment = comet_ml.Experiment(
                    project_name=getattr(config, "project", None),
                    workspace=getattr(config, "team", None))
                name = getattr(config, "job_name", None)
                if name:
                    self.experiment.set_name(name)
            except Exception as e:
                logger.warning(f"comet_ml unavailable: {e}")
                self.enabled = False

    def write_events(self, events: List[Event]) -> None:
        if self.experiment is None:
            return
        for tag, value, step in events:
            self.experiment.log_metric(tag, float(value), step=step)


class MonitorMaster(Monitor):
    """Fan-out to all enabled backends (reference ``monitor/monitor.py:30``)."""

    def __init__(self, ds_config):
        self.backends: List[Monitor] = []
        for backend_cls, cfg in (
            (TensorBoardMonitor, ds_config.tensorboard),
            (csvMonitor, ds_config.csv_monitor),
            (WandbMonitor, ds_config.wandb),
            (CometMonitor, ds_config.comet),
        ):
            if getattr(cfg, "enabled", False):
                self.backends.append(backend_cls(cfg))
        self.enabled = any(b.enabled for b in self.backends)

    def write_events(self, events: List[Event]) -> None:
        for b in self.backends:
            if b.enabled:
                b.write_events(events)
