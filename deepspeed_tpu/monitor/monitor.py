"""Monitoring fan-out: TensorBoard / CSV / W&B.

Parity: reference ``monitor/monitor.py:30`` (``MonitorMaster`` fanning out to
``TensorBoardMonitor``, ``WandbMonitor``, ``csvMonitor``). Events are
``(tag, value, step)`` triples written from process 0 only (SPMD: every host has
identical values; writing once is the rank-0 gating analog).
"""
from __future__ import annotations

import csv
import os
from typing import Any, List, Optional, Tuple

import jax

from deepspeed_tpu.utils.logging import logger

Event = Tuple[str, Any, int]


class Monitor:
    def __init__(self, config):
        self.enabled = bool(getattr(config, "enabled", False))

    def write_events(self, events: List[Event]) -> None:
        raise NotImplementedError

    def close(self) -> None:
        """Flush and release backend resources (file handles, writers).
        Safe to call more than once; a closed monitor may still receive
        write_events (it reopens or no-ops per backend)."""


class csvMonitor(Monitor):
    def __init__(self, config):
        super().__init__(config)
        self.output_path = getattr(config, "output_path", "") or "./csv_monitor"
        self.job_name = getattr(config, "job_name", "job")
        # tag -> open append-mode file handle; without the cache every event
        # paid an open/close syscall pair (the cache existed but was unused)
        self._files = {}
        if self.enabled and jax.process_index() == 0:
            os.makedirs(os.path.join(self.output_path, self.job_name), exist_ok=True)

    def _file_for(self, tag: str):
        f = self._files.get(tag)
        if f is None or f.closed:
            fname = os.path.join(self.output_path, self.job_name,
                                 tag.replace("/", "_") + ".csv")
            new = not os.path.exists(fname) or os.path.getsize(fname) == 0
            f = open(fname, "a", newline="")
            if new:
                csv.writer(f).writerow(["step", tag])
            self._files[tag] = f
        return f

    def write_events(self, events: List[Event]) -> None:
        if not self.enabled or jax.process_index() != 0:
            return
        touched = set()
        for tag, value, step in events:
            f = self._file_for(tag)
            csv.writer(f).writerow([step, float(value)])
            touched.add(tag)
        for tag in touched:   # one flush per batch, not per event — readers
            self._files[tag].flush()   # (tests, tail -f) see complete rows

    def close(self) -> None:
        for f in self._files.values():
            if not f.closed:
                f.flush()
                f.close()
        self._files.clear()



class TensorBoardMonitor(Monitor):
    def __init__(self, config):
        super().__init__(config)
        self.writer = None
        if self.enabled and jax.process_index() == 0:
            try:
                from torch.utils.tensorboard import SummaryWriter

                path = os.path.join(getattr(config, "output_path", "") or "./runs",
                                    getattr(config, "job_name", "job"))
                self.writer = SummaryWriter(log_dir=path)
            except Exception as e:  # tensorboard optional
                logger.warning(f"tensorboard unavailable: {e}")
                self.enabled = False

    def write_events(self, events: List[Event]) -> None:
        if self.writer is None:
            return
        for tag, value, step in events:
            self.writer.add_scalar(tag, float(value), step)
        self.writer.flush()

    def close(self) -> None:
        if self.writer is not None:
            self.writer.close()
            self.writer = None


class WandbMonitor(Monitor):
    def __init__(self, config):
        super().__init__(config)
        self.run = None
        if self.enabled and jax.process_index() == 0:
            try:
                import wandb

                self.run = wandb.init(
                    project=getattr(config, "project", None) or "deepspeed_tpu",
                    group=getattr(config, "group", None),
                    name=getattr(config, "job_name", None))
            except Exception as e:
                logger.warning(f"wandb unavailable: {e}")
                self.enabled = False

    def write_events(self, events: List[Event]) -> None:
        if self.run is None:
            return
        import wandb

        for tag, value, step in events:
            wandb.log({tag: float(value)}, step=step)

    def close(self) -> None:
        if self.run is not None:
            self.run.finish()
            self.run = None


class CometMonitor(Monitor):
    """Comet ML backend (reference ``monitor/comet.py``)."""

    def __init__(self, config):
        super().__init__(config)
        self.experiment = None
        if self.enabled and jax.process_index() == 0:
            try:
                import comet_ml

                self.experiment = comet_ml.Experiment(
                    project_name=getattr(config, "project", None),
                    workspace=getattr(config, "team", None))
                name = getattr(config, "job_name", None)
                if name:
                    self.experiment.set_name(name)
            except Exception as e:
                logger.warning(f"comet_ml unavailable: {e}")
                self.enabled = False

    def write_events(self, events: List[Event]) -> None:
        if self.experiment is None:
            return
        for tag, value, step in events:
            self.experiment.log_metric(tag, float(value), step=step)


class MonitorMaster(Monitor):
    """Fan-out to all enabled backends (reference ``monitor/monitor.py:30``)."""

    def __init__(self, ds_config):
        self.backends: List[Monitor] = []
        for backend_cls, cfg in (
            (TensorBoardMonitor, ds_config.tensorboard),
            (csvMonitor, ds_config.csv_monitor),
            (WandbMonitor, ds_config.wandb),
            (CometMonitor, ds_config.comet),
        ):
            if getattr(cfg, "enabled", False):
                self.backends.append(backend_cls(cfg))
        self.enabled = any(b.enabled for b in self.backends)

    def write_events(self, events: List[Event]) -> None:
        for b in self.backends:
            if not b.enabled:
                continue
            try:
                b.write_events(events)
            except Exception as e:
                # one dead backend (W&B connection drop, full disk) must not
                # abort a training step — count it and keep the others going
                from deepspeed_tpu import telemetry

                telemetry.counter(
                    "monitor_write_errors_total",
                    "monitor backend write_events failures",
                ).inc(backend=type(b).__name__)
                logger.warning(
                    f"monitor backend {type(b).__name__} failed to write "
                    f"({len(events)} events dropped there): {e}")

    def close(self) -> None:
        for b in self.backends:
            try:
                b.close()
            except Exception as e:
                logger.warning(
                    f"monitor backend {type(b).__name__} close failed: {e}")
