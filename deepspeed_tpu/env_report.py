"""Environment / compatibility report — the ``ds_report`` analog.

Parity: reference ``deepspeed/env_report.py`` (``op_report`` :30 + setup
report) printed by ``bin/ds_report``. Reports the JAX/XLA toolchain, device
topology, and the status of every native/Pallas op this framework ships.

CLI: ``python -m deepspeed_tpu.env_report``
"""
from __future__ import annotations

import importlib
import os
import shutil
import subprocess
import sys

GREEN_OK = "[OKAY]"
RED_NO = "[NO]"


def _try_version(mod: str) -> str:
    try:
        m = importlib.import_module(mod)
        return getattr(m, "__version__", "unknown")
    except Exception as e:  # import-time failures vary; surface the type
        return f"{RED_NO} ({type(e).__name__})"


def op_report() -> list:
    """Status of each accelerated op (reference ``op_report``)."""
    rows = []

    def probe(name, fn):
        try:
            fn()
            rows.append((name, GREEN_OK))
        except Exception as e:  # noqa: BLE001
            rows.append((name, f"{RED_NO} ({type(e).__name__})"))

    probe("pallas.flash_attention", lambda: importlib.import_module(
        "deepspeed_tpu.ops.pallas.flash_attention"))
    probe("pallas.fused_adam", lambda: importlib.import_module(
        "deepspeed_tpu.ops.pallas.fused_adam"))
    probe("pallas.norms", lambda: importlib.import_module(
        "deepspeed_tpu.ops.pallas.norms"))
    probe("quantized_collectives", lambda: importlib.import_module(
        "deepspeed_tpu.ops.quantization"))

    def aio():
        from deepspeed_tpu.ops.aio import _build_library

        _build_library()

    probe("aio (csrc build)", aio)
    return rows


def main() -> None:
    import jax

    import deepspeed_tpu

    print("-" * 60)
    print("deepspeed_tpu environment report")
    print("-" * 60)
    print(f"deepspeed_tpu version ... {deepspeed_tpu.__version__}")
    print(f"python .................. {sys.version.split()[0]}")
    print(f"jax ..................... {_try_version('jax')}")
    print(f"flax .................... {_try_version('flax')}")
    print(f"optax ................... {_try_version('optax')}")
    print(f"orbax.checkpoint ........ {_try_version('orbax.checkpoint')}")
    print(f"numpy ................... {_try_version('numpy')}")
    gxx = shutil.which("g++")
    print(f"g++ ..................... {gxx or RED_NO}")
    print("-" * 60)
    print(f"backend ................. {jax.default_backend()}")
    print(f"process count ........... {jax.process_count()}")
    print(f"device count ............ {jax.device_count()}")
    devs = jax.devices()
    if devs:
        print(f"device[0] ............... {devs[0].device_kind}")
    print("-" * 60)
    print("op compatibility:")
    for name, status in op_report():
        print(f"  {name:.<30} {status}")
    print("-" * 60)


if __name__ == "__main__":
    main()
