"""1-bit optimizer family: OnebitAdam, ZeroOneAdam, OnebitLamb.

Parity: reference ``runtime/fp16/onebit/{adam,zoadam,lamb}.py`` (``OnebitAdam``
``adam.py:14``) with the error-compensated compressed allreduce backends
(``runtime/comm/nccl.py:52``, ``compressed.py:58``).

Algorithm (1-bit Adam, NeurIPS'21): run plain Adam for ``freeze_step`` warmup
steps; then **freeze the variance** v and switch to communicating only the
momentum, compressed to sign+scale with per-worker error feedback. ZeroOneAdam
(0/1 Adam) generalizes with learning-rate-free variance refresh intervals that
grow geometrically; 1-bit LAMB adds a frozen per-layer trust-ratio scaling.

TPU split of responsibilities:

* **transport** — on TPU the gradient reduction rides ICI inside the jitted
  step; its compressed form is :func:`deepspeed_tpu.ops.quantization.
  onebit_allreduce` (sign+scale, error feedback) / ``quantized_reduce_scatter``
  (int8), usable via ``shard_map`` when per-rank gradients are explicit.
* **optimizer math** — this module: the frozen-variance schedule, the
  compression error-feedback buffers (which are *state*, checkpointed and
  sharded like moments), and the update rule. The compression operator applied
  to the momentum is exactly the wire format of the compressed collective, so
  convergence behavior matches the reference even when XLA chooses the
  transport.
"""
from __future__ import annotations

import dataclasses
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp

from deepspeed_tpu.ops.optimizer import TPUOptimizer, _tmap

PyTree = Any


def _sign_compress_with_error(x: jax.Array, err: jax.Array
                              ) -> Tuple[jax.Array, jax.Array]:
    """sent = sign(x+err) * mean|x+err|; new_err = (x+err) - sent.

    Tensor-wise scale (the reference compresses per flattened chunk; the scale
    granularity only affects constants, not the error-feedback contraction)."""
    corrected = x.astype(jnp.float32) + err
    scale = jnp.mean(jnp.abs(corrected))
    sent = jnp.where(corrected >= 0, scale, -scale)
    return sent, corrected - sent


@dataclasses.dataclass
class OnebitAdam(TPUOptimizer):
    """1-bit Adam (reference ``runtime/fp16/onebit/adam.py:14``)."""

    betas: Tuple[float, float] = (0.9, 0.999)
    # wire transport for the compressed momentum exchange: (m_new, err) ->
    # (m_eff, new_err). None = local sign compression (convergence parity
    # only); the engine injects a packed-sign ICI allreduce when per-rank
    # gradients are explicit (parallel/compressed.py packed_sign_allreduce,
    # reference runtime/comm/nccl.py:52 compressed_allreduce)
    transport: Optional[Any] = None
    eps: float = 1e-8
    freeze_step: int = 100
    moment_names: Tuple[str, ...] = ("exp_avg", "exp_avg_sq", "worker_error")

    def update(self, grads, state, params, lr=None):
        lr = self.lr if lr is None else lr
        compress = self.transport or _sign_compress_with_error
        b1, b2 = self.betas
        step = state["step"] + 1
        sf = step.astype(jnp.float32)
        # at least one warmup step: the frozen variance must be warm (v=0 with
        # bc2=0 would make the very first frozen update 0/0)
        freeze = max(self.freeze_step, 1)
        frozen = step > freeze
        bc1 = 1.0 - b1 ** sf
        bc2 = 1.0 - b2 ** jnp.minimum(sf, jnp.float32(freeze))

        def leaf(p, g, m, v, err):
            g = g.astype(jnp.float32)
            p32 = p.astype(jnp.float32)
            m_new = b1 * m + (1.0 - b1) * g
            # warmup: exact momentum, variance updates. frozen: compressed
            # momentum (sign+scale, error feedback), variance held.
            m_comp, err_new = compress(m_new, err)
            m_eff = jnp.where(frozen, m_comp, m_new)
            err_eff = jnp.where(frozen, err_new, err)
            v_new = jnp.where(frozen, v, b2 * v + (1.0 - b2) * jnp.square(g))
            upd = (m_eff / bc1) / (jnp.sqrt(v_new / bc2) + self.eps)
            if self.weight_decay:
                upd = upd + self.weight_decay * p32
            return (p32 - lr * upd).astype(p.dtype), m_eff, v_new, err_eff

        out = _tmap(leaf, params, grads, state["exp_avg"], state["exp_avg_sq"],
                    state["worker_error"])
        pick = lambda i: _tmap(lambda o: o[i], out,
                               is_leaf=lambda x: isinstance(x, tuple))
        return pick(0), {"exp_avg": pick(1), "exp_avg_sq": pick(2),
                         "worker_error": pick(3), "step": step}


@dataclasses.dataclass
class ZeroOneAdam(TPUOptimizer):
    """0/1 Adam (reference ``runtime/fp16/onebit/zoadam.py``): after
    ``var_freeze_step`` the variance is refreshed only at checkpoints spaced
    by a geometrically-growing interval (start ``var_update_scaler`` steps,
    doubling after each refresh); between refreshes the variance is held and
    the momentum is communicated compressed. The reference's momentum-sync
    skipping (``local_step_scaler``) chooses when ranks exchange momentum at
    all; under SPMD the transport is one compiled collective, so the policy
    that remains meaningful is the variance-refresh schedule.

    Scalar schedule state (``var_interval``, ``next_var_update``) lives in the
    optimizer state and is checkpointed with it."""

    betas: Tuple[float, float] = (0.9, 0.999)
    eps: float = 1e-8
    var_freeze_step: int = 100
    var_update_scaler: int = 16     # initial refresh interval after freeze
    transport: Optional[Any] = None
    moment_names: Tuple[str, ...] = ("exp_avg", "exp_avg_sq", "worker_error",
                                     "var_interval", "next_var_update")

    def init(self, params):
        state = {name: _tmap(jnp.zeros_like, params)
                 for name in ("exp_avg", "exp_avg_sq", "worker_error")}
        freeze = max(self.var_freeze_step, 1)
        state["var_interval"] = jnp.asarray(self.var_update_scaler, jnp.int32)
        state["next_var_update"] = jnp.asarray(
            freeze + self.var_update_scaler, jnp.int32)
        state["step"] = jnp.zeros((), jnp.int32)
        return state

    def update(self, grads, state, params, lr=None):
        lr = self.lr if lr is None else lr
        compress = self.transport or _sign_compress_with_error
        b1, b2 = self.betas
        step = state["step"] + 1
        sf = step.astype(jnp.float32)
        bc1 = 1.0 - b1 ** sf
        bc2 = 1.0 - b2 ** sf
        frozen = step > max(self.var_freeze_step, 1)
        at_refresh = step >= state["next_var_update"]
        refresh = jnp.logical_or(jnp.logical_not(frozen), at_refresh)
        # geometric growth: the interval doubles at each refresh checkpoint
        grow = jnp.logical_and(frozen, at_refresh)
        new_interval = jnp.where(grow, state["var_interval"] * 2,
                                 state["var_interval"])
        new_next = jnp.where(grow, step + new_interval,
                             state["next_var_update"])

        def leaf(p, g, m, v, err):
            g = g.astype(jnp.float32)
            p32 = p.astype(jnp.float32)
            m_new = b1 * m + (1.0 - b1) * g
            m_comp, err_new = compress(m_new, err)
            m_eff = jnp.where(frozen, m_comp, m_new)
            err_eff = jnp.where(frozen, err_new, err)
            v_new = jnp.where(refresh, b2 * v + (1.0 - b2) * jnp.square(g), v)
            upd = (m_eff / bc1) / (jnp.sqrt(v_new / bc2) + self.eps)
            if self.weight_decay:
                upd = upd + self.weight_decay * p32
            return (p32 - lr * upd).astype(p.dtype), m_eff, v_new, err_eff

        out = _tmap(leaf, params, grads, state["exp_avg"], state["exp_avg_sq"],
                    state["worker_error"])
        pick = lambda i: _tmap(lambda o: o[i], out,
                               is_leaf=lambda x: isinstance(x, tuple))
        return pick(0), {"exp_avg": pick(1), "exp_avg_sq": pick(2),
                         "worker_error": pick(3), "var_interval": new_interval,
                         "next_var_update": new_next, "step": step}


@dataclasses.dataclass
class OnebitLamb(TPUOptimizer):
    """1-bit LAMB (reference ``runtime/fp16/onebit/lamb.py``): LAMB during
    warmup; after freeze, compressed momentum with the per-layer trust ratio
    held at its frozen value (the reference caches ``scaling_coeff``)."""

    betas: Tuple[float, float] = (0.9, 0.999)
    eps: float = 1e-6
    freeze_step: int = 100
    max_coeff: float = 10.0
    min_coeff: float = 0.01
    transport: Optional[Any] = None
    moment_names: Tuple[str, ...] = ("exp_avg", "exp_avg_sq", "worker_error",
                                     "frozen_trust")

    def init(self, params):
        state = {name: _tmap(jnp.zeros_like, params)
                 for name in ("exp_avg", "exp_avg_sq", "worker_error")}
        state["frozen_trust"] = _tmap(
            lambda p: jnp.ones((), jnp.float32), params)
        state["step"] = jnp.zeros((), jnp.int32)
        return state

    def update(self, grads, state, params, lr=None):
        lr = self.lr if lr is None else lr
        compress = self.transport or _sign_compress_with_error
        b1, b2 = self.betas
        step = state["step"] + 1
        sf = step.astype(jnp.float32)
        freeze = max(self.freeze_step, 1)  # ≥1 warmup step: frozen v must be warm
        frozen = step > freeze
        bc1 = 1.0 - b1 ** sf
        bc2 = 1.0 - b2 ** jnp.minimum(sf, jnp.float32(freeze))

        def leaf(p, g, m, v, err, tr):
            g = g.astype(jnp.float32)
            p32 = p.astype(jnp.float32)
            m_new = b1 * m + (1.0 - b1) * g
            m_comp, err_new = compress(m_new, err)
            m_eff = jnp.where(frozen, m_comp, m_new)
            err_eff = jnp.where(frozen, err_new, err)
            v_new = jnp.where(frozen, v, b2 * v + (1.0 - b2) * jnp.square(g))
            upd = (m_eff / bc1) / (jnp.sqrt(v_new / bc2) + self.eps)
            if self.weight_decay:
                upd = upd + self.weight_decay * p32
            w_norm = jnp.linalg.norm(p32)
            u_norm = jnp.linalg.norm(upd)
            live_trust = jnp.where(
                (w_norm > 0) & (u_norm > 0),
                jnp.clip(w_norm / u_norm, self.min_coeff, self.max_coeff), 1.0)
            trust = jnp.where(frozen, tr, live_trust)
            # cache the trust ratio at the freeze boundary
            tr_new = jnp.where(step == freeze, live_trust, trust)
            return (p32 - lr * trust * upd).astype(p.dtype), m_eff, v_new, \
                err_eff, tr_new

        out = _tmap(leaf, params, grads, state["exp_avg"], state["exp_avg_sq"],
                    state["worker_error"], state["frozen_trust"])
        pick = lambda i: _tmap(lambda o: o[i], out,
                               is_leaf=lambda x: isinstance(x, tuple))
        return pick(0), {"exp_avg": pick(1), "exp_avg_sq": pick(2),
                         "worker_error": pick(3), "frozen_trust": pick(4),
                         "step": step}
