"""Functional optimizers — the ops/adam, ops/lion, ops/lamb, ops/adagrad family.

Parity: reference ``ops/adam/fused_adam.py:18`` (FusedAdam, csrc/adam CUDA
multi-tensor kernels), ``ops/lion``, ``ops/lamb``, ``ops/adagrad``,
``zero/muon/muon_optimizer.py:14`` (Muon with aux Adam). On TPU "fusion" is XLA's
job: each update below is a pure jnp expression over the (sharded) state pytree
which XLA fuses into a handful of elementwise kernels per shard — the multi-tensor
apply machinery is unnecessary. A Pallas fused path exists for the hottest case
(see ``deepspeed_tpu/ops/pallas/fused_adam.py``).

State layout mirrors the param pytree per-moment ({"exp_avg": tree, ...}) so the
ZeRO sharding policy (``parallel/partitioning.py``) derives optimizer-state
shardings directly from param shardings — the stage-1 partitioning analog.
"""
from __future__ import annotations

import dataclasses
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp

from deepspeed_tpu.utils.logging import logger

PyTree = Any


def _tmap(fn, *trees, **kwargs):
    return jax.tree.map(fn, *trees, **kwargs)


@dataclasses.dataclass
class TPUOptimizer:
    """Base: subclasses define per-leaf math; state mirrors params per moment."""

    lr: float = 1e-3
    weight_decay: float = 0.0

    # names of per-leaf moment buffers, e.g. ("exp_avg", "exp_avg_sq")
    moment_names: Tuple[str, ...] = ()

    def init(self, params: PyTree) -> Dict[str, Any]:
        state = {name: _tmap(jnp.zeros_like, params) for name in self.moment_names}
        state["step"] = jnp.zeros((), jnp.int32)
        return state

    def update(self, grads: PyTree, state: Dict[str, Any], params: PyTree,
               lr: Optional[jax.Array] = None) -> Tuple[PyTree, Dict[str, Any]]:
        raise NotImplementedError

    def state_moment_trees(self, state: Dict[str, Any]):
        return {k: state[k] for k in self.moment_names}


@dataclasses.dataclass
class FusedAdam(TPUOptimizer):
    """Adam/AdamW (reference ``ops/adam/fused_adam.py``; ``adam_w_mode`` semantics)."""

    betas: Tuple[float, float] = (0.9, 0.999)
    eps: float = 1e-8
    adam_w_mode: bool = True
    bias_correction: bool = True
    moment_names: Tuple[str, ...] = ("exp_avg", "exp_avg_sq")

    def update(self, grads, state, params, lr=None):
        lr = self.lr if lr is None else lr
        b1, b2 = self.betas
        step = state["step"] + 1
        sf = step.astype(jnp.float32)
        if self.bias_correction:
            bc1 = 1.0 - b1 ** sf
            bc2 = 1.0 - b2 ** sf
        else:
            bc1 = bc2 = jnp.float32(1.0)

        def leaf(p, g, m, v):
            g = g.astype(jnp.float32)
            p32 = p.astype(jnp.float32)
            if not self.adam_w_mode and self.weight_decay:
                g = g + self.weight_decay * p32
            m = b1 * m + (1.0 - b1) * g
            v = b2 * v + (1.0 - b2) * jnp.square(g)
            upd = (m / bc1) / (jnp.sqrt(v / bc2) + self.eps)
            if self.adam_w_mode and self.weight_decay:
                upd = upd + self.weight_decay * p32
            return (p32 - lr * upd).astype(p.dtype), m, v

        out = _tmap(leaf, params, grads, state["exp_avg"], state["exp_avg_sq"])
        new_params = _tmap(lambda o: o[0], out, is_leaf=lambda x: isinstance(x, tuple))
        new_m = _tmap(lambda o: o[1], out, is_leaf=lambda x: isinstance(x, tuple))
        new_v = _tmap(lambda o: o[2], out, is_leaf=lambda x: isinstance(x, tuple))
        return new_params, {"exp_avg": new_m, "exp_avg_sq": new_v, "step": step}


@dataclasses.dataclass
class Lion(TPUOptimizer):
    """Lion (reference ``ops/lion``/``csrc/lion``): sign of interpolated momentum."""

    betas: Tuple[float, float] = (0.9, 0.99)
    moment_names: Tuple[str, ...] = ("exp_avg",)

    def update(self, grads, state, params, lr=None):
        lr = self.lr if lr is None else lr
        b1, b2 = self.betas

        def leaf(p, g, m):
            g = g.astype(jnp.float32)
            p32 = p.astype(jnp.float32)
            upd = jnp.sign(b1 * m + (1.0 - b1) * g)
            if self.weight_decay:
                upd = upd + self.weight_decay * p32
            m_new = b2 * m + (1.0 - b2) * g
            return (p32 - lr * upd).astype(p.dtype), m_new

        out = _tmap(leaf, params, grads, state["exp_avg"])
        new_params = _tmap(lambda o: o[0], out, is_leaf=lambda x: isinstance(x, tuple))
        new_m = _tmap(lambda o: o[1], out, is_leaf=lambda x: isinstance(x, tuple))
        return new_params, {"exp_avg": new_m, "step": state["step"] + 1}


@dataclasses.dataclass
class FusedLamb(TPUOptimizer):
    """LAMB (reference ``ops/lamb``): Adam direction × trust ratio per layer."""

    betas: Tuple[float, float] = (0.9, 0.999)
    eps: float = 1e-6
    max_coeff: float = 10.0
    min_coeff: float = 0.01
    moment_names: Tuple[str, ...] = ("exp_avg", "exp_avg_sq")

    def update(self, grads, state, params, lr=None):
        lr = self.lr if lr is None else lr
        b1, b2 = self.betas
        step = state["step"] + 1
        sf = step.astype(jnp.float32)
        bc1 = 1.0 - b1 ** sf
        bc2 = 1.0 - b2 ** sf

        def leaf(p, g, m, v):
            g = g.astype(jnp.float32)
            p32 = p.astype(jnp.float32)
            m = b1 * m + (1.0 - b1) * g
            v = b2 * v + (1.0 - b2) * jnp.square(g)
            upd = (m / bc1) / (jnp.sqrt(v / bc2) + self.eps)
            if self.weight_decay:
                upd = upd + self.weight_decay * p32
            w_norm = jnp.linalg.norm(p32)
            u_norm = jnp.linalg.norm(upd)
            trust = jnp.where(
                (w_norm > 0) & (u_norm > 0),
                jnp.clip(w_norm / u_norm, self.min_coeff, self.max_coeff), 1.0)
            return (p32 - lr * trust * upd).astype(p.dtype), m, v

        out = _tmap(leaf, params, grads, state["exp_avg"], state["exp_avg_sq"])
        new_params = _tmap(lambda o: o[0], out, is_leaf=lambda x: isinstance(x, tuple))
        new_m = _tmap(lambda o: o[1], out, is_leaf=lambda x: isinstance(x, tuple))
        new_v = _tmap(lambda o: o[2], out, is_leaf=lambda x: isinstance(x, tuple))
        return new_params, {"exp_avg": new_m, "exp_avg_sq": new_v, "step": step}


@dataclasses.dataclass
class FusedAdagrad(TPUOptimizer):
    """Adagrad (reference ``ops/adagrad``/``csrc/adagrad``)."""

    eps: float = 1e-10
    moment_names: Tuple[str, ...] = ("sum_sq",)

    def update(self, grads, state, params, lr=None):
        lr = self.lr if lr is None else lr

        def leaf(p, g, s):
            g = g.astype(jnp.float32)
            p32 = p.astype(jnp.float32)
            if self.weight_decay:
                g = g + self.weight_decay * p32
            s = s + jnp.square(g)
            return (p32 - lr * g / (jnp.sqrt(s) + self.eps)).astype(p.dtype), s

        out = _tmap(leaf, params, grads, state["sum_sq"])
        new_params = _tmap(lambda o: o[0], out, is_leaf=lambda x: isinstance(x, tuple))
        new_s = _tmap(lambda o: o[1], out, is_leaf=lambda x: isinstance(x, tuple))
        return new_params, {"sum_sq": new_s, "step": state["step"] + 1}


@dataclasses.dataclass
class Adafactor(TPUOptimizer):
    """Adafactor (Shazeer & Stern 2018) — factored second moment, no master.

    Not in the reference's ops/ family (its memory answer is ZeRO-Offload,
    CUDA+PCIe); on TPU the idiomatic single-chip memory answer is the one
    the TPU lineage (T5, PaLM) actually used: O(n+m) optimizer state per
    n×m matrix instead of 2nm fp32 moments. With ``bf16.fp32_master=false``
    this trains a 3B-param model in 16G HBM where Adam's 14 bytes/param
    needs 42G. Constant-lr variant: external LR schedule, β2 fixed,
    update-RMS clipping at ``clip_threshold`` (paper §6 d=1).

    State per leaf: matrices (ndim≥2, factored over the LAST TWO axes;
    leading axes — e.g. the stacked-layer L dim — are batch) carry
    ``{"adafac_r","adafac_c"}`` row/col EMAs; vectors carry ``{"adafac_v"}``
    full (key names are collision-proof vs model param dict keys — the
    factor tree is mapped first with an is_leaf on these keys). The tree
    does NOT mirror the param tree and takes the engine's replicated-aux
    sharding path (factors are O(n+m) — replication is noise)."""

    beta2: float = 0.999
    eps1: float = 1e-30          # inside-sqrt regulariser on g²
    clip_threshold: float = 1.0  # max RMS of the unscaled update
    # relative step size (paper §8 "scale by parameter scale", T5's mode):
    # the clipped update is DENSE with RMS ~1, so an absolute lr moves every
    # weight the same distance — 1e-2 is 0.5σ PER STEP for a 0.02-std
    # embedding and training diverges within steps (measured on llama_3b).
    # Scaling by max(eps2, RMS(param)) makes lr a RELATIVE step per leaf.
    scale_parameter: bool = True
    eps2: float = 1e-3           # floor for the parameter scale
    # leaves whose last-two dims are both below this stay UN-factored (full
    # v): stacked norm scales (L, h) would otherwise couple all layers'
    # statistics through one rank-1 fit, and the memory win is negligible
    # there (optax/T5x use the same 128 guard)
    min_dim_size_to_factor: int = 128
    # bf16 params without an fp32 master cannot absorb updates smaller than
    # bf16's 8-bit mantissa step (~0.4% of the param's magnitude) — they
    # round to zero and training stalls. Stochastic rounding makes the
    # EXPECTED update exact: round up with probability proportional to the
    # residual. Applied only when the param dtype is bf16.
    stochastic_rounding: bool = True
    moment_names: Tuple[str, ...] = ("fac",)

    @staticmethod
    def _is_factor(x) -> bool:
        return isinstance(x, dict) and ("adafac_r" in x or "adafac_v" in x)

    @staticmethod
    def _stoch_round_bf16(x32: jax.Array, step: jax.Array,
                          leaf_id: int = 0) -> jax.Array:
        """fp32 → bf16 with stochastic rounding: add uniform noise in the
        truncated mantissa bits, then truncate. Counter-based randomness
        (threefry on the step counter folded with a per-leaf id, so equal-
        shaped leaves draw independent noise) keeps the update a pure
        function of (state, grads) — same-step replays are bit-identical."""
        bits = jax.lax.bitcast_convert_type(x32, jnp.uint32)
        key = jax.random.fold_in(
            jax.random.fold_in(jax.random.PRNGKey(0x5eed), leaf_id), step)
        noise = jax.random.bits(key, x32.shape, jnp.uint32) & jnp.uint32(0xFFFF)
        return jax.lax.bitcast_convert_type(
            (bits + noise) & jnp.uint32(0xFFFF0000), jnp.float32
        ).astype(jnp.bfloat16)

    def _factorable(self, p) -> bool:
        return (p.ndim >= 2
                and p.shape[-1] >= self.min_dim_size_to_factor
                and p.shape[-2] >= self.min_dim_size_to_factor)

    def init(self, params: PyTree) -> Dict[str, Any]:
        def leaf(p):
            if self._factorable(p):
                return {"adafac_r": jnp.zeros(p.shape[:-1], jnp.float32),
                        "adafac_c": jnp.zeros(p.shape[:-2] + p.shape[-1:],
                                              jnp.float32)}
            return {"adafac_v": jnp.zeros(p.shape, jnp.float32)}
        return {"fac": _tmap(leaf, params),
                "step": jnp.zeros((), jnp.int32)}

    def update(self, grads, state, params, lr=None):
        lr = self.lr if lr is None else lr
        b2 = self.beta2

        leaf_counter = [0]

        def leaf(f, p, g):
            leaf_id = leaf_counter[0]   # trace-time constant per leaf
            leaf_counter[0] += 1
            g = g.astype(jnp.float32)
            p32 = p.astype(jnp.float32)
            g2 = jnp.square(g) + self.eps1
            if "adafac_r" in f:
                vr = b2 * f["adafac_r"] + (1 - b2) * jnp.mean(g2, axis=-1)
                vc = b2 * f["adafac_c"] + (1 - b2) * jnp.mean(g2, axis=-2)
                # V ≈ (vr ⊗ vc) / mean(vr): the rank-1 fit whose row/col
                # sums match the EMAs (paper eq. 4, means-normalised).
                # Normalise vr FIRST: vr·vc can underflow fp32 (g²~1e-33
                # early in training → product 1e-66 → 0 → rsqrt=inf→NaN);
                # vr/mean(vr) is O(1) so the product stays in range.
                vr_n = vr / jnp.mean(vr, axis=-1, keepdims=True)
                denom = vr_n[..., :, None] * vc[..., None, :]
                f_new = {"adafac_r": vr, "adafac_c": vc}
            else:
                denom = b2 * f["adafac_v"] + (1 - b2) * g2
                f_new = {"adafac_v": denom}
            u = g * jax.lax.rsqrt(denom)
            rms = jnp.sqrt(jnp.mean(jnp.square(u)))
            u = u / jnp.maximum(1.0, rms / self.clip_threshold)
            if self.weight_decay:
                u = u + self.weight_decay * p32
            lr_eff = lr
            if self.scale_parameter:
                p_scale = jnp.maximum(
                    jnp.sqrt(jnp.mean(jnp.square(p32))), self.eps2)
                lr_eff = lr * p_scale
            new32 = p32 - lr_eff * u
            if self.stochastic_rounding and p.dtype == jnp.bfloat16:
                return (self._stoch_round_bf16(new32, state["step"], leaf_id),
                        f_new)
            return new32.astype(p.dtype), f_new

        # factor tree FIRST: its is_leaf-truncated treedef lets params/grads
        # flatten_up_to their array leaves at the factor-dict positions
        out = _tmap(leaf, state["fac"], params, grads,
                    is_leaf=self._is_factor)
        istup = lambda x: isinstance(x, tuple)  # noqa: E731
        new_params = _tmap(lambda o: o[0], out, is_leaf=istup)
        new_f = _tmap(lambda o: o[1], out, is_leaf=istup)
        return new_params, {"fac": new_f, "step": state["step"] + 1}


@dataclasses.dataclass
class SGD(TPUOptimizer):
    momentum: float = 0.0
    nesterov: bool = False
    moment_names: Tuple[str, ...] = ("momentum_buf",)

    def update(self, grads, state, params, lr=None):
        lr = self.lr if lr is None else lr

        def leaf(p, g, buf):
            g = g.astype(jnp.float32)
            p32 = p.astype(jnp.float32)
            if self.weight_decay:
                g = g + self.weight_decay * p32
            buf = self.momentum * buf + g
            d = (g + self.momentum * buf) if self.nesterov else \
                (buf if self.momentum else g)
            return (p32 - lr * d).astype(p.dtype), buf

        out = _tmap(leaf, params, grads, state["momentum_buf"])
        new_params = _tmap(lambda o: o[0], out, is_leaf=lambda x: isinstance(x, tuple))
        new_buf = _tmap(lambda o: o[1], out, is_leaf=lambda x: isinstance(x, tuple))
        return new_params, {"momentum_buf": new_buf, "step": state["step"] + 1}


def _newton_schulz_orthogonalize(g: jax.Array, steps: int = 5, eps: float = 1e-7) -> jax.Array:
    """Quintic Newton-Schulz iteration approximating the orthogonal factor of g.

    The Muon core (reference ``zero/muon/muon_optimizer.py``); runs on the MXU in
    bfloat16 — matmul-dominated by design.
    """
    a, b, c = 3.4445, -4.7750, 2.0315
    transpose = g.shape[0] > g.shape[1]
    x = g.astype(jnp.bfloat16)
    if transpose:
        x = x.T
    x = x / (jnp.linalg.norm(x.astype(jnp.float32)).astype(jnp.bfloat16) + eps)

    def body(_, x):
        xxt = x @ x.T
        return a * x + (b * xxt + c * (xxt @ xxt)) @ x

    x = jax.lax.fori_loop(0, steps, body, x)
    if transpose:
        x = x.T
    return x.astype(jnp.float32)


@dataclasses.dataclass
class Muon(TPUOptimizer):
    """Muon with aux Adam for non-matrix params (reference
    ``zero/muon/muon_optimizer.py:14``: linear-layer weight matrices take the
    orthogonalized-momentum path; embeddings/heads/norms/biases take Adam — the
    reference flags params explicitly at ``__init__.py:84-90``).

    Routing here is by parameter name + rank: leaves whose path mentions
    emb/head/norm/bias/scale, or with rank < 2, take Adam. Rank-2 matrices and
    rank-3 *stacked* layer matrices (scan-over-layers layout ``(L, m, n)``) take
    Muon — the stacked case is vmapped over the leading layer dim."""

    momentum: float = 0.95
    ns_steps: int = 5
    betas: Tuple[float, float] = (0.9, 0.999)
    eps: float = 1e-8
    moment_names: Tuple[str, ...] = ("exp_avg", "exp_avg_sq")

    _ADAM_NAME_HINTS = ("emb", "head", "norm", "bias", "scale", "ln")

    def _use_muon(self, path: str, p) -> bool:
        name = path.lower()
        if any(h in name for h in self._ADAM_NAME_HINTS):
            return False
        return p.ndim in (2, 3) and min(p.shape[-2:]) >= 16

    def update(self, grads, state, params, lr=None):
        lr = self.lr if lr is None else lr
        b1, b2 = self.betas
        step = state["step"] + 1
        sf = step.astype(jnp.float32)
        bc1 = 1.0 - b1 ** sf
        bc2 = 1.0 - b2 ** sf

        def leaf(path, p, g, m, v):
            g = g.astype(jnp.float32)
            p32 = p.astype(jnp.float32)
            if self._use_muon(jax.tree_util.keystr(path), p):
                buf = self.momentum * m + g
                ns = _newton_schulz_orthogonalize
                ortho = (jax.vmap(lambda x: ns(x, self.ns_steps))(buf)
                         if p.ndim == 3 else ns(buf, self.ns_steps))
                scale = jnp.sqrt(jnp.float32(max(1.0, p.shape[-2] / p.shape[-1])))
                upd = ortho * scale
                if self.weight_decay:
                    upd = upd + self.weight_decay * p32
                return (p32 - lr * upd).astype(p.dtype), buf, v
            m2 = b1 * m + (1.0 - b1) * g
            v2 = b2 * v + (1.0 - b2) * jnp.square(g)
            upd = (m2 / bc1) / (jnp.sqrt(v2 / bc2) + self.eps)
            if self.weight_decay:
                upd = upd + self.weight_decay * p32
            return (p32 - lr * upd).astype(p.dtype), m2, v2

        out = jax.tree_util.tree_map_with_path(
            leaf, params, grads, state["exp_avg"], state["exp_avg_sq"])
        new_params = _tmap(lambda o: o[0], out, is_leaf=lambda x: isinstance(x, tuple))
        new_m = _tmap(lambda o: o[1], out, is_leaf=lambda x: isinstance(x, tuple))
        new_v = _tmap(lambda o: o[2], out, is_leaf=lambda x: isinstance(x, tuple))
        return new_params, {"exp_avg": new_m, "exp_avg_sq": new_v, "step": step}


@dataclasses.dataclass
class MaskedOptimizer(TPUOptimizer):
    """Wraps an optimizer to update only masked-trainable leaves.

    The LoRA/frozen-params path (reference ``linear/optimized_linear.py``'s
    LoRA param groups; engine frozen-param checkpoint handling): optimizer
    state exists ONLY for trainable leaves — frozen params carry no moments
    and pass through update() unchanged."""

    inner: Optional[TPUOptimizer] = None
    mask: Any = None  # pytree of bools mirroring params

    def __post_init__(self):
        if self.inner is not None:
            self.lr = self.inner.lr
            self.weight_decay = self.inner.weight_decay
            self.moment_names = self.inner.moment_names

    def init(self, params):
        from deepspeed_tpu.utils.tree import prune_tree

        return self.inner.init(prune_tree(params, self.mask))

    def update(self, grads, state, params, lr=None):
        from deepspeed_tpu.utils.tree import merge_tree, prune_tree

        sub_p = prune_tree(params, self.mask)
        sub_g = prune_tree(grads, self.mask)
        new_sub_p, new_state = self.inner.update(sub_g, state, sub_p, lr=lr)
        return merge_tree(params, new_sub_p, self.mask), new_state


_OPTIMIZERS = {
    "adam": FusedAdam,
    "adamw": FusedAdam,
    "fusedadam": FusedAdam,
    "lion": Lion,
    "fusedlion": Lion,
    "lamb": FusedLamb,
    "fusedlamb": FusedLamb,
    "adagrad": FusedAdagrad,
    "adafactor": Adafactor,
    "sgd": SGD,
    "muon": Muon,
}


def _register_onebit():
    # deferred import: onebit.py imports from this module
    from deepspeed_tpu.ops.onebit import OnebitAdam, OnebitLamb, ZeroOneAdam

    _OPTIMIZERS.update({
        "onebitadam": OnebitAdam,
        "zerooneadam": ZeroOneAdam,
        "onebitlamb": OnebitLamb,
    })


def get_optimizer(name: str, params: Dict[str, Any]) -> TPUOptimizer:
    key = name.lower().replace("_", "")
    if key.startswith(("onebit", "zeroone")) and key not in _OPTIMIZERS:
        _register_onebit()
    if key not in _OPTIMIZERS:
        raise ValueError(f"unknown optimizer {name!r}; supported: {sorted(_OPTIMIZERS)}")
    cls = _OPTIMIZERS[key]
    kwargs = dict(params)
    if "betas" in kwargs:
        kwargs["betas"] = tuple(kwargs["betas"])
    kwargs.pop("torch_adam", None)
    kwargs.pop("adam_w_mode", None) if cls is not FusedAdam else None
    field_names = {f.name for f in dataclasses.fields(cls)}
    unknown = set(kwargs) - field_names
    for k in unknown:
        logger.warning(f"optimizer param {k!r} not supported by {cls.__name__} — ignored")
        kwargs.pop(k)
    return cls(**kwargs)
