"""Functional optimizers — the ops/adam, ops/lion, ops/lamb, ops/adagrad family.

Parity: reference ``ops/adam/fused_adam.py:18`` (FusedAdam, csrc/adam CUDA
multi-tensor kernels), ``ops/lion``, ``ops/lamb``, ``ops/adagrad``,
``zero/muon/muon_optimizer.py:14`` (Muon with aux Adam). On TPU "fusion" is XLA's
job: each update below is a pure jnp expression over the (sharded) state pytree
which XLA fuses into a handful of elementwise kernels per shard — the multi-tensor
apply machinery is unnecessary. A Pallas fused path exists for the hottest case
(see ``deepspeed_tpu/ops/pallas/fused_adam.py``).

State layout mirrors the param pytree per-moment ({"exp_avg": tree, ...}) so the
ZeRO sharding policy (``parallel/partitioning.py``) derives optimizer-state
shardings directly from param shardings — the stage-1 partitioning analog.
"""
from __future__ import annotations

import dataclasses
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp

from deepspeed_tpu.utils.logging import logger

PyTree = Any


def _tmap(fn, *trees, **kwargs):
    return jax.tree.map(fn, *trees, **kwargs)


@dataclasses.dataclass
class TPUOptimizer:
    """Base: subclasses define per-leaf math; state mirrors params per moment."""

    lr: float = 1e-3
    weight_decay: float = 0.0

    # names of per-leaf moment buffers, e.g. ("exp_avg", "exp_avg_sq")
    moment_names: Tuple[str, ...] = ()

    def init(self, params: PyTree) -> Dict[str, Any]:
        state = {name: _tmap(jnp.zeros_like, params) for name in self.moment_names}
        state["step"] = jnp.zeros((), jnp.int32)
        return state

    def update(self, grads: PyTree, state: Dict[str, Any], params: PyTree,
               lr: Optional[jax.Array] = None) -> Tuple[PyTree, Dict[str, Any]]:
        raise NotImplementedError

    def state_moment_trees(self, state: Dict[str, Any]):
        return {k: state[k] for k in self.moment_names}


@dataclasses.dataclass
class FusedAdam(TPUOptimizer):
    """Adam/AdamW (reference ``ops/adam/fused_adam.py``; ``adam_w_mode`` semantics)."""

    betas: Tuple[float, float] = (0.9, 0.999)
    eps: float = 1e-8
    adam_w_mode: bool = True
    bias_correction: bool = True
    moment_names: Tuple[str, ...] = ("exp_avg", "exp_avg_sq")

    def update(self, grads, state, params, lr=None):
        lr = self.lr if lr is None else lr
        b1, b2 = self.betas
        step = state["step"] + 1
        sf = step.astype(jnp.float32)
        if self.bias_correction:
            bc1 = 1.0 - b1 ** sf
            bc2 = 1.0 - b2 ** sf
        else:
            bc1 = bc2 = jnp.float32(1.0)

        def leaf(p, g, m, v):
            g = g.astype(jnp.float32)
            p32 = p.astype(jnp.float32)
            if not self.adam_w_mode and self.weight_decay:
                g = g + self.weight_decay * p32
            m = b1 * m + (1.0 - b1) * g
            v = b2 * v + (1.0 - b2) * jnp.square(g)
            upd = (m / bc1) / (jnp.sqrt(v / bc2) + self.eps)
            if self.adam_w_mode and self.weight_decay:
                upd = upd + self.weight_decay * p32
            return (p32 - lr * upd).astype(p.dtype), m, v

        out = _tmap(leaf, params, grads, state["exp_avg"], state["exp_avg_sq"])
        new_params = _tmap(lambda o: o[0], out, is_leaf=lambda x: isinstance(x, tuple))
        new_m = _tmap(lambda o: o[1], out, is_leaf=lambda x: isinstance(x, tuple))
        new_v = _tmap(lambda o: o[2], out, is_leaf=lambda x: isinstance(x, tuple))
        return new_params, {"exp_avg": new_m, "exp_avg_sq": new_v, "step": step}


@dataclasses.dataclass
class Lion(TPUOptimizer):
    """Lion (reference ``ops/lion``/``csrc/lion``): sign of interpolated momentum."""

    betas: Tuple[float, float] = (0.9, 0.99)
    moment_names: Tuple[str, ...] = ("exp_avg",)

    def update(self, grads, state, params, lr=None):
        lr = self.lr if lr is None else lr
        b1, b2 = self.betas

        def leaf(p, g, m):
            g = g.astype(jnp.float32)
            p32 = p.astype(jnp.float32)
            upd = jnp.sign(b1 * m + (1.0 - b1) * g)
            if self.weight_decay:
                upd = upd + self.weight_decay * p32
            m_new = b2 * m + (1.0 - b2) * g
            return (p32 - lr * upd).astype(p.dtype), m_new

        out = _tmap(leaf, params, grads, state["exp_avg"])
        new_params = _tmap(lambda o: o[0], out, is_leaf=lambda x: isinstance(x, tuple))
        new_m = _tmap(lambda o: o[1], out, is_leaf=lambda x: isinstance(x, tuple))
        return new_params, {"exp_avg": new_m, "step": state["step"] + 1}


@dataclasses.dataclass
class FusedLamb(TPUOptimizer):
    """LAMB (reference ``ops/lamb``): Adam direction × trust ratio per layer."""

    betas: Tuple[float, float] = (0.9, 0.999)
    eps: float = 1e-6
    max_coeff: float = 10.0
    min_coeff: float = 0.01
    moment_names: Tuple[str, ...] = ("exp_avg", "exp_avg_sq")

    def update(self, grads, state, params, lr=None):
        lr = self.lr if lr is None else lr
        b1, b2 = self.betas
        step = state["step"] + 1
        sf = step.astype(jnp.float32)
        bc1 = 1.0 - b1 ** sf
        bc2 = 1.0 - b2 ** sf

        def leaf(p, g, m, v):
            g = g.astype(jnp.float32)
            p32 = p.astype(jnp.float32)
            m = b1 * m + (1.0 - b1) * g
            v = b2 * v + (1.0 - b2) * jnp.square(g)
            upd = (m / bc1) / (jnp.sqrt(v / bc2) + self.eps)
            if self.weight_decay:
                upd = upd + self.weight_decay * p32
            w_norm = jnp.linalg.norm(p32)
            u_norm = jnp.linalg.norm(upd)
            trust = jnp.where(
                (w_norm > 0) & (u_norm > 0),
                jnp.clip(w_norm / u_norm, self.min_coeff, self.max_coeff), 1.0)
            return (p32 - lr * trust * upd).astype(p.dtype), m, v

        out = _tmap(leaf, params, grads, state["exp_avg"], state["exp_avg_sq"])
        new_params = _tmap(lambda o: o[0], out, is_leaf=lambda x: isinstance(x, tuple))
        new_m = _tmap(lambda o: o[1], out, is_leaf=lambda x: isinstance(x, tuple))
        new_v = _tmap(lambda o: o[2], out, is_leaf=lambda x: isinstance(x, tuple))
        return new_params, {"exp_avg": new_m, "exp_avg_sq": new_v, "step": step}


@dataclasses.dataclass
class FusedAdagrad(TPUOptimizer):
    """Adagrad (reference ``ops/adagrad``/``csrc/adagrad``)."""

    eps: float = 1e-10
    moment_names: Tuple[str, ...] = ("sum_sq",)

    def update(self, grads, state, params, lr=None):
        lr = self.lr if lr is None else lr

        def leaf(p, g, s):
            g = g.astype(jnp.float32)
            p32 = p.astype(jnp.float32)
            if self.weight_decay:
                g = g + self.weight_decay * p32
            s = s + jnp.square(g)
            return (p32 - lr * g / (jnp.sqrt(s) + self.eps)).astype(p.dtype), s

        out = _tmap(leaf, params, grads, state["sum_sq"])
        new_params = _tmap(lambda o: o[0], out, is_leaf=lambda x: isinstance(x, tuple))
        new_s = _tmap(lambda o: o[1], out, is_leaf=lambda x: isinstance(x, tuple))
        return new_params, {"sum_sq": new_s, "step": state["step"] + 1}


@dataclasses.dataclass
class SGD(TPUOptimizer):
    momentum: float = 0.0
    nesterov: bool = False
    moment_names: Tuple[str, ...] = ("momentum_buf",)

    def update(self, grads, state, params, lr=None):
        lr = self.lr if lr is None else lr

        def leaf(p, g, buf):
            g = g.astype(jnp.float32)
            p32 = p.astype(jnp.float32)
            if self.weight_decay:
                g = g + self.weight_decay * p32
            buf = self.momentum * buf + g
            d = (g + self.momentum * buf) if self.nesterov else \
                (buf if self.momentum else g)
            return (p32 - lr * d).astype(p.dtype), buf

        out = _tmap(leaf, params, grads, state["momentum_buf"])
        new_params = _tmap(lambda o: o[0], out, is_leaf=lambda x: isinstance(x, tuple))
        new_buf = _tmap(lambda o: o[1], out, is_leaf=lambda x: isinstance(x, tuple))
        return new_params, {"momentum_buf": new_buf, "step": state["step"] + 1}


def _newton_schulz_orthogonalize(g: jax.Array, steps: int = 5, eps: float = 1e-7) -> jax.Array:
    """Quintic Newton-Schulz iteration approximating the orthogonal factor of g.

    The Muon core (reference ``zero/muon/muon_optimizer.py``); runs on the MXU in
    bfloat16 — matmul-dominated by design.
    """
    a, b, c = 3.4445, -4.7750, 2.0315
    transpose = g.shape[0] > g.shape[1]
    x = g.astype(jnp.bfloat16)
    if transpose:
        x = x.T
    x = x / (jnp.linalg.norm(x.astype(jnp.float32)).astype(jnp.bfloat16) + eps)

    def body(_, x):
        xxt = x @ x.T
        return a * x + (b * xxt + c * (xxt @ xxt)) @ x

    x = jax.lax.fori_loop(0, steps, body, x)
    if transpose:
        x = x.T
    return x.astype(jnp.float32)


@dataclasses.dataclass
class Muon(TPUOptimizer):
    """Muon with aux Adam for non-matrix params (reference
    ``zero/muon/muon_optimizer.py:14``: linear-layer weight matrices take the
    orthogonalized-momentum path; embeddings/heads/norms/biases take Adam — the
    reference flags params explicitly at ``__init__.py:84-90``).

    Routing here is by parameter name + rank: leaves whose path mentions
    emb/head/norm/bias/scale, or with rank < 2, take Adam. Rank-2 matrices and
    rank-3 *stacked* layer matrices (scan-over-layers layout ``(L, m, n)``) take
    Muon — the stacked case is vmapped over the leading layer dim."""

    momentum: float = 0.95
    ns_steps: int = 5
    betas: Tuple[float, float] = (0.9, 0.999)
    eps: float = 1e-8
    moment_names: Tuple[str, ...] = ("exp_avg", "exp_avg_sq")

    _ADAM_NAME_HINTS = ("emb", "head", "norm", "bias", "scale", "ln")

    def _use_muon(self, path: str, p) -> bool:
        name = path.lower()
        if any(h in name for h in self._ADAM_NAME_HINTS):
            return False
        return p.ndim in (2, 3) and min(p.shape[-2:]) >= 16

    def update(self, grads, state, params, lr=None):
        lr = self.lr if lr is None else lr
        b1, b2 = self.betas
        step = state["step"] + 1
        sf = step.astype(jnp.float32)
        bc1 = 1.0 - b1 ** sf
        bc2 = 1.0 - b2 ** sf

        def leaf(path, p, g, m, v):
            g = g.astype(jnp.float32)
            p32 = p.astype(jnp.float32)
            if self._use_muon(jax.tree_util.keystr(path), p):
                buf = self.momentum * m + g
                ns = _newton_schulz_orthogonalize
                ortho = (jax.vmap(lambda x: ns(x, self.ns_steps))(buf)
                         if p.ndim == 3 else ns(buf, self.ns_steps))
                scale = jnp.sqrt(jnp.float32(max(1.0, p.shape[-2] / p.shape[-1])))
                upd = ortho * scale
                if self.weight_decay:
                    upd = upd + self.weight_decay * p32
                return (p32 - lr * upd).astype(p.dtype), buf, v
            m2 = b1 * m + (1.0 - b1) * g
            v2 = b2 * v + (1.0 - b2) * jnp.square(g)
            upd = (m2 / bc1) / (jnp.sqrt(v2 / bc2) + self.eps)
            if self.weight_decay:
                upd = upd + self.weight_decay * p32
            return (p32 - lr * upd).astype(p.dtype), m2, v2

        out = jax.tree_util.tree_map_with_path(
            leaf, params, grads, state["exp_avg"], state["exp_avg_sq"])
        new_params = _tmap(lambda o: o[0], out, is_leaf=lambda x: isinstance(x, tuple))
        new_m = _tmap(lambda o: o[1], out, is_leaf=lambda x: isinstance(x, tuple))
        new_v = _tmap(lambda o: o[2], out, is_leaf=lambda x: isinstance(x, tuple))
        return new_params, {"exp_avg": new_m, "exp_avg_sq": new_v, "step": step}


@dataclasses.dataclass
class MaskedOptimizer(TPUOptimizer):
    """Wraps an optimizer to update only masked-trainable leaves.

    The LoRA/frozen-params path (reference ``linear/optimized_linear.py``'s
    LoRA param groups; engine frozen-param checkpoint handling): optimizer
    state exists ONLY for trainable leaves — frozen params carry no moments
    and pass through update() unchanged."""

    inner: Optional[TPUOptimizer] = None
    mask: Any = None  # pytree of bools mirroring params

    def __post_init__(self):
        if self.inner is not None:
            self.lr = self.inner.lr
            self.weight_decay = self.inner.weight_decay
            self.moment_names = self.inner.moment_names

    def init(self, params):
        from deepspeed_tpu.utils.tree import prune_tree

        return self.inner.init(prune_tree(params, self.mask))

    def update(self, grads, state, params, lr=None):
        from deepspeed_tpu.utils.tree import merge_tree, prune_tree

        sub_p = prune_tree(params, self.mask)
        sub_g = prune_tree(grads, self.mask)
        new_sub_p, new_state = self.inner.update(sub_g, state, sub_p, lr=lr)
        return merge_tree(params, new_sub_p, self.mask), new_state


_OPTIMIZERS = {
    "adam": FusedAdam,
    "adamw": FusedAdam,
    "fusedadam": FusedAdam,
    "lion": Lion,
    "fusedlion": Lion,
    "lamb": FusedLamb,
    "fusedlamb": FusedLamb,
    "adagrad": FusedAdagrad,
    "sgd": SGD,
    "muon": Muon,
}


def _register_onebit():
    # deferred import: onebit.py imports from this module
    from deepspeed_tpu.ops.onebit import OnebitAdam, OnebitLamb, ZeroOneAdam

    _OPTIMIZERS.update({
        "onebitadam": OnebitAdam,
        "zerooneadam": ZeroOneAdam,
        "onebitlamb": OnebitLamb,
    })


def get_optimizer(name: str, params: Dict[str, Any]) -> TPUOptimizer:
    key = name.lower().replace("_", "")
    if key.startswith(("onebit", "zeroone")) and key not in _OPTIMIZERS:
        _register_onebit()
    if key not in _OPTIMIZERS:
        raise ValueError(f"unknown optimizer {name!r}; supported: {sorted(_OPTIMIZERS)}")
    cls = _OPTIMIZERS[key]
    kwargs = dict(params)
    if "betas" in kwargs:
        kwargs["betas"] = tuple(kwargs["betas"])
    kwargs.pop("torch_adam", None)
    kwargs.pop("adam_w_mode", None) if cls is not FusedAdam else None
    field_names = {f.name for f in dataclasses.fields(cls)}
    unknown = set(kwargs) - field_names
    for k in unknown:
        logger.warning(f"optimizer param {k!r} not supported by {cls.__name__} — ignored")
        kwargs.pop(k)
    return cls(**kwargs)
