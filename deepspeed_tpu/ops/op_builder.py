"""Op-builder registry — the L1 dispatch seam.

Parity: reference ``op_builder/builder.py:116`` (``OpBuilder`` ABC:
``sources``/``include_paths``/``is_compatible``/``load``) and the per-accelerator
builder trees. On TPU there is no nvcc step: "building" an op resolves a Pallas
kernel (or its interpret-mode/XLA fallback, playing the role of the CPU fallback
builders), so ``load()`` returns a python module-like namespace immediately.
Native host-side ops (async file I/O) JIT-compile C++ with the system toolchain.
"""
from __future__ import annotations

import importlib
from typing import Any, Dict, List, Optional, Type

from deepspeed_tpu.utils.logging import logger


class OpBuilder:
    NAME = "op"

    def absolute_name(self) -> str:
        return f"deepspeed_tpu.ops.{self.NAME}"

    def is_compatible(self, verbose: bool = False) -> bool:
        return True

    def sources(self) -> List[str]:
        return []

    def include_paths(self) -> List[str]:
        return []

    def load(self, verbose: bool = True) -> Any:
        return importlib.import_module(self.absolute_name())


class PallasOpBuilder(OpBuilder):
    """An op whose implementation is a Pallas TPU kernel with an XLA fallback."""

    MODULE: str = ""

    def absolute_name(self) -> str:
        return self.MODULE

    def is_compatible(self, verbose: bool = False) -> bool:
        try:
            import jax

            platforms = {d.platform for d in jax.devices()}
            ok = "tpu" in platforms or "cpu" in platforms  # interpret-mode fallback
            if verbose and not ok:
                logger.warning(f"{self.NAME}: no TPU and no CPU interpret fallback")
            return ok
        except Exception as e:   # no backend at all -> not compatible
            logger.debug(f"{self.NAME}: compatibility probe failed "
                         f"({type(e).__name__}: {e})")
            return False


class FusedAdamBuilder(PallasOpBuilder):
    NAME = "fused_adam"
    MODULE = "deepspeed_tpu.ops.optimizer"


class FlashAttnBuilder(PallasOpBuilder):
    NAME = "flash_attn"
    MODULE = "deepspeed_tpu.ops.pallas.flash_attention"


class RMSNormBuilder(PallasOpBuilder):
    NAME = "rms_norm"
    MODULE = "deepspeed_tpu.ops.pallas.rms_norm"


class QuantizerBuilder(PallasOpBuilder):
    NAME = "quantizer"
    MODULE = "deepspeed_tpu.ops.quantizer"


class AsyncIOBuilder(OpBuilder):
    """Host-side async file I/O (the csrc/aio analog; C++ via ctypes)."""

    NAME = "async_io"

    def absolute_name(self) -> str:
        return "deepspeed_tpu.ops.aio"

    def is_compatible(self, verbose: bool = False) -> bool:
        import shutil

        return shutil.which("g++") is not None


ALL_OPS: Dict[str, Type[OpBuilder]] = {
    cls.NAME: cls
    for cls in (FusedAdamBuilder, FlashAttnBuilder, RMSNormBuilder, QuantizerBuilder,
                AsyncIOBuilder)
}
__op_builders__ = [cls() for cls in ALL_OPS.values()]


def get_op_builder(name: str) -> Optional[Type[OpBuilder]]:
    return ALL_OPS.get(name)
