"""Pallas TPU kernels — the framework's native-kernel layer.

Plays the role of the reference's ``csrc/`` CUDA tree (SURVEY.md §2.5): instead
of nvcc-compiled extensions dispatched by op builders, kernels here are Pallas
programs compiled by Mosaic for TPU, with ``interpret=True`` as the CPU
fallback (the analog of the reference's CPU op builders).
"""
from deepspeed_tpu.ops.pallas.flash_attention import flash_attention

__all__ = ["flash_attention"]
