"""Block-sparse attention as a Pallas TPU kernel (forward + backward).

Parity: reference ``deepspeed/ops/sparse_attention/`` (triton ``matmul.py`` /
``softmax.py`` block-sparse kernels + ``sparsity_config.py`` layout builders:
Dense, Fixed, BigBird, BSLongformer, Variable) and ``csrc/sparse_attention``.

TPU design: one flash-style online-softmax kernel whose kv-block loop is gated
by a **block layout** — an ``[num_q_blocks, num_kv_blocks]`` {0,1} matrix held
in SMEM. Inactive blocks skip the QK^T/PV matmuls entirely (``pl.when``), so
MXU work scales with layout density; the backward pass recomputes
probabilities from the saved logsumexp (flash-attention-2 decomposition) under
the same gating. Rows whose every block is inactive produce zero output (and
lse = -inf), matching the reference softmax semantics for fully-masked rows.

Layout builders are host-side numpy (they are config, not compute) and mirror
the reference's ``SparsityConfig.make_layout`` family.
"""
from __future__ import annotations

import functools
import math
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl

try:
    from jax.experimental.pallas import tpu as pltpu
except ImportError:  # pragma: no cover
    pltpu = None

NEG_INF = -1e30


def _use_interpret() -> bool:
    return jax.default_backend() != "tpu"


def _vmem(shape, dtype):
    if pltpu is not None:
        return pltpu.VMEM(shape, dtype)
    return pl.MemoryRef(shape, dtype)  # pragma: no cover


# --------------------------------------------------------------------------- #
# layout builders (reference ops/sparse_attention/sparsity_config.py)
# --------------------------------------------------------------------------- #

def dense_layout(n_blocks: int) -> np.ndarray:
    return np.ones((n_blocks, n_blocks), np.int32)


def fixed_layout(n_blocks: int, local_window: int = 4,
                 global_stride: int = 4) -> np.ndarray:
    """'Fixed' pattern: local banded window + periodic global columns
    (reference ``FixedSparsityConfig``)."""
    lay = np.zeros((n_blocks, n_blocks), np.int32)
    for i in range(n_blocks):
        lo = max(0, i - local_window + 1)
        lay[i, lo:i + 1] = 1
    lay[:, ::global_stride] = 1
    return np.ascontiguousarray(np.tril(lay) + np.triu(lay, 1) * lay)


def bigbird_layout(n_blocks: int, num_random: int = 2, num_local: int = 3,
                   num_global: int = 1, seed: int = 0) -> np.ndarray:
    """BigBird: global + sliding window + random blocks
    (reference ``BigBirdSparsityConfig``)."""
    rng = np.random.RandomState(seed)
    lay = np.zeros((n_blocks, n_blocks), np.int32)
    half = num_local // 2
    for i in range(n_blocks):
        lay[i, max(0, i - half):min(n_blocks, i + half + 1)] = 1
        if num_random > 0:
            lay[i, rng.choice(n_blocks, size=min(num_random, n_blocks),
                              replace=False)] = 1
    lay[:num_global, :] = 1
    lay[:, :num_global] = 1
    return lay


def bslongformer_layout(n_blocks: int, window: int = 3,
                        global_blocks: Tuple[int, ...] = (0,)) -> np.ndarray:
    """BSLongformer: symmetric sliding window + designated global blocks
    (reference ``BSLongformerSparsityConfig``)."""
    lay = np.zeros((n_blocks, n_blocks), np.int32)
    half = window // 2
    for i in range(n_blocks):
        lay[i, max(0, i - half):min(n_blocks, i + half + 1)] = 1
    for g in global_blocks:
        lay[g, :] = 1
        lay[:, g] = 1
    return lay


def variable_layout(n_blocks: int, local_windows: Tuple[int, ...] = (4,),
                    global_indices: Tuple[int, ...] = (0,)) -> np.ndarray:
    """Variable: per-row local windows cycling through ``local_windows`` +
    global columns (reference ``VariableSparsityConfig``)."""
    lay = np.zeros((n_blocks, n_blocks), np.int32)
    for i in range(n_blocks):
        w = local_windows[i % len(local_windows)]
        lay[i, max(0, i - w + 1):i + 1] = 1
    for g in global_indices:
        lay[:, g] = 1
    return lay


def causal_layout(layout: np.ndarray) -> np.ndarray:
    """Restrict any layout to the lower block triangle (decoder use)."""
    return np.ascontiguousarray(np.tril(layout).astype(np.int32))


# --------------------------------------------------------------------------- #
# forward kernel
# --------------------------------------------------------------------------- #

def _fwd_kernel(lay_ref, q_ref, k_ref, v_ref, o_ref, lse_ref,
                acc_ref, m_ref, l_ref,
                *, scale: float, causal: bool, seq_len: int,
                block_q: int, block_kv: int):
    i = pl.program_id(1)
    j = pl.program_id(2)
    n_kv = pl.num_programs(2)

    @pl.when(j == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)
        m_ref[...] = jnp.full_like(m_ref, NEG_INF)
        l_ref[...] = jnp.zeros_like(l_ref)

    active = lay_ref[i, j] > 0

    @pl.when(active)
    def _compute():
        q = q_ref[0].astype(jnp.float32)
        k = k_ref[0].astype(jnp.float32)
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * scale
        row = i * block_q + jax.lax.broadcasted_iota(jnp.int32, s.shape, 0)
        col = j * block_kv + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
        mask = col < seq_len
        if causal:
            mask = jnp.logical_and(mask, col <= row)
        s = jnp.where(mask, s, NEG_INF)

        m_prev = m_ref[...]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=1, keepdims=True))
        p = jnp.exp(s - m_new)
        p = jnp.where(mask, p, 0.0)
        alpha = jnp.exp(m_prev - m_new)
        l_ref[...] = alpha * l_ref[...] + jnp.sum(p, axis=1, keepdims=True)
        acc_ref[...] = acc_ref[...] * alpha + jax.lax.dot_general(
            p, v_ref[0].astype(jnp.float32), (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        m_ref[...] = m_new

    @pl.when(j == n_kv - 1)
    def _finalize():
        l = l_ref[...]
        safe_l = jnp.where(l > 0, l, 1.0)
        o_ref[0] = (acc_ref[...] / safe_l).astype(o_ref.dtype)
        lse = jnp.where(l > 0, m_ref[...] + jnp.log(safe_l), NEG_INF)
        lse_ref[0] = lse[:, 0].astype(jnp.float32)


def _fwd(q, k, v, layout, *, scale, causal, seq_len, block_q, block_kv,
         interpret):
    bh, sq, d = q.shape
    n_q, n_kv = sq // block_q, k.shape[1] // block_kv
    grid = (bh, n_q, n_kv)
    kernel = functools.partial(
        _fwd_kernel, scale=scale, causal=causal, seq_len=seq_len,
        block_q=block_q, block_kv=block_kv)
    if pltpu is not None:
        lay_spec = pl.BlockSpec(memory_space=pltpu.SMEM)
    else:  # pragma: no cover
        lay_spec = pl.BlockSpec(memory_space=pl.ANY)
    return pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            lay_spec,
            pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, block_kv, d), lambda b, i, j: (b, j, 0)),
            pl.BlockSpec((1, block_kv, d), lambda b, i, j: (b, j, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, block_q), lambda b, i, j: (b, i)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bh, sq, d), q.dtype),
            jax.ShapeDtypeStruct((bh, sq), jnp.float32),
        ],
        scratch_shapes=[
            _vmem((block_q, d), jnp.float32),
            _vmem((block_q, 1), jnp.float32),
            _vmem((block_q, 1), jnp.float32),
        ],
        interpret=interpret,
    )(layout, q, k, v)


# --------------------------------------------------------------------------- #
# backward kernels
# --------------------------------------------------------------------------- #

def _bwd_dq_kernel(lay_ref, q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                   dq_ref, acc_ref,
                   *, scale: float, causal: bool, seq_len: int,
                   block_q: int, block_kv: int):
    i = pl.program_id(1)
    j = pl.program_id(2)
    n_kv = pl.num_programs(2)

    @pl.when(j == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)

    @pl.when(lay_ref[i, j] > 0)
    def _compute():
        q = q_ref[0].astype(jnp.float32)
        k = k_ref[0].astype(jnp.float32)
        v = v_ref[0].astype(jnp.float32)
        do = do_ref[0].astype(jnp.float32)
        lse = lse_ref[0].astype(jnp.float32)[:, None]
        delta = delta_ref[0].astype(jnp.float32)[:, None]
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * scale
        row = i * block_q + jax.lax.broadcasted_iota(jnp.int32, s.shape, 0)
        col = j * block_kv + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
        mask = col < seq_len
        if causal:
            mask = jnp.logical_and(mask, col <= row)
        p = jnp.where(mask, jnp.exp(s - lse), 0.0)
        dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        ds = p * (dp - delta)
        acc_ref[...] += jax.lax.dot_general(
            ds, k, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32) * scale

    @pl.when(j == n_kv - 1)
    def _finalize():
        dq_ref[0] = acc_ref[...].astype(dq_ref.dtype)


def _bwd_dkv_kernel(lay_ref, q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                    dk_ref, dv_ref, dk_acc_ref, dv_acc_ref,
                    *, scale: float, causal: bool, seq_len: int,
                    block_q: int, block_kv: int):
    j = pl.program_id(1)   # kv block (outer)
    i = pl.program_id(2)   # q block (inner)
    n_q = pl.num_programs(2)

    @pl.when(i == 0)
    def _init():
        dk_acc_ref[...] = jnp.zeros_like(dk_acc_ref)
        dv_acc_ref[...] = jnp.zeros_like(dv_acc_ref)

    @pl.when(lay_ref[i, j] > 0)
    def _compute():
        q = q_ref[0].astype(jnp.float32)
        k = k_ref[0].astype(jnp.float32)
        v = v_ref[0].astype(jnp.float32)
        do = do_ref[0].astype(jnp.float32)
        lse = lse_ref[0].astype(jnp.float32)[:, None]
        delta = delta_ref[0].astype(jnp.float32)[:, None]
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * scale
        row = i * block_q + jax.lax.broadcasted_iota(jnp.int32, s.shape, 0)
        col = j * block_kv + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
        mask = col < seq_len
        if causal:
            mask = jnp.logical_and(mask, col <= row)
        p = jnp.where(mask, jnp.exp(s - lse), 0.0)
        dv_acc_ref[...] += jax.lax.dot_general(
            p, do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        ds = p * (dp - delta)
        dk_acc_ref[...] += jax.lax.dot_general(
            ds, q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32) * scale

    @pl.when(i == n_q - 1)
    def _finalize():
        dk_ref[0] = dk_acc_ref[...].astype(dk_ref.dtype)
        dv_ref[0] = dv_acc_ref[...].astype(dv_ref.dtype)


def _bwd(scale, causal, seq_len, block_q, block_kv, interpret,
         res, do):
    q, k, v, o, lse, layout = res
    bh, sq, d = q.shape
    n_q, n_kv = sq // block_q, k.shape[1] // block_kv
    delta = jnp.sum(o.astype(jnp.float32) * do.astype(jnp.float32), axis=-1)

    if pltpu is not None:
        lay_spec = pl.BlockSpec(memory_space=pltpu.SMEM)
    else:  # pragma: no cover
        lay_spec = pl.BlockSpec(memory_space=pl.ANY)

    q_spec = pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0))
    kv_spec = pl.BlockSpec((1, block_kv, d), lambda b, i, j: (b, j, 0))
    row_spec = pl.BlockSpec((1, block_q), lambda b, i, j: (b, i))

    dq = pl.pallas_call(
        functools.partial(_bwd_dq_kernel, scale=scale, causal=causal,
                          seq_len=seq_len, block_q=block_q, block_kv=block_kv),
        grid=(bh, n_q, n_kv),
        in_specs=[lay_spec, q_spec, kv_spec, kv_spec, q_spec, row_spec,
                  row_spec],
        out_specs=q_spec,
        out_shape=jax.ShapeDtypeStruct(q.shape, q.dtype),
        scratch_shapes=[_vmem((block_q, d), jnp.float32)],
        interpret=interpret,
    )(layout, q, k, v, do, lse, delta)

    # dkv grid: kv outer, q inner — index maps swap (i, j) roles
    q_spec2 = pl.BlockSpec((1, block_q, d), lambda b, j, i: (b, i, 0))
    kv_spec2 = pl.BlockSpec((1, block_kv, d), lambda b, j, i: (b, j, 0))
    row_spec2 = pl.BlockSpec((1, block_q), lambda b, j, i: (b, i))
    dk, dv = pl.pallas_call(
        functools.partial(_bwd_dkv_kernel, scale=scale, causal=causal,
                          seq_len=seq_len, block_q=block_q, block_kv=block_kv),
        grid=(bh, n_kv, n_q),
        in_specs=[lay_spec, q_spec2, kv_spec2, kv_spec2, q_spec2, row_spec2,
                  row_spec2],
        out_specs=[kv_spec2, kv_spec2],
        out_shape=[jax.ShapeDtypeStruct(k.shape, k.dtype),
                   jax.ShapeDtypeStruct(v.shape, v.dtype)],
        scratch_shapes=[_vmem((block_kv, d), jnp.float32),
                        _vmem((block_kv, d), jnp.float32)],
        interpret=interpret,
    )(layout, q, k, v, do, lse, delta)
    return dq, dk, dv, None


# --------------------------------------------------------------------------- #
# public API
# --------------------------------------------------------------------------- #

@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6, 7))
def _sparse_attn(q, k, v, layout, scale, causal, block_q, block_kv):
    seq_len = q.shape[1]
    o, _ = _fwd(q, k, v, layout, scale=scale, causal=causal, seq_len=seq_len,
                block_q=block_q, block_kv=block_kv,
                interpret=_use_interpret())
    return o


def _sparse_attn_fwd(q, k, v, layout, scale, causal, block_q, block_kv):
    seq_len = q.shape[1]
    o, lse = _fwd(q, k, v, layout, scale=scale, causal=causal,
                  seq_len=seq_len, block_q=block_q, block_kv=block_kv,
                  interpret=_use_interpret())
    return o, (q, k, v, o, lse, layout)


def _sparse_attn_bwd(scale, causal, block_q, block_kv, res, do):
    q = res[0]
    return _bwd(scale, causal, q.shape[1], block_q, block_kv,
                _use_interpret(), res, do)


_sparse_attn.defvjp(_sparse_attn_fwd, _sparse_attn_bwd)


def block_sparse_attention(q: jax.Array, k: jax.Array, v: jax.Array,
                           layout: jax.Array, block_size: int = 128,
                           causal: bool = True,
                           scale: Optional[float] = None) -> jax.Array:
    """Block-sparse attention over a [n_blocks, n_blocks] {0,1} layout.

    q/k/v: [batch, heads, seq, head_dim] (seq must be a multiple of
    ``block_size``; pad the inputs otherwise). Returns [batch, heads, seq, dim].
    Layout rows with no active block produce zero output rows.
    """
    b, h, s, d = q.shape
    if s % block_size:
        raise ValueError(f"seq len {s} not a multiple of block {block_size}")
    n_blocks = s // block_size
    if layout.shape != (n_blocks, n_blocks):
        raise ValueError(f"layout {layout.shape} != {(n_blocks, n_blocks)}")
    scale = scale if scale is not None else 1.0 / math.sqrt(d)
    layout = jnp.asarray(layout, jnp.int32)

    def bn(x):
        return x.reshape(b * h, s, x.shape[-1])

    out = _sparse_attn(bn(q), bn(k), bn(v), layout, scale, causal,
                       block_size, block_size)
    return out.reshape(b, h, s, d)


def block_sparse_attention_reference(q, k, v, layout, block_size=128,
                                     causal=True, scale=None):
    """jnp reference (materializes the full mask) for numerics tests."""
    b, h, s, d = q.shape
    scale = scale if scale is not None else 1.0 / math.sqrt(d)
    mask = jnp.repeat(jnp.repeat(jnp.asarray(layout, bool), block_size, 0),
                      block_size, 1)
    if causal:
        mask = jnp.logical_and(mask, jnp.tril(jnp.ones((s, s), bool)))
    sc = jnp.einsum("bhqd,bhkd->bhqk", q.astype(jnp.float32),
                    k.astype(jnp.float32)) * scale
    sc = jnp.where(mask, sc, NEG_INF)
    row_any = jnp.any(mask, axis=-1)
    p = jax.nn.softmax(sc, axis=-1)
    p = jnp.where(row_any[None, None, :, None], p, 0.0)
    return jnp.einsum("bhqk,bhkd->bhqd", p,
                      v.astype(jnp.float32)).astype(q.dtype)
