"""Flash attention as a Pallas TPU kernel (forward + backward).

TPU-native replacement for the reference's fused attention CUDA kernels
(``csrc/transformer/`` softmax/transform kernels behind
``DeepSpeedTransformerLayer``, ``ops/transformer/transformer.py:296``, and the
triton flash path ``ops/transformer/inference/triton/attention.py``). Online
(blockwise) softmax never materializes the [S, S] score matrix in HBM:

* forward: grid (batch*q_heads, q_blocks, kv_blocks); kv innermost so the
  running max/denominator/accumulator live in VMEM scratch across kv steps;
* backward: two kernels (dq; dk+dv) recomputing probabilities from the saved
  logsumexp — the standard flash-attention-2 decomposition;
* GQA: kv tensors stay at [batch*kv_heads, S, D]; the q-head → kv-head
  mapping happens in the BlockSpec index maps (no ``jnp.repeat`` in HBM, and
  VJP residuals hold the small kv tensors);
* causal masking skips fully-masked kv blocks (upper-triangular block tiles
  are never computed);
* CPU fallback = ``interpret=True`` (the role the reference's CPU op builders
  play for its CUDA ops).
"""
from __future__ import annotations

import functools
import math
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

try:  # pltpu is importable on CPU builds of jax as well
    from jax.experimental.pallas import tpu as pltpu
except ImportError:  # pragma: no cover
    pltpu = None

NEG_INF = -1e30


def _use_interpret() -> bool:
    return jax.default_backend() != "tpu"


def _vmem(shape, dtype):
    if pltpu is not None:
        return pltpu.VMEM(shape, dtype)
    return pl.MemoryRef(shape, dtype)  # pragma: no cover


def _compiler_params():
    if pltpu is not None and not _use_interpret():
        return pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary"))
    return None


def _block_mask(q_start, kv_start, shape, causal, kv_len, q_len=None):
    row = q_start + jax.lax.broadcasted_iota(jnp.int32, shape, 0)
    col = kv_start + jax.lax.broadcasted_iota(jnp.int32, shape, 1)
    mask = col < kv_len
    if q_len is not None:
        mask = jnp.logical_and(mask, row < q_len)
    if causal:
        mask = jnp.logical_and(mask, col <= row)
    return mask


# --------------------------------------------------------------------------- #
# forward kernel
# --------------------------------------------------------------------------- #

def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref,
                acc_ref, m_ref, l_ref,
                *, scale: float, causal: bool, kv_len: int,
                block_q: int, block_kv: int):
    i = pl.program_id(1)
    j = pl.program_id(2)
    n_kv = pl.num_programs(2)

    @pl.when(j == 0)
    def _init():
        m_ref[:] = jnp.full_like(m_ref, NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)
        acc_ref[:] = jnp.zeros_like(acc_ref)

    # causal: skip blocks strictly above the diagonal; always skip blocks
    # fully beyond the (unpadded) kv length
    q_start = i * block_q
    kv_start = j * block_kv
    run = kv_start < kv_len
    if causal:
        run = jnp.logical_and(run, kv_start <= q_start + block_q - 1)

    @pl.when(run)
    def _compute():
        q = q_ref[0].astype(jnp.float32)
        k = k_ref[0].astype(jnp.float32)
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale   # [bq, bkv]
        s = jnp.where(_block_mask(q_start, kv_start, s.shape, causal, kv_len),
                      s, NEG_INF)

        m_prev = m_ref[:, 0:1]                            # [bq, 1]
        l_prev = l_ref[:, 0:1]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=1, keepdims=True))
        p = jnp.exp(s - m_new)                            # [bq, bkv]
        alpha = jnp.exp(m_prev - m_new)                   # [bq, 1]
        l_new = alpha * l_prev + jnp.sum(p, axis=1, keepdims=True)

        v = v_ref[0].astype(jnp.float32)
        pv = jax.lax.dot_general(
            p, v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)           # [bq, d]
        acc_ref[:] = acc_ref[:] * alpha + pv
        m_ref[:, 0:1] = m_new
        l_ref[:, 0:1] = l_new

    @pl.when(j == n_kv - 1)
    def _finalize():
        l = l_ref[:, 0:1]
        l_safe = jnp.where(l == 0.0, 1.0, l)
        o_ref[0] = (acc_ref[:] / l_safe).astype(o_ref.dtype)
        lse = m_ref[:, 0:1] + jnp.log(l_safe)
        lse_ref[0] = jnp.where(l == 0.0, NEG_INF, lse)


def _fwd(q, k, v, *, scale, causal, kv_len, rep, block_q, block_kv, interpret):
    BN, S_pad, D = q.shape
    BK, Skv_pad, _ = k.shape
    n_q = S_pad // block_q
    n_kv = Skv_pad // block_kv
    kv_of = _kv_index(rep)

    o, lse = pl.pallas_call(
        functools.partial(
            _fwd_kernel, scale=scale, causal=causal, kv_len=kv_len,
            block_q=block_q, block_kv=block_kv),
        grid=(BN, n_q, n_kv),
        in_specs=[
            pl.BlockSpec((1, block_q, D), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, block_kv, D), lambda b, i, j: (kv_of(b), j, 0)),
            pl.BlockSpec((1, block_kv, D), lambda b, i, j: (kv_of(b), j, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, block_q, D), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, block_q, 1), lambda b, i, j: (b, i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((BN, S_pad, D), q.dtype),
            # per-row logsumexp; trailing dim 1 == array dim keeps the TPU
            # tiling rules happy without lane-broadcasting into HBM
            jax.ShapeDtypeStruct((BN, S_pad, 1), jnp.float32),
        ],
        scratch_shapes=[
            _vmem((block_q, D), jnp.float32),
            _vmem((block_q, 128), jnp.float32),
            _vmem((block_q, 128), jnp.float32),
        ],
        compiler_params=_compiler_params(),
        interpret=interpret,
    )(q, k, v)
    return o, lse


# --------------------------------------------------------------------------- #
# backward kernels
# --------------------------------------------------------------------------- #

def _bwd_dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dq_ref,
                   acc_ref, *, scale: float, causal: bool, kv_len: int,
                   block_q: int, block_kv: int):
    i = pl.program_id(1)
    j = pl.program_id(2)
    n_kv = pl.num_programs(2)

    @pl.when(j == 0)
    def _init():
        acc_ref[:] = jnp.zeros_like(acc_ref)

    q_start = i * block_q
    kv_start = j * block_kv
    run = kv_start < kv_len
    if causal:
        run = jnp.logical_and(run, kv_start <= q_start + block_q - 1)

    @pl.when(run)
    def _compute():
        q = q_ref[0].astype(jnp.float32)
        k = k_ref[0].astype(jnp.float32)
        v = v_ref[0].astype(jnp.float32)
        do = do_ref[0].astype(jnp.float32)
        lse = lse_ref[0]                                   # [bq, 1]
        delta = delta_ref[0]

        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale
        mask = _block_mask(q_start, kv_start, s.shape, causal, kv_len)
        p = jnp.where(mask, jnp.exp(s - lse), 0.0)         # [bq, bkv]

        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)            # [bq, bkv]
        ds = p * (dp - delta) * scale
        acc_ref[:] += jax.lax.dot_general(
            ds, k, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    @pl.when(j == n_kv - 1)
    def _finalize():
        dq_ref[0] = acc_ref[:].astype(dq_ref.dtype)


def _bwd_dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                    dk_ref, dv_ref, dk_acc, dv_acc,
                    *, scale: float, causal: bool, kv_len: int, q_len: int,
                    n_q: int, block_q: int, block_kv: int):
    j = pl.program_id(1)       # kv block (outer)
    inner = pl.program_id(2)   # (q-head-in-group, q block) flattened (inner)
    n_inner = pl.num_programs(2)
    i = inner % n_q

    @pl.when(inner == 0)
    def _init():
        dk_acc[:] = jnp.zeros_like(dk_acc)
        dv_acc[:] = jnp.zeros_like(dv_acc)

    q_start = i * block_q
    kv_start = j * block_kv
    run = jnp.logical_and(kv_start < kv_len, q_start < q_len)
    if causal:
        run = jnp.logical_and(run, kv_start <= q_start + block_q - 1)

    @pl.when(run)
    def _compute():
        q = q_ref[0].astype(jnp.float32)
        k = k_ref[0].astype(jnp.float32)
        v = v_ref[0].astype(jnp.float32)
        do = do_ref[0].astype(jnp.float32)
        lse = lse_ref[0]
        delta = delta_ref[0]

        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale    # [bq, bkv]
        mask = _block_mask(q_start, kv_start, s.shape, causal, kv_len, q_len)
        p = jnp.where(mask, jnp.exp(s - lse), 0.0)

        # dv += p^T @ do
        dv_acc[:] += jax.lax.dot_general(
            p, do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)            # [bkv, d]
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)            # [bq, bkv]
        ds = p * (dp - delta) * scale
        # dk += ds^T @ q
        dk_acc[:] += jax.lax.dot_general(
            ds, q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    @pl.when(inner == n_inner - 1)
    def _finalize():
        dk_ref[0] = dk_acc[:].astype(dk_ref.dtype)
        dv_ref[0] = dv_acc[:].astype(dv_ref.dtype)


def _kv_index(rep: int):
    """Map a q-batch grid index (batch*q_heads) to the kv-batch index
    (batch*kv_heads) for GQA: consecutive groups of ``rep`` q-heads share one
    kv head. With rep == 1 this is the identity."""
    if rep == 1:
        return lambda b: b

    def kv_of(b):
        # b = batch * N + h; N = K * rep  →  kv = batch * K + h // rep
        return b // rep

    return kv_of


def _bwd(scale, causal, kv_len, q_len, rep, block_q, block_kv,
         residuals, g):
    q, k, v, o, lse = residuals
    do = g
    interpret = _use_interpret()
    BN, S_pad, D = q.shape
    BK, Skv_pad, _ = k.shape
    n_q = S_pad // block_q
    n_kv = Skv_pad // block_kv
    kv_of = _kv_index(rep)

    # delta_r = rowsum(dO * O) — cheap elementwise, let XLA fuse it
    delta = jnp.sum(do.astype(jnp.float32) * o.astype(jnp.float32),
                    axis=-1, keepdims=True)                # [BN, S_pad, 1]

    dq = pl.pallas_call(
        functools.partial(_bwd_dq_kernel, scale=scale, causal=causal,
                          kv_len=kv_len, block_q=block_q, block_kv=block_kv),
        grid=(BN, n_q, n_kv),
        in_specs=[
            pl.BlockSpec((1, block_q, D), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, block_kv, D), lambda b, i, j: (kv_of(b), j, 0)),
            pl.BlockSpec((1, block_kv, D), lambda b, i, j: (kv_of(b), j, 0)),
            pl.BlockSpec((1, block_q, D), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, block_q, 1), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, block_q, 1), lambda b, i, j: (b, i, 0)),
        ],
        out_specs=pl.BlockSpec((1, block_q, D), lambda b, i, j: (b, i, 0)),
        out_shape=jax.ShapeDtypeStruct((BN, S_pad, D), q.dtype),
        scratch_shapes=[_vmem((block_q, D), jnp.float32)],
        compiler_params=_compiler_params(),
        interpret=interpret,
    )(q, k, v, do, lse, delta)

    # dk/dv: grid batch dim is the KV batch; the inner dim flattens
    # (q-head-in-group × q-block) so the accumulator sums the whole GQA group
    def q_of(b, inner):
        return b * rep + inner // n_q

    dk, dv = pl.pallas_call(
        functools.partial(_bwd_dkv_kernel, scale=scale, causal=causal,
                          kv_len=kv_len, q_len=q_len, n_q=n_q,
                          block_q=block_q, block_kv=block_kv),
        grid=(BK, n_kv, rep * n_q),
        in_specs=[
            pl.BlockSpec((1, block_q, D),
                         lambda b, j, t: (q_of(b, t), t % n_q, 0)),
            pl.BlockSpec((1, block_kv, D), lambda b, j, t: (b, j, 0)),
            pl.BlockSpec((1, block_kv, D), lambda b, j, t: (b, j, 0)),
            pl.BlockSpec((1, block_q, D),
                         lambda b, j, t: (q_of(b, t), t % n_q, 0)),
            pl.BlockSpec((1, block_q, 1),
                         lambda b, j, t: (q_of(b, t), t % n_q, 0)),
            pl.BlockSpec((1, block_q, 1),
                         lambda b, j, t: (q_of(b, t), t % n_q, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, block_kv, D), lambda b, j, t: (b, j, 0)),
            pl.BlockSpec((1, block_kv, D), lambda b, j, t: (b, j, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((BK, Skv_pad, D), k.dtype),
            jax.ShapeDtypeStruct((BK, Skv_pad, D), v.dtype),
        ],
        scratch_shapes=[
            _vmem((block_kv, D), jnp.float32),
            _vmem((block_kv, D), jnp.float32),
        ],
        compiler_params=_compiler_params(),
        interpret=interpret,
    )(q, k, v, do, lse, delta)
    return dq, dk, dv


# --------------------------------------------------------------------------- #
# public entry — custom VJP over the padded [B*heads, S, D] layout
# --------------------------------------------------------------------------- #

@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7, 8, 9))
def _flash(q, k, v, scale, causal, kv_len, q_len, rep, block_q, block_kv):
    o, _ = _fwd(q, k, v, scale=scale, causal=causal, kv_len=kv_len, rep=rep,
                block_q=block_q, block_kv=block_kv, interpret=_use_interpret())
    return o


def _flash_fwd(q, k, v, scale, causal, kv_len, q_len, rep, block_q, block_kv):
    o, lse = _fwd(q, k, v, scale=scale, causal=causal, kv_len=kv_len, rep=rep,
                  block_q=block_q, block_kv=block_kv,
                  interpret=_use_interpret())
    return o, (q, k, v, o, lse)


def _flash_bwd(scale, causal, kv_len, q_len, rep, block_q, block_kv,
               residuals, g):
    return _bwd(scale, causal, kv_len, q_len, rep, block_q, block_kv,
                residuals, g)


_flash.defvjp(_flash_fwd, _flash_bwd)


def flash_attention(q: jax.Array, k: jax.Array, v: jax.Array,
                    causal: bool = True,
                    segment_mask: Optional[jax.Array] = None,
                    block_q: int = 512, block_kv: int = 1024) -> jax.Array:
    """Drop-in for ``models.transformer.dot_product_attention``.

    q: [B, S, N, D]; k, v: [B, S, K, D] (K divides N → GQA via kernel index
    maps, no repetition in HBM). Arbitrary masks fall back to the XLA
    reference implementation (the Pallas kernel handles causal/full only).

    Default blocks (512, 1024) are the measured v5e sweet spot — big tiles
    amortize the per-grid-step overhead and keep the MXU fed; 128×128 blocks
    measured ~2× slower end-to-end on GPT-2-125M grad steps. Blocks are
    capped to the (pow2-rounded) sequence length for short sequences.
    """
    if segment_mask is not None:
        from deepspeed_tpu.models.transformer import dot_product_attention

        return dot_product_attention(q, k, v, causal=causal,
                                     segment_mask=segment_mask)

    B, S, N, D = q.shape
    K = k.shape[2]
    if N % K != 0:
        raise ValueError(f"q heads {N} not divisible by kv heads {K}")
    rep = N // K
    Skv = k.shape[1]
    block_q = min(block_q, _round_pow2(S))
    block_kv = min(block_kv, _round_pow2(Skv))

    # [B, S, H, D] → [B*H, S, D]
    def to_bn(x):
        b, s, n, d = x.shape
        return x.transpose(0, 2, 1, 3).reshape(b * n, s, d)

    qb = _pad_seq(to_bn(q), block_q)
    kb = _pad_seq(to_bn(k), block_kv)
    vb = _pad_seq(to_bn(v), block_kv)

    scale = 1.0 / math.sqrt(D)
    o = _flash(qb, kb, vb, scale, causal, Skv, S, rep, block_q, block_kv)
    o = o[:, :S]
    return o.reshape(B, N, S, D).transpose(0, 2, 1, 3)


def _pad_seq(x, block):
    s = x.shape[1]
    pad = (-s) % block
    if pad == 0:
        return x
    return jnp.pad(x, ((0, 0), (0, pad), (0, 0)))


def _round_pow2(n: int) -> int:
    p = 1
    while p * 2 <= n:
        p *= 2
    return p
