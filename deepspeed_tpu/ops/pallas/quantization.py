"""Blockwise int8 quantize / fused dequant-reduce as Pallas TPU kernels.

TPU-native replacement for the reference's quantization kernel tree
(``csrc/quantization``): ``swizzled_quantize.cu`` (quantize + comm-layout
reorder) and ``quant_reduce.cu`` (fused dequantize-and-reduce consumed by the
qgZ quantized gradient path, ``runtime/comm/coalesced_collectives.py:31``).

* :func:`quantize_int8_blocks` — one VMEM pass per tile: amax, scale, round,
  int8 write. The reference's "swizzle" (reordering quantized output into
  per-rank-contiguous comm layout) is the caller's [world, chunk] reshape —
  XLA lays that out for free, so no separate swizzle kernel is needed.
* :func:`dequant_reduce` — the quant_reduce.cu analog: all ranks' int8
  chunks are dequantized and accumulated in fp32 in ONE pass over the int8
  data; the [world, chunk] fp32 intermediate the jnp path materializes never
  exists.

CPU fallback = interpret mode (same numerics).
"""
from __future__ import annotations

import functools
from typing import Tuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

# rows of quantization blocks processed per grid step
_ROW_TILE = 8


def _use_interpret() -> bool:
    return jax.default_backend() != "tpu"


# --------------------------------------------------------------------------- #
# quantize
# --------------------------------------------------------------------------- #

def _quant_kernel(x_ref, q_ref, s_ref):
    x = x_ref[...].astype(jnp.float32)                     # [R, B]
    amax = jnp.max(jnp.abs(x), axis=-1, keepdims=True)
    scale = jnp.where(amax > 0.0, amax / 127.0, 1.0)
    q_ref[...] = jnp.clip(jnp.round(x / scale), -127, 127).astype(jnp.int8)
    s_ref[...] = scale


def quantize_int8_blocks(x: jax.Array, block: int = 2048
                         ) -> Tuple[jax.Array, jax.Array]:
    """Symmetric per-block int8 quantization of a flat array.

    → (q int8 [N], scale fp32 [N/block]); ``block`` must divide N.
    Same contract as the jnp ``ops.quantization.quantize_int8``.
    """
    N = x.shape[0]
    if N % block:
        raise ValueError(f"size {N} must be a multiple of block={block}")
    rows = N // block
    tile = min(_ROW_TILE, rows)
    if rows % tile:
        tile = 1
    x2 = x.reshape(rows, block)
    q, s = pl.pallas_call(
        _quant_kernel,
        grid=(rows // tile,),
        in_specs=[pl.BlockSpec((tile, block), lambda i: (i, 0))],
        out_specs=[pl.BlockSpec((tile, block), lambda i: (i, 0)),
                   pl.BlockSpec((tile, 1), lambda i: (i, 0))],
        out_shape=[jax.ShapeDtypeStruct((rows, block), jnp.int8),
                   jax.ShapeDtypeStruct((rows, 1), jnp.float32)],
        interpret=_use_interpret(),
    )(x2)
    return q.reshape(-1), s[:, 0]


# --------------------------------------------------------------------------- #
# fused dequant + reduce (quant_reduce.cu analog)
# --------------------------------------------------------------------------- #

def _dequant_reduce_kernel(q_ref, s_ref, o_ref, *, world: int, mean: bool):
    acc = jnp.zeros(o_ref.shape, jnp.float32)              # [R, B]
    for w in range(world):                                  # static unroll
        acc = acc + q_ref[w].astype(jnp.float32) * s_ref[w]
    if mean:
        acc = acc / world
    o_ref[...] = acc


def dequant_reduce(q: jax.Array, scales: jax.Array, block: int = 2048,
                   mean: bool = False) -> jax.Array:
    """Sum W ranks' int8 contributions without materializing fp32 copies.

    q: int8 [W, C] (rank-major, C % block == 0); scales: fp32 [W, C/block].
    → fp32 [C] = Σ_w dequant(q[w]). One pass over the int8 data.
    """
    W, C = q.shape
    if C % block:
        raise ValueError(f"chunk {C} must be a multiple of block={block}")
    rows = C // block
    tile = min(_ROW_TILE, rows)
    if rows % tile:
        tile = 1
    q3 = q.reshape(W, rows, block)
    s3 = scales.reshape(W, rows, 1)
    out = pl.pallas_call(
        functools.partial(_dequant_reduce_kernel, world=W, mean=mean),
        grid=(rows // tile,),
        in_specs=[pl.BlockSpec((W, tile, block), lambda i: (0, i, 0)),
                  pl.BlockSpec((W, tile, 1), lambda i: (0, i, 0))],
        out_specs=pl.BlockSpec((tile, block), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((rows, block), jnp.float32),
        interpret=_use_interpret(),
    )(q3, s3)
    return out.reshape(-1)
