"""Pallas paged-attention kernel: per-token block-table KV gather + online
softmax, without materializing the gathered context in HBM.

Parity: reference ``inference/v2/kernels/ragged_ops`` (blocked flash attention
over the blocked KV cache, ``linear_blocked_kv_rotary`` etc.) — the CUDA tree
walks each sequence's block list; here the block list is a SCALAR-PREFETCH
argument so the BlockSpec ``index_map`` itself chases the table: grid step
(t, j) streams block ``tables[t, j]`` of the pool through VMEM for token t.

Decode attention is HBM-bandwidth-bound (read each live sequence's KV once);
the win over the XLA reference path (``models/paged.py
paged_attention_reference``) is avoiding the [T, MB*bs, K, D] gathered copy
in HBM — the kernel reads pool blocks directly.

Shapes: q [T, N, D]; kpool/vpool [NB, bs, K, D]; tables [T, MB] int32;
lengths [T] int32 (context length per token, pos+1). GQA via in-kernel
head-group batching (N = K * rep).
"""
from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

try:
    from jax.experimental.pallas import tpu as pltpu
except ImportError:  # pragma: no cover
    pltpu = None

NEG_INF = -1e30


def _use_interpret() -> bool:
    return jax.default_backend() != "tpu"


def _kernel(tables_ref, lengths_ref,           # scalar prefetch
            q_ref, k_ref, v_ref, o_ref,
            acc_ref, m_ref, l_ref,
            *, bs: int, rep: int, n_blocks_per_seq: int):
    t = pl.program_id(0)
    j = pl.program_id(1)

    @pl.when(j == 0)
    def _init():
        m_ref[:] = jnp.full_like(m_ref, NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)
        acc_ref[:] = jnp.zeros_like(acc_ref)

    length = lengths_ref[t]
    run = j * bs < length

    @pl.when(run)
    def _compute():
        q = q_ref[0].astype(jnp.float32)                  # [N, D]
        k = k_ref[0].astype(jnp.float32)                  # [bs, K, D]
        v = v_ref[0].astype(jnp.float32)
        N, D = q.shape
        K = k.shape[1]
        scale = 1.0 / jnp.sqrt(jnp.float32(D))

        q3 = q.reshape(K, rep, D)
        kt = jnp.swapaxes(k, 0, 1)                        # [K, bs, D]
        s = jax.lax.dot_general(
            q3, kt, (((2,), (2,)), ((0,), (0,))),
            preferred_element_type=jnp.float32) * scale   # [K, rep, bs]
        col = j * bs + jax.lax.broadcasted_iota(jnp.int32, s.shape, 2)
        s = jnp.where(col < length, s, NEG_INF)

        s2 = s.reshape(N, bs)
        m_prev = m_ref[:, 0:1]
        l_prev = l_ref[:, 0:1]
        m_new = jnp.maximum(m_prev, jnp.max(s2, axis=1, keepdims=True))
        p = jnp.exp(s2 - m_new)                           # [N, bs]
        alpha = jnp.exp(m_prev - m_new)
        l_ref[:, 0:1] = alpha * l_prev + jnp.sum(p, axis=1, keepdims=True)
        m_ref[:, 0:1] = m_new

        vt = jnp.swapaxes(v, 0, 1)                        # [K, bs, D]
        pv = jax.lax.dot_general(
            p.reshape(K, rep, bs), vt, (((2,), (1,)), ((0,), (0,))),
            preferred_element_type=jnp.float32)           # [K, rep, D]
        acc_ref[:] = acc_ref[:] * alpha + pv.reshape(N, D)

    @pl.when(j == n_blocks_per_seq - 1)
    def _finalize():
        l = l_ref[:, 0:1]
        l_safe = jnp.where(l == 0.0, 1.0, l)
        o_ref[0] = (acc_ref[:] / l_safe).astype(o_ref.dtype)


def paged_attention(q: jax.Array, kpool: jax.Array, vpool: jax.Array,
                    tables: jax.Array, lengths: jax.Array,
                    interpret: Optional[bool] = None) -> jax.Array:
    """Drop-in for ``models.paged.paged_attention_reference``."""
    if pltpu is None:
        raise ImportError(
            "jax.experimental.pallas.tpu is unavailable — use "
            "models.paged.paged_attention_reference instead")
    if interpret is None:
        interpret = _use_interpret()
    Tn, N, D = q.shape
    NB, bs, K, D2 = kpool.shape
    assert D == D2 and N % K == 0
    rep = N // K
    MB = tables.shape[1]

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(Tn, MB),
        in_specs=[
            pl.BlockSpec((1, N, D), lambda t, j, tbl, ln: (t, 0, 0)),
            pl.BlockSpec((1, bs, K, D),
                         lambda t, j, tbl, ln: (tbl[t, j], 0, 0, 0)),
            pl.BlockSpec((1, bs, K, D),
                         lambda t, j, tbl, ln: (tbl[t, j], 0, 0, 0)),
        ],
        out_specs=pl.BlockSpec((1, N, D), lambda t, j, tbl, ln: (t, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((N, D), jnp.float32),
            pltpu.VMEM((N, 128), jnp.float32),
            pltpu.VMEM((N, 128), jnp.float32),
        ],
    )
    kernel = functools.partial(_kernel, bs=bs, rep=rep, n_blocks_per_seq=MB)
    compiler_params = None
    if pltpu is not None and not interpret:
        compiler_params = pltpu.CompilerParams(
            dimension_semantics=("parallel", "arbitrary"))
    return pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((Tn, N, D), q.dtype),
        compiler_params=compiler_params,
        interpret=interpret,
    )(tables, lengths, q, kpool, vpool)
