"""RMSNorm / LayerNorm as Pallas TPU kernels (forward) with analytic VJPs.

TPU-native replacement for the reference's norm kernels
(``csrc/transformer/ds_layer_norm.cu``, ``csrc/transformer/inference/csrc/
layer_norm.cu`` / ``rms_norm.cu``). One grid step normalizes a block of rows
held in VMEM: the row is read once, stats (mean/var) accumulate in fp32, the
scaled result is written once — an HBM-bandwidth-bound op done at one
read + one write. Backward is a jnp expression (XLA fuses it into the
surrounding backward graph, which is where the reference's dedicated bwd
kernels spend their time too).

CPU fallback = interpret mode.
"""
from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

_ROW_BLOCK = 256


def _use_interpret() -> bool:
    return jax.default_backend() != "tpu"


def _rms_kernel(x_ref, s_ref, o_ref, *, eps: float):
    x = x_ref[...].astype(jnp.float32)
    var = jnp.mean(x * x, axis=-1, keepdims=True)
    o_ref[...] = (x * jax.lax.rsqrt(var + eps) * s_ref[...].astype(jnp.float32)
                  ).astype(o_ref.dtype)


def _ln_kernel(x_ref, s_ref, b_ref, o_ref, *, eps: float):
    x = x_ref[...].astype(jnp.float32)
    mean = jnp.mean(x, axis=-1, keepdims=True)
    var = jnp.mean((x - mean) ** 2, axis=-1, keepdims=True)
    o_ref[...] = ((x - mean) * jax.lax.rsqrt(var + eps)
                  * s_ref[...].astype(jnp.float32)
                  + b_ref[...].astype(jnp.float32)).astype(o_ref.dtype)


def _run_rows(kernel, x2d, *params):
    R, H = x2d.shape
    pad = (-R) % _ROW_BLOCK
    if pad:
        x2d = jnp.pad(x2d, ((0, pad), (0, 0)))
    grid = (x2d.shape[0] // _ROW_BLOCK,)
    in_specs = [pl.BlockSpec((_ROW_BLOCK, H), lambda i: (i, 0))]
    in_specs += [pl.BlockSpec((H,), lambda i: (0,)) for _ in params]
    out = pl.pallas_call(
        kernel, grid=grid, in_specs=in_specs,
        out_specs=pl.BlockSpec((_ROW_BLOCK, H), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct(x2d.shape, x2d.dtype),
        interpret=_use_interpret(),
    )(x2d, *params)
    return out[:R] if pad else out


# --------------------------------------------------------------------------- #
@functools.partial(jax.custom_vjp, nondiff_argnums=(2,))
def rms_norm(x: jax.Array, scale: jax.Array, eps: float = 1e-5) -> jax.Array:
    """x [..., H] * rsqrt(mean(x^2)) * scale, fp32 stats."""
    shape = x.shape
    out = _run_rows(functools.partial(_rms_kernel, eps=eps),
                    x.reshape(-1, shape[-1]), scale)
    return out.reshape(shape)


def _rms_fwd(x, scale, eps):
    return rms_norm(x, scale, eps), (x, scale)


def _rms_bwd(eps, res, g):
    x, scale = res
    x32 = x.astype(jnp.float32)
    g32 = g.astype(jnp.float32)
    var = jnp.mean(x32 * x32, axis=-1, keepdims=True)
    inv = jax.lax.rsqrt(var + eps)
    xhat = x32 * inv
    gs = g32 * scale.astype(jnp.float32)
    H = x.shape[-1]
    dx = inv * (gs - xhat * jnp.mean(gs * xhat, axis=-1, keepdims=True))
    dscale = jnp.sum((g32 * xhat).reshape(-1, H), axis=0)
    return dx.astype(x.dtype), dscale.astype(scale.dtype)


rms_norm.defvjp(_rms_fwd, _rms_bwd)


# --------------------------------------------------------------------------- #
@functools.partial(jax.custom_vjp, nondiff_argnums=(3,))
def layer_norm(x: jax.Array, scale: jax.Array, bias: jax.Array,
               eps: float = 1e-5) -> jax.Array:
    shape = x.shape
    out = _run_rows(functools.partial(_ln_kernel, eps=eps),
                    x.reshape(-1, shape[-1]), scale, bias)
    return out.reshape(shape)


def _ln_fwd(x, scale, bias, eps):
    return layer_norm(x, scale, bias, eps), (x, scale)


def _ln_bwd(eps, res, g):
    x, scale = res
    x32 = x.astype(jnp.float32)
    g32 = g.astype(jnp.float32)
    mean = jnp.mean(x32, axis=-1, keepdims=True)
    var = jnp.mean((x32 - mean) ** 2, axis=-1, keepdims=True)
    inv = jax.lax.rsqrt(var + eps)
    xhat = (x32 - mean) * inv
    gs = g32 * scale.astype(jnp.float32)
    H = x.shape[-1]
    dx = inv * (gs - jnp.mean(gs, axis=-1, keepdims=True)
                - xhat * jnp.mean(gs * xhat, axis=-1, keepdims=True))
    dscale = jnp.sum((g32 * xhat).reshape(-1, H), axis=0)
    dbias = jnp.sum(g32.reshape(-1, H), axis=0)
    return dx.astype(x.dtype), dscale.astype(scale.dtype), dbias.astype(scale.dtype)


layer_norm.defvjp(_ln_fwd, _ln_bwd)
