"""Evoformer (biased, gated) attention as a Pallas TPU kernel.

TPU-native replacement for the reference's CUTLASS Evoformer kernels
(``csrc/deepspeed4science/evoformer_attn`` — 14.9k LoC fwd/bwd behind
``DS4Sci_EvoformerAttention``): attention with an additive attention bias
(mask + pair biases, summed by the caller) computed flash-style — online
softmax over kv blocks, the [S, S] biased score matrix never materializes in
HBM; only the bias itself (which the model owns anyway: the pair
representation) is read tile by tile.

Backward: ``jax.vjp`` of the jnp reference (``ops/evoformer_attn.py``) —
correct by construction, including the pair-bias gradient the reference's
bwd kernels produce; it rematerializes scores per (batch, head) in XLA.
Wrap training calls in ``jax.checkpoint`` for flash-class total memory. The
sigmoid gating stays outside the kernel (XLA fuses the elementwise epilogue).
"""
from __future__ import annotations

import functools
import math
from typing import Tuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from deepspeed_tpu.ops.pallas.flash_attention import (_block_mask,
                                                      _compiler_params,
                                                      _use_interpret, _vmem,
                                                      NEG_INF)


def _evo_fwd_kernel(q_ref, k_ref, v_ref, b_ref, o_ref,
                    acc_ref, m_ref, l_ref,
                    *, scale: float, kv_len: int,
                    block_q: int, block_kv: int):
    j = pl.program_id(2)
    n_kv = pl.num_programs(2)

    @pl.when(j == 0)
    def _init():
        m_ref[:] = jnp.full_like(m_ref, NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)
        acc_ref[:] = jnp.zeros_like(acc_ref)

    i = pl.program_id(1)
    kv_start = j * block_kv

    @pl.when(kv_start < kv_len)
    def _compute():
        q = q_ref[0].astype(jnp.float32)
        k = k_ref[0].astype(jnp.float32)
        v = v_ref[0].astype(jnp.float32)
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * scale
        s = s + b_ref[0].astype(jnp.float32)
        mask = _block_mask(i * block_q, kv_start, s.shape, False, kv_len)
        s = jnp.where(mask, s, NEG_INF)

        m_prev = m_ref[:]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1, keepdims=True))
        p = jnp.exp(s - m_new)
        alpha = jnp.exp(m_prev - m_new)
        l_ref[:] = l_ref[:] * alpha + jnp.sum(p, axis=-1, keepdims=True)
        acc_ref[:] = acc_ref[:] * alpha + jax.lax.dot_general(
            p, v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        m_ref[:] = m_new

    @pl.when(j == n_kv - 1)
    def _finish():
        o_ref[0] = (acc_ref[:] / jnp.maximum(l_ref[:], 1e-30)
                    ).astype(o_ref.dtype)


def _pad_to(x: jax.Array, axis: int, mult: int, value=0.0) -> jax.Array:
    pad = (-x.shape[axis]) % mult
    if pad == 0:
        return x
    widths = [(0, 0)] * x.ndim
    widths[axis] = (0, pad)
    return jnp.pad(x, widths, constant_values=value)


def _evo_flash_fwd(q: jax.Array, k: jax.Array, v: jax.Array, bias: jax.Array,
                   block_q: int, block_kv: int) -> jax.Array:
    """q/k/v: [G, S, N, D]; bias: [Gb, N, S, S] with Gb ∈ {1, G}."""
    G, S, N, D = q.shape
    Gb = bias.shape[0]
    scale = 1.0 / math.sqrt(D)
    block_q = min(block_q, max(128, 1 << (S - 1).bit_length()))
    block_kv = min(block_kv, max(128, 1 << (S - 1).bit_length()))

    # [G, S, N, D] → [G*N, S, D]; bias [Gb, N, S, S] → [Gb*N, S, S]
    qh = _pad_to(q.transpose(0, 2, 1, 3).reshape(G * N, S, D), 1, block_q)
    kh = _pad_to(k.transpose(0, 2, 1, 3).reshape(G * N, S, D), 1, block_kv)
    vh = _pad_to(v.transpose(0, 2, 1, 3).reshape(G * N, S, D), 1, block_kv)
    bh = _pad_to(_pad_to(bias.reshape(Gb * N, S, S), 1, block_q),
                 2, block_kv)
    Sq, Skv = qh.shape[1], kh.shape[1]

    def bias_row(b):
        # broadcast over the leading batch (MSA-rows) dim when Gb == 1
        return b if Gb == G else b % N

    grid = (G * N, Sq // block_q, Skv // block_kv)
    out = pl.pallas_call(
        functools.partial(_evo_fwd_kernel, scale=scale, kv_len=S,
                          block_q=block_q, block_kv=block_kv),
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, block_q, D), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, block_kv, D), lambda b, i, j: (b, j, 0)),
            pl.BlockSpec((1, block_kv, D), lambda b, i, j: (b, j, 0)),
            pl.BlockSpec((1, block_q, block_kv),
                         lambda b, i, j: (bias_row(b), i, j)),
        ],
        out_specs=pl.BlockSpec((1, block_q, D), lambda b, i, j: (b, i, 0)),
        out_shape=jax.ShapeDtypeStruct((G * N, Sq, D), q.dtype),
        scratch_shapes=[
            _vmem((block_q, D), jnp.float32),
            _vmem((block_q, 1), jnp.float32),
            _vmem((block_q, 1), jnp.float32),
        ],
        compiler_params=_compiler_params(),
        interpret=_use_interpret(),
    )(qh, kh, vh, bh)
    return out[:, :S].reshape(G, N, S, D).transpose(0, 2, 1, 3)


def _reference(q, k, v, bias):
    scale = 1.0 / math.sqrt(q.shape[-1])
    s = jnp.einsum("gqnd,gknd->gnqk", q, k).astype(jnp.float32) * scale
    s = s + bias.astype(jnp.float32)
    p = jax.nn.softmax(s, axis=-1).astype(q.dtype)
    return jnp.einsum("gnqk,gknd->gqnd", p, v)


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5))
def evoformer_flash(q: jax.Array, k: jax.Array, v: jax.Array,
                    bias: jax.Array, block_q: int = 128,
                    block_kv: int = 128) -> jax.Array:
    """Flash-style biased attention. q/k/v: [G, S, N, D]; bias broadcastable
    to [G, N, S, S] on its leading dim (pass [1, N, S, S] to share the pair
    bias across MSA rows — it is read tile-wise, never expanded)."""
    return _evo_flash_fwd(q, k, v, bias, block_q, block_kv)


def _evo_vjp_fwd(q, k, v, bias, block_q, block_kv):
    return _evo_flash_fwd(q, k, v, bias, block_q, block_kv), (q, k, v, bias)


def _evo_vjp_bwd(block_q, block_kv, res, g):
    q, k, v, bias = res
    # reference-program VJP: includes the pair-bias gradient (summed over
    # the broadcast leading dim automatically by jax.vjp)
    _, pull = jax.vjp(_reference, q, k, v, bias)
    return pull(g)


evoformer_flash.defvjp(_evo_vjp_fwd, _evo_vjp_bwd)
