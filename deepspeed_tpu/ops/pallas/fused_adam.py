"""Fused Adam update as a Pallas TPU kernel.

TPU-native replacement for the reference's multi-tensor CUDA Adam
(``csrc/adam/multi_tensor_adam.cu`` behind ``ops/adam/fused_adam.py:18``): one
kernel updates param + both moments in a single pass over VMEM blocks, so the
four HBM streams (p, g, m, v) are each read/written exactly once. The
multi-tensor-apply machinery (kernel-arg chunking) is unnecessary — the caller
flattens the param pytree into one contiguous view per dtype and the grid
tiles it.

CPU fallback = interpret mode (the reference's CPU op-builder role).
"""
from __future__ import annotations

import functools
from typing import Tuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

try:
    from jax.experimental.pallas import tpu as pltpu
except ImportError:  # pragma: no cover
    pltpu = None

_BLOCK = 4096  # elements per grid step (multiple of the 8x128 vreg tile)


def _use_interpret() -> bool:
    return jax.default_backend() != "tpu"


def _adam_kernel(p_ref, g_ref, m_ref, v_ref, scal_ref,
                 p_out, m_out, v_out, *, adam_w: bool):
    lr = scal_ref[0]
    b1 = scal_ref[1]
    b2 = scal_ref[2]
    eps = scal_ref[3]
    wd = scal_ref[4]
    bc1 = scal_ref[5]
    bc2 = scal_ref[6]

    p = p_ref[...]
    g = g_ref[...].astype(jnp.float32)
    if not adam_w:
        g = g + wd * p
    m = b1 * m_ref[...] + (1.0 - b1) * g
    v = b2 * v_ref[...] + (1.0 - b2) * g * g
    upd = (m / bc1) / (jnp.sqrt(v / bc2) + eps)
    if adam_w:
        upd = upd + wd * p
    p_out[...] = p - lr * upd
    m_out[...] = m
    v_out[...] = v


def fused_adam_flat(p: jax.Array, g: jax.Array, m: jax.Array, v: jax.Array,
                    lr, step, betas: Tuple[float, float] = (0.9, 0.999),
                    eps: float = 1e-8, weight_decay: float = 0.0,
                    adam_w: bool = True, bias_correction: bool = True
                    ) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """Adam on flat fp32 views. p/m/v: [N] fp32, g: [N] (any float dtype).
    Returns (new_p, new_m, new_v)."""
    N = p.shape[0]
    b1, b2 = betas
    sf = jnp.asarray(step, jnp.float32)
    bc1 = 1.0 - b1 ** sf if bias_correction else jnp.float32(1.0)
    bc2 = 1.0 - b2 ** sf if bias_correction else jnp.float32(1.0)
    scal = jnp.stack([jnp.asarray(lr, jnp.float32), jnp.float32(b1),
                      jnp.float32(b2), jnp.float32(eps),
                      jnp.float32(weight_decay),
                      jnp.asarray(bc1, jnp.float32),
                      jnp.asarray(bc2, jnp.float32)])

    pad = (-N) % _BLOCK
    if pad:
        p, g, m, v = (jnp.pad(x, (0, pad)) for x in (p, g, m, v))
    n_blocks = p.shape[0] // _BLOCK

    spec = pl.BlockSpec((_BLOCK,), lambda i: (i,))
    scal_spec = pl.BlockSpec((7,), lambda i: (0,))
    kernel = functools.partial(_adam_kernel, adam_w=adam_w)
    new_p, new_m, new_v = pl.pallas_call(
        kernel,
        grid=(n_blocks,),
        in_specs=[spec, spec, spec, spec, scal_spec],
        out_specs=[spec, spec, spec],
        out_shape=[jax.ShapeDtypeStruct(p.shape, jnp.float32)] * 3,
        interpret=_use_interpret(),
    )(p, g, m, v, scal)
    if pad:
        new_p, new_m, new_v = (x[:N] for x in (new_p, new_m, new_v))
    return new_p, new_m, new_v


def fused_adam_tree(params, grads, exp_avg, exp_avg_sq, lr, step,
                    betas=(0.9, 0.999), eps=1e-8, weight_decay=0.0,
                    adam_w=True, bias_correction=True):
    """Pytree front-end: flatten → one kernel launch → unflatten.

    The single flat launch is the multi-tensor-apply analog: small leaves
    share grid steps instead of paying one kernel launch each."""
    leaves, treedef = jax.tree_util.tree_flatten(params)
    g_leaves = jax.tree_util.tree_leaves(grads)
    m_leaves = jax.tree_util.tree_leaves(exp_avg)
    v_leaves = jax.tree_util.tree_leaves(exp_avg_sq)
    sizes = [l.size for l in leaves]
    shapes = [l.shape for l in leaves]

    flat = lambda ls: jnp.concatenate(
        [l.reshape(-1).astype(jnp.float32) for l in ls])
    new_p, new_m, new_v = fused_adam_flat(
        flat(leaves), flat(g_leaves), flat(m_leaves), flat(v_leaves),
        lr, step, betas, eps, weight_decay, adam_w, bias_correction)

    def unflat(x):
        out, off = [], 0
        for size, shape in zip(sizes, shapes):
            out.append(x[off:off + size].reshape(shape))
            off += size
        return jax.tree_util.tree_unflatten(treedef, out)

    return unflat(new_p), unflat(new_m), unflat(new_v)
