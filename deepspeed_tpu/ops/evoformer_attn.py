"""Evoformer attention (DeepSpeed4Science / AlphaFold-family models).

Parity: reference ``csrc/deepspeed4science/evoformer_attn`` (14.9k LoC
CUTLASS fwd/bwd kernels behind ``deepspeed.ops.deepspeed4science.
DS4Sci_EvoformerAttention``): attention over MSA/pair representations with
up to two additive biases (mask bias + pair bias) and sigmoid gating.

TPU design: the computation is a biased softmax attention — XLA fuses the
bias adds and the gating elementwise into the surrounding matmuls, and the
flash-style memory behavior comes from ``jax.checkpoint`` at the caller (or
the Pallas flash kernel for the unbiased case). Shapes follow the reference
API: inputs ``[*, seq, heads, dim]`` with biases broadcastable to
``[*, heads, seq_q, seq_k]``.
"""
from __future__ import annotations

import math
from typing import Optional, Sequence

import jax
import jax.numpy as jnp


def evoformer_attention(q: jax.Array, k: jax.Array, v: jax.Array,
                        biases: Sequence[Optional[jax.Array]] = (),
                        gate: Optional[jax.Array] = None) -> jax.Array:
    """DS4Sci_EvoformerAttention analog.

    q/k/v: [..., S, N, D] (arbitrary leading batch dims — MSA rows/cols);
    biases: each broadcastable to [..., N, S_q, S_k] (e.g. mask bias
    [..., 1, 1, S_k] and pair bias [..., N, S_q, S_k]); gate: optional
    [..., S, N, D] sigmoid gate (the reference fuses it into the epilogue).
    fp32 softmax; output in q's dtype.
    """
    D = q.shape[-1]
    scale = 1.0 / math.sqrt(D)
    scores = jnp.einsum("...qnd,...knd->...nqk", q, k).astype(jnp.float32)
    scores = scores * scale
    for b in biases:
        if b is not None:
            scores = scores + b.astype(jnp.float32)
    probs = jax.nn.softmax(scores, axis=-1).astype(q.dtype)
    out = jnp.einsum("...nqk,...knd->...qnd", probs, v)
    if gate is not None:
        out = out * jax.nn.sigmoid(gate.astype(out.dtype))
    return out


def msa_row_attention_with_pair_bias(msa: jax.Array, pair_bias: jax.Array,
                                     wq, wk, wv, wo, w_gate=None,
                                     num_heads: int = 8) -> jax.Array:
    """MSA row-wise gated self-attention with pair bias (Evoformer block
    building block; reference evoformer examples).

    msa: [rows, S, C]; pair_bias: [N, S, S] (from the pair representation);
    projections are [C, N*D] / [N*D, C]."""
    R, S, C = msa.shape
    D = wq.shape[-1] // num_heads

    def proj(w):
        return (msa @ w).reshape(R, S, num_heads, D)

    q, k, v = proj(wq), proj(wk), proj(wv)
    gate = proj(w_gate) if w_gate is not None else None
    out = evoformer_attention(q, k, v, biases=(pair_bias[None],), gate=gate)
    return out.reshape(R, S, num_heads * D) @ wo
