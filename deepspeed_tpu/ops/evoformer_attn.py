"""Evoformer attention (DeepSpeed4Science / AlphaFold-family models).

Parity: reference ``csrc/deepspeed4science/evoformer_attn`` (14.9k LoC
CUTLASS fwd/bwd kernels behind ``deepspeed.ops.deepspeed4science.
DS4Sci_EvoformerAttention``): attention over MSA/pair representations with
up to two additive biases (mask bias + pair bias) and sigmoid gating.

TPU design: the computation is a biased softmax attention — XLA fuses the
bias adds and the gating elementwise into the surrounding matmuls, and the
flash-style memory behavior comes from ``jax.checkpoint`` at the caller (or
the Pallas flash kernel for the unbiased case). Shapes follow the reference
API: inputs ``[*, seq, heads, dim]`` with biases broadcastable to
``[*, heads, seq_q, seq_k]``.
"""
from __future__ import annotations

import math
from typing import Optional, Sequence

import jax
import jax.numpy as jnp


def _flash_dispatch(q, k, v, biases):
    """Try the Pallas flash path (``ops/pallas/evoformer.py``): flatten
    leading dims to one G axis and combine the biases into a single
    [1 or G, N, S, S] array. Returns None when the shapes don't reduce to
    the kernel's contract (caller falls back to the XLA path)."""
    try:
        if q.shape != k.shape or k.shape != v.shape:
            return None    # rectangular attention → XLA path
        *lead, S, N, D = q.shape
        G = 1
        for d in lead:
            G *= d
        combined = None
        for b in biases:
            if b is None:
                continue
            combined = b if combined is None else combined + b
        if combined is None:
            combined = jnp.zeros((1, N, S, S), jnp.float32)
        # normalize to exactly [*, N, S, S] (right-aligned broadcast)
        combined = jnp.broadcast_to(
            combined, jnp.broadcast_shapes(combined.shape, (1, N, S, S)))
        blead = combined.shape[:-3]
        if all(d == 1 for d in blead):
            # row-shared bias: keep Gb=1 — the kernel reads it tile-wise,
            # never expand it G-fold in HBM
            bias4 = combined.reshape(1, N, S, S)
        else:
            full = jnp.broadcast_to(combined, (*lead, N, S, S))
            if full.shape[:-3] != tuple(lead):
                return None
            bias4 = full.reshape(G, N, S, S)
    except (ValueError, TypeError):
        return None

    from deepspeed_tpu.ops.pallas.evoformer import evoformer_flash

    out = evoformer_flash(q.reshape(G, S, N, D), k.reshape(G, S, N, D),
                          v.reshape(G, S, N, D), bias4)
    return out.reshape(*lead, S, N, D)


def evoformer_attention(q: jax.Array, k: jax.Array, v: jax.Array,
                        biases: Sequence[Optional[jax.Array]] = (),
                        gate: Optional[jax.Array] = None,
                        use_flash: Optional[bool] = None) -> jax.Array:
    """DS4Sci_EvoformerAttention analog.

    q/k/v: [..., S, N, D] (arbitrary leading batch dims — MSA rows/cols);
    biases: each broadcastable to [..., N, S_q, S_k] (e.g. mask bias
    [..., 1, 1, S_k] and pair bias [..., N, S_q, S_k]); gate: optional
    [..., S, N, D] sigmoid gate (the reference fuses it into the epilogue).
    fp32 softmax; output in q's dtype.

    ``use_flash`` (default: auto — TPU backend only): route through the
    Pallas flash kernel (``ops/pallas/evoformer.py`` — the CUTLASS-kernel
    analog, [S,S] scores never hit HBM) when the bias shapes fit its
    contract; the XLA path covers everything else. Off-TPU the kernel would
    run in interpret mode, so auto keeps the fused XLA einsum; pass
    ``use_flash=True`` to force it (tests).
    """
    forced = use_flash is True
    if use_flash is None:
        use_flash = jax.default_backend() == "tpu"
    if use_flash and q.ndim >= 3:
        out = _flash_dispatch(q, k, v, biases)
        if out is not None:
            if gate is not None:
                out = out * jax.nn.sigmoid(gate.astype(out.dtype))
            return out
        if forced:
            raise ValueError("shapes do not fit the flash evoformer "
                             "kernel; pass use_flash=False")
    D = q.shape[-1]
    scale = 1.0 / math.sqrt(D)
    scores = jnp.einsum("...qnd,...knd->...nqk", q, k).astype(jnp.float32)
    scores = scores * scale
    for b in biases:
        if b is not None:
            scores = scores + b.astype(jnp.float32)
    probs = jax.nn.softmax(scores, axis=-1).astype(q.dtype)
    out = jnp.einsum("...nqk,...knd->...qnd", probs, v)
    if gate is not None:
        out = out * jax.nn.sigmoid(gate.astype(out.dtype))
    return out


def msa_row_attention_with_pair_bias(msa: jax.Array, pair_bias: jax.Array,
                                     wq, wk, wv, wo, w_gate=None,
                                     num_heads: int = 8) -> jax.Array:
    """MSA row-wise gated self-attention with pair bias (Evoformer block
    building block; reference evoformer examples).

    msa: [rows, S, C]; pair_bias: [N, S, S] (from the pair representation);
    projections are [C, N*D] / [N*D, C]."""
    R, S, C = msa.shape
    D = wq.shape[-1] // num_heads

    def proj(w):
        return (msa @ w).reshape(R, S, num_heads, D)

    q, k, v = proj(wq), proj(wk), proj(wv)
    gate = proj(w_gate) if w_gate is not None else None
    out = evoformer_attention(q, k, v, biases=(pair_bias[None],), gate=gate)
    return out.reshape(R, S, num_heads * D) @ wo
