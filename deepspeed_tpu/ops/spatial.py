"""Spatial (diffusion/UNet) inference ops.

Parity: reference ``csrc/spatial`` (``opt_bias_add.cu`` + ``pt_binding.cpp``
exposing ``nhwc_bias_add`` / ``nhwc_bias_add_add`` /
``nhwc_bias_add_bias_add`` through ``op_builder/spatial_inference.py``) —
vectorized fused bias-add variants for Stable-Diffusion UNet inference.

TPU translation: these are pure elementwise epilogues; XLA fuses them into
the producing convolution/matmul automatically, which is exactly what the
hand-written CUDA vectorization buys on GPU. The functions below provide the
same op surface (names and semantics) so reference callers port 1:1; each is
a single fused XLA expression, not a Python-level loop.

Layout note: the reference operates on NHWC half tensors; on TPU, NHWC is
also the native convolution layout (channels minor → lane dimension), so
``x`` is expected as [..., H, W, C] (or any [..., C]) with ``bias`` [C].
"""
from __future__ import annotations

import jax
import jax.numpy as jnp


def nhwc_bias_add(activation: jax.Array, bias: jax.Array) -> jax.Array:
    """result = activation + bias (reference ``seq_unroll_bias_add``)."""
    return activation + bias.astype(activation.dtype)


def nhwc_bias_add_add(activation: jax.Array, bias: jax.Array,
                      other: jax.Array) -> jax.Array:
    """result = (activation + bias) + other (reference ``seq_bias_add_add``
    — residual join in the UNet resblock)."""
    return activation + bias.astype(activation.dtype) + other


def nhwc_bias_add_bias_add(activation: jax.Array, bias: jax.Array,
                           other: jax.Array, other_bias: jax.Array
                           ) -> jax.Array:
    """result = (activation + bias) + (other + other_bias) (reference
    ``seq_bias_add_bias_add`` — joining two biased conv branches)."""
    return (activation + bias.astype(activation.dtype)
            + other + other_bias.astype(other.dtype))


def groupnorm_silu(x: jax.Array, scale: jax.Array, bias: jax.Array,
                   groups: int, eps: float = 1e-5) -> jax.Array:
    """GroupNorm → SiLU, the UNet resblock prologue the spatial kernels
    surround. [..., C] with C % groups == 0; fp32 statistics; one fused XLA
    expression (norm + affine + silu fold into a single pass)."""
    *lead, C = x.shape
    if C % groups:
        raise ValueError(f"channels {C} not divisible by groups {groups}")
    xg = x.astype(jnp.float32).reshape(*lead, groups, C // groups)
    # statistics per sample (dim 0) per group: reduce every other leading
    # (spatial) dim plus the within-group channels
    axes = tuple(range(1, len(lead))) + (len(lead) + 1,)
    mean = jnp.mean(xg, axis=axes, keepdims=True)
    var = jnp.mean(jnp.square(xg - mean), axis=axes, keepdims=True)
    y = (xg - mean) * jax.lax.rsqrt(var + eps)
    y = y.reshape(*lead, C) * scale + bias
    return jax.nn.silu(y).astype(x.dtype)
