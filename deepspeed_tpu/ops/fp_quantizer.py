"""Floating-point quantization (FP8 / FP6 / FP12) + FP8 matmul.

Parity: reference ``csrc/fp_quantizer`` (852 LoC CUDA: group-wise FP-to-FP
quantize/dequantize used for weight-only inference quantization) and
``ops/fp_quantizer/fp8_gemm*.py`` (Triton FP8 GEMM). The reference API is
``FP_Quantize.quantize(x, q_bits=6|8|12, group_size)`` /
``.dequantize`` (``deepspeed/ops/fp_quantizer/quantize.py``).

TPU design: no bit-twiddling kernels are needed —

* **FP8** uses JAX's native ``float8_e4m3fn`` / ``float8_e5m2`` dtypes. The MXU
  on v5p+/Trillium consumes fp8 operands directly, so :func:`fp8_matmul` is a
  ``dot_general`` on fp8 inputs with fp32 accumulation — the fp8_gemm Triton
  kernel's role, played by the compiler.
* **FP6/FP12** have no hardware type; they are *storage* formats in the
  reference (packed into bytes, dequantized in the GEMM epilogue). Here the
  same compression is expressed as value-space rounding onto the FP6 (e3m2) /
  FP12 (e4m7) representable grid, stored in int8/int16 containers sharded like
  the source tensor. XLA fuses the dequant into the consumer matmul, which is
  what the reference's fused dequant epilogue achieves.

Group-wise scaling matches the reference: each ``group_size`` run of elements
shares one fp32 scale chosen so the group's absmax maps to the format's max
normal value.
"""
from __future__ import annotations

import dataclasses
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax

# max normal magnitudes of the emulated formats
_FP6_E3M2_MAX = 28.0      # e3m2: exp in [-2,4] (bias 3), 2 mantissa bits → 1.75*2^4
_FP12_E4M7_MAX = 510.0    # e4m7 ~ fp16 with truncated mantissa; max ≈ 1.9921875*2^8
_FP8_E4M3_MAX = 448.0
_FP8_E5M2_MAX = 57344.0


def _round_to_fp_grid(x: jax.Array, mantissa_bits: int, min_exp: int,
                      max_exp: int) -> jax.Array:
    """Round fp32 values onto a low-precision floating-point grid.

    Emulates a 1-sign/E-exp/M-mantissa format by quantizing the mantissa at the
    value's own binade (round-to-nearest-even via jnp.round) and clamping the
    exponent range; subnormals flush toward the min-exponent fixed grid.
    """
    ax = jnp.abs(x)
    # exponent of each value, clamped into the format's normal range
    exp = jnp.clip(jnp.floor(jnp.log2(jnp.maximum(ax, 1e-30))), min_exp, max_exp)
    ulp = jnp.exp2(exp - mantissa_bits)
    q = jnp.round(ax / ulp) * ulp
    max_val = (2.0 - 2.0 ** (-mantissa_bits)) * (2.0 ** max_exp)
    q = jnp.minimum(q, max_val)
    return jnp.sign(x) * q


@dataclasses.dataclass(frozen=True)
class FPQuantConfig:
    q_bits: int = 8          # 6 | 8 | 12
    group_size: int = 512
    fp8_dtype: str = "e4m3"  # e4m3 | e5m2 (q_bits == 8 only)


class FPQuantizer:
    """Group-scaled FP quantizer (reference ``FP_Quantize`` API shape).

    ``quantize`` → (payload, scales); ``dequantize`` reconstructs fp32/bf16.
    Payload dtype: fp8 → native float8 array; fp6/fp12 → the *dequantized-grid*
    values stored in bf16/fp16 containers (storage compression is the
    container's job at checkpoint time; on-device the win is the smaller ICI /
    HBM footprint of the scales+grid representation after XLA fusion).
    """

    def __init__(self, config: Optional[FPQuantConfig] = None, **kw):
        self.config = config or FPQuantConfig(**kw)
        if self.config.q_bits not in (6, 8, 12):
            raise ValueError(f"q_bits must be 6, 8 or 12, got {self.config.q_bits}")

    # -- helpers ---------------------------------------------------------- #
    def _fmt_max(self) -> float:
        c = self.config
        if c.q_bits == 6:
            return _FP6_E3M2_MAX
        if c.q_bits == 12:
            return _FP12_E4M7_MAX
        return _FP8_E4M3_MAX if c.fp8_dtype == "e4m3" else _FP8_E5M2_MAX

    def _grouped(self, x: jax.Array) -> Tuple[jax.Array, Tuple[int, ...], int]:
        shape = x.shape
        flat = x.reshape(-1).astype(jnp.float32)
        g = self.config.group_size
        pad = (-flat.shape[0]) % g
        if pad:
            flat = jnp.pad(flat, (0, pad))
        return flat.reshape(-1, g), shape, pad

    # -- API -------------------------------------------------------------- #
    def quantize(self, x: jax.Array) -> Tuple[jax.Array, jax.Array]:
        """→ (q [same #elems, grouped], scales fp32 [n_groups])."""
        xg, shape, pad = self._grouped(x)
        amax = jnp.max(jnp.abs(xg), axis=1, keepdims=True)
        scale = jnp.where(amax > 0, amax / self._fmt_max(), 1.0)
        scaled = xg / scale
        c = self.config
        if c.q_bits == 8:
            dt = jnp.float8_e4m3fn if c.fp8_dtype == "e4m3" else jnp.float8_e5m2
            q = scaled.astype(dt)
        elif c.q_bits == 6:
            q = _round_to_fp_grid(scaled, mantissa_bits=2, min_exp=-2,
                                  max_exp=4).astype(jnp.bfloat16)
        else:  # 12
            q = _round_to_fp_grid(scaled, mantissa_bits=7, min_exp=-6,
                                  max_exp=8).astype(jnp.float16)
        return q, scale[:, 0]

    def dequantize(self, q: jax.Array, scale: jax.Array,
                   shape: Optional[Tuple[int, ...]] = None,
                   dtype=jnp.float32) -> jax.Array:
        import math

        out = q.astype(jnp.float32) * scale[:, None]
        out = out.reshape(-1)
        if shape is not None:
            out = out[: math.prod(shape)].reshape(shape)
        return out.astype(dtype)

    def roundtrip(self, x: jax.Array) -> jax.Array:
        """quantize→dequantize at the original shape (fake-quant for QAT/tests)."""
        q, s = self.quantize(x)
        return self.dequantize(q, s, shape=x.shape, dtype=x.dtype)


# --------------------------------------------------------------------------- #
# FP8 matmul (reference ops/fp_quantizer/fp8_gemm.py role)
# --------------------------------------------------------------------------- #

def fp8_quantize_tensorwise(x: jax.Array, dtype=jnp.float8_e4m3fn
                            ) -> Tuple[jax.Array, jax.Array]:
    """Tensor-wise dynamic scaling → (x_fp8, inv_scale fp32 scalar)."""
    fmt_max = _FP8_E4M3_MAX if dtype == jnp.float8_e4m3fn else _FP8_E5M2_MAX
    amax = jnp.max(jnp.abs(x.astype(jnp.float32)))
    scale = jnp.where(amax > 0, fmt_max / amax, 1.0)
    q = (x.astype(jnp.float32) * scale).astype(dtype)
    return q, 1.0 / scale


def fp8_matmul(a: jax.Array, b: jax.Array,
               a_dtype=jnp.float8_e4m3fn, b_dtype=jnp.float8_e4m3fn,
               out_dtype=jnp.bfloat16) -> jax.Array:
    """FP8×FP8 → bf16 matmul with fp32 accumulation and dynamic scaling.

    On v5p+/Trillium XLA maps the fp8 dot straight onto the MXU; elsewhere it
    upcasts — numerics are identical either way.
    """
    qa, sa = fp8_quantize_tensorwise(a, a_dtype)
    qb, sb = fp8_quantize_tensorwise(b, b_dtype)
    out = lax.dot_general(
        qa, qb,
        dimension_numbers=(((a.ndim - 1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)
    return (out * (sa * sb)).astype(out_dtype)


def fp8_linear(x: jax.Array, w_q: jax.Array, w_scale: jax.Array,
               bias: Optional[jax.Array] = None,
               out_dtype=jnp.bfloat16) -> jax.Array:
    """Weight-only-FP8 linear: activations quantized on the fly, weight is
    pre-quantized group-wise (the reference's weight-only inference path).

    w_q: fp8 [in, out] (grouped scaling folded per-column for matmul use);
    w_scale: fp32 broadcastable to [in, out] or [out].
    """
    qx, sx = fp8_quantize_tensorwise(x)
    out = lax.dot_general(
        qx, w_q, dimension_numbers=(((x.ndim - 1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)
    out = out * (sx * w_scale)
    if bias is not None:
        out = out + bias
    return out.astype(out_dtype)


def quantize_weight_fp8_columnwise(w: jax.Array, dtype=jnp.float8_e4m3fn
                                   ) -> Tuple[jax.Array, jax.Array]:
    """Per-output-column scaling for fp8_linear ([in, out] weights)."""
    fmt_max = _FP8_E4M3_MAX if dtype == jnp.float8_e4m3fn else _FP8_E5M2_MAX
    amax = jnp.max(jnp.abs(w.astype(jnp.float32)), axis=0, keepdims=True)
    scale = jnp.where(amax > 0, fmt_max / amax, 1.0)
    return (w.astype(jnp.float32) * scale).astype(dtype), (1.0 / scale)[0]
