"""Quantization + compressed collectives — the ZeRO++/1-bit comm path.

Parity: reference ``csrc/quantization`` (int quant/dequant, ``quant_reduce.cu``
fused dequant-reduce, ``swizzled_quantize.cu`` comm layout) used by ZeRO++ qgZ
(``runtime/comm/coalesced_collectives.py:31 all_to_all_quant_reduce``) and the
1-bit optimizer family's error-compensated compression
(``runtime/comm/nccl.py:52 compressed_allreduce``).

TPU design: quantize/dequant are jnp expressions XLA fuses into neighboring
ops (cf. EQuARX, PAPERS.md — on-the-fly (de)quant around ICI transfers); the
collectives are explicit ``shard_map`` programs:

* :func:`quantized_reduce_scatter` — the qgZ analog: int8-quantize the local
  shard, ``all_to_all`` the int8 blocks over the axis (4x less ICI traffic
  than fp32), then dequant-sum locally (full-precision accumulation, like
  quant_reduce.cu).
* :func:`onebit_allreduce` — sign-SGD compression with error feedback: send
  1 value of sign information per element (bool all_to_all) plus one fp32
  scale per block; the residual stays in the caller's error buffer.
"""
from __future__ import annotations

from functools import partial
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax, shard_map
from jax.sharding import Mesh, PartitionSpec as P

from deepspeed_tpu.comm.mesh import DATA_AXIS, get_mesh_manager

DEFAULT_BLOCK = 2048


# --------------------------------------------------------------------------- #
# blockwise int8 quantize / dequantize
# --------------------------------------------------------------------------- #

def quantize_int8(x: jax.Array, block: int = DEFAULT_BLOCK
                  ) -> Tuple[jax.Array, jax.Array]:
    """Symmetric per-block int8 quantization of a flat array.

    → (q int8 [N], scale fp32 [N/block]); N is padded to a block multiple by
    the caller (see :func:`pad_to_block`)."""
    n_blocks = x.shape[0] // block
    xb = x.reshape(n_blocks, block).astype(jnp.float32)
    amax = jnp.max(jnp.abs(xb), axis=1, keepdims=True)
    scale = jnp.where(amax > 0, amax / 127.0, 1.0)
    q = jnp.clip(jnp.round(xb / scale), -127, 127).astype(jnp.int8)
    return q.reshape(-1), scale[:, 0]


def dequantize_int8(q: jax.Array, scale: jax.Array,
                    block: int = DEFAULT_BLOCK) -> jax.Array:
    n_blocks = q.shape[0] // block
    xb = q.reshape(n_blocks, block).astype(jnp.float32) * scale[:, None]
    return xb.reshape(-1)


def pad_to_block(x: jax.Array, block: int = DEFAULT_BLOCK) -> Tuple[jax.Array, int]:
    pad = (-x.shape[0]) % block
    if pad:
        x = jnp.pad(x, (0, pad))
    return x, pad


# --------------------------------------------------------------------------- #
# quantized reduce-scatter (qgZ analog)
# --------------------------------------------------------------------------- #

def quantized_reduce_scatter(x: jax.Array, mesh: Optional[Mesh] = None,
                             axis_name: str = DATA_AXIS,
                             block: int = DEFAULT_BLOCK,
                             mean: bool = True,
                             use_pallas: Optional[bool] = None) -> jax.Array:
    """Reduce-scatter per-rank contributions with int8 transport.

    Input: [world, N] sharded over ``axis_name`` on dim 0 — row r is rank r's
    contribution (e.g. its local grads). Output: [world, N/world] with row r =
    the r-th reduced shard (fp32 accumulation). ICI bytes: N int8 + N/block
    fp32 scales, vs N fp32 for the plain path.

    ``use_pallas`` (default: on TPU) runs the quantize and the post-
    all-to-all dequant+sum as Pallas kernels (``ops/pallas/quantization.py``
    — the reference's ``swizzled_quantize.cu`` / ``quant_reduce.cu``):
    single-pass VMEM quantization and a fused dequant-reduce that never
    materializes the [world, chunk] fp32 intermediate.
    """
    m = mesh or get_mesh_manager().mesh
    world = m.shape.get(axis_name, 1)
    if world <= 1:
        return x
    if use_pallas is None:
        use_pallas = jax.default_backend() == "tpu"
    N = x.shape[1]
    if N % (world * block):
        raise ValueError(f"size {N} must divide world*block={world * block}")
    chunk = N // world

    def local(xl):
        # xl: [1, N] local contribution → world chunks, quantize each,
        # all_to_all so rank r gathers everyone's chunk r, dequant + sum.
        # The [world, chunk] reshape IS the comm-layout "swizzle".
        xc = xl[0].reshape(world, chunk)
        if use_pallas:
            from deepspeed_tpu.ops.pallas.quantization import \
                quantize_int8_blocks

            qf, sf = quantize_int8_blocks(xc.reshape(-1), block)
            q = qf.reshape(world, chunk)
            s = sf.reshape(world, chunk // block)
        else:
            q, s = jax.vmap(lambda c: quantize_int8(c, block))(xc)
        q = lax.all_to_all(q, axis_name, split_axis=0, concat_axis=0, tiled=True)
        s = lax.all_to_all(s, axis_name, split_axis=0, concat_axis=0, tiled=True)
        if use_pallas:
            from deepspeed_tpu.ops.pallas.quantization import dequant_reduce

            out = dequant_reduce(q, s, block, mean=mean)
        else:
            deq = jax.vmap(lambda qq, ss: dequantize_int8(qq, ss, block))(q, s)
            out = jnp.sum(deq, axis=0)
            if mean:
                out = out / world
        return out[None]

    spec = P(axis_name, None)
    fn = shard_map(local, mesh=m, in_specs=spec, out_specs=spec,
                   check_vma=False)
    return fn(x)


# --------------------------------------------------------------------------- #
# 1-bit (sign) allreduce with error feedback — packed wire format
# --------------------------------------------------------------------------- #

def pack_signs(sign: jax.Array) -> jax.Array:
    """bool [N] (N % 8 == 0) → uint8 [N/8] bitmask — the actual 1-bit wire
    payload (the reference packs on the CUDA side; here it is jnp and XLA
    fuses it into the transfer's producer)."""
    bits = sign.reshape(-1, 8).astype(jnp.uint8)
    weights = (1 << jnp.arange(8, dtype=jnp.uint8))
    return jnp.sum(bits * weights, axis=1).astype(jnp.uint8)


def unpack_signs(packed: jax.Array) -> jax.Array:
    """uint8 [M] → ±1.0 fp32 [M*8]."""
    bits = (packed[:, None] >> jnp.arange(8, dtype=jnp.uint8)) & 1
    return jnp.where(bits.astype(jnp.bool_), 1.0, -1.0).reshape(-1)


def packed_sign_allreduce(x: jax.Array, error: jax.Array, axes,
                          world: int, block: int = DEFAULT_BLOCK
                          ) -> Tuple[jax.Array, jax.Array]:
    """Mean-allreduce of ``x`` with 1-bit + per-block-scale wire format and
    error feedback. For use INSIDE a ``shard_map`` manual over ``axes``.

    x, error: fp32 [N] per-rank (N % lcm(8, block) == 0 — caller pads).
    Wire per rank: N/8 bytes of signs + N/block fp32 scales (vs 4N exact).
    Returns (reduced [N] — identical on all ranks, new_error [N] per-rank).
    Reference: ``runtime/comm/nccl.py:52 compressed_allreduce``.
    """
    nb = x.shape[0] // block
    sign, scale, new_error = onebit_compress(x, error, block)
    packed = pack_signs(sign.reshape(-1))                       # [N/8] u8
    signs_all = lax.all_gather(packed, axes, tiled=False)       # [world, N/8]
    scales_all = lax.all_gather(scale, axes, tiled=False)       # [world, nb]
    vals = jax.vmap(
        lambda s8, sc: unpack_signs(s8).reshape(nb, block) * sc[:, None]
    )(signs_all, scales_all)                                    # [world, nb, block]
    reduced = jnp.sum(vals, axis=0).reshape(-1) / world
    return reduced, new_error


def onebit_compress(x: jax.Array, error: jax.Array,
                    block: int = DEFAULT_BLOCK
                    ) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """Error-compensated sign compression (reference ``compressed_allreduce``
    ``runtime/comm/nccl.py:52``): corrected = x + error; sent = sign * mean|.|
    per block; new_error = corrected - sent."""
    corrected = x.astype(jnp.float32) + error
    n_blocks = corrected.shape[0] // block
    cb = corrected.reshape(n_blocks, block)
    scale = jnp.mean(jnp.abs(cb), axis=1)                # [n_blocks]
    sign = cb >= 0                                        # bool
    sent = jnp.where(sign, 1.0, -1.0) * scale[:, None]
    new_error = (cb - sent).reshape(-1)
    return sign, scale, new_error


def onebit_allreduce(x: jax.Array, error: jax.Array,
                     mesh: Optional[Mesh] = None,
                     axis_name: str = DATA_AXIS,
                     block: int = DEFAULT_BLOCK
                     ) -> Tuple[jax.Array, jax.Array]:
    """All-reduce (mean) with 1-bit payload + per-block scales + error feedback.

    Input: x/error [world, N] sharded over ``axis_name`` on dim 0 (row r =
    rank r's contribution / running compression error). Returns
    (reduced [N] fp32 — identical on every rank, new_error [world, N]).
    The reference's second (server-side) compression stage is folded away:
    summed sign-values are exact once scales are exchanged over ICI."""
    m = mesh or get_mesh_manager().mesh
    world = m.shape.get(axis_name, 1)
    N = x.shape[1]
    if N % block:
        raise ValueError(f"size {N} must be a multiple of block={block}")
    if world <= 1:
        corrected = x[0].astype(jnp.float32) + error[0]
        return corrected, jnp.zeros_like(error)

    def local(xl, el):
        # true 1-bit wire: packed sign bitmask + per-block fp32 scales ride
        # ICI (N/8 bytes + N/block*4, vs 4N for an exact allreduce)
        reduced, new_err = packed_sign_allreduce(
            xl[0], el[0], axis_name, world, block)
        return reduced, new_err[None]

    fn = shard_map(local, mesh=m,
                   in_specs=(P(axis_name, None), P(axis_name, None)),
                   out_specs=(P(None), P(axis_name, None)), check_vma=False)
    return fn(x, error)


# --------------------------------------------------------------------------- #
# group-wise weight-only quantization (inference)
#
# Parity: reference ``deepspeed/inference/quantization/utils.py`` (Quantizer:
# asymmetric group-wise INT4/INT8 over a group dim; DeQuantizer) and the
# post-init module wrappers (``quantization/layers.py``). Here a quantized
# weight is a {"q","scale","zero"} subtree living where the fp array used to
# be; the model dequantizes per layer inside the scan body
# (``dequant_params``), so at most one layer of fp weights is live at a time.
# --------------------------------------------------------------------------- #

def pack_int4(q: jax.Array) -> jax.Array:
    """Pack uint4 values (0..15) pairwise along the last axis → uint8."""
    lo = q[..., 0::2]
    hi = q[..., 1::2]
    return (lo | (hi << 4)).astype(jnp.uint8)


def unpack_int4(p: jax.Array) -> jax.Array:
    lo = p & 0xF
    hi = (p >> 4) & 0xF
    return jnp.stack([lo, hi], axis=-1).reshape(*p.shape[:-1], -1)


def weight_quantize_groupwise(w, num_bits: int = 8, group_size: int = 64):
    """Asymmetric group-wise quantization over the LAST axis.

    → {"q"|"q4": uint8 [..., G, gs or gs/2], "scale": f32 [..., G, 1],
       "zero": f32 [..., G, 1]} — the bit width is encoded in the KEY (a
    scalar leaf would break lax.scan slicing). Leading dims match w, so a
    stacked [L, ...] weight stays scannable (scan slices every leaf of the
    subtree along L together).
    """
    if num_bits not in (4, 8):
        raise ValueError("num_bits must be 4 or 8 (reference utils.py:47)")
    w = jnp.asarray(w)
    n = w.shape[-1]
    if n % group_size:
        raise ValueError(f"last dim {n} not divisible by group_size {group_size}")
    g = w.reshape(*w.shape[:-1], n // group_size, group_size).astype(jnp.float32)
    lo = jnp.min(g, axis=-1, keepdims=True)
    hi = jnp.max(g, axis=-1, keepdims=True)
    qmax = (1 << num_bits) - 1
    scale = jnp.where(hi > lo, (hi - lo) / qmax, 1.0)
    q = jnp.clip(jnp.round((g - lo) / scale), 0, qmax).astype(jnp.uint8)
    if num_bits == 4:
        return {"q4": pack_int4(q), "scale": scale, "zero": lo}
    return {"q": q, "scale": scale, "zero": lo}


def weight_dequantize_groupwise(d, dtype=jnp.bfloat16) -> jax.Array:
    scale, zero = d["scale"], d["zero"]
    q = unpack_int4(d["q4"]) if "q4" in d else d["q"]
    g = q.astype(jnp.float32) * scale + zero
    return g.reshape(*g.shape[:-2], -1).astype(dtype)


def is_quantized_weight(node) -> bool:
    """{"q"|"q4","scale","zero"} (groupwise int) or {"q8f","scale"}
    (columnwise native fp8)."""
    if not isinstance(node, dict):
        return False
    if "q8f" in node and "scale" in node:
        return True
    return ("q" in node or "q4" in node) and "scale" in node and "zero" in node


def dequantize_weight(node, dtype=jnp.bfloat16) -> jax.Array:
    if "q8f" in node:
        return (node["q8f"].astype(jnp.float32) * node["scale"]).astype(dtype)
    return weight_dequantize_groupwise(node, dtype)


def dequant_params(tree, dtype=jnp.bfloat16):
    """Replace quantized-weight subtrees with dequantized arrays; everything
    else passes through. Called inside the per-layer scan body so only the
    current layer's weights materialize in fp."""
    def walk(node):
        if is_quantized_weight(node):
            return dequantize_weight(node, dtype)
        if isinstance(node, dict):
            return {k: walk(v) for k, v in node.items()}
        return node
    return walk(tree)
