"""Python binding for the dstpu_aio C++ async file-I/O library.

Parity: reference ``ops/aio`` / ``csrc/aio/py_ds_aio.cpp`` ``aio_handle``
(``async_pread``/``async_pwrite``/``wait``) and the op-builder JIT-compile flow
(``op_builder/builder.py:545 jit_load``) — here the "builder" is one g++
invocation, cached next to the package (no torch cpp_extension machinery).
"""
from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import Dict, Optional

import numpy as np

from deepspeed_tpu.analysis.racelint.sanitizer import make_lock

_REPO_ROOT = os.path.abspath(os.path.join(os.path.dirname(__file__), "..", ".."))
_SRC = os.path.join(_REPO_ROOT, "csrc", "aio", "aio.cpp")
_BUILD_DIR = os.path.join(_REPO_ROOT, "build")
_SO_PATH = os.path.join(_BUILD_DIR, "libdstpu_aio.so")

_lib = None
_lib_lock = make_lock("aio._lib_lock")


def _build_library(force: bool = False) -> str:
    os.makedirs(_BUILD_DIR, exist_ok=True)
    if (force or not os.path.exists(_SO_PATH)
            or os.path.getmtime(_SO_PATH) < os.path.getmtime(_SRC)):
        cmd = ["g++", "-O2", "-std=c++17", "-shared", "-fPIC", "-pthread",
               _SRC, "-o", _SO_PATH]
        subprocess.run(cmd, check=True, capture_output=True)
    return _SO_PATH


def _load() -> ctypes.CDLL:
    global _lib
    with _lib_lock:
        if _lib is None:
            # build-once REQUIRES holding the lock across the compile:
            # two threads racing g++ on the same .so is the bug this
            # lock exists to prevent, hence the racelint suppressions
            try:
                lib = ctypes.CDLL(_build_library())   # racelint: disable=lock-across-blocking
            except OSError:
                # a cached .so built on another image (libstdc++/GLIBCXX
                # mismatch) passes the mtime check but fails to load —
                # rebuild for THIS toolchain and retry
                lib = ctypes.CDLL(_build_library(force=True))   # racelint: disable=lock-across-blocking
            lib.aio_handle_create.restype = ctypes.c_void_p
            lib.aio_handle_create.argtypes = [ctypes.c_int]
            lib.aio_handle_destroy.argtypes = [ctypes.c_void_p]
            lib.aio_submit_pwrite.restype = ctypes.c_int
            lib.aio_submit_pwrite.argtypes = [
                ctypes.c_void_p, ctypes.c_char_p, ctypes.c_void_p,
                ctypes.c_long, ctypes.c_long]
            lib.aio_submit_pread.restype = ctypes.c_int
            lib.aio_submit_pread.argtypes = [
                ctypes.c_void_p, ctypes.c_char_p, ctypes.c_void_p,
                ctypes.c_long, ctypes.c_long]
            lib.aio_wait.restype = ctypes.c_long
            lib.aio_wait.argtypes = [ctypes.c_void_p, ctypes.c_int]
            lib.aio_wait_all.restype = ctypes.c_int
            lib.aio_wait_all.argtypes = [ctypes.c_void_p]
            lib.aio_pending.restype = ctypes.c_int
            lib.aio_pending.argtypes = [ctypes.c_void_p]
            lib.aio_handle_create_ex.restype = ctypes.c_void_p
            lib.aio_handle_create_ex.argtypes = [
                ctypes.c_int, ctypes.c_int, ctypes.c_int, ctypes.c_long,
                ctypes.c_int]
            lib.aio_uring_supported.restype = ctypes.c_int
            lib.aio_uring_supported.argtypes = []
            _lib = lib
    return _lib


def uring_supported() -> bool:
    """True when the kernel accepts io_uring_setup (DeepNVMe fast path)."""
    try:
        return bool(_load().aio_uring_supported())
    except Exception as e:   # no compiler / load failure -> threads engine
        from deepspeed_tpu.utils.logging import logger

        logger.debug(f"io_uring probe failed ({type(e).__name__}: {e}); "
                     "falling back to the thread-pool engine")
        return False


class AsyncIOHandle:
    """The reference ``aio_handle`` analog over numpy buffers.

    Buffers passed to async ops MUST stay alive until wait(); the handle keeps
    a reference until the op is waited on to enforce that."""

    def __init__(self, n_threads: int = 4, engine: str = "auto",
                 odirect: bool = False, block_bytes: int = 1 << 20,
                 queue_depth: int = 32):
        """``engine``: 'threads' (pread/pwrite pool), 'uring' (raw io_uring
        chunked submission — the reference's libaio/io_uring engines), or
        'auto' (uring when the kernel supports it; DSTPU_AIO_ENGINE env
        overrides). ``odirect``/``block_bytes``/``queue_depth`` mirror the
        reference aio config (block_size / queue_depth / overlap knobs)."""
        self._lib = _load()
        if engine == "auto":
            # the env override applies ONLY to auto — an explicit engine
            # argument (tuning sweeps, tests) is always honored
            engine = os.environ.get("DSTPU_AIO_ENGINE", "auto")
        if engine == "auto":
            engine = "uring" if self._lib.aio_uring_supported() else "threads"
        if engine not in ("threads", "uring"):
            raise ValueError(f"engine must be auto|threads|uring, got {engine!r}")
        self.engine = engine
        self._h = self._lib.aio_handle_create_ex(
            n_threads, 1 if engine == "uring" else 0, int(odirect),
            block_bytes, queue_depth)
        self._live: Dict[int, np.ndarray] = {}

    def __del__(self):
        try:
            if getattr(self, "_h", None):
                self._lib.aio_wait_all(self._h)
                self._lib.aio_handle_destroy(self._h)
                self._h = None
        # interpreter teardown: ctypes globals / the lib itself may already
        # be gone, and raising from __del__ only prints noise
        except Exception:   # dslint: disable=silent-except
            pass

    # ------------------------------------------------------------ #
    def async_pwrite(self, buf: np.ndarray, path: str, offset: int = 0) -> int:
        buf = np.ascontiguousarray(buf)
        op = self._lib.aio_submit_pwrite(
            self._h, path.encode(), buf.ctypes.data_as(ctypes.c_void_p),
            buf.nbytes, offset)
        if op < 0:
            raise OSError(-op, os.strerror(-op), path)
        self._live[op] = buf
        return op

    def async_pread(self, buf: np.ndarray, path: str, offset: int = 0) -> int:
        if not buf.flags["C_CONTIGUOUS"] or not buf.flags["WRITEABLE"]:
            raise ValueError("pread buffer must be contiguous and writeable")
        op = self._lib.aio_submit_pread(
            self._h, path.encode(), buf.ctypes.data_as(ctypes.c_void_p),
            buf.nbytes, offset)
        if op < 0:
            raise OSError(-op, os.strerror(-op), path)
        self._live[op] = buf
        return op

    def wait(self, op_id: int) -> int:
        rc = self._lib.aio_wait(self._h, op_id)
        self._live.pop(op_id, None)
        if rc < 0:
            raise OSError(-rc, os.strerror(-rc))
        return int(rc)

    def wait_all(self) -> None:
        rc = self._lib.aio_wait_all(self._h)
        self._live.clear()
        if rc < 0:
            raise OSError(-rc, os.strerror(-rc))

    def pending(self) -> int:
        return int(self._lib.aio_pending(self._h))

    # sync convenience (reference sync_pread/sync_pwrite)
    def sync_pwrite(self, buf: np.ndarray, path: str, offset: int = 0) -> int:
        return self.wait(self.async_pwrite(buf, path, offset))

    def sync_pread(self, buf: np.ndarray, path: str, offset: int = 0) -> int:
        return self.wait(self.async_pread(buf, path, offset))
