"""Launcher CLI — bring up a (multi-host) training script.

Parity: reference ``bin/deepspeed`` → ``launcher/runner.py:436``. The
reference must fork one process per GPU and rendezvous them
(``launcher/launch.py:145``, PDSH/MPI transports for multi-node); on TPU the
model is one process per HOST with all local chips owned by that process, and
the rendezvous is ``jax.distributed.initialize()`` reading the TPU-pod
metadata — so the launcher reduces to: set env, optionally bootstrap
jax.distributed, run the script. Multi-host fan-out itself is the platform's
job (GKE/xpk/gcloud), matching how TPU pods are actually operated.

CLI:
    python -m deepspeed_tpu.launcher.runner [--bind_cores_to_rank] \
        script.py [args...]
"""
from __future__ import annotations

import argparse
import os
import runpy
import sys
from typing import List, Optional

from deepspeed_tpu.utils.logging import logger


def parse_args(argv: Optional[List[str]] = None) -> argparse.Namespace:
    p = argparse.ArgumentParser(
        prog="deepspeed_tpu.launcher",
        description="launch a deepspeed_tpu training script")
    p.add_argument("--master_addr", default=None,
                   help="coordinator address for multi-host bring-up "
                        "(host:port); defaults to TPU-pod auto-discovery")
    p.add_argument("--num_nodes", type=int, default=None,
                   help="process count for multi-host bring-up")
    p.add_argument("--node_rank", type=int, default=None,
                   help="this process's index for multi-host bring-up")
    p.add_argument("--bind_cores_to_rank", action="store_true",
                   help="pin this process to an equal slice of host cores "
                        "by local rank (reference bin/deepspeed "
                        "--bind_cores_to_rank; one process per TPU host ⇒ "
                        "the slice is usually all cores, but under "
                        "multi-process-per-host CPU lanes it partitions)")
    p.add_argument("--bind_core_list", default=None,
                   help="explicit comma/range core list to bind (e.g. "
                        "'0-7,16-23'); implies --bind_cores_to_rank")
    p.add_argument("--resume_dir", default=None,
                   help="checkpoint root for fault tolerance: exported as "
                        "DSTPU_RESUME_DIR, consumed by the engine's "
                        "fault_tolerance config as the default resume/"
                        "emergency-checkpoint dir")
    p.add_argument("--auto_resume", action="store_true",
                   help="resume from the newest committed checkpoint in "
                        "--resume_dir at initialize (exported as "
                        "DSTPU_AUTO_RESUME=1); a missing/empty dir is a "
                        "cold start — the restart-after-preemption loop "
                        "can always pass this flag")
    p.add_argument("--module", action="store_true",
                   help="run the target as a python module (python -m)")
    p.add_argument("script", help="training script (or module with --module)")
    p.add_argument("script_args", nargs=argparse.REMAINDER)
    return p.parse_args(argv)


def maybe_init_distributed(args: argparse.Namespace) -> None:
    """Bootstrap jax.distributed when multi-host flags/env are present."""
    import jax

    explicit = args.master_addr is not None
    env_pod = os.environ.get("MEGASCALE_COORDINATOR_ADDRESS") or \
        os.environ.get("TPU_WORKER_HOSTNAMES")
    if explicit:
        jax.distributed.initialize(
            coordinator_address=args.master_addr,
            num_processes=args.num_nodes,
            process_id=args.node_rank)
        logger.info(
            f"jax.distributed up: process {args.node_rank}/{args.num_nodes}")
    elif env_pod:
        jax.distributed.initialize()  # TPU-pod metadata discovery
        logger.info(
            f"jax.distributed up via pod metadata: "
            f"process {jax.process_index()}/{jax.process_count()}")


def parse_core_list(spec: str) -> List[int]:
    """'0-3,8,10-11' → [0,1,2,3,8,10,11]."""
    cores: List[int] = []
    for part in spec.split(","):
        part = part.strip()
        if not part:
            continue
        if "-" in part:
            lo, hi = part.split("-")
            cores.extend(range(int(lo), int(hi) + 1))
        else:
            cores.append(int(part))
    return cores


def bind_cores(args: argparse.Namespace) -> None:
    """Pin the process to its core slice (reference launcher/launch.py
    ``--bind_cores_to_rank``: numactl per local rank). One process per TPU
    host normally owns every core; when several processes share a host
    (CPU lanes, tests) each gets an equal contiguous slice by local rank."""
    if not (args.bind_cores_to_rank or args.bind_core_list):
        return
    avail = sorted(os.sched_getaffinity(0))
    pool = avail
    if args.bind_core_list:
        pool = [c for c in parse_core_list(args.bind_core_list)
                if c in avail] or avail
    local_rank = int(os.environ.get("LOCAL_RANK", 0) or 0)
    local_size = int(os.environ.get("LOCAL_WORLD_SIZE", 1) or 1)
    per = max(1, len(pool) // max(1, local_size))
    want = pool[local_rank * per:(local_rank + 1) * per] or pool
    os.sched_setaffinity(0, want)
    os.environ.setdefault("OMP_NUM_THREADS", str(len(want)))
    logger.info(f"bound to {len(want)} host cores: {want[0]}-{want[-1]}")


def export_fault_tolerance_env(args: argparse.Namespace) -> None:
    """Fault-tolerance flags → env (read by ``runtime/config.load_config``
    as section defaults; explicit JSON settings win)."""
    if args.resume_dir:
        os.environ["DSTPU_RESUME_DIR"] = os.path.abspath(args.resume_dir)
    if args.auto_resume:
        os.environ["DSTPU_AUTO_RESUME"] = "1"


def main(argv: Optional[List[str]] = None) -> None:
    args = parse_args(argv)
    bind_cores(args)
    export_fault_tolerance_env(args)
    maybe_init_distributed(args)
    sys.argv = [args.script] + args.script_args
    if args.module:
        runpy.run_module(args.script, run_name="__main__", alter_sys=True)
    else:
        runpy.run_path(args.script, run_name="__main__")


if __name__ == "__main__":
    main()
