"""Launcher CLI — bring up a (multi-host) training script.

Parity: reference ``bin/deepspeed`` → ``launcher/runner.py:436``. The
reference must fork one process per GPU and rendezvous them
(``launcher/launch.py:145``, PDSH/MPI transports for multi-node); on TPU the
model is one process per HOST with all local chips owned by that process, and
the rendezvous is ``jax.distributed.initialize()`` reading the TPU-pod
metadata — so the launcher reduces to: set env, optionally bootstrap
jax.distributed, run the script. Multi-host fan-out itself is the platform's
job (GKE/xpk/gcloud), matching how TPU pods are actually operated.

CLI:
    python -m deepspeed_tpu.launcher.runner [--bind_cores] script.py [args...]
"""
from __future__ import annotations

import argparse
import os
import runpy
import sys
from typing import List, Optional

from deepspeed_tpu.utils.logging import logger


def parse_args(argv: Optional[List[str]] = None) -> argparse.Namespace:
    p = argparse.ArgumentParser(
        prog="deepspeed_tpu.launcher",
        description="launch a deepspeed_tpu training script")
    p.add_argument("--master_addr", default=None,
                   help="coordinator address for multi-host bring-up "
                        "(host:port); defaults to TPU-pod auto-discovery")
    p.add_argument("--num_nodes", type=int, default=None,
                   help="process count for multi-host bring-up")
    p.add_argument("--node_rank", type=int, default=None,
                   help="this process's index for multi-host bring-up")
    p.add_argument("--module", action="store_true",
                   help="run the target as a python module (python -m)")
    p.add_argument("script", help="training script (or module with --module)")
    p.add_argument("script_args", nargs=argparse.REMAINDER)
    return p.parse_args(argv)


def maybe_init_distributed(args: argparse.Namespace) -> None:
    """Bootstrap jax.distributed when multi-host flags/env are present."""
    import jax

    explicit = args.master_addr is not None
    env_pod = os.environ.get("MEGASCALE_COORDINATOR_ADDRESS") or \
        os.environ.get("TPU_WORKER_HOSTNAMES")
    if explicit:
        jax.distributed.initialize(
            coordinator_address=args.master_addr,
            num_processes=args.num_nodes,
            process_id=args.node_rank)
        logger.info(
            f"jax.distributed up: process {args.node_rank}/{args.num_nodes}")
    elif env_pod:
        jax.distributed.initialize()  # TPU-pod metadata discovery
        logger.info(
            f"jax.distributed up via pod metadata: "
            f"process {jax.process_index()}/{jax.process_count()}")


def main(argv: Optional[List[str]] = None) -> None:
    args = parse_args(argv)
    maybe_init_distributed(args)
    sys.argv = [args.script] + args.script_args
    if args.module:
        runpy.run_module(args.script, run_name="__main__", alter_sys=True)
    else:
        runpy.run_path(args.script, run_name="__main__")


if __name__ == "__main__":
    main()
