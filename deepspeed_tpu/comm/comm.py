"""DeepSpeed-shaped collective communication API over XLA/ICI.

Parity: reference ``deepspeed/comm/comm.py`` — module-level functions mirroring
``torch.distributed`` (``all_reduce`` :645, ``all_gather`` :239, ``reduce_scatter``
:263, ``all_to_all_single`` :348, ``barrier`` :423, ``init_distributed`` :792,
group/rank queries :685-763, ``initialize_mesh_device`` :765), all wrapped by
``timed_op`` (:106) for the comms logger.

TPU-native design: there is ONE backend — ``jax_ici`` — and collectives are XLA ops.
Each function is dual-mode:

* **Traced** (inside ``jit``/``shard_map`` — the hot path): arguments are tracers;
  the op lowers to ``lax.psum`` / ``all_gather`` / ``psum_scatter`` / ``all_to_all``
  / ``ppermute`` over *named mesh axes*. "Groups" are axis names (or tuples of
  them); ``None`` means the dense-gradient reduction axes.
* **Eager** (host level): arguments are concrete; the call is executed via a tiny
  jitted ``shard_map`` over the live global mesh, timed, and logged. This is what
  the bench CLI and tests exercise; multi-host coordination uses
  ``jax.experimental.multihost_utils``.
"""
from __future__ import annotations

import enum
import functools
import os
import time
from typing import Optional, Sequence, Tuple, Union

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from deepspeed_tpu.comm import mesh as mesh_mod
from deepspeed_tpu.utils.comms_logging import CommsLogger, get_caller_func
from deepspeed_tpu.utils.logging import logger

AxisSpec = Union[str, Tuple[str, ...], None]


class ReduceOp(enum.Enum):
    SUM = 0
    PRODUCT = 1
    MIN = 2
    MAX = 3
    AVG = 4


comms_logger = CommsLogger()

_initialized = False


# --------------------------------------------------------------------------- #
# bring-up
# --------------------------------------------------------------------------- #

def init_distributed(
    dist_backend: str = "jax_ici",
    auto_mpi_discovery: bool = True,
    verbose: bool = True,
    timeout=None,
    init_method: Optional[str] = None,
    dist_init_required: Optional[bool] = None,
    config=None,
    rank: int = -1,
    world_size: int = -1,
    mesh_config: Optional[mesh_mod.MeshConfig] = None,
) -> None:
    """Initialize multi-host JAX (if applicable) and the global device mesh.

    Multi-host rendezvous is ``jax.distributed.initialize`` — driven by TPU-pod
    metadata or ``COORDINATOR_ADDRESS``/``NUM_PROCESSES``/``PROCESS_ID`` env, the
    role the reference fills with ``torch.distributed.init_process_group`` + MPI
    discovery (``comm/comm.py:861``).
    """
    global _initialized
    if _initialized:
        return
    n_proc_env = os.environ.get("NUM_PROCESSES") or os.environ.get("DSTPU_NUM_PROCESSES")
    coord = os.environ.get("COORDINATOR_ADDRESS") or os.environ.get("DSTPU_COORDINATOR")
    if coord and n_proc_env and int(n_proc_env) > 1:
        proc_id = int(os.environ.get("PROCESS_ID", os.environ.get("DSTPU_PROCESS_ID", 0)))
        jax.distributed.initialize(coordinator_address=coord,
                                   num_processes=int(n_proc_env), process_id=proc_id)
    elif os.environ.get("DSTPU_AUTO_DISTRIBUTED") == "1":
        # TPU-pod metadata discovery (the MPI-discovery analog). Opt-in: calling
        # it on a single host without pod metadata can block on rendezvous.
        jax.distributed.initialize()
    mesh_mod.initialize_mesh(mesh_config)
    _initialized = True
    if verbose:
        logger.info(
            f"init_distributed: backend={dist_backend} processes={jax.process_count()} "
            f"devices={jax.device_count()} mesh={mesh_mod.get_mesh_manager()}")


def is_initialized() -> bool:
    return _initialized


def initialize_mesh_device(mesh_shape, mesh_dim_names=None) -> Mesh:
    """Reference ``comm.py:765`` analog: build a (dp, sp) 2-D mesh."""
    if mesh_dim_names is None:
        mesh_dim_names = ("data", "seq")
    sizes = dict(zip(mesh_dim_names, mesh_shape))
    mgr = mesh_mod.initialize_mesh(mesh_mod.MeshConfig(
        data=sizes.get("data", 1), seq=sizes.get("seq", 1),
        tensor=sizes.get("tensor", 1), pipe=sizes.get("pipe", 1),
        expert=sizes.get("expert", 1)))
    return mgr.mesh


def destroy_process_group() -> None:
    global _initialized
    _initialized = False
    mesh_mod.reset_mesh()


# --------------------------------------------------------------------------- #
# group / rank queries
# --------------------------------------------------------------------------- #

def _axes(group: AxisSpec) -> Tuple[str, ...]:
    if group is None:
        return mesh_mod.DENSE_GRAD_REDUCE_AXES
    if isinstance(group, str):
        return (group,)
    return tuple(group)


def get_world_size(group: AxisSpec = None) -> int:
    mgr = mesh_mod.get_mesh_manager()
    if group is None:
        return mgr.world_size
    return int(np.prod([mgr.axis_size(a) for a in _axes(group)]))


def _group_size(group: AxisSpec) -> int:
    """Size of the axis group a collective actually reduces over (group=None →
    the dense-grad axes, NOT the full mesh — unlike torch-parity get_world_size)."""
    mgr = mesh_mod.get_mesh_manager()
    return int(np.prod([mgr.axis_size(a) for a in _axes(group)]))


def get_rank(group: AxisSpec = None) -> int:
    """Host-level rank = process index (SPMD single-controller semantics)."""
    return jax.process_index()


def get_local_rank() -> int:
    return 0


def get_axis_index(axis: str):
    """In-trace rank along a mesh axis (usable inside shard_map)."""
    return lax.axis_index(axis)


def get_data_parallel_world_size() -> int:
    return mesh_mod.get_mesh_manager().dp_world_size


def get_tensor_model_parallel_world_size() -> int:
    return mesh_mod.get_mesh_manager().tp_world_size


def barrier(group: AxisSpec = None, name: str = "barrier") -> None:
    from jax.experimental import multihost_utils

    if jax.process_count() > 1:
        multihost_utils.sync_global_devices(name)


# --------------------------------------------------------------------------- #
# timed-op plumbing
# --------------------------------------------------------------------------- #

def _is_traced(x) -> bool:
    return isinstance(x, jax.core.Tracer)


def _nbytes(x) -> int:
    try:
        return int(np.prod(x.shape)) * jnp.dtype(x.dtype).itemsize
    except (TypeError, AttributeError, ValueError):
        return 0   # not array-shaped (scalar leaf, odd dtype): no bytes



def timed_op(fn):
    """Wrap a collective: log traced ops by size/count, time eager ops by wall clock.

    Reference analog: ``comm/comm.py:106 timed_op``.
    """
    import inspect

    sig = inspect.signature(fn)

    @functools.wraps(fn)
    def wrapper(tensor, *args, **kwargs):
        log_name = kwargs.pop("log_name", fn.__name__)
        debug_name = f"{log_name}.{get_caller_func()}" if comms_logger.debug else log_name
        try:
            bound = sig.bind_partial(tensor, *args, **kwargs)
            group = bound.arguments.get("group")
        except TypeError:
            group = kwargs.get("group")
        if _is_traced(tensor):
            comms_logger.append_traced(fn.__name__, debug_name, _nbytes(tensor))
            return fn(tensor, *args, **kwargs)
        if not comms_logger.enabled:
            return fn(tensor, *args, **kwargs)
        start = time.perf_counter()
        out = fn(tensor, *args, **kwargs)
        jax.block_until_ready(out)
        comms_logger.append(fn.__name__, debug_name, time.perf_counter() - start,
                            _nbytes(tensor), _group_size(group))
        return out

    return wrapper


def configure(deepspeed_config=None, enabled=None, prof_all=None, prof_ops=None,
              verbose=None, debug=None) -> None:
    """Configure the comms logger (reference ``comm.py:198`` analog)."""
    if deepspeed_config is not None and getattr(deepspeed_config, "comms_config", None):
        comms_logger.configure(deepspeed_config.comms_config)
    if enabled is not None:
        comms_logger.enabled = enabled
    if prof_all is not None:
        comms_logger.prof_all = prof_all
    if prof_ops is not None:
        comms_logger.prof_ops = prof_ops
    if verbose is not None:
        comms_logger.verbose = verbose
    if debug is not None:
        comms_logger.debug = debug


def log_summary(show_straggler: bool = False) -> str:
    return comms_logger.log_summary(show_straggler=show_straggler)


# --------------------------------------------------------------------------- #
# eager execution helper: run a shard_map'd collective over the global mesh
# --------------------------------------------------------------------------- #

def _eager_shard_map(fn, x, in_spec: P, out_spec: P):
    mesh = mesh_mod.get_mesh()
    shmapped = jax.shard_map(fn, mesh=mesh, in_specs=(in_spec,), out_specs=out_spec)
    return jax.jit(shmapped)(x)


def _replicated(x):
    """Place an eager array replicated on the mesh so shard_map specs line up."""
    mesh = mesh_mod.get_mesh()
    return jax.device_put(jnp.asarray(x), NamedSharding(mesh, P()))


# --------------------------------------------------------------------------- #
# collectives
# --------------------------------------------------------------------------- #

@timed_op
def all_reduce(tensor, op: ReduceOp = ReduceOp.SUM, group: AxisSpec = None):
    """SUM/AVG/MIN/MAX/PRODUCT all-reduce over mesh axes. (reference comm.py:645)"""
    axes = _axes(group)
    if _is_traced(tensor):
        return _lax_reduce(tensor, op, axes)
    tensor = _replicated(tensor)
    return _eager_shard_map(lambda t: _lax_reduce(t, op, axes), tensor, P(), P())


def _lax_reduce(tensor, op: ReduceOp, axes: Tuple[str, ...]):
    if op == ReduceOp.SUM:
        return lax.psum(tensor, axes)
    if op == ReduceOp.AVG:
        return lax.pmean(tensor, axes)
    if op == ReduceOp.MIN:
        return lax.pmin(tensor, axes)
    if op == ReduceOp.MAX:
        return lax.pmax(tensor, axes)
    if op == ReduceOp.PRODUCT:
        return jnp.exp(lax.psum(jnp.log(tensor.astype(jnp.float32)), axes)).astype(tensor.dtype)
    raise ValueError(f"unsupported ReduceOp {op}")


def inference_all_reduce(tensor, op: ReduceOp = ReduceOp.SUM, group: AxisSpec = None):
    """Latency-oriented allreduce (reference comm.py:662). Same XLA op on TPU."""
    return all_reduce(tensor, op=op, group=group, log_name="inference_all_reduce")


@timed_op
def all_gather(tensor, group: AxisSpec = None, gather_axis: int = 0, tiled: bool = True):
    """Gather shards along ``gather_axis`` over mesh axes. (reference comm.py:239)

    ``tiled=True`` concatenates along the existing axis (torch
    ``all_gather_into_tensor`` semantics); ``tiled=False`` stacks a new leading axis.
    """
    axes = _axes(group)
    if _is_traced(tensor):
        return lax.all_gather(tensor, axes, axis=gather_axis, tiled=tiled)
    mesh = mesh_mod.get_mesh()
    in_spec = _spec_on_axis(tensor.ndim, gather_axis, axes)
    x = jax.device_put(jnp.asarray(tensor), NamedSharding(mesh, in_spec))
    return _eager_shard_map(
        lambda t: lax.all_gather(t, axes, axis=gather_axis, tiled=tiled), x, in_spec,
        P() if tiled else P())


def all_gather_into_tensor(output_tensor, tensor, group: AxisSpec = None):
    """torch-style in-out signature; returns the gathered tensor."""
    return all_gather(tensor, group=group, gather_axis=0, tiled=True,
                      log_name="all_gather_into_tensor")


@timed_op
def reduce_scatter(tensor, op: ReduceOp = ReduceOp.SUM, group: AxisSpec = None,
                   scatter_axis: int = 0, tiled: bool = True):
    """psum-scatter over mesh axes. (reference reduce_scatter_tensor comm.py:297)"""
    axes = _axes(group)
    if op == ReduceOp.AVG:
        n = _group_size(group)

        def f(t):
            return lax.psum_scatter(t, axes, scatter_dimension=scatter_axis, tiled=tiled) / n
    elif op == ReduceOp.SUM:
        def f(t):
            return lax.psum_scatter(t, axes, scatter_dimension=scatter_axis, tiled=tiled)
    else:
        raise ValueError(f"reduce_scatter supports SUM/AVG, got {op}")
    if _is_traced(tensor):
        return f(tensor)
    x = _replicated(tensor)
    out_spec = _spec_on_axis(tensor.ndim, scatter_axis, axes)
    return _eager_shard_map(f, x, P(), out_spec)


def reduce_scatter_tensor(output_tensor, tensor, op: ReduceOp = ReduceOp.SUM,
                          group: AxisSpec = None):
    return reduce_scatter(tensor, op=op, group=group, log_name="reduce_scatter_tensor")


@timed_op
def all_to_all_single(tensor, group: AxisSpec = None, split_axis: int = 0,
                      concat_axis: int = 0):
    """Transpose shards across the group. (reference comm.py:348)"""
    axes = _axes(group)

    def f(t):
        return lax.all_to_all(t, axes, split_axis=split_axis, concat_axis=concat_axis,
                              tiled=True)

    if _is_traced(tensor):
        return f(tensor)
    in_spec = _spec_on_axis(tensor.ndim, concat_axis, axes)
    x = jax.device_put(jnp.asarray(tensor),
                       NamedSharding(mesh_mod.get_mesh(), in_spec))
    out_spec = _spec_on_axis(tensor.ndim, split_axis, axes)
    return _eager_shard_map(f, x, in_spec, out_spec)


def all_to_all(output_list, input_list, group: AxisSpec = None):
    """List-of-tensors all_to_all; stacked then split (reference comm.py:367)."""
    stacked = jnp.stack(input_list, axis=0)
    out = all_to_all_single(stacked, group=group, split_axis=0, concat_axis=0,
                            log_name="all_to_all")
    return [out[i] for i in range(out.shape[0])]


@timed_op
def broadcast(tensor, src: int = 0, group: AxisSpec = None):
    """Broadcast from group-rank ``src``. Traced impl: masked psum. (comm.py:227)"""
    axes = _axes(group)

    def f(t):
        idx = _group_linear_index(axes)
        mask = (idx == src).astype(t.dtype)
        return lax.psum(t * mask, axes)

    if _is_traced(tensor):
        return f(tensor)
    # Eager single-process SPMD: every caller holds the value already. With
    # multiple PROCESSES host values can genuinely diverge (the case
    # broadcast exists for) — route through the real host broadcast. Only
    # the default (whole-world) group maps onto processes: for a subgroup,
    # ``src`` is a group rank and each group would need its own exchange —
    # refuse loudly rather than deliver process src's value to every group.
    if jax.process_count() > 1:
        if group is not None:
            raise NotImplementedError(
                "eager broadcast over a subgroup with process_count > 1 is "
                "not supported (host values can diverge per process, but "
                "host_broadcast only exchanges whole-world). Broadcast "
                "inside a traced step, or use group=None.")
        return jnp.asarray(host_broadcast(np.asarray(tensor), src=src))
    return jnp.asarray(tensor)


def _group_linear_index(axes: Tuple[str, ...]):
    idx = lax.axis_index(axes[0])
    for a in axes[1:]:
        idx = idx * lax.axis_size(a) + lax.axis_index(a)
    return idx


@timed_op
def permute(tensor, perm: Sequence[Tuple[int, int]], group: AxisSpec = None):
    """Point-to-point via collective permute — the p2p send/recv analog
    (reference ``runtime/pipe/p2p.py:46,67``); only meaningful inside shard_map."""
    axes = _axes(group)
    axis = axes[0] if len(axes) == 1 else axes
    return lax.ppermute(tensor, axis, list(perm))


def send(tensor, dst: int, group: AxisSpec = None):
    raise NotImplementedError(
        "SPMD programs express p2p as comm.permute(...) inside shard_map; "
        "eager send/recv has no analog under XLA.")


def recv(tensor, src: int, group: AxisSpec = None):
    raise NotImplementedError(
        "SPMD programs express p2p as comm.permute(...) inside shard_map.")


def _spec_on_axis(ndim: int, axis: int, mesh_axes: Tuple[str, ...]) -> P:
    parts = [None] * ndim
    axis = axis % max(ndim, 1)
    parts[axis] = mesh_axes if len(mesh_axes) > 1 else mesh_axes[0]
    return P(*parts)


# --------------------------------------------------------------------------- #
# host-value helpers (cross-process coordination)
# --------------------------------------------------------------------------- #

def host_allgather(value):
    """Gather a host value from every process (numpy out). Multi-host safe."""
    from jax.experimental import multihost_utils

    if jax.process_count() == 1:
        return np.asarray(value)[None]
    return np.asarray(multihost_utils.process_allgather(jnp.asarray(value)))


def host_broadcast(value, src: int = 0):
    from jax.experimental import multihost_utils

    if jax.process_count() == 1:
        return value
    return multihost_utils.broadcast_one_to_all(value, is_source=jax.process_index() == src)
