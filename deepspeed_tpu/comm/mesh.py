"""Device mesh construction and parallel-topology state.

This layer replaces BOTH the reference's process-group factory
(``deepspeed/utils/groups.py``, 916 LoC of cached torch ProcessGroups) and its
``ProcessTopology`` named-axes rank grid (``runtime/pipe/topology.py:12``): on TPU a
single ``jax.sharding.Mesh`` with named axes *is* the topology, and "groups" are mesh
axis subsets addressed by name inside ``shard_map``/``pjit``.

Axis order is chosen so the most bandwidth-hungry axes are innermost on the ICI
torus: ``('pipe', 'data', 'expert', 'seq', 'tensor')``. On multi-slice/multi-host
deployments the outermost non-trivial axis rides DCN (hybrid mesh).
"""
from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh

from deepspeed_tpu.utils.logging import logger

# Canonical axis names, outermost → innermost.
PIPE_AXIS = "pipe"
DATA_AXIS = "data"
ZSHARD_AXIS = "zshard"   # MiCS/hpZ replica-group subdivision of the DP width:
                         # ZeRO states shard over 'zshard' (the subgroup, inner
                         # on the ICI torus) and replicate over 'data' (the
                         # replica groups) — reference zero/mics.py:63 MiCS_Init
                         # partition groups / ZeRO++ hpZ (zero/config.py:309).
EXPERT_AXIS = "expert"
SEQ_AXIS = "seq"
TENSOR_AXIS = "tensor"
DEFAULT_AXIS_ORDER: Tuple[str, ...] = (PIPE_AXIS, DATA_AXIS, ZSHARD_AXIS,
                                       EXPERT_AXIS, SEQ_AXIS, TENSOR_AXIS)

# Dense-parameter gradients are averaged over every axis that replicates dense
# params: data, expert (experts-within-dp layout, reference groups.py:304) and seq
# (Ulysses ranks share parameters, reference sequence/layer.py).
DENSE_GRAD_REDUCE_AXES: Tuple[str, ...] = (DATA_AXIS, ZSHARD_AXIS, EXPERT_AXIS,
                                           SEQ_AXIS)
# Expert parameters are sharded over 'expert'; their grads reduce over the rest.
EXPERT_GRAD_REDUCE_AXES: Tuple[str, ...] = (DATA_AXIS, ZSHARD_AXIS, SEQ_AXIS)


@dataclasses.dataclass(frozen=True)
class MeshConfig:
    pipe: int = 1
    data: int = -1  # -1 = absorb all remaining devices
    zshard: int = 1  # MiCS/hpZ partition size (1 = ZeRO shards over full 'data')
    expert: int = 1
    seq: int = 1
    tensor: int = 1

    def resolve(self, n_devices: int) -> Dict[str, int]:
        sizes = {PIPE_AXIS: self.pipe, DATA_AXIS: self.data,
                 ZSHARD_AXIS: self.zshard, EXPERT_AXIS: self.expert,
                 SEQ_AXIS: self.seq, TENSOR_AXIS: self.tensor}
        fill_axes = [a for a, s in sizes.items() if s == -1]
        fixed = int(np.prod([s for s in sizes.values() if s != -1]))
        if n_devices % fixed != 0:
            raise ValueError(
                f"mesh shape {sizes} does not divide device count {n_devices}")
        remaining = n_devices // fixed
        if not fill_axes:
            if fixed != n_devices:
                raise ValueError(
                    f"mesh shape {sizes} (={fixed}) != device count {n_devices}")
        elif len(fill_axes) == 1:
            sizes[fill_axes[0]] = remaining
        else:
            raise ValueError("at most one mesh axis may be -1")
        return sizes


class MeshManager:
    """Holds the live Mesh plus derived parallel-dimension queries."""

    def __init__(self, mesh: Mesh):
        self.mesh = mesh

    # --- sizes ---
    def axis_size(self, axis: str) -> int:
        return self.mesh.shape.get(axis, 1)

    @property
    def world_size(self) -> int:
        return self.mesh.size

    @property
    def dp_world_size(self) -> int:
        # "data parallel" in the reference's sense: number of dense-param replicas.
        return int(np.prod([self.axis_size(a) for a in
                            (DATA_AXIS, ZSHARD_AXIS, EXPERT_AXIS, SEQ_AXIS)]))

    @property
    def tp_world_size(self) -> int:
        return self.axis_size(TENSOR_AXIS)

    @property
    def pp_world_size(self) -> int:
        return self.axis_size(PIPE_AXIS)

    @property
    def ep_world_size(self) -> int:
        return self.axis_size(EXPERT_AXIS)

    @property
    def sp_world_size(self) -> int:
        return self.axis_size(SEQ_AXIS)

    def axis_names(self) -> Tuple[str, ...]:
        return tuple(self.mesh.axis_names)

    def __repr__(self) -> str:
        shape = {a: self.axis_size(a) for a in self.mesh.axis_names}
        return f"MeshManager(shape={shape})"


_GLOBAL_MESH: Optional[MeshManager] = None


def initialize_mesh(
    mesh_config: Optional[MeshConfig] = None,
    devices: Optional[Sequence[jax.Device]] = None,
    allow_split_physical_axes: bool = False,
) -> MeshManager:
    """Create and install the global mesh.

    Uses ``jax.make_mesh`` so device ordering respects the physical ICI topology;
    for multi-slice (DCN-connected) deployments the outermost non-unit axis is laid
    out across slices by ``mesh_utils.create_hybrid_device_mesh`` when granule info
    is available.
    """
    global _GLOBAL_MESH
    mesh_config = mesh_config or MeshConfig()
    devices = list(devices) if devices is not None else jax.devices()
    sizes = mesh_config.resolve(len(devices))
    shape = tuple(sizes[a] for a in DEFAULT_AXIS_ORDER)
    # AxisType landed in newer jax; older builds default every axis to the
    # same auto sharding behavior, so simply omit the kwarg there
    axis_type_cls = getattr(jax.sharding, "AxisType", None)
    kw = {} if axis_type_cls is None else {
        "axis_types": tuple(axis_type_cls.Auto for _ in DEFAULT_AXIS_ORDER)}
    try:
        mesh = jax.make_mesh(shape, DEFAULT_AXIS_ORDER, devices=devices,
                             **kw)
    except Exception as e:
        # make_mesh is missing on older jax and rejects kwargs across
        # versions — the raw Mesh fallback is topology-order-naive but
        # always constructible, so note WHY we degraded
        logger.debug(f"jax.make_mesh unavailable/failed "
                     f"({type(e).__name__}: {e}); using raw Mesh fallback")
        dev_array = np.asarray(devices).reshape(shape)
        mesh = Mesh(dev_array, DEFAULT_AXIS_ORDER, **kw)
    _GLOBAL_MESH = MeshManager(mesh)
    logger.info(f"initialized device mesh: {_GLOBAL_MESH}")
    return _GLOBAL_MESH


def set_mesh(mesh: Mesh) -> MeshManager:
    global _GLOBAL_MESH
    _GLOBAL_MESH = MeshManager(mesh)
    return _GLOBAL_MESH


def get_mesh_manager() -> MeshManager:
    global _GLOBAL_MESH
    if _GLOBAL_MESH is None:
        initialize_mesh()
    return _GLOBAL_MESH


def get_mesh() -> Mesh:
    return get_mesh_manager().mesh


def maybe_mesh() -> Optional[Mesh]:
    """The process mesh if one can be (lazily) initialized, else None —
    THE probe idiom for layers that degrade gracefully to replicated
    execution (MoE dispatch, inference TP, AutoSP planning). The broad
    catch is deliberate and traced at debug level: mesh construction can
    fail for backend-specific reasons (no devices yet, incompatible jax
    build), and every caller treats "no mesh" as "run unsharded"."""
    try:
        return get_mesh_manager().mesh
    except Exception as e:
        logger.debug(f"mesh unavailable ({type(e).__name__}: {e}); "
                     "callers degrade to replicated execution")
        return None


def mesh_is_initialized() -> bool:
    return _GLOBAL_MESH is not None


def reset_mesh() -> None:
    global _GLOBAL_MESH
    _GLOBAL_MESH = None
    for hook in _RESET_HOOKS:
        hook()


# callbacks run on reset_mesh() — lets mesh-keyed caches elsewhere (e.g.
# moe.layer._SHARDED_FN_CACHE's compiled shard_map programs) die with the
# mesh instead of leaking across re-initializations
_RESET_HOOKS = []


def on_reset_mesh(hook) -> None:
    _RESET_HOOKS.append(hook)
