"""Collective bandwidth math — THE one copy of the busbw correction factors.

``utils/comms_logging.calc_bw_log`` and ``utils/comm_bench`` used to each
carry their own factor table; at world size *n* the two could (and briefly
did) disagree about which ops get the ``(n-1)/n`` ring correction, which
made "busbw" in a bench row and "busbw" in a CommsLogger summary silently
different quantities. Both now import from here, and the compiled-collective
ledger (``profiling/observatory``) uses the same table for its *predicted*
bandwidths — so a wire-byte diff across rounds compares one convention.

Conventions (NCCL-tests / reference ``comms_logging.py``):

* ``size_bytes`` is the FULL logical tensor (the gathered/reduced result,
  not the per-rank shard) — algbw = size / time;
* busbw = algbw × factor, where the ring factor is ``2(n-1)/n`` for
  all-reduce (reduce-scatter + all-gather wire phases) and ``(n-1)/n``
  for all-gather / reduce-scatter / all-to-all (each rank moves all but
  its own shard);
* point-to-point shuffles (collective-permute / broadcast / unknown ops)
  take factor 1.0 — algbw is already the wire rate.

Stdlib-only: importable before jax loads (bench orchestrator, HLO parser).
"""
from __future__ import annotations

from typing import Dict

#: canonical collective kinds (the ledger's vocabulary)
ALL_REDUCE = "all_reduce"
ALL_GATHER = "all_gather"
REDUCE_SCATTER = "reduce_scatter"
ALL_TO_ALL = "all_to_all"
COLLECTIVE_PERMUTE = "collective_permute"
BROADCAST = "broadcast"
UNKNOWN = "unknown"

COLLECTIVE_KINDS = (ALL_REDUCE, ALL_GATHER, REDUCE_SCATTER, ALL_TO_ALL,
                    COLLECTIVE_PERMUTE, BROADCAST, UNKNOWN)

# every alias the reference API, jax lax names, and HLO opcodes use for
# the same logical collective
_ALIASES: Dict[str, str] = {
    # reference deepspeed comm op names
    "all_reduce": ALL_REDUCE, "inference_all_reduce": ALL_REDUCE,
    "all_reduce_coalesced": ALL_REDUCE,
    "all_gather": ALL_GATHER, "all_gather_into_tensor": ALL_GATHER,
    "all_gather_object": ALL_GATHER,
    "reduce_scatter": REDUCE_SCATTER, "reduce_scatter_tensor": REDUCE_SCATTER,
    "all_to_all": ALL_TO_ALL, "all_to_all_single": ALL_TO_ALL,
    "broadcast": BROADCAST, "broadcast_object_list": BROADCAST,
    # jax lax spellings
    "psum": ALL_REDUCE, "pmean": ALL_REDUCE,
    "psum_scatter": REDUCE_SCATTER,
    "ppermute": COLLECTIVE_PERMUTE, "pshuffle": COLLECTIVE_PERMUTE,
    # HLO opcodes (async -start variants normalize in canonical_kind)
    "all-reduce": ALL_REDUCE,
    "all-gather": ALL_GATHER,
    "reduce-scatter": REDUCE_SCATTER,
    "all-to-all": ALL_TO_ALL,
    "collective-permute": COLLECTIVE_PERMUTE,
    "collective-broadcast": BROADCAST,
}


def canonical_kind(op: str) -> str:
    """Map any op spelling (reference API name, jax lax name, HLO opcode,
    including async ``-start``/``-done`` variants) to a canonical kind;
    unrecognized spellings → ``"unknown"`` (never raises)."""
    name = (op or "").strip().lower()
    for suffix in ("-start", "-done"):
        if name.endswith(suffix):
            name = name[: -len(suffix)]
    return _ALIASES.get(name, UNKNOWN)


def busbw_factor(op: str, n: int) -> float:
    """Bus-bandwidth correction factor for ``op`` at group size ``n``.

    busbw = algbw × factor. ``n <= 1`` is a degenerate group (no wire
    traffic) — factor 0 for the ring collectives, 1 for point-to-point.
    """
    n = int(n)
    kind = canonical_kind(op)
    if n <= 1:
        return 0.0 if kind in (ALL_REDUCE, ALL_GATHER, REDUCE_SCATTER,
                               ALL_TO_ALL) else 1.0
    if kind == ALL_REDUCE:
        return 2.0 * (n - 1) / n
    if kind in (ALL_GATHER, REDUCE_SCATTER, ALL_TO_ALL):
        return (n - 1) / n
    # collective-permute / broadcast / unknown: the message rate IS the
    # wire rate
    return 1.0


def bw_log(op: str, size_bytes: int, duration_s: float,
           n: int) -> Dict[str, float]:
    """Algorithmic + bus bandwidth of one timed collective (GB/s) — the
    body behind ``utils/comms_logging.calc_bw_log``."""
    duration_s = max(float(duration_s), 1e-9)
    tput = float(size_bytes) / duration_s
    return {"tput_GBps": tput / 1e9,
            "busbw_GBps": tput * busbw_factor(op, n) / 1e9}


# --------------------------------------------------------------------- #
# datasheet link bandwidth (the ledger's comm-time prediction referent)
# --------------------------------------------------------------------- #

#: aggregate ICI bandwidth per chip, GB/s (datasheet: v4 2400 Gb/s,
#: v5e 1600, v5p 4800, v6e/Trillium 3584)
ICI_GBPS = {"v4": 300.0, "v5e": 200.0, "v5 lite": 200.0,
            "v5p": 600.0, "v6e": 448.0, "v6 lite": 448.0}

#: fallback when the device kind is unrecognized (CPU hosts, tests):
#: software collectives through shared memory land in this order
DEFAULT_LINK_GBPS = 10.0


def chip_link_gbps(device_kind: str, default: float = DEFAULT_LINK_GBPS) -> float:
    """Per-chip ICI GB/s for a PJRT ``device_kind`` string."""
    kind = (device_kind or "").lower()
    for key, gbps in ICI_GBPS.items():
        if key in kind:
            return gbps
    return default


def predicted_seconds(op: str, size_bytes: int, n: int,
                      link_gbps: float) -> float:
    """Predicted wire time of one collective at the given per-chip link
    bandwidth: bus bytes (size × busbw factor) over the link rate."""
    if link_gbps <= 0:
        return 0.0
    return float(size_bytes) * busbw_factor(op, n) / (link_gbps * 1e9)
