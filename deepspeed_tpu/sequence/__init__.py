"""Sequence / context parallelism (reference ``deepspeed/sequence/`` +
``runtime/sequence_parallel/``; SURVEY.md §5.7).

Long-context mechanisms, all over the 'seq' mesh axis:

* :func:`ulysses_attention` — all-to-all head-scatter attention (Ulysses).
* :func:`ulysses_attention_shard_map` — explicit-collective variant.
* :func:`ring_attention` — KV ring over ICI (idiomatic TPU context parallelism;
  capability not present in the reference, see SURVEY.md §2.3).
* :func:`chunked_attention` — FPDT-style query chunking.
* :func:`sequence_tiled_compute` / :func:`tiled_lm_loss` — ALST tiling.
"""
from deepspeed_tpu.sequence.ring import ring_attention
from deepspeed_tpu.sequence.tiled import (
    chunked_attention,
    sequence_tiled_compute,
    tiled_lm_loss,
)
from deepspeed_tpu.sequence.ulysses import (
    ulysses_attention,
    ulysses_attention_shard_map,
)

__all__ = [
    "ring_attention",
    "chunked_attention",
    "sequence_tiled_compute",
    "tiled_lm_loss",
    "ulysses_attention",
    "ulysses_attention_shard_map",
]
