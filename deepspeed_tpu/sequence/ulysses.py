"""Ulysses sequence parallelism — all-to-all head-scatter / seq-gather attention.

Parity: reference ``deepspeed/sequence/layer.py`` (``DistributedAttention`` :351,
``_SeqAllToAll`` :297, ``single_all_to_all`` :241). DeepSpeed-Ulysses shards the
sequence dim outside attention and swaps to head-sharding around it with two
all-to-alls, cutting attention comm >10x vs Megatron-SP (SURVEY.md §5.7).

TPU-native design — two interchangeable implementations:

* ``ulysses_attention`` (default): **GSPMD re-sharding**. Activations arrive
  seq-sharded (``P(dp, 'seq', ...)``); we constrain q/k/v to head-sharded specs
  (``P(dp, None, 'seq', ...)``) and the output back to seq-sharded. XLA lowers
  the spec change to exactly the reference's all-to-all pair, scheduled on ICI
  and overlapped by the latency-hiding scheduler. Composes with any inner
  attention (XLA fused, Pallas flash) because the inner fn sees global shapes.
* ``ulysses_attention_shard_map``: **explicit** ``lax.all_to_all`` inside
  ``shard_map`` — the literal ``_SeqAllToAll`` dataflow, kept for tests and for
  kernels that must see per-device shapes.

GQA note: when kv_heads < sp, k/v all-to-all cannot split the head dim; the
explicit variant repeats KV heads up to ``sp`` first (the reference's
uneven-heads path, ``sequence/layer.py:131``).
"""
from __future__ import annotations

from functools import partial
from typing import Callable, Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax, shard_map
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from deepspeed_tpu.comm.mesh import (
    DATA_AXIS,
    EXPERT_AXIS,
    SEQ_AXIS,
    TENSOR_AXIS,
    ZSHARD_AXIS,
    get_mesh_manager,
)


def _batch_axes(mesh: Mesh) -> Tuple[str, ...]:
    # MUST match the engine's batch sharding (partitioning.py batch_axes:
    # data × zshard × expert — hpZ's 'zshard' is a DP subgroup). Omitting
    # an axis here silently forces a batch re-shard at the attention
    # boundary, which the SPMD partitioner can only do by replicate-then-
    # repartition in the backward ("involuntary full rematerialization",
    # caught by __graft_entry__.dryrun_multichip's stderr assert).
    return tuple(a for a in (DATA_AXIS, ZSHARD_AXIS, EXPERT_AXIS)
                 if mesh.shape.get(a, 1) > 1)


def _maybe(axes: Tuple[str, ...]):
    if not axes:
        return None
    return axes if len(axes) > 1 else axes[0]


def seq_sharded_spec(mesh: Mesh) -> P:
    """[B, S, N, D] with S on 'seq' (and heads on 'tensor' if present)."""
    tp = TENSOR_AXIS if mesh.shape.get(TENSOR_AXIS, 1) > 1 else None
    return P(_maybe(_batch_axes(mesh)), SEQ_AXIS, tp, None)


def head_sharded_spec(mesh: Mesh) -> P:
    """[B, S, N, D] with N on ('tensor','seq') — the inside-attention layout."""
    heads = tuple(a for a in (TENSOR_AXIS, SEQ_AXIS) if mesh.shape.get(a, 1) > 1)
    return P(_maybe(_batch_axes(mesh)), None, _maybe(heads), None)


def ulysses_attention(inner: Optional[Callable] = None,
                      mesh: Optional[Mesh] = None) -> Callable:
    """GSPMD Ulysses: re-shard seq→heads around ``inner`` attention."""
    from deepspeed_tpu.models.transformer import dot_product_attention

    inner = inner or dot_product_attention

    def attn(q: jax.Array, k: jax.Array, v: jax.Array, causal: bool = True,
             segment_mask=None) -> jax.Array:
        m = mesh or get_mesh_manager().mesh
        if m.shape.get(SEQ_AXIS, 1) <= 1:
            return inner(q, k, v, causal=causal, segment_mask=segment_mask)
        inside = NamedSharding(m, head_sharded_spec(m))
        outside = NamedSharding(m, seq_sharded_spec(m))
        q, k, v = (lax.with_sharding_constraint(x, inside) for x in (q, k, v))
        o = inner(q, k, v, causal=causal, segment_mask=segment_mask)
        return lax.with_sharding_constraint(o, outside)

    return attn


def _a2a_scatter_heads(x: jax.Array, axis_name: str) -> jax.Array:
    """[B, S/sp, N, D] → [B, S, N/sp, D] (reference single_all_to_all :241)."""
    return lax.all_to_all(x, axis_name, split_axis=2, concat_axis=1, tiled=True)


def _a2a_gather_seq(x: jax.Array, axis_name: str) -> jax.Array:
    """[B, S, N/sp, D] → [B, S/sp, N, D]."""
    return lax.all_to_all(x, axis_name, split_axis=1, concat_axis=2, tiled=True)


def ulysses_attention_shard_map(inner: Optional[Callable] = None,
                                mesh: Optional[Mesh] = None,
                                axis_name: str = SEQ_AXIS) -> Callable:
    """Explicit all-to-all Ulysses inside shard_map (``_SeqAllToAll`` parity)."""
    from deepspeed_tpu.models.transformer import dot_product_attention

    inner = inner or dot_product_attention

    def attn(q: jax.Array, k: jax.Array, v: jax.Array, causal: bool = True,
             segment_mask=None) -> jax.Array:
        if segment_mask is not None:
            raise NotImplementedError("segment_mask not supported in shard_map ulysses")
        m = mesh or get_mesh_manager().mesh
        sp = m.shape.get(axis_name, 1)
        if sp <= 1:
            return inner(q, k, v, causal=causal)
        if q.shape[2] % sp != 0:
            raise ValueError(f"num_heads {q.shape[2]} not divisible by sp={sp}")

        def local(qs, ks, vs):
            # uneven KV heads (GQA with kv_heads < sp): replicate to sp heads
            kv = ks.shape[2]
            if kv % sp != 0:
                rep = -(-sp // kv)  # ceil
                ks_, vs_ = (jnp.repeat(t, rep, axis=2) for t in (ks, vs))
            else:
                ks_, vs_ = ks, vs
            qg = _a2a_scatter_heads(qs, axis_name)
            kg = _a2a_scatter_heads(ks_, axis_name)
            vg = _a2a_scatter_heads(vs_, axis_name)
            og = inner(qg, kg, vg, causal=causal)
            return _a2a_gather_seq(og, axis_name)

        spec = seq_sharded_spec(m)
        return shard_map(local, mesh=m, in_specs=(spec, spec, spec),
                         out_specs=spec, check_vma=False)(q, k, v)

    return attn
