"""Ring attention — blockwise causal attention with KV rotation over ICI.

Capability: the reference has NO ring attention (SURVEY.md §2.3 "CP / ring
attention: NOT PRESENT"); its long-context answer is Ulysses + FPDT chunking
(``sequence/fpdt_layer.py:545``). On TPU a ring over the 'seq' mesh axis is the
idiomatic context-parallel kernel: each device keeps its Q shard resident and
rotates K/V shards around the ICI ring with ``lax.ppermute``, accumulating a
numerically-stable online softmax (the Blockwise/RingAttention recipe, PAPERS.md).
Comm per step is one neighbor hop — bandwidth-optimal on the torus and fully
overlappable with the block matmuls by XLA's latency-hiding scheduler.

Causality is handled per (q-shard, kv-shard) pair: kv shards strictly in the
future are skipped-by-masking, the diagonal shard gets the triangular mask, past
shards attend densely. Output is bitwise-comparable (up to fp tolerance) with
full attention.
"""
from __future__ import annotations

import math
from functools import partial
from typing import Callable, Optional

import jax
import jax.numpy as jnp
from jax import lax, shard_map
from jax.sharding import Mesh, PartitionSpec as P

from deepspeed_tpu.comm.mesh import SEQ_AXIS, get_mesh_manager
from deepspeed_tpu.sequence.ulysses import seq_sharded_spec

_NEG = -1e30


def _ring_local(q: jax.Array, k: jax.Array, v: jax.Array, *, causal: bool,
                axis_name: str, sp: int) -> jax.Array:
    """Per-device ring loop. q/k/v: [B, S/sp, N|K, D] local shards."""
    B, S, N, D = q.shape
    K = k.shape[2]
    if K != N:  # GQA: replicate KV heads locally (cheap; K/V stay blockwise)
        k = jnp.repeat(k, N // K, axis=2)
        v = jnp.repeat(v, N // K, axis=2)
    idx = lax.axis_index(axis_name)
    scale = 1.0 / math.sqrt(D)
    qf = q.astype(jnp.float32)

    perm = [(j, (j + 1) % sp) for j in range(sp)]
    q_pos = idx * S + jnp.arange(S)

    def body(i, carry):
        o, m, l, kc, vc = carry
        src = (idx - i) % sp  # which global shard kc/vc currently holds
        scores = jnp.einsum("bsnd,btnd->bnst", qf, kc.astype(jnp.float32)) * scale
        if causal:
            k_pos = src * S + jnp.arange(S)
            mask = q_pos[:, None] >= k_pos[None, :]
            scores = jnp.where(mask[None, None], scores, _NEG)
        m_new = jnp.maximum(m, jnp.max(scores, axis=-1))
        p = jnp.exp(scores - m_new[..., None])          # [B,N,Sq,Sk]
        alpha = jnp.exp(m - m_new)                      # [B,N,Sq]
        l_new = l * alpha + jnp.sum(p, axis=-1)
        o_new = o * alpha.transpose(0, 2, 1)[..., None] + jnp.einsum(
            "bnst,btnd->bsnd", p, vc.astype(jnp.float32))
        kc = lax.ppermute(kc, axis_name, perm)
        vc = lax.ppermute(vc, axis_name, perm)
        return o_new, m_new, l_new, kc, vc

    o0 = jnp.zeros((B, S, N, D), jnp.float32)
    m0 = jnp.full((B, N, S), _NEG, jnp.float32)
    l0 = jnp.zeros((B, N, S), jnp.float32)
    o, m, l, _, _ = lax.fori_loop(0, sp, body, (o0, m0, l0, k, v))
    o = o / jnp.maximum(l, 1e-30).transpose(0, 2, 1)[..., None]
    return o.astype(q.dtype)


def ring_attention(mesh: Optional[Mesh] = None,
                   axis_name: str = SEQ_AXIS) -> Callable:
    """Attention fn (drop-in for the model zoo) running a KV ring over 'seq'."""

    def attn(q: jax.Array, k: jax.Array, v: jax.Array, causal: bool = True,
             segment_mask=None) -> jax.Array:
        if segment_mask is not None:
            raise NotImplementedError("segment_mask not supported in ring attention")
        m = mesh or get_mesh_manager().mesh
        sp = m.shape.get(axis_name, 1)
        if sp <= 1:
            from deepspeed_tpu.models.transformer import dot_product_attention

            return dot_product_attention(q, k, v, causal=causal)
        spec = seq_sharded_spec(m)
        fn = shard_map(
            partial(_ring_local, causal=causal, axis_name=axis_name, sp=sp),
            mesh=m, in_specs=(spec, spec, spec), out_specs=spec, check_vma=False)
        return fn(q, k, v)

    return attn
