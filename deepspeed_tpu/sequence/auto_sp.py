"""AutoSP: automatic sequence-parallel planning and spec rewriting.

Parity: reference ``sequence/auto_sp.py`` + ``autosp_detector.py`` /
``autosp_fusion.py`` and the DeepCompile pass ``compile/passes/sp_compile.py``
(engine hook ``compile_autosp`` ``engine.py:1160``): a compiler pass that
detects attention subgraphs in the fx graph and inserts sequence-dim
partitioning + the Ulysses all-to-alls automatically.

TPU translation: there is no fx graph to rewrite — the model is declarative
(TransformerConfig + pluggable attention), so AutoSP is a **planning pass
over the spec**: given the live mesh and the model's shape, it decides

* whether SP applies (mesh 'seq' axis > 1),
* which mechanism fits — Ulysses head-scatter (heads % sp == 0: cheapest,
  all-to-all keeps full-attention exactness) vs ring/blockwise attention
  (head-count indivisible or very long sequences: KV rotates over `ppermute`),
* whether to tile the logits/loss computation (long seq → ALST
  TiledFusedLogitsLoss analog),

and returns a rewritten ModelSpec plus a human-readable plan. The engine
applies it when ``sequence_parallel.auto`` is set; it is also a library
entry point for direct use.
"""
from __future__ import annotations

import dataclasses
from typing import Any, Optional

from deepspeed_tpu.comm.mesh import SEQ_AXIS, get_mesh_manager
from deepspeed_tpu.utils.logging import log_dist

# sequences at or beyond this many tokens get tiled loss by default
TILED_LOSS_SEQ_THRESHOLD = 16_384


@dataclasses.dataclass(frozen=True)
class SPPlan:
    enabled: bool
    sp_size: int = 1
    mechanism: str = "none"     # none | ulysses | ring
    loss_tiles: int = 0
    reason: str = ""

    def describe(self) -> str:
        if not self.enabled:
            return f"AutoSP: disabled ({self.reason})"
        return (f"AutoSP: {self.mechanism} over seq={self.sp_size}"
                + (f", loss tiled x{self.loss_tiles}" if self.loss_tiles > 1
                   else "") + f" ({self.reason})")


def plan_sp(num_heads: int, seq_len: Optional[int] = None,
            sp_size: Optional[int] = None) -> SPPlan:
    """Decide the SP mechanism (the detector analog)."""
    if sp_size is None:
        try:
            sp_size = get_mesh_manager().axis_size(SEQ_AXIS)
        except Exception:
            sp_size = 1
    if sp_size <= 1:
        return SPPlan(False, 1, "none", 0, "mesh has no 'seq' axis > 1")
    tiles = 0
    if seq_len and seq_len >= TILED_LOSS_SEQ_THRESHOLD:
        tiles = max(2, seq_len // (TILED_LOSS_SEQ_THRESHOLD // 2))
    if num_heads % sp_size == 0:
        return SPPlan(True, sp_size, "ulysses", tiles,
                      f"heads {num_heads} divisible by sp {sp_size}")
    return SPPlan(True, sp_size, "ring", tiles,
                  f"heads {num_heads} not divisible by sp {sp_size}; "
                  "KV ring over ppermute")


def apply_sp_plan(spec, plan: SPPlan):
    """Rewrite a causal-LM ModelSpec according to the plan (the fusion-pass
    analog: swaps the attention callable, retiles the loss)."""
    if not plan.enabled:
        return spec
    from deepspeed_tpu.models.api import causal_lm_spec

    cfg = getattr(spec, "config", None)
    if cfg is None:
        raise ValueError("apply_sp_plan needs a spec built by causal_lm_spec "
                         "(carries its TransformerConfig)")
    attention = "ulysses" if plan.mechanism == "ulysses" else "ring"
    new = causal_lm_spec(cfg, attention=attention,
                         loss_tiles=plan.loss_tiles)
    return dataclasses.replace(new, name=spec.name + f"+autosp:{plan.mechanism}")


def auto_sp(spec, seq_len: Optional[int] = None, sp_size: Optional[int] = None):
    """One-call AutoSP: plan from the live mesh + rewrite. Returns
    (new_spec, plan)."""
    cfg = getattr(spec, "config", None)
    heads = cfg.num_heads if cfg is not None else 0
    plan = plan_sp(heads, seq_len or (cfg.max_seq_len if cfg else None),
                   sp_size)
    log_dist(plan.describe())
    if not plan.enabled:
        return spec, plan
    return apply_sp_plan(spec, plan), plan
