"""AutoSP: automatic sequence-parallel detection, planning and spec rewriting.

Parity: reference ``sequence/auto_sp.py`` + ``autosp_detector.py`` (attention
-site detection with an architecture registry, incl. multimodal ViT+LLM
models) + ``autosp_fusion.py`` (modality-fusion adapters) and the DeepCompile
pass ``compile/passes/sp_compile.py`` (engine hook ``compile_autosp``
``engine.py:1160``).

TPU translation: there is no fx graph to rewrite — the model is declarative
(TransformerConfig + pluggable attention), so AutoSP is a planning pass:

* **detection** (:func:`detect_sp_info`): an architecture registry maps zoo
  configs and HF configs (model_type) to their attention-site shape — heads,
  KV heads, head dim, max sequence, causal vs bidirectional. Multimodal
  archs (LLaVA-style) plan over the LLM trunk (``text_config``) with the
  vision tower flagged — the reference's fusion adapters
  (``autosp_fusion.py:78``) splice visual embeds into the sharded text
  sequence; here the trunk is the shardable surface.
* **mechanism choice** (:func:`plan_sp`): feasibility (Ulysses needs
  heads % sp == 0; ring needs the sequence divisible) then an analytic
  per-layer comm-volume comparison — Ulysses moves q,k,v,o through
  all-to-alls (volume ∝ (2·H_q + 2·H_kv)·S/sp·D), the KV ring rotates k,v
  through sp-1 ppermute hops (volume ∝ 2·H_kv·S/sp·D·(sp-1)); MQA/GQA with
  few KV heads and large sp favors the ring.
* **loss tiling**: long sequences get the ALST TiledFusedLogitsLoss analog.
* **fusion** (:func:`apply_sp_plan`): rewrites the ModelSpec — swaps the
  attention callable, retiles the loss.

Config integration: ``{"sequence_parallel": {"auto": true}}`` makes the
engine run this pass at initialize (the reference's ``compile_autosp``).
"""
from __future__ import annotations

import dataclasses
from typing import Any, Optional

from deepspeed_tpu.comm.mesh import SEQ_AXIS, maybe_mesh
from deepspeed_tpu.utils.logging import log_dist

# sequences at or beyond this many tokens get tiled loss by default
TILED_LOSS_SEQ_THRESHOLD = 16_384

# HF model_types whose text trunk follows the Llama attribute schema
# (reference _LLM_ATTN_CLASSNAMES, autosp_detector.py:45 — class-name
# detection becomes model_type detection in a functional world)
_LLM_SCHEMA_TYPES = {
    "llama", "mistral", "mixtral", "qwen2", "qwen2_moe", "qwen3", "qwen3_moe",
    "gemma", "phi", "phi3", "falcon", "gpt_neox", "internlm2", "mpt",
}

# multimodal wrappers: plan over .text_config, flag the vision tower
# (reference _VIT_ATTN_CLASSNAMES + fusion adapters, autosp_fusion.py)
_MULTIMODAL_TYPES = {
    "llava", "llava_next", "qwen2_vl", "internvl_chat", "idefics2",
    "paligemma",
}


@dataclasses.dataclass(frozen=True)
class SPSiteInfo:
    """Detected attention-site shape (reference ``SPModelInfo``,
    ``autosp_detector.py:73``)."""
    num_heads: int
    kv_heads: int
    head_dim: int
    seq_len: Optional[int] = None
    causal: bool = True
    arch: str = "unknown"
    vision_tower: bool = False   # multimodal: vision encoder present,
    #                              planned over the LLM trunk only


@dataclasses.dataclass(frozen=True)
class SPPlan:
    enabled: bool
    sp_size: int = 1
    mechanism: str = "none"     # none | ulysses | ring
    loss_tiles: int = 0
    reason: str = ""

    def describe(self) -> str:
        if not self.enabled:
            return f"AutoSP: disabled ({self.reason})"
        return (f"AutoSP: {self.mechanism} over seq={self.sp_size}"
                + (f", loss tiled x{self.loss_tiles}" if self.loss_tiles > 1
                   else "") + f" ({self.reason})")


def detect_sp_info(model_or_config: Any) -> SPSiteInfo:
    """Zoo TransformerConfig / ModelSpec / HF config → :class:`SPSiteInfo`.

    Raises ValueError for shapes it cannot read (the reference detector
    returns an empty SPModelInfo; an explicit error is more useful here).
    """
    cfg = getattr(model_or_config, "config", model_or_config)
    vision = False
    # multimodal: descend into the text trunk
    mt = getattr(cfg, "model_type", None)
    if mt in _MULTIMODAL_TYPES:
        text = getattr(cfg, "text_config", None)
        if text is None:
            raise ValueError(
                f"multimodal config {mt!r} has no text_config to plan over")
        cfg, vision = text, True
        mt = getattr(cfg, "model_type", mt)

    # zoo TransformerConfig
    if hasattr(cfg, "num_heads") and hasattr(cfg, "kv_heads"):
        return SPSiteInfo(
            num_heads=cfg.num_heads, kv_heads=cfg.kv_heads,
            head_dim=cfg.head_dim, seq_len=cfg.max_seq_len,
            causal=getattr(cfg, "causal", True), arch="zoo",
            vision_tower=vision)

    # HF llama-schema config
    heads = getattr(cfg, "num_attention_heads", None)
    if heads:
        hidden = getattr(cfg, "hidden_size", 0)
        kv = getattr(cfg, "num_key_value_heads", None) or heads
        head_dim = getattr(cfg, "head_dim", None) or (
            hidden // heads if hidden else 0)
        arch = mt if mt in _LLM_SCHEMA_TYPES else (mt or "hf")
        return SPSiteInfo(
            num_heads=int(heads), kv_heads=int(kv), head_dim=int(head_dim),
            seq_len=getattr(cfg, "max_position_embeddings", None),
            causal=not getattr(cfg, "is_encoder", False), arch=arch,
            vision_tower=vision)
    raise ValueError(
        f"cannot detect attention shape from {type(cfg).__name__}")


def _comm_cost(mechanism: str, info: SPSiteInfo, sp: int) -> float:
    """Per-device, per-layer attention comm volume (elements) under SP=sp.

    Ulysses (``sequence/ulysses.py``): 2 all-to-alls in (q,k,v) + 1 out —
    each moves that tensor's local shard once: (2·H_q + 2·H_kv)·(S/sp)·D
    scaled by the (sp-1)/sp non-local fraction. KV ring
    (``sequence/ring.py``): sp-1 ppermute hops each carrying the local
    K and V blocks: 2·H_kv·(S/sp)·D·(sp-1).
    """
    S = info.seq_len or 1
    seq_shard = S / sp
    D = info.head_dim
    if mechanism == "ulysses":
        # kv replicated up to sp when kv_heads < sp (ulysses.py:116)
        kv = max(info.kv_heads, sp)
        return (2 * info.num_heads + 2 * kv) * seq_shard * D * (sp - 1) / sp
    return 2 * info.kv_heads * seq_shard * D * (sp - 1)


def plan_sp(num_heads: Optional[int] = None, seq_len: Optional[int] = None,
            sp_size: Optional[int] = None,
            info: Optional[SPSiteInfo] = None) -> SPPlan:
    """Decide mechanism by feasibility then analytic comm cost.

    Callable either with a detected ``info`` or bare ``num_heads``/``seq_len``
    (back-compat; kv_heads then assumed == num_heads)."""
    if info is None:
        info = SPSiteInfo(num_heads=num_heads or 0, kv_heads=num_heads or 0,
                          head_dim=64, seq_len=seq_len)
    if sp_size is None:
        mesh = maybe_mesh()
        sp_size = mesh.shape.get(SEQ_AXIS, 1) if mesh is not None else 1
    if sp_size <= 1:
        return SPPlan(False, 1, "none", 0, "mesh has no 'seq' axis > 1")
    if info.num_heads <= 0:
        return SPPlan(False, sp_size, "none", 0, "no attention sites detected")

    seq_len = seq_len or info.seq_len
    tiles = 0
    if seq_len and seq_len >= TILED_LOSS_SEQ_THRESHOLD:
        tiles = max(2, seq_len // (TILED_LOSS_SEQ_THRESHOLD // 2))

    # both mechanisms shard the sequence dim (ulysses re-shards it around the
    # all-to-all), so seq divisibility gates everything when seq is known
    seq_ok = seq_len is None or seq_len % sp_size == 0
    feasible = []
    if seq_ok and info.num_heads % sp_size == 0:
        feasible.append("ulysses")
    if seq_ok:
        feasible.append("ring")
    if not feasible:
        return SPPlan(False, sp_size, "none", 0,
                      f"neither heads {info.num_heads} nor seq {seq_len} "
                      f"divisible by sp {sp_size}")

    costs = {m: _comm_cost(m, info, sp_size) for m in feasible}
    best = min(feasible, key=lambda m: costs[m])  # ties → ulysses (listed first)
    why = (f"heads {info.num_heads}/kv {info.kv_heads} over sp {sp_size}; "
           + ", ".join(f"{m} comm {costs[m]:.3g}" for m in feasible))
    if info.vision_tower:
        why += ("; multimodal: LLM trunk sharded, vision tower replicated "
                "(fusion adapters not implemented)")
    return SPPlan(True, sp_size, best, tiles, why)


def apply_sp_plan(spec, plan: SPPlan):
    """Rewrite a ModelSpec according to the plan (the fusion-pass analog:
    swaps the attention callable, retiles the loss) through the spec's own
    ``builder`` — customizations (LoRA adapters, imported weights, trainable
    masks, pipeline schedule) survive the rewrite."""
    if not plan.enabled:
        return spec
    builder = getattr(spec, "builder", None)
    if builder is None:
        raise ValueError(
            "apply_sp_plan needs a rebuildable spec (ModelSpec.builder); "
            "specs from causal_lm_spec/spec_from_hf/lora_causal_lm_spec "
            "carry one")
    attention = "ulysses" if plan.mechanism == "ulysses" else "ring"
    new = builder(attention=attention, loss_tiles=plan.loss_tiles)
    return dataclasses.replace(new, name=spec.name + f"+autosp:{plan.mechanism}")


def auto_sp(spec, seq_len: Optional[int] = None, sp_size: Optional[int] = None):
    """One-call AutoSP: detect + plan from the live mesh + rewrite. Returns
    (new_spec, plan). Specs whose shape can't be read or that can't rebuild
    themselves get a DISABLED plan (and the spec back unchanged) rather than
    a crash — the engine hook must be safe on any spec."""
    try:
        info = detect_sp_info(spec)
    except ValueError as e:
        plan = SPPlan(False, 1, "none", 0, f"detection failed: {e}")
        log_dist(plan.describe())
        return spec, plan
    plan = plan_sp(info.num_heads, seq_len or info.seq_len, sp_size, info=info)
    if plan.enabled and getattr(spec, "builder", None) is None:
        plan = SPPlan(False, plan.sp_size, "none", 0,
                      "spec has no builder (cannot be rewritten); construct "
                      "it with causal_lm_spec or set ModelSpec.builder")
    log_dist(plan.describe())
    if not plan.enabled:
        return spec, plan
    return apply_sp_plan(spec, plan), plan
