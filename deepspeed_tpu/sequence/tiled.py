"""Tiled sequence compute — ALST's memory-capping tricks, the XLA way.

Parity: reference ``runtime/sequence_parallel/ulysses_sp.py`` (``TiledMLP``
:943, ``TiledFusedLogitsLoss`` :1065, ``sequence_tiled_compute`` :720) — for
arbitrary-length training the sequence dim is processed in tiles so that
position-wise layers (MLP, logits+loss) never materialize the full [B, S, ...]
activation. Here each helper is a ``lax.scan`` over sequence tiles with
``jax.checkpoint`` on the tile body — the backward recomputes one tile at a
time, giving the same peak-memory cap as the reference's autograd-function
shards, but fused into the surrounding XLA program.
"""
from __future__ import annotations

from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp
from jax import lax

PyTree = Any


def _split_tiles(x: jax.Array, num_tiles: int, axis: int) -> jax.Array:
    S = x.shape[axis]
    if S % num_tiles != 0:
        raise ValueError(f"seq len {S} not divisible by num_tiles {num_tiles}")
    tile = S // num_tiles
    x = jnp.moveaxis(x, axis, 0)
    return x.reshape((num_tiles, tile) + x.shape[1:])


def _merge_tiles(x: jax.Array, axis: int) -> jax.Array:
    x = x.reshape((x.shape[0] * x.shape[1],) + x.shape[2:])
    return jnp.moveaxis(x, 0, axis)


def sequence_tiled_compute(fn: Callable[[jax.Array], jax.Array], x: jax.Array,
                           num_tiles: int, axis: int = 1,
                           remat: bool = True) -> jax.Array:
    """Apply a position-wise ``fn`` over sequence tiles (TiledMLP analog).

    ``fn`` must be position-wise along ``axis`` (MLP, norm, elementwise...)."""
    if num_tiles <= 1:
        return fn(x)
    tiles = _split_tiles(x, num_tiles, axis)  # [T, tile, ...] (axis moved to front)

    def body(_, t):
        # t: [tile, ...]; restore the tile's dims to fn's expected layout
        return None, jnp.moveaxis(fn(jnp.moveaxis(t, 0, axis)), axis, 0)

    if remat:
        body = jax.checkpoint(body)
    _, out = lax.scan(body, None, tiles)      # [T, tile, ...]
    return _merge_tiles(out, axis)


def tiled_lm_loss(hidden: jax.Array, head: jax.Array, tokens: jax.Array,
                  loss_mask: Optional[jax.Array] = None,
                  num_tiles: int = 8, remat: bool = True) -> jax.Array:
    """Next-token CE without materializing [B, S, vocab] logits.

    Parity: ``TiledFusedLogitsLoss`` (``ulysses_sp.py:1065``). hidden: [B,S,H]
    (pre-head final activations), head: [H,V]. Scans sequence tiles, computing
    per-tile logits + log-softmax; backward rematerializes one tile at a time.
    """
    B, S, H = hidden.shape
    # shift: predict token t+1 from position t
    hid = hidden[:, :-1]
    tgt = tokens[:, 1:]
    mask = None if loss_mask is None else loss_mask[:, 1:].astype(jnp.float32)
    Sm = S - 1
    pad = (-Sm) % num_tiles
    if pad:
        hid = jnp.pad(hid, ((0, 0), (0, pad), (0, 0)))
        tgt = jnp.pad(tgt, ((0, 0), (0, pad)))
        mask = jnp.pad(mask if mask is not None else jnp.ones((B, Sm), jnp.float32),
                       ((0, 0), (0, pad)))
    elif mask is None:
        mask = jnp.ones((B, Sm), jnp.float32)

    hid_t = _split_tiles(hid, num_tiles, 1)    # [T, tile, B, H]
    tgt_t = _split_tiles(tgt, num_tiles, 1)    # [T, tile, B]
    mask_t = _split_tiles(mask, num_tiles, 1)  # [T, tile, B]
    head_c = head.astype(hidden.dtype)

    def tile_body(carry, operand):
        from deepspeed_tpu.models.transformer import head_matmul

        h, t, mk = operand                     # [tile,B,H], [tile,B], [tile,B]
        logits = head_matmul(h, head_c)                  # [tile, B, V] fp32
        logz = jax.nn.logsumexp(logits, axis=-1)
        picked = jnp.take_along_axis(logits, t[..., None], axis=-1)[..., 0]
        nll = (logz - picked) * mk
        loss_sum, count = carry
        return (loss_sum + jnp.sum(nll), count + jnp.sum(mk)), None

    if remat:
        tile_body = jax.checkpoint(tile_body)
    (loss_sum, count), _ = lax.scan(
        tile_body, (jnp.float32(0.0), jnp.float32(0.0)), (hid_t, tgt_t, mask_t))
    return loss_sum / jnp.maximum(count, 1.0)


def chunked_attention(q: jax.Array, k: jax.Array, v: jax.Array,
                      causal: bool = True, segment_mask=None,
                      num_chunks: int = 4, remat: bool = True) -> jax.Array:
    """FPDT-style query-chunked attention (``sequence/fpdt_layer.py:545`` analog).

    Scans over Q chunks against the full K/V so peak score memory is
    [B, N, S/chunks, S]; with ``remat`` the backward recomputes per chunk. The
    reference offloads KV chunks to host; on TPU the scan + remat achieves the
    memory cap without host traffic (XLA keeps K/V resident in HBM).
    """
    import math

    if segment_mask is not None:
        raise NotImplementedError("segment_mask unsupported in chunked attention")
    B, S, N, D = q.shape
    K = k.shape[2]
    if K != N:
        k = jnp.repeat(k, N // K, axis=2)
        v = jnp.repeat(v, N // K, axis=2)
    if num_chunks <= 1 or S % num_chunks != 0:
        from deepspeed_tpu.models.transformer import dot_product_attention

        return dot_product_attention(q, k, v, causal=causal)
    C = S // num_chunks
    scale = 1.0 / math.sqrt(D)
    qc = q.reshape(B, num_chunks, C, N, D).transpose(1, 0, 2, 3, 4)
    kf = k.astype(jnp.float32)
    vf = v.astype(jnp.float32)
    kv_pos = jnp.arange(S)

    def chunk_body(carry, operand):
        i, qi = operand                        # qi: [B, C, N, D]
        scores = jnp.einsum("bcnd,btnd->bnct", qi.astype(jnp.float32), kf) * scale
        if causal:
            q_pos = i * C + jnp.arange(C)
            mask = q_pos[:, None] >= kv_pos[None, :]
            scores = jnp.where(mask[None, None], scores, -1e30)
        probs = jax.nn.softmax(scores, axis=-1)
        out = jnp.einsum("bnct,btnd->bcnd", probs, vf)
        return carry, out.astype(q.dtype)

    if remat:
        chunk_body = jax.checkpoint(chunk_body)
    _, chunks = lax.scan(chunk_body, None, (jnp.arange(num_chunks), qc))
    return chunks.transpose(1, 0, 2, 3, 4).reshape(B, S, N, D)


def _memory_constraint(x: jax.Array, kind: str) -> jax.Array:
    """Move an intermediate to a memory kind ('pinned_host'/'device', TPU
    memories API, jit-traceable device_put); identity where unsupported
    (CPU test backend)."""
    if jax.default_backend() not in ("tpu", "axon"):
        return x
    try:
        return jax.device_put(x, jax.sharding.TransferToMemoryKind(kind))
    except Exception as e:  # memories API unavailable on this backend/version
        from deepspeed_tpu.utils.logging import logger

        logger.debug(f"memories API unavailable ({type(e).__name__}: {e}); "
                     f"keeping intermediate on-device instead of {kind!r}")
        return x


def _host_constraint(x: jax.Array) -> jax.Array:
    return _memory_constraint(x, "pinned_host")


def fpdt_attention(q: jax.Array, k: jax.Array, v: jax.Array,
                   causal: bool = True, segment_mask=None,
                   num_chunks: int = 4, kv_chunks: int = 4,
                   offload_kv: bool = True, remat: bool = True) -> jax.Array:
    """FPDT attention with host-offloaded KV (``sequence/fpdt_layer.py``
    ``_FPDTGPUOffloadingAttentionImpl_`` :545 analog).

    The full K/V live in **pinned host memory**; the scan walks (q-chunk,
    kv-chunk) pairs with online-softmax accumulation, so device HBM holds one
    [B, C, N, D] KV chunk at a time — the multi-million-token recipe. XLA
    emits the host↔device DMAs from the memory-kind constraints and its
    scheduler overlaps the next chunk's fetch with the current chunk's
    matmuls (the reference's double-buffered prefetch, compiler-scheduled).
    On non-TPU backends the host constraint is an identity and the math is
    unchanged.
    """
    import math

    if segment_mask is not None:
        raise NotImplementedError("segment_mask unsupported in FPDT attention")
    B, S, N, D = q.shape
    K = k.shape[2]
    if K != N:
        k = jnp.repeat(k, N // K, axis=2)
        v = jnp.repeat(v, N // K, axis=2)
    if (num_chunks <= 1 or S % num_chunks or kv_chunks <= 1
            or S % kv_chunks):
        return chunked_attention(q, k, v, causal=causal,
                                 num_chunks=max(num_chunks, 1), remat=remat)
    C = S // num_chunks
    CK = S // kv_chunks
    scale = 1.0 / math.sqrt(D)

    kh = k.reshape(B, kv_chunks, CK, N, D).transpose(1, 0, 2, 3, 4)
    vh = v.reshape(B, kv_chunks, CK, N, D).transpose(1, 0, 2, 3, 4)
    if offload_kv:
        kh = _host_constraint(kh)
        vh = _host_constraint(vh)
    qc = q.reshape(B, num_chunks, C, N, D).transpose(1, 0, 2, 3, 4)

    def q_body(_, operand):
        qi_idx, qi = operand                      # qi: [B, C, N, D]
        q32 = qi.astype(jnp.float32)
        q_pos = qi_idx * C + jnp.arange(C)

        def kv_body(carry, kv_operand):
            acc, m, l = carry
            kj_idx, kj, vj = kv_operand           # [B, CK, N, D]
            if offload_kv:
                # pull ONE chunk into device HBM (the streamed fetch)
                kj = _memory_constraint(kj, "device")
                vj = _memory_constraint(vj, "device")
            kj = kj.astype(jnp.float32)
            vj = vj.astype(jnp.float32)
            s = jnp.einsum("bcnd,btnd->bnct", q32, kj) * scale
            if causal:
                kv_pos = kj_idx * CK + jnp.arange(CK)
                mask = q_pos[:, None] >= kv_pos[None, :]
                s = jnp.where(mask[None, None], s, -1e30)
            m_new = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))
            p = jnp.exp(s - m_new)
            alpha = jnp.exp(m - m_new)
            l_new = alpha * l + jnp.sum(p, axis=-1, keepdims=True)
            acc_new = acc * alpha + jnp.einsum("bnct,btnd->bnc d".replace(" ", ""), p, vj)
            return (acc_new, m_new, l_new), None

        init = (jnp.zeros((B, N, C, D), jnp.float32),
                jnp.full((B, N, C, 1), -1e30, jnp.float32),
                jnp.zeros((B, N, C, 1), jnp.float32))
        (acc, m, l), _ = lax.scan(
            kv_body, init, (jnp.arange(kv_chunks), kh, vh))
        out = acc / jnp.maximum(l, 1e-30)
        return None, out.transpose(0, 2, 1, 3).astype(q.dtype)  # [B, C, N, D]

    if remat:
        q_body = jax.checkpoint(q_body)
    _, chunks = lax.scan(q_body, None, (jnp.arange(num_chunks), qc))
    return chunks.transpose(1, 0, 2, 3, 4).reshape(B, S, N, D)
