"""Runtime compatibility shims for older jax builds.

The codebase targets the modern ``jax.shard_map`` API (top-level export,
``axis_names`` + ``check_vma`` kwargs). Some toolchain images pin a jax
where shard_map still lives in ``jax.experimental.shard_map`` with the
``auto`` + ``check_rep`` spelling — on those, EVERY ``from jax import
shard_map`` in the repo raised ImportError and the whole test tier failed
at collection. ``install()`` (called from the package ``__init__`` before
any submodule import) grafts an adapter into the jax namespace when the
top-level export is missing; on current jax it does nothing.
"""
from __future__ import annotations

import jax


def _shard_map_adapter(f=None, *, mesh=None, in_specs=None, out_specs=None,
                       axis_names=None, check_vma=None, check_rep=None,
                       auto=None, **ignored):
    """New-API surface mapped onto ``jax.experimental.shard_map``:

    * ``axis_names`` (manual axes subset) → ``auto`` (its complement);
    * ``check_vma`` → ``check_rep``;
    * unknown future kwargs are dropped rather than raised on.
    """
    from jax.experimental.shard_map import shard_map as _sm

    if auto is None:
        if axis_names:
            auto = frozenset(mesh.axis_names) - frozenset(axis_names)
        else:
            auto = frozenset()
    if check_rep is None:
        check_rep = bool(check_vma) if check_vma is not None else False
    kwargs = dict(mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                  check_rep=check_rep, auto=auto)
    if f is None:   # decorator-style usage
        return lambda fn: _sm(fn, **kwargs)
    return _sm(f, **kwargs)


def install() -> None:
    if not hasattr(jax, "shard_map"):
        jax.shard_map = _shard_map_adapter
    if not hasattr(jax.lax, "pcast"):
        # pcast marks values as varying over manual axes for the VMA type
        # system; pre-VMA jax has no replication tracking inside shard_map
        # (we run check_rep=False there), so the no-op is semantically exact
        jax.lax.pcast = lambda x, axes=None, *, to=None: x
    tree = getattr(jax, "tree", None)   # jax.tree itself is newer than some
    if tree is not None:                # pins — don't let the shim crash
        if not hasattr(tree, "leaves_with_path"):
            tree.leaves_with_path = jax.tree_util.tree_leaves_with_path
        if not hasattr(tree, "map_with_path"):
            tree.map_with_path = jax.tree_util.tree_map_with_path
