"""deepspeed_tpu — a TPU-native distributed training & inference framework.

A from-scratch JAX/XLA/Pallas framework with the capabilities of DeepSpeed
(reference: meefs/DeepSpeed v0.19.3; structural map in SURVEY.md). The public
surface mirrors the reference (``deepspeed/__init__.py:93 initialize``,
``:328 init_inference``, ``deepspeed.comm``), while the internals are idiomatic
SPMD over a named device mesh.
"""
from __future__ import annotations

from typing import Any, Optional, Tuple

__version__ = "0.1.0"
version = __version__

from deepspeed_tpu import compat as _compat  # noqa: E402

_compat.install()   # graft jax.shard_map on older jax builds (no-op on new)

from deepspeed_tpu import comm  # noqa: E402
from deepspeed_tpu import telemetry  # noqa: E402
from deepspeed_tpu.accelerator import get_accelerator  # noqa: E402
from deepspeed_tpu.models.api import (  # noqa: E402
    ModelSpec,
    causal_lm_spec,
    spec_from_hf,
)
from deepspeed_tpu.runtime.config import DeepSpeedTPUConfig, load_config  # noqa: E402
from deepspeed_tpu.runtime.engine import DeepSpeedTPUEngine  # noqa: E402
from deepspeed_tpu.utils.logging import logger  # noqa: E402


def initialize(
    args: Any = None,
    model: Optional[ModelSpec] = None,
    optimizer: Any = None,
    model_parameters: Any = None,
    training_data: Any = None,
    lr_scheduler: Any = None,
    distributed_port: Optional[int] = None,
    mpu: Any = None,
    dist_init_required: Optional[bool] = None,
    collate_fn: Any = None,
    config: Any = None,
    mesh_param: Any = None,
    config_params: Any = None,
    mesh_manager: Any = None,
) -> Tuple[DeepSpeedTPUEngine, Any, Any, Any]:
    """Initialize the engine (reference ``deepspeed.initialize`` signature,
    ``deepspeed/__init__.py:93``). Returns (engine, optimizer, dataloader,
    lr_scheduler) like the reference.

    ``mesh_manager`` (a ``comm.mesh.MeshManager``) pins the engine to an
    explicitly-built mesh instead of the config-derived one — the elastic
    agent's engine factory uses it to build a world-M engine on a host
    that physically has N devices (``initialize_mesh(cfg,
    devices=jax.devices()[:M])``)."""
    config = config if config is not None else config_params
    if config is None and args is not None and hasattr(args, "deepspeed_config"):
        config = args.deepspeed_config
    if model is None:
        raise ValueError("deepspeed_tpu.initialize requires a ModelSpec via `model=`")

    engine = DeepSpeedTPUEngine(
        model=model, config=config, optimizer=optimizer, lr_scheduler=lr_scheduler,
        mesh_manager=mesh_manager)

    from deepspeed_tpu.monitor.monitor import MonitorMaster

    engine.monitor = MonitorMaster(engine.config)

    # fault tolerance (config "fault_tolerance"): arm the graceful-
    # preemption SIGTERM handler and restore the newest committed
    # checkpoint before handing the engine back
    ft = engine.config.fault_tolerance
    if ft.graceful_preemption and (ft.resume_dir or ft.auto_resume):
        engine.enable_preemption_handler()
    if ft.auto_resume:
        engine.maybe_auto_resume()

    dataloader = None
    if training_data is not None:
        dataloader = engine.deepspeed_io(training_data)
    return engine, engine.optimizer, dataloader, engine.lr_scheduler


def init_distributed(dist_backend: str = "jax_ici", **kwargs) -> None:
    """Reference ``deepspeed.init_distributed`` analog."""
    comm.init_distributed(dist_backend=dist_backend, **kwargs)


def init_inference(model, params=None, config=None, **kwargs):
    """Reference ``deepspeed.init_inference`` (``deepspeed/__init__.py:328``)."""
    from deepspeed_tpu.inference.engine import init_inference as _ii

    return _ii(model, params=params, config=config, **kwargs)
