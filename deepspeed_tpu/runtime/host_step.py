"""Host-executed optimizer step with async overlap (SuperOffload / ZenFlow).

Parity: reference ``runtime/superoffload/superoffload_stage3.py``
(``SuperOffloadOptimizer_Stage3:27`` — CPU-side Adam over C2C with bucketed
grad streaming overlapping GPU compute) and the async half of ZenFlow
(``runtime/zenflow/zenflow_stage_1_and_2.py`` — CPU optimizer work hidden
behind device compute; the importance-split half lives in
``runtime/zenflow.py``).

TPU translation: JAX always has a CPU backend next to the TPU, and dispatch
is async on both — so the "asynchronous CPU optimizer" needs no threads:

* device jit computes loss + grads only (fp32-accumulated over GAS);
* grads stream device→host (``jax.device_put`` onto the CPU backend — an
  async D2H DMA);
* a CPU-jitted update applies unscale/clip/optimizer math to the fp32
  master + moments THAT LIVE ON HOST PERMANENTLY, and casts the new compute
  params to 16-bit on the host (halving the H2D return traffic — the
  reference streams fp16 params back over C2C the same way);
* the 16-bit params stream host→device.

Device HBM holds only 16-bit compute params + transient grads — the
ZeRO-Offload/SuperOffload memory model.

``overlap_step`` (ZenFlow's flag): when True, step k runs on the params of
update k-2 while the host crunches update k-1 — the host work and the D2H/
H2D streams fully overlap device compute at a documented one-step staleness
(the reference's cold-path staleness model; here the whole update is
deferred one step, where the reference keeps hot coordinates fresh — pair
with ``zenflow.enabled`` to keep the hot/cold split semantics in the host
update). When False, ordering is synchronous (update k applies before step
k+1) and only the transfers pipeline.
"""
from __future__ import annotations

from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp

from deepspeed_tpu.utils.logging import log_dist

PyTree = Any


def _cpu_device():
    try:
        return jax.local_devices(backend="cpu")[0]
    except Exception as e:  # pragma: no cover - cpu backend always exists
        raise RuntimeError(f"host_step needs the JAX CPU backend: {e}")


class HostStepRunner:
    """Owns the split train step: device grads / host update."""

    def __init__(self, engine):
        from deepspeed_tpu.runtime.config_utils import DeepSpeedConfigError

        if engine.fp16_enabled:
            raise DeepSpeedConfigError(
                "offload_optimizer.host_step does not support fp16 loss "
                "scaling; use bf16 (the reference's SuperOffload targets "
                "bf16 too)")
        if engine.mesh.shape.get("pipe", 1) > 1:
            raise DeepSpeedConfigError(
                "host_step is not supported with pipeline parallelism")
        if jax.process_count() > 1:
            raise DeepSpeedConfigError(
                "host_step is single-host for now: the update runs on this "
                "process's CPU backend and cannot address remote shards")
        self.engine = engine
        self.cpu = _cpu_device()
        # HOST-SHARDED state (reference SuperOffload is a STAGE-3 optimizer,
        # superoffload_stage3.py:27): the fp32 master + moments shard across
        # the host backend's devices — each holds 1/H of the state and the
        # update runs SPMD over the host mesh. One CPU device (production
        # TPU host) degenerates to the full-resident model; the 8-virtual-
        # device test env exercises real host sharding. Device-side 16-bit
        # params keep the engine's param_spec (stage-3 sharded on device),
        # so ZeRO stages now compose with host_step.
        self.host_mesh, self._host_shardings = self._build_host_placement()
        zcfg = engine.config.zero_optimization
        explicit = zcfg.offload_optimizer.overlap_step
        if explicit is not None:
            self.overlap = bool(explicit)   # user's word is final
        else:
            self.overlap = (zcfg.zenflow.enabled
                            and zcfg.zenflow.overlap_step)
        self._pending16: Optional[PyTree] = None
        self._grad_jit: Dict[int, Any] = {}
        self._update_jit = None
        self.device_params: Optional[PyTree] = None
        log_dist(f"host-step optimizer active (overlap={self.overlap}): "
                 "fp32 master + moments on host, 16-bit params on device")

    # ------------------------------------------------------------- state
    def _build_host_placement(self):
        """Host mesh over the CPU backend's local devices + per-leaf
        shardings: each fp32 leaf shards its largest H-divisible dim over
        the 'host' axis (replicated when none divides — tiny leaves)."""
        import numpy as np
        from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

        devs = jax.local_devices(backend="cpu")
        mesh = Mesh(np.array(devs), ("host",))
        H = len(devs)

        def spec_of(shape):
            for i in sorted(range(len(shape)), key=lambda j: -shape[j]):
                if shape[i] % H == 0 and shape[i] >= H:
                    parts = [None] * len(shape)
                    parts[i] = "host"
                    return P(*parts)
            return P()

        def shardings_like(tree):
            return jax.tree.map(
                lambda x: NamedSharding(mesh, spec_of(tuple(x.shape))), tree)

        return mesh, shardings_like

    def adopt_state(self) -> None:
        """Move master/opt of ``engine.state`` onto the host mesh (sharded)
        and (re)build the device 16-bit params. Called at init and after
        checkpoint restore."""
        eng = self.engine
        st = eng.state
        st["master"] = jax.device_put(st["master"],
                                      self._host_shardings(st["master"]))
        st["opt"] = jax.device_put(st["opt"],
                                   self._host_shardings(st["opt"]))
        from jax.sharding import NamedSharding, PartitionSpec as P

        st["step"] = jax.device_put(
            st["step"], NamedSharding(self.host_mesh, P()))
        # jnp.array (copy=True): the cast is a no-op when master is already
        # fp32 on this device (CPU tests) and the update jit DONATES master —
        # device_params must never alias it
        compute16 = jax.tree.map(
            lambda x: jnp.array(x, eng.precision), st["master"])
        self.device_params = jax.device_put(
            compute16, eng.policy.to_shardings(eng.param_spec))
        self._pending16 = None

    # ------------------------------------------------------------- jits
    def _build_grad_step(self, gas: int):
        eng = self.engine

        def grad_step(params, batch):
            zeros = jax.tree.map(
                lambda p: jnp.zeros(p.shape,
                                    self.engine._grad_accum_dtype()), params)
            return type(eng).accumulate_microbatches(
                lambda mb: jax.value_and_grad(eng.model_spec.loss_fn)(
                    params, mb),
                zeros, batch, gas)

        return jax.jit(grad_step)

    def _build_update(self):
        eng = self.engine

        def host_update(master, opt, grads, step, gas_scale, lr_mult):
            from deepspeed_tpu.runtime.loss_scaler import (
                clip_by_global_norm, global_grad_norm)

            grads = jax.tree.map(lambda g: g / gas_scale, grads)
            lr = eng._lr_at(step) * lr_mult
            if eng._trainable_mask is not None:
                # norm over trainable leaves only (mirrors the device path,
                # engine._apply_update) — frozen-base grads must not inflate
                # the clip norm
                from deepspeed_tpu.utils.tree import prune_tree

                norm = global_grad_norm(
                    prune_tree(grads, eng._trainable_mask))
            else:
                norm = global_grad_norm(grads)
            if eng.config.gradient_clipping > 0:
                grads = clip_by_global_norm(
                    grads, eng.config.gradient_clipping, norm)
            new_master, new_opt = eng.optimizer.update(grads, opt, master,
                                                       lr=lr)
            compute16 = jax.tree.map(
                lambda x: jnp.asarray(x, eng.precision), new_master)
            return new_master, new_opt, compute16, {"grad_norm": norm,
                                                    "lr": lr}

        # runs on the CPU backend: all array inputs are committed to self.cpu
        return jax.jit(host_update, donate_argnums=(0, 1))

    # ------------------------------------------------------------- step
    def _apply_pending(self) -> None:
        if self._pending16 is None:
            return
        eng = self.engine
        self.device_params = jax.device_put(
            self._pending16, eng.policy.to_shardings(eng.param_spec))
        self._pending16 = None

    def train_batch(self, batch: PyTree, gas: int
                    ) -> Tuple[jax.Array, Dict[str, jax.Array]]:
        """One global step. Returns (mean_loss, metrics). Never blocks in
        Python — ordering rides JAX's async dispatch."""
        eng = self.engine
        if gas not in self._grad_jit:
            self._grad_jit[gas] = self._build_grad_step(gas)
        if self._update_jit is None:
            self._update_jit = self._build_update()

        if not self.overlap:
            self._apply_pending()               # update k-1 before step k
        with eng.mesh:
            grads, loss = self._grad_jit[gas](self.device_params, batch)
        if self.overlap:
            # step k ran on update k-2's params while the host computed
            # update k-1; land it now (one-step staleness, full overlap)
            self._apply_pending()

        from jax.sharding import NamedSharding, PartitionSpec as P

        lr_mult = jnp.float32(1.0)
        if isinstance(batch, dict) and "lr_scale" in batch:
            lr_mult = jnp.mean(batch["lr_scale"].astype(jnp.float32))
        # async D2H stream, SCATTERED: each host shard receives only its
        # slice of the gradients
        gh = jax.device_put(grads, self._host_shardings(grads))
        st = eng.state
        rep = NamedSharding(self.host_mesh, P())
        new_master, new_opt, compute16, m = self._update_jit(
            st["master"], st["opt"], gh, st["step"],
            jnp.float32(gas), jax.device_put(lr_mult, rep))
        eng.state = {"step": st["step"] + 1, "master": new_master,
                     "opt": new_opt}
        self._pending16 = compute16
        if not self.overlap:
            self._apply_pending()
        m = dict(m)
        m["loss"] = loss
        m["overflow"] = jnp.float32(0.0)
        return loss, m
