"""LR schedules: LRRangeTest / OneCycle / WarmupLR / WarmupDecayLR / WarmupCosineLR.

Parity: reference ``runtime/lr_schedules.py:277-784``. Implemented as pure
``step → lr`` functions (jit-compatible: they accept traced step values), wrapped
in stateful classes exposing the reference's ``step()`` / ``get_last_lr()`` /
``state_dict()`` API for user code.
"""
from __future__ import annotations

import math
from typing import Any, Dict, List, Optional

import jax.numpy as jnp

VALID_SCHEDULES = ["LRRangeTest", "OneCycle", "WarmupLR", "WarmupDecayLR",
                   "WarmupCosineLR"]


class LRSchedule:
    """Base: holds base lr; subclasses implement lr_at(step) with jnp math."""

    def __init__(self, base_lr: float):
        self.base_lr = base_lr
        self.last_batch_iteration = -1

    def lr_at(self, step):
        raise NotImplementedError

    # --- torch-like stateful API (reference behavior) ---
    def step(self, last_batch_iteration: Optional[int] = None) -> None:
        if last_batch_iteration is None:
            last_batch_iteration = self.last_batch_iteration + 1
        self.last_batch_iteration = last_batch_iteration

    def get_lr(self) -> List[float]:
        return [float(self.lr_at(jnp.maximum(0, self.last_batch_iteration)))]

    def get_last_lr(self) -> List[float]:
        return self.get_lr()

    def state_dict(self) -> Dict[str, Any]:
        return {"last_batch_iteration": self.last_batch_iteration}

    def load_state_dict(self, sd: Dict[str, Any]) -> None:
        self.last_batch_iteration = sd["last_batch_iteration"]


class WarmupLR(LRSchedule):
    """Linear warmup 0→base then constant (reference :672)."""

    def __init__(self, base_lr: float, warmup_min_lr: float = 0.0,
                 warmup_max_lr: float = 0.001, warmup_num_steps: int = 1000,
                 warmup_type: str = "log", **_):
        super().__init__(warmup_max_lr)
        self.min_lr = warmup_min_lr
        self.max_lr = warmup_max_lr
        self.warmup_num_steps = max(2, warmup_num_steps)
        self.warmup_type = warmup_type

    def _warmup_frac(self, step):
        frac = jnp.clip(step.astype(jnp.float32) / self.warmup_num_steps, 0.0, 1.0)
        if self.warmup_type == "log":
            frac = jnp.log1p(frac * (math.e - 1.0))
        return frac

    def lr_at(self, step):
        step = jnp.asarray(step)
        return self.min_lr + (self.max_lr - self.min_lr) * self._warmup_frac(step)


class WarmupDecayLR(WarmupLR):
    """Warmup then linear decay to 0 at total_num_steps (reference :738)."""

    def __init__(self, base_lr: float, total_num_steps: int = 10000, **kwargs):
        super().__init__(base_lr, **kwargs)
        self.total_num_steps = max(total_num_steps, self.warmup_num_steps)

    def lr_at(self, step):
        step = jnp.asarray(step)
        warm = super().lr_at(step)
        decay = jnp.clip(
            (self.total_num_steps - step.astype(jnp.float32))
            / max(1, self.total_num_steps - self.warmup_num_steps), 0.0, 1.0)
        return jnp.where(step < self.warmup_num_steps, warm, self.max_lr * decay)


class WarmupCosineLR(LRSchedule):
    """Linear warmup then cosine decay (reference :784)."""

    def __init__(self, base_lr: float, total_num_steps: int = 10000,
                 warmup_min_ratio: float = 0.0, warmup_num_steps: int = 1000,
                 cos_min_ratio: float = 0.0001, **_):
        super().__init__(base_lr)
        self.total_num_steps = total_num_steps
        self.warmup_min_ratio = warmup_min_ratio
        self.warmup_num_steps = max(1, warmup_num_steps)
        self.cos_min_ratio = cos_min_ratio

    def lr_at(self, step):
        step = jnp.asarray(step).astype(jnp.float32)
        warm_ratio = self.warmup_min_ratio + (1 - self.warmup_min_ratio) * jnp.clip(
            step / self.warmup_num_steps, 0.0, 1.0)
        progress = jnp.clip((step - self.warmup_num_steps)
                            / max(1, self.total_num_steps - self.warmup_num_steps), 0.0, 1.0)
        cos_ratio = self.cos_min_ratio + (1 - self.cos_min_ratio) * 0.5 * (
            1 + jnp.cos(jnp.pi * progress))
        ratio = jnp.where(step < self.warmup_num_steps, warm_ratio, cos_ratio)
        return self.base_lr * ratio


class LRRangeTest(LRSchedule):
    """LR range sweep for tuning (reference :277)."""

    def __init__(self, base_lr: float, lr_range_test_min_lr: float = 1e-3,
                 lr_range_test_step_size: int = 2000,
                 lr_range_test_step_rate: float = 1.0,
                 lr_range_test_staircase: bool = False, **_):
        super().__init__(lr_range_test_min_lr)
        self.min_lr = lr_range_test_min_lr
        self.step_size = lr_range_test_step_size
        self.step_rate = lr_range_test_step_rate
        self.staircase = lr_range_test_staircase

    def lr_at(self, step):
        step = jnp.asarray(step).astype(jnp.float32)
        count = step / self.step_size
        if self.staircase:
            count = jnp.floor(count)
        return self.min_lr * (1 + self.step_rate * count)


class OneCycle(LRSchedule):
    """1-cycle policy (reference :391): lr up then down then decay."""

    def __init__(self, base_lr: float, cycle_min_lr: float = 0.0, cycle_max_lr: float = 0.001,
                 decay_lr_rate: float = 0.0, cycle_first_step_size: int = 2000,
                 cycle_second_step_size: Optional[int] = None,
                 decay_step_size: int = 0, **_):
        super().__init__(cycle_max_lr)
        self.cycle_min_lr = cycle_min_lr
        self.cycle_max_lr = cycle_max_lr
        self.decay_lr_rate = decay_lr_rate
        self.first = cycle_first_step_size
        self.second = cycle_second_step_size or cycle_first_step_size
        self.decay_step_size = max(decay_step_size, 1)

    def lr_at(self, step):
        step = jnp.asarray(step).astype(jnp.float32)
        up = self.cycle_min_lr + (self.cycle_max_lr - self.cycle_min_lr) * jnp.clip(
            step / self.first, 0.0, 1.0)
        down_progress = jnp.clip((step - self.first) / self.second, 0.0, 1.0)
        down = self.cycle_max_lr - (self.cycle_max_lr - self.cycle_min_lr) * down_progress
        end_cycle = self.first + self.second
        decay_steps = jnp.maximum(0.0, step - end_cycle) / self.decay_step_size
        decayed = self.cycle_min_lr / (1.0 + self.decay_lr_rate * decay_steps)
        lr = jnp.where(step <= self.first, up,
                       jnp.where(step <= end_cycle, down, decayed))
        return lr


_SCHEDULES = {
    "WarmupLR": WarmupLR,
    "WarmupDecayLR": WarmupDecayLR,
    "WarmupCosineLR": WarmupCosineLR,
    "LRRangeTest": LRRangeTest,
    "OneCycle": OneCycle,
}


def get_lr_schedule(name: Optional[str], params: Dict[str, Any],
                    base_lr: float) -> Optional[LRSchedule]:
    if name is None:
        return None
    if name not in _SCHEDULES:
        raise ValueError(f"unknown scheduler {name!r}; supported: {sorted(_SCHEDULES)}")
    return _SCHEDULES[name](base_lr, **params)
