"""ZenFlow: importance-split optimizer updates (hot now, cold deferred).

Parity: reference ``runtime/zenflow/zenflow_stage_1_and_2.py``
(``ZenFlowZeroOptimizer`` :47, Sequential/Parallel variants :590/:599) +
``zenflow_stage3.py``. The reference's problem: with CPU-offloaded optimizers
the CPU-side Adam step takes longer than the backward pass (>4s vs 2s ⇒ >60%
GPU idle, ``blogs/deepspeed-zenflow/README.md:94``). Its fix: update the
top-k *important* gradient coordinates on-GPU every step, and batch the
remaining (cold) coordinates into a CPU update that runs asynchronously every
``update_interval`` steps.

TPU translation: the optimizer math itself is fused into the XLA step program
(no CPU Adam to hide), so what remains valuable — and is implemented here —
is the **semantics**: selective immediate updates for important coordinates,
deferred accumulated updates for the bulk. Wins on TPU:

* the cold bulk contributes through an accumulator applied every
  ``update_interval`` steps, matching the reference's staleness model (cold
  grads land with up to K-step delay) — the convergence-relevant behavior;
* hot coordinates keep full-rate Adam updates, so loss curves track plain
  training closely at topk_ratio ≈ 1-5%.

State (checkpointed like any moments): inner optimizer state + ``cold_acc``
gradient accumulator + schedule scalars.
"""
from __future__ import annotations

import dataclasses
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp

from deepspeed_tpu.ops.optimizer import TPUOptimizer, _tmap

PyTree = Any


@dataclasses.dataclass
class ZenFlowSectionConfig:
    """Config section (reference zenflow config on the zero section)."""

    enabled: bool = False
    topk_ratio: float = 0.01        # fraction of coordinates updated hot
    update_interval: int = 4        # cold-update period (steps)
    full_warm_up_rounds: int = 0    # initial steps with full (non-split) updates
    select_strategy: str = "auto"   # accepted for parity; importance = |grad|
    overlap_step: bool = True       # accepted for parity (XLA schedules)


@dataclasses.dataclass
class ZenFlowOptimizer(TPUOptimizer):
    """Wraps any TPUOptimizer with hot/cold importance-split updates."""

    inner: Optional[TPUOptimizer] = None
    topk_ratio: float = 0.01
    update_interval: int = 4
    full_warm_up_rounds: int = 0

    def __post_init__(self):
        if self.inner is not None:
            self.lr = self.inner.lr
            self.weight_decay = self.inner.weight_decay
            self.moment_names = tuple(self.inner.moment_names) + ("cold_acc",)

    def init(self, params):
        state = self.inner.init(params)
        state["cold_acc"] = _tmap(jnp.zeros_like, params)
        return state

    def _hot_mask(self, g: jax.Array) -> jax.Array:
        """{0,1} mask of the top ``topk_ratio`` fraction by |g| (per leaf).

        The reference selects important *columns* per matrix; per-coordinate
        selection is the shape-agnostic analog and is what its 'auto'
        strategy degenerates to for 1-D tensors."""
        if g.size == 0:
            return jnp.ones_like(g)
        flat = jnp.abs(g.reshape(-1))
        k = max(1, int(flat.shape[0] * self.topk_ratio))
        threshold = jax.lax.top_k(flat, k)[0][-1]
        return (jnp.abs(g) >= threshold).astype(g.dtype)

    def update(self, grads, state, params, lr=None):
        step = state["step"] + 1  # inner increments too; use for scheduling
        warm = step <= self.full_warm_up_rounds
        boundary = (step % self.update_interval) == 0

        def split(g, acc):
            hot = self._hot_mask(g)
            g32 = g.astype(jnp.float32)
            hot_g = g32 * hot
            new_acc = acc + g32 * (1.0 - hot)
            # at the boundary the cold accumulator (mean over the window)
            # joins the applied gradient and resets
            applied = jnp.where(
                warm, g32,
                jnp.where(boundary, hot_g + new_acc / self.update_interval,
                          hot_g))
            new_acc = jnp.where(jnp.logical_or(warm, boundary),
                                jnp.zeros_like(new_acc), new_acc)
            return applied, new_acc

        out = _tmap(split, grads, state["cold_acc"])
        applied = _tmap(lambda o: o[0], out,
                        is_leaf=lambda x: isinstance(x, tuple))
        new_acc = _tmap(lambda o: o[1], out,
                        is_leaf=lambda x: isinstance(x, tuple))

        inner_state = {k: v for k, v in state.items() if k != "cold_acc"}
        new_params, new_inner = self.inner.update(applied, inner_state, params,
                                                 lr=lr)
        new_inner["cold_acc"] = new_acc
        return new_params, new_inner


def maybe_wrap_zenflow(optimizer: TPUOptimizer,
                       zcfg: Optional[ZenFlowSectionConfig]) -> TPUOptimizer:
    if zcfg is None or not zcfg.enabled:
        return optimizer
    return ZenFlowOptimizer(
        inner=optimizer, topk_ratio=zcfg.topk_ratio,
        update_interval=zcfg.update_interval,
        full_warm_up_rounds=zcfg.full_warm_up_rounds)
