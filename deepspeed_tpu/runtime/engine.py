"""DeepSpeedTPUEngine — the core training runtime.

Parity: reference ``runtime/engine.py:235`` (``DeepSpeedEngine``: ``forward``
:2675, ``backward`` :3066, ``step`` :3241, ``train_batch`` via pipe engine,
``save_checkpoint`` :4557, ``load_checkpoint`` :4079) and its ZeRO optimizers
(``stage_1_and_2.py``, ``stage3.py``).

TPU-native architecture: instead of an ``nn.Module`` wrapper with per-param
hooks, the engine owns a **sharded train state** (fp32 master params + optimizer
moments + loss-scale state) and a **single jitted train step** that fuses the
reference's forward → backward → allreduce/reduce-scatter → optimizer-step →
allgather flow into one XLA program over the device mesh:

* gradient accumulation = ``lax.scan`` over the micro-batch axis *inside* jit
  (the IPG-bucket flow, ``stage_1_and_2.py:1125``, becomes a loop-carried sum);
* ZeRO stages = sharding constraints (see ``parallel/partitioning.py``) — XLA
  emits the reduce-scatter/all-gather schedule the reference hand-manages, with
  overlap from the latency-hiding scheduler;
* mixed precision = cast-on-use from fp32 master (``bf16_optimizer.py:37`` /
  ``fp16/fused_optimizer.py:33`` semantics) with dynamic loss scaling as a
  ``lax.cond`` skip-update branch.

The eager ``forward()/backward()/step()`` triple is preserved for API parity:
``forward`` computes loss+grads in one jitted call, ``backward`` accumulates into
a sharded buffer, ``step`` applies the (jitted) update at the GAS boundary.
"""
from __future__ import annotations

import json
import os
import threading
import time
import weakref
from functools import partial
from typing import Any, Dict, Iterator, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from deepspeed_tpu import comm as dist
from deepspeed_tpu.analysis.racelint.sanitizer import make_lock
from deepspeed_tpu.comm.mesh import MeshManager, get_mesh_manager
from deepspeed_tpu.models.api import ModelSpec
from deepspeed_tpu.ops.optimizer import TPUOptimizer, get_optimizer
from deepspeed_tpu.parallel.partitioning import ShardingPolicy
from deepspeed_tpu.runtime.config import DeepSpeedTPUConfig, load_config
from deepspeed_tpu.runtime.config_utils import DeepSpeedConfigError
from deepspeed_tpu.runtime.dataloader import (
    DeepSpeedTPUDataLoader,
    RepeatingLoader,
    shard_host_batch,
)
from deepspeed_tpu.runtime.loss_scaler import (
    DynamicLossScaler,
    clip_by_global_norm,
    global_grad_norm,
)
from deepspeed_tpu.runtime.lr_schedules import LRSchedule, get_lr_schedule
from deepspeed_tpu.testing.chaos import chaos_point
from deepspeed_tpu.utils.logging import log_dist, logger
from deepspeed_tpu.utils.timer import (
    BACKWARD_GLOBAL_TIMER,
    FORWARD_GLOBAL_TIMER,
    STEP_GLOBAL_TIMER,
    SynchronizedWallClockTimer,
    ThroughputTimer,
    TRAIN_BATCH_TIMER,
)

PyTree = Any


class DeepSpeedTPUEngine:
    def __init__(
        self,
        model: ModelSpec,
        config: Any,
        optimizer: Optional[TPUOptimizer] = None,
        lr_scheduler: Optional[LRSchedule] = None,
        mesh_manager: Optional[MeshManager] = None,
        seed: Optional[int] = None,
    ):
        self.model_spec = model
        self.config: DeepSpeedTPUConfig = load_config(config)
        # MiCS / ZeRO++ hpZ: replica-group sharding resolves onto the 'zshard'
        # mesh axis (shard within the subgroup, replicate across 'data').
        # zero_hpz_partition_size is VALIDATED like the bucket keys (PR 8):
        # type/spelling normalization lives in ZeroConfig.validate(); the
        # mesh-dependent checks — the subgroup must divide the device world
        # and must not contradict an explicit mesh.zshard — are here, and
        # they RAISE: a mis-sized subgroup silently degrading to exact
        # full-world collectives is the config no-op class of bug
        zcfg = self.config.zero_optimization
        # cached autotune plan ("autotuning" config section): applied HERE,
        # before ANY knob is consumed — zero_hpz_partition_size feeds the
        # subgroup resolution just below, the bucket/overlap keys feed
        # _setup_overlap_scheduler — so a loaded plan covers all of them
        self._load_autotune_plan(zcfg)
        subgroup = zcfg.mics_shard_size or (
            zcfg.zero_hpz_partition_size if zcfg.zero_hpz_partition_size > 1 else 0)
        if subgroup:
            key = ("mics_shard_size" if zcfg.mics_shard_size
                   else "zero_hpz_partition_size")
            if self.config.mesh.zshard not in (1, subgroup):
                raise DeepSpeedConfigError(
                    f"zero_optimization.{key}={subgroup} conflicts with "
                    f"mesh.zshard={self.config.mesh.zshard} — the subgroup IS "
                    "the 'zshard' axis; set one of them, or make them agree")
            n_dev = jax.device_count()
            if n_dev % subgroup != 0:
                raise DeepSpeedConfigError(
                    f"zero_optimization.{key}={subgroup} must divide the "
                    f"device world ({n_dev} devices) — a non-dividing hpZ "
                    "subgroup cannot form replica groups, and falling back "
                    "to exact full-world collectives would silently drop "
                    "the secondary partition")
            self.config.mesh.zshard = subgroup
            try:
                # dividing the raw device count is necessary, not
                # sufficient: other fixed mesh axes (tensor/pipe/seq)
                # consume devices too — resolve the full mesh NOW so a
                # non-fitting subgroup names the config key instead of
                # failing later with a generic mesh-shape error
                self.config.mesh.to_mesh_config().resolve(n_dev)
            except ValueError as e:
                raise DeepSpeedConfigError(
                    f"zero_optimization.{key}={subgroup} does not fit the "
                    f"mesh: {e}") from None
        if not dist.is_initialized():
            dist.init_distributed(mesh_config=self.config.mesh.to_mesh_config())
        if mesh_manager is None:
            import jax as _jax

            from deepspeed_tpu.comm.mesh import initialize_mesh

            mesh_manager = get_mesh_manager()
            want = self.config.mesh.to_mesh_config().resolve(_jax.device_count())
            have = {a: mesh_manager.axis_size(a) for a in mesh_manager.axis_names()}
            if want != have:
                # config disagrees with the live mesh (e.g. a second engine with a
                # different layout) — rebuild rather than silently reuse
                mesh_manager = initialize_mesh(self.config.mesh.to_mesh_config())
        self.mesh_manager = mesh_manager
        self.mesh = self.mesh_manager.mesh

        # batch triad: dp width = replicas of the model over the batch dim
        self.dp_world_size = (self.mesh_manager.axis_size("data")
                              * self.mesh_manager.axis_size("zshard")
                              * self.mesh_manager.axis_size("expert"))
        self.config.resolve_batch_size(self.dp_world_size)

        self.zero_stage = self.config.zero_optimization.stage
        self.policy = ShardingPolicy(self.mesh, self.zero_stage)

        # AutoSP: config-driven sequence-parallel pass over the spec
        # (reference compile_autosp engine hook, engine.py:1160)
        self.sp_plan = None
        sp_cfg = self.config.sequence_parallel
        if sp_cfg.size and sp_cfg.size != self.mesh_manager.axis_size("seq"):
            raise DeepSpeedConfigError(
                f"sequence_parallel.size {sp_cfg.size} != mesh seq axis "
                f"{self.mesh_manager.axis_size('seq')}"
                + ("" if sp_cfg.auto else
                   " (note: sequence_parallel.size alone does not enable SP; "
                   "set \"auto\": true and a mesh 'seq' axis)"))
        if sp_cfg.auto:
            from deepspeed_tpu.sequence.auto_sp import auto_sp

            model, self.sp_plan = auto_sp(model)
            self.model_spec = model

        # activation_checkpointing.policy → the spec's remat policy
        # (reference runtime/activation_checkpointing config; also what the
        # autotuner's remat dimension tunes). Applied via the spec's own
        # builder so customizations survive.
        ac_policy = self.config.activation_checkpointing.policy
        if ac_policy and ac_policy != "none":
            spec_cfg = getattr(model, "config", None)
            if spec_cfg is not None and getattr(spec_cfg, "remat", None) == ac_policy:
                pass   # already built with this policy
            elif getattr(model, "builder", None) is not None:
                model = model.builder(remat=ac_policy)
                self.model_spec = model
            else:
                logger.warning(
                    f"activation_checkpointing.policy={ac_policy!r} ignored: "
                    "the model spec carries no builder to rebuild with")

        # precision
        self.precision = self.config.precision_dtype  # float32|float16|bfloat16
        self.fp16_enabled = self.precision == "float16"
        self.scaler = DynamicLossScaler.from_config(self.config.fp16) \
            if self.fp16_enabled else None

        # optimizer + schedule
        if optimizer is None:
            opt_cfg = self.config.optimizer
            if opt_cfg is None:
                raise ValueError("config must define an optimizer (or pass one in)")
            optimizer = get_optimizer(opt_cfg.type, opt_cfg.params)
        # ZenFlow: importance-split hot/cold updates (runtime/zenflow.py)
        from deepspeed_tpu.runtime.zenflow import maybe_wrap_zenflow

        optimizer = maybe_wrap_zenflow(optimizer, zcfg.zenflow)
        # frozen params (LoRA etc.): optimizer state only for trainable leaves
        self._trainable_mask = None
        if model.trainable_fn is not None:
            from deepspeed_tpu.ops.optimizer import MaskedOptimizer

            self._trainable_mask = model.trainable_fn()
            optimizer = MaskedOptimizer(inner=optimizer,
                                        mask=self._trainable_mask)
        self.optimizer = optimizer
        _inner_opt = optimizer
        while hasattr(_inner_opt, "inner"):   # MaskedOptimizer/ZenFlow wrap
            _inner_opt = _inner_opt.inner
        if (self.precision == "bfloat16"
                and not self.config.bf16.fp32_master
                and not getattr(_inner_opt, "stochastic_rounding", False)):
            # without an fp32 master, updates below bf16's 8-bit-mantissa
            # step (~0.4% relative) round to zero and training silently
            # stalls — only stochastic-rounding optimizers can absorb them
            raise ValueError(
                "bf16.fp32_master=false requires a stochastic-rounding "
                "optimizer (adafactor); "
                f"{type(optimizer).__name__} would silently stall")
        if lr_scheduler is None and self.config.scheduler and self.config.scheduler.type:
            lr_scheduler = get_lr_schedule(
                self.config.scheduler.type, self.config.scheduler.params,
                base_lr=self.optimizer.lr)
        self.lr_scheduler = lr_scheduler

        dist.configure(self.config)

        # sharding spec trees
        self._axes = model.axes_fn()
        seed = self.config.seed if seed is None else seed
        self._init_rng = jax.random.PRNGKey(seed)
        self._shapes = jax.eval_shape(model.init_fn, self._init_rng)
        self.master_spec = self.policy.state_spec(self._axes, self._shapes)
        self.param_spec = self.policy.param_spec(self._axes, self._shapes)
        self.grad_spec = self.policy.grad_spec(self._axes, self._shapes)
        self.batch_spec = self.policy.batch_spec()

        # ZeRO-Offload: optimizer state lives in host memory between steps
        # (reference runtime/zero/offload_config.py + swap_tensor swappers;
        # the device↔host moves bracket the jitted step like the reference's
        # swap-in/step/swap-out flow, stage_1_and_2.py initialize/step)
        if self.config.zero_optimization.super_offload:
            # SuperOffload alias → host-executed optimizer with overlap.
            # Explicit user settings win: an explicit overlap_step=False is
            # honored (no silent staleness) and a conflicting device raises.
            off = self.config.zero_optimization.offload_optimizer
            if off.device not in ("none", "cpu"):
                raise DeepSpeedConfigError(
                    f"super_offload conflicts with offload_optimizer.device="
                    f"{off.device!r}; it implies device='cpu'")
            off.device, off.host_step = "cpu", True
            if off.overlap_step is None:
                off.overlap_step = True
        offload_dev = self.config.zero_optimization.offload_optimizer.device
        if (self.config.zero_optimization.offload_optimizer.host_step
                and offload_dev != "cpu"):
            raise DeepSpeedConfigError(
                "offload_optimizer.host_step requires device='cpu' (got "
                f"{offload_dev!r}) — the host CPU backend runs the update")
        self._host_step = (offload_dev == "cpu" and
                           self.config.zero_optimization.offload_optimizer.host_step)
        self._offload_opt = offload_dev == "cpu" and not self._host_step
        # NVMe tier: optimizer state swapped to local disk around the step
        # (reference swap_tensor/partitioned_optimizer_swapper.py:27)
        self._offload_nvme = offload_dev == "nvme"
        self._opt_swapper = None   # built lazily (needs self.state)

        # ZeRO-Infinity PARAMETER tier (reference swap_tensor/
        # partitioned_param_swapper.py:37 AsyncPartitionedParameterSwapper +
        # zero/offload_config.py:19-41): at stage 3 the fp32 master shards
        # are PINNED-HOST resident — the jitted step's layer scan streams
        # each layer's slice H2D on use and the update writes back to host,
        # so HBM holds only the transient 16-bit working copies (verified
        # via compiled memory_analysis: device argument bytes for the
        # master drop to 0). The NVMe variant additionally round-trips the
        # host master through TensorSwapper files between steps.
        pcfg = self.config.zero_optimization.offload_param
        self._offload_param = False
        self._offload_param_nvme = False
        if pcfg.device not in ("none", None):
            if pcfg.device not in ("cpu", "nvme"):
                raise DeepSpeedConfigError(
                    f"offload_param.device must be none|cpu|nvme, got "
                    f"{pcfg.device!r}")
            if self.zero_stage < 3:
                logger.warning(
                    "offload_param is a ZeRO-3 tier (reference "
                    "zero/offload_config.py) but zero_optimization.stage="
                    f"{self.zero_stage} — parameter offload is DISABLED. "
                    "Set stage: 3 to enable it.")
            else:
                self._offload_param = True
                self._offload_param_nvme = pcfg.device == "nvme"
        self._param_swapper = None  # built lazily (NVMe variant)
        # In-step H2D streaming (host-resident master INPUTS + in-program
        # device_put per use) needs XLA memories support in the SPMD
        # partitioner — present on the TPU backend, absent on CPU (both
        # host-input and device-output placement annotations fail to
        # partition there). CPU falls back to jit-boundary swaps: master
        # parked pinned-host between steps, moved whole to device around
        # the step (the ZeRO-Offload pattern _opt_swap also uses).
        # ZeRO++ compressed collectives (qwZ/qgZ) + 1-bit optimizer transport
        self._resolve_compressed_modes(zcfg)
        # the compressed/1-bit step builders are not host-input aware
        # (their shard_map state layouts assume device memory) — those
        # combos use the boundary-swap mode
        self._offload_param_stream = (
            self._offload_param and jax.default_backend() == "tpu"
            and not self._compressed and not self._onebit_wire)

        # training-run guardian (config "guardian"; README "Training
        # guardian"): device-side non-finite skip for bf16/fp32 — the fp16
        # loss-scaler's lax.cond branch, minus the scaler. Resolved BEFORE
        # _init_state so the state tree carries the `skips` counter.
        gcfg = self.config.guardian
        self._nonfinite_guard = bool(
            gcfg.enabled and gcfg.nonfinite_guard and not self.fp16_enabled)
        if self._nonfinite_guard and self._host_step:
            logger.warning(
                "guardian.nonfinite_guard is unavailable with "
                "offload_optimizer.host_step (the host-executed update has "
                "no device-side skip branch) — host-side anomaly detection "
                "still runs")
            self._nonfinite_guard = False
        self._guardian = None          # attached by TrainingGuardian
        self._gc_protect_tags: set = set()   # rollback anchors keep_n must keep
        self._gc_protect_root: Optional[str] = None
        self._gc_pin_stale = False   # superseded by an in-flight async commit
        self._restored_client_state: Optional[Dict] = None
        self._tm_skips_lock = make_lock("engine._tm_skips_lock")

        # bucketed compute/collective overlap scheduler (ROADMAP item 2;
        # parallel/overlap.py): chunk the layer scan at the prefetch-bucket
        # granularity and emit each chunk's gradient sync mid-backward so
        # XLA's async-collective pass can hide it under remaining compute
        self._setup_overlap_scheduler(zcfg)

        # data-efficiency features (reference runtime/data_pipeline/ +
        # progressive_layer_drop.py — config-driven, engine-injected)
        self._setup_data_efficiency()

        self.state = self._init_state()
        self._compiled: Dict[Any, Any] = {}
        # step-phase overlap: seed the double-buffered param publish so
        # the FIRST step's forward has a buffer to consume
        self._refresh_param_buffer()
        if self._offload_opt:
            self._opt_swap("out")
        self._host_runner = None
        if self._host_step:
            from deepspeed_tpu.runtime.host_step import HostStepRunner

            if self._compressed or self._onebit_wire:
                raise DeepSpeedConfigError(
                    "host_step cannot combine with compressed collectives")
            self._host_runner = HostStepRunner(self)
            self._host_runner.adopt_state()

        # eager-API accumulation
        self._grad_buffer: Optional[PyTree] = None
        self._pending_grads: Optional[PyTree] = None
        self._micro_in_window = 0

        # bookkeeping
        self.global_steps = 0
        self.micro_steps = 0
        self.timers = SynchronizedWallClockTimer()
        self.tput_timer = ThroughputTimer(
            batch_size=self.config.train_batch_size or 1,
            steps_per_output=self.config.steps_per_print)
        self._last_metrics_dev: Dict[str, jax.Array] = {}
        self.monitor = None  # attached by initialize() when configured

        # fault tolerance (config "fault_tolerance"; README "Fault
        # tolerance"): preemption flag checked at step boundaries, a lock
        # serializing emergency saves (watchdog thread vs signal handler),
        # and the last save_checkpoint dir as the emergency fallback root
        self._preempt_requested = False
        self._in_step = False
        self._guard_busy = False   # defer_preemption scope (guardian)
        self._saving = False
        self._ft_lock = make_lock("engine._ft_lock")
        self._last_save_dir: Optional[str] = None
        self._prev_sig_handlers: Dict[int, Any] = {}
        self._setup_telemetry()

        # EP-dispatch drop visibility: under an 'expert' mesh axis the ragged
        # MoE path can overflow its fixed all-to-all buffer on router skew;
        # the overflowed choices silently fall through to the residual, so a
        # degrading router would otherwise hurt training quality invisibly.
        self._moe_drop_frac = 0.0
        if self.mesh_manager.axis_size("expert") > 1:
            import weakref

            from deepspeed_tpu.moe.layer import set_drop_monitor

            # weakref: the module-global monitor must not pin a dead engine
            # (params + compiled steps) for the life of the process
            ref = weakref.ref(self)

            def _sink(frac):
                eng = ref()
                if eng is not None:
                    eng._record_moe_drops(frac)

            set_drop_monitor(_sink)

        n_params = model.num_params
        log_dist(
            f"engine up: model={model.name} params={n_params or '?'} "
            f"zero_stage={self.zero_stage} precision={self.precision} "
            f"mesh={self.mesh_manager} micro_bs={self.train_micro_batch_size()} "
            f"gas={self.gradient_accumulation_steps()}")
        self._enforce_hlolint()
        self._enforce_memlint()

    def _enforce_hlolint(self) -> None:
        """Compiled-program contract enforcement at initialize (the
        ``"hlolint"`` config section): lower the REAL fused step once
        (the observatory cache keeps it — ledger/report calls reuse the
        same lowering) and lint it; with ``fail_on_violation`` a
        violation refuses the job BEFORE chip time is spent."""
        hlolint_cfg = self.config.hlolint
        if not hlolint_cfg.enabled:
            return
        findings = self.lint_step(contract=hlolint_cfg.contract or None)
        if not findings:
            log_dist("hlolint: compiled train step clean"
                     + (f" (contract {hlolint_cfg.contract})"
                        if hlolint_cfg.contract else ""))
            return
        for f in findings:
            log_dist(f"hlolint: {f.render()}")
        if hlolint_cfg.fail_on_violation:
            from deepspeed_tpu.analysis.hlolint import HloLintViolation

            raise HloLintViolation(
                f"hlolint: {len(findings)} compiled-program contract "
                f"violation(s) in the lowered train step — first: "
                f"{findings[0].render()} (set hlolint.fail_on_violation "
                "false to proceed anyway)")

    def _memlint_budget_bytes(self) -> Optional[float]:
        """The OOM pre-flight budget: the explicit
        ``memlint.hbm_budget_bytes`` when set, else the chip's datasheet
        HBM capacity (``utils/chip_specs``). None on the datasheet-less
        CPU tier without an explicit budget — the gate stays disarmed
        there rather than inheriting a TPU part's capacity."""
        explicit = self.config.memlint.hbm_budget_bytes
        if explicit:
            return float(explicit)
        from deepspeed_tpu.utils.chip_specs import chip_hbm_bytes

        try:
            kind = getattr(jax.devices()[0], "device_kind", "")
        except (RuntimeError, IndexError):
            return None
        cap = chip_hbm_bytes(kind)
        return float(cap) if cap else None

    def _enforce_memlint(self) -> None:
        """Memory-contract enforcement at initialize (the ``"memlint"``
        config section — hlolint's memory-side sibling): donation/
        aliasing verification, residency vs the ZeRO prediction, the
        committed memory contract, and the OOM pre-flight gate. Reuses
        the SAME cached lowering hlolint/the ledger read; with
        ``fail_on_violation`` a violation — including a predicted peak
        over the HBM budget — refuses the job BEFORE any chip time is
        spent."""
        mcfg = self.config.memlint
        if not mcfg.enabled:
            return
        findings = self.lint_memory(contract=mcfg.contract or None,
                                    hbm_budget_bytes=self._memlint_budget_bytes())
        if not findings:
            log_dist("memlint: compiled train step memory clean"
                     + (f" (contract {mcfg.contract})"
                        if mcfg.contract else ""))
            return
        for f in findings:
            log_dist(f"memlint: {f.render()}")
        if mcfg.fail_on_violation:
            from deepspeed_tpu.analysis.memlint import MemLintViolation

            raise MemLintViolation(
                f"memlint: {len(findings)} memory contract violation(s) "
                f"in the lowered train step — first: "
                f"{findings[0].render()} (set memlint.fail_on_violation "
                "false to proceed anyway)")

    # ------------------------------------------------------------------ #
    # compressed collectives (ZeRO++ qwZ/qgZ, 1-bit transport)
    # ------------------------------------------------------------------ #
    def _resolve_compressed_modes(self, zcfg) -> None:
        """Decide whether the train step uses wire-compressed collectives.

        qwZ/qgZ (reference ``zero/config.py:309-330``,
        ``runtime/comm/coalesced_collectives.py``): int8 parameter all-gather /
        gradient reduce-scatter inside a shard_map manual over the ZeRO axes.
        1-bit transport (reference ``runtime/comm/nccl.py:52``): packed-sign
        momentum allreduce — stage 0 only, as in the reference (1-bit
        optimizers are incompatible with ZeRO partitioning there too).
        Every accepted-but-inapplicable flag warns loudly (round-1 verdict:
        silent config no-ops are bugs)."""
        from deepspeed_tpu.comm.mesh import DATA_AXIS as _D, ZSHARD_AXIS as _Z

        shape = self.mesh.shape
        self._dp_manual_axes = tuple(
            a for a in (_D, _Z) if shape.get(a, 1) >= 1)
        self._dp_manual_world = int(
            np.prod([shape.get(a, 1) for a in self._dp_manual_axes]))
        # expert>1 is allowed: the MoE batch/weight shardings over 'expert'
        # stay GSPMD-auto inside the dp-manual shard_map (the reference's
        # loudest qgZ win is exactly MoE gradients, BASELINE.md #9); hpZ
        # (zshard>1) composes via per-leaf subgroup gathers — the full
        # ZeRO++ trio (zero/config.py:309-330)
        eligible = (self._dp_manual_world > 1
                    and shape.get("seq", 1) == 1
                    and shape.get("pipe", 1) == 1)

        quant_w = bool(zcfg.zero_quantized_weights
                       or zcfg.zero_quantized_nontrainable_weights)
        quant_g = bool(zcfg.zero_quantized_gradients)
        self._compressed: Optional[Dict[str, bool]] = None
        if quant_w or quant_g:
            if self.zero_stage < 1:
                logger.warning(
                    "zero_quantized_weights/gradients require ZeRO stage >= 1 "
                    f"(got stage {self.zero_stage}) — exact collectives used")
            elif not eligible:
                logger.warning(
                    "zero_quantized_weights/gradients need data-parallel width "
                    "> 1 and seq=pipe=1 in the mesh — exact collectives "
                    f"used (mesh={dict(shape)})")
            else:
                self._compressed = {"quant_weights": quant_w,
                                    "quant_grads": quant_g}
                log_dist(f"ZeRO++ compressed collectives active: qwZ={quant_w} "
                         f"qgZ={quant_g} over axes {self._dp_manual_axes}")
        if zcfg.loco_error_feedback:
            if self._compressed is not None \
                    and self._compressed["quant_grads"]:
                self._compressed["loco"] = True
                log_dist("LoCo error feedback active for the qgZ reduce "
                         "(reference coalesced_collectives.py:81)")
            else:
                logger.warning(
                    "loco_error_feedback requires an ACTIVE "
                    "zero_quantized_gradients path — ignored")

        opt_type = (self.config.optimizer.type if self.config.optimizer
                    else "").lower().replace("_", "")
        self._onebit_wire = False
        if opt_type.startswith("zeroone"):
            # ZeroOneAdam's post-freeze variance REFRESH consumes the raw
            # gradient; with wire transport gradients stay unreduced per-rank
            # after freeze, so v (and then params) would silently diverge
            # across ranks — local compression only for this optimizer.
            logger.warning(
                "ZeroOneAdam runs with LOCAL compression only (its variance "
                "refresh consumes raw gradients, which stay per-rank under "
                "wire transport); use onebit_adam/onebit_lamb for the "
                "compressed-transport path")
        elif opt_type.startswith("onebit"):
            # fp16 excluded: the overflow skip decision would be taken on
            # per-rank (unreduced) grad norms — divergent control flow around
            # the transport collectives. expert=1 stays required HERE (qgZ
            # composes with MoE; the 1-bit momentum transport's per-rank
            # error buffers under expert sharding are untested territory).
            onebit_ok = eligible and shape.get("expert", 1) == 1
            if self.zero_stage == 0 and onebit_ok and not self.fp16_enabled \
                    and hasattr(self.optimizer, "transport"):
                self._onebit_wire = True
                log_dist("1-bit optimizer wire transport active: packed-sign "
                         f"momentum allreduce over {self._dp_manual_axes}")
            else:
                logger.warning(
                    "1-bit optimizer running with LOCAL compression only "
                    "(convergence parity, no wire saving): transport needs "
                    "ZeRO stage 0 (reference parity: 1-bit optimizers are "
                    "incompatible with ZeRO partitioning), dp width > 1 and "
                    f"expert=seq=pipe=1 (stage={self.zero_stage}, "
                    f"mesh={dict(shape)})")
        if self._compressed and self._onebit_wire:
            logger.warning("qwZ/qgZ and 1-bit transport are mutually "
                           "exclusive — using 1-bit transport")
            self._compressed = None

    # ------------------------------------------------------------------ #
    # autotune plan cache ("autotuning" section; autotuning/planner.py)
    # ------------------------------------------------------------------ #
    def _load_autotune_plan(self, zcfg) -> None:
        """Load and apply the cached autotune plan for this engine's
        ``(model_fingerprint, mesh_shape, wire_format, platform)`` key.

        Called at the TOP of ``__init__`` — before the hpZ subgroup
        resolution and the overlap scheduler consume any of the planned
        knobs. A knob the user ALSO set explicitly (tracked via
        ``_explicit_zero_keys`` from ``load_config``) is never
        overwritten: agreement is a hit, contradiction is a STALE plan —
        refused outright under ``autotuning.fail_on_stale``, else the
        explicit value wins with a loud warning. ``self._plan_status``
        ∈ {disabled, miss, hit, stale} for bench/report consumers.
        """
        self._plan_status = "disabled"
        self._plan_key: Optional[str] = None
        self._plan_doc: Optional[Dict] = None
        acfg = self.config.autotuning
        if acfg is None or not acfg.enabled:
            return
        from deepspeed_tpu import telemetry
        from deepspeed_tpu.autotuning import planner as _planner

        key, _fields = _planner.plan_key_for_config(self.config,
                                                    self.model_spec)
        self._plan_key = key
        path = _planner.plan_path(acfg.plan_cache_dir, key)
        miss = telemetry.counter(
            "autotune_plan_cache_misses_total",
            "engine initializations with no usable cached plan")
        if not os.path.exists(path):
            self._plan_status = "miss"
            miss.inc()
            return
        try:
            doc = _planner.load_plan(path)
        except _planner.PlanError as e:
            if acfg.fail_on_stale:
                raise DeepSpeedConfigError(
                    f"autotuning.fail_on_stale: cached plan {path} is "
                    f"unreadable or schema-invalid ({e}) — regenerate it "
                    "with tools/plan or unset fail_on_stale") from None
            logger.warning(f"cached autotune plan {path} invalid — "
                           f"ignored ({e})")
            self._plan_status = "miss"
            miss.inc()
            return
        explicit = getattr(self.config, "_explicit_zero_keys", None)
        from deepspeed_tpu.runtime.config import ZeroConfig as _ZC

        defaults = _ZC()
        conflicts, applied = [], []
        for k, v in doc["knobs"].items():
            cur = getattr(zcfg, k, None)
            is_explicit = (k in explicit if explicit is not None
                           else cur != getattr(defaults, k, None))
            if is_explicit:
                if cur != v:
                    conflicts.append(f"{k}: config={cur!r} plan={v!r}")
                continue
            if k == "zero_hpz_partition_size" and v and int(v) > 1:
                # the subgroup IS the zshard axis: the planner's hpZ
                # candidates shrink 'data' by the subgroup width (same
                # device world, data x zshard layout) — mirror that, or
                # skip the knob when this mesh can't host it (an
                # explicit data axis not divisible by the subgroup)
                mesh = self.config.mesh
                if mesh.zshard == 1 and mesh.data > 1 \
                        and mesh.data % int(v) == 0:
                    mesh.data //= int(v)
                elif mesh.data > 0 and mesh.zshard == 1:
                    logger.warning(
                        f"autotune plan knob zero_hpz_partition_size={v} "
                        f"does not divide mesh.data={mesh.data} — knob "
                        "skipped")
                    continue
            setattr(zcfg, k, v)
            applied.append(k)
        if conflicts:
            self._plan_status = "stale"
            detail = "; ".join(conflicts)
            if acfg.fail_on_stale:
                raise DeepSpeedConfigError(
                    f"autotuning.fail_on_stale: engine config contradicts "
                    f"cached plan {path} ({detail}) — re-run tools/plan "
                    "for this config or drop the conflicting explicit "
                    "keys")
            logger.warning(
                f"cached autotune plan {path} is STALE against explicit "
                f"config keys ({detail}) — explicit values kept; planned "
                f"values applied only to: {applied or 'none'}")
            return
        self._plan_status = "hit"
        self._plan_doc = doc
        telemetry.counter(
            "autotune_plan_cache_hits_total",
            "engine initializations that applied a cached plan").inc()
        log_dist(f"autotune plan {key} applied "
                 f"(winner={doc.get('winner')}, knobs={applied})")

    # ------------------------------------------------------------------ #
    # overlap scheduler (parallel/overlap.py — README "Overlap scheduler")
    # ------------------------------------------------------------------ #
    def _setup_overlap_scheduler(self, zcfg) -> None:
        """Resolve the bucketed overlap scheduler and (when applicable)
        rebuild the model spec with a chunked layer scan + mid-backward
        grad-sync points.

        Honors the reference bucket keys WITH the reference's units
        (element counts): ``reduce_bucket_size`` bounds gradient-sync
        buckets, ``stage3_prefetch_bucket_size`` (stage 3) /
        ``allgather_bucket_size`` (stages 1-2) bound the layer-chunk
        parameter elements. Gated by ``overlap_comm`` at stage >= 1.

        Wire format and overlap are ORTHOGONAL axes of the step-builder
        pipeline (ISSUE 10): the qwZ/qgZ step composes — its chunk sync
        point is the manual-region-safe ordering fence
        (``overlap.manual_chunk_sync``; named sharding constraints don't
        exist inside shard_map), its grad buckets fence the int8
        reduces (``compressed.reduce_tree_bucketed``) and its ZeRO-3
        chunk gathers follow the same chunk plan on the quantized wire
        (``compressed.chunked_gather_tree_fn``). Only the 1-bit
        transport stays outside the scheduler, structurally: it is a
        stage-0 optimizer-side transport and the scheduler gates at
        stage >= 1."""
        from deepspeed_tpu.parallel.overlap import (
            OverlapConfig,
            chunk_layers,
            manual_chunk_sync,
        )

        self._overlap = OverlapConfig.from_zero_config(zcfg, self.zero_stage)
        # step-phase overlap (ROADMAP item 2; Automatic Cross-Replica
        # Sharding of Weight Update, 2004.13336): bucketed weight update
        # under the fence chain + the post-update param publish deferred
        # into a double buffer the NEXT step's forward consumes. Rides
        # the scheduler gate; the param buffer additionally needs a
        # fused device step that owns both the forward and the update
        # (no pipeline loss_and_grads_fn, no host-resident master, no
        # host-executed update; the 1-bit transport is stage 0 and never
        # reaches here with the scheduler on).
        ub = zcfg.update_bucket_size
        self._update_bucket_elems = (self._overlap.reduce_bucket_elems
                                     if ub == "auto" else int(ub))
        # dp world 1 has NO update-phase collectives to hide (GSPMD
        # elides them — the same reason hlolint's fence-defeat floor
        # only arms at dp > 1): the fences would only perturb fusion on
        # a program with nothing to overlap, so the serial step is kept
        # bit-identical there (incl. the single-chip CPU bench tier)
        self._step_overlap = bool(zcfg.overlap_step) \
            and self._overlap.enabled and self._dp_manual_world > 1
        # a pipe mesh activates the spec's explicit-backward
        # loss_and_grads_fn path, which bypasses the buffered forward
        pipelined = self.mesh_manager.axis_size("pipe") > 1
        self._param_buffer = (self._step_overlap and not pipelined
                              and not self._offload_param
                              and not self._host_step
                              and not self._onebit_wire)
        self._publish_fn = None     # lazy _publish_tree_fn cache
        self._consume_fn = None     # lazy _consume_param_buffer cache
        self._overlap_plan: Dict[str, Any] = {
            "enabled": self._overlap.enabled, "scan_chunks": 1,
            "chunk_bounds": [], "grad_sync_points": False,
            "step_overlap": self._step_overlap,
            "param_buffer": self._param_buffer,
            "update_bucket_elems": self._update_bucket_elems,
            "wire_format": self._wire_format()}
        if not self._overlap.enabled:
            return
        wire = self._compressed is not None
        model = self.model_spec
        spec_cfg = getattr(model, "config", None)
        n_layers = getattr(spec_cfg, "num_layers", 0) or 0
        can_chunk = (model.builder is not None and spec_cfg is not None
                     and hasattr(spec_cfg, "scan_chunks") and n_layers > 1
                     and self.mesh_manager.axis_size("pipe") == 1)
        bounds = []
        if can_chunk:
            per_layer = self._blocks_elems_per_layer(n_layers)
            # stage 3: the prefetch bucket IS the gather granularity;
            # stages 1-2: allgather_bucket_size alone (the README
            # contract — reduce_bucket_size governs grad buckets only)
            chunk_elems = (self._overlap.prefetch_bucket_elems
                           if self.zero_stage >= 3
                           else self._overlap.allgather_bucket_elems)
            bounds = chunk_layers(n_layers, per_layer, chunk_elems)
        n_chunks = max(len(bounds), 1)
        # mid-backward sync points need a sharded gradient layout to pin
        # (stage >= 2); at stage 1 the chunked scan alone supplies the
        # gather granularity
        sync_fn = None
        if can_chunk and self.zero_stage >= 2:
            sync_fn = manual_chunk_sync() if wire \
                else self._make_chunk_grad_sync()
        if can_chunk and (n_chunks > 1 or sync_fn is not None):
            self.model_spec = model.builder(scan_chunks=n_chunks,
                                            param_sync_fn=sync_fn)
            self._overlap_plan.update(
                scan_chunks=n_chunks, chunk_bounds=bounds,
                grad_sync_points=sync_fn is not None)
            log_dist(f"overlap scheduler active: {n_chunks} layer chunk(s), "
                     f"wire={self._overlap_plan['wire_format']}, "
                     f"grad sync {'per chunk mid-backward' if sync_fn else 'bucketed at step level'}, "
                     f"reduce_bucket={self._overlap.reduce_bucket_elems} "
                     f"prefetch_bucket={self._overlap.prefetch_bucket_elems}")

    def _blocks_elems_per_layer(self, n_layers: int) -> int:
        """Per-layer parameter ELEMENTS (what a ZeRO-3 chunk gather
        moves per layer, in the bucket keys' reference unit)."""
        from deepspeed_tpu.parallel.overlap import leaf_count

        shapes = self._shapes.get("blocks") \
            if isinstance(self._shapes, dict) else None
        if shapes is None:
            return 0
        total = sum(leaf_count(s.shape) for s in jax.tree.leaves(shapes))
        return max(total // max(n_layers, 1), 1)

    def _make_chunk_grad_sync(self):
        """Closure for ``parallel/overlap.make_grad_sync``: constrain a
        layer-chunk's COTANGENT to its ZeRO gradient sharding so XLA
        emits the chunk's reduce as soon as its backward completes.
        Captures mesh/policy/axes — not the engine (no cycle)."""
        from deepspeed_tpu.parallel.overlap import make_grad_sync
        from deepspeed_tpu.parallel.partitioning import (
            _is_axes_leaf,
            logical_to_spec,
        )

        axes_blocks = self._axes.get("blocks") \
            if isinstance(self._axes, dict) else None
        if axes_blocks is None:
            return None
        mesh, policy = self.mesh, self.policy

        def _norm(spec):
            parts = list(spec)
            while parts and parts[-1] is None:
                parts.pop()
            return tuple(parts)

        def constrain(cotangent: PyTree) -> PyTree:
            def one(axes, g):
                spec = policy.leaf_grad_spec(axes, g.shape)
                if _norm(spec) == _norm(logical_to_spec(axes,
                                                        policy.tp_rules)):
                    # the chunk slice has no zero-divisible dim at this
                    # granularity — constraining would PIN a replicated
                    # layout mid-backward (a full all-reduce plus a
                    # reshard against the step-level sharded spec);
                    # leave the leaf to the step-end constraint instead
                    return g
                return jax.lax.with_sharding_constraint(
                    g, NamedSharding(mesh, spec))

            return jax.tree.map(one, axes_blocks, cotangent,
                                is_leaf=_is_axes_leaf)

        return make_grad_sync(constrain)

    def overlap_plan(self) -> Dict[str, Any]:
        """The resolved overlap-scheduler plan (chunk bounds, bucket
        sizes, sync-point installation, step-phase overlap + param
        double buffer) — step-report / test hook."""
        plan = dict(self._overlap_plan)
        plan.update(reduce_bucket_elems=self._overlap.reduce_bucket_elems,
                    allgather_bucket_elems=self._overlap.allgather_bucket_elems,
                    prefetch_bucket_elems=self._overlap.prefetch_bucket_elems)
        return plan

    # ------------------------------------------------------------------ #
    # step-phase overlap: bucketed update + double-buffered params
    # (ROADMAP item 2; 2004.13336 — README "Overlap scheduler")
    # ------------------------------------------------------------------ #
    def _buffer_shardings(self) -> Any:
        """Shardings of the double-buffered gathered-params state leaf:
        the wire step's buffer is the per-rank FULL param tree
        (replicated — the persistent form of the stage-2-like transient
        the reduce-outside-vjp formulation already materialized); the
        exact step's buffer is the compute-param layout
        (``param_spec`` — stages 1-2 replicated, stage 3 sharded with
        per-use gathers staying in the forward)."""
        if self._compressed is not None:
            rep = NamedSharding(self.mesh, P())
            return jax.tree.map(lambda _: rep, self.master_spec,
                                is_leaf=lambda x: isinstance(x, P))
        return self.policy.to_shardings(self.param_spec)

    def _publish_tree_fn(self):
        """The tree-level deferred publish: new master → the gathered
        compute-param buffer the NEXT forward consumes. Wire steps run
        the SAME (chunk-fenced) qwZ/hpZ gather the forward used to
        issue at step start (``compressed.publish_gather_tree_fn`` —
        the wire rides the deferral unchanged); exact steps run the
        ``_compute_params`` cast/constrain, which at stages 1-2 IS the
        post-update all-gather. Traced under the ``zero_param_update``
        name scope so the observatory prices it as the update phase.
        Also the ``_refresh_param_buffer`` recompute — publish values
        are deterministic in the master, so a recomputed buffer is
        bit-equal to the in-step one."""
        if self._publish_fn is not None:
            return self._publish_fn
        if self._compressed is not None:
            from jax import shard_map

            from deepspeed_tpu.parallel import compressed as C

            axes = self._dp_manual_axes
            world = self._dp_manual_world
            dtype = jnp.dtype(self.precision)
            bounds = (self._overlap_plan.get("chunk_bounds") or [])
            gather = C.publish_gather_tree_fn(
                self.master_spec, axes, world, dtype,
                quant_weights=self._compressed["quant_weights"],
                chunk_bounds=bounds, axis_sizes=dict(self.mesh.shape))
            master_manual = jax.tree.map(
                lambda s: C.manual_spec(s, axes), self.master_spec,
                is_leaf=lambda x: isinstance(x, P))
            rep_specs = jax.tree.map(
                lambda _: P(), self.master_spec,
                is_leaf=lambda x: isinstance(x, P))
            mesh = self.mesh

            def publish(master):
                fn = shard_map(gather, mesh=mesh,
                               in_specs=(master_manual,),
                               out_specs=rep_specs,
                               axis_names=set(axes), check_vma=False)
                return fn(master)
        else:
            def publish(master):
                with jax.named_scope("zero_param_update"):
                    return self._compute_params(master)
        self._publish_fn = publish
        return publish

    def _publish_leaf_fns(self):
        """Per-leaf exact-path publish (master flatten order) — the
        ``_compute_params`` cast/constrain leaf-by-leaf
        (``_compute_param_leaf`` — the shared implementation), so each
        update bucket's publish can chain ONE fence behind its update
        in ``fenced_update_chain`` instead of waiting for the whole
        tree."""
        param_sh = jax.tree.leaves(
            self.policy.to_shardings(self.param_spec),
            is_leaf=lambda x: isinstance(x, NamedSharding))
        return [lambda m, sh=sh: self._compute_param_leaf(m, sh)
                for sh in param_sh]

    def _fence_update_buckets(self, new_master: PyTree, new_opt: Dict
                              ) -> Tuple[PyTree, Dict]:
        """Restructure the tree-wide optimizer update into per-bucket
        fenced groups (``update_bucket_size`` elements, reversed-flatten
        backward-completion order — the SAME plan the grad-sync buckets
        use, so update bucket k consumes grad bucket k). Optimizer
        moment trees that mirror the master tree ride the same fences;
        auxiliary state of other structures (factored Adafactor
        moments, per-layer scalars) is left to data dependence. Values
        are bit-identical to the unfenced update. The deferred publish
        consumes these FENCED leaves per bucket (``_publish_fenced``),
        so publish bucket k still launches the moment update bucket k
        lands — it runs outside this call (and outside the skip cond,
        see ``_apply_update``)."""
        from deepspeed_tpu.parallel.overlap import (
            fenced_update_chain,
            leaf_count,
            plan_buckets,
        )

        m_leaves, m_def = jax.tree.flatten(new_master)
        if not m_leaves:
            return new_master, new_opt
        sizes = [leaf_count(x.shape) for x in m_leaves]
        buckets = plan_buckets(sizes, self._update_bucket_elems)
        aux_names, aux_lists = [], []
        if isinstance(new_opt, dict):
            for name in getattr(self.optimizer, "moment_names", ()):
                sub = new_opt.get(name)
                if sub is None:
                    continue
                leaves, sdef = jax.tree.flatten(sub)
                if sdef == m_def:
                    aux_names.append(name)
                    aux_lists.append(leaves)
        out_m, out_aux, _ = fenced_update_chain(
            m_leaves, aux_lists, buckets)
        new_master = m_def.unflatten(out_m)
        if aux_names:
            new_opt = dict(new_opt)
            for name, leaves in zip(aux_names, out_aux):
                new_opt[name] = m_def.unflatten(leaves)
        return new_master, new_opt

    def _publish_fenced(self, master: PyTree) -> PyTree:
        """The deferred publish on the (fenced) post-update master:
        exact path — per-leaf cast/constrain grouped into the SAME
        bucket plan as the update fences and chained behind
        ``optimization_barrier`` tokens (``fenced_bucket_apply``), so
        each bucket's publish all-gather launches as its update lands;
        wire path — the tree-level chunk-fenced qwZ/hpZ gather
        (``_publish_tree_fn``)."""
        if self._compressed is not None or not self._step_overlap:
            return self._publish_tree_fn()(master)
        from deepspeed_tpu.parallel.overlap import (
            fenced_bucket_apply,
            leaf_count,
            plan_buckets,
        )

        leaves, tdef = jax.tree.flatten(master)
        pubs = self._publish_leaf_fns()
        if not leaves or len(pubs) != len(leaves):   # defensive drift
            return self._publish_tree_fn()(master)
        buckets = plan_buckets([leaf_count(x.shape) for x in leaves],
                               self._update_bucket_elems)
        return tdef.unflatten(fenced_bucket_apply(leaves, buckets, pubs))

    def _consume_param_buffer(self):
        """Straight-through consumption of the double-buffered params:
        the forward VALUE is the buffer (published by the PREVIOUS
        step's update phase — bit-equal to ``_compute_params(master)``
        by construction, both are deterministic in the master), while
        gradients flow exactly as if the forward had computed
        ``_compute_params(master)`` in-step — so the buffered step's
        backward (and its mid-backward sync points) is identical to the
        serial step's."""
        if self._consume_fn is not None:
            return self._consume_fn

        @jax.custom_vjp
        def use_buf(master, buf):
            return buf

        def fwd(master, buf):
            return buf, master

        def bwd(master, g):
            _, vjp = jax.vjp(self._compute_params, master)
            (gm,) = vjp(g)
            return gm, jax.tree.map(jnp.zeros_like, g)

        use_buf.defvjp(fwd, bwd)
        self._consume_fn = use_buf
        return use_buf

    def _refresh_param_buffer(self) -> None:
        """(Re)compute ``state['gathered']`` from the CURRENT master —
        at initialize and after any restore that replaces the master
        out-of-band (checkpoint load, universal load). The buffer is
        deliberately NEVER checkpointed: a recompute from the committed
        master is always consistent, so no checkpoint can capture a
        buffer one step stale relative to the weights it rode with."""
        if not self._param_buffer:
            return
        if self._compressed is None:
            # exact path: eager per-leaf cast + reshard — bit-equal to
            # the in-step publish (same cast, same layout) without a
            # per-engine XLA compile of a fused publish program at init.
            # A no-op cast (fp32 model, bf16 no-master) would ALIAS the
            # master leaf — the train step donates state, and a buffer
            # appearing under two donated leaves aborts Execute() —
            # so the same-dtype branch forces a real copy.
            dtype = jnp.dtype(self.precision)
            param_sh = self.policy.to_shardings(self.param_spec)

            def one(p, sh):
                x = p.astype(dtype) if p.dtype != dtype \
                    else jnp.array(p, copy=True)
                return jax.device_put(x, sh)

            with self.mesh:
                self.state["gathered"] = jax.tree.map(
                    one, self.state["master"], param_sh)
            return
        # wire path: the publish is a shard_map'd (possibly chunk-fenced
        # quantized) gather — jit it once per engine
        if "publish" not in self._compiled:
            self._compiled["publish"] = jax.jit(
                self._publish_tree_fn(),
                out_shardings=self._buffer_shardings())
        with self.mesh:
            self.state["gathered"] = self._compiled["publish"](
                self.state["master"])

    def _checkpoint_state(self) -> Dict[str, Any]:
        """The persisted view of train-step state: everything except the
        derived ``gathered`` double buffer (see
        ``_refresh_param_buffer`` — recomputed on every restore)."""
        if self._param_buffer and "gathered" in self.state:
            return {k: v for k, v in self.state.items() if k != "gathered"}
        return self.state

    # ------------------------------------------------------------------ #
    # data efficiency (curriculum / random-LTD / PLD / variable batch)
    # ------------------------------------------------------------------ #
    def _setup_data_efficiency(self) -> None:
        from deepspeed_tpu.runtime.data_pipeline import (
            CurriculumScheduler,
            RandomLTDScheduler,
        )
        from deepspeed_tpu.runtime.progressive_layer_drop import (
            ProgressiveLayerDrop,
        )

        pipe = self.mesh_manager.axis_size("pipe") > 1
        de = self.config.data_efficiency
        self._curriculum = None
        cur = self.config.curriculum
        de_cur = de.data_sampling.curriculum_learning
        if de_cur.enabled and not cur.enabled:
            logger.warning(
                "curriculum_learning.enabled is set under data_efficiency "
                "but data_efficiency.enabled / data_sampling.enabled are "
                "not — curriculum stays OFF (reference parent-gate "
                "semantics)")
        if cur.enabled:
            self._curriculum = CurriculumScheduler(cur.scheduler_dict())
            log_dist(f"curriculum learning active: {cur.schedule_type} "
                     f"{cur.min_difficulty}→{cur.max_difficulty}")

        self._ltd = None
        ltd = de.data_routing.random_ltd
        if ltd.enabled and not (de.enabled and de.data_routing.enabled):
            logger.warning(
                "random_ltd.enabled is set but data_efficiency.enabled / "
                "data_routing.enabled are not — random-LTD stays OFF "
                "(reference parent-gate semantics)")
        elif ltd.enabled:
            if pipe:
                logger.warning("random-LTD is not supported with pipeline "
                               "parallelism — disabled")
            else:
                self._ltd = RandomLTDScheduler(
                    {"random_ltd_schedule": ltd.random_ltd_schedule,
                     "max_value": ltd.max_value})
                log_dist("random-LTD active")

        self._pld = None
        pld = self.config.progressive_layer_drop
        if pld.enabled:
            if pipe:
                logger.warning("progressive layer drop is not supported with "
                               "pipeline parallelism — disabled")
            else:
                self._pld = ProgressiveLayerDrop(pld.theta, pld.gamma)
                log_dist(f"progressive layer drop active: theta={pld.theta} "
                         f"gamma={pld.gamma}")
        self._np_rng = np.random.default_rng(self.config.seed)

    def _n_layers(self) -> int:
        cfg = getattr(self.model_spec, "config", None)
        return getattr(cfg, "num_layers", 0) or 0

    # ------------------------------------------------------------------ #
    # telemetry (deepspeed_tpu/telemetry — README "Observability")
    # ------------------------------------------------------------------ #
    def _setup_telemetry(self) -> None:
        """Attach the engine to the process-wide metrics registry.

        Hot-path cost is a few dict/float ops per optimizer step (host
        side, no device fences). Everything priced — device_get of the
        last step's metrics, the one-off FLOPS cost analysis behind
        measured MFU — runs in a registry COLLECTOR, i.e. only when
        something scrapes ``telemetry.snapshot()`` / the ``/metrics``
        endpoint or the monitor bridge publishes."""
        tcfg = self.config.telemetry
        self._tm = None
        self._watchdog = None   # racelint: single-thread — every writer (telemetry setup/teardown and the SIGTERM handler, which CPython delivers between MAIN-thread bytecodes) runs on the main thread; the watchdog thread only calls beat()/check() through its own reference
        self._tm_bridge = None
        self._tm_tokens_per_step = 0
        # device-side overflow/non-finite skip counter, delta-folded into
        # the monotone train_skipped_steps_total (set before the enabled
        # gate: the guardian folds through this path too)
        self._tm_skips_seen = 0
        self._tm_fenced_best_s: Optional[float] = None
        self._tm_flops_cache: Optional[float] = None
        self._tm_flops_lock = make_lock("engine._tm_flops_lock")
        self._tm_owner_thread = threading.get_ident()
        from deepspeed_tpu import telemetry

        # the registry gate is process-wide (last engine's config wins, as
        # with the global mesh) — without this, "enabled": false would only
        # skip the engine's own instruments while fastgen/timer/comms kept
        # recording
        telemetry.get_registry().enabled = bool(tcfg.enabled)
        # tracer gate is process-wide too (same last-engine-wins rule);
        # configuring with enabled=False keeps every span() site at its
        # one-attribute-check disabled cost
        from deepspeed_tpu.telemetry import tracing as _tracing

        _tracing.configure(
            enabled=bool(tcfg.enabled and tcfg.tracing),
            capacity=tcfg.trace_buffer_events,
            sample_rate=tcfg.trace_sample_rate,
            dump_dir=tcfg.flight_dump_dir)
        if not tcfg.enabled:
            return

        self._tm = telemetry.get_registry()
        self._tm_steps = telemetry.counter(
            "train_steps_total", "completed optimizer steps")
        self._tm_tokens = telemetry.counter(
            "train_tokens_total", "tokens consumed by completed steps "
            "(global batch, all chips)")
        self._tm_step_hist = telemetry.histogram(
            "train_step_seconds", "host wall time around each step "
            "dispatch (async backends may record enqueue-only samples; "
            "throughput/MFU gauges use fenced windows instead)")

        def _on_fenced_window(duration: float, steps: int) -> None:
            # fires inside ThroughputTimer._close_window — training thread
            # only, AFTER a device fence, so per-step time is real
            per = duration / steps
            if self._tm_fenced_best_s is None \
                    or per < self._tm_fenced_best_s:
                self._tm_fenced_best_s = per

        self.tput_timer.window_hook = _on_fenced_window
        self._tm_heartbeat = telemetry.gauge(
            "train_heartbeat_timestamp_seconds",
            "unix time the last optimizer step completed")
        ref = weakref.ref(self)

        def _collect():
            eng = ref()
            if eng is None:
                return False   # engine gone — deregister (weakref idiom)
            eng._collect_telemetry()

        self._tm.add_collector(_collect)
        if tcfg.http_port >= 0 and jax.process_index() == 0:
            try:
                server = telemetry.start_metrics_server(tcfg.http_port)
                log_dist(f"telemetry /metrics endpoint: {server.url}")
            except OSError as e:
                # port in use (second run on the host) — observability must
                # never abort training; metrics stay scrapeable in-process
                logger.warning(
                    f"telemetry /metrics endpoint on port {tcfg.http_port} "
                    f"failed to start ({e}); continuing without it")
        if tcfg.stall_deadline_s > 0:
            on_stall = None
            action = self.config.fault_tolerance.on_stall
            if action in ("dump_trace", "checkpoint"):
                # escalate detection → response, both flavors leading
                # with a flight-recorder dump named after the last
                # completed span (the timeline that led INTO the stall);
                # "checkpoint" then saves the LAST COMPLETED state from
                # the watchdog thread (self.state is immutable jax
                # arrays, replaced only at step boundaries — a stalled
                # step by definition hasn't replaced it)
                wref = weakref.ref(self)

                def on_stall():
                    eng = wref()
                    if eng is None:
                        return
                    last = eng._tm.last_span if eng._tm is not None \
                        else None
                    _tracing.get_tracer().dump_flight(
                        "stall", note=last[0] if last else None)
                    if action == "checkpoint":
                        eng._emergency_save("stall")

            self._watchdog = telemetry.StallWatchdog(
                tcfg.stall_deadline_s, self._tm, on_stall=on_stall).start()

    def _fold_skipped_steps(self, skips: int, resync: bool = False) -> None:
        """Fold the device-side skip counter into the monotone
        ``train_skipped_steps_total`` (delta-based). Fed from two paths:
        the scrape-time collector (``resync=True`` — a guardian rollback
        restores an OLDER device counter, and the watermark must follow
        it down or post-rollback skips go uncounted) and the guardian's
        log-cadence observe (no resync — a skip must reach the metric
        even if a rollback rewinds the device counter before the next
        scrape)."""
        # locked: the scrape-time collector runs on the /metrics HTTP
        # thread concurrently with the guardian's training-thread fold —
        # an unlocked read-modify-write of the watermark double-counts
        with self._tm_skips_lock:
            self._fold_skips_locked(skips, resync=resync)

    def _fold_skips_locked(self, skips: int,
                           resync: bool = False) -> None:   # locked: _tm_skips_lock
        from deepspeed_tpu import telemetry

        delta = skips - self._tm_skips_seen
        if delta > 0:
            telemetry.counter(
                "train_skipped_steps_total",
                "optimizer steps skipped by the device-side "
                "non-finite guard (fp16 overflow + guardian "
                "bf16/fp32 sentinel)").inc(delta)
        if delta > 0 or resync:
            self._tm_skips_seen = skips

    def _chip_peak_flops(self) -> Optional[float]:
        from deepspeed_tpu.utils.chip_specs import chip_peak_tflops

        peak = chip_peak_tflops(
            getattr(jax.devices()[0], "device_kind", ""))
        # CPU backend etc.: no meaningful MFU referent → None
        return peak * 1e12 if peak else None

    def _measured_flops_per_step(self) -> float:
        """One-off XLA cost analysis of the train step (what the flops
        profiler reports; PER-DEVICE flops of the SPMD executable); cached
        under a lock so concurrent scrapes price at most one compile.
        Disable via ``telemetry.measure_mfu: false`` when the scrape-time
        compile is unwanted (e.g. a huge model behind a live endpoint)."""
        with self._tm_flops_lock:
            if self._tm_flops_cache is None:
                if not self.config.telemetry.measure_mfu:
                    self._tm_flops_cache = 0.0
                else:
                    try:
                        from deepspeed_tpu.profiling.flops_profiler import (
                            FlopsProfiler,
                        )

                        prof = FlopsProfiler(self)
                        self._tm_flops_cache = prof.profile_train_step()
                        if prof.cost_analysis_unavailable:
                            # this jax build's cost_analysis() yields no
                            # usable costs: the cached 0.0 means "unknown"
                            # — say so once instead of silently leaving
                            # train_mfu/train_model_flops_per_sec unset
                            logger.warning(
                                "telemetry MFU pricing: XLA cost analysis "
                                "unavailable on this jax build — "
                                "train_mfu/train_model_flops_per_sec stay "
                                "unset (not 0)")
                            from deepspeed_tpu import telemetry

                            telemetry.counter(
                                "telemetry_collector_errors_total",
                                "collector callbacks that raised during a "
                                "scrape").inc(
                                    error="cost_analysis_unavailable")
                    except Exception as e:
                        # cache the failure (retrying an expensive broken
                        # compile every scrape would be worse) but say so —
                        # a silent 0.0 makes the missing MFU gauge
                        # undiagnosable
                        self._tm_flops_cache = 0.0
                        logger.warning(
                            "telemetry MFU pricing failed — train_mfu/"
                            f"train_model_flops_per_sec stay unset: {e}")
                        from deepspeed_tpu import telemetry

                        telemetry.counter(
                            "telemetry_collector_errors_total",
                            "collector callbacks that raised during a "
                            "scrape").inc(error="mfu_pricing")
            return self._tm_flops_cache

    def _collect_telemetry(self) -> None:
        """Scrape-time collector: lazily-priced gauges (loss/grad-norm from
        the device metrics of the last step, tokens/s from the step-latency
        histogram, measured MFU from the FLOPS profiler).

        May run on the /metrics HTTP thread concurrent with training, so it
        avoids mutating engine state: the step histogram (registry-locked)
        gives steps/sec without touching ThroughputTimer's unsynchronized
        window state or fencing the device mid-step. The one exception is
        the FIRST MFU pricing, which compiles a cost-analysis copy of the
        step (lock-guarded, never stored on the engine; opt out with
        ``telemetry.measure_mfu: false``)."""
        from deepspeed_tpu import telemetry

        if self._last_metrics_dev:
            try:
                host = {k: float(jax.device_get(v))
                        for k, v in self._last_metrics_dev.items()}
            except Exception as e:   # deleted buffers between steps: skip
                logger.debug(f"last-step metric device_get failed "
                             f"({type(e).__name__}: {e})")
                host = {}
            for k in ("loss", "grad_norm", "lr", "loss_scale", "overflow"):
                if k in host:
                    telemetry.gauge(f"train_{k}").set(host[k])
        if "skips" in self.state:
            # device read + fold under ONE lock acquisition: a guardian
            # rollback resyncing the watermark between an unlocked read
            # and the fold would double-count the restored skips
            with self._tm_skips_lock:
                try:
                    skips = int(jax.device_get(self.state["skips"]))
                except Exception as e:   # deleted buffers: skip this scrape
                    logger.debug(f"skip-counter device_get failed "
                                 f"({type(e).__name__}: {e})")
                    skips = None
                if skips is not None:
                    self._fold_skips_locked(skips, resync=True)
        expensive = getattr(self._tm, "collecting_expensive", True)
        if expensive and threading.get_ident() == self._tm_owner_thread:
            # only the engine's own thread may close the fenced throughput
            # window (it fences the device and mutates the timer's
            # unsynchronized window state); HTTP-thread scrapes reuse the
            # last fenced sample
            self.tput_timer.avg_samples_per_sec()
        # best FENCED per-step wall (bench best-window methodology): the
        # un-fenced dispatch walls in the histogram can be enqueue-only
        # under async dispatch, and an all-time mean would fold warmup/
        # compile into the rate
        steps_per_sec = (1.0 / self._tm_fenced_best_s
                         if self._tm_fenced_best_s else 0.0)
        if steps_per_sec > 0 and self._tm_tokens_per_step:
            telemetry.gauge(
                "train_tokens_per_sec", "global token throughput from the "
                "best fenced throughput window").set(
                steps_per_sec * self._tm_tokens_per_step)
        if steps_per_sec > 0 and expensive:
            flops = self._measured_flops_per_step()
            if flops:
                # cost analysis reports the per-device SPMD executable's
                # flops, so rate/peak are already per-chip — no device_count
                # factor (the same per-chip accounting bench.py's mfu uses)
                telemetry.gauge(
                    "train_model_flops_per_sec",
                    "measured per-device FLOPS rate (XLA cost analysis x "
                    "step rate)").set(flops * steps_per_sec)
                peak = self._chip_peak_flops()
                if peak:
                    telemetry.gauge(
                        "train_mfu", "model FLOPS utilization vs chip bf16 "
                        "peak").set(flops * steps_per_sec / peak)

    def collective_ledger(self, fold: bool = True,
                          seq_len: Optional[int] = None):
        """Compiled-collective ledger of the live fused train step (the
        execution-observatory hook): every all-reduce / reduce-scatter /
        all-gather / all-to-all / collective-permute XLA's partitioner
        emitted for this engine's ZeRO stage, with bytes, replica groups,
        and issuing-subsystem attribution. ``fold=True`` publishes the
        ``comm_ledger_*`` metrics (README "Execution observatory").
        Cached per engine — the one-off lowering compile is priced on the
        first call only. Returns a
        :class:`~deepspeed_tpu.profiling.observatory.CollectiveLedger`.
        """
        from deepspeed_tpu.profiling.observatory import ledger_for_engine

        return ledger_for_engine(self, fold=fold, seq_len=seq_len)[0]

    def step_report(self, **kwargs) -> Dict[str, Any]:
        """Roofline step report (ledger + overlap + memory vs the ZeRO
        partitioning prediction + per-phase bound verdicts) — the
        ``tools/step-report`` CLI in library form."""
        from deepspeed_tpu.profiling.observatory import step_report

        return step_report(self, **kwargs)

    def lint_step(self, contract: Optional[str] = None,
                  seq_len: Optional[int] = None) -> List:
        """hlolint over THIS engine's lowered fused train step — the
        ``tools/hlolint --live`` path in library form. The linted
        program is the one ``_dispatch_train_step`` runs (the
        observatory's ``ledger_for_engine`` mirrors
        ``_select_step_builder`` and caches the lowering), and the lint
        config comes from the engine's resolved wire format, overlap
        plan, and bucket plan. ``contract`` names a committed contract
        JSON to enforce on top of the structural rules. Returns the
        violations (empty = clean)."""
        from deepspeed_tpu.analysis.hlolint import lint_engine

        return lint_engine(self, contract=contract, seq_len=seq_len)

    def lint_memory(self, contract: Optional[str] = None,
                    seq_len: Optional[int] = None,
                    hbm_budget_bytes: Optional[float] = None) -> List:
        """memlint over THIS engine's lowered fused train step — the
        ``tools/memlint --live`` path in library form (donation/aliasing
        verification, residency vs the ZeRO partitioning-math
        prediction, the OOM pre-flight at ``hbm_budget_bytes``, plus a
        committed memory ``contract`` when named). The linted program
        is the SAME cached lowering ``lint_step``/the ledger read — a
        memory lint never pays a second compile. Returns the
        violations (empty = clean)."""
        from deepspeed_tpu.analysis.memlint import lint_engine

        return lint_engine(self, contract=contract, seq_len=seq_len,
                           hbm_budget_bytes=hbm_budget_bytes)

    @staticmethod
    def _count_tokens(stacked: PyTree) -> int:
        """Token count of one stacked step window (global batch)."""
        arr = stacked
        if isinstance(stacked, dict):
            # engine-injected control keys (_pld_keep, _random_ltd_idx,
            # lr_scale) sort first in the leaf order and are NOT tokens —
            # prefer the conventional token keys, then any data key
            for key in ("tokens", "input_ids"):
                if key in stacked:
                    arr = stacked[key]
                    break
            else:
                data_keys = sorted(k for k in stacked
                                   if not str(k).startswith("_")
                                   and k != "lr_scale")
                arr = stacked[data_keys[0]] if data_keys else None
        if arr is None:
            return 0
        # metadata only — np.asarray on a jax array would be a full D2H copy
        size = getattr(arr, "size", None)
        return int(size) if size is not None else int(np.asarray(arr).size)

    def shutdown_telemetry(self) -> None:
        """Stop the stall watchdog thread. Called on engine GC too —
        otherwise every watchdog-armed run that simply FINISHES training
        would log a false stall (the watchdog can't distinguish 'done'
        from 'stuck'); long-lived processes that keep the engine alive
        after the last step should call this explicitly."""
        if self._watchdog is not None:
            self._watchdog.stop()
            self._watchdog = None

    def __del__(self):
        try:
            self.shutdown_telemetry()
        # interpreter teardown: attributes may already be gone, and
        # raising from __del__ only prints noise
        except Exception:   # dslint: disable=silent-except
            pass

    def _inject_data_efficiency(self, stacked: PyTree, gas: int) -> PyTree:
        """Add per-micro PLD keep masks / random-LTD kept-token indices to
        the stacked batch dict (underscore keys — replicated, consumed by the
        model spec's loss_fn)."""
        if self._ltd is None and self._pld is None:
            return stacked
        if not isinstance(stacked, dict):
            stacked = {"tokens": stacked}
        else:
            stacked = dict(stacked)
        if self._pld is not None:
            from deepspeed_tpu.runtime.progressive_layer_drop import (
                layer_keep_probs,
            )

            L = self._n_layers()
            theta = self._pld.update_state(self.global_steps)
            probs = np.asarray(jax.device_get(layer_keep_probs(theta, L)))
            stacked["_pld_keep"] = (
                self._np_rng.random((gas, L)) < probs[None]
            ).astype(np.float32)
        if self._ltd is not None:
            seq_len = np.asarray(stacked["tokens"]).shape[-1]
            kept = min(self._ltd.get_kept_tokens(self.global_steps), seq_len)
            idx = np.stack([
                np.sort(self._np_rng.choice(seq_len, kept, replace=False))
                for _ in range(gas)]).astype(np.int32)
            stacked["_random_ltd_idx"] = idx
        return stacked

    # ------------------------------------------------------------------ #
    # state construction
    # ------------------------------------------------------------------ #
    def _state_shardings(self) -> Dict[str, Any]:
        to_sh = self.policy.to_shardings
        master_sh = to_sh(self.master_spec)
        moment_sh = master_sh
        moment_shapes = self._shapes
        if self._trainable_mask is not None:
            from deepspeed_tpu.utils.tree import prune_tree

            moment_sh = prune_tree(master_sh, self._trainable_mask)
            moment_shapes = prune_tree(self._shapes, self._trainable_mask)
        # optimizer state leaves that mirror the param shape inherit its
        # sharding; auxiliary leaves of other shapes (e.g. OnebitLamb's
        # per-layer frozen trust scalars) are replicated.
        rep = NamedSharding(self.mesh, P())
        opt_shapes = jax.eval_shape(self.optimizer.init, self._shapes)
        moment_structure = jax.tree.structure(moment_shapes)
        opt_sh = {}
        for name in self.optimizer.moment_names:
            sub = opt_shapes[name]
            if jax.tree.structure(sub) == moment_structure:
                opt_sh[name] = jax.tree.map(
                    lambda os, sh, ms: sh if os.shape == ms.shape else rep,
                    sub, moment_sh, moment_shapes)
            else:
                # schedule scalars etc. that don't mirror the param tree
                opt_sh[name] = jax.tree.map(lambda _: rep, sub)
        opt_sh["step"] = NamedSharding(self.mesh, P())
        if self._onebit_wire:
            axes = self._dp_manual_axes
            row = axes if len(axes) > 1 else axes[0]
            opt_sh["worker_error"] = jax.tree.map(
                lambda _: NamedSharding(self.mesh, P(row)),
                opt_sh["worker_error"])
        sh = {"step": NamedSharding(self.mesh, P()), "master": master_sh, "opt": opt_sh}
        if self.fp16_enabled:
            rep = NamedSharding(self.mesh, P())
            sh["scaler"] = jax.tree.map(lambda _: rep, self.scaler.init_state())
            sh["skips"] = rep
        elif self._nonfinite_guard:
            sh["skips"] = NamedSharding(self.mesh, P())
        if self._compressed is not None and self._compressed.get("loco"):
            axes = self._dp_manual_axes
            row = axes if len(axes) > 1 else axes[0]
            sh["loco_err"] = jax.tree.map(
                lambda s: NamedSharding(
                    self.mesh, P(row, *([None] * len(s.shape)))),
                self._shapes)
        if self._param_buffer:
            # double-buffered gathered params (step-phase overlap):
            # published at step END, consumed by the NEXT forward
            sh["gathered"] = self._buffer_shardings()
        return sh

    @staticmethod
    def _to_host_shardings(sh_tree: Any) -> Any:
        """Same layout, pinned host memory (ZeRO-Offload storage tier)."""
        return jax.tree.map(
            lambda s: s.with_memory_kind("pinned_host"), sh_tree,
            is_leaf=lambda x: isinstance(x, NamedSharding))

    def _make_state(self, rng) -> Dict[str, Any]:
        master = self.model_spec.init_fn(rng)
        if self.precision == "bfloat16" and not self.config.bf16.fp32_master:
            # no-fp32-master mode: the "master" IS the bf16 compute tree;
            # optimizer updates still compute in fp32 per-leaf (cast inside
            # the fused update — nothing fp32 is materialized tree-wide)
            master = jax.tree.map(
                lambda p: p.astype(jnp.bfloat16)
                if jnp.issubdtype(p.dtype, jnp.floating) else p, master)
        state = {
            "step": jnp.zeros((), jnp.int32),
            "master": master,
            "opt": self.optimizer.init(master),
        }
        if self._onebit_wire:
            # per-worker compression error: one row per DP rank (the
            # reference's worker_error buffers are per-rank by construction;
            # under SPMD that is a leading sharded world dim)
            state["opt"]["worker_error"] = jax.tree.map(
                lambda e: jnp.zeros((self._dp_manual_world,) + e.shape,
                                    e.dtype),
                state["opt"]["worker_error"])
        if self.fp16_enabled:
            state["scaler"] = self.scaler.init_state()
            state["skips"] = jnp.zeros((), jnp.int32)
        elif self._nonfinite_guard:
            # bf16/fp32 sentinel: same device-side skip counter as fp16
            state["skips"] = jnp.zeros((), jnp.int32)
        if self._compressed is not None and self._compressed.get("loco"):
            # per-rank LoCo residuals: leading sharded world dim (same
            # pattern as the 1-bit worker_error buffers); full-gradient
            # shape per rank, fp32
            state["loco_err"] = jax.tree.map(
                lambda s: jnp.zeros(
                    (self._dp_manual_world,) + s.shape, jnp.float32),
                self._shapes)
        return state

    def _master_host_shardings(self) -> Any:
        """The offload_param storage tier: master layout, pinned host."""
        return self._to_host_shardings(
            self.policy.to_shardings(self.master_spec))

    def _park_master(self) -> None:
        """Move the master to its pinned-host tier (offload_param).

        Runs at the JIT BOUNDARY: in-program pinned-host OUTPUT annotations
        don't partition under SPMD ("side-effect ops cannot be
        replicated"), while host-resident INPUTS do — so each step takes
        the host master in (the model streams layer slices H2D inside its
        layer scan), produces the updated master on device, and this moves
        it back out."""
        self.state["master"] = jax.device_put(self.state["master"],
                                              self._master_host_shardings())

    def _unpark_master(self) -> None:
        """Boundary-swap mode (no in-step streaming): move the parked
        master onto device before the step."""
        self.state["master"] = jax.device_put(
            self.state["master"],
            self.policy.to_shardings(self.master_spec))

    def _materialize_master(self) -> None:
        """Direct-use paths (eval/predict/eager forward/step, fp32
        consolidation) read ``state['master']`` as a plain device tree —
        restore it from whichever offload tier currently holds it
        (NVMe files and/or pinned host)."""
        if self._offload_param_nvme and self._param_swapper is not None:
            self._param_swapper.swap_in_params()
        if self._offload_param:
            from deepspeed_tpu.utils.memory import is_host_resident

            leaves = jax.tree.leaves(self.state["master"])
            if leaves and is_host_resident(leaves[0]):
                self._unpark_master()

    def _ensure_master_tier_for_step(self) -> None:
        """Put the master where the compiled step expects it: pinned host
        for the streaming step (whose in_shardings declare host inputs —
        a direct-use path may have materialized it on device), device for
        boundary-swap mode."""
        if not self._offload_param:
            return
        if self._offload_param_stream:
            from deepspeed_tpu.utils.memory import is_host_resident

            leaves = jax.tree.leaves(self.state["master"])
            if leaves and not is_host_resident(leaves[0]):
                self._park_master()
        else:
            self._unpark_master()

    def _init_state(self) -> Dict[str, Any]:
        shardings = self._state_shardings()
        # the gathered double buffer is DERIVED state — built by
        # _refresh_param_buffer right after init, never by _make_state
        shardings.pop("gathered", None)
        init = jax.jit(self._make_state, out_shardings=shardings)
        with self.mesh:
            state = init(self._init_rng)
        if self._offload_param:
            state["master"] = jax.device_put(state["master"],
                                             self._master_host_shardings())
        return state

    # ------------------------------------------------------------------ #
    # jitted step builders
    # ------------------------------------------------------------------ #
    def _compute_param_leaf(self, p, sh):
        """THE per-leaf master → compute-param math (cast + constrain).
        ``_compute_params`` and the per-bucket fenced publish
        (``_publish_leaf_fns``) must stay ONE implementation: the
        double-buffered forward consumes the publish VALUE while
        gradients flow through ``_compute_params``, so any drift
        between them silently breaks the buffer's bit-equality
        contract."""
        return jax.lax.with_sharding_constraint(
            p.astype(jnp.dtype(self.precision)), sh)

    def _compute_params(self, master: PyTree) -> PyTree:
        """Cast fp32 master → compute dtype, constrained to the param sharding
        (stage 3: sharded → XLA gathers per use; else replicated over data).

        offload_param: by the time this runs, the engine has already
        streamed the host master onto device in the sharded layout
        (``_loss_and_grads``), so the normal cast/constrain applies."""
        param_sh = self.policy.to_shardings(self.param_spec)
        return jax.tree.map(self._compute_param_leaf, master, param_sh)

    def _constrain_grads(self, grads: PyTree) -> PyTree:
        grad_sh = self.policy.to_shardings(self.grad_spec)
        if getattr(self, "_overlap", None) is None \
                or not self._overlap.enabled:
            return jax.tree.map(jax.lax.with_sharding_constraint, grads,
                                grad_sh)
        return self._constrain_grads_bucketed(grads, grad_sh)

    def _constrain_grads_bucketed(self, grads: PyTree,
                                  grad_sh: PyTree) -> PyTree:
        """Bucketed gradient sync: top-level leaves grouped into
        ``reduce_bucket_size``-bounded buckets (element counts, the
        reference's unit; reversed tree-flatten order — the
        backward-completion approximation) and constrained
        bucket-by-bucket behind ``optimization_barrier`` fences, so the
        collectives stay size-bounded and ordered in the lowered program
        instead of fusing into one step-end sync. Identical values —
        the fences and constraints are identities (allclose-pinned in
        tests/unit/test_overlap.py)."""
        from deepspeed_tpu.parallel.overlap import (
            fenced_bucket_apply,
            leaf_count,
            plan_buckets,
        )

        leaves, treedef = jax.tree.flatten(grads)
        sh_leaves = jax.tree.leaves(grad_sh)
        if len(leaves) != len(sh_leaves) or not leaves:
            return jax.tree.map(jax.lax.with_sharding_constraint, grads,
                                grad_sh)
        sizes = [leaf_count(x.shape) for x in leaves]
        buckets = plan_buckets(sizes, self._overlap.reduce_bucket_elems)
        fns = [lambda x, s=s: jax.lax.with_sharding_constraint(x, s)
               for s in sh_leaves]
        return jax.tree.unflatten(
            treedef, fenced_bucket_apply(leaves, buckets, fns))

    def _loss_and_grads(self, master: PyTree, batch: PyTree, scale,
                        params_buf: Optional[PyTree] = None
                        ) -> Tuple[jax.Array, PyTree]:
        if self._offload_param:
            # H2D stream OUTSIDE the autodiff: differentiating w.r.t. the
            # host-resident master would put every cotangent in host space
            # (the device_put VJP transposes to D2H) and drag the whole
            # backward into host memory. Streaming first keeps grads on
            # device; the stream lands in the ZeRO-3 SHARDED layout (f32
            # master never replicates), and the updated master is parked
            # back to pinned host at the jit boundary (_park_master).
            from deepspeed_tpu.utils.memory import stream_to_shardings

            master = stream_to_shardings(
                master, self.policy.to_shardings(self.master_spec))
        # schedules with an explicit backward (1F1B pipeline) return grads
        # directly — autodiff over the loss would rebuild the O(M)-memory
        # GPipe reverse wavefront
        fn = getattr(self.model_spec, "loss_and_grads_fn", None)
        if fn is not None:
            out = fn(self._compute_params(master), batch, scale)
            if out is not None:
                loss, grads = out
                grads = jax.tree.map(
                    lambda g, m: g.astype(m.dtype), grads, master)
                return loss, self._constrain_grads(grads)

        def scaled_loss(m):
            if params_buf is not None:
                # double-buffered forward (step-phase overlap): consume
                # the buffer published by the PREVIOUS step's update
                # phase; gradients still flow through _compute_params
                # (straight-through — see _consume_param_buffer)
                params = self._consume_param_buffer()(m, params_buf)
            else:
                params = self._compute_params(m)
            loss = self.model_spec.loss_fn(params, batch)
            return loss * scale if scale is not None else loss

        loss, grads = jax.value_and_grad(scaled_loss)(master)
        if scale is not None:
            loss = loss / scale
        return loss, self._constrain_grads(grads)

    def _lr_at(self, step):
        if self.lr_scheduler is not None:
            return self.lr_scheduler.lr_at(step)
        return jnp.asarray(self.optimizer.lr, jnp.float32)

    def _apply_update(self, state: Dict[str, Any], grads: PyTree,
                      grad_scale, lr_mult=None
                      ) -> Tuple[Dict[str, Any], Dict[str, jax.Array]]:
        """Unscale, clip, (maybe skip on overflow), optimizer update.

        Step-phase overlap (``overlap_step``; 2004.13336): the update's
        outputs are restructured into per-bucket fenced groups in
        backward-completion order (``_fence_update_buckets``) so each
        bucket's apply — and, double-buffered, its param publish —
        leaves the critical path the moment its gradients land instead
        of waiting for the whole tree; the publish lands in
        ``state['gathered']`` for the NEXT step's forward. The skip
        branch (fp16 overflow / guardian non-finite) skips every
        bucket's update coherently (ONE ``lax.cond`` around the whole
        phase) and republishes the UNCHANGED buffer."""
        grads = jax.tree.map(lambda g: g.astype(jnp.float32) / grad_scale, grads)
        lr = self._lr_at(state["step"])
        if lr_mult is not None:
            # variable-batch LR scaling (reference
            # variable_batch_size_and_lr.py scale_lr)
            lr = lr * lr_mult
        if self._trainable_mask is not None:
            from deepspeed_tpu.utils.tree import prune_tree

            norm = global_grad_norm(prune_tree(grads, self._trainable_mask))
        else:
            norm = global_grad_norm(grads)
        if self.config.gradient_clipping > 0:
            grads = clip_by_global_norm(grads, self.config.gradient_clipping, norm)

        def _stream_master(master):
            if not self._offload_param:
                return master
            from deepspeed_tpu.utils.memory import stream_to_shardings

            return stream_to_shardings(
                master, self.policy.to_shardings(self.master_spec))

        buffered = self._param_buffer and "gathered" in state
        step_fenced = self._step_overlap

        def do_update(operand):
            master, opt, g = operand
            new_master, new_opt = self.optimizer.update(
                g, opt, _stream_master(master), lr=lr)
            if step_fenced:
                with jax.named_scope("zero_param_update"):
                    new_master, new_opt = self._fence_update_buckets(
                        new_master, new_opt)
            return new_master, new_opt

        def skip_update(operand):
            master, opt, _ = operand
            # both lax.cond branches must produce the same memory space
            return _stream_master(master), opt

        if self.fp16_enabled:
            overflow = jnp.logical_not(jnp.isfinite(norm))
            new_master, new_opt = jax.lax.cond(
                overflow, skip_update, do_update,
                (state["master"], state["opt"], grads))
            new_scaler = self.scaler.update(state["scaler"], overflow)
        elif self._nonfinite_guard:
            # guardian numerics sentinel (config "guardian"): the fp16
            # skip-update lax.cond extended to bf16/fp32 — no scaler, pure
            # skip. A non-finite gradient step must never touch the
            # weights; the same device-side isfinite reduction (the norm
            # is already computed for clipping) decides, the same
            # device-side `skips` counter records it, and no host sync is
            # added to the hot path.
            overflow = jnp.logical_not(jnp.isfinite(norm))
            new_master, new_opt = jax.lax.cond(
                overflow, skip_update, do_update,
                (state["master"], state["opt"], grads))
            new_scaler = None
        else:
            overflow = jnp.asarray(False)
            new_master, new_opt = do_update((state["master"], state["opt"], grads))
            new_scaler = None
        new_gathered = None
        if buffered:
            # the deferred publish runs OUTSIDE the skip cond: the
            # publish is deterministic in the master, so a skipped step
            # republishes the UNCHANGED buffer bit-equal (master didn't
            # move) — and the guarded program keeps the unguarded one's
            # collective shape (a publish inside a cond branch forces
            # GSPMD resharding around the branch; the guardian's
            # zero-added-collectives pin forbids that)
            with jax.named_scope("zero_param_update"):
                new_gathered = self._publish_fenced(new_master)

        new_state = {"step": state["step"] + 1, "master": new_master, "opt": new_opt}
        if new_gathered is not None:
            new_state["gathered"] = new_gathered
        if new_scaler is not None:
            new_state["scaler"] = new_scaler
        if "skips" in state:
            new_state["skips"] = state["skips"] + overflow.astype(jnp.int32)
        metrics = {"grad_norm": norm, "lr": lr,
                   "overflow": overflow.astype(jnp.float32)}
        if self.fp16_enabled:
            metrics["loss_scale"] = new_state["scaler"].scale
        return new_state, metrics

    def _grad_accum_dtype(self):
        """GAS accumulator dtype: fp32 default; data_types.grad_accum_dtype
        opts into bf16 (reference data_types section, including its
        "bf16"/"fp16"/"fp32" spellings). Shared by every step builder —
        at multi-B params the fp32 grad buffer IS the HBM ceiling."""
        name = self.config.data_types.grad_accum_dtype
        alias = {"bf16": "bfloat16", "fp16": "float16", "fp32": "float32"}
        return jnp.dtype(alias.get(name, name) if name else jnp.float32)

    @staticmethod
    def accumulate_microbatches(micro_fn, zeros, batch, gas,
                                constrain=lambda x: x, extra0=None):
        """Shared GAS loop: accumulate grads IN THE DTYPE OF ``zeros``
        (callers build zeros via ``_grad_accum_dtype()``; fp32 default)
        from ``micro_fn(mb) -> (loss, grads)`` over the leading micro-batch
        dim (scan for gas>1).
        Used by the fused step, the host-step runner, and available to
        custom step builders — keep ONE copy of these semantics.

        ``extra0``: optional extra carry threaded through the micros (LoCo
        residuals); micro_fn is then called as ``micro_fn(mb, extra) ->
        (loss, grads, extra)`` and the return gains the final extra."""
        with_extra = extra0 is not None

        def micro(carry, mb):
            if with_extra:
                acc, extra = carry
                loss, grads, extra = micro_fn(mb, extra)
            else:
                acc = carry
                loss, grads = micro_fn(mb)
            acc = jax.tree.map(
                lambda a, g: a + g.astype(a.dtype), acc, grads)
            acc = constrain(acc)
            return ((acc, extra) if with_extra else acc), loss

        carry0 = (zeros, extra0) if with_extra else zeros
        if gas == 1:
            squeezed = jax.tree.map(lambda x: x[0], batch)
            carry, loss = micro(carry0, squeezed)
        else:
            carry, losses = jax.lax.scan(micro, carry0, batch)
            loss = jnp.mean(losses)
        if with_extra:
            (grads_sum, extra) = carry
            return grads_sum, loss, extra
        return carry, loss

    def _train_step_fn(self, gas: int):
        """The raw (unjitted) fused-step body — shared by the single-step
        jit and the multi-step ``lax.scan`` wrapper."""

        acc_dt = self._grad_accum_dtype()

        def train_step(state, batch):
            scale = state["scaler"].scale if self.fp16_enabled else None
            zeros = jax.tree.map(
                lambda s: jnp.zeros(s.shape, acc_dt), self._shapes)
            zeros = self._constrain_grads(zeros)

            def micro_fn(mb):
                # chaos train/nan_grads injection (testing/chaos.py): the
                # per-micro `_nan_grads` flag rides the batch dict only when
                # the fault is armed — absent, the traced program is
                # byte-identical to the uninjected step
                flag = None
                if isinstance(mb, dict) and "_nan_grads" in mb:
                    mb = dict(mb)
                    flag = mb.pop("_nan_grads")
                loss, grads = self._loss_and_grads(
                    state["master"], mb, scale,
                    params_buf=(state.get("gathered")
                                if self._param_buffer else None))
                if flag is not None:
                    bad = jnp.where(flag > 0, jnp.nan, 1.0)
                    grads = jax.tree.map(
                        lambda g: g * bad.astype(g.dtype), grads)
                return loss, grads

            grads_sum, mean_loss = self.accumulate_microbatches(
                micro_fn, zeros, batch, gas, constrain=self._constrain_grads)

            grad_scale = jnp.float32(gas) * (scale if scale is not None else 1.0)
            lr_mult = None
            if isinstance(batch, dict) and "lr_scale" in batch:
                lr_mult = jnp.mean(batch["lr_scale"].astype(jnp.float32))
            new_state, metrics = self._apply_update(state, grads_sum,
                                                    grad_scale, lr_mult)
            metrics["loss"] = mean_loss
            return new_state, metrics

        return train_step

    def _in_state_shardings(self) -> Dict[str, Any]:
        """Input-side state shardings: offload_param parks the master in
        pinned host BETWEEN steps, so the step's jit must be told its
        master inputs are host-resident EXPLICITLY — trace-time memory-
        space detection (is_host_resident → in-program H2D streams) only
        sees spaces declared via in_shardings, not ones inferred from
        committed arrays."""
        sh = self._state_shardings()
        if self._offload_param_stream:
            sh = dict(sh, master=self._master_host_shardings())
        return sh

    def _build_train_step(self, gas: int):
        """Fused step: scan grad accumulation over [gas, ...] batch inside jit."""
        state_sh = self._state_shardings()
        # batch shardings are committed on the inputs by _shard_batch; jit honors
        # them without an explicit in_shardings entry.
        # streaming offload: donation would alias the pinned-host master
        # input to the device-resident master output (XLA rejects the
        # cross-memory-kind alias). Cost: the moments lose donation too
        # (state donates whole) — transiently double moment buffers; when
        # that matters, compose with offload_optimizer, whose tier moves
        # them off-device entirely.
        donate = () if self._offload_param_stream else (0,)
        return jax.jit(self._train_step_fn(gas),
                       in_shardings=(self._in_state_shardings(), None),
                       out_shardings=(state_sh, None),
                       donate_argnums=donate)

    def _build_train_multi(self, gas: int, n_steps: int):
        """``n_steps`` fused steps in ONE dispatch: ``lax.scan`` over the
        step body on a [n_steps, gas, ...] batch. On TPU each dispatch pays
        host-side latency (dispatch gaps; two orders worse through a remote
        tunnel) — pipelining steps device-side removes it. The LR schedule
        advances inside the scan via ``state['step']``."""
        step = self._train_step_fn(gas)

        def multi(state, batches):
            if self._offload_param_stream:
                # the scan carry must keep ONE memory space: stream the
                # pinned-host master onto device before the scan (it stays
                # device-resident for the whole fused window — the between-
                # step host parking only happens at the call boundary)
                from deepspeed_tpu.utils.memory import stream_to_shardings

                state = dict(state, master=stream_to_shardings(
                    state["master"],
                    self.policy.to_shardings(self.master_spec)))
            state, ms = jax.lax.scan(step, state, batches)
            metrics = jax.tree.map(lambda x: x[-1], ms)
            metrics["loss"] = jnp.mean(ms["loss"])
            return state, metrics

        state_sh = self._state_shardings()
        donate = () if self._offload_param_stream else (0,)
        # offload_param_stream parks the master pinned-host and streams
        # slices in-program: the device state is a transient copy the
        # host master outlives, so NOT donating is the deliberate
        # double-buffer there  # dslint: disable=donation
        return jax.jit(multi,
                       in_shardings=(self._in_state_shardings(), None),
                       out_shardings=(state_sh, None),
                       donate_argnums=donate)

    # ------------------------------------------------------------------ #
    # wire-format step builders (ZeRO++ qwZ/qgZ/LoCo, 1-bit transport)
    # ------------------------------------------------------------------ #
    def _manual_batch_spec(self, ndim: int) -> P:
        axes = self._dp_manual_axes
        row = axes if len(axes) > 1 else axes[0]
        return P(None, row, *([None] * (ndim - 2)))

    def _wire_format(self) -> str:
        """The resolved wire format of the fused step — one of ``exact``
        / ``qz`` / ``qz+loco`` / ``onebit``. With the overlap scheduler
        this is the OTHER axis of the step-builder pipeline; the single
        source for builder selection (``_select_step_builder``) and the
        overlap plan's ``wire_format`` field."""
        if self._onebit_wire:
            return "onebit"
        if self._compressed:
            return "qz+loco" if self._compressed.get("loco") else "qz"
        return "exact"

    def _select_step_builder(self, gas: int):
        """ONE selection point of the step-builder pipeline: wire format
        × overlap compose inside each builder rather than forking here.
        Mirrored by the observatory's ``ledger_for_engine`` so the
        ledgered program is always the dispatched program."""
        wire = self._wire_format()
        if wire == "onebit":
            return self._build_train_step_onebit(gas)
        if wire != "exact":
            return self._build_train_step_wire(gas)
        return self._build_train_step(gas)

    def _build_train_step_wire(self, gas: int):
        """ZeRO++ wire-compressed step (qwZ/qgZ, optional LoCo).

        Two formulations share ONE wire protocol
        (``parallel/compressed.py``):

        * **straight-through** — the param gather's ``custom_vjp`` emits
          the per-leaf quantized reduce inside autodiff; lowest memory.
          Used when neither LoCo nor the overlap scheduler needs the
          reduce outside the vjp.
        * **bucketed** — grads w.r.t. the FULL gathered params, reduce
          outside the vjp through ``reduce_bucket_size``-bounded fenced
          buckets; composes with the overlap scheduler and carries the
          LoCo residuals.
        """
        if not self._compressed.get("loco") and not self._overlap.enabled:
            return self._build_train_step_qz(gas)
        return self._build_train_step_bucketed_wire(gas)

    def _build_train_step_bucketed_wire(self, gas: int):
        """The composed wire×overlap step (and the LoCo home; reference
        ``coalesced_collectives.py:31/:81`` + the PR-8 scheduler).

        Grads are taken w.r.t. the FULL gathered params (no collective
        inside autodiff) and the wire reduce runs OUTSIDE the vjp — the
        formulation LoCo already required (its residual must persist
        across reduces), now also the seam where overlap composes:

        * gradient leg: ``compressed.reduce_tree_bucketed`` — per-bucket
          qgZ int8 reduce-scatter, LoCo residual slices riding the SAME
          chained ``optimization_barrier`` fences as the exact path's
          bucketed constraints (residuals stay keyed per leaf, so
          re-bucketing never relayouts LoCo state);
        * parameter leg: ``compressed.chunked_gather_tree_fn`` — the
          qwZ all-gathers follow the layer-chunk plan one fence apart,
          so the chunked scan's next chunk can gather (int8 when qwZ,
          hpZ subgroups riding each leaf's spec) under the current
          chunk's compute;
        * mid-backward sync: the model spec was rebuilt with
          ``overlap.manual_chunk_sync`` (ordering fence — named
          constraints don't exist in a shard_map manual region).

        Memory: a transient full-gradient tree per rank (stage-2-like)
        plus the fp32 residual buffers when LoCo."""
        from jax import shard_map

        from deepspeed_tpu.parallel import compressed as C

        axes = self._dp_manual_axes
        world = self._dp_manual_world
        dtype = jnp.dtype(self.precision)
        mode = self._compressed
        loco = bool(mode.get("loco"))
        sizes = dict(self.mesh.shape)
        overlap_on = self._overlap.enabled
        bucket_elems = self._overlap.reduce_bucket_elems if overlap_on \
            else None
        bounds = (self._overlap_plan.get("chunk_bounds") or []) \
            if overlap_on else []
        buffered = self._param_buffer
        if len(bounds) > 1:
            gather_tree = C.chunked_gather_tree_fn(
                self.master_spec, axes, world, dtype,
                quant_weights=mode["quant_weights"], chunk_bounds=bounds,
                axis_sizes=sizes)
        else:
            gather_tree = C.gather_tree_fn(
                self.master_spec, axes, world, dtype,
                quant_weights=mode["quant_weights"], quant_grads=False,
                axis_sizes=sizes)  # bwd unused: grads w.r.t. FULL params
        master_manual = jax.tree.map(
            lambda s: C.manual_spec(s, axes), self.master_spec,
            is_leaf=lambda x: isinstance(x, P))
        rep_specs = jax.tree.map(lambda s: P(), self.master_spec,
                                 is_leaf=lambda x: isinstance(x, P))
        row = axes if len(axes) > 1 else axes[0]

        acc_dt = self._grad_accum_dtype()

        def core(master_local, err0, batch_local, scale,
                 params_full=None):
            zeros = jax.tree.map(
                lambda x: jnp.zeros(x.shape, acc_dt), master_local)
            # loop-invariant: ONE (possibly quantized, possibly chunk-
            # fenced) param gather per step, not per micro — and with
            # the double buffer (overlap_step) ZERO: the forward
            # consumes the params the PREVIOUS step's update phase
            # published (bit-equal: the publish runs the same wire on
            # the same master), moving the gather off this step's
            # critical path entirely
            params = params_full if params_full is not None \
                else gather_tree(master_local)

            def full_loss(pf, b):
                return self.model_spec.loss_fn(pf, b) * scale

            if loco:
                def micro(b, err):
                    loss, gfull = jax.value_and_grad(full_loss)(params, b)
                    gl, err = C.reduce_tree_bucketed(
                        gfull, self.master_spec, axes, world, sizes,
                        bucket_elems=bucket_elems, err_tree=err)
                    return loss, gl, err

                grads_sum, losses_mean, err = self.accumulate_microbatches(
                    micro, zeros, batch_local, gas, extra0=err0)
            else:
                def micro(b):
                    loss, gfull = jax.value_and_grad(full_loss)(params, b)
                    # quant_grads honored: a qwZ-only config buckets
                    # EXACT gradient reduces, same as the straight-
                    # through path's quant_grads=False backward
                    gl, _ = C.reduce_tree_bucketed(
                        gfull, self.master_spec, axes, world, sizes,
                        bucket_elems=bucket_elems,
                        quant_grads=mode["quant_grads"])
                    return loss, gl

                grads_sum, losses_mean = self.accumulate_microbatches(
                    micro, zeros, batch_local, gas)
                err = None
            mean_loss = jax.lax.pmean(losses_mean, axes) / scale
            return grads_sum, err, mean_loss

        def local_loco(master_local, err_local, batch_local, scale,
                       *buf):
            err0 = jax.tree.map(lambda e: e[0], err_local)   # drop world row
            grads_sum, err, mean_loss = core(master_local, err0,
                                             batch_local, scale,
                                             buf[0] if buf else None)
            err_out = jax.tree.map(lambda e: e[None], err)
            return grads_sum, err_out, mean_loss

        def local_plain(master_local, batch_local, scale, *buf):
            grads_sum, _, mean_loss = core(master_local, None,
                                           batch_local, scale,
                                           buf[0] if buf else None)
            return grads_sum, mean_loss

        def train_step(state, batch):
            scale = state["scaler"].scale if self.fp16_enabled \
                else jnp.float32(1.0)
            b_specs = jax.tree.map(
                lambda x: self._manual_batch_spec(x.ndim), batch)
            buf_in = (rep_specs,) if buffered else ()
            buf_arg = (state["gathered"],) if buffered else ()
            if loco:
                err_specs = jax.tree.map(
                    lambda s: P(row, *([None] * len(s.shape))), self._shapes)
                fn = shard_map(
                    local_loco, mesh=self.mesh,
                    in_specs=(master_manual, err_specs, b_specs, P())
                    + buf_in,
                    out_specs=(master_manual, err_specs, P()),
                    axis_names=set(axes), check_vma=False)
                grads_sum, new_err, mean_loss = fn(
                    state["master"], state["loco_err"], batch, scale,
                    *buf_arg)
            else:
                fn = shard_map(
                    local_plain, mesh=self.mesh,
                    in_specs=(master_manual, b_specs, P()) + buf_in,
                    out_specs=(master_manual, P()),
                    axis_names=set(axes), check_vma=False)
                grads_sum, mean_loss = fn(state["master"], batch, scale,
                                          *buf_arg)
                new_err = None
            grad_scale = jnp.float32(gas) * scale
            new_state, metrics = self._apply_update(state, grads_sum,
                                                    grad_scale)
            if loco:
                # fp16 overflow: _apply_update skips the weight update, and
                # the residuals computed from inf/NaN gradients must not
                # poison the persistent state — reset them so recovery
                # matches plain qgZ
                overflow = metrics["overflow"] > 0
                new_state["loco_err"] = jax.tree.map(
                    lambda n: jnp.where(overflow, jnp.zeros_like(n), n),
                    new_err)
            metrics["loss"] = mean_loss
            return new_state, metrics

        state_sh = self._state_shardings()
        return jax.jit(train_step, out_shardings=(state_sh, None),
                       donate_argnums=(0,))

    def _build_train_step_qz(self, gas: int):
        """ZeRO++ qwZ/qgZ straight-through step: shard_map manual over the
        ZeRO axes; the parameter all-gather (fwd) and gradient
        reduce-scatter (bwd) are one straight-through primitive with an
        int8 wire format (``parallel/compressed.py``). The overlap-
        composed / LoCo variants route through
        ``_build_train_step_bucketed_wire`` instead (see
        ``_build_train_step_wire``)."""
        from jax import shard_map

        from deepspeed_tpu.parallel import compressed as C

        axes = self._dp_manual_axes
        world = self._dp_manual_world
        dtype = jnp.dtype(self.precision)
        mode = self._compressed
        gather_tree = C.gather_tree_fn(
            self.master_spec, axes, world, dtype,
            quant_weights=mode["quant_weights"],
            quant_grads=mode["quant_grads"],
            axis_sizes=dict(self.mesh.shape))
        master_manual = jax.tree.map(
            lambda s: C.manual_spec(s, axes), self.master_spec,
            is_leaf=lambda x: isinstance(x, P))

        acc_dt_c = self._grad_accum_dtype()

        def local(master_local, batch_local, scale):
            zeros = jax.tree.map(
                lambda x: jnp.zeros(x.shape, acc_dt_c), master_local)

            def scaled_loss(ml, b):
                params = gather_tree(ml)
                loss = self.model_spec.loss_fn(params, b)
                return loss * scale

            def micro(acc, b):
                loss, g = jax.value_and_grad(scaled_loss)(master_local, b)
                return jax.tree.map(jnp.add, acc, g), loss

            if gas == 1:
                squeezed = jax.tree.map(lambda x: x[0], batch_local)
                grads_sum, loss = micro(zeros, squeezed)
                losses_mean = loss
            else:
                grads_sum, losses = jax.lax.scan(micro, zeros, batch_local)
                losses_mean = jnp.mean(losses)
            mean_loss = jax.lax.pmean(losses_mean, axes) / scale
            return grads_sum, mean_loss

        def train_step(state, batch):
            scale = state["scaler"].scale if self.fp16_enabled \
                else jnp.float32(1.0)
            b_specs = jax.tree.map(
                lambda x: self._manual_batch_spec(x.ndim), batch)
            fn = shard_map(
                local, mesh=self.mesh,
                in_specs=(master_manual, b_specs, P()),
                out_specs=(master_manual, P()),
                axis_names=set(axes), check_vma=False)
            grads_sum, mean_loss = fn(state["master"], batch, scale)
            grad_scale = jnp.float32(gas) * scale
            new_state, metrics = self._apply_update(state, grads_sum, grad_scale)
            metrics["loss"] = mean_loss
            return new_state, metrics

        state_sh = self._state_shardings()
        return jax.jit(train_step, out_shardings=(state_sh, None),
                       donate_argnums=(0,))

    def _build_train_step_onebit(self, gas: int):
        """1-bit optimizer step with wire transport: the WHOLE step (grads +
        optimizer) runs shard_map-manual over the DP axes. Warmup steps
        exact-allreduce gradients; frozen steps skip the gradient reduction
        entirely and exchange packed-sign compressed momentum inside the
        optimizer update (reference ``runtime/fp16/onebit/adam.py`` +
        ``runtime/comm/nccl.py:52``)."""
        from jax import shard_map

        from deepspeed_tpu.parallel import compressed as C

        axes = self._dp_manual_axes
        world = self._dp_manual_world
        freeze = max(getattr(self.optimizer, "freeze_step", 0) or
                     getattr(self.optimizer, "var_freeze_step", 0), 1)
        block = 2048

        def transport(m_new, err):
            from deepspeed_tpu.ops.quantization import pad_to_block

            n = m_new.size
            fp, _ = pad_to_block(m_new.reshape(-1).astype(jnp.float32), block)
            ep, _ = pad_to_block(err.reshape(-1).astype(jnp.float32), block)
            reduced, new_err = C.packed_sign_allreduce(fp, ep, axes, world,
                                                      block)
            return (reduced[:n].reshape(m_new.shape),
                    new_err[:n].reshape(err.shape))

        self.optimizer.transport = transport

        def local(state_local, batch_local):
            opt = dict(state_local["opt"])
            opt["worker_error"] = jax.tree.map(
                lambda e: e[0], opt["worker_error"])
            st = dict(state_local, opt=opt)
            scale = st["scaler"].scale if self.fp16_enabled else None
            dtype = jnp.dtype(self.precision)

            zeros = jax.tree.map(
                lambda x: jnp.zeros(x.shape, jnp.float32), st["master"])

            def micro(acc, b):
                def wrt_master(m):
                    p = jax.tree.map(lambda x: x.astype(dtype), m)
                    loss = self.model_spec.loss_fn(p, b)
                    return loss * scale if scale is not None else loss

                loss, g = jax.value_and_grad(wrt_master)(st["master"])
                return jax.tree.map(jnp.add, acc, g), loss

            if gas == 1:
                squeezed = jax.tree.map(lambda x: x[0], batch_local)
                grads_sum, loss = micro(zeros, squeezed)
                losses_mean = loss
            else:
                grads_sum, losses = jax.lax.scan(micro, zeros, batch_local)
                losses_mean = jnp.mean(losses)

            # warmup: exact grad allreduce (identical ranks feed identical
            # momentum). frozen: gradients stay LOCAL — only the compressed
            # momentum crosses the wire (inside optimizer.update).
            frozen = st["step"] >= freeze
            grads_sum = jax.lax.cond(
                frozen, lambda g: g,
                lambda g: jax.tree.map(lambda x: jax.lax.pmean(x, axes), g),
                grads_sum)

            grad_scale = jnp.float32(gas) * (scale if scale is not None
                                             else 1.0)
            new_state, metrics = self._apply_update(st, grads_sum, grad_scale)
            new_state["opt"]["worker_error"] = jax.tree.map(
                lambda e: e[None], new_state["opt"]["worker_error"])
            metrics = {k: jax.lax.pmean(v, axes) for k, v in metrics.items()}
            metrics["loss"] = jax.lax.pmean(losses_mean, axes)
            if scale is not None:
                metrics["loss"] = metrics["loss"] / new_state["scaler"].scale
            return new_state, metrics

        row = axes if len(axes) > 1 else axes[0]
        rep = P()

        def state_specs(state):
            sp = jax.tree.map(lambda _: rep, state)
            sp["opt"]["worker_error"] = jax.tree.map(
                lambda _: P(row), state["opt"]["worker_error"])
            return sp

        def train_step(state, batch):
            b_specs = jax.tree.map(
                lambda x: self._manual_batch_spec(x.ndim), batch)
            fn = shard_map(
                local, mesh=self.mesh,
                in_specs=(state_specs(state), b_specs),
                out_specs=(state_specs(state), rep),
                axis_names=set(axes), check_vma=False)
            return fn(state, batch)

        state_sh = self._state_shardings()
        return jax.jit(train_step, out_shardings=(state_sh, None),
                       donate_argnums=(0,))

    def _batch_shardings(self, leading: int = 0):
        """``leading`` counts unsharded leading dims (1 = [gas, ...],
        2 = [n_steps, gas, ...] for the fused multi-step path)."""
        n = int(leading)

        def spec_for(ndim: int) -> NamedSharding:
            if n:
                inner = self.policy.batch_spec(ndim - n)
                return NamedSharding(self.mesh, P(*([None] * n), *inner))
            return NamedSharding(self.mesh, self.policy.batch_spec(ndim))

        return spec_for

    def _shard_batch(self, batch: PyTree, leading: int = 0) -> PyTree:
        spec_for = self._batch_shardings(leading)
        rep = NamedSharding(self.mesh, P())

        def one(path, x):
            x = np.asarray(x)
            # underscore keys (engine-injected controls: PLD masks, LTD
            # indices, lr_scale) and scalars are replicated, not batch-sharded
            keys = [getattr(p, "key", None) for p in path]
            if x.ndim == 0 or any(isinstance(k, str) and k.startswith("_")
                                  for k in keys) or "lr_scale" in keys:
                if leading and x.ndim > 0:
                    return shard_host_batch(
                        x, NamedSharding(self.mesh,
                                         P(*([None] * x.ndim))))
                return shard_host_batch(x, rep)
            return shard_host_batch(x, spec_for(x.ndim))

        return jax.tree_util.tree_map_with_path(one, batch)

    # ------------------------------------------------------------------ #
    # public batch-size queries (reference engine API)
    # ------------------------------------------------------------------ #
    def train_batch_size(self) -> int:
        return self.config.train_batch_size

    def train_micro_batch_size(self) -> int:
        return self.config.train_micro_batch_size_per_gpu

    def gradient_accumulation_steps(self) -> int:
        return self.config.gradient_accumulation_steps

    def get_lr(self) -> List[float]:
        if self.lr_scheduler is not None:
            return [float(self.lr_scheduler.lr_at(jnp.asarray(self.global_steps)))]
        return [self.optimizer.lr]

    def get_global_grad_norm(self) -> Optional[float]:
        if "grad_norm" not in self._last_metrics_dev:
            return None
        return float(jax.device_get(self._last_metrics_dev["grad_norm"]))

    @property
    def skipped_steps(self) -> int:
        """Exact count of skipped optimizer steps (device-side counter):
        fp16 overflow skips, plus bf16/fp32 non-finite skips under
        ``guardian.nonfinite_guard``."""
        if "skips" not in self.state:
            return 0
        return int(jax.device_get(self.state["skips"]))

    @property
    def loss_scale(self) -> float:
        if not self.fp16_enabled:
            return 1.0
        return float(jax.device_get(self.state["scaler"].scale))

    def is_gradient_accumulation_boundary(self) -> bool:
        return self._micro_in_window == 0

    def _opt_swap(self, direction: str) -> None:
        """Move optimizer moments host↔device around the step ('in'/'out')."""
        opt_sh = self._state_shardings()["opt"]
        target = self._to_host_shardings(opt_sh) if direction == "out" else opt_sh
        self.state["opt"] = jax.device_put(self.state["opt"], target)

    def _nvme_swapper(self):
        """Lazy NVMe optimizer-state swapper (reference
        ``swap_tensor/partitioned_optimizer_swapper.py:27``; config path
        ``offload_optimizer.device == "nvme"``)."""
        if self._opt_swapper is None:
            from deepspeed_tpu.runtime.swap_tensor import OptimizerSwapper

            self._opt_swapper = OptimizerSwapper(self)
            log_dist("NVMe optimizer offload active: "
                     f"{self._opt_swapper.swapper.swap_dir}")
        return self._opt_swapper

    def _param_nvme_swapper(self):
        """Lazy NVMe parameter swapper (reference
        ``swap_tensor/partitioned_param_swapper.py:37``; config path
        ``offload_param.device == "nvme"`` at stage 3)."""
        if self._param_swapper is None:
            from deepspeed_tpu.runtime.swap_tensor import ParamSwapper

            self._param_swapper = ParamSwapper(self)
            log_dist("NVMe parameter offload active: "
                     f"{self._param_swapper.swapper.swap_dir}")
        return self._param_swapper

    # ------------------------------------------------------------------ #
    # offload_states / reload_states (reference engine.py:5573/:5603)
    # ------------------------------------------------------------------ #
    def offload_states(self, include: Optional[List[str]] = None,
                       device: str = "cpu") -> None:
        """Move engine state tiers to host memory on demand.

        ``include`` ⊆ {'optim_states', 'hp_params'}; None = both."""
        if device != "cpu":
            raise ValueError("offload_states supports device='cpu' (host memory);"
                             " use OptimizerSwapper for the NVMe tier")
        include = include or ["optim_states", "hp_params"]
        sh = self._state_shardings()
        if "optim_states" in include:
            self.state["opt"] = jax.device_put(
                self.state["opt"], self._to_host_shardings(sh["opt"]))
        if "hp_params" in include:
            self.state["master"] = jax.device_put(
                self.state["master"], self._to_host_shardings(sh["master"]))

    def reload_states(self) -> None:
        sh = self._state_shardings()
        self.state["opt"] = jax.device_put(self.state["opt"], sh["opt"])
        self.state["master"] = jax.device_put(self.state["master"], sh["master"])

    # ------------------------------------------------------------------ #
    # fused train path
    # ------------------------------------------------------------------ #
    @staticmethod
    def _stack_micros(micros: list) -> PyTree:
        def stack(*xs):
            arrs = [np.asarray(x) for x in xs]
            if len({a.shape for a in arrs}) > 1:
                raise ValueError(
                    "micro-batches in one accumulation window have different "
                    f"shapes {[a.shape for a in arrs]} — variable/token-"
                    "budget batching requires gradient_accumulation_steps=1")
            return np.stack(arrs)

        return jax.tree.map(stack, *micros)

    def train_batch(self, data_iter: Iterator[PyTree]) -> jax.Array:
        """Pull GAS micro-batches, run the fused jitted step. Returns mean loss."""
        gas = self.gradient_accumulation_steps()
        stacked = self._stack_micros([next(data_iter) for _ in range(gas)])
        stacked = self._inject_data_efficiency(stacked, gas)
        return self._dispatch_train_step(stacked, gas)

    def _maybe_inject_nan_grads(self, stacked: PyTree, gas: int) -> PyTree:
        """``train/nan_grads`` chaos injection point: when the armed fault
        window covers this step, ride a per-micro poison flag into the
        batch dict — the jitted step multiplies every gradient leaf by NaN
        (``_train_step_fn``), which is exactly the shape of a real
        non-finite backward. Unarmed cost: one global-is-None check."""
        from deepspeed_tpu.testing.chaos import chaos_should_fire

        if self._wire_format() != "exact" or self._host_runner is not None \
                or not isinstance(stacked, dict):
            # only the exact-wire fused builders strip the poison flag
            # before the model's loss_fn — for wire-compressed / 1-bit /
            # host-step builders the key would leak into the model batch
            # (or silently never poison), and a NON-DICT batch can't
            # carry the flag without changing the pytree the model sees.
            # The point stays unarmed on those paths.
            return stacked
        if not chaos_should_fire("train/nan_grads"):
            return stacked
        stacked = dict(stacked)
        stacked["_nan_grads"] = np.ones((gas,), np.float32)
        logger.warning("chaos: train/nan_grads poisoning the gradients of "
                       f"step {self.global_steps + 1}")
        return stacked

    def _dispatch_train_step(self, stacked: PyTree, gas: int) -> jax.Array:
        """Run ONE fused step on an already-stacked [gas, ...] window."""

        stacked = self._maybe_inject_nan_grads(stacked, gas)
        if self._host_runner is None:
            key = ("train_step", gas)
            if key not in self._compiled:
                self._compiled[key] = self._select_step_builder(gas)
            step_fn = self._compiled[key]

        batch = self._shard_batch(stacked, leading=True)
        if self.config.wall_clock_breakdown:
            self.timers(TRAIN_BATCH_TIMER).start()
        self.tput_timer.start()
        t0 = time.perf_counter()
        self._in_step = True   # a preemption signal now defers to the
        try:                   # boundary check below
            with self._train_span("train_step"):
                chaos_point("train/step")
                if self._host_runner is not None:
                    # SuperOffload/ZenFlow host-executed update (runtime/host_step.py)
                    _, metrics = self._host_runner.train_batch(batch, gas)
                else:
                    if self._offload_opt:
                        self._opt_swap("in")
                    if self._offload_nvme:
                        self._nvme_swapper().swap_in_optimizer()
                    if self._offload_param_nvme:
                        self._param_nvme_swapper().swap_in_params()
                    self._ensure_master_tier_for_step()
                    with self.mesh:
                        self.state, metrics = step_fn(self.state, batch)
                    if self._offload_opt:
                        self._opt_swap("out")
                    if self._offload_nvme:
                        self._nvme_swapper().swap_out_optimizer()
                    if self._offload_param:
                        self._park_master()
                    if self._offload_param_nvme:
                        self._param_nvme_swapper().swap_out_params()
            self.global_steps += 1
            self.micro_steps += gas
            self._after_step(metrics, wall_s=time.perf_counter() - t0,
                             tokens=self._count_tokens(stacked)
                             if self._tm is not None else 0)
            if self.config.wall_clock_breakdown:
                self.timers(TRAIN_BATCH_TIMER).stop()
                self.timers.log([TRAIN_BATCH_TIMER])
        except Exception:
            # crash context for an unhandled step failure: the flight
            # recorder's last N spans ARE the timeline that led here
            # (no-op unless telemetry.tracing is on); then re-raise
            self._dump_step_crash_context()
            raise
        finally:
            # even a raising step must re-enable immediate preemption
            # handling (a deferred SIGTERM would otherwise wait forever)
            self._in_step = False
        self._check_preemption_boundary()
        return metrics["loss"]

    def train_batches(self, data_iter: Iterator[PyTree],
                      n_steps: int) -> jax.Array:
        """Run ``n_steps`` optimizer steps in ONE device dispatch.

        A TPU dispatch pays fixed host latency (Python + runtime transport;
        ~100 ms through a remote-tunnel runtime) regardless of step cost —
        ``lax.scan`` over the fused step amortizes it to once per call.
        Beyond the reference engine API (its ``train_batch`` is per-step);
        falls back to a per-step loop for variants with host-side phases
        (host-runner, 1-bit wire, compressed collectives, offload swappers).
        Returns the mean loss over the ``n_steps`` steps.
        """
        if n_steps <= 1:
            return self.train_batch(data_iter)
        if (self._host_runner is not None or self._onebit_wire
                or self._compressed or self._offload_opt
                or self._offload_nvme or self._offload_param_nvme
                or self._ltd is not None
                or self._pld is not None or self._curriculum is not None):
            # host-side per-step phases (or step-indexed host schedules):
            # the per-step path keeps their semantics exact
            losses = [self.train_batch(data_iter) for _ in range(n_steps)]
            return jnp.mean(jnp.stack(losses))  # same mean-loss contract
        gas = self.gradient_accumulation_steps()
        steps = []
        for _ in range(n_steps):
            stacked = self._stack_micros(
                [next(data_iter) for _ in range(gas)])
            steps.append(self._inject_data_efficiency(stacked, gas))
        try:
            big = jax.tree.map(lambda *xs: np.stack(xs), *steps)
        except ValueError:
            # variable shapes across steps (token-budget batching at gas=1):
            # run the already-built windows through the per-step path
            losses = [self._dispatch_train_step(s, gas) for s in steps]
            return jnp.mean(jnp.stack(losses))
        key = ("train_multi", gas, n_steps)
        if key not in self._compiled:
            self._compiled[key] = self._build_train_multi(gas, n_steps)
        batch = self._shard_batch(big, leading=2)
        self.tput_timer.start()
        t0 = time.perf_counter()
        self._in_step = True
        try:
            with self._train_span("train_window"):
                chaos_point("train/step")
                self._ensure_master_tier_for_step()
                with self.mesh:
                    self.state, metrics = self._compiled[key](self.state, batch)
                if self._offload_param:
                    self._park_master()
            self.global_steps += n_steps
            self.micro_steps += gas * n_steps
            self._after_step(metrics, n_steps=n_steps,
                             wall_s=time.perf_counter() - t0,
                             tokens=self._count_tokens(big)
                             if self._tm is not None else 0)
        except Exception:
            self._dump_step_crash_context()   # then re-raise unchanged
            raise
        finally:
            self._in_step = False
        self._check_preemption_boundary()
        return metrics["loss"]

    def _record_moe_drops(self, frac) -> None:
        """Async jax.debug.callback sink (moe.layer.set_drop_monitor) — keeps
        the worst dropped-choice fraction seen since the last print window."""
        self._moe_drop_frac = max(self._moe_drop_frac, float(frac))

    def _dump_step_crash_context(self) -> None:
        """Flight-recorder dump for an unhandled train-step exception
        (no-op unless ``telemetry.tracing`` is on). Must never raise —
        it runs on the exception path it exists to explain."""
        try:
            from deepspeed_tpu.telemetry import tracing

            tracing.get_tracer().dump_flight(
                "engine_step_exception", note=f"step={self.global_steps}")
        except Exception as e:   # the original exception must win
            logger.warning(f"flight dump on step failure failed too: {e}")

    def _train_span(self, name: str):
        """telemetry.span when enabled; inert otherwise."""
        if self._tm is None:
            import contextlib

            return contextlib.nullcontext()
        from deepspeed_tpu import telemetry

        return telemetry.span(name)

    def _after_step(self, metrics: Dict[str, jax.Array],
                    n_steps: int = 1, wall_s: Optional[float] = None,
                    tokens: int = 0) -> None:
        self.tput_timer.stop(global_step=True, steps=n_steps)
        self._last_metrics_dev = metrics  # lazy: no host sync off the print path
        if self._tm is not None:
            self._tm_steps.inc(n_steps)
            if tokens:
                self._tm_tokens.inc(tokens)
                self._tm_tokens_per_step = tokens // n_steps
            if wall_s is not None:
                # amortize a fused window over its steps so the histogram
                # stays per-step comparable across dispatch modes
                self._tm_step_hist.observe(wall_s / n_steps, n=n_steps)
            # exported unix timestamp (train_heartbeat_timestamp_seconds is
            # compared against scrape-side wall clocks, not used as an
            # interval here)  # dslint: disable=wall-clock
            self._tm_heartbeat.set(time.time())
            if self._watchdog is not None:
                self._watchdog.beat()
        if self.lr_scheduler is not None:
            self.lr_scheduler.step(self.global_steps)
        if self.global_steps % max(1, self.config.steps_per_print) == 0:
            host = {k: float(jax.device_get(v)) for k, v in metrics.items()}
            if self._guardian is not None:
                # host-side numerics sentinel: the guardian's anomaly
                # detector rides THIS device_get — the one the log cadence
                # already pays — so detection adds zero hot-path syncs
                self._guardian.observe(self.global_steps, host)
            if self._moe_drop_frac > 0:
                logger.warning(
                    f"MoE expert-parallel dispatch dropped "
                    f"{self._moe_drop_frac:.2%} of token-choices (EP buffer "
                    "overflow — router skew); dropped choices fall through "
                    "to the residual. Consider a larger capacity headroom "
                    "or rebalancing (aux loss weight).")
                host["moe_drop_frac"] = self._moe_drop_frac
                self._moe_drop_frac = 0.0
            log_dist(
                f"step={self.global_steps} loss={host.get('loss', float('nan')):.4f} "
                f"lr={host.get('lr', 0):.3e} grad_norm={host.get('grad_norm', 0):.3f}"
                + (f" loss_scale={host.get('loss_scale', 0):.0f}" if self.fp16_enabled else ""))
            # (train_loss/grad_norm/... gauges are set by the registry
            # collector from _last_metrics_dev on every read path — no
            # duplicate update here)
            if self.monitor is not None and self.monitor.enabled:
                events = [(f"Train/{k}", v, self.global_steps) for k, v in host.items()]
                self.monitor.write_events(events)
            if self._tm is not None and self.config.telemetry.monitor_bridge \
                    and self.monitor is not None and self.monitor.enabled:
                if self._tm_bridge is None:
                    from deepspeed_tpu import telemetry

                    self._tm_bridge = telemetry.MonitorBridge(
                        self.monitor, self._tm)
                self._tm_bridge.publish(self.global_steps)

    # ------------------------------------------------------------------ #
    # eager forward/backward/step (API parity path)
    # ------------------------------------------------------------------ #
    def forward(self, batch: PyTree) -> jax.Array:
        """Compute loss (and cache grads) for one micro-batch."""
        if self._onebit_wire:
            raise NotImplementedError(
                "the eager forward()/backward()/step() path is unavailable "
                "with 1-bit wire transport (per-rank error buffers live "
                "inside the fused step's shard_map) — use train_batch()")
        if self._offload_nvme:
            raise NotImplementedError(
                "the eager forward()/backward()/step() path is unavailable "
                "with offload_optimizer.device='nvme' (moments are swapped "
                "around the fused step) — use train_batch()")
        if self._host_runner is not None:
            raise NotImplementedError(
                "the eager forward()/backward()/step() path is unavailable "
                "with offload_optimizer.host_step — use train_batch()")
        self._materialize_master()
        if "fwd_bwd" not in self._compiled:
            def fwd_bwd(state, b):
                scale = state["scaler"].scale if self.fp16_enabled else None
                # the eager path consumes the double buffer too — its
                # step() republishes after every update, so the publish
                # is never wasted work on this path either
                return self._loss_and_grads(
                    state["master"], b, scale,
                    params_buf=(state.get("gathered")
                                if self._param_buffer else None))

            # state is READ-ONLY here (returns loss+grads; the eager
            # path's apply() owns the state donation); donating would
            # invalidate self.state mid-window  # dslint: disable=donation
            self._compiled["fwd_bwd"] = jax.jit(fwd_bwd)
        batch = self._shard_batch(batch)
        if self.config.wall_clock_breakdown:
            self.timers(FORWARD_GLOBAL_TIMER).start()
        with self.mesh:
            loss, grads = self._compiled["fwd_bwd"](self.state, batch)
        if self.config.wall_clock_breakdown:
            self.timers(FORWARD_GLOBAL_TIMER).stop()
        self._pending_grads = grads
        return loss

    def backward(self, loss: jax.Array = None) -> None:
        """Accumulate the cached grads (autograd already ran fused in forward)."""
        if self._pending_grads is None:
            raise RuntimeError("backward() called before forward()")
        if self.config.wall_clock_breakdown:
            self.timers(BACKWARD_GLOBAL_TIMER).start()
        if self._grad_buffer is None:
            self._grad_buffer = self._pending_grads
        else:
            if "grad_add" not in self._compiled:
                self._compiled["grad_add"] = jax.jit(
                    lambda a, b: jax.tree.map(jnp.add, a, b), donate_argnums=(0,))
            with self.mesh:
                self._grad_buffer = self._compiled["grad_add"](
                    self._grad_buffer, self._pending_grads)
        self._pending_grads = None
        self.micro_steps += 1
        self._micro_in_window = (self._micro_in_window + 1) % \
            self.gradient_accumulation_steps()
        if self.config.wall_clock_breakdown:
            self.timers(BACKWARD_GLOBAL_TIMER).stop()

    def step(self) -> None:
        """Apply the optimizer at the GAS boundary (no-op otherwise)."""
        if not self.is_gradient_accumulation_boundary():
            return
        if self._grad_buffer is None:
            raise RuntimeError("step() called with no accumulated gradients")
        gas = self.gradient_accumulation_steps()
        if "apply" not in self._compiled:
            state_sh = self._state_shardings()

            def apply(state, grads):
                scale = state["scaler"].scale if self.fp16_enabled else jnp.float32(1.0)
                return self._apply_update(state, grads, jnp.float32(gas) * scale)

            self._compiled["apply"] = jax.jit(
                apply, out_shardings=(state_sh, None), donate_argnums=(0, 1))
        if self.config.wall_clock_breakdown:
            self.timers(STEP_GLOBAL_TIMER).start()
        self._in_step = True   # preemption defers to the boundary check
        try:
            if self._offload_opt:
                self._opt_swap("in")
            self._materialize_master()
            with self.mesh:
                self.state, metrics = self._compiled["apply"](self.state, self._grad_buffer)
            if self._offload_opt:
                self._opt_swap("out")
            if self._offload_param:
                self._park_master()
            if self._offload_param_nvme:
                self._param_nvme_swapper().swap_out_params()
            self._grad_buffer = None
            self.global_steps += 1
            self._after_step(metrics)
            if self.config.wall_clock_breakdown:
                self.timers(STEP_GLOBAL_TIMER).stop()
                self.timers.log([FORWARD_GLOBAL_TIMER, BACKWARD_GLOBAL_TIMER,
                                 STEP_GLOBAL_TIMER])
        finally:
            self._in_step = False
        self._check_preemption_boundary()

    def eval_batch(self, batch: PyTree) -> jax.Array:
        if self._host_runner is not None:
            # host-step mode: evaluate on the device 16-bit params
            self._host_runner._apply_pending()
            if "eval" not in self._compiled:
                self._compiled["eval"] = jax.jit(self.model_spec.loss_fn)
            batch = self._shard_batch(batch)
            with self.mesh:
                return self._compiled["eval"](
                    self._host_runner.device_params, batch)
        self._materialize_master()
        if "eval" not in self._compiled:
            def ev(state, b):
                params = self._compute_params(state["master"])
                return self.model_spec.loss_fn(params, b)

            # eval reads state and returns a scalar loss — donating
            # would destroy the live train state  # dslint: disable=donation
            self._compiled["eval"] = jax.jit(ev)
        batch = self._shard_batch(batch)
        with self.mesh:
            return self._compiled["eval"](self.state, batch)

    def predict(self, batch: PyTree):
        """Model outputs (logits) — the reference's module __call__ analog."""
        if self.model_spec.apply_fn is None:
            raise ValueError("model spec has no apply_fn")
        if self._host_runner is not None:
            self._host_runner._apply_pending()
            if "predict" not in self._compiled:
                self._compiled["predict"] = jax.jit(self.model_spec.apply_fn)
            batch = self._shard_batch(batch)
            with self.mesh:
                return self._compiled["predict"](
                    self._host_runner.device_params, batch)
        self._materialize_master()
        if "predict" not in self._compiled:
            def pr(state, b):
                params = self._compute_params(state["master"])
                return self.model_spec.apply_fn(params, b)

            # predict reads state and returns logits — donating would
            # destroy the live train state  # dslint: disable=donation
            self._compiled["predict"] = jax.jit(pr)
        batch = self._shard_batch(batch)
        with self.mesh:
            return self._compiled["predict"](self.state, batch)

    # ------------------------------------------------------------------ #
    # dataloader
    # ------------------------------------------------------------------ #
    def deepspeed_io(self, source, repeat: bool = True) -> Iterator[PyTree]:
        """Wrap a host numpy batch source (reference ``deepspeed_io`` engine.py:2486).

        Re-iterable sources are wrapped in RepeatingLoader when ``repeat``;
        one-shot iterators/generators pass through unchanged (make them infinite
        if you need repetition). With ``curriculum_learning`` enabled in the
        config, batches are difficulty-truncated per step (reference
        ``data_pipeline/data_sampling/curriculum_scheduler.py``). With
        ``data_efficiency.data_sampling.dynamic_batching`` enabled, ``source``
        must be a SEQUENCE OF SAMPLES (variable-length 1-D token arrays) and
        is regrouped into token-budget batches with per-batch LR scaling
        (reference ``variable_batch_size_and_lr.py``; requires gas=1)."""
        de = self.config.data_efficiency
        dyn = de.data_sampling.dynamic_batching
        if dyn.enabled and de.enabled and de.data_sampling.enabled:
            from deepspeed_tpu.runtime.data_pipeline.variable_batch import (
                variable_batch_dataloader,
            )

            samples = list(source)
            if not samples or np.asarray(samples[0]).ndim != 1:
                raise ValueError(
                    "dynamic_batching needs a sequence of 1-D token samples")
            if self.gradient_accumulation_steps() != 1:
                raise ValueError("dynamic_batching requires "
                                 "gradient_accumulation_steps=1")
            return variable_batch_dataloader(
                samples, max_tokens=dyn.max_tokens,
                base_batch_size=self.train_micro_batch_size(),
                lr_scaling_method=dyn.lr_scaling_method,
                min_batch_size=dyn.min_batch_size,
                max_batch_size=dyn.max_batch_size,
                order=dyn.sentence_picking_order,
                seed=de.seed, batch_multiple=self.dp_world_size,
                loop=repeat)
        elif dyn.enabled:
            logger.warning(
                "dynamic_batching.enabled is set but data_efficiency.enabled "
                "/ data_sampling.enabled are not — dynamic batching stays OFF")
        loader = source
        if repeat and hasattr(source, "__iter__") and iter(source) is not source:
            loader = RepeatingLoader(source)
        it = iter(loader)
        if self._curriculum is not None:
            from deepspeed_tpu.runtime.data_pipeline import (
                curriculum_dataloader,
            )

            it = curriculum_dataloader(it, self._curriculum,
                                       lambda: self.global_steps)
        return it

    # ------------------------------------------------------------------ #
    # fault tolerance: preemption handling + emergency checkpoints
    # (config "fault_tolerance"; README "Fault tolerance")
    # ------------------------------------------------------------------ #
    def enable_preemption_handler(self, signals=None) -> bool:
        """Install the graceful-preemption signal handler (SIGTERM by
        default — what GCE/GKE send a preempted VM). On delivery the
        engine drains any in-flight async save, writes an emergency
        checkpoint, and exits 0; a signal landing mid-step defers to the
        step boundary (interrupting a dispatched XLA program to do I/O
        from the handler frame is not safe). Returns False off the main
        thread (signal.signal would raise there)."""
        import signal

        signals = signals or (signal.SIGTERM,)
        try:
            for s in signals:
                self._prev_sig_handlers[s] = signal.signal(
                    s, self._on_preempt_signal)
        except ValueError:   # not the main thread
            logger.warning("preemption handler not installed (not on the "
                           "main thread)")
            return False
        log_dist(f"graceful-preemption handler armed for "
                 f"{[signal.Signals(s).name for s in signals]}")
        return True

    def _on_preempt_signal(self, signum, frame) -> None:
        self._preempt_requested = True
        busy = self._in_step or self._saving or self._guard_busy
        logger.warning(
            f"received signal {signum}: preemption imminent — will drain "
            "saves, write an emergency checkpoint, and exit cleanly"
            + (" (deferred to the step/save boundary)" if busy else ""))
        # a signal-handler frame interrupting a dispatched step or an
        # in-flight save must not reenter checkpoint I/O (same-thread
        # reentrancy into save_state) — defer to the boundary checks
        if not busy:
            self._preemption_exit()

    def _preemption_exit(self) -> None:
        """Drain → emergency save → clean exit (SystemExit(0) unwinds the
        training loop; preemption is a normal lifecycle event, not a
        failure)."""
        self._preempt_requested = False   # the exit is running — don't recurse
        from deepspeed_tpu.checkpoint.engine import finalize_async

        try:
            finalize_async()
        except Exception as e:
            logger.warning(f"async-save drain during preemption failed: {e}")
        self._emergency_save("preemption")
        # the last seconds of timeline ride along with the emergency
        # checkpoint — what WAS the run doing when the VM was reclaimed
        # (no-op unless telemetry.tracing is on)
        from deepspeed_tpu.telemetry import tracing

        tracing.get_tracer().dump_flight("preemption")
        self.shutdown_telemetry()
        log_dist("preemption: emergency checkpoint committed — exiting 0")
        raise SystemExit(0)

    def preemption_requested(self) -> bool:
        """Cooperative check for training loops that manage their own
        shutdown (the handler already exits at the next step boundary)."""
        return self._preempt_requested

    def _emergency_save(self, reason: str) -> Optional[str]:
        """Synchronous committed checkpoint into the fault-tolerance
        resume dir (fallback: the last ``save_checkpoint`` dir). Non-
        blocking lock: a second trigger while one save runs (watchdog
        thread vs signal handler) is dropped, not deadlocked."""
        if not self._ft_lock.acquire(blocking=False):
            return None
        try:
            ftc = self.config.fault_tolerance
            save_dir = ftc.resume_dir or self._last_save_dir
            if not save_dir:
                logger.error(
                    f"emergency checkpoint ({reason}) skipped: no "
                    "fault_tolerance.resume_dir and no prior save dir")
                return None
            tag = f"{ftc.emergency_tag_prefix}_step{self.global_steps}"
            from deepspeed_tpu import telemetry

            telemetry.counter(
                "checkpoint_emergency_saves_total",
                "emergency checkpoints by trigger (preemption/stall)"
            ).inc(reason=reason)
            try:
                self.save_checkpoint(save_dir, tag=tag, async_save=False)
            except Exception as e:
                logger.error(f"emergency checkpoint ({reason}) FAILED: {e}")
                return None
            return tag
        finally:
            self._ft_lock.release()

    def maybe_auto_resume(self) -> bool:
        """``fault_tolerance.auto_resume``: restore the newest committed
        checkpoint from ``resume_dir`` (called by ``initialize``). A
        missing/empty dir is a cold start, not an error."""
        ftc = self.config.fault_tolerance
        if not ftc.auto_resume:
            return False
        if not ftc.resume_dir:
            logger.warning("auto_resume=true but no fault_tolerance."
                           "resume_dir — cold start")
            return False
        from deepspeed_tpu.checkpoint.engine import read_latest_tag
        from deepspeed_tpu.checkpoint.fault_tolerance import find_restore_tag

        ckcfg = self.config.checkpoint
        has_ckpt = (find_restore_tag(
            ftc.resume_dir, checksums=ckcfg.verify_checksums) is not None
            or read_latest_tag(ftc.resume_dir) is not None)
        if not has_ckpt:
            log_dist(f"auto_resume: no checkpoint in {ftc.resume_dir} — "
                     "cold start")
            return False
        self.load_checkpoint(ftc.resume_dir)
        log_dist(f"auto_resume: restored step {self.global_steps} from "
                 f"{ftc.resume_dir}")
        return True

    # ------------------------------------------------------------------ #
    # training-run guardian hooks (runtime/guardian.py; config "guardian")
    # ------------------------------------------------------------------ #
    def attach_guardian(self, guardian) -> Optional[Dict]:
        """Register a :class:`~deepspeed_tpu.runtime.guardian.
        TrainingGuardian`: its loader/detector state rides every
        checkpoint's client state, ``load_checkpoint`` restores it, and
        the log-cadence metrics device_get feeds its anomaly detector.
        Returns the client state of a checkpoint restored BEFORE the
        guardian existed (``auto_resume`` at initialize), if any."""
        self._guardian = guardian
        return self._restored_client_state

    def defer_preemption(self):
        """Context manager deferring SIGTERM handling to scope exit while
        the caller holds un-checkpointable in-flight state — the guardian
        wraps each pull+step+containment cycle so an emergency checkpoint
        can never capture a loader that advanced past a batch the step
        hasn't trained (the offset/global_steps replay contract)."""
        import contextlib

        @contextlib.contextmanager
        def _scope():
            # a separate flag, not _in_step: the wrapped engine.train_batch
            # sets and CLEARS _in_step itself, which would re-open the
            # window mid-scope
            self._guard_busy = True
            try:
                yield
            finally:
                # boundary check INSIDE the finally: a body that raises
                # (e.g. the guardian's RestartableFailure escalation) must
                # still honor a deferred SIGTERM — preemption outranks the
                # in-flight exception (emergency save + exit 0)
                self._guard_busy = False
                self._check_preemption_boundary()

        return _scope()

    def protect_checkpoint_tag(self, tag: Optional[str],
                               root: Optional[str] = None) -> None:
        """Pin ``tag`` (in checkpoint dir ``root``) against ``keep_n``
        retention GC — the guardian's rollback anchor must survive until
        a newer anchor commits. ``None`` clears the pins;
        ``save_checkpoint`` clears them automatically once a newer tag
        commits to the same dir (the walk-back then prefers that tag, so
        the old anchor is obsolete)."""
        if tag is None:
            self._gc_protect_tags.clear()
            self._gc_protect_root = None
        else:
            self._gc_protect_tags = {tag}
            # normalized: supersession compares this to later save dirs —
            # a different SPELLING of the same dir must still clear the pin
            self._gc_protect_root = os.path.abspath(root) if root else None
        self._gc_pin_stale = False

    def probe_microbatch(self, micro: PyTree) -> Dict[str, float]:
        """Replay ONE microbatch against the numerics sentinel WITHOUT
        touching engine state — the guardian's bisect primitive. Runs a
        jitted loss+grad pass (compiled once, cached; strictly off the
        hot path) and returns host floats: ``loss``, ``grad_norm`` (fp16:
        unscaled), ``finite``."""
        if "probe" not in self._compiled:
            def probe(state, b):
                scale = state["scaler"].scale if self.fp16_enabled else None
                loss, grads = self._loss_and_grads(state["master"], b, scale)
                norm = global_grad_norm(grads)
                if scale is not None:
                    norm = norm / scale
                return {"loss": loss, "grad_norm": norm}

            # probe_microbatch is side-effect-free BY CONTRACT (the
            # guardian bisect replays batches against it) — donation
            # would mutate the state it promises to leave untouched
            self._compiled["probe"] = jax.jit(probe)  # dslint: disable=donation
        self._materialize_master()
        batch = self._shard_batch(micro)
        with self.mesh:
            out = self._compiled["probe"](self.state, batch)
        host = {k: float(jax.device_get(v)) for k, v in out.items()}
        host["finite"] = float(np.isfinite(host["loss"])
                               and np.isfinite(host["grad_norm"]))
        return host

    def _check_preemption_boundary(self) -> None:
        """Step/save-boundary half of the deferred preemption handshake.
        Main thread only: SystemExit from a worker thread (e.g. a
        watchdog-thread save that finished while preemption was pending)
        would kill that thread, not the process."""
        if self._preempt_requested and not self._guard_busy and \
                threading.current_thread() is threading.main_thread():
            self._preemption_exit()

    # ------------------------------------------------------------------ #
    # checkpointing (reference engine.py:4557 / :4079)
    # ------------------------------------------------------------------ #
    def save_checkpoint(self, save_dir: str, tag: Optional[str] = None,
                        client_state: Optional[Dict] = None,
                        save_latest: bool = True,
                        async_save: bool = False) -> None:
        from deepspeed_tpu.checkpoint.engine import save_state

        if self._offload_nvme:
            self._nvme_swapper().swap_in_optimizer()
        if self._offload_param_nvme and self._param_swapper is not None:
            self._param_swapper.swap_in_params()
        tag = tag or f"global_step{self.global_steps}"
        if self._gc_pin_stale:
            # an async save superseded the anchor earlier; its commit has
            # drained by now (save_state finalizes in-flight saves first)
            self.protect_checkpoint_tag(None)
        client_state = dict(client_state or {})
        client_state.update({
            "global_steps": self.global_steps,
            "micro_steps": self.micro_steps,
            "skipped_steps": self.skipped_steps,
            "lr_scheduler": self.lr_scheduler.state_dict() if self.lr_scheduler else None,
            "curriculum": (self._curriculum.state_dict()
                           if self._curriculum else None),
            # host RNG (data-efficiency sampling: PLD masks, LTD indices) —
            # auto_resume must not replay or skip sampled randomness
            "np_rng": self._np_rng.bit_generator.state,
            # the world this checkpoint was written at — a fresh elastic
            # agent process compares it against the acquired world to
            # decide native reload vs universal resharding
            "world_size": int(self.dp_world_size),
        })
        if self._guardian is not None:
            # loader position + quarantine list + detector bands ride every
            # checkpoint — including the SIGTERM emergency tag — so resume
            # replays the exact batch sequence (README "Training guardian")
            client_state.update(self._guardian.client_state())
        ck = self.config.checkpoint
        self._saving = True   # a preemption signal mid-save defers here
        try:
            # _checkpoint_state: the gathered double buffer is derived
            # state, excluded from every checkpoint (incl. SIGTERM
            # emergency tags) and recomputed on restore — a checkpoint
            # can never capture a buffer stale relative to its master
            save_state(save_dir, tag, self._checkpoint_state(), client_state,
                       save_latest=save_latest, async_save=async_save,
                       writer=self.config.effective_checkpoint_writer,
                       keep_n=ck.keep_n, fsync=ck.fsync,
                       checksums=ck.verify_checksums, retries=ck.save_retries,
                       retry_backoff_s=ck.retry_backoff_s,
                       retry_jitter_s=ck.retry_jitter_s,
                       protect=tuple(self._gc_protect_tags))
        finally:
            self._saving = False
        self._last_save_dir = save_dir
        if (self._gc_protect_tags and tag not in self._gc_protect_tags
                and self._gc_protect_root in (None,
                                              os.path.abspath(save_dir))):
            if async_save:
                # the superseding tag's COMMIT is still in flight — mark
                # the pin stale and clear it at the next save, whose
                # finalize_async will have drained this commit first
                self._gc_pin_stale = True
            else:
                # a NEWER tag just committed to the anchor's dir: the
                # walk-back now prefers it, so the pinned rollback anchor
                # is obsolete — let the next save's keep_n GC reclaim it
                self.protect_checkpoint_tag(None)
        log_dist(f"saved checkpoint {save_dir}/{tag}"
                 + (" (async, commit in flight)" if async_save else ""))
        self._check_preemption_boundary()

    def save_16bit_model(self, save_dir: str,
                         save_filename: str = "pytorch_model.npz") -> None:
        """Gather params and export in the compute dtype (reference
        ``save_16bit_model`` engine.py:5355 / ``_zero3_consolidated_16bit_state_dict``
        :5285 — the live-consolidation path)."""
        import ml_dtypes
        import numpy as np_

        os.makedirs(save_dir, exist_ok=True)
        params = self.get_fp32_params()
        # bf16 is stored AS bf16 (ml_dtypes registers it with numpy; fp16
        # would silently drop bf16's exponent range — |x| > 65504 → inf)
        # bf16 → ml_dtypes bf16; fp16 → fp16; fp32 engines export fp32
        # unchanged (downcasting would overflow-to-inf above 65504)
        dtype = (ml_dtypes.bfloat16 if self.precision == "bfloat16"
                 else np_.dtype(self.precision))
        flat = {}
        for path, leaf in jax.tree_util.tree_flatten_with_path(params)[0]:
            key = "/".join(p.key if hasattr(p, "key") else str(p.idx) for p in path)
            flat[key] = np_.asarray(jax.device_get(leaf)).astype(dtype)
        if jax.process_index() == 0:
            np_.savez(os.path.join(save_dir, save_filename), **flat)
            # npz round-trips bf16 bytes but loses the dtype name (numpy
            # reads it back as raw V2); the sidecar manifest restores it —
            # consumed by checkpoint.engine.load_16bit_model
            with open(os.path.join(save_dir, save_filename + ".dtypes.json"),
                      "w") as f:
                json.dump({k: str(np_.dtype(v.dtype)) for k, v in flat.items()},
                          f)
        log_dist(f"saved 16-bit model to {save_dir}/{save_filename} "
                 f"(dtype={np_.dtype(dtype)})")

    def load_checkpoint(self, load_dir: str, tag: Optional[str] = None,
                        load_optimizer_states: bool = True,
                        load_lr_scheduler_states: bool = True):
        from deepspeed_tpu.checkpoint.engine import load_state

        if (self._offload_nvme and self._opt_swapper is not None
                and not load_optimizer_states):
            # the checkpoint will NOT supply moments, so the live (NVMe-swapped)
            # ones must be materialized before `state["opt"]` is carried over;
            # on the default path the restore overwrites them anyway and the
            # placeholders suffice as the orbax target template — swapping in
            # there would transiently double optimizer-state HBM
            self._opt_swapper.swap_in_optimizer()
        load_sh = self._state_shardings()
        load_sh.pop("gathered", None)   # derived buffer: never persisted
        state, client_state = load_state(
            load_dir, tag, self._checkpoint_state(), load_sh,
            verify_checksums=self.config.checkpoint.verify_checksums)
        if not load_optimizer_states:
            state["opt"] = self.state["opt"]
        self.state = state
        # republish the double buffer from the RESTORED master — the
        # next forward must consume exactly the restored weights
        self._refresh_param_buffer()
        if self._offload_opt:
            self._opt_swap("out")
        if (self._offload_nvme and self._opt_swapper is not None
                and load_optimizer_states):
            # the restore put real moments in state['opt'] but the swapper
            # still thinks its (stale) swap files are authoritative
            # (_swapped=True) — the next step's swap_in would clobber the
            # restored moments. Re-swap-out: fresh files, consistent state,
            # HBM freed again.
            self._opt_swapper.swap_out_optimizer()
        if self._offload_param:
            self._park_master()   # restored master → pinned-host tier
        if self._offload_param_nvme and self._param_swapper is not None:
            # same reload-clobber hazard as the optimizer swapper: the
            # restored master must supersede the stale swap files
            self._param_swapper.swap_out_params()
        if self._host_runner is not None:
            self._host_runner.adopt_state()   # re-home master/opt + params
        self.global_steps = int(client_state.get("global_steps", 0))
        self.micro_steps = int(client_state.get("micro_steps", 0))
        if load_lr_scheduler_states and self.lr_scheduler is not None and \
                client_state.get("lr_scheduler"):
            self.lr_scheduler.load_state_dict(client_state["lr_scheduler"])
        if self._curriculum is not None and client_state.get("curriculum"):
            self._curriculum.load_state_dict(client_state["curriculum"])
        if client_state.get("np_rng"):
            try:
                self._np_rng.bit_generator.state = client_state["np_rng"]
            except (TypeError, ValueError) as e:
                logger.warning(f"host RNG state in checkpoint not "
                               f"restorable ({e}) — fresh stream")
        # guardian/loader state: restore through an attached guardian, and
        # keep the raw client state so a guardian attached AFTER this load
        # (auto_resume runs at initialize, before TrainingGuardian exists)
        # can still pick it up (TrainingGuardian.__init__ does)
        self._restored_client_state = client_state
        if self._guardian is not None:
            self._guardian.restore_client_state(client_state)
        log_dist(f"loaded checkpoint from {load_dir} (tag={tag or 'latest'})")
        return load_dir, client_state

    def load_universal_checkpoint(self, universal_dir: str,
                                  load_optimizer_states: bool = True) -> None:
        """Load a universal (per-param atom) checkpoint at ANY topology
        (reference ``load_universal_checkpoint``; converter:
        ``deepspeed_tpu.checkpoint.universal``): the world-elastic resume
        path. Master weights and optimizer moments land on this engine's
        mesh whatever world they were saved at; per-rank residual trees
        (LoCo ``loco_err``, onebit ``worker_error``) are re-partitioned
        sum-preservingly onto ``_dp_manual_world``; the guardian/loader
        exact-resume client state rides along so the batch sequence
        continues where the old world left off."""
        from deepspeed_tpu.checkpoint.universal import load_universal_into_engine

        load_universal_into_engine(self, universal_dir, load_optimizer_states)
        log_dist(f"loaded universal checkpoint from {universal_dir} "
                 f"(world {self._dp_manual_world})")

    # ------------------------------------------------------------------ #
    def get_fp32_params(self) -> PyTree:
        """Gathered fp32 master params (the zero_to_fp32 consolidation analog)."""
        self._materialize_master()
        rep = jax.tree.map(lambda _: NamedSharding(self.mesh, P()), self._shapes)
        with self.mesh:
            return jax.jit(lambda m: m, out_shardings=rep)(self.state["master"])
