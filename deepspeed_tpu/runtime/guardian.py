"""Training-run guardian: numerics sentinel, automatic rollback, and
bad-batch quarantine over the checkpointable data pipeline.

The serving path survives chaos (circuit breaker, fleet failover); this
module gives the *training* loop the same guarantee — a fault is
detected, contained, and survived, automatically, with the blast radius
named (README "Training guardian"; config section ``"guardian"``):

1. **Numerics sentinel.** Device side, the engine extends the fp16
   loss-scaler's ``isfinite`` + skip-update ``lax.cond`` to bf16/fp32
   (``guardian.nonfinite_guard``; ``runtime/engine.py _apply_update``) —
   a non-finite step never touches the weights and lands in the
   device-side ``skips`` counter. Host side, :class:`AnomalyDetector`
   keeps EMA mean/variance bands over loss and grad-norm and flags
   ``z_threshold``-sigma spikes — fed by the metrics the engine already
   ``device_get``\\ s each ``steps_per_print`` cadence, so the hot path
   gains zero host syncs.
2. **Rollback.** On a confirmed anomaly, dump a flight trace (reason
   ``anomaly``), then roll engine + optimizer + scaler + loader back to
   the last committed checkpoint tag — ``load_checkpoint``'s walk-back
   reuses the commit-manifest verification, and the restored anchor is
   pinned against ``keep_n`` retention GC until a newer anchor commits.
3. **Quarantine.** Bisect the offending window by replaying its
   microbatches against the sentinel (``engine.probe_microbatch`` —
   loss/grad-norm/finiteness per micro, engine state untouched),
   quarantine the culprit in the loader's state-carried quarantine list,
   and continue past it.
4. **Bounded escalation.** More than ``max_rollbacks`` rollbacks inside
   ``rollback_window_steps`` raises a structured
   :class:`~deepspeed_tpu.elasticity.elastic_agent.RestartableFailure`
   (``reason="guardian"``) into the :class:`ElasticAgent` backoff path;
   when the agent's restart budget is also exhausted the failure is
   flight-dumped and re-raised — never a silent crash loop.
"""
from __future__ import annotations

import dataclasses
import math
from typing import Any, Dict, Iterator, List, Optional, Tuple

from deepspeed_tpu.elasticity.elastic_agent import RestartableFailure
from deepspeed_tpu.utils.logging import log_dist, logger

PyTree = Any

#: signals the detector bands, and the anomaly kind a spike maps to
_BAND_SIGNALS = (("loss", "loss_spike"), ("grad_norm", "grad_norm_spike"))


def _counter(name: str, description: str = ""):
    from deepspeed_tpu import telemetry

    return telemetry.counter(name, description)


def _dump_flight(reason: str, note: Optional[str] = None) -> None:
    """Flight-recorder dump that must never raise into the anomaly
    handler it documents (one shared helper —
    ``telemetry.tracing.safe_dump_flight``)."""
    from deepspeed_tpu.telemetry.tracing import safe_dump_flight

    safe_dump_flight(reason, note=note)


@dataclasses.dataclass
class Anomaly:
    kind: str      # nonfinite | loss_spike | grad_norm_spike
    step: int
    value: float
    detail: str


class AnomalyDetector:
    """EMA mean/variance bands with warmup over per-signal scalars.

    Pure host math, JSON-serializable state (it rides the checkpoint's
    client state so a restored run resumes with its learned bands, not a
    cold warmup). An observed outlier is NOT folded into the band — a
    spike must not raise the band it is judged against — and non-finite
    observations short-circuit to a ``nonfinite`` anomaly.
    """

    #: per-signal variance floor, as a fraction of the band mean: a run
    #: of near-identical observations (memorized batches) collapses the
    #: EMA variance, and without a floor ordinary jitter becomes an
    #: infinite z-score. Gradient norms swing ±50% step-to-step in
    #: healthy training (measured on the tier-1 tiny lanes), so their
    #: floor is wide — a REAL grad explosion is multiples of the mean,
    #: not half a sigma of it.
    REL_FLOORS = {"grad_norm": 0.25}
    DEFAULT_REL_FLOOR = 0.05

    def __init__(self, z_threshold: float = 6.0,
                 warmup_observations: int = 8, ema_decay: float = 0.7):
        self.z_threshold = float(z_threshold)
        self.warmup = int(warmup_observations)
        self.decay = float(ema_decay)
        self._stats: Dict[str, Dict[str, float]] = {}

    # ------------------------------------------------------------- bands
    def _band(self, signal: str) -> Optional[Tuple[float, float]]:
        st = self._stats.get(signal)
        if st is None or st["n"] < self.warmup:
            return None
        std = math.sqrt(max(st["var"], 0.0))
        rel = self.REL_FLOORS.get(signal, self.DEFAULT_REL_FLOOR)
        floor = max(rel * abs(st["mean"]), 1e-8)
        return st["mean"], max(std, floor)

    def is_outlier(self, signal: str, value: float) -> bool:
        """One-sided: only values ABOVE the band spike (a falling loss is
        the goal, not an anomaly)."""
        if not math.isfinite(value):
            return True
        band = self._band(signal)
        if band is None:
            return False
        mean, std = band
        return value > mean + self.z_threshold * std

    def _fold(self, signal: str, value: float) -> None:
        st = self._stats.setdefault(
            signal, {"mean": value, "var": 0.0, "n": 0})
        if 0 < st["n"] <= self.warmup:
            # warmup: equal-weight Welford — an EMA variance seeded from
            # 2-3 samples is pathologically tight and turns normal
            # early-training drift into false spikes
            delta = value - st["mean"]
            st["mean"] += delta / (st["n"] + 1)
            st["var"] += (delta * (value - st["mean"]) - st["var"]) \
                / (st["n"] + 1)
        elif st["n"] > self.warmup:
            delta = value - st["mean"]
            st["mean"] += (1.0 - self.decay) * delta
            st["var"] = self.decay * (st["var"]
                                      + (1.0 - self.decay) * delta * delta)
        st["n"] += 1

    # ---------------------------------------------------------- observe
    def observe(self, step: int, metrics: Dict[str, float]
                ) -> List[Anomaly]:
        """Judge one log-cadence metrics sample; returns the anomalies it
        triggers (empty = clean, and the sample is folded into the
        bands)."""
        out: List[Anomaly] = []
        overflow = metrics.get("overflow") or 0.0
        nonfinite = [k for k in ("loss", "grad_norm")
                     if k in metrics and not math.isfinite(metrics[k])]
        if overflow > 0 or nonfinite:
            detail = ("device skip (overflow metric)" if overflow > 0
                      else f"non-finite {','.join(nonfinite)}")
            out.append(Anomaly("nonfinite", step,
                               metrics.get("loss", float("nan")), detail))
            return out   # a poisoned sample must not touch the bands
        for signal, kind in _BAND_SIGNALS:
            value = metrics.get(signal)
            if value is None:
                continue
            if self.is_outlier(signal, value):
                mean, std = self._band(signal)
                out.append(Anomaly(
                    kind, step, value,
                    f"{signal}={value:.4g} vs band mean={mean:.4g} "
                    f"std={std:.4g} (z>{self.z_threshold:g})"))
            else:
                self._fold(signal, value)
        return out

    # ------------------------------------------------------------ state
    def state_dict(self) -> Dict[str, Any]:
        return {"stats": {k: dict(v) for k, v in self._stats.items()}}

    def load_state_dict(self, sd: Dict[str, Any]) -> None:
        self._stats = {
            str(k): {"mean": float(v["mean"]), "var": float(v["var"]),
                     "n": int(v["n"])}
            for k, v in (sd.get("stats") or {}).items()}


class _CountingStream:
    """Adapter giving a plain iterable synthetic batch ids ``(0, n)`` —
    used when the guardian's loader has no ``host_stream``/state; no
    quarantine or fast-forward, but detection/rollback still work."""

    def __init__(self, source):
        self._it = iter(source)
        self._n = 0

    def __iter__(self):
        return self

    def __next__(self):
        batch = next(self._it)
        bid = (0, self._n)
        self._n += 1
        return bid, batch


class TrainingGuardian:
    """Wraps an engine + checkpointable loader into a guarded train loop.

    ::

        engine, *_ = deepspeed_tpu.initialize(model=spec, config=cfg)
        loader = DeepSpeedTPUDataLoader(source, sharding)   # stateful
        guardian = TrainingGuardian(engine, loader,
                                    checkpoint_dir="/ckpt")
        guardian.run(num_steps=1000)        # or guardian.train_batch()

    The guardian attaches to the engine: loader position, quarantine
    list, and detector bands ride every checkpoint's client state
    (including SIGTERM emergency tags), ``load_checkpoint`` restores
    them, and the engine's log-cadence metrics feed :meth:`observe`. A
    checkpoint restored by ``auto_resume`` BEFORE the guardian existed
    is picked up at construction.
    """

    def __init__(self, engine, loader,
                 checkpoint_dir: Optional[str] = None):
        cfg = engine.config.guardian
        if not cfg.enabled:
            raise ValueError(
                'TrainingGuardian needs `"guardian": {"enabled": true}` in '
                "the engine config — the device-side non-finite skip is "
                "compiled into the train step at initialize, so arming the "
                "guardian after the fact would silently miss it")
        self.engine = engine
        self.loader = loader
        self.cfg = cfg
        self.checkpoint_dir = (checkpoint_dir
                               or engine.config.fault_tolerance.resume_dir
                               or engine._last_save_dir)
        self.detector = AnomalyDetector(cfg.z_threshold,
                                        cfg.warmup_observations,
                                        cfg.ema_decay)
        self._pending: List[Anomaly] = []
        # rollback budget: global_steps at which rollbacks happened, kept
        # within rollback_window_steps. Deliberately NOT checkpointed — an
        # elastic-agent restart starts with a fresh budget (the agent's
        # max_restarts bounds the outer loop).
        self._rollback_steps: List[int] = []
        self.quarantined_total = 0
        self._skips_seen = int(engine.skipped_steps)
        self._stream: Optional[Iterator] = None
        self.last_window_ids: List[Tuple[int, int]] = []
        restored = engine.attach_guardian(self)
        if restored:
            self.restore_client_state(restored)
        log_dist(
            f"training guardian armed: z={cfg.z_threshold} warmup="
            f"{cfg.warmup_observations} max_rollbacks={cfg.max_rollbacks}"
            f"/{cfg.rollback_window_steps} steps, nonfinite_guard="
            f"{engine._nonfinite_guard or engine.fp16_enabled}, "
            f"anchor dir={self.checkpoint_dir or '<none — escalate only>'}")

    # ------------------------------------------------------------ state
    def client_state(self) -> Dict[str, Any]:
        cs: Dict[str, Any] = {"guardian": {
            "detector": self.detector.state_dict(),
            "quarantined_total": self.quarantined_total,
        }}
        sd = getattr(self.loader, "state_dict", None)
        if callable(sd):
            cs["loader"] = sd()
        return cs

    def restore_client_state(self, client_state: Dict[str, Any]) -> None:
        g = client_state.get("guardian") or {}
        if g.get("detector"):
            self.detector.load_state_dict(g["detector"])
        if "quarantined_total" in g:
            self.quarantined_total = int(g["quarantined_total"])
        loader_sd = client_state.get("loader")
        restore = getattr(self.loader, "load_state_dict", None)
        if loader_sd is not None and callable(restore):
            restore(loader_sd)
        # any live pull generator holds the pre-restore position
        self._stream = None
        self._skips_seen = int(self.engine.skipped_steps)

    # ------------------------------------------------------- data pull
    def _new_stream(self) -> Iterator:
        host = getattr(self.loader, "host_stream", None)
        if callable(host):
            return host()
        return _CountingStream(self.loader)

    def _next_micro(self) -> Tuple[Tuple[int, int], PyTree]:
        empty_passes = 0
        while True:
            if self._stream is None:
                self._stream = self._new_stream()
            try:
                micro = next(self._stream)
                return micro
            except StopIteration:
                self._stream = None   # epoch boundary — next pass
                empty_passes += 1
                if empty_passes >= 2:
                    # two consecutive passes yielded NOTHING: empty
                    # source, or every batch quarantined — spinning
                    # through epochs forever would hang the run silently
                    raise RuntimeError(
                        "guardian: the data loader yielded no batches "
                        "for two consecutive epochs (empty source, or "
                        "the quarantine list covers everything)")

    # -------------------------------------------------------- sentinel
    def observe(self, step: int, host_metrics: Dict[str, float]) -> None:
        """Engine hook (``_after_step``, log cadence): feed the anomaly
        detector from the already-fetched host metrics, plus the delta of
        the device-side skip counter (a skip EARLIER in the cadence
        window would otherwise be invisible — the overflow metric only
        reflects the last step)."""
        host = dict(host_metrics)
        fp16 = self.engine.fp16_enabled
        if fp16:
            # the dynamic loss scaler OWNS fp16 overflow recovery: warmup
            # overflows are routine and self-healing (device skip + scale
            # halving), not anomalies to roll a run back over — and the
            # non-finite SCALED grad norm is the same event. A non-finite
            # LOSS still escalates (the scaler never produces one).
            host.pop("overflow", None)
            gn = host.get("grad_norm")
            if gn is not None and not math.isfinite(gn):
                host.pop("grad_norm")
        anomalies = self.detector.observe(step, host)
        skips = int(self.engine.skipped_steps)
        # fold into train_skipped_steps_total NOW: a rollback rewinds the
        # device counter, so waiting for the next /metrics scrape could
        # lose the skip from the accounting entirely
        self.engine._fold_skipped_steps(skips)
        if not fp16 and skips > self._skips_seen and not any(
                a.kind == "nonfinite" for a in anomalies):
            anomalies.append(Anomaly(
                "nonfinite", step, float(skips - self._skips_seen),
                f"device skip counter advanced {self._skips_seen} -> "
                f"{skips} inside the cadence window"))
        self._skips_seen = max(self._skips_seen, skips)
        for a in anomalies:
            _counter("guardian_anomalies_total",
                     "training anomalies confirmed by the guardian "
                     "sentinel").inc(kind=a.kind)
            logger.warning(f"guardian: {a.kind} anomaly at step {a.step}: "
                           f"{a.detail}")
        self._pending.extend(anomalies)

    def pending_anomalies(self) -> List[Anomaly]:
        return list(self._pending)

    # ------------------------------------------------------ train loop
    def train_batch(self) -> float:
        """One guarded optimizer step: pull the window from the
        checkpointable loader, run the fused step, then contain any
        anomaly the sentinel confirmed (rollback → bisect → quarantine →
        continue, or a structured escalation)."""
        with self.engine.defer_preemption():
            # a SIGTERM inside this scope defers to scope exit: the
            # emergency checkpoint must never capture a loader that
            # advanced past a pulled-but-untrained window, or a
            # containment mid-flight (the exact-replay contract)
            gas = self.engine.gradient_accumulation_steps()
            window = [self._next_micro() for _ in range(gas)]
            self.last_window_ids = [bid for bid, _ in window]
            loss = self.engine.train_batch(iter(m for _, m in window))
            if self._pending:
                self._contain(anomaly_step=self.engine.global_steps)
        return float(loss)

    def run(self, num_steps: int) -> Optional[float]:
        """Run until ``num_steps`` MORE committed steps exist (rolled-back
        steps are re-earned). ``guardian.checkpoint_every`` > 0 writes
        rollback anchors at that cadence into ``checkpoint_dir``."""
        target = self.engine.global_steps + int(num_steps)
        every = self.cfg.checkpoint_every
        loss = None
        while self.engine.global_steps < target:
            loss = self.train_batch()
            if every and self.checkpoint_dir \
                    and self.engine.global_steps % every == 0:
                self.engine.save_checkpoint(self.checkpoint_dir)
        return loss

    # ----------------------------------------------------- containment
    def _contain(self, anomaly_step: int) -> None:
        anomalies, self._pending = list(self._pending), []
        kinds = ",".join(sorted({a.kind for a in anomalies}))
        _dump_flight("anomaly",
                     note=f"step={anomaly_step} kinds={kinds}: "
                          + "; ".join(a.detail for a in anomalies[:4]))
        window = self.cfg.rollback_window_steps
        self._rollback_steps = [
            s for s in self._rollback_steps
            if anomaly_step - s <= window]
        if len(self._rollback_steps) >= self.cfg.max_rollbacks:
            raise RestartableFailure(
                f"guardian: anomaly ({kinds}) at step {anomaly_step} after "
                f"{len(self._rollback_steps)} rollbacks within the last "
                f"{window} steps — rollback budget exhausted, escalating "
                "to the elastic agent", reason="guardian")
        anchor_tag, anchor_step = self._rollback(anomaly_step, kinds)
        self._rollback_steps.append(anomaly_step)
        if self.cfg.bisect_microbatches:
            culprits = self._bisect(anchor_step, anomaly_step)
            for bid, probe in culprits:
                log_dist(f"guardian: bisect culprit batch {bid}: "
                         f"loss={probe['loss']:.4g} "
                         f"grad_norm={probe['grad_norm']:.4g} "
                         f"finite={bool(probe['finite'])}")
                if self.cfg.quarantine \
                        and callable(getattr(self.loader, "quarantine",
                                             None)):
                    self.loader.quarantine(bid)
                    self.quarantined_total += 1
                    _counter("guardian_quarantined_batches_total",
                             "culprit batches quarantined after a bisect"
                             ).inc()
        log_dist(f"guardian: contained {kinds} anomaly — rolled back "
                 f"step {anomaly_step} -> {anchor_step} "
                 f"(anchor {anchor_tag!r}), resuming")

    def _rollback(self, anomaly_step: int, kinds: str
                  ) -> Tuple[str, int]:
        """Restore engine + optimizer + scaler + loader to the newest
        committed checkpoint tag (manifest-verified walk-back). Returns
        ``(tag, restored_step)``; escalates when there is no anchor."""
        if not self.checkpoint_dir:
            raise RestartableFailure(
                f"guardian: anomaly ({kinds}) at step {anomaly_step} and "
                "no checkpoint dir to roll back to — escalating",
                reason="guardian")
        tag = self._pick_anchor_tag(anomaly_step)
        try:
            self.engine.load_checkpoint(self.checkpoint_dir, tag=tag)
        except FileNotFoundError:
            raise RestartableFailure(
                f"guardian: anomaly ({kinds}) at step {anomaly_step} and "
                f"no committed checkpoint in {self.checkpoint_dir!r} — "
                "escalating", reason="guardian") from None
        if tag is not None:
            # the anchor must survive keep_n GC for as long as it IS the
            # anchor (a re-rollback inside the window needs it intact);
            # tag=None = a legacy latest-file checkpoint restored without
            # a commit marker — nothing committed to pin
            self.engine.protect_checkpoint_tag(tag,
                                               root=self.checkpoint_dir)
        else:
            tag = "<legacy latest>"
        self._stream = None   # loader position was restored
        self._skips_seen = int(self.engine.skipped_steps)
        # the device counter rewound with the restore — follow it down so
        # post-rollback skips keep counting (the total stays monotone)
        self.engine._fold_skipped_steps(self._skips_seen, resync=True)
        _counter("guardian_rollbacks_total",
                 "anomaly rollbacks to the last committed checkpoint"
                 ).inc()
        return tag, int(self.engine.global_steps)

    def _pick_anchor_tag(self, anomaly_step: int) -> Optional[str]:
        """Choose the rollback anchor: the NEWEST committed+intact tag
        whose step pre-dates the whole detection window. Detection lags
        up to one log cadence behind the fault, so a tag committed
        inside ``(anomaly_step - cadence, anomaly_step]`` may already
        hold poisoned weights — anchoring there would replay a window
        that EXCLUDES the culprit and burn the rollback budget on
        identical poisoned anchors. Falls back to the plain newest-intact
        walk-back (with a warning) when no tag is old enough, and to
        ``None`` (the loader-side legacy resolution) when nothing
        carries a marker."""
        from deepspeed_tpu.checkpoint.fault_tolerance import (
            committed_tags,
            find_restore_tag,
            read_marker,
            verify_tag,
        )

        checksums = self.engine.config.checkpoint.verify_checksums
        cadence = max(1, self.engine.config.steps_per_print)
        safe_step = anomaly_step - cadence
        for tag in committed_tags(self.checkpoint_dir):
            marker = read_marker(self.checkpoint_dir, tag) or {}
            step = marker.get("step")
            if not isinstance(step, int) or step > safe_step:
                continue
            ok, _why = verify_tag(self.checkpoint_dir, tag,
                                  checksums=checksums)
            if ok:
                return tag
        tag = find_restore_tag(self.checkpoint_dir, checksums=checksums)
        if tag is not None:
            logger.warning(
                f"guardian: no committed anchor at step <= {safe_step} "
                f"(anomaly at {anomaly_step}, detection cadence "
                f"{cadence}) — rolling back to {tag!r}, which may "
                "post-date the fault; the bisect window may miss the "
                "culprit")
        return tag

    def _bisect(self, anchor_step: int, anomaly_step: int
                ) -> List[Tuple[Tuple[int, int], Dict[str, float]]]:
        """Replay the rolled-back window's microbatches against the
        sentinel (probe only — engine state untouched) and name the
        culprits; then rewind the loader to the anchor position so
        training replays from exactly where the rollback left it."""
        sd = getattr(self.loader, "state_dict", None)
        snapshot = sd() if callable(sd) else None
        if snapshot is None:
            # a stateless loader cannot be rewound after the probe replay
            # — bisecting would permanently consume the probed batches
            # from the live stream (and there is no quarantine() to feed
            # anyway); detection + rollback still ran
            logger.warning(
                "guardian: bisect skipped — the loader has no "
                "state_dict() to rewind after the probe replay")
            return []
        gas = self.engine.gradient_accumulation_steps()
        culprits = []
        for _ in range(max(anomaly_step - anchor_step, 0)):
            for _ in range(gas):
                bid, micro = self._next_micro()
                probe = self.engine.probe_microbatch(micro)
                # culprit criteria: non-finite, or per-micro LOSS outside
                # the band. Deliberately NOT the grad-norm band: its
                # statistics are per-STEP (gas-averaged gradients — norm
                # ~1/sqrt(gas) of a single micro's), so judging a single
                # micro against it would quarantine healthy batches at
                # large gas. Loss is a mean either way — scale-compatible.
                if not probe["finite"] \
                        or self.detector.is_outlier("loss", probe["loss"]):
                    culprits.append((bid, probe))
        restore = getattr(self.loader, "load_state_dict", None)
        if snapshot is not None and callable(restore):
            restore(snapshot)
        self._stream = None
        return culprits
