"""Static + dynamic loss scaling for fp16 training.

Parity: reference ``runtime/fp16/loss_scaler.py`` (``LossScaler`` /
``DynamicLossScaler``). TPU-native: the scaler state is a pytree carried inside
the jitted train step; overflow detection is a global ``isfinite`` reduction on
the (sharded) gradients, and the skip-update branch is a ``lax.cond`` — the same
semantics as the reference's ``_overflow_check_and_loss_scale_update``
(``stage3.py:2552``) without host round-trips.
"""
from __future__ import annotations

import dataclasses
from typing import Any, Tuple

import jax
import jax.numpy as jnp


@jax.tree_util.register_dataclass
@dataclasses.dataclass
class LossScaleState:
    scale: jax.Array          # f32 scalar
    good_steps: jax.Array     # i32 scalar: consecutive overflow-free steps
    hysteresis: jax.Array     # i32 scalar: remaining tolerated overflows

    @staticmethod
    def create(initial_scale: float, hysteresis: int = 2) -> "LossScaleState":
        return LossScaleState(
            scale=jnp.asarray(initial_scale, jnp.float32),
            good_steps=jnp.zeros((), jnp.int32),
            hysteresis=jnp.asarray(hysteresis, jnp.int32),
        )


@dataclasses.dataclass
class DynamicLossScaler:
    """Config + pure update rules (state lives in the train step)."""

    initial_scale: float = 2.0 ** 16
    scale_factor: float = 2.0
    scale_window: int = 1000
    min_scale: float = 1.0
    hysteresis: int = 2
    consecutive_hysteresis: bool = False
    dynamic: bool = True

    @staticmethod
    def from_config(fp16_config) -> "DynamicLossScaler":
        if not fp16_config.dynamic_loss_scale:
            return DynamicLossScaler(initial_scale=fp16_config.loss_scale, dynamic=False)
        return DynamicLossScaler(
            initial_scale=2.0 ** fp16_config.initial_scale_power,
            scale_window=fp16_config.loss_scale_window,
            min_scale=fp16_config.min_loss_scale,
            hysteresis=fp16_config.hysteresis,
            consecutive_hysteresis=fp16_config.consecutive_hysteresis,
        )

    def init_state(self) -> LossScaleState:
        return LossScaleState.create(self.initial_scale, self.hysteresis)

    def has_overflow(self, grads: Any) -> jax.Array:
        leaves = jax.tree.leaves(grads)
        finite = jnp.asarray(True)
        for g in leaves:
            finite = jnp.logical_and(finite, jnp.all(jnp.isfinite(g)))
        return jnp.logical_not(finite)

    def update(self, state: LossScaleState, overflow: jax.Array) -> LossScaleState:
        if not self.dynamic:
            return state

        def on_overflow(s: LossScaleState) -> LossScaleState:
            hyst = s.hysteresis - 1
            new_scale = jnp.where(
                hyst <= 0,
                jnp.maximum(s.scale / self.scale_factor, self.min_scale),
                s.scale)
            return LossScaleState(scale=new_scale, good_steps=jnp.zeros((), jnp.int32),
                                  hysteresis=jnp.maximum(hyst, 1))

        def on_good(s: LossScaleState) -> LossScaleState:
            good = s.good_steps + 1
            grow = (good % self.scale_window) == 0
            new_scale = jnp.where(grow, s.scale * self.scale_factor, s.scale)
            hyst = jnp.asarray(self.hysteresis, jnp.int32) if self.consecutive_hysteresis \
                else s.hysteresis
            return LossScaleState(scale=new_scale, good_steps=good, hysteresis=hyst)

        return jax.lax.cond(overflow, on_overflow, on_good, state)


def global_grad_norm(grads: Any, axes=None) -> jax.Array:
    """L2 norm over the full (possibly sharded) gradient pytree. Under pjit the
    partial sums are combined by XLA; under shard_map pass reduction ``axes``."""
    leaves = jax.tree.leaves(grads)
    total = jnp.zeros((), jnp.float32)
    for g in leaves:
        total = total + jnp.sum(jnp.square(g.astype(jnp.float32)))
    if axes:
        total = jax.lax.psum(total, axes)
    return jnp.sqrt(total)


def clip_by_global_norm(grads: Any, max_norm: float, norm: jax.Array) -> Any:
    factor = jnp.minimum(1.0, max_norm / (norm + 1e-6))
    return jax.tree.map(lambda g: (g.astype(jnp.float32) * factor).astype(g.dtype), grads)
