"""NVMe tensor swapping — the ZeRO-Infinity offload tier.

Parity: reference ``runtime/swap_tensor/`` (``AsyncPartitionedParameterSwapper``
``partitioned_param_swapper.py:37``, ``PartitionedOptimizerSwapper``
``partitioned_optimizer_swapper.py:27``, pipelined variant :52) over the
DeepNVMe aio handle. Here a pytree of (sharded) jax arrays round-trips to
files under an NVMe path with async thread-pool I/O
(``deepspeed_tpu/ops/aio.py`` ← ``csrc/aio/aio.cpp``); swap-out overlaps with
compute because the write happens from a host snapshot while the device moves
on.
"""
from __future__ import annotations

import json
import os
from typing import Any, Dict, List, Optional, Tuple

import jax
import numpy as np

from deepspeed_tpu.ops.aio import AsyncIOHandle

PyTree = Any

MANIFEST = "swap_manifest.json"


def _flatten(tree: PyTree) -> List[Tuple[str, Any]]:
    out = []
    for path, leaf in jax.tree_util.tree_flatten_with_path(tree)[0]:
        key = "/".join(p.key if hasattr(p, "key") else str(p.idx) for p in path)
        out.append((key, leaf))
    return out


class TensorSwapper:
    """Swap a pytree of arrays to NVMe files and back (async)."""

    def __init__(self, swap_dir: str, n_threads: int = 4):
        self.swap_dir = swap_dir
        os.makedirs(swap_dir, exist_ok=True)
        self.handle = AsyncIOHandle(n_threads)
        self._manifest: Dict[str, Dict] = {}

    def _path(self, key: str) -> str:
        return os.path.join(self.swap_dir, key.replace("/", "__") + ".bin")

    # ------------------------------------------------------------ #
    def swap_out(self, tree: PyTree, wait: bool = True) -> None:
        """Write every leaf to its file (async unless ``wait``)."""
        for key, leaf in _flatten(tree):
            host = np.asarray(jax.device_get(leaf))
            self._manifest[key] = {
                "shape": list(host.shape), "dtype": str(host.dtype)}
            self.handle.async_pwrite(host, self._path(key))
        with open(os.path.join(self.swap_dir, MANIFEST), "w") as f:
            json.dump(self._manifest, f)
        if wait:
            self.handle.wait_all()

    def swap_in(self, template: Optional[PyTree] = None,
                shardings: Optional[PyTree] = None) -> PyTree:
        """Read all leaves back; returns a pytree shaped like ``template``
        (or a flat dict when no template is given)."""
        if not self._manifest:
            with open(os.path.join(self.swap_dir, MANIFEST)) as f:
                self._manifest = json.load(f)
        bufs: Dict[str, np.ndarray] = {}
        for key, meta in self._manifest.items():
            buf = np.empty(meta["shape"], np.dtype(meta["dtype"]))
            self.handle.async_pread(buf, self._path(key))
            bufs[key] = buf
        self.handle.wait_all()

        if template is None:
            return bufs
        leaves, treedef = jax.tree_util.tree_flatten(template)
        keys = [k for k, _ in _flatten(template)]
        out_leaves = []
        for key, tmpl in zip(keys, leaves):
            arr = bufs[key]
            out_leaves.append(arr)
        tree = jax.tree_util.tree_unflatten(treedef, out_leaves)
        if shardings is not None:
            tree = jax.tree.map(jax.device_put, tree, shardings)
        return tree

    def wait_all(self) -> None:
        self.handle.wait_all()


class OptimizerSwapper:
    """Engine-facing NVMe optimizer-state swapper (reference
    ``PartitionedOptimizerSwapper``): ``swap_out_optimizer(engine)`` after the
    step frees HBM; ``swap_in_optimizer(engine)`` restores it before the next."""

    def __init__(self, engine, swap_dir: Optional[str] = None, n_threads: int = 4):
        cfg = engine.config.zero_optimization.offload_optimizer
        swap_dir = swap_dir or cfg.nvme_path or "/tmp/dstpu_swap"
        self.engine = engine
        self.swapper = TensorSwapper(os.path.join(swap_dir, "optimizer"),
                                     n_threads)
        self._swapped = False
        self._template = None

    def swap_out_optimizer(self, wait: bool = True) -> None:
        """Write moments to NVMe and DROP the device buffers (the engine's
        ``state['opt']`` holds ShapeDtypeStructs while swapped — HBM is
        actually freed, matching the reference swapper's release). Call
        ``swap_in_optimizer`` before anything that reads optimizer state
        (next step, checkpoint save)."""
        opt = self.engine.state["opt"]
        self._template = jax.tree.map(
            lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype), opt)
        self.swapper.swap_out(opt, wait=wait)
        self.engine.state["opt"] = self._template
        self._swapped = True

    def swap_in_optimizer(self) -> None:
        if not self._swapped:
            return
        shardings = self.engine._state_shardings()["opt"]
        self.engine.state["opt"] = self.swapper.swap_in(
            self._template, shardings)
        self._swapped = False
