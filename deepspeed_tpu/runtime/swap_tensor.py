"""NVMe tensor swapping — the ZeRO-Infinity offload tier.

Parity: reference ``runtime/swap_tensor/`` (``AsyncPartitionedParameterSwapper``
``partitioned_param_swapper.py:37``, ``PartitionedOptimizerSwapper``
``partitioned_optimizer_swapper.py:27``, pipelined variant :52) over the
DeepNVMe aio handle. Here a pytree of (sharded) jax arrays round-trips to
files under an NVMe path with async thread-pool I/O
(``deepspeed_tpu/ops/aio.py`` ← ``csrc/aio/aio.cpp``); swap-out overlaps with
compute because the write happens from a host snapshot while the device moves
on.
"""
from __future__ import annotations

import json
import os
from typing import Any, Dict, List, Optional, Tuple

import jax
import numpy as np

from deepspeed_tpu.ops.aio import AsyncIOHandle

PyTree = Any

MANIFEST = "swap_manifest.json"


def _flatten(tree: PyTree) -> List[Tuple[str, Any]]:
    out = []
    for path, leaf in jax.tree_util.tree_flatten_with_path(tree)[0]:
        key = "/".join(p.key if hasattr(p, "key") else str(p.idx) for p in path)
        out.append((key, leaf))
    return out


class TensorSwapper:
    """Swap a pytree of arrays to NVMe files and back (async)."""

    def __init__(self, swap_dir: str, n_threads: int = 4):
        self.swap_dir = swap_dir
        os.makedirs(swap_dir, exist_ok=True)
        self.handle = AsyncIOHandle(n_threads)
        self._manifest: Dict[str, Dict] = {}

    def _path(self, key: str) -> str:
        return os.path.join(self.swap_dir, key.replace("/", "__") + ".bin")

    # ------------------------------------------------------------ #
    def swap_out(self, tree: PyTree, wait: bool = True) -> None:
        """Write every leaf to its file (async unless ``wait``)."""
        for key, leaf in _flatten(tree):
            host = np.asarray(jax.device_get(leaf))
            self._manifest[key] = {
                "shape": list(host.shape), "dtype": str(host.dtype)}
            self.handle.async_pwrite(host, self._path(key))
        with open(os.path.join(self.swap_dir, MANIFEST), "w") as f:
            json.dump(self._manifest, f)
        if wait:
            self.handle.wait_all()

    def swap_in(self, template: Optional[PyTree] = None,
                shardings: Optional[PyTree] = None) -> PyTree:
        """Read all leaves back; returns a pytree shaped like ``template``
        (or a flat dict when no template is given)."""
        if not self._manifest:
            with open(os.path.join(self.swap_dir, MANIFEST)) as f:
                self._manifest = json.load(f)
        bufs: Dict[str, np.ndarray] = {}
        for key, meta in self._manifest.items():
            buf = np.empty(meta["shape"], np.dtype(meta["dtype"]))
            self.handle.async_pread(buf, self._path(key))
            bufs[key] = buf
        self.handle.wait_all()

        if template is None:
            return bufs
        leaves, treedef = jax.tree_util.tree_flatten(template)
        keys = [k for k, _ in _flatten(template)]
        out_leaves = []
        for key, tmpl in zip(keys, leaves):
            arr = bufs[key]
            out_leaves.append(arr)
        tree = jax.tree_util.tree_unflatten(treedef, out_leaves)
        if shardings is not None:
            tree = jax.tree.map(jax.device_put, tree, shardings)
        return tree

    def wait_all(self) -> None:
        self.handle.wait_all()


class _StateSwapper:
    """Engine-facing NVMe swapper for ONE tier of ``engine.state``: the
    swap_out/template/swap_in/_swapped protocol is shared; subclasses pick
    the state key, restore shardings, and config section.

    While swapped out the state slot holds ShapeDtypeStructs — memory is
    actually freed, matching the reference swappers' release; restore
    before anything that reads that tier (next step, checkpoint save)."""

    state_key: str
    subdir: str

    def __init__(self, engine, swap_dir: Optional[str] = None,
                 n_threads: int = 4):
        swap_dir = swap_dir or self._config(engine).nvme_path \
            or "/tmp/dstpu_swap"
        self.engine = engine
        self.swapper = TensorSwapper(os.path.join(swap_dir, self.subdir),
                                     n_threads)
        self._swapped = False
        self._template = None

    def _config(self, engine):
        raise NotImplementedError

    def _restore_shardings(self):
        raise NotImplementedError

    def _swap_out(self, wait: bool = True) -> None:
        tree = self.engine.state[self.state_key]
        self._template = jax.tree.map(
            lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype), tree)
        self.swapper.swap_out(tree, wait=wait)
        self.engine.state[self.state_key] = self._template
        self._swapped = True

    def _swap_in(self) -> None:
        if not self._swapped:
            return
        self.engine.state[self.state_key] = self.swapper.swap_in(
            self._template, self._restore_shardings())
        self._swapped = False


class OptimizerSwapper(_StateSwapper):
    """NVMe optimizer-state swapper (reference
    ``PartitionedOptimizerSwapper`` ``partitioned_optimizer_swapper.py:27``;
    config ``offload_optimizer.device == "nvme"``)."""

    state_key = "opt"
    subdir = "optimizer"

    def _config(self, engine):
        return engine.config.zero_optimization.offload_optimizer

    def _restore_shardings(self):
        return self.engine._state_shardings()["opt"]

    swap_out_optimizer = _StateSwapper._swap_out
    swap_in_optimizer = _StateSwapper._swap_in


class ParamSwapper(_StateSwapper):
    """NVMe PARAMETER swapper (reference
    ``AsyncPartitionedParameterSwapper`` ``partitioned_param_swapper.py:37``;
    config ``offload_param.device == "nvme"`` at stage 3). Restores straight
    to the pinned-host tier — the step streams/unparks from there; landing
    on device first would spike HBM."""

    state_key = "master"
    subdir = "param"

    def _config(self, engine):
        return engine.config.zero_optimization.offload_param

    def _restore_shardings(self):
        return self.engine._master_host_shardings()

    swap_out_params = _StateSwapper._swap_out
    swap_in_params = _StateSwapper._swap_in
