"""Sparse gradients for giant embeddings.

Parity: reference ``runtime/sparse_tensor.py`` (``SparseTensor`` wrapping
torch COO) + the engine's sparse allreduce path (``engine.py:3619-3687``
``sparse_allreduce_bucket``: all-gather per-rank indices/values instead of
reducing the dense [vocab, H] gradient; used for ``nn.Embedding(sparse=True)``).

TPU translation: inside one jitted step XLA already keeps embedding gradients
as scatter-adds, so the *intra-program* problem disappears. What remains real
is the **cross-replica reduction cost**: a dense [V, H] grad allreduce moves
V·H floats even though each batch touches ≤ B·S rows. This module provides
the COO row representation and a row-gather allreduce that moves only
``world × touched_rows × H``:

* :class:`SparseRows` — (rows [nnz], values [nnz, H], vocab) with static nnz
  (padded; jit-friendly);
* :func:`embedding_grad_rows` — build from the token batch (touched rows =
  the tokens themselves — exact, no thresholding);
* :func:`sparse_allreduce` — ``shard_map`` all-gather of (rows, values) over
  the data axes + scatter-add to dense, or kept sparse with
  ``combine=False`` (the reference returns the concatenated sparse form).

Use when vocab ≫ batch·seq (e.g. recommendation / retrieval embeddings).
"""
from __future__ import annotations

import dataclasses
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax, shard_map
from jax.sharding import Mesh, PartitionSpec as P

from deepspeed_tpu.comm.mesh import DATA_AXIS, get_mesh_manager


@dataclasses.dataclass
class SparseRows:
    """COO-by-row sparse tensor with a static row budget (jit-safe)."""

    rows: jax.Array      # [nnz] int32 row ids (may repeat; -1 = padding)
    values: jax.Array    # [nnz, H]
    vocab: int

    def to_dense(self) -> jax.Array:
        safe = jnp.where(self.rows >= 0, self.rows, self.vocab)
        dense = jnp.zeros((self.vocab + 1, self.values.shape[-1]),
                          self.values.dtype)
        dense = dense.at[safe].add(self.values)
        return dense[: self.vocab]

    @property
    def nnz(self) -> int:
        return self.rows.shape[0]


def embedding_grad_rows(tokens: jax.Array, grad_rows: jax.Array,
                        vocab: int) -> SparseRows:
    """Sparse embedding gradient from the batch itself.

    tokens [B, S] int32; grad_rows [B, S, H] = upstream grad per token slot
    (d loss / d emb[token]). Exact: the dense grad is the scatter-add of
    these rows."""
    flat_t = tokens.reshape(-1).astype(jnp.int32)
    flat_g = grad_rows.reshape(flat_t.shape[0], -1)
    return SparseRows(rows=flat_t, values=flat_g, vocab=vocab)


def sparse_allreduce(st: SparseRows, mesh: Optional[Mesh] = None,
                     axis_name: str = DATA_AXIS, mean: bool = True,
                     combine: bool = True):
    """Reduce a per-replica sparse grad across the data axis.

    ICI bytes: world × nnz × (H+1) versus vocab × H for the dense path —
    a win whenever world·nnz ≪ vocab. ``combine=True`` → dense [V, H];
    False → concatenated SparseRows (world×nnz entries, the reference's
    sparse output form)."""
    m = mesh or get_mesh_manager().mesh
    world = m.shape.get(axis_name, 1)
    if world <= 1:
        return st.to_dense() if combine else st

    def local(rows, vals):
        rows_g = lax.all_gather(rows, axis_name, tiled=True)
        vals_g = lax.all_gather(vals, axis_name, tiled=True)
        return rows_g, vals_g

    rows_g, vals_g = shard_map(
        local, mesh=m,
        in_specs=(P(axis_name), P(axis_name, None)),
        out_specs=(P(), P()), check_vma=False)(st.rows, st.values)
    scale = (1.0 / world) if mean else 1.0
    out = SparseRows(rows=rows_g, values=vals_g * scale, vocab=st.vocab)
    return out.to_dense() if combine else out
