"""Hessian eigenvalue estimation via power iteration.

Parity: reference ``runtime/eigenvalue.py:13`` (``Eigenvalue``: block-wise
power iteration on module gradients, used by compression-aware training to
set per-layer quantization schedules). The reference iterates torch autograd
``grad(grad·v)``; here the Hessian-vector product is ``jax.jvp`` over
``jax.grad`` — exact forward-over-reverse HVP, one jit."""
from __future__ import annotations

from typing import Any, Callable, Dict, Optional, Tuple

import jax
import jax.numpy as jnp

PyTree = Any


def _tree_dot(a: PyTree, b: PyTree) -> jax.Array:
    return sum(jnp.vdot(x, y) for x, y in
               zip(jax.tree.leaves(a), jax.tree.leaves(b)))


def _tree_norm(a: PyTree) -> jax.Array:
    return jnp.sqrt(_tree_dot(a, a).real)


def hvp(loss_fn: Callable[[PyTree], jax.Array], params: PyTree,
        v: PyTree) -> PyTree:
    """Hessian·v by forward-over-reverse (exact, two passes)."""
    return jax.jvp(jax.grad(loss_fn), (params,), (v,))[1]


class Eigenvalue:
    """Power-iteration top Hessian eigenvalue (reference class name/API)."""

    def __init__(self, verbose: bool = False, max_iter: int = 100,
                 tol: float = 1e-2, stability: float = 1e-6,
                 gas_boundary_resolution: int = 1):
        self.max_iter = max_iter
        self.tol = tol
        self.stability = stability
        self.verbose = verbose

    def compute_eigenvalue(self, loss_fn: Callable[[PyTree], jax.Array],
                           params: PyTree, rng: Optional[jax.Array] = None
                           ) -> Tuple[float, PyTree]:
        """→ (top eigenvalue estimate, eigenvector pytree)."""
        rng = rng if rng is not None else jax.random.PRNGKey(0)
        leaves, treedef = jax.tree_util.tree_flatten(params)
        keys = jax.random.split(rng, len(leaves))
        v = jax.tree_util.tree_unflatten(
            treedef, [jax.random.normal(k, l.shape, jnp.float32)
                      for k, l in zip(keys, leaves)])
        nrm = _tree_norm(v)
        v = jax.tree.map(lambda x: x / (nrm + self.stability), v)

        hvp_jit = jax.jit(lambda p, vv: hvp(loss_fn, p, vv))
        eig = 0.0
        for i in range(self.max_iter):
            hv = hvp_jit(params, v)
            new_eig = float(_tree_dot(v, hv).real)
            nrm = float(_tree_norm(hv))
            if nrm < self.stability:
                break
            v = jax.tree.map(lambda x: x / nrm, hv)
            if i > 0 and abs(new_eig - eig) <= self.tol * abs(new_eig):
                eig = new_eig
                break
            eig = new_eig
        return eig, v
