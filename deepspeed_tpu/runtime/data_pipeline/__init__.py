"""Data efficiency (reference ``runtime/data_pipeline/``): curriculum
learning + random-LTD + the offline difficulty analyzer."""
from deepspeed_tpu.runtime.data_pipeline.curriculum_scheduler import (
    CurriculumScheduler,
    curriculum_dataloader,
)
from deepspeed_tpu.runtime.data_pipeline.data_analyzer import (
    DataAnalysis,
    DataAnalyzer,
    curriculum_sample_dataloader,
)
from deepspeed_tpu.runtime.data_pipeline.random_ltd import (
    RandomLTDScheduler,
    gather_tokens,
    random_token_select,
    scatter_tokens,
)

__all__ = [
    "CurriculumScheduler",
    "curriculum_dataloader",
    "DataAnalyzer",
    "DataAnalysis",
    "curriculum_sample_dataloader",
    "RandomLTDScheduler",
    "gather_tokens",
    "random_token_select",
    "scatter_tokens",
]
