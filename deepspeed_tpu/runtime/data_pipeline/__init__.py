"""Data efficiency (reference ``runtime/data_pipeline/``): curriculum
learning + random-LTD."""
from deepspeed_tpu.runtime.data_pipeline.curriculum_scheduler import (
    CurriculumScheduler,
    curriculum_dataloader,
)
from deepspeed_tpu.runtime.data_pipeline.random_ltd import (
    RandomLTDScheduler,
    gather_tokens,
    random_token_select,
    scatter_tokens,
)

__all__ = [
    "CurriculumScheduler",
    "curriculum_dataloader",
    "RandomLTDScheduler",
    "gather_tokens",
    "random_token_select",
    "scatter_tokens",
]
