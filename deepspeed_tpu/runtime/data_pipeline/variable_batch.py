"""Variable batch size + LR scaling — token-budget batching.

Parity: reference ``runtime/data_pipeline/data_sampling/
variable_batch_size_and_lr.py:1-492`` (``batch_by_size``: group
variable-length samples so each batch holds ≈``max_tokens``; scale the LR per
batch so the update magnitude matches the nominal batch size).

TPU adaptation: XLA needs static shapes, so each emitted batch is PADDED to a
(batch-bucket × seq-bucket) grid — a handful of compiled programs instead of
one per composition. The LR scale rides the batch dict (``"lr_scale"``) and
the engine folds it into the step's learning rate inside jit.
"""
from __future__ import annotations

import math
from typing import Any, Dict, Iterator, List, Optional, Sequence

import numpy as np


def batch_by_tokens(lengths: Sequence[int], max_tokens: int,
                    min_batch_size: int = 1, max_batch_size: int = 0,
                    order: str = "dataloader",
                    seed: int = 0) -> List[List[int]]:
    """Group sample indices into batches of ≈``max_tokens`` total (padded)
    tokens (reference ``batch_by_size``). Batch cost = n_samples × max_len
    (padded rectangle, what the chip actually computes)."""
    idx = list(range(len(lengths)))
    if order == "random":
        np.random.default_rng(seed).shuffle(idx)
    elif order == "seqlen":
        idx.sort(key=lambda i: lengths[i])
    batches: List[List[int]] = []
    cur: List[int] = []
    cur_max = 0
    for i in idx:
        new_max = max(cur_max, lengths[i])
        if cur and ((len(cur) + 1) * new_max > max_tokens
                    or (max_batch_size and len(cur) >= max_batch_size)):
            batches.append(cur)
            cur, cur_max = [], 0
            new_max = lengths[i]
        cur.append(i)
        cur_max = new_max
    if cur:
        batches.append(cur)
    # fold undersized batches into a neighbor (reference drops or merges;
    # merging loses no data). Walk with an index — mutating while iterating
    # skips elements and `index-1` would wrap batch 0 to the END of the list.
    i = 0
    while i < len(batches):
        if len(batches[i]) < min_batch_size and len(batches) > 1:
            target = i - 1 if i > 0 else 1
            batches[target].extend(batches.pop(i))
            # the pop shifted the list — recheck the same index
            continue
        i += 1
    return batches


def lr_scale_for(batch_size: int, base_batch_size: int,
                 method: str = "linear") -> float:
    """Reference ``scale_lr``: linear (Goyal et al.) or sqrt (Hoffer et al.)
    scaling of the LR with the realized batch size."""
    if method == "none" or base_batch_size <= 0:
        return 1.0
    r = batch_size / base_batch_size
    if method == "linear":
        return r
    if method == "sqrt":
        return math.sqrt(r)
    raise ValueError(f"unknown lr_scaling_method {method!r}")


def _bucket_pow2(n: int, minimum: int = 1) -> int:
    b = minimum
    while b < n:
        b *= 2
    return b


def variable_batch_dataloader(samples: Sequence[np.ndarray], max_tokens: int,
                              base_batch_size: int,
                              lr_scaling_method: str = "linear",
                              min_batch_size: int = 1,
                              max_batch_size: int = 0,
                              order: str = "dataloader",
                              pad_token: int = 0,
                              seed: int = 0,
                              batch_multiple: int = 1,
                              loop: bool = True) -> Iterator[Dict[str, Any]]:
    """Yield dict batches {'tokens': [B_pad, S_pad], 'loss_mask', 'lr_scale'}.

    B and S are bucketed to powers of two so the engine compiles a bounded
    program set; ``batch_multiple`` additionally rounds B up to the data-
    parallel width so the batch dim shards evenly. ``lr_scale`` reflects the
    REAL (unpadded) sample count; padded rows carry a zero loss mask.
    """
    lengths = [len(s) for s in samples]
    batches = batch_by_tokens(lengths, max_tokens, min_batch_size,
                              max_batch_size, order, seed)
    while True:
        for group in batches:
            real_b = len(group)
            s_max = max(lengths[i] for i in group)
            B = _bucket_pow2(real_b, minimum=max(1, batch_multiple))
            B = -(-B // batch_multiple) * batch_multiple
            S = _bucket_pow2(s_max, minimum=8)
            tokens = np.full((B, S), pad_token, np.int32)
            mask = np.zeros((B, S), np.float32)
            for r, i in enumerate(group):
                n = lengths[i]
                tokens[r, :n] = samples[i]
                mask[r, :n] = 1.0
            yield {"tokens": tokens, "loss_mask": mask,
                   "lr_scale": np.float32(
                       lr_scale_for(real_b, base_batch_size,
                                    lr_scaling_method))}
        if not loop:
            return
