"""Random-LTD — random layerwise token dropping.

Parity: reference ``runtime/data_pipeline/data_routing/`` (random-LTD scheduler
+ ``csrc/random_ltd`` gather/scatter kernels): middle transformer layers train
on a random subset of tokens, with the kept-token count ramping up over
training. The gather/scatter is jnp ``take``/``scatter`` (XLA fuses; the CUDA
kernels' job), the schedule mirrors the reference's linear ramp.

Model integration (``random_ltd_transform``): tokens are dropped once for the
whole middle stack — the scan-over-layers layout keeps per-layer shapes
uniform, so the drop boundary sits between scans rather than inside one (same
memory/compute saving, one fewer degree of freedom than the reference).
"""
from __future__ import annotations

from typing import Dict, Optional, Tuple

import jax
import jax.numpy as jnp


class RandomLTDScheduler:
    """Linear ramp of kept-token count (reference
    ``random_ltd_scheduler.py`` semantics: seq starts at ``start_value``,
    reaches full length at ``total_steps``)."""

    def __init__(self, config: Dict):
        sched = config.get("random_ltd_schedule", {})
        sub = sched.get("schedule_config", {})
        # reference JSON nests min_value/max_value inside random_ltd_schedule;
        # start_value / top-level max_value kept as aliases
        self.start_tokens = int(sched.get("min_value",
                                          sched.get("start_value", 128)))
        self.step_size = int(sub.get("seq_per_step", 16))
        self.total_steps = int(sub.get("require_steps", 1000))
        self.max_tokens = int(sched.get("max_value",
                                        config.get("max_value", 1024)))

    def get_kept_tokens(self, global_step: int) -> int:
        t = min(1.0, global_step / max(1, self.total_steps))
        kept = self.start_tokens + t * (self.max_tokens - self.start_tokens)
        kept = int(kept // self.step_size * self.step_size)
        return max(self.start_tokens, min(self.max_tokens, kept))


def random_token_select(rng: jax.Array, seq_len: int, keep: int
                        ) -> Tuple[jax.Array, jax.Array]:
    """→ (kept_idx [keep] sorted, mask [seq_len] bool). The gather index set
    of the reference's ``token_sort``/``gather`` kernels."""
    perm = jax.random.permutation(rng, seq_len)
    kept = jnp.sort(perm[:keep])
    mask = jnp.zeros((seq_len,), bool).at[kept].set(True)
    return kept, mask


def gather_tokens(x: jax.Array, idx: jax.Array) -> jax.Array:
    """x [B, S, ...] → [B, keep, ...] (csrc/random_ltd gather analog)."""
    return jnp.take(x, idx, axis=1)


def scatter_tokens(full: jax.Array, part: jax.Array, idx: jax.Array) -> jax.Array:
    """Write processed kept tokens back into the full sequence
    (csrc/random_ltd scatter analog): dropped positions keep ``full``."""
    return full.at[:, idx].set(part)
