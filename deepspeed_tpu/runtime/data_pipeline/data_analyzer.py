"""Offline data analysis feeding curriculum learning.

Parity: reference ``runtime/data_pipeline/data_sampling/data_analyzer.py``
(``DataAnalyzer`` — maps every sample to a difficulty metric, writes index
files, and the curriculum consumes difficulty→sample maps) and
``data_sampling/indexed_dataset`` (the persisted index). The repo's
curriculum scheduler previously consumed a difficulty SCHEDULE but nothing
produced per-sample difficulty indices — this closes that loop.

TPU note: analysis is a host-side, offline pass (numpy); nothing here runs
under jit.
"""
from __future__ import annotations

import dataclasses
import json
import os
from typing import Any, Callable, Dict, Iterable, Iterator, Optional

import numpy as np

MANIFEST = "data_analysis.json"


def _seqlen_metric(sample: np.ndarray, pad_token_id: int) -> float:
    """Non-pad token count (the reference's seqlen curriculum metric)."""
    return float(np.sum(np.asarray(sample) != pad_token_id))


def _vocab_rarity_metric(sample: np.ndarray, pad_token_id: int) -> float:
    """Mean token id as a cheap rarity proxy (BPE ids are roughly
    frequency-ranked — the reference's vocabularyrarity metric uses the
    same observation)."""
    s = np.asarray(sample)
    s = s[s != pad_token_id]
    return float(np.mean(s)) if s.size else 0.0


METRICS: Dict[str, Callable[[np.ndarray, int], float]] = {
    "seqlen": _seqlen_metric,
    "vocab_rarity": _vocab_rarity_metric,
}


@dataclasses.dataclass
class DataAnalysis:
    """Per-sample difficulty index (the analyzer's output artifact)."""

    metric: str
    difficulties: np.ndarray           # [N] float — difficulty per sample

    def sample_map(self, max_difficulty: float) -> np.ndarray:
        """Indices of samples at or below a difficulty threshold — what the
        curriculum draws from at its current difficulty (reference
        curriculum data-sampling semantics)."""
        return np.nonzero(self.difficulties <= max_difficulty)[0]

    def sorted_indices(self) -> np.ndarray:
        """Sample indices easiest-first (stable)."""
        return np.argsort(self.difficulties, kind="stable")

    def save(self, out_dir: str) -> None:
        os.makedirs(out_dir, exist_ok=True)
        np.save(os.path.join(out_dir, "difficulties.npy"), self.difficulties)
        with open(os.path.join(out_dir, MANIFEST), "w") as f:
            json.dump({"metric": self.metric,
                       "n_samples": int(self.difficulties.shape[0]),
                       "min": float(self.difficulties.min()),
                       "max": float(self.difficulties.max())}, f)

    @classmethod
    def load(cls, out_dir: str) -> "DataAnalysis":
        with open(os.path.join(out_dir, MANIFEST)) as f:
            meta = json.load(f)
        diffs = np.load(os.path.join(out_dir, "difficulties.npy"))
        return cls(metric=meta["metric"], difficulties=diffs)


class DataAnalyzer:
    """Offline pass over a dataset producing a :class:`DataAnalysis`.

    ``metric``: a key of :data:`METRICS` or a callable
    ``fn(sample) -> float`` (e.g. a model-loss scorer).
    """

    def __init__(self, metric: Any = "seqlen", pad_token_id: int = 0):
        if callable(metric):
            self._fn = lambda s, _pad: float(metric(s))
            self.metric_name = getattr(metric, "__name__", "custom")
        else:
            if metric not in METRICS:
                raise ValueError(
                    f"unknown metric {metric!r}; one of {sorted(METRICS)} "
                    "or a callable")
            self._fn = METRICS[metric]
            self.metric_name = metric
        self.pad_token_id = pad_token_id

    def run(self, samples: Iterable[np.ndarray]) -> DataAnalysis:
        diffs = np.asarray(
            [self._fn(np.asarray(s), self.pad_token_id) for s in samples],
            np.float32)
        if diffs.size == 0:
            raise ValueError("empty dataset")
        return DataAnalysis(metric=self.metric_name, difficulties=diffs)


def curriculum_sample_dataloader(samples, analysis: DataAnalysis,
                                 scheduler, step_fn,
                                 batch_size: int,
                                 seed: int = 0) -> Iterator[np.ndarray]:
    """Difficulty-SAMPLED curriculum batches: each batch is drawn only from
    samples whose analyzed difficulty ≤ the scheduler's current difficulty
    (the reference's data-map consumption — complements the existing
    sequence-truncation ``curriculum_dataloader``). Samples must share a
    shape (pad beforehand)."""
    rng = np.random.default_rng(seed)
    arr = np.asarray(samples)
    while True:
        d = scheduler.update_difficulty(step_fn())
        pool = analysis.sample_map(d)
        if pool.size == 0:
            # always have something to train on: fall back to the easiest
            pool = analysis.sorted_indices()[:max(1, batch_size)]
        idx = rng.choice(pool, size=batch_size, replace=pool.size < batch_size)
        yield arr[idx]
