"""Curriculum learning scheduler (difficulty ramps, usually sequence length).

Parity: reference ``runtime/data_pipeline/data_sampling/curriculum_scheduler.py``
(schedule types fixed_linear / fixed_root / fixed_discrete, config keys
``curriculum_learning`` in ``data_efficiency``). Difficulty here is an integer
(e.g. tokens of context); the dataloader wrapper truncates batches to the
current difficulty — under jit this produces one compiled program per bucket,
so schedules should step in coarse increments (``difficulty_step``).
"""
from __future__ import annotations

import math
from typing import Any, Dict, Iterator, Optional

import numpy as np

FIXED_LINEAR = "fixed_linear"
FIXED_ROOT = "fixed_root"
FIXED_DISCRETE = "fixed_discrete"


class CurriculumScheduler:
    def __init__(self, config: Dict[str, Any]):
        self.schedule_type = config.get("schedule_type", FIXED_LINEAR)
        self.min_difficulty = int(config.get("min_difficulty", 8))
        self.max_difficulty = int(config.get("max_difficulty", 1024))
        self.total_curriculum_step = int(config.get("total_curriculum_step", 1000))
        self.difficulty_step = int(config.get("difficulty_step", 8))
        self.root_degree = int(config.get("root_degree", 2))
        # fixed_discrete: explicit (difficulty, until_step) stairs
        self.difficulties = config.get("difficulty", [])
        self.max_steps = config.get("max_step", [])
        self.current_difficulty = self.min_difficulty

    def _clip(self, d: float) -> int:
        d = int(d // self.difficulty_step * self.difficulty_step)
        return int(np.clip(d, self.min_difficulty, self.max_difficulty))

    def get_difficulty(self, global_step: int) -> int:
        t = min(1.0, global_step / max(1, self.total_curriculum_step))
        if self.schedule_type == FIXED_LINEAR:
            d = self.min_difficulty + t * (self.max_difficulty - self.min_difficulty)
        elif self.schedule_type == FIXED_ROOT:
            d = self.min_difficulty + (t ** (1.0 / self.root_degree)) * (
                self.max_difficulty - self.min_difficulty)
        elif self.schedule_type == FIXED_DISCRETE:
            d = self.difficulties[-1]
            for diff, until in zip(self.difficulties, self.max_steps):
                if global_step < until:
                    d = diff
                    break
            return int(d)
        else:
            raise ValueError(f"unknown schedule_type {self.schedule_type!r}")
        return self._clip(d)

    def update_difficulty(self, global_step: int) -> int:
        self.current_difficulty = self.get_difficulty(global_step)
        return self.current_difficulty

    def state_dict(self) -> Dict[str, Any]:
        return {"current_difficulty": self.current_difficulty}

    def load_state_dict(self, sd: Dict[str, Any]) -> None:
        self.current_difficulty = sd["current_difficulty"]


def curriculum_dataloader(data_iter: Iterator, scheduler: CurriculumScheduler,
                          step_fn, seq_key: str = "tokens") -> Iterator:
    """Wrap a batch iterator: truncate the sequence dim to the current
    difficulty (reference truncation semantics in
    ``deepspeed/runtime/data_pipeline/curriculum_scheduler`` usage).
    ``step_fn()`` must return the current global step (e.g.
    ``lambda: engine.global_steps``)."""
    for batch in data_iter:
        d = scheduler.update_difficulty(step_fn())
        if isinstance(batch, dict):
            out = {k: (np.asarray(v)[:, :d] if k == seq_key or
                       (hasattr(v, "ndim") and np.asarray(v).ndim >= 2)
                       else v)
                   for k, v in batch.items()}
        else:
            out = np.asarray(batch)[:, :d]
        yield out
