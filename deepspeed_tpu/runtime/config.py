"""JSON config → typed config tree.

Parity: reference ``runtime/config.py:676`` (``DeepSpeedConfig``) and the pydantic
sub-models (``runtime/zero/config.py:90`` ``DeepSpeedZeroConfig``, fp16/bf16
sections, ``monitor/config.py``, comms logger config). Key names are kept
JSON-compatible with the reference so existing DeepSpeed configs parse unchanged
(CUDA-only knobs are accepted and ignored with a warning). TPU-native additions
live under the ``"mesh"`` section (parallel axis sizes).
"""
from __future__ import annotations

import dataclasses
import json
from typing import Any, Dict, List, Optional

from deepspeed_tpu.runtime.config_utils import (
    DeepSpeedConfigError,
    config_from_dict,
)
from deepspeed_tpu.comm.mesh import MeshConfig
from deepspeed_tpu.runtime.zenflow import ZenFlowSectionConfig
from deepspeed_tpu.utils.logging import logger


@dataclasses.dataclass
class FP16Config:
    """Reference ``runtime/fp16`` config section."""
    enabled: bool = False
    auto_cast: bool = False
    loss_scale: float = 0.0  # 0 = dynamic
    initial_scale_power: int = 16
    loss_scale_window: int = 1000
    hysteresis: int = 2
    consecutive_hysteresis: bool = False
    min_loss_scale: float = 1.0

    @property
    def dynamic_loss_scale(self) -> bool:
        return self.loss_scale == 0.0


@dataclasses.dataclass
class BF16Config:
    enabled: bool = False
    # bf16 grad accumulation dtype (reference bf16 section + data_types)
    immediate_grad_update: bool = True
    # False drops the fp32 master copy: params live in bf16, each optimizer
    # leaf computes its update in fp32 on the fly (no materialized fp32
    # tree). Not a reference option (its bf16_optimizer always keeps an
    # fp32 flat master, runtime/bf16_optimizer.py) — the TPU memory answer
    # for fitting multi-B-param models in one chip's HBM, paired with
    # optimizer="adafactor" (ops/optimizer.py).
    fp32_master: bool = True


@dataclasses.dataclass
class OptimizerConfig:
    type: str = "adam"
    params: Dict[str, Any] = dataclasses.field(default_factory=dict)


@dataclasses.dataclass
class SchedulerConfig:
    type: Optional[str] = None
    params: Dict[str, Any] = dataclasses.field(default_factory=dict)


@dataclasses.dataclass
class OffloadConfig:
    """Reference ``runtime/zero/offload_config.py`` analog."""
    device: str = "none"  # none | cpu (host memory) | nvme
    nvme_path: Optional[str] = None
    pin_memory: bool = True
    buffer_count: int = 5
    buffer_size: int = 100_000_000
    ratio: float = 1.0
    # SuperOffload-class host execution (reference superoffload_stage3.py):
    # run the optimizer update ON the host CPU backend with fp32 master +
    # moments resident in host RAM; device keeps 16-bit params only.
    host_step: bool = False
    # ZenFlow overlap semantics for host_step: defer applying the host
    # update by one step so it fully overlaps device compute. None = unset:
    # zenflow.overlap_step decides when zenflow is enabled, else off. An
    # explicit False always wins (no silent staleness).
    overlap_step: Optional[bool] = None


@dataclasses.dataclass
class ZeroConfig:
    """Reference ``DeepSpeedZeroConfig`` (``runtime/zero/config.py:90``).

    On TPU the stages are sharding policies applied to the train state:
      0 = replicated; 1 = optimizer state sharded over data axes;
      2 = + gradients reduce-scattered; 3 = + parameters sharded (FSDP-style).

    Overlap scheduling (``parallel/overlap.py``; README "Overlap
    scheduler"): ``overlap_comm`` gates the bucketed compute/collective
    overlap scheduler inside the compiled step. ``reduce_bucket_size``
    bounds each gradient-sync bucket (leaves grouped and fenced so each
    bucket's reduce can start as soon as its grads are final);
    ``allgather_bucket_size`` bounds the layer-chunk parameters at
    stages 1-2; ``stage3_prefetch_bucket_size`` bounds the ZeRO-3
    layer-chunk whose parameters are all-gathered one chunk ahead of
    compute (the double-buffered prefetch). All three are the
    reference's JSON spellings, semantics AND units — ELEMENT counts
    (numel), not bytes, exactly as in ``stage_1_and_2.py`` IPG buckets
    and ``partitioned_param_coordinator`` prefetch — so a ported
    reference config buckets at the same granularity here.

    Step-phase overlap (the optimizer update — Automatic Cross-Replica
    Sharding of Weight Update, arXiv:2004.13336): ``overlap_step``
    splits the sharded weight update into ``update_bucket_size``-bounded
    fenced buckets in backward-completion order and defers the
    post-update parameter publish (cast/all-gather) behind the same
    fence chain, double-buffering the gathered compute params through
    train-step state into the NEXT step's forward. Rides the overlap
    scheduler (inactive when ``overlap_comm`` is off or stage < 1).
    ``update_bucket_size`` follows the PR-8 bucket-key contract
    (ELEMENT counts, float/"auto" coercion); ``"auto"`` = follow
    ``reduce_bucket_size`` so update buckets chain one-for-one onto the
    grad-sync buckets.
    """
    stage: int = 0
    contiguous_gradients: bool = True
    reduce_scatter: bool = True
    reduce_bucket_size: int = 500_000_000
    allgather_partitions: bool = True
    allgather_bucket_size: int = 500_000_000
    overlap_comm: bool = True
    # step-phase overlap (2004.13336): bucketed weight update under the
    # fence chain + deferred param publish double-buffered into the next
    # forward. Gated by overlap_comm like the rest of the scheduler.
    overlap_step: bool = True
    # "auto" = follow reduce_bucket_size (update buckets chain onto the
    # grad-sync buckets one-for-one); element counts otherwise
    update_bucket_size: Any = "auto"
    offload_optimizer: OffloadConfig = dataclasses.field(default_factory=OffloadConfig)
    offload_param: OffloadConfig = dataclasses.field(default_factory=OffloadConfig)
    sub_group_size: int = 1_000_000_000
    stage3_max_live_parameters: int = 1_000_000_000
    stage3_max_reuse_distance: int = 1_000_000_000
    stage3_prefetch_bucket_size: int = 50_000_000
    stage3_param_persistence_threshold: int = 100_000
    stage3_gather_16bit_weights_on_model_save: bool = False
    # ZeRO++ knobs (hpZ / qwZ / qgZ — reference zero/config.py:309-330)
    zero_hpz_partition_size: int = 1
    # MiCS replica-group sharding (reference zero/mics.py:63 MiCS_Init): shard
    # ZeRO state within groups of this size, replicate across groups. Resolved
    # onto the 'zshard' mesh axis; zero_hpz_partition_size behaves the same way
    # (hpZ secondary partition = MiCS-style subgrouping on TPU).
    mics_shard_size: int = 0
    mics_hierarchical_params_gather: bool = False
    # ZenFlow importance-split updates (reference runtime/zenflow/)
    zenflow: "ZenFlowSectionConfig" = dataclasses.field(
        default_factory=lambda: ZenFlowSectionConfig())
    # SuperOffload alias (reference superoffload/superoffload_stage3.py):
    # equivalent to offload_optimizer={"device": "cpu", "host_step": true,
    # "overlap_step": true}
    super_offload: bool = False
    zero_quantized_weights: bool = False
    zero_quantized_gradients: bool = False
    zero_quantized_nontrainable_weights: bool = False
    # LoCo error feedback for the quantized gradient reduce (reference
    # runtime/comm/coalesced_collectives.py:81 all_to_all_loco_quant_reduce):
    # per-rank residual re-enters the next round's send. Requires
    # zero_quantized_gradients; costs one full-gradient-sized fp32 buffer
    # per rank.
    loco_error_feedback: bool = False
    round_robin_gradients: bool = False
    ignore_unused_parameters: bool = True

    def validate(self) -> None:
        if self.stage not in (0, 1, 2, 3):
            raise DeepSpeedConfigError(f"zero_optimization.stage must be 0-3, got {self.stage}")
        for key in ("reduce_bucket_size", "allgather_bucket_size",
                    "stage3_prefetch_bucket_size"):
            val = getattr(self, key)
            # reference-ecosystem spellings normalize: JSON scientific
            # notation (5e8 -> float) coerces to int, HF-integration
            # "auto" falls back to the schema default
            if val == "auto":
                val = dataclasses.fields(type(self))
                val = next(f.default for f in val if f.name == key)
                setattr(self, key, val)
            elif isinstance(val, float) and not isinstance(val, bool) \
                    and float(val).is_integer():
                val = int(val)
                setattr(self, key, val)
            if not isinstance(val, int) or isinstance(val, bool) or val <= 0:
                # consumed by the overlap scheduler (parallel/overlap.py):
                # a zero/negative bucket would plan nothing silently
                raise DeepSpeedConfigError(
                    f"zero_optimization.{key} must be a positive int "
                    f"(elements), got {val!r}")
        # update_bucket_size follows the same normalization contract but
        # keeps "auto" as its resolved spelling: auto = follow
        # reduce_bucket_size (the engine resolves it, which knows the
        # final reduce bucket after ITS coercion)
        ub = self.update_bucket_size
        if ub != "auto":
            if isinstance(ub, float) and not isinstance(ub, bool) \
                    and float(ub).is_integer():
                ub = int(ub)
                self.update_bucket_size = ub
            if not isinstance(ub, int) or isinstance(ub, bool) or ub <= 0:
                raise DeepSpeedConfigError(
                    "zero_optimization.update_bucket_size must be a "
                    f"positive int (elements) or \"auto\", got {ub!r}")
        if not isinstance(self.overlap_step, bool):
            raise DeepSpeedConfigError(
                "zero_optimization.overlap_step must be a bool, got "
                f"{self.overlap_step!r}")
        # the subgroup keys follow the same normalization contract but
        # both have an OFF spelling the reference schema allows (hpZ:
        # ge=0 — 0 and 1 both mean no secondary partition; MiCS: 0) —
        # non-negative, never positive-only. Anything else raises loudly:
        # a malformed subgroup silently degrading to exact full-world
        # collectives is the config-no-op class of bug. The mesh-
        # dependent half (must divide and fit the device world) lives in
        # the engine, which knows the world.
        for key in ("zero_hpz_partition_size", "mics_shard_size"):
            val = getattr(self, key)
            if val == "auto":
                val = dataclasses.fields(type(self))
                val = next(f.default for f in val if f.name == key)
                setattr(self, key, val)
            elif isinstance(val, float) and not isinstance(val, bool) \
                    and float(val).is_integer():
                val = int(val)
                setattr(self, key, val)
            if not isinstance(val, int) or isinstance(val, bool) or val < 0:
                raise DeepSpeedConfigError(
                    f"zero_optimization.{key} must be a non-negative int "
                    f"(ranks; 0 = off), got {val!r}")


@dataclasses.dataclass
class CommsLoggerConfig:
    enabled: bool = False
    verbose: bool = False
    prof_all: bool = True
    prof_ops: List[str] = dataclasses.field(default_factory=list)
    debug: bool = False


@dataclasses.dataclass
class TelemetryConfig:
    """The unified telemetry subsystem (``deepspeed_tpu/telemetry``).

    ``enabled`` gates metric recording process-wide (the registry is also
    process-0 gated like the monitor). ``http_port`` starts the Prometheus
    ``/metrics`` endpoint when >= 0 (0 = ephemeral port; -1 = off).
    ``stall_deadline_s`` arms the training stall watchdog: a warning (with
    the last-completed span) logs when no optimizer step finishes within
    the deadline. ``monitor_bridge`` forwards registry scalars into the
    configured MonitorMaster backends at the ``steps_per_print`` cadence
    (a no-op unless a monitor backend is enabled).

    ``tracing`` turns on the structured tracer + flight recorder
    (``telemetry/tracing.py``): every ``telemetry.span`` site and every
    serving request gets a timeline entry in a ring buffer of
    ``trace_buffer_events`` completed spans, sampled per trace at
    ``trace_sample_rate``, with crash-context dumps (stall, circuit
    open, preemption, engine-step exception) written under
    ``flight_dump_dir``. Off by default — a disabled tracer costs one
    attribute check per span."""
    enabled: bool = True
    http_port: int = -1
    stall_deadline_s: float = 0.0
    monitor_bridge: bool = True
    # measured-MFU gauge prices ONE cost-analysis compile of the train step
    # at first scrape — disable for huge models behind a live endpoint
    measure_mfu: bool = True
    tracing: bool = False
    trace_buffer_events: int = 4096
    trace_sample_rate: float = 1.0
    flight_dump_dir: str = "flight_dumps"

    def validate(self) -> None:
        if not (0.0 <= self.trace_sample_rate <= 1.0):
            raise DeepSpeedConfigError(
                "telemetry.trace_sample_rate must be in [0, 1], got "
                f"{self.trace_sample_rate}")
        if self.trace_buffer_events < 1:
            raise DeepSpeedConfigError(
                "telemetry.trace_buffer_events must be >= 1, got "
                f"{self.trace_buffer_events} (a zero-size flight recorder "
                "dumps empty context)")


@dataclasses.dataclass
class HlolintSectionConfig:
    """Compiled-program contract enforcement at initialize
    (``deepspeed_tpu/analysis/hlolint``).

    ``enabled`` lowers the engine's REAL fused train step once at
    initialize (the same lowering the observatory ledger caches — no
    extra compile for jobs that also ledger/report) and runs the
    hlolint rule passes over it: async-pair structure, fenced bucket
    counts, wire dtypes, replication, host transfers. ``contract``
    names a committed contract JSON to hold the step to on top of the
    structural rules. With ``fail_on_violation`` (default) a violation
    refuses the job before any chip time is spent — the same posture
    bench.py takes before recording a round; off, violations log and
    the job proceeds."""
    enabled: bool = False
    contract: str = ""
    fail_on_violation: bool = True

    def validate(self) -> None:
        if self.contract and not isinstance(self.contract, str):
            raise DeepSpeedConfigError(
                f"hlolint.contract must be a path string, got "
                f"{type(self.contract).__name__}")


@dataclasses.dataclass
class MemlintSectionConfig:
    """Compiled-program MEMORY contract enforcement at initialize
    (``deepspeed_tpu/analysis/memlint`` — hlolint's memory-side
    sibling; README "Memory contracts").

    ``enabled`` lints the engine's REAL lowered train step once at
    initialize (the same cached observatory lowering hlolint and the
    ledger share — no extra compile): donation/aliasing verification
    over the entry header, residency vs the ZeRO partitioning-math
    prediction, and the OOM pre-flight gate. ``contract`` names a
    committed memory contract JSON to hold the step to on top.
    ``hbm_budget_bytes`` sets the pre-flight budget explicitly — 0
    (default) means the chip's datasheet HBM capacity
    (``utils/chip_specs``); the datasheet-less CPU tier arms the gate
    only from an explicit budget. With ``fail_on_violation`` (default)
    a violation refuses the job before any chip time is spent."""
    enabled: bool = False
    contract: str = ""
    hbm_budget_bytes: int = 0
    fail_on_violation: bool = True

    def validate(self) -> None:
        if self.contract and not isinstance(self.contract, str):
            raise DeepSpeedConfigError(
                f"memlint.contract must be a path string, got "
                f"{type(self.contract).__name__}")
        if not isinstance(self.hbm_budget_bytes, (int, float)) \
                or isinstance(self.hbm_budget_bytes, bool) \
                or self.hbm_budget_bytes < 0:
            raise DeepSpeedConfigError(
                "memlint.hbm_budget_bytes must be a non-negative byte "
                f"count (0 = datasheet capacity), got "
                f"{self.hbm_budget_bytes!r}")
        self.hbm_budget_bytes = int(self.hbm_budget_bytes)


@dataclasses.dataclass
class AutotuningSectionConfig:
    """Observatory-driven plan engine (``deepspeed_tpu/autotuning/planner``).

    Reuses the reference's ``"autotuning"`` section name (previously
    accepted-and-ignored on TPU) for the TPU-native plan cache:
    ``enabled`` makes the engine look up a committed plan for its
    ``(model_fingerprint, mesh_shape, wire_format, platform)`` key under
    ``plan_cache_dir`` at initialize and apply the planned knobs to any
    knob the user left at its default (explicit JSON settings always
    win). ``fail_on_stale`` refuses initialize when the user's explicit
    config CONTRADICTS the cached plan (a stale plan silently mis-tuned
    a job once; the refusal names the conflicting knobs) — off, the
    conflict logs and the user's values stand. ``confirm_top_k`` /
    ``max_candidates`` bound the planner's measured-confirmation windows
    and enumerated candidate count when ``tools/plan`` builds the cache.
    """
    enabled: bool = False
    plan_cache_dir: str = "autotune_plans"
    confirm_top_k: int = 2
    max_candidates: int = 64
    fail_on_stale: bool = False

    def validate(self) -> None:
        if not isinstance(self.plan_cache_dir, str):
            raise DeepSpeedConfigError(
                "autotuning.plan_cache_dir must be a path string, got "
                f"{type(self.plan_cache_dir).__name__}")
        if not isinstance(self.confirm_top_k, int) \
                or isinstance(self.confirm_top_k, bool) \
                or self.confirm_top_k < 0:
            raise DeepSpeedConfigError(
                "autotuning.confirm_top_k must be a non-negative int, "
                f"got {self.confirm_top_k!r}")
        if not isinstance(self.max_candidates, int) \
                or isinstance(self.max_candidates, bool) \
                or self.max_candidates < 1:
            raise DeepSpeedConfigError(
                "autotuning.max_candidates must be a positive int, got "
                f"{self.max_candidates!r}")


@dataclasses.dataclass
class ElasticitySectionConfig:
    """World-size-elastic training (``deepspeed_tpu/elasticity/``;
    README "Elastic worlds").

    Consumed by :class:`~deepspeed_tpu.elasticity.elastic_agent.
    ElasticAgent` via ``ElasticAgentConfig.from_section``: ``enabled``
    marks the run as supervise-and-resize (the launcher/driver decides
    to wrap ``train`` in an agent); ``max_restarts`` /
    ``restart_backoff_s`` / ``restart_backoff_max_s`` bound the
    supervised restart loop; ``reload_on_restart`` reloads the newest
    committed checkpoint on every rebuild — through the universal
    RESHARDING path when the acquired world differs from the
    checkpointed one. ``min_world_size`` is the floor below which a
    resize is terminal rather than a silent slow resume.
    ``hpz_candidates`` lists ZeRO++ hpZ subgroup sizes the placement
    oracle surveys per acquired world (non-divisors are skipped).
    ``universal_dir`` overrides where the resharding conversion lands
    ("" = ``<checkpoint_dir>/universal``). NOTE: the legacy reference
    keys (``elastic_training``/``micro_batch_sizes`` …) stay handled by
    ``elasticity/elasticity.compute_elastic_config`` — this section
    configures the TPU-native agent, not the batch-size solver."""
    enabled: bool = False
    max_restarts: int = 3
    restart_backoff_s: float = 1.0
    restart_backoff_max_s: float = 60.0
    reload_on_restart: bool = True
    min_world_size: int = 1
    hpz_candidates: list = dataclasses.field(default_factory=list)
    universal_dir: str = ""

    def validate(self) -> None:
        if not isinstance(self.max_restarts, int) \
                or isinstance(self.max_restarts, bool) \
                or self.max_restarts < 0:
            raise DeepSpeedConfigError(
                "elasticity.max_restarts must be a non-negative int, "
                f"got {self.max_restarts!r}")
        if self.restart_backoff_s <= 0 \
                or self.restart_backoff_max_s < self.restart_backoff_s:
            raise DeepSpeedConfigError(
                "elasticity restart backoff must satisfy 0 < "
                "restart_backoff_s <= restart_backoff_max_s, got "
                f"{self.restart_backoff_s} / {self.restart_backoff_max_s}")
        if not isinstance(self.min_world_size, int) \
                or isinstance(self.min_world_size, bool) \
                or self.min_world_size < 1:
            raise DeepSpeedConfigError(
                "elasticity.min_world_size must be a positive int, got "
                f"{self.min_world_size!r}")
        if not isinstance(self.hpz_candidates, (list, tuple)) or any(
                not isinstance(h, int) or isinstance(h, bool) or h < 1
                for h in self.hpz_candidates):
            raise DeepSpeedConfigError(
                "elasticity.hpz_candidates must be a list of positive "
                f"ints (subgroup sizes), got {self.hpz_candidates!r}")
        if not isinstance(self.universal_dir, str):
            raise DeepSpeedConfigError(
                "elasticity.universal_dir must be a path string, got "
                f"{type(self.universal_dir).__name__}")


@dataclasses.dataclass
class ServingSectionConfig:
    """Serving resilience front-end (``deepspeed_tpu/serving``).

    Admission is bounded by ``max_queue`` live requests and a KV-pool
    ``kv_high_watermark`` (projected utilization after admitting the
    prompt); past either bound the configured ``shed_policy`` decides who
    pays: ``reject_newest`` turns the incoming request away,
    ``reject_oldest`` sheds the longest-lived request to make room, and
    ``deadline_aware`` sheds whichever request (incoming included) is
    least likely to meet its deadline at current decode throughput.
    Between ``kv_degrade_watermark`` and the high watermark new
    admissions are accepted but their ``max_new_tokens`` is clamped to
    ``degraded_max_new_tokens`` (graceful degradation before shedding).

    The circuit breaker opens after ``circuit_failure_threshold``
    consecutive engine-tick failures: requests are rejected immediately
    for ``circuit_backoff_s`` (doubling per re-open up to
    ``circuit_backoff_max_s``), then ONE half-open probe tick decides
    between closing and re-opening. ``heartbeat_timeout_s`` bounds the
    ``/healthz`` liveness window (stale tick heartbeat = sick replica)."""
    max_queue: int = 64
    kv_high_watermark: float = 0.95
    kv_degrade_watermark: float = 0.80
    degraded_max_new_tokens: int = 32
    default_max_new_tokens: int = 128
    shed_policy: str = "reject_newest"  # reject_newest | reject_oldest | deadline_aware
    circuit_failure_threshold: int = 5
    circuit_backoff_s: float = 0.5
    circuit_backoff_max_s: float = 30.0
    # open-window endpoint jitter (fraction of the ramp value, uniform,
    # stretch-only): replicas that trip together must not probe in
    # lockstep (fleet-level thundering herd); 0 disables
    circuit_jitter_frac: float = 0.1
    heartbeat_timeout_s: float = 15.0
    # retry-after hint fallback when no decode-throughput sample exists
    # yet (cold engine): assumed seconds per generated token
    assumed_token_seconds: float = 0.05
    # terminal RequestResult records kept for result() polling, oldest
    # evicted first — sustained overload with fresh uids must not grow
    # frontend memory without bound (callers should drop_result() after
    # delivery; this cap is the backstop)
    max_result_history: int = 4096

    def validate(self) -> None:
        if self.shed_policy not in ("reject_newest", "reject_oldest",
                                    "deadline_aware"):
            raise DeepSpeedConfigError(
                "serving.shed_policy must be reject_newest|reject_oldest|"
                f"deadline_aware, got {self.shed_policy!r}")
        if not (0.0 < self.kv_high_watermark <= 1.0):
            raise DeepSpeedConfigError(
                f"serving.kv_high_watermark must be in (0, 1], got "
                f"{self.kv_high_watermark}")
        if self.kv_degrade_watermark > self.kv_high_watermark:
            raise DeepSpeedConfigError(
                "serving.kv_degrade_watermark must not exceed "
                f"kv_high_watermark ({self.kv_degrade_watermark} > "
                f"{self.kv_high_watermark})")
        if self.max_queue < 1:
            raise DeepSpeedConfigError(
                f"serving.max_queue must be >= 1, got {self.max_queue}")
        if self.circuit_failure_threshold < 1:
            raise DeepSpeedConfigError(
                "serving.circuit_failure_threshold must be >= 1, got "
                f"{self.circuit_failure_threshold}")
        if self.max_result_history < 1:
            raise DeepSpeedConfigError(
                "serving.max_result_history must be >= 1, got "
                f"{self.max_result_history}")
        if self.kv_degrade_watermark < 0:
            raise DeepSpeedConfigError(
                "serving.kv_degrade_watermark must be >= 0, got "
                f"{self.kv_degrade_watermark}")
        if self.degraded_max_new_tokens < 1 \
                or self.default_max_new_tokens < 1:
            raise DeepSpeedConfigError(
                "serving.degraded_max_new_tokens / default_max_new_tokens "
                f"must be >= 1, got {self.degraded_max_new_tokens} / "
                f"{self.default_max_new_tokens}")
        if self.circuit_backoff_s <= 0 \
                or self.circuit_backoff_max_s < self.circuit_backoff_s:
            raise DeepSpeedConfigError(
                "serving circuit backoff must satisfy 0 < circuit_backoff_s "
                f"<= circuit_backoff_max_s, got {self.circuit_backoff_s} / "
                f"{self.circuit_backoff_max_s} (a zero backoff probes a "
                "sick device at full tick rate — the hammering the breaker "
                "exists to prevent)")
        if self.heartbeat_timeout_s <= 0 or self.assumed_token_seconds <= 0:
            raise DeepSpeedConfigError(
                "serving.heartbeat_timeout_s and assumed_token_seconds "
                f"must be > 0, got {self.heartbeat_timeout_s} / "
                f"{self.assumed_token_seconds}")
        if not (0.0 <= self.circuit_jitter_frac < 1.0):
            raise DeepSpeedConfigError(
                "serving.circuit_jitter_frac must be in [0, 1), got "
                f"{self.circuit_jitter_frac}")


@dataclasses.dataclass
class FleetSectionConfig:
    """Multi-replica serving fleet (``deepspeed_tpu/serving/fleet.py``).

    A :class:`~deepspeed_tpu.serving.fleet.FleetRouter` owns N serving
    frontends and routes by measured decode throughput, KV headroom,
    circuit state and queue depth. ``min_ready_replicas`` is the
    readiness quorum (``/readyz`` is ready iff at least that many
    replicas are routable). Failover resubmits a lost request up to
    ``max_attempts`` total attempts with exponential backoff
    (``retry_backoff_s`` doubling to ``retry_backoff_max_s``, stretched
    by up to ``retry_jitter_frac`` of uniform jitter) and an
    excluded-replica set; a replica whose last tick blocked longer than
    ``heartbeat_stale_s`` (or whose heartbeat is that stale with work
    pending) is treated as hung. Hedged dispatch (``hedge_enabled``)
    duplicates a still-running request onto a second replica once its
    age passes the ``hedge_percentile`` of observed completion
    latencies (floored at ``hedge_min_s``); first completion wins and
    the loser is cancelled. ``migrate_on_drain`` moves in-flight work
    off a draining replica instead of waiting it out.

    Autoscaling (``serving/fleet.FleetAutoscaler``; README "Elastic
    worlds"): driven by telemetry the frontends already export — mean
    active requests per ready replica (queue depth), the worst
    replica's KV-pool utilization, and the p99 of observed completion
    latency (the TTFT proxy when no request has finished yet). Scale-out
    adds a replica when queue depth exceeds ``scale_out_queue_depth``,
    KV utilization exceeds ``scale_out_kv_util``, or p99 latency
    exceeds ``scale_out_p99_latency_s`` (0 disables that trigger);
    scale-in drains+migrates the least-loaded replica when queue depth
    falls below ``scale_in_queue_depth`` AND KV pressure is off. Both
    directions respect ``autoscale_min_replicas`` /
    ``autoscale_max_replicas`` and wait ``autoscale_cooldown_ticks``
    ticks between scale events (resize thrash protection)."""
    min_ready_replicas: int = 1
    max_attempts: int = 3
    retry_backoff_s: float = 0.05
    retry_backoff_max_s: float = 2.0
    retry_jitter_frac: float = 0.25
    heartbeat_stale_s: float = 5.0
    hedge_enabled: bool = False
    hedge_percentile: float = 0.95
    hedge_min_s: float = 0.05
    migrate_on_drain: bool = True
    max_result_history: int = 4096
    autoscale_min_replicas: int = 1
    autoscale_max_replicas: int = 8
    scale_out_queue_depth: float = 8.0
    scale_in_queue_depth: float = 1.0
    scale_out_kv_util: float = 0.85
    scale_out_p99_latency_s: float = 0.0
    autoscale_cooldown_ticks: int = 8

    def validate(self) -> None:
        if self.min_ready_replicas < 1:
            raise DeepSpeedConfigError(
                "fleet.min_ready_replicas must be >= 1, got "
                f"{self.min_ready_replicas}")
        if self.max_attempts < 1:
            raise DeepSpeedConfigError(
                f"fleet.max_attempts must be >= 1, got {self.max_attempts}")
        if self.retry_backoff_s <= 0 \
                or self.retry_backoff_max_s < self.retry_backoff_s:
            raise DeepSpeedConfigError(
                "fleet retry backoff must satisfy 0 < retry_backoff_s <= "
                f"retry_backoff_max_s, got {self.retry_backoff_s} / "
                f"{self.retry_backoff_max_s}")
        if not (0.0 <= self.retry_jitter_frac < 1.0):
            raise DeepSpeedConfigError(
                "fleet.retry_jitter_frac must be in [0, 1), got "
                f"{self.retry_jitter_frac}")
        if self.heartbeat_stale_s <= 0:
            raise DeepSpeedConfigError(
                "fleet.heartbeat_stale_s must be > 0, got "
                f"{self.heartbeat_stale_s}")
        if not (0.0 < self.hedge_percentile <= 1.0):
            raise DeepSpeedConfigError(
                "fleet.hedge_percentile must be in (0, 1], got "
                f"{self.hedge_percentile}")
        if self.hedge_min_s < 0:
            raise DeepSpeedConfigError(
                f"fleet.hedge_min_s must be >= 0, got {self.hedge_min_s}")
        if self.max_result_history < 1:
            raise DeepSpeedConfigError(
                "fleet.max_result_history must be >= 1, got "
                f"{self.max_result_history}")
        if not (1 <= self.autoscale_min_replicas
                <= self.autoscale_max_replicas):
            raise DeepSpeedConfigError(
                "fleet autoscale bounds must satisfy 1 <= "
                "autoscale_min_replicas <= autoscale_max_replicas, got "
                f"{self.autoscale_min_replicas} / "
                f"{self.autoscale_max_replicas}")
        if self.scale_in_queue_depth >= self.scale_out_queue_depth:
            raise DeepSpeedConfigError(
                "fleet.scale_in_queue_depth must be below "
                "scale_out_queue_depth (equal thresholds oscillate), got "
                f"{self.scale_in_queue_depth} >= "
                f"{self.scale_out_queue_depth}")
        if not (0.0 < self.scale_out_kv_util <= 1.0):
            raise DeepSpeedConfigError(
                "fleet.scale_out_kv_util must be in (0, 1], got "
                f"{self.scale_out_kv_util}")
        if self.scale_out_p99_latency_s < 0:
            raise DeepSpeedConfigError(
                "fleet.scale_out_p99_latency_s must be >= 0 (0 disables "
                f"the latency trigger), got {self.scale_out_p99_latency_s}")
        if not isinstance(self.autoscale_cooldown_ticks, int) \
                or isinstance(self.autoscale_cooldown_ticks, bool) \
                or self.autoscale_cooldown_ticks < 0:
            raise DeepSpeedConfigError(
                "fleet.autoscale_cooldown_ticks must be a non-negative "
                f"int, got {self.autoscale_cooldown_ticks!r}")


@dataclasses.dataclass
class TenantQuotaConfig:
    """One tenant's QoS entry inside ``tenancy.tenants`` (see
    :class:`TenancySectionConfig`). Every quota defaults to 0 =
    unlimited; ``tier`` places the tenant on the shed ladder (``batch``
    sheds before ``standard`` before ``realtime``) and picks its default
    fair-share weight."""
    tier: str = "standard"       # realtime | standard | batch
    requests_per_s: float = 0.0  # token-bucket rate limits (0 = none)
    tokens_per_s: float = 0.0
    burst_requests: float = 0.0  # bucket capacities (0 = one rate-second)
    burst_tokens: float = 0.0
    max_concurrent: int = 0      # live request copies (0 = unlimited)
    max_kv_blocks: int = 0       # projected KV blocks held (0 = unlimited)
    weight: float = 0.0          # fair-share weight (0 = tier default)

    def validate(self) -> None:
        if self.tier not in ("realtime", "standard", "batch"):
            raise DeepSpeedConfigError(
                "tenancy tenant tier must be realtime|standard|batch, "
                f"got {self.tier!r}")
        for key in ("requests_per_s", "tokens_per_s", "burst_requests",
                    "burst_tokens", "weight"):
            if getattr(self, key) < 0:
                raise DeepSpeedConfigError(
                    f"tenancy tenant {key} must be >= 0, got "
                    f"{getattr(self, key)}")
        if self.max_concurrent < 0 or self.max_kv_blocks < 0:
            raise DeepSpeedConfigError(
                "tenancy tenant max_concurrent / max_kv_blocks must be "
                f">= 0, got {self.max_concurrent} / {self.max_kv_blocks}")


@dataclasses.dataclass
class TenancySectionConfig:
    """Multi-tenant QoS (``deepspeed_tpu/serving/tenancy.py``; README
    "Multi-tenant QoS").

    ``tenants`` maps tenant name to a :class:`TenantQuotaConfig` dict;
    unknown tenants (and untagged traffic, which resolves to the
    ``"default"`` tenant) fall back to ``default_tier`` with no quotas.
    ``tier_weights`` sets the fair-share weight per tier (overridable
    per tenant). Under contended capacity — queue at least
    ``fair_contention_queue_frac`` of ``serving.max_queue`` full, or KV
    past the degrade watermark — a tenant whose virtual token counter
    leads the fair-queueing floor by more than
    ``fair_share_horizon_tokens`` weighted tokens is turned away with a
    drain-time retry hint. ``poison_quarantine_threshold`` suspect
    evictions inside ``poison_quarantine_s`` quarantine the tenant for
    that window (per-tenant circuit instead of a whole-replica blast).
    ``max_tenant_labels`` bounds per-tenant metric label cardinality
    (overflow folds into ``"other"``); ``max_tracked_tenants`` bounds
    internal registry state (idle tenants evicted LRU-first)."""
    default_tier: str = "standard"
    tier_weights: Dict[str, float] = dataclasses.field(
        default_factory=lambda: {"realtime": 8.0, "standard": 4.0,
                                 "batch": 1.0})
    tenants: Dict[str, Any] = dataclasses.field(default_factory=dict)
    max_tenant_labels: int = 32
    max_tracked_tenants: int = 1024
    fair_share_horizon_tokens: float = 256.0
    fair_contention_queue_frac: float = 0.5
    poison_quarantine_threshold: int = 3
    poison_quarantine_s: float = 30.0

    def validate(self) -> None:
        if self.default_tier not in ("realtime", "standard", "batch"):
            raise DeepSpeedConfigError(
                "tenancy.default_tier must be realtime|standard|batch, "
                f"got {self.default_tier!r}")
        for tier, w in self.tier_weights.items():
            if tier not in ("realtime", "standard", "batch"):
                raise DeepSpeedConfigError(
                    f"tenancy.tier_weights has unknown tier {tier!r}")
            if not isinstance(w, (int, float)) or w <= 0:
                raise DeepSpeedConfigError(
                    f"tenancy.tier_weights[{tier!r}] must be > 0, got "
                    f"{w!r}")
        if not isinstance(self.tenants, dict):
            raise DeepSpeedConfigError(
                "tenancy.tenants must be a dict of tenant name -> quota "
                f"entry, got {type(self.tenants).__name__}")
        if self.max_tenant_labels < 1:
            raise DeepSpeedConfigError(
                "tenancy.max_tenant_labels must be >= 1, got "
                f"{self.max_tenant_labels}")
        if self.max_tracked_tenants < 1:
            raise DeepSpeedConfigError(
                "tenancy.max_tracked_tenants must be >= 1, got "
                f"{self.max_tracked_tenants}")
        if self.fair_share_horizon_tokens <= 0:
            raise DeepSpeedConfigError(
                "tenancy.fair_share_horizon_tokens must be > 0, got "
                f"{self.fair_share_horizon_tokens}")
        if not (0.0 < self.fair_contention_queue_frac <= 1.0):
            raise DeepSpeedConfigError(
                "tenancy.fair_contention_queue_frac must be in (0, 1], "
                f"got {self.fair_contention_queue_frac}")
        if self.poison_quarantine_threshold < 1:
            raise DeepSpeedConfigError(
                "tenancy.poison_quarantine_threshold must be >= 1, got "
                f"{self.poison_quarantine_threshold}")
        if self.poison_quarantine_s <= 0:
            raise DeepSpeedConfigError(
                "tenancy.poison_quarantine_s must be > 0, got "
                f"{self.poison_quarantine_s}")


@dataclasses.dataclass
class SloObjectiveConfig:
    """One declarative objective inside ``slo.objectives`` (see
    :class:`SloSectionConfig`). ``metric`` picks the measured signal:
    ``ttft_p99_s`` (queue-wait to first service), ``decode_token_p99_s``
    (per-token decode latency) — both latency objectives need a
    ``threshold_s`` — or ``availability`` (fraction of terminal requests
    that completed). ``target`` is the objective itself (e.g. 0.99 =
    "99% of requests under threshold" / "99% of requests succeed");
    burn rate is bad-fraction divided by the (1 - target) error budget.
    ``tenant`` scopes the objective to one tenant's traffic ("" =
    fleet-wide)."""
    name: str = ""
    metric: str = "ttft_p99_s"  # ttft_p99_s | decode_token_p99_s | availability
    threshold_s: float = 0.0
    target: float = 0.99
    tenant: str = ""

    def validate(self) -> None:
        if not self.name:
            raise DeepSpeedConfigError(
                "slo objective entries need a non-empty name (alert "
                "state and report rows are keyed by it)")
        if self.metric not in ("ttft_p99_s", "decode_token_p99_s",
                               "availability"):
            raise DeepSpeedConfigError(
                f"slo objective {self.name!r} metric must be ttft_p99_s|"
                f"decode_token_p99_s|availability, got {self.metric!r}")
        if not (0.0 < self.target < 1.0):
            raise DeepSpeedConfigError(
                f"slo objective {self.name!r} target must be in (0, 1) — "
                "a target of 1.0 leaves a zero error budget and every "
                f"burn rate divides by zero — got {self.target}")
        if self.metric != "availability" and self.threshold_s <= 0:
            raise DeepSpeedConfigError(
                f"slo objective {self.name!r} ({self.metric}) needs "
                f"threshold_s > 0, got {self.threshold_s}")


@dataclasses.dataclass
class SloSectionConfig:
    """SLO burn-rate engine (``serving/observatory/slo.py``; README
    "Fleet observatory").

    ``objectives`` is a list of :class:`SloObjectiveConfig` dicts.
    Each objective is evaluated SRE-workbook style over TWO sliding
    windows (``fast_window_s`` / ``slow_window_s``): an alert FIRES only
    while BOTH windows burn error budget faster than
    ``burn_rate_threshold`` (fast window = responsive, slow window =
    de-flappers), and clears as soon as either recovers. The
    request-lifecycle ring keeps the last ``ledger_size`` terminal
    records (availability objectives and the fleet-report CLI read it).
    Actions are observe-only by default: ``autoscale_on_burn`` lets a
    firing objective become a ``slo_burn`` scale-out reason for the
    ``FleetAutoscaler``; ``shed_on_burn`` tightens the admission
    ladder's queue bound by ``shed_tighten_frac`` while any objective
    fires. Both default False so the engine provably changes no
    decision until the operator opts in."""
    enabled: bool = True
    objectives: List[Any] = dataclasses.field(default_factory=list)
    fast_window_s: float = 60.0
    slow_window_s: float = 300.0
    burn_rate_threshold: float = 14.4
    ledger_size: int = 2048
    autoscale_on_burn: bool = False
    shed_on_burn: bool = False
    shed_tighten_frac: float = 0.25

    def validate(self) -> None:
        if not isinstance(self.objectives, list):
            raise DeepSpeedConfigError(
                "slo.objectives must be a list of objective entries, got "
                f"{type(self.objectives).__name__}")
        if not (0 < self.fast_window_s < self.slow_window_s):
            raise DeepSpeedConfigError(
                "slo windows must satisfy 0 < fast_window_s < "
                f"slow_window_s, got {self.fast_window_s} / "
                f"{self.slow_window_s}")
        if self.burn_rate_threshold <= 0:
            raise DeepSpeedConfigError(
                "slo.burn_rate_threshold must be > 0, got "
                f"{self.burn_rate_threshold}")
        if self.ledger_size < 1:
            raise DeepSpeedConfigError(
                f"slo.ledger_size must be >= 1, got {self.ledger_size}")
        if not (0.0 <= self.shed_tighten_frac < 1.0):
            raise DeepSpeedConfigError(
                "slo.shed_tighten_frac must be in [0, 1) — tightening by "
                "a full 1.0 would close the queue entirely — got "
                f"{self.shed_tighten_frac}")
        names = set()
        for entry in self.objectives:
            if isinstance(entry, SloObjectiveConfig):
                obj = entry
                obj.validate()
            elif isinstance(entry, dict):
                from deepspeed_tpu.runtime.config_utils import (
                    config_from_dict as _cfd)
                obj = _cfd(SloObjectiveConfig, entry, path="slo.objectives.")
            else:
                raise DeepSpeedConfigError(
                    "slo.objectives entries must be dicts, got "
                    f"{type(entry).__name__}")
            if obj.name in names:
                raise DeepSpeedConfigError(
                    f"slo.objectives has duplicate name {obj.name!r}")
            names.add(obj.name)

    def parsed_objectives(self) -> List[SloObjectiveConfig]:
        """The objectives as validated dataclasses (dict entries from a
        JSON config are built here; already-typed entries pass through)."""
        out: List[SloObjectiveConfig] = []
        for entry in self.objectives:
            if isinstance(entry, SloObjectiveConfig):
                out.append(entry)
            else:
                from deepspeed_tpu.runtime.config_utils import (
                    config_from_dict as _cfd)
                out.append(_cfd(SloObjectiveConfig, entry,
                                path="slo.objectives."))
        return out


@dataclasses.dataclass
class CheckpointSectionConfig:
    """Durable-checkpoint knobs (``checkpoint/fault_tolerance.py``).

    Every save commits atomically: tmp-dir write → fsync → ``COMMITTED``
    manifest (per-file size + CRC32 + step) → rename → ``latest``.
    ``writer`` supersedes the legacy top-level ``checkpoint_writer`` when
    set. ``keep_n`` prunes all but the newest N committed tags after each
    commit (0 = keep everything). ``verify_checksums=False`` skips the
    CRC pass on load/walk-back (size + marker checks remain). Transient
    I/O errors retry ``save_retries`` times with exponential backoff
    (``retry_backoff_s`` doubling) + uniform jitter (``retry_jitter_s``)."""
    writer: Optional[str] = None   # orbax | fast (None → checkpoint_writer)
    keep_n: int = 0
    verify_checksums: bool = True
    fsync: bool = True
    save_retries: int = 3
    retry_backoff_s: float = 0.2
    retry_jitter_s: float = 0.2

    def validate(self) -> None:
        if self.writer not in (None, "orbax", "fast"):
            raise DeepSpeedConfigError(
                f"checkpoint.writer must be orbax|fast, got {self.writer!r}"
                " (a typo would silently fall back to the orbax path)")


@dataclasses.dataclass
class FaultToleranceConfig:
    """Preemption-safe training (``runtime/engine.py`` handlers).

    ``resume_dir`` is the checkpoint root used for ``auto_resume`` and
    emergency saves (env ``DSTPU_RESUME_DIR`` supplies a default — set by
    ``launcher --resume_dir``). ``auto_resume=True`` makes ``initialize``
    restore the newest committed checkpoint there (step + RNG + scheduler
    client state) before returning; a missing/empty dir is a cold start,
    not an error (env ``DSTPU_AUTO_RESUME=1`` also enables this).
    ``graceful_preemption`` installs a SIGTERM handler that drains any
    in-flight async save, writes an emergency checkpoint, and exits 0 —
    the preemptible-VM contract; it arms only when ``resume_dir`` or
    ``auto_resume`` is also set (a handler with nowhere to save would
    change process signal behavior for nothing). ``on_stall`` escalates
    the telemetry stall watchdog beyond its log line: ``"dump_trace"``
    writes a flight-recorder dump naming the last-completed span
    (requires ``telemetry.tracing``; a no-op without it), and
    ``"checkpoint"`` additionally writes an emergency checkpoint of the
    last completed state (the dump rides along — a stall report without
    its surrounding timeline answers nothing)."""
    # tri-state so env defaults can't override an EXPLICIT false in the
    # JSON (None = unset → falsy, env DSTPU_AUTO_RESUME may enable)
    auto_resume: Optional[bool] = None
    resume_dir: Optional[str] = None
    graceful_preemption: bool = True
    emergency_tag_prefix: str = "emergency"
    on_stall: str = "log"   # log | dump_trace | checkpoint

    def validate(self) -> None:
        if self.on_stall not in ("log", "dump_trace", "checkpoint"):
            raise DeepSpeedConfigError(
                f"fault_tolerance.on_stall must be log|dump_trace|"
                f"checkpoint, got {self.on_stall!r}")


@dataclasses.dataclass
class GuardianSectionConfig:
    """Training-run guardian (``runtime/guardian.py``; README "Training
    guardian").

    ``enabled`` arms the whole subsystem. ``nonfinite_guard`` extends the
    fp16 loss-scaler's device-side skip-update ``lax.cond`` to bf16/fp32:
    a step whose gradients are non-finite never touches the weights (no
    scaler — pure skip, counted in the same device-side ``skips``
    counter). Host-side anomaly detection rides the metrics the engine
    already device_gets each ``steps_per_print`` cadence — zero extra
    host syncs on the hot path: ``z_threshold`` standard deviations
    outside the EMA/variance band of loss or grad-norm (after
    ``warmup_observations`` samples; ``ema_decay`` is the band's memory)
    flags an anomaly. On a confirmed anomaly the guardian dumps a flight
    trace, rolls engine+optimizer+scaler+loader back to the last
    committed checkpoint tag, bisects the offending window microbatch by
    microbatch (``bisect_microbatches``), quarantines the culprit batch
    (``quarantine``) and continues. More than ``max_rollbacks`` rollbacks
    within ``rollback_window_steps`` escalates a structured
    ``RestartableFailure`` into the ``ElasticAgent`` backoff path.
    ``checkpoint_every`` > 0 makes ``TrainingGuardian.run`` write its own
    rollback anchors at that step cadence (0 = the caller checkpoints)."""
    enabled: bool = False
    nonfinite_guard: bool = True
    z_threshold: float = 6.0
    warmup_observations: int = 8
    ema_decay: float = 0.7
    max_rollbacks: int = 2
    rollback_window_steps: int = 500
    checkpoint_every: int = 0
    bisect_microbatches: bool = True
    quarantine: bool = True

    def validate(self) -> None:
        if self.z_threshold <= 0:
            raise DeepSpeedConfigError(
                f"guardian.z_threshold must be > 0, got {self.z_threshold}")
        if not 0.0 < self.ema_decay < 1.0:
            raise DeepSpeedConfigError(
                "guardian.ema_decay must be in (0, 1), got "
                f"{self.ema_decay}")
        for key in ("warmup_observations", "max_rollbacks",
                    "rollback_window_steps", "checkpoint_every"):
            val = getattr(self, key)
            if not isinstance(val, int) or isinstance(val, bool) or val < 0:
                raise DeepSpeedConfigError(
                    f"guardian.{key} must be a non-negative int, got "
                    f"{val!r}")


@dataclasses.dataclass
class ActivationCheckpointingConfig:
    """Reference ``runtime/activation_checkpointing`` config. On TPU this selects a
    ``jax.checkpoint`` (remat) policy applied to the per-layer scan."""
    partition_activations: bool = False
    cpu_checkpointing: bool = False
    contiguous_memory_optimization: bool = False
    number_checkpoints: Optional[int] = None
    synchronize_checkpoint_boundary: bool = False
    profile: bool = False
    # TPU-native: named remat policy (see runtime/activation_checkpointing)
    policy: str = "none"  # none | full | dots_saveable | save_nothing | offload_dots


@dataclasses.dataclass
class FlopsProfilerConfig:
    enabled: bool = False
    profile_step: int = 1
    module_depth: int = -1
    top_modules: int = 1
    detailed: bool = True
    output_file: Optional[str] = None


@dataclasses.dataclass
class MonitorBackendConfig:
    enabled: bool = False
    output_path: str = ""
    job_name: str = "DeepSpeedTPUJobName"
    team: Optional[str] = None
    project: Optional[str] = None
    group: Optional[str] = None


@dataclasses.dataclass
class DataTypesConfig:
    grad_accum_dtype: Optional[str] = None


@dataclasses.dataclass
class MeshSectionConfig:
    """TPU-native: named mesh axis sizes. -1 absorbs remaining devices."""
    pipe: int = 1
    data: int = -1
    zshard: int = 1  # MiCS/hpZ subgroup size (see zero_optimization.mics_shard_size)
    expert: int = 1
    seq: int = 1
    tensor: int = 1

    def to_mesh_config(self) -> MeshConfig:
        return MeshConfig(pipe=self.pipe, data=self.data, zshard=self.zshard,
                          expert=self.expert, seq=self.seq, tensor=self.tensor)


@dataclasses.dataclass
class TensorParallelConfig:
    autotp_size: int = 1
    tp_grain_size: int = 1


@dataclasses.dataclass
class SequenceParallelConfig:
    """AutoSP config hook (reference ``compile_autosp`` engine.py:1160 /
    DeepCompile ``sp_compile`` pass): when ``auto`` is set the engine runs
    the AutoSP planning pass (``sequence/auto_sp.py``) over the model spec at
    initialize — mechanism (ulysses vs KV ring) chosen by feasibility + comm
    cost on the mesh's 'seq' axis."""
    auto: bool = False
    # informational check: if set, must match the mesh 'seq' axis
    size: int = 0


@dataclasses.dataclass
class PipelineSectionConfig:
    stages: int = 1
    micro_batches: Optional[int] = None
    activation_checkpoint_interval: int = 0


@dataclasses.dataclass
class CurriculumConfig:
    """Reference ``data_efficiency.data_sampling.curriculum_learning`` keys
    (``runtime/data_pipeline/data_sampling/curriculum_scheduler.py``).
    Real DeepSpeed JSON nests ramp parameters under ``schedule_config`` —
    both placements are accepted (``schedule_config`` wins)."""
    enabled: bool = False
    schedule_type: str = "fixed_linear"
    min_difficulty: int = 8
    max_difficulty: int = 1024
    total_curriculum_step: int = 1000
    difficulty_step: int = 8
    root_degree: int = 2
    difficulty: list = dataclasses.field(default_factory=list)
    max_step: list = dataclasses.field(default_factory=list)
    schedule_config: dict = dataclasses.field(default_factory=dict)

    def scheduler_dict(self) -> dict:
        d = dataclasses.asdict(self)
        d.update(d.pop("schedule_config") or {})
        return d


@dataclasses.dataclass
class DynamicBatchingConfig:
    """Reference ``variable_batch_size_and_lr.py`` (492 LoC): token-budget
    batching of variable-length samples with LR scaling."""
    enabled: bool = False
    max_tokens: int = 8192
    lr_scaling_method: str = "linear"   # linear | sqrt | none
    min_batch_size: int = 1
    max_batch_size: int = 0             # 0 → unlimited
    sentence_picking_order: str = "dataloader"  # dataloader | random | seqlen


@dataclasses.dataclass
class RandomLTDConfig:
    """Reference ``data_efficiency.data_routing.random_ltd``."""
    enabled: bool = False
    total_layer_num: int = 0            # 0 → all middle layers
    random_ltd_layer_num: int = 0
    max_value: int = 1024
    random_ltd_schedule: dict = dataclasses.field(default_factory=dict)


@dataclasses.dataclass
class DataSamplingConfig:
    enabled: bool = False
    curriculum_learning: CurriculumConfig = dataclasses.field(
        default_factory=CurriculumConfig)
    dynamic_batching: DynamicBatchingConfig = dataclasses.field(
        default_factory=DynamicBatchingConfig)


@dataclasses.dataclass
class DataRoutingConfig:
    enabled: bool = False
    random_ltd: RandomLTDConfig = dataclasses.field(
        default_factory=RandomLTDConfig)


@dataclasses.dataclass
class DataEfficiencyConfig:
    """Reference ``data_efficiency`` section (``runtime/data_pipeline/``)."""
    enabled: bool = False
    seed: int = 1234
    data_sampling: DataSamplingConfig = dataclasses.field(
        default_factory=DataSamplingConfig)
    data_routing: DataRoutingConfig = dataclasses.field(
        default_factory=DataRoutingConfig)


@dataclasses.dataclass
class ProgressiveLayerDropConfig:
    """Reference ``progressive_layer_drop`` section
    (``runtime/progressive_layer_drop.py``; engine hook engine.py:430)."""
    enabled: bool = False
    theta: float = 0.5
    gamma: float = 0.001


# CUDA-only reference sections accepted and ignored (keeps real DeepSpeed JSON
# configs loadable); each logs once when present. "autotuning" left this
# list in PR 16 (TPU-native plan engine); "elasticity" in PR 17 (it now
# configures the world-elastic agent — ElasticitySectionConfig).
_IGNORED_SECTIONS = (
    "amp", "aio", "hybrid_engine", "compression_training",
    "sparse_attention", "zero_allow_untested_optimizer", "communication_data_type",
)


@dataclasses.dataclass
class DeepSpeedTPUConfig:
    train_batch_size: Optional[int] = None
    train_micro_batch_size_per_gpu: Optional[int] = None
    gradient_accumulation_steps: Optional[int] = None
    steps_per_print: int = 10
    wall_clock_breakdown: bool = False
    memory_breakdown: bool = False
    gradient_clipping: float = 0.0
    prescale_gradients: bool = False
    gradient_predivide_factor: float = 1.0
    dump_state: bool = False
    optimizer: Optional[OptimizerConfig] = None
    scheduler: Optional[SchedulerConfig] = None
    fp16: FP16Config = dataclasses.field(default_factory=FP16Config)
    bf16: BF16Config = dataclasses.field(default_factory=BF16Config)
    zero_optimization: ZeroConfig = dataclasses.field(default_factory=ZeroConfig)
    comms_logger: CommsLoggerConfig = dataclasses.field(default_factory=CommsLoggerConfig)
    telemetry: TelemetryConfig = dataclasses.field(default_factory=TelemetryConfig)
    serving: ServingSectionConfig = dataclasses.field(
        default_factory=ServingSectionConfig)
    fleet: FleetSectionConfig = dataclasses.field(
        default_factory=FleetSectionConfig)
    tenancy: TenancySectionConfig = dataclasses.field(
        default_factory=TenancySectionConfig)
    slo: SloSectionConfig = dataclasses.field(
        default_factory=SloSectionConfig)
    hlolint: HlolintSectionConfig = dataclasses.field(
        default_factory=HlolintSectionConfig)
    memlint: MemlintSectionConfig = dataclasses.field(
        default_factory=MemlintSectionConfig)
    autotuning: AutotuningSectionConfig = dataclasses.field(
        default_factory=AutotuningSectionConfig)
    elasticity: ElasticitySectionConfig = dataclasses.field(
        default_factory=ElasticitySectionConfig)
    activation_checkpointing: ActivationCheckpointingConfig = dataclasses.field(
        default_factory=ActivationCheckpointingConfig)
    flops_profiler: FlopsProfilerConfig = dataclasses.field(default_factory=FlopsProfilerConfig)
    tensorboard: MonitorBackendConfig = dataclasses.field(default_factory=MonitorBackendConfig)
    csv_monitor: MonitorBackendConfig = dataclasses.field(default_factory=MonitorBackendConfig)
    wandb: MonitorBackendConfig = dataclasses.field(default_factory=MonitorBackendConfig)
    comet: MonitorBackendConfig = dataclasses.field(default_factory=MonitorBackendConfig)
    data_types: DataTypesConfig = dataclasses.field(default_factory=DataTypesConfig)
    mesh: MeshSectionConfig = dataclasses.field(default_factory=MeshSectionConfig)
    tensor_parallel: TensorParallelConfig = dataclasses.field(default_factory=TensorParallelConfig)
    sequence_parallel: SequenceParallelConfig = dataclasses.field(
        default_factory=SequenceParallelConfig)
    pipeline: PipelineSectionConfig = dataclasses.field(default_factory=PipelineSectionConfig)
    seed: int = 1234
    zero_force_ds_cpu_optimizer: bool = False
    checkpoint_tag_validation: str = "Warn"  # Ignore | Warn | Fail
    checkpoint_writer: str = "orbax"  # orbax | fast (checkpoint_engine.py)
    checkpoint: CheckpointSectionConfig = dataclasses.field(
        default_factory=CheckpointSectionConfig)
    fault_tolerance: FaultToleranceConfig = dataclasses.field(
        default_factory=FaultToleranceConfig)
    guardian: GuardianSectionConfig = dataclasses.field(
        default_factory=GuardianSectionConfig)
    data_efficiency: DataEfficiencyConfig = dataclasses.field(
        default_factory=DataEfficiencyConfig)
    # legacy top-level section (reference supports both placements)
    curriculum_learning: CurriculumConfig = dataclasses.field(
        default_factory=CurriculumConfig)
    progressive_layer_drop: ProgressiveLayerDropConfig = dataclasses.field(
        default_factory=ProgressiveLayerDropConfig)

    @property
    def curriculum(self) -> CurriculumConfig:
        """Active curriculum config: the data_efficiency placement applies
        when its parent gates are on (reference semantics); the legacy
        top-level section needs no parent."""
        de = self.data_efficiency
        cur = de.data_sampling.curriculum_learning
        if cur.enabled and de.enabled and de.data_sampling.enabled:
            return cur
        return self.curriculum_learning

    # resolved fields (filled by _resolve_batch_size)
    _dp_world_size: int = 1

    @property
    def zero_enabled(self) -> bool:
        return self.zero_optimization.stage > 0

    @property
    def effective_checkpoint_writer(self) -> str:
        """``checkpoint.writer`` when set, else the legacy top-level
        ``checkpoint_writer`` (both spellings stay valid)."""
        return self.checkpoint.writer or self.checkpoint_writer

    @property
    def precision_dtype(self) -> str:
        if self.fp16.enabled and self.bf16.enabled:
            raise DeepSpeedConfigError("fp16 and bf16 cannot both be enabled")
        if self.fp16.enabled:
            return "float16"
        if self.bf16.enabled:
            return "bfloat16"
        return "float32"

    def resolve_batch_size(self, dp_world_size: int) -> None:
        """Batch-size triad resolution: train = micro × GAS × dp (reference
        ``runtime/config.py`` ``_batch_assertion``)."""
        self._dp_world_size = dp_world_size
        tb, mb, gas = (self.train_batch_size, self.train_micro_batch_size_per_gpu,
                       self.gradient_accumulation_steps)
        if tb is not None and mb is not None and gas is not None:
            if tb != mb * gas * dp_world_size:
                raise DeepSpeedConfigError(
                    f"train_batch_size {tb} != micro {mb} × gas {gas} × dp {dp_world_size}")
        elif tb is not None and mb is not None:
            if tb % (mb * dp_world_size) != 0:
                raise DeepSpeedConfigError(
                    f"train_batch_size {tb} not divisible by micro {mb} × dp {dp_world_size}")
            self.gradient_accumulation_steps = tb // (mb * dp_world_size)
        elif tb is not None and gas is not None:
            if tb % (gas * dp_world_size) != 0:
                raise DeepSpeedConfigError(
                    f"train_batch_size {tb} not divisible by gas {gas} × dp {dp_world_size}")
            self.train_micro_batch_size_per_gpu = tb // (gas * dp_world_size)
        elif tb is not None:
            self.gradient_accumulation_steps = 1
            if tb % dp_world_size != 0:
                raise DeepSpeedConfigError(
                    f"train_batch_size {tb} not divisible by dp {dp_world_size}")
            self.train_micro_batch_size_per_gpu = tb // dp_world_size
        elif mb is not None:
            self.gradient_accumulation_steps = gas or 1
            self.train_batch_size = mb * self.gradient_accumulation_steps * dp_world_size
        else:
            raise DeepSpeedConfigError(
                "config must set train_batch_size or train_micro_batch_size_per_gpu")


def load_config(config) -> DeepSpeedTPUConfig:
    """Accepts a dict, a JSON file path, or an existing config object."""
    if isinstance(config, DeepSpeedTPUConfig):
        return config
    if isinstance(config, str):
        with open(config) as f:
            config = json.load(f)
    if not isinstance(config, dict):
        raise DeepSpeedConfigError(f"config must be dict or path, got {type(config)}")
    config = dict(config)
    for section in _IGNORED_SECTIONS:
        if section in config:
            logger.warning(f"config section {section!r} is not applicable on TPU — ignored")
            config.pop(section)
    cfg = config_from_dict(DeepSpeedTPUConfig, config)
    # which zero_optimization knobs the USER spelled out, verbatim — the
    # plan engine's apply/stale logic needs "explicitly set" vs "left at
    # default", and a dataclass can't tell the difference after the fact
    zo = config.get("zero_optimization")
    cfg._explicit_zero_keys = frozenset(zo) if isinstance(zo, dict) \
        else frozenset()
    # launcher/env defaults (deepspeed_tpu.launcher --resume_dir /
    # --auto_resume): explicit JSON settings always win
    import os as _os

    env_dir = _os.environ.get("DSTPU_RESUME_DIR")
    if env_dir and cfg.fault_tolerance.resume_dir is None:
        cfg.fault_tolerance.resume_dir = env_dir
    if cfg.fault_tolerance.auto_resume is None and \
            _os.environ.get("DSTPU_AUTO_RESUME", "").lower() in \
            ("1", "true", "yes"):
        cfg.fault_tolerance.auto_resume = True
    return cfg


# Back-compat alias matching the reference class name.
DeepSpeedConfig = DeepSpeedTPUConfig
