"""Domino: tensor-parallel compute/communication overlap.

Parity: reference ``runtime/domino/transformer.py`` (``DominoTransformer``
:411, ``ShardedAttention`` :108): row/column-split TP layers whose batch is
split into two half-chunks so each chunk's TP allreduce runs asynchronously
under the other chunk's compute (hand-managed CUDA streams + async allreduce
handles; motivation: TP comm up to 43% of iteration time,
``blogs/deepspeed-domino/README.md:36``).

TPU translation — two mechanisms, both expressed here:

1. **XLA latency hiding (free Domino).** Under SPMD the TP collectives
   (psum after row-parallel matmuls) are emitted by the partitioner, and
   XLA's latency-hiding scheduler already overlaps them with independent
   compute, which is the bulk of what Domino hand-builds. The knobs live in
   :data:`XLA_OVERLAP_FLAGS` — enabled by default on recent libtpu; exposed
   so deployments can assert/force them.

2. **Explicit chunk interleaving.** :func:`domino_lm_loss` recreates
   Domino's batch-split: the microbatch is split into ``n_chunks`` along
   batch, each chunk's layer stack is traced independently, and the chunks'
   programs interleave in the scheduler's window. Losses combine exactly
   (equal chunks ⇒ identical numerics to the unsplit loss).

MEASURED (round 2, TP=2 on the 8-device CPU mesh — the only multi-device
venue available): chunked = 0.99× of unsplit, i.e. NO win — XLA's scheduler
already overlaps whatever it can and the chunk split only shrinks per-matmul
surfaces. The chunk path is therefore an OPT-IN mechanism (``domino_spec``)
kept for parity and for future multi-chip ICI profiling, not an asserted
speedup; mechanism 1 (the default compiler behavior + flags above) is the
production answer to Domino on TPU. See ``tests/unit/test_domino_zenflow.py``
for the parity + measurement harness.
"""
from __future__ import annotations

from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp

from deepspeed_tpu.models import transformer as T

PyTree = Any

# XLA flags that control collective/compute overlap on TPU (documented for
# deployment parity with Domino's async-allreduce machinery; current libtpu
# enables the scheduler by default). Apply through
# :func:`apply_overlap_flags` — NEVER by blindly appending to XLA_FLAGS:
# the set spans jaxlib generations and an unknown ``--xla_*`` flag
# hard-aborts backend creation (``F parse_flags_from_env``). The probe
# (``utils/xla_compat.probe_xla_flags``, same machinery as
# tests/conftest.py's collective-timeout flags) vets each flag in a
# throwaway subprocess and the unsupported ones are logged and skipped.
XLA_OVERLAP_FLAGS = (
    "--xla_tpu_enable_async_collective_fusion=true",
    "--xla_tpu_enable_async_collective_fusion_fuse_all_gather=true",
    "--xla_tpu_overlap_compute_collective_tc=true",
    "--xla_enable_async_all_gather=true",
    "--xla_enable_async_collective_permute=true",
)


def supported_overlap_flags() -> tuple:
    """The subset of :data:`XLA_OVERLAP_FLAGS` this jaxlib accepts
    (probed once per jaxlib version, cached; see
    ``utils/xla_compat.probe_xla_flags``)."""
    from deepspeed_tpu.utils.xla_compat import probe_xla_flags

    return probe_xla_flags(XLA_OVERLAP_FLAGS)


def apply_overlap_flags() -> str:
    """Append the PROBED overlap flags to ``XLA_FLAGS`` (idempotent).

    Returns the flags actually APPENDED by this call, as one string —
    empty when nothing changed: no flag supported, or every flag name
    already present in ``XLA_FLAGS`` (a user's explicit ``=false`` is
    respected, not overridden, and not reported as armed). Every
    skipped flag is logged, not raised: an older jaxlib must degrade to
    its default scheduler, not crash. Call BEFORE the first jax backend
    use — once a backend exists the env change is inert, and this logs
    a warning instead of pretending otherwise."""
    import os

    from deepspeed_tpu.utils.logging import logger

    supported = supported_overlap_flags()
    skipped = [f for f in XLA_OVERLAP_FLAGS if f not in supported]
    if skipped:
        logger.info(
            f"domino overlap flags not supported by this jaxlib — "
            f"skipped: {' '.join(skipped)}")
    if not supported:
        return ""
    current = os.environ.get("XLA_FLAGS", "")
    # compare flag NAMES, not full tokens: a user who explicitly set
    # --xla_...=false must not have it silently overridden by appending
    # our =true after it (XLA takes the last occurrence)
    present = {tok.split("=", 1)[0] for tok in current.split()}
    missing = [f for f in supported
               if f.split("=", 1)[0] not in present]
    if missing:
        backend_up = False
        try:
            from jax._src import xla_bridge as _xb

            backend_up = bool(getattr(_xb, "_backends", None))
        except (ImportError, AttributeError):
            pass   # private surface moved — best-effort warning only
        if backend_up:
            logger.warning(
                "domino overlap flags applied AFTER jax backend "
                "initialization — they take effect in subprocesses "
                "(bench entries, launcher workers), not this process")
        os.environ["XLA_FLAGS"] = (current + " " + " ".join(missing)).strip()
    return " ".join(missing)


def domino_lm_loss(params: PyTree, tokens: jax.Array, cfg: T.TransformerConfig,
                   n_chunks: int = 2,
                   attention_fn: Optional[Callable] = None,
                   activation_constraint: Optional[Callable] = None,
                   loss_mask: Optional[jax.Array] = None) -> jax.Array:
    """Causal-LM loss with the batch split into ``n_chunks`` interleaved
    chunks (the Domino batch-split; reference ``DominoTransformer`` forward).

    Each chunk runs the full layer stack as an independent program slice, so
    the TP allreduce of one chunk overlaps the compute of the next. With
    equal chunk sizes the result is numerically identical to the unsplit
    loss (mean of per-chunk means over equal token counts).
    """
    B = tokens.shape[0]
    if B % n_chunks:
        raise ValueError(f"batch {B} not divisible by n_chunks={n_chunks}")
    step = B // n_chunks
    losses = []
    for c in range(n_chunks):
        tk = jax.lax.slice_in_dim(tokens, c * step, (c + 1) * step, axis=0)
        hidden, head, aux = T.forward_hidden(
            params, tk, cfg, attention_fn=attention_fn,
            activation_constraint=activation_constraint)
        logits = T.head_matmul(hidden, head.astype(hidden.dtype))
        mk = None
        if loss_mask is not None:
            mk = jax.lax.slice_in_dim(loss_mask, c * step, (c + 1) * step, 0)
        loss = T.causal_lm_loss(logits, tk, mk)
        if cfg.n_experts > 0:
            loss = loss + cfg.moe_aux_coef * aux
        losses.append(loss)
    return jnp.mean(jnp.stack(losses))


def domino_spec(cfg, n_chunks: int = 2, attention: Optional[str] = None,
                **overrides):
    """ModelSpec whose loss uses Domino chunk interleaving — drop-in for
    ``causal_lm_spec`` when TP comm dominates (``deepspeed_tpu.initialize``
    consumes it unchanged)."""
    import dataclasses as _dc

    from deepspeed_tpu.models.api import causal_lm_spec, resolve_attention

    base = causal_lm_spec(cfg, attention=attention, **overrides)
    attention_fn = resolve_attention(attention)
    model_cfg = base.config

    def loss_fn(params, batch):
        tokens = batch["tokens"] if isinstance(batch, dict) else batch
        mask = batch.get("loss_mask") if isinstance(batch, dict) else None
        return domino_lm_loss(params, tokens, model_cfg, n_chunks=n_chunks,
                              attention_fn=attention_fn, loss_mask=mask)

    return _dc.replace(base, loss_fn=loss_fn, name=base.name + f"+domino{n_chunks}")
