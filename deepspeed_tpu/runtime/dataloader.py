"""Deterministic sharded data loading for SPMD training.

Parity: reference ``runtime/dataloader.py`` (``DeepSpeedDataLoader``,
``RepeatingLoader``). SPMD twist: a batch is ONE global ``jax.Array`` sharded
over the mesh, not per-rank tensors — each host feeds its addressable shard via
``jax.make_array_from_process_local_data``.
"""
from __future__ import annotations

from typing import Any, Callable, Dict, Iterator, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding

PyTree = Any


class RepeatingLoader:
    """Wraps a re-iterable, restarting it when exhausted (reference analog).

    Generators cannot be restarted — ``iter()`` on an exhausted generator returns
    the same exhausted object — so they are rejected with a clear error rather
    than silently raising StopIteration mid-epoch.
    """

    def __init__(self, loader):
        self.loader = loader
        self.data_iter = iter(self.loader)
        if self.data_iter is loader:
            raise TypeError(
                "RepeatingLoader needs a re-iterable source (list, DataLoader, ...); "
                "got a one-shot iterator/generator. Make the source infinite instead "
                "(e.g. synthetic_lm_data(num_batches=None)) or pass a sequence.")

    def __iter__(self):
        return self

    def __next__(self):
        try:
            return next(self.data_iter)
        except StopIteration:
            self.data_iter = iter(self.loader)
            return next(self.data_iter)


class DeepSpeedTPUDataLoader:
    """Yields global sharded batches from a host-local numpy source.

    ``source`` yields numpy pytrees with a leading *global* batch dim (single
    process) or the process-local slice (multi-host) — ``make_array_from_
    process_local_data`` assembles the global array either way.
    """

    def __init__(self, source, batch_sharding: NamedSharding,
                 drop_last: bool = True):
        self.source = source
        self.batch_sharding = batch_sharding
        self.drop_last = drop_last

    def __iter__(self) -> Iterator[PyTree]:
        for host_batch in self.source:
            yield shard_host_batch(host_batch, self.batch_sharding)

    def __len__(self):
        return len(self.source)


def shard_host_batch(host_batch: PyTree, sharding: NamedSharding) -> PyTree:
    def put(x):
        x = np.asarray(x)
        if jax.process_count() == 1:
            return jax.device_put(x, sharding)
        return jax.make_array_from_process_local_data(sharding, x)

    return jax.tree.map(put, host_batch)


def synthetic_lm_data(batch_size: int, seq_len: int, vocab_size: int,
                      seed: int = 0, num_batches: Optional[int] = None,
                      dtype=np.int32):
    """Deterministic synthetic token stream (the ``random_dataloader`` fixture
    analog, reference ``tests/unit/simple_model.py:275``)."""
    rng = np.random.default_rng(seed)
    i = 0
    while num_batches is None or i < num_batches:
        yield {"tokens": rng.integers(0, vocab_size, (batch_size, seq_len), dtype=dtype)}
        i += 1
