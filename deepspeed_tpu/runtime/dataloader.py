"""Deterministic sharded data loading for SPMD training.

Parity: reference ``runtime/dataloader.py`` (``DeepSpeedDataLoader``,
``RepeatingLoader``). SPMD twist: a batch is ONE global ``jax.Array`` sharded
over the mesh, not per-rank tensors — each host feeds its addressable shard via
``jax.make_array_from_process_local_data``.

Checkpointable pipeline (README "Training guardian"): the loaders carry
explicit position state — ``state_dict()`` / ``load_state_dict()`` with
epoch, within-epoch offset, shuffle RNG, and a **quarantine list** of
batch ids the stream must skip — so ``auto_resume`` after a preemption
(and the guardian's anomaly rollback) replays the EXACT batch sequence an
uninterrupted run would have seen, minus quarantined culprits. A batch id
is the ``(epoch, offset)`` occurrence pair: ``offset`` counts batches READ
from the source this epoch (quarantined reads included), so ids are stable
across replays and fast-forwards.

The ``data/poison_batch`` chaos injection point lives on the host read
path here: when an armed ``fail`` window covers the read, the batch's
token leaves are re-rolled from a poison RNG — the bad-disk/bad-shard
shape the guardian's bisect must localize. The poisoned occurrence id is
remembered on the loader instance (NOT in ``state_dict``) so a rollback
replay re-reads the same corruption until the batch is quarantined,
which is how real on-disk corruption behaves.
"""
from __future__ import annotations

from typing import Any, Dict, Iterator, List, Optional, Tuple

import jax
import numpy as np
from jax.sharding import NamedSharding

from deepspeed_tpu.testing.chaos import chaos_should_fire

PyTree = Any


class RepeatingLoader:
    """Wraps a re-iterable, restarting it when exhausted (reference analog).

    Generators cannot be restarted — ``iter()`` on an exhausted generator returns
    the same exhausted object — so they are rejected with a clear error rather
    than silently raising StopIteration mid-epoch.

    Stateful: ``state_dict()`` records ``(epoch, offset)`` — epochs completed
    and items yielded this epoch — and ``load_state_dict()`` fast-forwards a
    fresh pass to the exact position (delegating to the inner loader's own
    ``state_dict`` when it has one, so a stateful inner stream is restored
    natively instead of replayed).
    """

    def __init__(self, loader):
        self.loader = loader
        self.data_iter = iter(self.loader)
        if self.data_iter is loader:
            raise TypeError(
                "RepeatingLoader needs a re-iterable source (list, DataLoader, ...); "
                "got a one-shot iterator/generator. Make the source infinite instead "
                "(e.g. synthetic_lm_data(num_batches=None)) or pass a sequence.")
        self.epoch = 0
        self.offset = 0

    def __iter__(self):
        return self

    def __next__(self):
        try:
            item = next(self.data_iter)
        except StopIteration:
            self.data_iter = iter(self.loader)
            self.epoch += 1
            self.offset = 0
            item = next(self.data_iter)
        self.offset += 1
        return item

    def state_dict(self) -> Dict[str, Any]:
        sd: Dict[str, Any] = {"epoch": self.epoch, "offset": self.offset}
        inner = getattr(self.loader, "state_dict", None)
        if callable(inner):
            sd["inner"] = inner()
        return sd

    def load_state_dict(self, sd: Dict[str, Any]) -> None:
        self.epoch = int(sd.get("epoch", 0))
        self.offset = int(sd.get("offset", 0))
        inner = getattr(self.loader, "load_state_dict", None)
        if callable(inner) and sd.get("inner") is not None:
            inner(sd["inner"])
            self.data_iter = iter(self.loader)
            return
        # fast-forward exact: a fresh pass discards `offset` items so the
        # next __next__ yields the same batch the interrupted run would have
        self.data_iter = iter(self.loader)
        for _ in range(self.offset):
            next(self.data_iter)


def _poison_tokens(host_batch: PyTree, batch_id: Tuple[int, int]) -> PyTree:
    """``data/poison_batch`` corruption: re-roll every integer leaf from a
    poison RNG seeded by the batch id (deterministic — a replay of the same
    occurrence reproduces the same corruption, like real disk rot). Float
    leaves are scrambled with seeded noise."""
    rng = np.random.default_rng((0xBAD, batch_id[0], batch_id[1]))

    def corrupt(x):
        x = np.asarray(x)
        if np.issubdtype(x.dtype, np.integer):
            hi = max(int(x.max()) + 1, 2)
            return rng.integers(0, hi, x.shape).astype(x.dtype)
        if np.issubdtype(x.dtype, np.floating):
            return rng.standard_normal(x.shape).astype(x.dtype) * 1e3
        return x

    return jax.tree.map(corrupt, host_batch)


class DeepSpeedTPUDataLoader:
    """Yields global sharded batches from a host-local numpy source.

    ``source`` yields numpy pytrees with a leading *global* batch dim (single
    process) or the process-local slice (multi-host) — ``make_array_from_
    process_local_data`` assembles the global array either way.

    The loader is ONE logical stream across epochs: each ``__iter__`` pass
    continues from the current position (a fresh loader starts at epoch 0,
    offset 0; exhausting the source ends the epoch, and the next pass is
    the next epoch). ``shuffle=True`` (sequence sources only) draws a
    deterministic permutation per epoch from the seeded shuffle RNG.
    Quarantined batch ids are skipped on read; ``state_dict()`` /
    ``load_state_dict()`` round-trip epoch, offset, shuffle RNG, and the
    quarantine list so resume replays the exact remaining sequence.
    """

    def __init__(self, source, batch_sharding: NamedSharding,
                 drop_last: bool = True, shuffle: bool = False,
                 seed: int = 0):
        self.source = source
        self.batch_sharding = batch_sharding
        self.drop_last = drop_last
        self.shuffle = shuffle
        if shuffle and not (hasattr(source, "__len__")
                            and hasattr(source, "__getitem__")):
            raise TypeError("shuffle=True needs a sequence source "
                            "(__len__ + __getitem__)")
        self.epoch = 0
        self.offset = 0          # batches READ this epoch (incl. quarantined)
        self.quarantined: List[Tuple[int, int]] = []
        self._rng = np.random.default_rng(seed)
        # RNG state snapshot taken before the CURRENT epoch's permutation
        # draw — load_state_dict restores it and redraws, so a mid-epoch
        # resume sees the same shuffle order
        self._epoch_rng_state = self._rng.bit_generator.state
        self._perm: Optional[np.ndarray] = None
        # chaos bookkeeping (instance-level, NOT checkpointed: corruption
        # is a property of the storage, not of the reader's position)
        self._chaos_poisoned: List[Tuple[int, int]] = []

    # -------------------------------------------------------------- #
    # position state
    # -------------------------------------------------------------- #
    @property
    def last_batch_id(self) -> Tuple[int, int]:
        """Id of the most recently yielded batch: ``(epoch, offset - 1)``
        where offset counts source reads this epoch."""
        return (self.epoch, self.offset - 1)

    def quarantine(self, batch_id) -> None:
        """Skip this ``(epoch, offset)`` occurrence on any future read
        (the guardian calls this with the bisected culprit's id)."""
        bid = (int(batch_id[0]), int(batch_id[1]))
        if bid not in self.quarantined:
            self.quarantined.append(bid)

    def state_dict(self) -> Dict[str, Any]:
        sd: Dict[str, Any] = {
            "epoch": self.epoch,
            "offset": self.offset,
            "quarantined": [list(b) for b in self.quarantined],
            "shuffle_rng": self._epoch_rng_state if self.shuffle else None,
        }
        inner = getattr(self.source, "state_dict", None)
        if callable(inner):
            sd["source"] = inner()
        return sd

    def load_state_dict(self, sd: Dict[str, Any]) -> None:
        self.epoch = int(sd.get("epoch", 0))
        self.offset = int(sd.get("offset", 0))
        self.quarantined = [
            (int(b[0]), int(b[1])) for b in sd.get("quarantined") or []]
        if self.shuffle and sd.get("shuffle_rng"):
            self._epoch_rng_state = sd["shuffle_rng"]
            self._rng.bit_generator.state = self._epoch_rng_state
        self._perm = None   # redrawn (from the restored state) on next pass
        inner = getattr(self.source, "load_state_dict", None)
        if callable(inner) and sd.get("source") is not None:
            inner(sd["source"])

    # -------------------------------------------------------------- #
    # the stream
    # -------------------------------------------------------------- #
    def _epoch_perm(self, n: int) -> np.ndarray:
        if self._perm is None or len(self._perm) != n:
            self._rng.bit_generator.state = self._epoch_rng_state
            self._perm = self._rng.permutation(n)
        return self._perm

    def _host_batches(self) -> Iterator[Tuple[Tuple[int, int], PyTree]]:
        """One epoch's worth of (batch_id, host_batch) from the current
        offset, reading the source directly (no sharding)."""
        if self.shuffle:
            n = len(self.source)
            perm = self._epoch_perm(n)
            while self.offset < n:
                idx = int(perm[self.offset])
                bid = (self.epoch, self.offset)
                self.offset += 1
                yield bid, self.source[idx]
        else:
            it = iter(self.source)
            if not callable(getattr(self.source, "state_dict", None)):
                # fast-forward after load_state_dict by re-reading and
                # discarding; a STATEFUL source restored its own position
                # natively, so its fresh iterator already continues there
                for _ in range(self.offset):
                    next(it)
            for host_batch in it:
                bid = (self.epoch, self.offset)
                self.offset += 1
                yield bid, host_batch

    def _end_epoch(self) -> None:
        self.epoch += 1
        self.offset = 0
        if self.shuffle:
            # snapshot BEFORE the next epoch's draw so a checkpoint taken
            # any time during that epoch can reproduce its permutation
            self._epoch_rng_state = self._rng.bit_generator.state
            self._perm = None

    def host_stream(self) -> Iterator[Tuple[Tuple[int, int], PyTree]]:
        """One epoch of ``(batch_id, host_batch)`` with chaos poison
        injection and quarantine filtering applied, NO device sharding —
        the guardian's pull path (``engine.train_batch`` stacks + shards
        host windows itself)."""
        for bid, host_batch in self._host_batches():
            if chaos_should_fire("data/poison_batch") \
                    and bid not in self._chaos_poisoned:
                self._chaos_poisoned.append(bid)
            if bid in self._chaos_poisoned:
                host_batch = _poison_tokens(host_batch, bid)
            if bid in self.quarantined:
                continue
            yield bid, host_batch
        self._end_epoch()

    def __iter__(self) -> Iterator[PyTree]:
        for _, host_batch in self.host_stream():
            yield shard_host_batch(host_batch, self.batch_sharding)

    def __len__(self):
        return len(self.source)


class SyntheticLMLoader:
    """Re-iterable, checkpointable synthetic token stream.

    Batch ``i`` of the stream is a pure function of ``(seed, i %
    num_distinct)`` — random access, so ``state_dict`` is just the emitted
    count. ``num_distinct`` bounds the vocabulary of batches: a small value
    makes the stream memorizable (loss falls), which is what the guardian's
    loss-spike detection tests need — a poisoned batch then stands out
    against a learnable baseline instead of hiding in uniform noise.
    An epoch is ``num_batches`` batches (``None`` = one infinite epoch).
    """

    def __init__(self, batch_size: int, seq_len: int, vocab_size: int,
                 seed: int = 0, num_batches: Optional[int] = None,
                 num_distinct: Optional[int] = None, dtype=np.int32):
        self.batch_size = batch_size
        self.seq_len = seq_len
        self.vocab_size = vocab_size
        self.seed = seed
        self.num_batches = num_batches
        self.num_distinct = num_distinct
        self.dtype = dtype
        self.emitted = 0   # absolute ordinal of the next batch

    def batch_at(self, i: int) -> Dict[str, np.ndarray]:
        key = i if self.num_distinct is None else i % self.num_distinct
        rng = np.random.default_rng((self.seed, key))
        return {"tokens": rng.integers(
            0, self.vocab_size, (self.batch_size, self.seq_len),
            dtype=self.dtype)}

    def __iter__(self):
        start = self.emitted
        while self.num_batches is None \
                or self.emitted - start < self.num_batches:
            batch = self.batch_at(self.emitted)
            self.emitted += 1
            yield batch

    def __len__(self):
        if self.num_batches is None:
            raise TypeError("infinite SyntheticLMLoader has no len()")
        return self.num_batches

    def state_dict(self) -> Dict[str, Any]:
        return {"emitted": self.emitted}

    def load_state_dict(self, sd: Dict[str, Any]) -> None:
        self.emitted = int(sd.get("emitted", 0))


def shard_host_batch(host_batch: PyTree, sharding: NamedSharding) -> PyTree:
    def put(x):
        x = np.asarray(x)
        if jax.process_count() == 1:
            return jax.device_put(x, sharding)
        return jax.make_array_from_process_local_data(sharding, x)

    return jax.tree.map(put, host_batch)


def synthetic_lm_data(batch_size: int, seq_len: int, vocab_size: int,
                      seed: int = 0, num_batches: Optional[int] = None,
                      dtype=np.int32):
    """Deterministic synthetic token stream (the ``random_dataloader`` fixture
    analog, reference ``tests/unit/simple_model.py:275``)."""
    rng = np.random.default_rng(seed)
    i = 0
    while num_batches is None or i < num_batches:
        yield {"tokens": rng.integers(0, vocab_size, (batch_size, seq_len), dtype=dtype)}
        i += 1
