"""Typed-config plumbing.

Parity: reference ``runtime/config_utils.py`` (``DeepSpeedConfigModel`` pydantic
base). Implemented as dataclasses with a strict ``from_dict`` that reports unknown
keys — same user-facing behavior (typo detection, defaults, nesting) without the
pydantic dependency.
"""
from __future__ import annotations

import dataclasses
from typing import Any, Dict, Type, TypeVar, get_args, get_origin, get_type_hints

from deepspeed_tpu.utils.logging import logger

T = TypeVar("T")


class DeepSpeedConfigError(Exception):
    pass


def config_from_dict(cls: Type[T], data: Dict[str, Any], path: str = "") -> T:
    """Build dataclass ``cls`` from a JSON dict, recursing into nested configs."""
    if data is None:
        data = {}
    if not isinstance(data, dict):
        raise DeepSpeedConfigError(f"config section {path or cls.__name__} must be a "
                                   f"dict, got {type(data).__name__}")
    fields = {f.name: f for f in dataclasses.fields(cls)}
    hints = get_type_hints(cls)
    kwargs: Dict[str, Any] = {}
    for key, value in data.items():
        if key not in fields:
            logger.warning(f"unknown config key {path + key!r} — ignored")
            continue
        ftype = hints.get(key, fields[key].type)
        origin = get_origin(ftype)
        if origin is None and dataclasses.is_dataclass(ftype) and isinstance(value, dict):
            kwargs[key] = config_from_dict(ftype, value, path=f"{path}{key}.")
        elif origin is not None and type(None) in get_args(ftype):
            inner = [a for a in get_args(ftype) if a is not type(None)]
            if len(inner) == 1 and dataclasses.is_dataclass(inner[0]) and isinstance(value, dict):
                kwargs[key] = config_from_dict(inner[0], value, path=f"{path}{key}.")
            else:
                kwargs[key] = value
        else:
            kwargs[key] = value
    try:
        obj = cls(**kwargs)
    except TypeError as e:
        raise DeepSpeedConfigError(f"invalid config section {path or cls.__name__}: {e}")
    if hasattr(obj, "validate"):
        obj.validate()
    return obj


def config_to_dict(obj) -> Dict[str, Any]:
    return dataclasses.asdict(obj)
