"""Hybrid engine — one model flipping between ZeRO training and generation.

Parity: reference ``runtime/hybrid_engine.py:30`` (``DeepSpeedHybridEngine``,
``generate`` :168): RLHF actors train under ZeRO-3 then roll out with
inference kernels, which the reference implements by gathering params and
swapping module containers in/out. Here the flip is free by construction: the
training state's fp32 master is a global sharded array tree, and the
generate program simply *reads* it — GSPMD gathers per-use exactly as the
training forward does. No container surgery, no LoRA fuse/unfuse, no
weight-copy latency ("release_inference_cache" etc. become jit cache keys).
"""
from __future__ import annotations

from typing import Any, Dict, List, Optional, Sequence

import jax

from deepspeed_tpu.inference.engine import InferenceEngine
from deepspeed_tpu.runtime.engine import DeepSpeedTPUEngine


class DeepSpeedHybridEngine:
    """Wraps a training engine with a generate path sharing its weights."""

    def __init__(self, engine: DeepSpeedTPUEngine,
                 max_seq_len: Optional[int] = None):
        cfg = engine.model_spec.config
        if cfg is None:
            raise ValueError(
                "hybrid engine needs model_spec.config (use causal_lm_spec)")
        self.engine = engine
        self._inference = InferenceEngine(
            cfg, params=engine.state["master"], max_seq_len=max_seq_len,
            mesh=engine.mesh)

    # training API passthrough ------------------------------------------- #
    def train_batch(self, data_iter):
        return self.engine.train_batch(data_iter)

    def forward(self, batch):
        return self.engine.forward(batch)

    def backward(self, loss=None):
        return self.engine.backward(loss)

    def step(self):
        return self.engine.step()

    # rollout ------------------------------------------------------------- #
    def generate(self, prompts: Sequence[Sequence[int]], **kwargs
                 ) -> List[List[int]]:
        """Generate with the CURRENT training weights (reference ``generate``
        :168). The param tree is re-pointed each call — after an optimizer
        step the new master arrays are picked up with zero copies."""
        self._inference.params = self.engine.state["master"]
        return self._inference.generate(prompts, **kwargs)

    def eval_batch(self, batch):
        return self.engine.eval_batch(batch)
