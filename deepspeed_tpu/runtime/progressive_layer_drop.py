"""Progressive layer drop — stochastic-depth schedule.

Parity: reference ``runtime/progressive_layer_drop.py`` (``ProgressiveLayerDrop``:
theta(t) = (1 - theta_0) * exp(-gamma * t) ... keep probability ramps DOWN over
training; engine hook at ``engine.py:430``). The per-layer keep probability at
depth l of L is ``1 - (l / L) * (1 - theta)`` (deeper layers drop more, PLD
paper). Model integration: ``keep_mask`` below is consumed by the transformer
scan — a dropped layer contributes identity (residual passthrough) for that
batch.
"""
from __future__ import annotations

import math
from typing import Optional

import jax
import jax.numpy as jnp


class ProgressiveLayerDrop:
    def __init__(self, theta: float = 0.5, gamma: float = 0.001):
        self.theta = theta
        self.gamma = gamma
        self.current_theta = 1.0

    def get_theta(self) -> float:
        return self.current_theta

    def get_state(self):
        return {"progressive_layer_drop": True, "pld_theta": self.get_theta()}

    def update_state(self, global_step: int) -> float:
        """theta(t) → theta as t → ∞ (keep prob decays from 1 to theta)."""
        self.current_theta = ((1.0 - self.theta)
                              * math.exp(-self.gamma * global_step)
                              + self.theta)
        return self.current_theta


def layer_keep_probs(theta: float, num_layers: int) -> jax.Array:
    """Per-layer keep probability: deeper layers drop more (PLD eq. 6)."""
    l = jnp.arange(1, num_layers + 1, dtype=jnp.float32)
    return 1.0 - (l / num_layers) * (1.0 - theta)


def sample_keep_mask(rng: jax.Array, theta: float, num_layers: int) -> jax.Array:
    """[L] float mask (1 keep / 0 drop) for one step's layer scan."""
    probs = layer_keep_probs(theta, num_layers)
    return (jax.random.uniform(rng, (num_layers,)) < probs).astype(jnp.float32)
