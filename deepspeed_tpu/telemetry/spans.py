"""Span-based tracing + the stall watchdog.

``span("decode_tick")`` does four things at once:

* records the span's wall time into the ``span_seconds{span=...}`` histogram
  of the active registry (host-visible latency, scrapeable);
* emits a ``jax.profiler.TraceAnnotation`` so the span brackets the ops it
  dispatched in an XLA device trace (the compute/collective-overlap view
  that T3-style analyses need — a captured ``jax.profiler.trace`` shows
  these names on the host timeline aligned with device streams);
* feeds the structured tracer (``telemetry/tracing.py``) when tracing is
  enabled, so every already-instrumented site lands in the flight
  recorder's timeline for free (disabled: one attribute check);
* notes itself as the registry's *last completed span*, which is what the
  stall watchdog reports when a training step misses its deadline.

NOTE on async dispatch: the host wall time of a span that only *dispatches*
work is not device time. Spans measure what the host observed — for fenced
device timings use ``utils/timer.py``'s fenced timers (which also feed the
``train_phase_seconds`` histogram) or a profiler trace.
"""
from __future__ import annotations

import contextlib
import threading
import time
from typing import Optional

from deepspeed_tpu.analysis.racelint.sanitizer import make_lock
from deepspeed_tpu.testing.chaos import sync_point
from deepspeed_tpu.telemetry import tracing as _tracing
from deepspeed_tpu.telemetry.registry import MetricsRegistry

SPAN_HISTOGRAM = "span_seconds"


def _trace_annotation(name: str):
    """jax.profiler.TraceAnnotation when jax is importable; inert otherwise
    (the registry itself is dependency-free and must stay usable without a
    device runtime, e.g. from the HTTP scrape thread)."""
    try:
        from jax.profiler import TraceAnnotation

        return TraceAnnotation(name)
    # a span must NEVER raise into the section it brackets, whatever the
    # profiler backend is doing — inert fallback, no logging on what can
    # be a per-tick path  # dslint: disable=silent-except
    except Exception:
        return contextlib.nullcontext()


@contextlib.contextmanager
def span(name: str, registry: MetricsRegistry, **labels):
    hist = registry.histogram(
        SPAN_HISTOGRAM, "wall time of telemetry.span sections")
    t0 = time.perf_counter()
    with _trace_annotation(name), _tracing.get_tracer().span(name, **labels):
        try:
            yield
        finally:
            hist.observe(time.perf_counter() - t0, span=name, **labels)
            registry.note_span_end(name)


class StallWatchdog:
    """Logs a warning when no heartbeat lands within ``deadline_s``.

    The engine beats (``beat()``) once per completed optimizer step/window;
    a daemon thread checks at deadline/4 cadence and warns ONCE per stall
    episode, naming the last completed span — the first question anyone asks
    a wedged run is "what was it doing last". Recovery re-arms the warning.
    A ``telemetry_stalls_total`` counter makes stall history scrapeable.

    ``on_stall``: optional escalation callback fired (once per stall
    episode, on the watchdog thread) after the warning — the training
    engine hooks its emergency-checkpoint path here when
    ``fault_tolerance.on_stall == "checkpoint"``, turning detection into
    response. A raising callback is counted
    (``telemetry_stall_action_errors_total``) and never kills the thread.

    Clocks + threading: deadlines are measured on ``time.monotonic()`` —
    the wall clock steps under NTP slew and VM suspend/resume, and a 30s
    correction must not fake (or mask) a stall. ``beat()`` runs on the
    training thread while ``check()`` runs on the watchdog thread, so the
    beat/armed/stalled triple is updated under a small lock; the
    ``on_stall`` callback and all logging run OUTSIDE it (an emergency
    checkpoint must not block the training thread's next ``beat()``).

    The deadline ARMS at the first beat: the watchdog monitors steady-state
    training, and the first step's XLA compile routinely exceeds any sane
    step deadline — firing during legitimate compilation would put a false
    stall in every large-model run's metrics. (The cost: a run that never
    completes step 1 is not flagged — that failure mode presents as an
    obvious hang, not a mid-run stall.)
    """

    def __init__(self, deadline_s: float, registry: MetricsRegistry,
                 name: str = "train", logger=None, on_stall=None):
        if deadline_s <= 0:
            raise ValueError("StallWatchdog needs a positive deadline")
        self.deadline_s = float(deadline_s)
        self.registry = registry
        self.name = name
        if logger is None:
            from deepspeed_tpu.utils.logging import logger as _l

            logger = _l
        self.logger = logger
        self.on_stall = on_stall
        self._lock = make_lock("watchdog._lock")
        self._last_beat = time.monotonic()  # guarded-by: self._lock
        self._armed = False                 # guarded-by: self._lock
        self._stalled = False               # guarded-by: self._lock
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._stall_counter = registry.counter(
            "telemetry_stalls_total",
            "watchdog deadline misses (no step completed in time)")

    def beat(self) -> None:
        with self._lock:
            self._last_beat = time.monotonic()
            self._armed = True
            recovered, self._stalled = self._stalled, False
        if recovered:
            self.logger.warning(
                f"[watchdog:{self.name}] recovered — a step completed after "
                "the stall warning")

    def start(self) -> "StallWatchdog":
        if self._thread is None:
            # restartable: a prior stop() left the event set, and a new
            # thread would otherwise exit its wait-loop immediately
            self._stop.clear()
            self._thread = threading.Thread(
                target=self._run, name=f"telemetry-watchdog-{self.name}",
                daemon=True)
            self._thread.start()
        return self

    def stop(self) -> None:
        """Idempotent (thread popped before the join, so stacked
        teardown paths can't double-join); join-with-timeout; no lock
        held across the join (stop never takes self._lock)."""
        self._stop.set()
        thread, self._thread = self._thread, None
        sync_point("watchdog/stop/pre_join")
        if thread is not None:
            thread.join(timeout=2.0)

    def check(self, now: Optional[float] = None) -> bool:
        """One deadline check (the thread's body; callable directly in
        tests — ``now`` is a ``time.monotonic()`` reading). Returns True
        when a stall was (newly) reported."""
        now = time.monotonic() if now is None else now
        with self._lock:
            if not self._armed or self._stalled \
                    or now - self._last_beat <= self.deadline_s:
                return False
            self._stalled = True
            last_beat = self._last_beat
        self._stall_counter.inc()
        last = self.registry.last_span
        where = (f"last completed span: {last[0]!r} "
                 f"{now - last[1]:.1f}s ago" if last
                 else "no span completed yet")
        self.logger.warning(
            f"[watchdog:{self.name}] no step finished in "
            f"{now - last_beat:.1f}s (deadline {self.deadline_s:.1f}s) "
            f"— {where}")
        if self.on_stall is not None:
            try:
                self.on_stall()
            except Exception as e:
                self.registry.counter(
                    "telemetry_stall_action_errors_total",
                    "on_stall escalation callbacks that raised"
                ).inc(error=type(e).__name__)
                self.logger.warning(
                    f"[watchdog:{self.name}] on_stall action failed: {e}")
        return True

    def _run(self) -> None:
        interval = max(self.deadline_s / 4.0, 0.05)
        while not self._stop.wait(interval):
            self.check()
