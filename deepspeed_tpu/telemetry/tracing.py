"""Structured tracing + the flight recorder.

Aggregates (the metrics registry) answer "how slow on average"; this
module answers "why was THIS step/request slow" and "what was the loop
doing in the seconds before it died". Three pieces:

* **Tracer** — in-process structured spans: trace/span ids with parent
  links, monotonic durations, key/value attributes and point-in-time
  events. Span timing is ``time.perf_counter()`` throughout; ONE
  wall-clock anchor captured at tracer (re)configuration converts
  monotonic readings into real timestamps at export time, so exported
  traces line up with log timestamps without any interval ever being
  computed from the wall clock.
* **Flight recorder** — completed spans land in a bounded ring buffer
  (oldest evicted, counted by ``trace_events_dropped_total``). On a
  trigger — stall-watchdog escalation, circuit-breaker open, SIGTERM
  emergency checkpoint, an unhandled engine-step exception — the buffer
  is dumped to a JSON file (``flight_recorder_dumps_total`` by reason):
  the last N seconds of timeline, attached to the failure that needed it.
* **Chrome trace-event export** — the buffer (plus still-open request
  spans, marked ``in_flight``) serializes losslessly to the Chrome
  trace-event JSON format, loadable in Perfetto / ``chrome://tracing``;
  ``python -m deepspeed_tpu.telemetry.tracing <dump.json>`` (also
  ``tools/trace-dump``) prints a terminal summary (slowest spans,
  per-phase totals).

Request-scoped traces: the serving front-end opens one trace per uid
(``request_begin``/``request_event``/``request_end``) so a single slow
request's full timeline — admission verdict, queue wait, the ticks that
served it, terminal state — is reconstructable after the fact.

Config-gated (``"telemetry"`` section: ``tracing``,
``trace_buffer_events``, ``trace_sample_rate``, ``flight_dump_dir``)
and DISABLED by default: a disabled tracer's ``span()`` is one attribute
check returning a shared null context (measured in the tier-1 overhead
guard), so every instrumented site stays free until someone needs it.

Dependency-free (stdlib + the logger): recordable from watchdog / HTTP /
signal-handler adjacent paths without touching a device runtime.
"""
from __future__ import annotations

import collections
import json
import os
import random
import sys
import threading
import time
import zlib
from typing import Any, Dict, List, Optional, Tuple

from deepspeed_tpu.analysis.racelint.sanitizer import make_lock
from deepspeed_tpu.utils.logging import logger

#: schema tag written into every export/dump (consumers can gate on it)
TRACE_FORMAT_VERSION = 1

#: shared no-op context for the disabled path — allocated once so a
#: disabled span() costs an attribute check and nothing else
class _NullSpan:
    __slots__ = ()

    def __enter__(self):
        return None

    def __exit__(self, *exc):
        return False


_NULL_SPAN = _NullSpan()


class _SpanRecord:
    """One span: ids, monotonic bounds, attrs, point events. ``t1`` is
    None while the span is open (request spans between begin and end)."""

    __slots__ = ("trace_id", "span_id", "parent_id", "name", "cat", "tid",
                 "t0", "t1", "attrs", "points")

    def __init__(self, trace_id: int, span_id: int, parent_id: int,
                 name: str, cat: str, tid: int, t0: float,
                 attrs: Dict[str, Any]):
        self.trace_id = trace_id
        self.span_id = span_id
        self.parent_id = parent_id
        self.name = name
        self.cat = cat
        self.tid = tid
        self.t0 = t0
        self.t1: Optional[float] = None
        self.attrs = attrs
        # (monotonic t, name, attrs) instants inside this span. Appended
        # by the span's owning thread only (serving loop / traced thread)
        self.points: List[Tuple[float, str, Dict[str, Any]]] = []


class _SpanCtx:
    """Context manager for one stack span. Kept as a class (not a
    generator contextmanager) so enter/exit stay cheap and the exit can
    pop itself BY IDENTITY — a mid-span enable/disable toggle must not
    desync the per-thread stack."""

    __slots__ = ("_tracer", "rec")

    def __init__(self, tracer: "Tracer", rec: Optional[_SpanRecord]):
        self._tracer = tracer
        self.rec = rec   # None = trace unsampled (children skip too)

    def __enter__(self):
        self._tracer._stack().append(self)
        return self.rec

    def __exit__(self, *exc):
        stack = self._tracer._stack()
        if stack and stack[-1] is self:
            stack.pop()
        elif self in stack:   # toggled mid-flight: remove wherever we are
            stack.remove(self)
        if self.rec is not None:
            self.rec.t1 = time.perf_counter()
            self._tracer._push(self.rec)
        return False


def _int_tid(uid: Any) -> int:
    """Stable integer tid for a request uid (Chrome trace tids are ints;
    uids in this repo are, but don't crash on a string one)."""
    if isinstance(uid, int):
        return uid
    return zlib.crc32(str(uid).encode())


class Tracer:
    """Structured tracer + flight recorder over one bounded ring buffer.

    Thread model: stack spans are per-thread (thread-local stack);
    request spans are keyed by uid and owned by the single-threaded
    serving loop; the ring buffer and open-request map are the shared
    state and sit under ``_lock`` (record path: one append under the
    lock). Exports copy under the lock and serialize outside it.
    """

    def __init__(self, enabled: bool = False, capacity: int = 4096,
                 sample_rate: float = 1.0,
                 dump_dir: str = "flight_dumps", keep_dumps: int = 20):
        self.enabled = enabled
        self.sample_rate = float(sample_rate)
        self.dump_dir = dump_dir
        # retention cap on dump FILES: a persistently-sick replica
        # re-opens its circuit once per backoff window forever, and each
        # dump serializes the full buffer — without a cap that fills the
        # disk of an unattended host (same bounding story as the ring
        # buffer itself). Oldest pruned first; 0 = keep everything.
        self.keep_dumps = keep_dumps
        self._lock = make_lock("tracer._lock")
        self._buf: collections.deque = collections.deque(
            maxlen=max(1, int(capacity)))       # guarded-by: self._lock
        self._open_reqs: Dict[Any, _SpanRecord] = {}  # guarded-by: self._lock
        self._next_id = 0                       # guarded-by: self._lock
        self._dump_seq = 0                      # guarded-by: self._lock
        self._tls = threading.local()
        self._rng = random.Random()
        self._set_anchor()

    def _set_anchor(self) -> None:
        """The ONE wall-clock read: pairs a monotonic reading with epoch
        time so exported timestamps are real without any interval ever
        being wall-clock-derived."""
        self._anchor_mono = time.perf_counter()
        # per-trace epoch anchor: exported Chrome `ts` values must be
        # real timestamps (they are compared against log lines, never
        # used as intervals)  # dslint: disable=wall-clock
        self._anchor_wall = time.time()

    # ------------------------------------------------------------------ #
    # internals
    # ------------------------------------------------------------------ #
    def _stack(self) -> list:
        stack = getattr(self._tls, "stack", None)
        if stack is None:
            stack = self._tls.stack = []
        return stack

    def _alloc_id(self) -> int:
        with self._lock:
            self._next_id += 1
            return self._next_id

    def _push(self, rec: _SpanRecord) -> None:
        with self._lock:
            dropped = len(self._buf) == self._buf.maxlen
            self._buf.append(rec)
        if dropped:
            # counter inc OUTSIDE the tracer lock (the registry has its
            # own lock; never hold both)
            self._tm_dropped().inc()

    def _tm_dropped(self):
        from deepspeed_tpu import telemetry

        return telemetry.counter(
            "trace_events_dropped_total",
            "trace events evicted from the flight-recorder ring buffer")

    def _tm_dumps(self):
        from deepspeed_tpu import telemetry

        return telemetry.counter(
            "flight_recorder_dumps_total",
            "flight-recorder dumps written, by trigger reason")

    def _ts_us(self, t_mono: float) -> float:
        """Monotonic reading → wall-clock microseconds via the anchor."""
        return (self._anchor_wall + (t_mono - self._anchor_mono)) * 1e6

    # ------------------------------------------------------------------ #
    # recording
    # ------------------------------------------------------------------ #
    def span(self, name: str, cat: str = "span", **attrs):
        """Context manager for one span. Child of the current thread's
        open span when one exists, else the root of a new trace (where
        the ``trace_sample_rate`` decision applies — an unsampled root
        silences its whole subtree)."""
        if not self.enabled:
            return _NULL_SPAN
        stack = self._stack()
        parent = stack[-1].rec if stack else None
        if stack and parent is None:
            return _SpanCtx(self, None)    # inside an unsampled trace
        if parent is None and self.sample_rate < 1.0 \
                and self._rng.random() >= self.sample_rate:
            return _SpanCtx(self, None)
        span_id = self._alloc_id()
        rec = _SpanRecord(
            trace_id=parent.trace_id if parent is not None else span_id,
            span_id=span_id,
            parent_id=parent.span_id if parent is not None else 0,
            name=name, cat=cat, tid=threading.get_ident(),
            t0=time.perf_counter(), attrs=dict(attrs))
        return _SpanCtx(self, rec)

    def event(self, name: str, cat: str = "event", **attrs) -> None:
        """Point-in-time event: attached to the current thread's open
        span when one exists, else recorded standalone (zero-duration)."""
        if not self.enabled:
            return
        now = time.perf_counter()
        stack = self._stack()
        if stack:
            rec = stack[-1].rec
            if rec is not None:
                rec.points.append((now, name, dict(attrs)))
            return   # unsampled trace drops its events too
        span_id = self._alloc_id()
        rec = _SpanRecord(span_id, span_id, 0, name, cat,
                          threading.get_ident(), now, dict(attrs))
        rec.t1 = now
        self._push(rec)

    def record_span(self, name: str, duration_s: float, cat: str = "span",
                    **attrs) -> None:
        """Record an already-measured section ending now (the compile-log
        path: the caller timed the work itself)."""
        if not self.enabled:
            return
        now = time.perf_counter()
        span_id = self._alloc_id()
        rec = _SpanRecord(span_id, span_id, 0, name, cat,
                          threading.get_ident(), now - max(0.0, duration_s),
                          dict(attrs))
        rec.t1 = now
        self._push(rec)

    # ------------------------------------------------------------------ #
    # request-scoped traces (serving front-end)
    # ------------------------------------------------------------------ #
    def request_begin(self, uid: Any, **attrs) -> None:
        """Open a request trace for ``uid``. No-op when one is already
        open (a duplicate submission must not destroy the live request's
        timeline — the rejection lands as an event on it instead)."""
        if not self.enabled:
            return
        if self.sample_rate < 1.0 \
                and self._rng.random() >= self.sample_rate:
            return
        span_id = self._alloc_id()
        rec = _SpanRecord(span_id, span_id, 0, f"request/{uid}", "request",
                          _int_tid(uid), time.perf_counter(), dict(attrs))
        evicted = None
        with self._lock:
            if uid in self._open_reqs:
                return
            if len(self._open_reqs) >= self._buf.maxlen:
                # leak guard: a caller that never resolves uids must not
                # grow this map without bound — close out the oldest
                evicted = self._open_reqs.pop(next(iter(self._open_reqs)))
                # mutate while still under the lock (a concurrent export
                # snapshot may hold a reference); push after release
                evicted.t1 = time.perf_counter()
                evicted.attrs.setdefault("state", "abandoned")
            self._open_reqs[uid] = rec
        if evicted is not None:
            self._push(evicted)

    def request_event(self, uid: Any, name: str, **attrs) -> None:
        if not self.enabled:
            return
        now = time.perf_counter()
        # mutate rec UNDER the lock: export_chrome snapshots open request
        # records and iterates rec.points concurrently — an unlocked
        # append races that read (the scrape-vs-mutate class)
        with self._lock:
            rec = self._open_reqs.get(uid)
            if rec is not None:
                rec.points.append((now, name, dict(attrs)))

    def request_end(self, uid: Any, state: str, **attrs) -> None:
        """Close ``uid``'s trace with its terminal state; the completed
        span moves into the ring buffer. Unknown uids no-op (unsampled,
        or tracing enabled mid-request)."""
        if not self.enabled:
            return
        now = time.perf_counter()
        # popping rec does NOT give this thread sole ownership: a
        # concurrent export_chrome may already hold a snapshot reference
        # and read rec.attrs (``dict(rec.attrs)`` raises if it changes
        # size mid-copy) — so the terminal-state mutation happens under
        # the lock too, and only the _push (which re-takes it) is outside
        with self._lock:
            rec = self._open_reqs.pop(uid, None)
            if rec is not None:
                rec.t1 = now
                rec.attrs["state"] = state
                for k, v in attrs.items():
                    if v not in (None, ""):
                        rec.attrs[k] = v
        if rec is not None:
            self._push(rec)

    # ------------------------------------------------------------------ #
    # export / flight dumps
    # ------------------------------------------------------------------ #
    def export_chrome(self) -> Dict[str, Any]:
        """The buffer (+ open request spans, marked ``in_flight``) as a
        Chrome trace-event JSON document: complete ``X`` events with
        real-timestamp ``ts`` (µs) and monotonic ``dur``, instant ``i``
        events for span points, ``pid``/``tid`` on every event, sorted
        by ``ts`` — loadable in Perfetto / ``chrome://tracing``."""
        now = time.perf_counter()
        pid = os.getpid()
        events: List[Dict[str, Any]] = []
        # render under the lock: a snapshot of the record LIST is not
        # enough — open request records' points/attrs keep mutating
        # (under this lock, see request_event/request_end), and
        # ``dict(rec.attrs)`` racing a writer is exactly the
        # scrape-vs-mutate bug this lock now covers end to end
        with self._lock:
            recs = list(self._buf) + list(self._open_reqs.values())
            for rec in recs:
                t1 = rec.t1 if rec.t1 is not None else now
                args = dict(rec.attrs)
                args["trace_id"] = rec.trace_id
                if rec.parent_id:
                    args["parent_span_id"] = rec.parent_id
                if rec.t1 is None:
                    args["in_flight"] = True
                events.append({
                    "name": rec.name, "cat": rec.cat, "ph": "X",
                    "ts": self._ts_us(rec.t0),
                    "dur": max(0.0, (t1 - rec.t0) * 1e6),
                    "pid": pid, "tid": rec.tid, "args": args,
                })
                for (t, name, attrs) in rec.points:
                    events.append({
                        "name": name, "cat": rec.cat, "ph": "i", "s": "t",
                        "ts": self._ts_us(t), "pid": pid, "tid": rec.tid,
                        "args": dict(attrs, trace_id=rec.trace_id),
                    })
        events.sort(key=lambda e: e["ts"])
        return {
            "traceEvents": events,
            "displayTimeUnit": "ms",
            "otherData": {
                "format_version": TRACE_FORMAT_VERSION,
                "producer": "deepspeed_tpu.telemetry.tracing",
                "pid": pid,
                "export_unix_time": self._anchor_wall
                + (now - self._anchor_mono),
            },
        }

    def flight_status(self) -> Dict[str, Any]:
        """Live flight-recorder status (the ``/flight`` endpoint body)."""
        with self._lock:
            return {
                "enabled": self.enabled,
                "buffered_events": len(self._buf),
                "capacity": self._buf.maxlen,
                "open_requests": len(self._open_reqs),
                "sample_rate": self.sample_rate,
                "dump_dir": self.dump_dir,
                "dumps_written": self._dump_seq,
            }

    def dump_flight(self, reason: str,
                    note: Optional[str] = None) -> Optional[str]:
        """Write the flight-recorder buffer to
        ``<dump_dir>/flight_<reason>_<pid>_<seq>.json`` and count it;
        dumps beyond ``keep_dumps`` are pruned oldest-first. Returns the
        path, or None when tracing is disabled or the dump failed — it
        runs INSIDE failure handlers (circuit-open, SIGTERM, step
        exceptions), so NOTHING here may take down the path that
        triggered it: every failure is logged and swallowed."""
        if not self.enabled:
            return None
        try:
            doc = self.export_chrome()
            doc["otherData"]["reason"] = reason
            if note:
                doc["otherData"]["note"] = note
            with self._lock:
                self._dump_seq += 1
                seq = self._dump_seq
            path = os.path.join(
                self.dump_dir, f"flight_{reason}_{os.getpid()}_{seq}.json")
            os.makedirs(self.dump_dir, exist_ok=True)
            tmp = path + ".tmp"
            with open(tmp, "w") as f:
                json.dump(doc, f, default=str)   # exotic attr values
                # degrade to their repr rather than killing the dump
            os.replace(tmp, path)   # never leave a torn dump named .json
            self._prune_dumps()
            self._tm_dumps().inc(reason=reason)
            logger.warning(
                f"flight recorder: {len(doc['traceEvents'])} events -> "
                f"{path} (reason={reason}"
                + (f", note={note}" if note else "") + ")")
            return path
        except Exception as e:
            logger.warning(f"flight recorder: dump ({reason}) failed: "
                           f"{type(e).__name__}: {e}")
            return None

    def _prune_dumps(self) -> None:
        """Keep the newest ``keep_dumps`` flight files in ``dump_dir``
        (0 = unbounded); a sick replica re-dumping once per backoff
        window must not fill the disk. Best-effort: a racing unlink is
        someone else pruning the same dir."""
        if self.keep_dumps <= 0:
            return
        try:
            files = [os.path.join(self.dump_dir, f)
                     for f in os.listdir(self.dump_dir)
                     if f.startswith("flight_") and f.endswith(".json")]
            files.sort(key=os.path.getmtime)
            for stale in files[:-self.keep_dumps]:
                os.unlink(stale)
        except OSError as e:
            logger.warning(f"flight recorder: dump retention GC failed: {e}")

    # ------------------------------------------------------------------ #
    # aggregation (bench rows, CLI summary)
    # ------------------------------------------------------------------ #
    def phase_stats(self) -> Dict[str, Dict[str, float]]:
        """Per-span-name latency distribution over the buffered spans:
        ``{name: {count, total_s, p50_s, p95_s, p99_s}}`` — exact
        quantiles (the buffer is bounded), what ``bench.py`` embeds next
        to ``telemetry.snapshot()`` in each entry row."""
        with self._lock:
            recs = [(r.name, r.t1 - r.t0) for r in self._buf
                    if r.t1 is not None]
        by_name: Dict[str, List[float]] = {}
        for name, dur in recs:
            by_name.setdefault(name, []).append(dur)
        out: Dict[str, Dict[str, float]] = {}
        for name, durs in sorted(by_name.items()):
            durs.sort()
            n = len(durs)

            def q(frac: float) -> float:
                return durs[min(int(frac * n), n - 1)]

            out[name] = {
                "count": n,
                "total_s": round(sum(durs), 9),
                "p50_s": round(q(0.50), 9),
                "p95_s": round(q(0.95), 9),
                "p99_s": round(q(0.99), 9),
            }
        return out

    # ------------------------------------------------------------------ #
    def clear(self) -> None:
        """Tests only: drop buffered + open spans and the dump counter."""
        with self._lock:
            self._buf.clear()
            self._open_reqs.clear()
            self._dump_seq = 0


# --------------------------------------------------------------------- #
# module-level default tracer (what config wiring + instrumented sites use)
# --------------------------------------------------------------------- #
_default_tracer = Tracer()


def safe_dump_flight(reason: str, note: Optional[str] = None
                     ) -> Optional[str]:
    """Module-level convenience for failure handlers: dump the process
    tracer's flight recorder, never raising. ``Tracer.dump_flight``
    already swallows its own failures; this additionally guards the
    tracer lookup itself, so callers (guardian anomaly containment,
    elastic-agent give-up) need no boilerplate try/except."""
    try:
        return get_tracer().dump_flight(reason, note=note)
    except Exception as e:   # the caller's failure must win
        logger.warning(f"flight dump ({reason}) failed: {e}")
        return None


def get_tracer() -> Tracer:
    return _default_tracer


def configure(enabled: Optional[bool] = None,
              capacity: Optional[int] = None,
              sample_rate: Optional[float] = None,
              dump_dir: Optional[str] = None,
              keep_dumps: Optional[int] = None) -> Tracer:
    """(Re)configure the default tracer in place — process-wide, last
    caller wins (the same convention as the registry enabled gate).
    ``None`` leaves a setting unchanged; a capacity change rebuilds the
    ring buffer keeping the newest events; enabling refreshes the
    wall-clock anchor (a process may run for days before someone turns
    tracing on)."""
    tr = _default_tracer
    if capacity is not None and int(capacity) != tr._buf.maxlen:
        with tr._lock:
            tr._buf = collections.deque(tr._buf,
                                        maxlen=max(1, int(capacity)))
    if sample_rate is not None:
        tr.sample_rate = float(sample_rate)
    if dump_dir is not None:
        tr.dump_dir = dump_dir
    if keep_dumps is not None:
        tr.keep_dumps = int(keep_dumps)
    if enabled is not None:
        if enabled and not tr.enabled:
            tr._set_anchor()
        tr.enabled = bool(enabled)
    return tr


def reset() -> None:
    """Tests only: disable and clear the default tracer (defaults
    restored; ``telemetry.reset()`` calls this)."""
    tr = _default_tracer
    tr.enabled = False
    tr.sample_rate = 1.0
    tr.dump_dir = "flight_dumps"
    tr.keep_dumps = 20
    configure(capacity=4096)
    tr.clear()


# --------------------------------------------------------------------- #
# CLI: `python -m deepspeed_tpu.telemetry.tracing <dump.json>`
# (also `tools/trace-dump`) — terminal summary of a trace/flight dump
# --------------------------------------------------------------------- #
def _load_dump(path: str) -> Dict[str, Any]:
    with open(path) as f:
        doc = json.load(f)
    if not isinstance(doc, dict) or "traceEvents" not in doc:
        raise ValueError(f"{path} is not a Chrome trace-event JSON dump "
                         "(no 'traceEvents' key)")
    return doc


def summarize(doc: Dict[str, Any], top: int = 10) -> str:
    """Human summary of one dump: header, per-phase totals, slowest
    spans. Pure function over the parsed JSON (tested directly)."""
    events = doc.get("traceEvents", [])
    spans = [e for e in events if e.get("ph") == "X"]
    other = doc.get("otherData", {})
    lines = []
    head = f"{len(events)} events ({len(spans)} spans)"
    if "reason" in other:
        head += f", dump reason: {other['reason']}"
        if "note" in other:
            head += f" (note: {other['note']})"
    lines.append(head)
    if spans:
        t_lo = min(e["ts"] for e in spans)
        t_hi = max(e["ts"] + e.get("dur", 0.0) for e in spans)
        lines.append(f"timeline: {(t_hi - t_lo) / 1e6:.3f}s "
                     f"across {len({e['tid'] for e in spans})} track(s)")
        by_name: Dict[str, List[float]] = {}
        for e in spans:
            by_name.setdefault(e["name"], []).append(e.get("dur", 0.0))
        lines.append("")
        lines.append(f"{'phase':<32} {'count':>6} {'total_ms':>10} "
                     f"{'p50_ms':>9} {'p95_ms':>9} {'p99_ms':>9}")
        for name, durs in sorted(by_name.items(),
                                 key=lambda kv: -sum(kv[1])):
            durs.sort()
            n = len(durs)

            def q(frac: float) -> float:
                return durs[min(int(frac * n), n - 1)]

            lines.append(
                f"{name[:32]:<32} {n:>6} {sum(durs) / 1e3:>10.3f} "
                f"{q(.5) / 1e3:>9.3f} {q(.95) / 1e3:>9.3f} "
                f"{q(.99) / 1e3:>9.3f}")
        lines.append("")
        lines.append(f"slowest {min(top, len(spans))} spans:")
        for e in sorted(spans, key=lambda e: -e.get("dur", 0.0))[:top]:
            state = e.get("args", {}).get("state", "")
            lines.append(
                f"  {e.get('dur', 0.0) / 1e3:>10.3f} ms  {e['name']}"
                + (f"  [{state}]" if state else ""))
    return "\n".join(lines)


def main(argv: Optional[List[str]] = None) -> int:
    argv = sys.argv[1:] if argv is None else argv
    if not argv or argv[0] in ("-h", "--help"):
        print("usage: python -m deepspeed_tpu.telemetry.tracing "
              "<dump.json> [--top N]\n"
              "Summarize a trace/flight-recorder dump: per-phase "
              "p50/p95/p99 and the slowest spans.\n"
              "Open the same file in https://ui.perfetto.dev for the "
              "full timeline.")
        return 0 if argv else 2
    top = 10
    if "--top" in argv:
        i = argv.index("--top")
        try:
            top = int(argv[i + 1])
        except (IndexError, ValueError):
            print("error: --top needs an integer value", file=sys.stderr)
            return 2
        argv = argv[:i] + argv[i + 2:]
    try:
        doc = _load_dump(argv[0])
    except (OSError, ValueError, json.JSONDecodeError) as e:
        print(f"error: {e}", file=sys.stderr)
        return 2
    print(summarize(doc, top=top))
    return 0


if __name__ == "__main__":
    sys.exit(main())
