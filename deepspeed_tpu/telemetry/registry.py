"""Dependency-free metrics registry: Counter / Gauge / Histogram with labels.

The unification point for the repo's four metric islands (``utils/timer``,
``monitor/monitor``, ``profiling/flops_profiler``, ``utils/comms_logging``):
everything records here, and the exposition layer (``telemetry/exposition``)
serves one Prometheus text endpoint + one JSON snapshot over it.

Design constraints:

* stdlib-only (no jax import on the record path — metrics must be writable
  from watchdog/HTTP threads without touching a device runtime);
* process-0 gated like ``monitor/monitor.py`` (SPMD: every host records the
  same values; one writer is the rank-0 analog). The gate is evaluated
  lazily on first record so importing telemetry never initializes jax;
* recording is O(dict lookup + float add) under an RLock — cheap enough for
  per-tick serving paths, but anything per-device-op still belongs in
  ``jax.profiler`` traces, not here.

Collectors: callables registered via :meth:`MetricsRegistry.add_collector`
run right before a snapshot/render — the hook for lazily-priced values
(device_get of the last step's metrics, allocator occupancy). A collector
that returns ``False`` is deregistered (the weakref-to-owner idiom); one
that raises is dropped into ``telemetry_collector_errors_total`` instead of
breaking the scrape.
"""
from __future__ import annotations

import collections
import threading

from deepspeed_tpu.analysis.racelint.sanitizer import make_lock
import time
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

LabelKey = Tuple[Tuple[str, str], ...]

# Prometheus-style latency buckets (seconds), wide enough for both a ~100us
# CPU tick and a multi-second fused train window through a remote tunnel.
DEFAULT_BUCKETS = (0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1,
                   0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0, 60.0, 120.0)

# Sliding-window defaults: every histogram keeps a ring of per-interval
# snapshots alongside its lifetime state, so windowed quantiles reflect
# the last ``window_s`` seconds instead of the whole process lifetime
# (one slow startup tick must not skew a p99 gauge — or a hedge
# threshold — forever). Granularity is ``window_s / window_intervals``.
DEFAULT_WINDOW_S = 60.0
DEFAULT_WINDOW_INTERVALS = 6

_process_zero: Optional[bool] = None


def _is_process_zero() -> bool:
    """Rank-0 gate, resolved lazily (jax.process_index initializes the
    backend — must not happen at import time)."""
    global _process_zero
    if _process_zero is None:
        try:
            import jax

            _process_zero = jax.process_index() == 0
        # any failure (no jax, no backend, mid-init) means single-process:
        # record. The registry is dependency-free by contract, so no logger
        # here — and this resolves ONCE.  # dslint: disable=silent-except
        except Exception:
            _process_zero = True
    return _process_zero


def _label_key(labels: Dict[str, Any]) -> LabelKey:
    return tuple(sorted((k, str(v)) for k, v in labels.items()))


def format_labels(key: LabelKey) -> str:
    if not key:
        return ""
    return "{" + ",".join(f'{k}="{v}"' for k, v in key) + "}"


class _Metric:
    kind = "untyped"

    def __init__(self, name: str, description: str, registry: "MetricsRegistry"):
        self.name = name
        self.description = description
        self._registry = registry
        self._lock = registry._lock
        self._children: Dict[LabelKey, Any] = {}

    def _enabled(self) -> bool:
        return self._registry.enabled and _is_process_zero()

    def labels_items(self):
        with self._lock:
            return list(self._children.items())


class Counter(_Metric):
    """Monotone counter; ``inc`` only accepts non-negative amounts."""

    kind = "counter"

    def inc(self, amount: float = 1.0, **labels) -> None:
        if not self._enabled():
            return
        if amount < 0:
            raise ValueError(f"counter {self.name} cannot decrease")
        key = _label_key(labels)
        with self._lock:
            self._children[key] = self._children.get(key, 0.0) + amount

    def value(self, **labels) -> float:
        with self._lock:
            return self._children.get(_label_key(labels), 0.0)

    def total(self) -> float:
        """Sum across every label combination."""
        with self._lock:
            return sum(self._children.values())


class Gauge(_Metric):
    kind = "gauge"

    def set(self, value: float, **labels) -> None:
        if not self._enabled():
            return
        with self._lock:
            self._children[_label_key(labels)] = float(value)

    def set_max(self, value: float, **labels) -> None:
        """Monotone high-water mark (peak queue depth, peak occupancy)."""
        if not self._enabled():
            return
        key = _label_key(labels)
        with self._lock:
            self._children[key] = max(self._children.get(key, float("-inf")),
                                      float(value))

    def inc(self, amount: float = 1.0, **labels) -> None:
        if not self._enabled():
            return
        key = _label_key(labels)
        with self._lock:
            self._children[key] = self._children.get(key, 0.0) + amount

    def value(self, **labels) -> Optional[float]:
        with self._lock:
            return self._children.get(_label_key(labels))


class _HistogramChild:
    __slots__ = ("bucket_counts", "count", "sum", "min", "max")

    def __init__(self, n_buckets: int):
        self.bucket_counts = [0] * (n_buckets + 1)  # +1 = the +Inf bucket
        self.count = 0
        self.sum = 0.0
        self.min = float("inf")
        self.max = float("-inf")

    def merge(self, other: "_HistogramChild") -> None:
        for i, n in enumerate(other.bucket_counts):
            self.bucket_counts[i] += n
        self.count += other.count
        self.sum += other.sum
        self.min = min(self.min, other.min)
        self.max = max(self.max, other.max)


class Histogram(_Metric):
    """Cumulative-bucket histogram (Prometheus semantics). ``observe`` takes
    an optional ``n`` weight so a fused window can credit its per-item mean
    once per item without a Python loop.

    Alongside the lifetime state every child keeps a bounded ring of
    per-interval snapshots (``window_s`` seconds in ``window_intervals``
    slices): ``windowed_summary`` / ``windowed_quantile`` answer over
    the last N seconds only, while ``summary`` keeps its process-lifetime
    semantics for bench back-compat. ``set_window_clock`` injects a
    deterministic clock (the serving fleet points it at its own, so the
    chaos tests' seeded clocks drive window expiry too)."""

    kind = "histogram"

    def __init__(self, name: str, description: str, registry: "MetricsRegistry",
                 buckets: Optional[Sequence[float]] = None,
                 window_s: float = DEFAULT_WINDOW_S,
                 window_intervals: int = DEFAULT_WINDOW_INTERVALS):
        super().__init__(name, description, registry)
        self.buckets = tuple(sorted(buckets if buckets is not None
                                    else DEFAULT_BUCKETS))
        self.window_s = float(window_s)
        self.window_intervals = max(1, int(window_intervals))
        self._interval_s = self.window_s / self.window_intervals
        self._clock = time.monotonic
        # per-label ring of (interval_index, interval child), newest last
        self._win: Dict[LabelKey, collections.deque] = {}

    def set_window_clock(self, clock: Callable[[], float]) -> None:
        """Point the sliding window at an injectable clock (tests, the
        fleet's deterministic clock). Lifetime state is clock-free."""
        with self._lock:
            self._clock = clock

    def labels_items(self):
        """Consistent SNAPSHOTS of each child, copied under the registry
        lock — readers (exposition, bridge) iterate bucket lists outside
        the lock, and a live child mutating mid-scrape would emit a
        malformed histogram (count > +Inf bucket)."""
        with self._lock:
            out = []
            for key, c in self._children.items():
                cc = _HistogramChild.__new__(_HistogramChild)
                cc.bucket_counts = list(c.bucket_counts)
                cc.count, cc.sum = c.count, c.sum
                cc.min, cc.max = c.min, c.max
                out.append((key, cc))
            return out

    def observe(self, value: float, n: int = 1, **labels) -> None:
        if not self._enabled() or n < 1:
            return
        value = float(value)
        key = _label_key(labels)
        with self._lock:
            child = self._children.get(key)
            if child is None:
                child = self._children[key] = _HistogramChild(len(self.buckets))
            idx = len(self.buckets)
            for i, edge in enumerate(self.buckets):
                if value <= edge:
                    idx = i
                    break
            child.bucket_counts[idx] += n
            child.count += n
            child.sum += value * n
            child.min = min(child.min, value)
            child.max = max(child.max, value)
            # the windowed twin: same observation lands in the current
            # interval's snapshot; expired intervals fall off the ring
            wchild = self._win_child(key)
            wchild.bucket_counts[idx] += n
            wchild.count += n
            wchild.sum += value * n
            wchild.min = min(wchild.min, value)
            wchild.max = max(wchild.max, value)

    def _win_child(self, key: LabelKey) -> _HistogramChild:
        """Current interval's child for ``key`` (caller holds the lock)."""
        now_idx = int(self._clock() // self._interval_s)
        ring = self._win.get(key)
        if ring is None:
            ring = self._win[key] = collections.deque()
        if not ring or ring[-1][0] != now_idx:
            ring.append((now_idx, _HistogramChild(len(self.buckets))))
        while ring and ring[0][0] <= now_idx - self.window_intervals:
            ring.popleft()
        return ring[-1][1]

    def windowed_child(self, window_s: Optional[float] = None,
                       **labels) -> Optional[_HistogramChild]:
        """Merged snapshot of the intervals inside the last ``window_s``
        seconds (default: the full configured window; longer requests are
        clamped to what the ring retains). None when no observation
        landed inside the window."""
        if window_s is None:
            window_s = self.window_s
        span = max(1, int(round(window_s / self._interval_s)))
        span = min(span, self.window_intervals)
        with self._lock:
            ring = self._win.get(_label_key(labels))
            if not ring:
                return None
            now_idx = int(self._clock() // self._interval_s)
            merged = _HistogramChild(len(self.buckets))
            for idx, child in ring:
                if now_idx - span < idx <= now_idx:
                    merged.merge(child)
        return merged if merged.count else None

    def windowed_quantile(self, q: float,
                          window_s: Optional[float] = None,
                          **labels) -> Optional[float]:
        """Bucket-interpolated quantile over the sliding window, or None
        when the window is empty — callers fall back to their floor (the
        hedge threshold) or the lifetime view."""
        child = self.windowed_child(window_s=window_s, **labels)
        if child is None:
            return None
        return self._quantile(self.buckets, child, q)

    def windowed_summary(self, window_s: Optional[float] = None,
                         **labels) -> Dict[str, float]:
        """Like :meth:`summary` but over the sliding window only, with a
        p99 column (the SLO engine's quantile source)."""
        child = self.windowed_child(window_s=window_s, **labels)
        if child is None:
            return {"count": 0, "sum": 0.0}
        return {
            "count": child.count,
            "sum": round(child.sum, 9),
            "mean": round(child.sum / child.count, 9),
            "min": round(child.min, 9),
            "max": round(child.max, 9),
            "p50": round(self._quantile(self.buckets, child, 0.5), 9),
            "p95": round(self._quantile(self.buckets, child, 0.95), 9),
            "p99": round(self._quantile(self.buckets, child, 0.99), 9),
        }

    def windowed_bad_fraction(self, threshold: float,
                              window_s: Optional[float] = None,
                              **labels) -> Optional[Tuple[float, int]]:
        """``(bad_fraction, total)`` over the window, where *bad* means an
        observation above ``threshold`` — counted at bucket granularity
        (the smallest bucket edge >= threshold bounds the good side), so
        the verdict is deterministic and scrape-consistent. None when the
        window is empty."""
        child = self.windowed_child(window_s=window_s, **labels)
        if child is None or child.count == 0:
            return None
        good = 0
        for i, edge in enumerate(self.buckets):
            if edge > threshold:
                break
            good += child.bucket_counts[i]
        return (child.count - good) / child.count, child.count

    def child(self, **labels) -> Optional[_HistogramChild]:
        with self._lock:
            return self._children.get(_label_key(labels))

    @staticmethod
    def _quantile(buckets: Sequence[float], child: _HistogramChild,
                  q: float) -> float:
        """Bucket-interpolated quantile estimate (what the snapshot reports;
        exact samples are not retained)."""
        if child.count == 0:
            return 0.0
        target = q * child.count
        seen = 0
        lo = 0.0
        for i, edge in enumerate(buckets):
            n = child.bucket_counts[i]
            if seen + n >= target and n > 0:
                frac = (target - seen) / n
                return min(lo + (edge - lo) * frac, child.max)
            seen += n
            lo = edge
        return child.max

    def quantile(self, q: float, **labels) -> Optional[float]:
        """Lifetime-view quantile estimate (``windowed_quantile`` is the
        recency-bounded sibling); None before any observation."""
        with self._lock:
            live = self._children.get(_label_key(labels))
            if live is None or live.count == 0:
                return None
            child = _HistogramChild.__new__(_HistogramChild)
            child.bucket_counts = list(live.bucket_counts)
            child.count, child.sum = live.count, live.sum
            child.min, child.max = live.min, live.max
        return self._quantile(self.buckets, child, q)

    def summary(self, **labels) -> Dict[str, float]:
        with self._lock:   # copy, not live — same torn-read hazard as
            live = self._children.get(_label_key(labels))   # labels_items
            if live is None or live.count == 0:
                return {"count": 0, "sum": 0.0}
            child = _HistogramChild.__new__(_HistogramChild)
            child.bucket_counts = list(live.bucket_counts)
            child.count, child.sum = live.count, live.sum
            child.min, child.max = live.min, live.max
        return {
            "count": child.count,
            "sum": round(child.sum, 9),
            "mean": round(child.sum / child.count, 9),
            "min": round(child.min, 9),
            "max": round(child.max, 9),
            "p50": round(self._quantile(self.buckets, child, 0.5), 9),
            "p95": round(self._quantile(self.buckets, child, 0.95), 9),
        }


class MetricsRegistry:
    def __init__(self, enabled: bool = True):
        self.enabled = enabled
        self._lock = make_lock("registry._lock", reentrant=True)
        self._metrics: Dict[str, _Metric] = {}          # guarded-by: self._lock
        self._collectors: List[Callable[[], Any]] = []  # guarded-by: self._lock
        # watchdog substrate: the last completed span as (name, monotonic
        # end time) — interval math only, never exported as a timestamp
        self.last_span: Optional[Tuple[str, float]] = None  # guarded-by: self._lock
        # per-thread collection mode (see collect()): thread-local so a
        # concurrent /metrics scrape can't flip a cheap bridge publish on
        # the training thread into an expensive one mid-iteration
        self._collect_tls = threading.local()

    @property
    def collecting_expensive(self) -> bool:
        """Whether the CURRENT THREAD's in-flight collect() may price
        expensive values (compiles, fences). True outside a collect()."""
        return getattr(self._collect_tls, "expensive", True)

    # -- metric construction (idempotent by name, kind-checked) ---------- #
    def _get_or_make(self, cls, name: str, description: str, **kw):
        with self._lock:
            existing = self._metrics.get(name)
            if existing is not None:
                if not isinstance(existing, cls):
                    raise ValueError(
                        f"metric {name!r} already registered as "
                        f"{existing.kind}, requested {cls.kind}")
                return existing
            metric = cls(name, description, self, **kw)
            self._metrics[name] = metric
            return metric

    def counter(self, name: str, description: str = "") -> Counter:
        return self._get_or_make(Counter, name, description)

    def gauge(self, name: str, description: str = "") -> Gauge:
        return self._get_or_make(Gauge, name, description)

    def histogram(self, name: str, description: str = "",
                  buckets: Optional[Sequence[float]] = None,
                  window_s: float = DEFAULT_WINDOW_S,
                  window_intervals: int = DEFAULT_WINDOW_INTERVALS,
                  ) -> Histogram:
        return self._get_or_make(Histogram, name, description,
                                 buckets=buckets, window_s=window_s,
                                 window_intervals=window_intervals)

    def get(self, name: str) -> Optional[_Metric]:
        with self._lock:
            return self._metrics.get(name)

    def metrics(self) -> List[_Metric]:
        with self._lock:
            return list(self._metrics.values())

    # -- collectors ------------------------------------------------------ #
    def add_collector(self, fn: Callable[[], Any]) -> None:
        """Register a pre-scrape callback. Return ``False`` from the callback
        to deregister it (weakref-owner idiom); exceptions are counted in
        ``telemetry_collector_errors_total`` and the scrape proceeds."""
        with self._lock:
            self._collectors.append(fn)

    def collect(self, expensive: bool = True) -> None:
        """Run collectors. ``expensive=False`` (the MonitorBridge's print-
        cadence publish, which runs ON the training thread) tells
        collectors to skip anything priced — one-off compiles, device
        fences; they read the mode via ``self.collecting_expensive``."""
        with self._lock:
            collectors = list(self._collectors)
        self._collect_tls.expensive = expensive
        dead = []
        try:
            for fn in collectors:
                try:
                    if fn() is False:
                        dead.append(fn)
                except Exception as e:  # broken collector must not kill scrapes
                    self.counter(
                        "telemetry_collector_errors_total",
                        "collector callbacks that raised during a scrape",
                    ).inc(error=type(e).__name__)
        finally:
            self._collect_tls.expensive = True
        if dead:
            with self._lock:
                self._collectors = [f for f in self._collectors
                                    if f not in dead]

    # -- span bookkeeping (see telemetry/spans.py) ----------------------- #
    def note_span_end(self, name: str) -> None:
        with self._lock:
            self.last_span = (name, time.monotonic())

    def reset(self) -> None:
        """Tests only: zero every metric and drop collectors/span state.

        Children are cleared IN PLACE and the metric objects stay
        registered — engines (training or FastGen) cache their handles at
        construction, and dropping the dict would strand a long-lived
        engine's recordings in orphaned objects invisible to snapshots."""
        with self._lock:
            for m in self._metrics.values():
                m._children.clear()
                win = getattr(m, "_win", None)
                if win is not None:
                    win.clear()
            self._collectors.clear()
            self.last_span = None
