"""Unified telemetry: metrics registry, trace spans, exposition.

One process-wide registry unifies the repo's metric islands — fenced timers
(``utils/timer``), monitor fan-out (``monitor/monitor``), FLOPS profiling
(``profiling/flops_profiler``), comms stats (``utils/comms_logging``) — and
the two hot subsystems are instrumented end-to-end (``runtime/engine``,
``inference/fastgen``). Read paths: a Prometheus-text ``/metrics`` HTTP
endpoint, a JSON ``snapshot()``, and a bridge into ``MonitorMaster`` so
CSV/TensorBoard/W&B get every scalar for free.

Module-level convenience API (all operate on the default registry)::

    from deepspeed_tpu import telemetry

    ticks = telemetry.counter("fastgen_ticks_total", "SplitFuse ticks")
    ticks.inc(kind="decode")
    with telemetry.span("decode_tick"):      # histogram + XLA trace annotation
        run_tick()
    telemetry.snapshot()                      # JSON-ready dict
    srv = telemetry.start_metrics_server(0)   # /metrics on an ephemeral port

Metric name catalog: README.md "Observability".
"""
from __future__ import annotations

from typing import Any, Dict, Optional, Sequence

from deepspeed_tpu.telemetry.bridge import MonitorBridge
from deepspeed_tpu.telemetry.exposition import (
    MetricsServer,
    clear_health_probes,
    clear_slo_provider,
    health_probe_names,
    health_report,
    register_health_probe,
    render_prometheus as _render,
    snapshot as _snapshot,
    start_metrics_server as _start_server,
    stop_metrics_server as _stop_server,
    unique_health_probe_name,
    unregister_health_probe,
)
from deepspeed_tpu.telemetry.registry import (
    DEFAULT_WINDOW_INTERVALS,
    DEFAULT_WINDOW_S,
    Counter,
    Gauge,
    Histogram,
    MetricsRegistry,
)
from deepspeed_tpu.telemetry.spans import StallWatchdog, span as _span
from deepspeed_tpu.telemetry import tracing
from deepspeed_tpu.telemetry.tracing import (
    Tracer,
    configure as configure_tracing,
    get_tracer,
)

__all__ = [
    "Counter", "Gauge", "Histogram", "MetricsRegistry", "MetricsServer",
    "MonitorBridge", "StallWatchdog", "Tracer", "counter", "gauge",
    "histogram", "get_registry", "get_tracer", "configure_tracing",
    "tracing", "span", "snapshot", "render_prometheus",
    "start_metrics_server", "stop_metrics_server", "add_collector", "reset",
    "register_health_probe", "unregister_health_probe", "health_report",
    "health_probe_names", "clear_health_probes", "unique_health_probe_name",
]

_default_registry = MetricsRegistry()


def get_registry() -> MetricsRegistry:
    return _default_registry


def counter(name: str, description: str = "") -> Counter:
    return _default_registry.counter(name, description)


def gauge(name: str, description: str = "") -> Gauge:
    return _default_registry.gauge(name, description)


def histogram(name: str, description: str = "",
              buckets: Optional[Sequence[float]] = None,
              window_s: float = DEFAULT_WINDOW_S,
              window_intervals: int = DEFAULT_WINDOW_INTERVALS) -> Histogram:
    return _default_registry.histogram(
        name, description, buckets=buckets,
        window_s=window_s, window_intervals=window_intervals)


def span(name: str, **labels):
    return _span(name, _default_registry, **labels)


def add_collector(fn) -> None:
    _default_registry.add_collector(fn)


def snapshot() -> Dict[str, Any]:
    return _snapshot(_default_registry)


def render_prometheus() -> str:
    return _render(_default_registry)


def start_metrics_server(port: int = 0) -> MetricsServer:
    return _start_server(_default_registry, port=port)


def stop_metrics_server() -> None:
    _stop_server()


def reset() -> None:
    """Tests only: stop the server, clear the default registry, drop any
    registered health probes and /slo provider, and disable/clear the
    default tracer."""
    _stop_server()
    clear_health_probes()
    clear_slo_provider()
    tracing.reset()
    _default_registry.reset()
