"""Exposition: Prometheus text format, JSON snapshot, and the /metrics server.

Two read paths over one registry:

* ``render_prometheus(registry)`` — Prometheus text exposition format 0.0.4
  (``# HELP`` / ``# TYPE`` + samples; histograms as cumulative ``_bucket``
  series with ``le`` labels plus ``_sum``/``_count``);
* ``snapshot(registry)`` — a JSON-ready dict with counters/gauges verbatim
  and histograms summarized (count/sum/mean/min/max/p50/p95) — what
  ``bench.py`` embeds next to each bench row and what tests assert against.

``MetricsServer`` is a stdlib ThreadingHTTPServer on a daemon thread serving
``/metrics`` (text) and ``/snapshot`` (JSON). Port 0 binds an ephemeral port
(exposed as ``.port``) — the tier-1 smoke test scrapes that. Start it on
process 0 only (callers gate; the registry record path already is).
"""
from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Dict, Optional

from deepspeed_tpu.telemetry.registry import (
    Counter,
    Gauge,
    Histogram,
    MetricsRegistry,
    format_labels,
)


def _format_value(v: float) -> str:
    if v == float("inf"):
        return "+Inf"
    if v != v:  # NaN
        return "NaN"
    return repr(float(v))


def render_prometheus(registry: MetricsRegistry) -> str:
    registry.collect()
    lines = []
    for metric in registry.metrics():
        lines.append(f"# HELP {metric.name} {metric.description}")
        lines.append(f"# TYPE {metric.name} {metric.kind}")
        if isinstance(metric, Histogram):
            for key, child in metric.labels_items():
                cum = 0
                for i, edge in enumerate(metric.buckets):
                    cum += child.bucket_counts[i]
                    lk = format_labels(key + (("le", _format_value(edge)),))
                    lines.append(f"{metric.name}_bucket{lk} {cum}")
                cum += child.bucket_counts[-1]
                lk = format_labels(key + (("le", "+Inf"),))
                lines.append(f"{metric.name}_bucket{lk} {cum}")
                lines.append(
                    f"{metric.name}_sum{format_labels(key)} "
                    f"{_format_value(child.sum)}")
                lines.append(
                    f"{metric.name}_count{format_labels(key)} {child.count}")
        elif isinstance(metric, (Counter, Gauge)):
            for key, value in metric.labels_items():
                lines.append(f"{metric.name}{format_labels(key)} "
                             f"{_format_value(value)}")
    return "\n".join(lines) + "\n"


def snapshot(registry: MetricsRegistry) -> Dict[str, Any]:
    registry.collect()
    out: Dict[str, Any] = {"counters": {}, "gauges": {}, "histograms": {}}
    for metric in registry.metrics():
        if isinstance(metric, Histogram):
            for key, _child in metric.labels_items():
                out["histograms"][metric.name + format_labels(key)] = \
                    metric.summary(**dict(key))
        elif isinstance(metric, Counter):
            for key, value in metric.labels_items():
                out["counters"][metric.name + format_labels(key)] = value
        elif isinstance(metric, Gauge):
            for key, value in metric.labels_items():
                out["gauges"][metric.name + format_labels(key)] = value
    return out


class _Handler(BaseHTTPRequestHandler):
    registry: MetricsRegistry = None  # set by MetricsServer

    def do_GET(self):  # noqa: N802 (http.server API)
        try:
            if self.path.split("?")[0] in ("/metrics", "/"):
                body = render_prometheus(self.registry).encode()
                ctype = "text/plain; version=0.0.4; charset=utf-8"
            elif self.path.split("?")[0] == "/snapshot":
                body = json.dumps(snapshot(self.registry)).encode()
                ctype = "application/json"
            else:
                self.send_error(404)
                return
        except Exception as e:  # pragma: no cover - defensive
            self.send_error(500, str(e)[:100])
            return
        self.send_response(200)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def log_message(self, *args):  # silence per-request stderr spam
        pass


class MetricsServer:
    """``/metrics`` + ``/snapshot`` on a daemon thread; ``port=0`` binds an
    ephemeral port (read ``.port`` after construction)."""

    def __init__(self, registry: MetricsRegistry, port: int = 0,
                 host: str = "127.0.0.1"):
        handler = type("BoundHandler", (_Handler,), {"registry": registry})
        self._httpd = ThreadingHTTPServer((host, port), handler)
        self._httpd.daemon_threads = True
        self.host = host
        self.port = self._httpd.server_address[1]
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, name="telemetry-metrics-server",
            daemon=True)
        self._thread.start()

    @property
    def url(self) -> str:
        return f"http://{self.host}:{self.port}/metrics"

    def stop(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()
        self._thread.join(timeout=2.0)


_server: Optional[MetricsServer] = None
_server_lock = threading.Lock()


def start_metrics_server(registry: MetricsRegistry,
                         port: int = 0) -> MetricsServer:
    """Idempotent module-level server (one per process); returns the live
    server. A second call with a different port keeps the first server —
    stop it explicitly to rebind."""
    global _server
    with _server_lock:
        if _server is None:
            _server = MetricsServer(registry, port=port)
        return _server


def stop_metrics_server() -> None:
    global _server
    with _server_lock:
        if _server is not None:
            _server.stop()
            _server = None
