"""Exposition: Prometheus text format, JSON snapshot, and the /metrics server.

Two read paths over one registry:

* ``render_prometheus(registry)`` — Prometheus text exposition format 0.0.4
  (``# HELP`` / ``# TYPE`` + samples; histograms as cumulative ``_bucket``
  series with ``le`` labels plus ``_sum``/``_count``);
* ``snapshot(registry)`` — a JSON-ready dict with counters/gauges verbatim
  and histograms summarized (count/sum/mean/min/max/p50/p95) — what
  ``bench.py`` embeds next to each bench row and what tests assert against.

``MetricsServer`` is a stdlib ThreadingHTTPServer on a daemon thread serving
``/metrics`` (text) and ``/snapshot`` (JSON). Port 0 binds an ephemeral port
(exposed as ``.port``) — the tier-1 smoke test scrapes that. Start it on
process 0 only (callers gate; the registry record path already is).

**Trace surfaces** ride the same server: ``/trace`` serves the flight
recorder's current ring buffer as Chrome trace-event JSON (curl it into
a file, open in Perfetto — a live timeline of the last N spans without
waiting for a crash dump) and ``/flight`` reports flight-recorder
status (enabled, buffer fill, open request traces, dumps written).
Both answer from ``telemetry/tracing.py``'s default tracer; with
tracing disabled ``/trace`` is an empty (but valid) trace document.

**Health surfaces** ride the same server: ``/healthz`` (liveness) and
``/readyz`` (readiness) run the probes registered via
:func:`register_health_probe` and answer 200 (all probes ok) or 503 with
a JSON body of per-probe details — the contract external load balancers
use to drain a sick replica. With no probes registered both endpoints
answer 200 (a bare metrics process is alive, and nothing claims it
unready); the serving front-end (``deepspeed_tpu/serving``) registers
tick-heartbeat liveness and circuit/queue readiness probes. Probe
callbacks run on the HTTP thread — they must be cheap, lock-light, and
never touch a device runtime.
"""
from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Callable, Dict, Optional, Tuple

from deepspeed_tpu.analysis.racelint.sanitizer import make_lock
from deepspeed_tpu.testing.chaos import sync_point
from deepspeed_tpu.telemetry.registry import (
    Counter,
    Gauge,
    Histogram,
    MetricsRegistry,
    format_labels,
)


def _format_value(v: float) -> str:
    if v == float("inf"):
        return "+Inf"
    if v != v:  # NaN
        return "NaN"
    return repr(float(v))


# --------------------------------------------------------------------- #
# per-tenant filtered views (?tenant= on /metrics and /snapshot)
# --------------------------------------------------------------------- #
#: addressable tenant-label cardinality for ?tenant= filtering — kept in
#: lockstep with ``tenancy.max_tenant_labels`` by whoever adopts a
#: tenancy config (overflow tenants fold into "other" there, so serving
#: filtered views past the cap would only ever show empty series)
_tenant_filter_cap = 32
_tenant_filter_lock = make_lock("exposition._tenant_filter_lock")


def set_tenant_filter_cap(n: int) -> None:
    global _tenant_filter_cap
    with _tenant_filter_lock:
        _tenant_filter_cap = max(1, int(n))


def tenant_filter_cap() -> int:
    with _tenant_filter_lock:
        return _tenant_filter_cap


def _addressable_tenants(registry: MetricsRegistry) -> list:
    """Distinct ``tenant`` label values across the registry, sorted,
    truncated at the filter cap — the only values ``?tenant=`` serves."""
    values = set()
    for metric in registry.metrics():
        for key, _ in metric.labels_items():
            for k, v in key:
                if k == "tenant":
                    values.add(v)
    return sorted(values)[:tenant_filter_cap()]


def _keep(key, tenant: Optional[str]) -> bool:
    """With no filter keep everything; with one, keep label-less and
    non-tenant series (fleet-wide context) plus the matching tenant's."""
    if tenant is None:
        return True
    for k, v in key:
        if k == "tenant":
            return v == tenant
    return True


def render_prometheus(registry: MetricsRegistry,
                      tenant: Optional[str] = None) -> str:
    registry.collect()
    lines = []
    for metric in registry.metrics():
        lines.append(f"# HELP {metric.name} {metric.description}")
        lines.append(f"# TYPE {metric.name} {metric.kind}")
        if isinstance(metric, Histogram):
            for key, child in metric.labels_items():
                if not _keep(key, tenant):
                    continue
                cum = 0
                for i, edge in enumerate(metric.buckets):
                    cum += child.bucket_counts[i]
                    lk = format_labels(key + (("le", _format_value(edge)),))
                    lines.append(f"{metric.name}_bucket{lk} {cum}")
                cum += child.bucket_counts[-1]
                lk = format_labels(key + (("le", "+Inf"),))
                lines.append(f"{metric.name}_bucket{lk} {cum}")
                lines.append(
                    f"{metric.name}_sum{format_labels(key)} "
                    f"{_format_value(child.sum)}")
                lines.append(
                    f"{metric.name}_count{format_labels(key)} {child.count}")
        elif isinstance(metric, (Counter, Gauge)):
            for key, value in metric.labels_items():
                if not _keep(key, tenant):
                    continue
                lines.append(f"{metric.name}{format_labels(key)} "
                             f"{_format_value(value)}")
    return "\n".join(lines) + "\n"


def snapshot(registry: MetricsRegistry,
             tenant: Optional[str] = None) -> Dict[str, Any]:
    registry.collect()
    out: Dict[str, Any] = {"counters": {}, "gauges": {}, "histograms": {}}
    for metric in registry.metrics():
        if isinstance(metric, Histogram):
            for key, _child in metric.labels_items():
                if not _keep(key, tenant):
                    continue
                out["histograms"][metric.name + format_labels(key)] = \
                    metric.summary(**dict(key))
        elif isinstance(metric, Counter):
            for key, value in metric.labels_items():
                if not _keep(key, tenant):
                    continue
                out["counters"][metric.name + format_labels(key)] = value
        elif isinstance(metric, Gauge):
            for key, value in metric.labels_items():
                if not _keep(key, tenant):
                    continue
                out["gauges"][metric.name + format_labels(key)] = value
    if tenant is not None:
        out["tenant_filter"] = tenant
    return out


# --------------------------------------------------------------------- #
# health probes (/healthz, /readyz)
# --------------------------------------------------------------------- #
#: probe: () -> (ok, detail_dict). Registered per kind under a unique
#: name so several subsystems can contribute to one endpoint.
HealthProbe = Callable[[], Tuple[bool, Dict[str, Any]]]

_health_probes: Dict[str, Dict[str, HealthProbe]] = {"live": {}, "ready": {}}
_health_lock = make_lock("exposition._health_lock")


def register_health_probe(kind: str, name: str, fn: HealthProbe) -> None:
    """Register ``fn`` under ``/healthz`` (kind ``"live"``) or ``/readyz``
    (kind ``"ready"``). Re-registering a name replaces the probe (the
    restart-the-frontend idiom)."""
    if kind not in _health_probes:
        raise ValueError(f"health probe kind must be live|ready, got {kind!r}")
    with _health_lock:
        _health_probes[kind][name] = fn


def unregister_health_probe(kind: str, name: str) -> None:
    with _health_lock:
        _health_probes.get(kind, {}).pop(name, None)


def health_probe_names(kind: str) -> list:
    """Registered probe names for one endpoint (callers picking a fresh
    name — e.g. a second serving frontend in one process — check here
    instead of silently replacing someone else's probe)."""
    with _health_lock:
        return list(_health_probes.get(kind, {}))


def unique_health_probe_name(base: str) -> str:
    """First of ``base``, ``base-2``, ``base-3``… not registered on
    EITHER endpoint — the one collision-suffix idiom shared by every
    subsystem that registers probes (a second serving frontend, a fleet
    router): registering must never silently replace someone else's
    probe, and closing one registrant must not unregister a survivor's."""
    with _health_lock:
        taken = set(_health_probes["live"]) | set(_health_probes["ready"])
    name, i = base, 1
    while name in taken:
        i += 1
        name = f"{base}-{i}"
    return name


def clear_health_probes() -> None:
    """Tests only: drop every registered probe (telemetry.reset calls
    this so one test's frontend can't leak unreadiness into the next)."""
    with _health_lock:
        for probes in _health_probes.values():
            probes.clear()


def health_report(kind: str) -> Tuple[bool, Dict[str, Any]]:
    """Aggregate verdict for one endpoint: ok iff EVERY probe is ok.
    A probe that raises reports as failed (a broken check must read as
    sick, not healthy) rather than breaking the endpoint."""
    with _health_lock:
        probes = dict(_health_probes.get(kind, {}))
    ok = True
    checks: Dict[str, Any] = {}
    for name, fn in sorted(probes.items()):
        try:
            p_ok, detail = fn()
        except Exception as e:  # pragma: no cover - defensive
            p_ok, detail = False, {"error": f"{type(e).__name__}: {e}"}
        ok = ok and bool(p_ok)
        checks[name] = {"ok": bool(p_ok), **detail}
    return ok, {"status": "ok" if ok else "unavailable", "checks": checks}


# --------------------------------------------------------------------- #
# /slo provider
# --------------------------------------------------------------------- #
#: one provider per process (matching the one-exposition-server model):
#: a zero-arg callable returning the JSON-ready /slo body — the fleet's
#: ``SloEngine.state``. Last registrant wins.
_slo_provider: Optional[Callable[[], Dict[str, Any]]] = None
_slo_lock = make_lock("exposition._slo_lock")


def register_slo_provider(fn: Callable[[], Dict[str, Any]]) -> None:
    global _slo_provider
    with _slo_lock:
        _slo_provider = fn


def unregister_slo_provider(fn: Callable[[], Dict[str, Any]]) -> None:
    """Unregister ``fn`` if it is still the current provider (a closing
    fleet must not tear down a successor's registration)."""
    global _slo_provider
    with _slo_lock:
        if _slo_provider is fn:
            _slo_provider = None


def clear_slo_provider() -> None:
    """Tests only (telemetry.reset): drop the provider unconditionally."""
    global _slo_provider
    with _slo_lock:
        _slo_provider = None


def slo_report() -> Dict[str, Any]:
    """The /slo body: the provider's state, or an explicit 'no engine'
    document (the endpoint always answers — absence is a finding, not a
    404, so dashboards don't conflate 'no SLOs' with 'server gone')."""
    with _slo_lock:
        provider = _slo_provider
    if provider is None:
        return {"enabled": False, "objectives": [], "alerts": [],
                "any_firing": False,
                "detail": "no SLO engine registered in this process"}
    return provider()


class _Handler(BaseHTTPRequestHandler):
    registry: MetricsRegistry = None  # set by MetricsServer

    def _query_tenant(self) -> Optional[str]:
        """The validated ?tenant= filter value, or None. Only values
        inside the addressable set (distinct tenant labels, capped at
        ``set_tenant_filter_cap``) select series; anything else filters
        everything tenant-labeled out — the same fold-don't-explode
        stance the tenancy cardinality guard takes on the write path."""
        from urllib.parse import parse_qs, urlsplit

        query = parse_qs(urlsplit(self.path).query)
        wanted = query.get("tenant", [None])[0]
        if wanted is None:
            return None
        if wanted in _addressable_tenants(self.registry):
            return wanted
        return "\x00unaddressable"   # matches no real label value

    def do_GET(self):  # noqa: N802 (http.server API)
        status = 200
        try:
            path = self.path.split("?")[0]
            if path in ("/metrics", "/"):
                body = render_prometheus(
                    self.registry, tenant=self._query_tenant()).encode()
                ctype = "text/plain; version=0.0.4; charset=utf-8"
            elif path == "/snapshot":
                body = json.dumps(snapshot(
                    self.registry, tenant=self._query_tenant())).encode()
                ctype = "application/json"
            elif path == "/slo":
                body = json.dumps(slo_report()).encode()
                ctype = "application/json"
            elif path in ("/trace", "/flight"):
                from deepspeed_tpu.telemetry import tracing

                tracer = tracing.get_tracer()
                body = json.dumps(tracer.export_chrome()
                                  if path == "/trace"
                                  else tracer.flight_status()).encode()
                ctype = "application/json"
            elif path in ("/healthz", "/readyz"):
                ok, report = health_report(
                    "live" if path == "/healthz" else "ready")
                status = 200 if ok else 503
                body = json.dumps(report).encode()
                ctype = "application/json"
            else:
                self.send_error(404)
                return
        except Exception as e:  # pragma: no cover - defensive
            self.send_error(500, str(e)[:100])
            return
        self.send_response(status)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def log_message(self, *args):  # silence per-request stderr spam
        pass


class MetricsServer:
    """``/metrics`` + ``/snapshot`` on a daemon thread; ``port=0`` binds an
    ephemeral port (read ``.port`` after construction)."""

    def __init__(self, registry: MetricsRegistry, port: int = 0,
                 host: str = "127.0.0.1"):
        handler = type("BoundHandler", (_Handler,), {"registry": registry})
        self._httpd = ThreadingHTTPServer((host, port), handler)
        self._httpd.daemon_threads = True
        self.host = host
        self.port = self._httpd.server_address[1]
        self._stopped = False   # racelint: single-thread — only stop() flips it, and stop() is serialized by stop_metrics_server
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, name="telemetry-metrics-server",
            daemon=True)
        self._thread.start()

    @property
    def url(self) -> str:
        return f"http://{self.host}:{self.port}/metrics"

    def stop(self) -> None:
        """Idempotent: a second stop() (engine teardown racing an atexit
        or signal-path shutdown) is a no-op instead of a double
        server_close on a dead socket."""
        if self._stopped:
            return
        self._stopped = True
        self._httpd.shutdown()
        self._httpd.server_close()
        self._thread.join(timeout=2.0)


_server: Optional[MetricsServer] = None
_server_lock = make_lock("exposition._server_lock")


def start_metrics_server(registry: MetricsRegistry,
                         port: int = 0) -> MetricsServer:
    """Idempotent module-level server (one per process); returns the live
    server. A second call with a different port keeps the first server —
    stop it explicitly to rebind."""
    global _server
    with _server_lock:
        if _server is None:
            _server = MetricsServer(registry, port=port)
        return _server


def stop_metrics_server() -> None:
    """Pop the server under the lock, stop it OUTSIDE: stop() joins the
    HTTP thread, and holding ``_server_lock`` across that join would
    stall every concurrent start/stop caller for the full drain (a
    scrape handler blocked on a slow collector holds the join up to its
    2s timeout)."""
    global _server
    with _server_lock:
        server, _server = _server, None
    sync_point("exposition/stop/pre_join")
    if server is not None:
        server.stop()
