"""Registry → MonitorMaster bridge: scalars fan out to CSV/TB/W&B for free.

The monitor backends speak ``(tag, value, step)`` events; the bridge walks
the registry's counters and gauges (histograms forward their count/sum —
the backends have no native histogram type) and writes one event batch.
The engine calls :meth:`publish` at its existing print boundary, so the
monitor cadence matches the reference's ``steps_per_print`` flow and no new
host syncs land on the hot path.
"""
from __future__ import annotations

from typing import List, Tuple

from deepspeed_tpu.telemetry.registry import (
    Counter,
    Gauge,
    Histogram,
    MetricsRegistry,
    format_labels,
)


class MonitorBridge:
    def __init__(self, monitor, registry: MetricsRegistry,
                 prefix: str = "Telemetry/"):
        self.monitor = monitor
        self.registry = registry
        self.prefix = prefix

    def _tag(self, name: str, key) -> str:
        # CSV backends turn '/' into '_'; labels flatten into the tag
        suffix = format_labels(key).replace('"', "").replace("{", ".") \
            .replace("}", "").replace("=", "_").replace(",", ".")
        return f"{self.prefix}{name}{suffix}"

    def events(self, step: int) -> List[Tuple[str, float, int]]:
        # cheap collection: publish runs ON the training thread at the
        # print cadence — it must never trigger priced collector work
        # (e.g. the measured-MFU cost-analysis compile)
        self.registry.collect(expensive=False)
        events: List[Tuple[str, float, int]] = []
        for metric in self.registry.metrics():
            if isinstance(metric, Histogram):
                for key, child in metric.labels_items():
                    base = self._tag(metric.name, key)
                    events.append((base + ".count", float(child.count), step))
                    events.append((base + ".sum", float(child.sum), step))
            elif isinstance(metric, (Counter, Gauge)):
                for key, value in metric.labels_items():
                    events.append((self._tag(metric.name, key),
                                   float(value), step))
        return events

    def publish(self, step: int) -> None:
        if self.monitor is None or not getattr(self.monitor, "enabled", False):
            return
        self.monitor.write_events(self.events(step))
