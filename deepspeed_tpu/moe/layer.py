"""MoE expert layer — dense dispatch/combine einsums over the 'expert' mesh axis.

Parity: reference ``deepspeed/moe/layer.py`` (``MoE`` :17) and
``sharded_moe.py`` (``MOELayer`` :536, ``_AllToAll`` :97). The reference
dispatches with an explicit all-to-all over the expert-parallel process group;
here expert weights carry the 'expert' logical axis (sharded over the 'expert'
mesh axis by ``parallel/partitioning.py``) and the dispatch einsum's sharding
makes GSPMD emit the same all-to-all on ICI — no hand-written collective.

Capacity-factor dense dispatch (GShard): tokens → [E, C, H] buffers, expert
FFNs run as one batched einsum over the (sharded) E dim — MXU-friendly, static
shapes.
"""
from __future__ import annotations

from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import NamedSharding, PartitionSpec as P

from deepspeed_tpu.comm.mesh import EXPERT_AXIS, get_mesh_manager
from deepspeed_tpu.moe.gating import GateOutput, topk_gating

PyTree = Any


def _expert_constraint(x: jax.Array, n_lead: int = 1) -> jax.Array:
    """Constrain the leading expert dim onto the 'expert' mesh axis (if present)."""
    try:
        mesh = get_mesh_manager().mesh
    except Exception:
        return x
    if mesh.shape.get(EXPERT_AXIS, 1) <= 1:
        return x
    spec = [None] * x.ndim
    spec[0] = EXPERT_AXIS
    return lax.with_sharding_constraint(x, NamedSharding(mesh, P(*spec)))


def _dense_ffn(xt: jax.Array, w_up: jax.Array, w_down: jax.Array,
               w_gate: Optional[jax.Array], activation: str) -> jax.Array:
    """Plain FFN on flat tokens [T,H] (the shared-expert path)."""
    dt = xt.dtype
    up = xt @ w_up.astype(dt)
    if w_gate is not None:
        up = jax.nn.silu(xt @ w_gate.astype(dt)) * up
    elif activation == "gelu":
        up = jax.nn.gelu(up, approximate=True)
    else:
        up = jax.nn.relu(up)
    return up @ w_down.astype(dt)


def moe_ffn(x: jax.Array, gate_w: jax.Array, experts: Dict[str, jax.Array],
            activation: str = "gelu", k: int = 2,
            capacity_factor: float = 1.25, min_capacity: int = 4,
            rng: Optional[jax.Array] = None, noise_std: float = 0.0,
            score_func: str = "softmax", route_norm: bool = True,
            route_scale: float = 1.0,
            shared: Optional[Dict[str, jax.Array]] = None,
            gate_bias: Optional[jax.Array] = None,
            n_group: int = 1, topk_group: int = 1
            ) -> Tuple[jax.Array, jax.Array]:
    """Mixture-of-experts FFN.

    x: [B, S, H]; gate_w: [H, E]; experts: w_up [E, H, F], w_down [E, F, H],
    optional w_gate [E, H, F] (swiglu). Returns (y [B,S,H], aux_loss scalar).

    Routing variants (AutoEP presets): ``score_func`` softmax|sigmoid,
    ``route_norm`` renormalizes top-k weights, ``route_scale`` scales the
    routed output (DeepSeek routed_scaling_factor). ``shared`` adds an
    always-on shared expert (sw_up [H,Fs], sw_down [Fs,H], optional sw_gate
    [H,Fs], optional shared_gate_w [H,1] sigmoid gate — Qwen2-MoE).
    """
    B, S, H = x.shape
    dt = x.dtype
    T = B * S
    xt = x.reshape(T, H)

    logits = xt.astype(jnp.float32) @ gate_w.astype(jnp.float32)   # [T, E]
    gate: GateOutput = topk_gating(
        logits, k=k, capacity_factor=capacity_factor,
        min_capacity=min_capacity, rng=rng, noise_std=noise_std,
        normalize=route_norm, score_func=score_func,
        select_bias=gate_bias, n_group=n_group, topk_group=topk_group)

    # dispatch: [T,E,C] × [T,H] → [E,C,H]; GSPMD turns the resharding of the
    # token dim (data/expert-sharded) onto the expert dim into an all-to-all
    xe = jnp.einsum("tec,th->ech", gate.dispatch.astype(dt), xt)
    xe = _expert_constraint(xe)

    up = jnp.einsum("ech,ehf->ecf", xe, experts["w_up"].astype(dt))
    if "w_gate" in experts:
        g = jnp.einsum("ech,ehf->ecf", xe, experts["w_gate"].astype(dt))
        act = jax.nn.silu(g) * up
    elif activation == "gelu":
        act = jax.nn.gelu(up, approximate=True)
    else:
        act = jax.nn.relu(up)
    ye = jnp.einsum("ecf,efh->ech", act, experts["w_down"].astype(dt))
    ye = _expert_constraint(ye)

    y = jnp.einsum("tec,ech->th", gate.combine.astype(dt), ye)
    if route_scale != 1.0:
        y = y * jnp.asarray(route_scale, dt)
    if shared:
        ys = _dense_ffn(xt, shared["sw_up"], shared["sw_down"],
                        shared.get("sw_gate"), activation)
        if "shared_gate_w" in shared:
            sg = jax.nn.sigmoid(
                xt.astype(jnp.float32) @ shared["shared_gate_w"].astype(jnp.float32))
            ys = ys * sg.astype(dt)
        y = y + ys
    return y.reshape(B, S, H), gate.aux_loss
