"""MoE expert layer — dropless ragged dispatch + dense GShard fallback.

Parity: reference ``deepspeed/moe/layer.py`` (``MoE`` :17) and
``sharded_moe.py`` (``MOELayer`` :536, ``_AllToAll`` :97). The reference
dispatches with an explicit all-to-all over the expert-parallel process group;
here expert weights carry the 'expert' logical axis (sharded over the 'expert'
mesh axis by ``parallel/partitioning.py``) and the dispatch einsum's sharding
makes GSPMD emit the same all-to-all on ICI — no hand-written collective.

Two dispatch modes (``dispatch=`` / ``TransformerConfig.moe_dispatch``):

* ``ragged`` (default when available) — DROPLESS: sort token-choices by
  expert, one grouped matmul per weight via ``lax.ragged_dot`` (MXU-tiled by
  Mosaic), combine by inverse-permutation gather. No capacity, no dropped
  tokens, no [T,E,C] one-hot tensors — the MegaBlocks idea, TPU-style.
  Under token-sharded meshes the sort runs per-shard inside ``shard_map``
  (a global argsort would gather the batch); under expert parallelism a
  fixed-capacity all-to-all moves packed token buffers between expert
  shards (capacity is per expert-SHARD — E/ep coarser than per-expert, so
  drops are far rarer than the dense path at equal capacity_factor; i.e.
  ragged is only fully dropless OFF expert-parallel meshes — under EP a
  skewed router can still overflow the buffer, observable via
  :func:`set_drop_monitor` / the engine's periodic drop warning).
* ``dense`` — capacity-factor GShard dispatch/combine einsums: tokens →
  [E, C, H] buffers, expert FFNs as one batched einsum over the (sharded)
  E dim. Static shapes everywhere; drops beyond capacity. Kept as the
  reference-parity path and for meshes ragged doesn't cover.
"""
from __future__ import annotations

from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax, shard_map
from jax.ad_checkpoint import checkpoint_name as _ckpt_name
from jax.sharding import NamedSharding, PartitionSpec as P

from deepspeed_tpu.comm.mesh import (
    DATA_AXIS,
    EXPERT_AXIS,
    SEQ_AXIS,
    TENSOR_AXIS,
    ZSHARD_AXIS,
    maybe_mesh,
    on_reset_mesh,
)
from deepspeed_tpu.utils.logging import logger
from deepspeed_tpu.moe.gating import (
    GateOutput,
    IndexGateOutput,
    topk_gating,
    topk_gating_indices,
)

PyTree = Any

# jitted shard_map programs keyed on (mesh, static config, shapes) — eager
# callers would otherwise rebuild + retrace the program every invocation.
# Cleared when the global mesh is torn down: stale Mesh keys would pin the
# old mesh + its compiled programs for the life of the process.
_SHARDED_FN_CACHE: Dict[Any, Any] = {}

on_reset_mesh(_SHARDED_FN_CACHE.clear)

# Installed observer for EP-dispatch buffer overflows (None → no callback is
# traced, zero cost). Under expert parallelism the 'dropless' path is only
# dropless per destination SHARD: a skewed router can overflow the fixed
# all-to-all buffer and the overflowed choices silently fall through to the
# residual. The engine installs a monitor so that degradation is visible.
_DROP_MONITOR = None


def set_drop_monitor(fn) -> None:
    """``fn(dropped_frac: float)`` called (async, via jax.debug.callback)
    with the global fraction of token-choices dropped by the EP buffer on
    each dispatch. Pass None to uninstall. Trace-time gated: install BEFORE
    the step is compiled."""
    global _DROP_MONITOR
    _DROP_MONITOR = fn


def _expert_constraint(x: jax.Array, n_lead: int = 1) -> jax.Array:
    """Constrain the leading expert dim onto the 'expert' mesh axis (if present)."""
    mesh = maybe_mesh()
    if mesh is None or mesh.shape.get(EXPERT_AXIS, 1) <= 1:
        return x
    spec = [None] * x.ndim
    spec[0] = EXPERT_AXIS
    return lax.with_sharding_constraint(x, NamedSharding(mesh, P(*spec)))


def _dense_ffn(xt: jax.Array, w_up: jax.Array, w_down: jax.Array,
               w_gate: Optional[jax.Array], activation: str) -> jax.Array:
    """Plain FFN on flat tokens [T,H] (the shared-expert path)."""
    dt = xt.dtype
    up = xt @ w_up.astype(dt)
    if w_gate is not None:
        up = jax.nn.silu(xt @ w_gate.astype(dt)) * up
    elif activation == "gelu":
        up = jax.nn.gelu(up, approximate=True)
    else:
        up = jax.nn.relu(up)
    return up @ w_down.astype(dt)


def _expert_act(up: jax.Array, gate: Optional[jax.Array], activation: str
                ) -> jax.Array:
    if gate is not None:
        return jax.nn.silu(gate) * up
    if activation == "gelu":
        return jax.nn.gelu(up, approximate=True)
    return jax.nn.relu(up)


def _pick_tile(dim: int, prefer: int) -> Optional[int]:
    """Tile for one gmm axis: the whole dim when it fits ``prefer`` (e.g.
    K=768 untiled — measured fastest), else the largest power of two ≤
    ``prefer`` dividing ``dim`` (pow2 start so non-pow2 prefers like 3072
    still ladder onto pow2 dims like 4096); None when nothing divides
    (caller falls back to lax.ragged_dot)."""
    if prefer <= 0:
        return None            # degrade to ragged_dot, not a crash
    if 0 < dim <= prefer:
        return dim
    if dim % prefer == 0:
        return prefer          # an explicit tile that divides is honored
    t = 1 << (prefer.bit_length() - 1)   # largest pow2 <= prefer
    while t >= 128:
        if dim % t == 0:
            return t
        t //= 2
    return None


def grouped_dot(x: jax.Array, w: jax.Array, group_sizes: jax.Array
                ) -> jax.Array:
    """Grouped GEMM ``x[rows of group e] @ w[e]`` → [M, N].

    On TPU this is the Pallas megablocks kernel (``megablox.gmm``, custom
    VJP with ``tgmm`` weight grads) with explicitly-tuned tiles — measured
    1.6× faster fwd+bwd than ``lax.ragged_dot``'s default lowering on the
    bench shapes ([16k, 768] × [4, 768, 3072] on v5e). Elsewhere (and for
    shapes the tile ladder can't divide) ``lax.ragged_dot``.

    NOTE: rows past ``sum(group_sizes)`` are zeros under ragged_dot but
    UNDEFINED under gmm — callers must not read them (the EP path never
    gathers them back; the local path has no tail rows).
    """
    M, K = x.shape
    N = w.shape[-1]
    if jax.default_backend() == "tpu":
        import os

        from deepspeed_tpu.utils import env_int

        # Tile defaults: (512, K-whole-up-to-1024, 1024) — the r4-measured
        # optimum that fits the 16M scoped-vmem budget in-program for
        # forward, dgrad AND tgmm. The r5 sweep (PROFILE.md) found wider
        # tiles ((1024, 768, 3072): 43 vs 30 TF/s standalone FORWARD) but
        # every variant either exceeds the in-program scoped-vmem limit
        # (fwd 17.9M, dgrad 36M at 16M/20M budgets) or — with the limit
        # raised via libtpu — REGRESSES the whole step (56.3k → 47.2k
        # tok/s: the global limit also governs XLA's fusion buffering).
        # ~43 TF/s standalone is therefore the measured KERNEL ceiling for
        # these shapes, not an achievable in-program rate.
        tiles, explicit = [], False
        for env, dim, default in (("DSTPU_GMM_TM", M, 512),
                                  ("DSTPU_GMM_TK", K, 1024),
                                  ("DSTPU_GMM_TN", N, 1024)):
            explicit |= env in os.environ
            tiles.append(_pick_tile(dim, env_int(env, default)))
        tm, tk, tn = tiles
        if explicit and not (tm and tk and tn):
            import warnings

            warnings.warn(
                f"DSTPU_GMM_* tiles unusable for gmm shape [{M},{K}]x[E,{K},"
                f"{N}] (no pow2 ladder value divides the dim) — falling back "
                "to lax.ragged_dot, typically ~1.6x slower fwd+bwd; the "
                "number you measure will NOT be the tile's performance")
        if tm and tk and tn:
            from jax.experimental.pallas.ops.tpu.megablox import gmm

            return gmm(x, w, group_sizes, x.dtype, (tm, tk, tn))
    return lax.ragged_dot(x, w, group_sizes)


def ragged_expert_ffn(x_sorted: jax.Array, group_sizes: jax.Array,
                      experts: Dict[str, jax.Array], activation: str
                      ) -> jax.Array:
    """Grouped expert FFN on expert-sorted tokens.

    x_sorted [M, H] — rows grouped contiguously by expert; group_sizes [E]
    int32 summing to M. Each weight application is ONE grouped GEMM
    (:func:`grouped_dot`) instead of E small matmuls or a [T,E,C] einsum.
    """
    dt = x_sorted.dtype
    # named so remat="moe_selective" can store up/act (backward then never
    # re-runs the grouped GEMMs); measured slower than recompute on v5e at
    # the bench shapes, kept for bigger-expert configs where the trade flips
    up = _ckpt_name(
        grouped_dot(x_sorted, experts["w_up"].astype(dt), group_sizes),
        "moe_up")
    g = (_ckpt_name(
        grouped_dot(x_sorted, experts["w_gate"].astype(dt), group_sizes),
        "moe_up")
        if "w_gate" in experts else None)
    act = _ckpt_name(_expert_act(up, g, activation), "moe_act")
    return grouped_dot(act, experts["w_down"].astype(dt), group_sizes)


def expert_sort(flat: jax.Array, E: int
                ) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """Counting sort of expert assignments → (order, inverse, counts).

    ``order[i]`` = row of the i-th element in expert-sorted layout (stable);
    ``inv[r]`` = sorted slot of row r (the inverse permutation, free here);
    ``counts[e]`` = occupancy of expert e (= ragged_dot group_sizes).

    A general ``argsort`` of 16k keys costs ~2.5 ms on a v5e (measured) —
    the single biggest cost of the naive sort-based dispatch. With E small
    the one-hot + cumsum counting sort is a few hundred µs and also
    produces counts + inverse without further sorts.
    """
    Tk = flat.shape[0]
    onehot = jax.nn.one_hot(flat, E, dtype=jnp.int32)        # [Tk, E]
    within = jnp.cumsum(onehot, axis=0) - 1                  # pos within expert
    counts = jnp.sum(onehot, axis=0)                         # [E]
    starts = jnp.cumsum(counts) - counts                     # exclusive
    slot = jnp.take_along_axis(within, flat[:, None], 1)[:, 0] \
        + jnp.take(starts, flat)
    slot = slot.astype(jnp.int32)
    order = jnp.zeros((Tk,), jnp.int32).at[slot].set(
        jnp.arange(Tk, dtype=jnp.int32))
    return order, slot, counts.astype(jnp.int32)


@jax.custom_vjp
def permute_rows(x: jax.Array, perm: jax.Array, inv_perm: jax.Array
                 ) -> jax.Array:
    """``x[perm]`` for a PERMUTATION ``perm`` whose inverse is known.

    XLA transposes a plain gather into a scatter-add (slow, serialized on
    TPU); for a permutation the transpose is just a gather by the inverse —
    this custom VJP tells XLA so, keeping both directions pure gathers.
    """
    return jnp.take(x, perm, axis=0)


def _permute_rows_fwd(x, perm, inv_perm):
    return jnp.take(x, perm, axis=0), (perm, inv_perm)


def _permute_rows_bwd(res, g):
    perm, inv_perm = res
    return jnp.take(g, inv_perm, axis=0), None, None


permute_rows.defvjp(_permute_rows_fwd, _permute_rows_bwd)


def _take_pad_zero(x: jax.Array, idx: jax.Array) -> jax.Array:
    """``x[idx]`` where ``idx == len(x)`` (one-past sentinel) reads a zero row."""
    pad = jnp.zeros((1,) + x.shape[1:], x.dtype)
    return jnp.take(jnp.concatenate([x, pad], axis=0), idx, axis=0)


@jax.custom_vjp
def buffer_exchange(vals: jax.Array, fwd_idx: jax.Array, bwd_idx: jax.Array
                    ) -> jax.Array:
    """``vals[fwd_idx]`` (sentinel → 0) whose transpose is ``g[bwd_idx]``.

    For the EP pack/unpack buffers the forward and backward index maps are
    each other's (partial) inverses — slots are filled by at most one row —
    so both directions are pure gathers, never TPU scatter-adds.
    """
    return _take_pad_zero(vals, fwd_idx)


def _buffer_exchange_fwd(vals, fwd_idx, bwd_idx):
    return _take_pad_zero(vals, fwd_idx), bwd_idx


def _buffer_exchange_bwd(bwd_idx, g):
    return _take_pad_zero(g, bwd_idx), None, None


buffer_exchange.defvjp(_buffer_exchange_fwd, _buffer_exchange_bwd)


@jax.custom_vjp
def buffer_exchange_kdup(x: jax.Array, fwd_rows: jax.Array,
                         bwd_idx2d: jax.Array) -> jax.Array:
    """:func:`buffer_exchange` with the k-duplication folded into the index
    map (the EP-path sibling of :func:`dispatch_gather`): ``out[j] =
    x[fwd_rows[j]]`` where ``fwd_rows = slot2row // k`` — the one-past
    sentinel ``t*k`` divides to exactly ``t``, the zero pad row — so the
    [t*k, H] broadcast of x is never materialized. Transpose:
    ``dx[t] = Σ_c zero-padded g[bwd_idx2d[t, c]]`` — pure gathers.
    """
    return _take_pad_zero(x, fwd_rows)


def _buffer_exchange_kdup_fwd(x, fwd_rows, bwd_idx2d):
    return _take_pad_zero(x, fwd_rows), bwd_idx2d


def _buffer_exchange_kdup_bwd(bwd_idx2d, g):
    t, k = bwd_idx2d.shape
    dx = _take_pad_zero(g, bwd_idx2d.reshape(t * k)) \
        .reshape(t, k, g.shape[-1]).sum(axis=1)
    return dx, None, None


buffer_exchange_kdup.defvjp(_buffer_exchange_kdup_fwd,
                            _buffer_exchange_kdup_bwd)


@jax.custom_vjp
def dispatch_gather(x: jax.Array, order: jax.Array, inv2d: jax.Array
                    ) -> jax.Array:
    """Expert-sorted token rows WITHOUT materializing the k-duplicated
    [T*k, H] intermediate: ``out[j] = x[order[j] // k]`` in one gather.

    ``inv2d`` [T, k] is the inverse map (sorted slot of token t's c-th
    choice); the transpose is then also pure gathers:
    ``dx[t] = Σ_c g[inv2d[t, c]]`` — never a TPU scatter-add.
    """
    k = inv2d.shape[-1]
    return jnp.take(x, order // k, axis=0)


def _dispatch_gather_fwd(x, order, inv2d):
    return dispatch_gather(x, order, inv2d), inv2d


def _dispatch_gather_bwd(inv2d, g):
    return jnp.take(g, inv2d, axis=0).sum(axis=1), None, None


dispatch_gather.defvjp(_dispatch_gather_fwd, _dispatch_gather_bwd)


@jax.custom_vjp
def combine_gather(y_s: jax.Array, weights: jax.Array, order: jax.Array,
                   inv2d: jax.Array) -> jax.Array:
    """Weighted combine straight from the expert-sorted rows:
    ``out[t] = Σ_c weights[t, c] · y_s[inv2d[t, c]]`` — the gate-weight
    multiply and the k-way reduction fuse into the un-sort gather, skipping
    two [T*k, H] materializations (the weighted rows and the un-sorted
    rows). Backward is pure gathers: ``dy_s[j] = w[j] · g[order[j] // k]``
    and ``dw[t, c] = ⟨y_s[inv2d[t, c]], g[t]⟩``.
    """
    w = weights.astype(y_s.dtype)
    return (jnp.take(y_s, inv2d, axis=0) * w[..., None]).sum(axis=1)


def _combine_gather_fwd(y_s, weights, order, inv2d):
    return combine_gather(y_s, weights, order, inv2d), \
        (y_s, weights, order, inv2d)


def _combine_gather_bwd(res, g):
    y_s, weights, order, inv2d = res
    k = inv2d.shape[-1]
    w_s = jnp.take(weights.reshape(-1), order).astype(y_s.dtype)
    dy = jnp.take(g, order // k, axis=0) * w_s[:, None]
    dw = jnp.einsum("tkh,th->tk", jnp.take(y_s, inv2d, axis=0), g,
                    preferred_element_type=jnp.float32).astype(weights.dtype)
    return dy, dw, None, None


combine_gather.defvjp(_combine_gather_fwd, _combine_gather_bwd)


def _ragged_dispatch_local(xt: jax.Array, weights: jax.Array, idx: jax.Array,
                           experts: Dict[str, jax.Array], activation: str
                           ) -> jax.Array:
    """Dropless dispatch on local tokens: sort → ragged matmul → un-sort.

    xt [T, H]; weights/idx [T, k]. Dispatch = :func:`dispatch_gather`
    (one gather straight from [T, H], k-duplication folded into the index
    map); combine = :func:`combine_gather` (gate weights + k-reduction
    fused into the inverse gather) — no [T*k, H] broadcast, weighted copy
    or un-sorted copy is ever materialized, and no direction is a TPU
    scatter-add.
    """
    T, H = xt.shape
    k = idx.shape[-1]
    Tk = T * k
    E = experts["w_up"].shape[0]
    flat = idx.reshape(Tk)
    order, inv, group_sizes = expert_sort(flat, E)
    # tiny [Tk] ints + [T,k] weights: named so the selective remat policy
    # STORES them — bwd then skips re-running the whole gate + counting sort
    order = _ckpt_name(order, "moe_gate")
    inv2d = _ckpt_name(inv.reshape(T, k), "moe_gate")
    group_sizes = _ckpt_name(group_sizes, "moe_gate")
    weights = _ckpt_name(weights, "moe_gate")
    x_s = dispatch_gather(xt, order, inv2d)
    y_s = ragged_expert_ffn(x_s, group_sizes, experts, activation)
    return combine_gather(y_s, weights.astype(xt.dtype), order, inv2d)


def _already_manual_axes() -> set:
    """Axes manualized by an ENCLOSING shard_map at trace time (e.g. the
    engine's compressed-collective step is manual over data/zshard; the
    pipeline over 'pipe') — our shard_map must not re-manualize them, and
    inside that context the tokens are already per-shard on those axes."""
    try:
        am = jax.sharding.get_abstract_mesh()
        return {n for n, t in zip(am.axis_names, am.axis_types)
                if "Manual" in str(t)}
    except Exception as e:
        # abstract-mesh introspection only exists on newer jax; absence
        # means no enclosing shard_map manualized anything
        logger.debug(f"abstract-mesh probe unavailable "
                     f"({type(e).__name__}: {e}); assuming no manual axes")
        return set()


def _token_axes(mesh) -> Tuple[Tuple[str, ...], Optional[str]]:
    """Mesh axes that shard the token stream: (batch axes, seq axis) —
    excluding axes an enclosing shard_map already made manual."""
    manual = _already_manual_axes()
    batch = tuple(a for a in (DATA_AXIS, ZSHARD_AXIS, EXPERT_AXIS)
                  if mesh.shape.get(a, 1) > 1 and a not in manual)
    seq = SEQ_AXIS if (mesh.shape.get(SEQ_AXIS, 1) > 1
                       and SEQ_AXIS not in manual) else None
    return batch, seq


def ragged_mesh_plan(mesh, B: int, S: Optional[int], E: int):
    """How the ragged dispatch should lower on ``mesh`` for a [B,S,H] input.

    Returns ``('local', None)`` (plain program — no axis sharded),
    ``('shard', (batch_axes, seq_ax, ep, tp))`` (shard_map program), or
    ``('indivisible', None)`` (shapes don't divide the sharded mesh; caller
    decides between the dense path and the GSPMD-placed local program).
    The ONE copy of this predicate — used by both :func:`resolve_dispatch`
    and :func:`_ragged_routed` so auto-selection and lowering can't drift.
    """
    if mesh is None:
        return "local", None
    manual = _already_manual_axes()
    batch_axes, seq_ax = _token_axes(mesh)
    ep = mesh.shape.get(EXPERT_AXIS, 1) if EXPERT_AXIS not in manual else 1
    tp = TENSOR_AXIS if (mesh.shape.get(TENSOR_AXIS, 1) > 1
                         and TENSOR_AXIS not in manual) else None
    if not (batch_axes or seq_ax or tp or ep > 1):
        return "local", None
    bshards = 1
    for a in batch_axes:
        bshards *= mesh.shape[a]
    if B % bshards or (seq_ax and (S is None or S % mesh.shape[seq_ax])) \
            or (ep > 1 and E % ep):
        return "indivisible", None
    return "shard", (batch_axes, seq_ax, ep, tp)


def resolve_dispatch(dispatch: str, rng: Optional[jax.Array],
                     noise_std: float, B: Optional[int] = None,
                     S: Optional[int] = None, E: Optional[int] = None) -> str:
    """'auto' → 'ragged' wherever it's implemented, else 'dense'.

    ragged covers: single shard, token-sharded meshes (per-shard sort in
    shard_map), and expert-parallel meshes (fixed-capacity all-to-all) —
    provided the batch/seq dims divide the mesh (shard_map is exact about
    shapes where GSPMD constraints are hints) and E divides the expert axis.
    Noisy gating stays dense: per-shard RNG streams inside shard_map would
    decorrelate from the global-batch reference semantics.
    """
    if dispatch not in ("auto", "ragged", "dense"):
        raise ValueError(
            f"moe dispatch must be auto|ragged|dense, got {dispatch!r}")
    noisy = rng is not None and noise_std > 0.0
    if dispatch == "ragged" and noisy:
        raise ValueError(
            "dispatch='ragged' does not implement noisy gating (per-shard "
            "RNG streams would decorrelate from global-batch semantics) — "
            "use dispatch='dense' or 'auto' with noisy gating")
    if dispatch != "auto":
        return dispatch
    if noisy:
        return "dense"
    if B is not None:
        kind, _ = ragged_mesh_plan(maybe_mesh(), B, S,
                                   E if E is not None else 1)
        if kind == "indivisible":
            return "dense"
    return "ragged"


def routing_drop_stats(logits: jax.Array, k: int, capacity_factor: float,
                       min_capacity: int = 4, ep: int = 1,
                       tokens_per_shard: Optional[int] = None
                       ) -> Dict[str, float]:
    """Dropped-token-choice fractions for both dispatch modes on one batch.

    ``dense``: per-EXPERT capacity C (GShard) — the fraction of the T*k
    choices that overflow an expert's capacity slots.
    ``ragged``: 0 off expert-parallel meshes (dropless by construction);
    under EP, the fraction overflowing a per-destination-SHARD buffer of
    :func:`ep_shard_capacity` slots, evaluated per token shard.
    """
    from deepspeed_tpu.moe.gating import gate_capacity, topk_gating

    T, E = logits.shape
    gate = topk_gating(logits, k=k, capacity_factor=capacity_factor,
                       min_capacity=min_capacity)
    kept = float(jnp.sum(gate.dispatch))
    dense_frac = 1.0 - kept / (T * k)

    ragged_frac = 0.0
    if ep > 1:
        t = tokens_per_shard or T
        idx = jnp.argsort(-logits, axis=-1)[:, :k]           # top-k experts
        dest = idx // (E // ep)                               # [T, k]
        Cs = ep_shard_capacity(t * k, ep)
        dropped = 0
        for s0 in range(0, T, t):
            d = dest[s0:s0 + t].reshape(-1)
            counts = jnp.bincount(d, length=ep)
            dropped += float(jnp.sum(jnp.maximum(counts - Cs, 0)))
        ragged_frac = dropped / (T * k)
    return {"dense": dense_frac, "ragged": ragged_frac,
            "dense_capacity": gate_capacity(T, E, k, capacity_factor,
                                            min_capacity)}


def _gate_indices(xt: jax.Array, gate_w: jax.Array,
                  gate_bias: Optional[jax.Array], k: int, score_func: str,
                  route_norm: bool, n_group: int, topk_group: int
                  ) -> IndexGateOutput:
    logits = xt.astype(jnp.float32) @ gate_w.astype(jnp.float32)
    return topk_gating_indices(
        logits, k=k, normalize=route_norm, score_func=score_func,
        select_bias=gate_bias, n_group=n_group, topk_group=topk_group)


def ep_shard_capacity(local_choices: int, ep: int) -> int:
    """Per-destination-shard buffer slots for the EP all-to-all.

    Balanced load is ``local_choices/ep``; 2× headroom makes shard-level
    drops rare (the shard buffer pools E/ep experts, so imbalance averages
    out — far coarser than the dense path's per-EXPERT capacity). Tiny
    inputs get a fully dropless buffer (the comm overhead is noise there).
    """
    return min(local_choices, max(64, -(-local_choices * 2 // ep)))


def _ragged_routed(x: jax.Array, gate_w: jax.Array,
                   experts: Dict[str, jax.Array],
                   gate_bias: Optional[jax.Array], *, activation: str, k: int,
                   score_func: str, route_norm: bool, n_group: int,
                   topk_group: int) -> Tuple[jax.Array, jax.Array]:
    """Dropless routed-expert computation. Returns (y [B,S,H], aux).

    Three lowerings by mesh shape: single-shard sort+ragged_dot; per-shard
    sort inside ``shard_map`` when only token axes are sharded; and the
    expert-parallel fixed-capacity all-to-all (reference ``_AllToAll``
    ``sharded_moe.py:97`` — but with packed variable-occupancy buffers and a
    grouped matmul instead of [E,C,H] einsums).
    """
    B, S, H = x.shape
    E = gate_w.shape[1]
    mesh = maybe_mesh()

    kind, plan = ragged_mesh_plan(mesh, B, S, E)
    if kind != "shard":
        # 'local': nothing sharded (a pipe-only mesh never shards tokens or
        # experts). 'indivisible' (e.g. direct small-batch calls under a
        # lazily-initialized global mesh): shard_map is exact about shapes,
        # so run the plain local program and let GSPMD place it however the
        # inputs are actually sharded.
        xt = x.reshape(-1, H)
        gate = _gate_indices(xt, gate_w, gate_bias, k, score_func,
                             route_norm, n_group, topk_group)
        y = _ragged_dispatch_local(xt, gate.weights, gate.experts, experts,
                                   activation)
        return y.reshape(B, S, H), gate.aux_loss

    batch_axes, seq_ax, ep, tp = plan
    used_axes = set(batch_axes) | ({seq_ax} if seq_ax else set()) \
        | ({tp} if tp else set()) | ({EXPERT_AXIS} if ep > 1 else set())
    e_ax = EXPERT_AXIS if ep > 1 else None
    mean_axes = batch_axes + ((seq_ax,) if seq_ax else ())

    def _global_aux(gate: IndexGateOutput) -> jax.Array:
        """EXACT global-batch Switch aux under sharding: token-means of
        probs and first-choice mask are pmean'd BEFORE the dot product —
        identical to the dense path's estimator, not a mean of per-shard
        aux values (a product of means ≠ mean of products)."""
        me = jnp.mean(gate.probs, axis=0)
        ce = jnp.mean(jax.nn.one_hot(gate.experts[:, 0], E,
                                     dtype=jnp.float32), axis=0)
        if mean_axes:
            me = lax.pmean(me, mean_axes)
            ce = lax.pmean(ce, mean_axes)
        return jnp.sum(me * ce) * E

    bspec = P(batch_axes if batch_axes else None, seq_ax, None)
    espec = {kk: (P(e_ax, tp, None) if kk == "w_down" else P(e_ax, None, tp))
             for kk in experts}
    # bias of zeros ≡ no bias for SELECTION: argmax over gate_source+0 picks
    # the same experts as argmax over logits (softmax/sigmoid are monotone),
    # and combine weights never see the bias — keeps the in_specs pytree
    # uniform whether or not the model has e_score_correction_bias.
    gb = gate_bias if gate_bias is not None else jnp.zeros((E,), jnp.float32)

    if ep == 1:
        def local_fn(x_l, gw_l, ex_l, gb_l):
            b, s, _ = x_l.shape
            xt = x_l.reshape(-1, H)
            gate = _gate_indices(xt, gw_l, gb_l, k, score_func, route_norm,
                                 n_group, topk_group)
            y = _ragged_dispatch_local(xt, gate.weights, gate.experts, ex_l,
                                       activation)
            if tp is not None:
                y = lax.psum(y, tp)
            return y.reshape(b, s, H), _global_aux(gate), jnp.float32(0.0)
    else:
        if E % ep:
            raise ValueError(f"n_experts={E} not divisible by expert mesh axis {ep}")
        E_l = E // ep

        def local_fn(x_l, gw_l, ex_l, gb_l):
            b, s, _ = x_l.shape
            xt = x_l.reshape(-1, H)
            t = xt.shape[0]
            dt = xt.dtype
            gate = _gate_indices(xt, gw_l, gb_l, k, score_func, route_norm,
                                 n_group, topk_group)
            tk = t * k
            Cs = ep_shard_capacity(tk, ep)
            flat_e = gate.experts.reshape(tk)
            dest = flat_e // E_l                          # dest expert-shard
            # per-row slot in the packed send buffer, sort-free: position
            # within the destination's group via one-hot cumsum; overflow →
            # OOB sentinel (scatter drops it; the zero pad row on the way
            # back ⇒ dropped choice contributes 0, token falls through the
            # residual — dense-path drop semantics)
            onehot = jax.nn.one_hot(dest, ep, dtype=jnp.int32)
            pos = jnp.take_along_axis(jnp.cumsum(onehot, axis=0) - 1,
                                      dest[:, None], 1)[:, 0]
            slot = _ckpt_name(jnp.where(pos < Cs, dest * Cs + pos,
                                        ep * Cs).astype(jnp.int32), "moe_gate")
            if monitored:
                # global dropped-choice fraction across every source shard —
                # returned from the shard_map and reported via an async host
                # callback OUTSIDE it (debug callbacks don't lower inside a
                # partial-manual shard_map)
                ax = tuple(dict.fromkeys(
                    list(batch_axes) + ([seq_ax] if seq_ax else [])
                    + [EXPERT_AXIS]))
                drop_frac = (lax.psum(jnp.sum((slot == ep * Cs).astype(
                    jnp.float32)), ax) / lax.psum(jnp.float32(tk), ax))
            else:
                drop_frac = jnp.float32(0.0)
            # slot2row inverts slot (sentinel tk = empty buffer slot): both
            # buffer directions become pure gathers via buffer_exchange
            slot2row = _ckpt_name(
                jnp.full((ep * Cs,), tk, jnp.int32).at[slot].set(
                    jnp.arange(tk, dtype=jnp.int32), mode="drop"), "moe_gate")
            # k-duplication folded into the gather index (slot2row // k;
            # sentinel tk divides to t = xt's zero pad row) — the [tk, H]
            # broadcast copy is never materialized
            send_x = buffer_exchange_kdup(xt, slot2row // k,
                                          slot.reshape(t, k))
            send_e = jnp.where(
                slot2row < tk,
                jnp.take(flat_e % E_l, jnp.minimum(slot2row, tk - 1)),
                E_l)                                      # E_l = empty slot

            recv_x = lax.all_to_all(send_x.reshape(ep, Cs, H), EXPERT_AXIS,
                                    0, 0, tiled=True).reshape(ep * Cs, H)
            recv_e = lax.all_to_all(send_e.reshape(ep, Cs), EXPERT_AXIS,
                                    0, 0, tiled=True).reshape(ep * Cs)

            # counting sort by local expert; empties (sentinel E_l) land
            # past sum(group_sizes) — those rows are ZEROS under
            # lax.ragged_dot but UNDEFINED under the gmm path
            # (grouped_dot's contract): nothing below may read them — the
            # combine gathers strictly by `slot` (buffer_exchange), whose
            # sentinel hits the zero pad row, never a tail row of y_r
            ro, rinv, rc = expert_sort(recv_e, E_l + 1)
            ro = _ckpt_name(ro, "moe_gate")
            rinv = _ckpt_name(rinv, "moe_gate")
            rc = _ckpt_name(rc, "moe_gate")
            rx = permute_rows(recv_x, ro, rinv)
            y_r = ragged_expert_ffn(rx, rc[:E_l], ex_l, activation)
            if tp is not None:
                y_r = lax.psum(y_r, tp)                   # w_down F-sharded
            y_slots = permute_rows(y_r, rinv, ro).reshape(ep, Cs, H)

            y_back = lax.all_to_all(y_slots, EXPERT_AXIS, 0, 0,
                                    tiled=True).reshape(ep * Cs, H)
            # renormalize combine weights over the choices that SURVIVED the
            # buffer (dense-path semantics: denom runs over kept gates only)
            keep = (slot < ep * Cs).reshape(t, k).astype(jnp.float32)
            w = gate.weights * keep
            if route_norm:
                w = w / jnp.maximum(jnp.sum(w, axis=1, keepdims=True), 1e-9)
            contrib = buffer_exchange(y_back, slot, slot2row) * \
                w.reshape(tk)[:, None].astype(dt)
            y = contrib.reshape(t, k, H).sum(axis=1)
            return y.reshape(b, s, H), _global_aux(gate), drop_frac

    # manualize only the axes we use — nests under the pipeline's
    # axis_names={'pipe'} shard_map and leaves other axes to GSPMD. The
    # jit wrapper is inlined when already tracing (the normal engine path)
    # and makes eager calls legal (partial-manual out_specs are only
    # accepted under jit); it's cached so eager callers don't recompile
    # per invocation (jit caches on function identity).
    # under an ENCLOSING shard_map (compressed step manual over data/zshard,
    # pipeline manual over 'pipe') the nested shard_map must be built on the
    # context's abstract mesh — its axis_types record what is already manual
    sm_mesh = mesh
    if _already_manual_axes():
        sm_mesh = jax.sharding.get_abstract_mesh()
    # trace-time: drop reporting is active only when a monitor is installed
    # AND we're not under an enclosing manual context (where the callback
    # can't lower) — gate BOTH the psums and the callback on it so the
    # unmonitored trace stays the zero-cost constant path
    monitored = (_DROP_MONITOR is not None and ep > 1
                 and not _already_manual_axes())
    cache_key = (sm_mesh, k, activation, score_func, route_norm, n_group,
                 topk_group, x.shape, str(x.dtype), gate_w.shape,
                 monitored,
                 tuple(sorted((kk, v.shape, str(v.dtype))
                              for kk, v in experts.items())))
    fn = _SHARDED_FN_CACHE.get(cache_key)
    if fn is None:
        fn = jax.jit(shard_map(local_fn, mesh=sm_mesh,
                               in_specs=(bspec, P(None, None), espec,
                                         P(None)),
                               out_specs=(bspec, P(), P()), check_vma=False,
                               axis_names=used_axes))
        if len(_SHARDED_FN_CACHE) >= 32:
            _SHARDED_FN_CACHE.pop(next(iter(_SHARDED_FN_CACHE)))
        _SHARDED_FN_CACHE[cache_key] = fn
    y, aux, drop_frac = fn(x, gate_w, experts, gb)
    if monitored:
        # async host report. Outside our shard_map; skipped under an
        # ENCLOSING manual context (compressed-collective step) where debug
        # callbacks can't lower — those runs still have routing_drop_stats.
        jax.debug.callback(_DROP_MONITOR, drop_frac)
    return y, aux


def moe_ffn(x: jax.Array, gate_w: jax.Array, experts: Dict[str, jax.Array],
            activation: str = "gelu", k: int = 2,
            capacity_factor: float = 1.25, min_capacity: int = 4,
            rng: Optional[jax.Array] = None, noise_std: float = 0.0,
            score_func: str = "softmax", route_norm: bool = True,
            route_scale: float = 1.0,
            shared: Optional[Dict[str, jax.Array]] = None,
            gate_bias: Optional[jax.Array] = None,
            n_group: int = 1, topk_group: int = 1,
            dispatch: str = "auto"
            ) -> Tuple[jax.Array, jax.Array]:
    """Mixture-of-experts FFN.

    x: [B, S, H]; gate_w: [H, E]; experts: w_up [E, H, F], w_down [E, F, H],
    optional w_gate [E, H, F] (swiglu). Returns (y [B,S,H], aux_loss scalar).

    ``dispatch``: 'auto' | 'ragged' (dropless sort + grouped matmul) |
    'dense' (capacity-factor GShard einsums) — see module docstring.

    Routing variants (AutoEP presets): ``score_func`` softmax|sigmoid,
    ``route_norm`` renormalizes top-k weights, ``route_scale`` scales the
    routed output (DeepSeek routed_scaling_factor). ``shared`` adds an
    always-on shared expert (sw_up [H,Fs], sw_down [Fs,H], optional sw_gate
    [H,Fs], optional shared_gate_w [H,1] sigmoid gate — Qwen2-MoE).
    """
    B, S, H = x.shape
    dt = x.dtype
    T = B * S
    xt = x.reshape(T, H)

    mode = resolve_dispatch(dispatch, rng, noise_std, B, S, gate_w.shape[1])
    if mode == "ragged":
        y, aux = _ragged_routed(
            x, gate_w, experts, gate_bias, activation=activation, k=k,
            score_func=score_func, route_norm=route_norm, n_group=n_group,
            topk_group=topk_group)
        y = y.reshape(T, H)
    else:
        logits = xt.astype(jnp.float32) @ gate_w.astype(jnp.float32)   # [T, E]
        gate: GateOutput = topk_gating(
            logits, k=k, capacity_factor=capacity_factor,
            min_capacity=min_capacity, rng=rng, noise_std=noise_std,
            normalize=route_norm, score_func=score_func,
            select_bias=gate_bias, n_group=n_group, topk_group=topk_group)
        aux = gate.aux_loss

        # dispatch: [T,E,C] × [T,H] → [E,C,H]; GSPMD turns the resharding of
        # the token dim (data/expert-sharded) onto the expert dim into an
        # all-to-all
        xe = jnp.einsum("tec,th->ech", gate.dispatch.astype(dt), xt)
        xe = _expert_constraint(xe)

        up = jnp.einsum("ech,ehf->ecf", xe, experts["w_up"].astype(dt))
        g = (jnp.einsum("ech,ehf->ecf", xe, experts["w_gate"].astype(dt))
             if "w_gate" in experts else None)
        act = _expert_act(up, g, activation)
        ye = jnp.einsum("ecf,efh->ech", act, experts["w_down"].astype(dt))
        ye = _expert_constraint(ye)

        y = jnp.einsum("tec,ech->th", gate.combine.astype(dt), ye)
    if route_scale != 1.0:
        y = y * jnp.asarray(route_scale, dt)
    if shared:
        ys = _dense_ffn(xt, shared["sw_up"], shared["sw_down"],
                        shared.get("sw_gate"), activation)
        if "shared_gate_w" in shared:
            sg = jax.nn.sigmoid(
                xt.astype(jnp.float32) @ shared["shared_gate_w"].astype(jnp.float32))
            ys = ys * sg.astype(dt)
        y = y + ys
    return y.reshape(B, S, H), aux
