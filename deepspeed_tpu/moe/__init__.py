"""Mixture-of-experts / expert parallelism (reference ``deepspeed/moe/``).

* :mod:`gating` — top-1/2/k gates with capacity + load-balancing loss
  (``sharded_moe.py:184,291,375``).
* :mod:`layer` — dense dispatch/combine einsums; the 'expert' mesh axis plays
  the role of the reference's expert-parallel process groups
  (``utils/groups.py:304``), with GSPMD emitting the dispatch all-to-all
  (``sharded_moe.py:97 _AllToAll``).

Model integration: set ``n_experts > 0`` on a ``TransformerConfig`` (e.g. the
``tiny_moe`` / ``mixtral_8x7b`` presets).
"""
from deepspeed_tpu.moe.gating import (
    GateOutput,
    gate_capacity,
    top1_gating,
    top2_gating,
    topk_gating,
)
from deepspeed_tpu.moe.layer import moe_ffn
from deepspeed_tpu.moe.presets import (EPTopology, MoEPreset, PRESETS,
                                       ep_topology, fold_group_tables,
                                       preset_for_model_type, resolve_preset)

__all__ = [
    "GateOutput",
    "gate_capacity",
    "top1_gating",
    "top2_gating",
    "topk_gating",
    "moe_ffn",
    "MoEPreset",
    "PRESETS",
    "EPTopology",
    "ep_topology",
    "fold_group_tables",
    "preset_for_model_type",
    "resolve_preset",
]
