"""Mixture-of-experts / expert parallelism (reference ``deepspeed/moe/``).

* :mod:`gating` — top-1/2/k gates with capacity + load-balancing loss
  (``sharded_moe.py:184,291,375``).
* :mod:`layer` — dropless ragged dispatch (sort + ``lax.ragged_dot`` grouped
  matmul, fixed-capacity all-to-all under expert parallelism) with the dense
  GShard dispatch/combine einsums as the reference-parity fallback; the
  'expert' mesh axis plays the role of the reference's expert-parallel
  process groups (``utils/groups.py:304``, ``sharded_moe.py:97 _AllToAll``).

Model integration: set ``n_experts > 0`` on a ``TransformerConfig`` (e.g. the
``tiny_moe`` / ``mixtral_8x7b`` presets).
"""
from deepspeed_tpu.moe.gating import (
    GateOutput,
    IndexGateOutput,
    gate_capacity,
    top1_gating,
    top2_gating,
    topk_gating,
    topk_gating_indices,
)
from deepspeed_tpu.moe.layer import (
    ep_shard_capacity,
    moe_ffn,
    ragged_expert_ffn,
    resolve_dispatch,
)
from deepspeed_tpu.moe.presets import (EPTopology, MoEPreset, PRESETS,
                                       ep_topology, fold_group_tables,
                                       preset_for_model_type, resolve_preset)

__all__ = [
    "GateOutput",
    "IndexGateOutput",
    "gate_capacity",
    "top1_gating",
    "top2_gating",
    "topk_gating",
    "topk_gating_indices",
    "moe_ffn",
    "ragged_expert_ffn",
    "ep_shard_capacity",
    "resolve_dispatch",
    "MoEPreset",
    "PRESETS",
    "EPTopology",
    "ep_topology",
    "fold_group_tables",
    "preset_for_model_type",
    "resolve_preset",
]
