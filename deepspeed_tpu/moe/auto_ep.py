"""AutoEP: automatic expert-parallel detection, planning and injection.

Parity: reference ``module_inject/auto_ep.py`` (599 LoC detection/replacement
driver) + ``auto_ep_presets/`` (family registry — see ``moe/presets.py``) +
``auto_ep_folding.py`` (topology math — also ``presets.py``) +
``auto_ep_layer.py`` (the EP layer — ``moe/layer.py``'s sharded dispatch).

TPU translation: expert layout is declarative — expert tensors carry an
'expert' logical axis that the sharding policy maps onto the 'expert' mesh
axis (``parallel/partitioning.py``), and dispatch is the all-to-all MoE layer
(``moe/layer.py``). What AutoEP contributes here:

* **detection** (:func:`detect_moe`): preset-registry resolution of the MoE
  family from an HF config (mixtral / qwen2_moe / qwen3_moe / deepseek_v2/v3)
  with per-family routing knobs, plus a generic attribute fallback and zoo
  TransformerConfig support;
* **planning** (:func:`plan_ep`): expert-parallel width from the device count
  and expert count, with edp/etp widths and divisibility validation
  (reference ParallelFoldingSpec);
* **injection** (:func:`auto_ep`): imports the HF MoE model through the
  preset's schema (weight *folding* = stacking ModuleList experts into
  [L, E, in, out] arrays at import) and returns (spec, mesh_section, plan)
  to pass straight into ``deepspeed_tpu.initialize``. Families the zoo can't
  run (DeepSeek MLA attention) fail with the preset's documented note
  (reference ``unsupported_preset_for_hf_model_type``).
"""
from __future__ import annotations

import dataclasses
from typing import Any, Dict, Optional, Tuple

from deepspeed_tpu.moe.presets import (EPTopology, ep_topology,
                                       preset_for_model_type, resolve_preset)
from deepspeed_tpu.utils.logging import log_dist

# Generic HF config attribute names marking MoE archs (fallback when no
# preset matches the model_type)
_MOE_ATTRS = (
    ("num_local_experts", "num_experts_per_tok"),      # mixtral-like
    ("num_experts", "num_experts_per_tok"),            # qwen-moe-like
    ("n_routed_experts", "num_experts_per_tok"),       # deepseek-like
    ("moe_num_experts", "moe_top_k"),                  # misc
)


@dataclasses.dataclass(frozen=True)
class EPPlan:
    enabled: bool
    n_experts: int = 0
    top_k: int = 0
    ep_size: int = 1
    edp_size: int = 1
    etp_size: int = 1
    preset: Optional[str] = None
    reason: str = ""

    def describe(self) -> str:
        if not self.enabled:
            return f"AutoEP: disabled ({self.reason})"
        fam = f" [{self.preset}]" if self.preset else ""
        return (f"AutoEP{fam}: {self.n_experts} experts top-{self.top_k} over "
                f"ep={self.ep_size}×edp={self.edp_size}×etp={self.etp_size} "
                f"({self.reason})")

    def topology(self) -> EPTopology:
        return EPTopology(
            world_size=self.ep_size * self.edp_size * self.etp_size,
            ep_size=self.ep_size, edp_size=self.edp_size,
            etp_size=self.etp_size)


def detect_moe(config: Any) -> Tuple[int, int]:
    """→ (n_experts, top_k); (0, 0) when the model is dense.

    Accepts an HF config object or a zoo TransformerConfig. Preset registry
    first (family semantics), generic attribute sweep second."""
    n = getattr(config, "n_experts", 0)
    if n:
        return int(n), int(getattr(config, "moe_top_k", 2))
    resolved = resolve_preset(config)
    if resolved is not None:
        knobs = resolved[1]
        return knobs["n_experts"], knobs["top_k"]
    for n_attr, k_attr in _MOE_ATTRS:
        n = getattr(config, n_attr, 0) or 0
        if n:
            return int(n), int(getattr(config, k_attr, 2) or 2)
    return 0, 0


def plan_ep(config: Any, n_devices: Optional[int] = None,
            max_ep: Optional[int] = None,
            etp_size: int = 1) -> EPPlan:
    """Pick the expert-parallel width: the largest divisor of the device
    count that also divides the expert count (capped by ``max_ep``); the
    remaining width becomes expert-data parallelism."""
    n_experts, top_k = detect_moe(config)
    preset = preset_for_model_type(getattr(config, "model_type", None))
    pname = preset.name if preset else None
    if not n_experts:
        return EPPlan(False, reason="no MoE layers detected")
    if n_devices is None:
        import jax

        n_devices = jax.device_count()
    if n_devices % etp_size != 0:
        raise ValueError(f"etp_size {etp_size} does not divide device count "
                         f"{n_devices}")
    avail = n_devices // etp_size
    ep = 1
    for cand in range(1, min(n_experts, avail, max_ep or n_experts) + 1):
        if avail % cand == 0 and n_experts % cand == 0:
            ep = cand
    edp = avail // ep
    if ep == 1:
        return EPPlan(True, n_experts, top_k, 1, edp, etp_size, pname,
                      "no common divisor > 1 of devices and experts; "
                      "experts replicated")
    plan = EPPlan(True, n_experts, top_k, ep, edp, etp_size, pname,
                  f"{n_experts} experts over {n_devices} devices")
    plan.topology().validate(n_experts)
    return plan


def auto_ep(model_or_spec, n_devices: Optional[int] = None,
            max_ep: Optional[int] = None, etp_size: int = 1,
            **spec_kwargs) -> Tuple[Any, Dict[str, int], EPPlan]:
    """Detect + plan + inject. Accepts an HF model (anything
    ``import_hf_model`` takes) or a zoo ModelSpec.

    → (model_spec, mesh_section, plan); pass ``config={'mesh': mesh_section,
    ...}`` to ``initialize``. Unsupported families (DeepSeek MLA) raise with
    the preset's documented note."""
    from deepspeed_tpu.models.api import ModelSpec

    preset = None
    if isinstance(model_or_spec, ModelSpec):
        spec = model_or_spec
        cfg = spec.config
    else:
        hf_cfg = getattr(model_or_spec, "config", None)
        if hf_cfg is None and isinstance(model_or_spec, tuple):
            hf_cfg = model_or_spec[1]
        preset = preset_for_model_type(
            getattr(hf_cfg, "model_type", None)) if hf_cfg is not None else None
        if preset is not None and not preset.importable:
            raise NotImplementedError(
                f"AutoEP preset {preset.name!r}: {preset.unsupported_note}")
        from deepspeed_tpu.models.api import spec_from_hf

        spec = spec_from_hf(model_or_spec, **spec_kwargs)
        cfg = spec.config

    plan = plan_ep(cfg, n_devices=n_devices, max_ep=max_ep, etp_size=etp_size)
    if preset is not None and plan.preset is None:
        # the zoo config the plan saw has no model_type; carry the family over
        plan = dataclasses.replace(plan, preset=preset.name)
    log_dist(plan.describe())
    mesh_section: Dict[str, int] = {}
    if plan.enabled:
        mesh_section["expert"] = plan.ep_size
        if plan.etp_size > 1:
            mesh_section["tensor"] = plan.etp_size
    return spec, mesh_section, plan
