"""AutoEP: automatic expert-parallel detection, planning and injection.

Parity: reference ``module_inject/auto_ep.py`` (+ ``auto_ep_layer.py``,
``auto_ep_folding.py``, presets): detects MoE blocks inside an HF model,
replaces them with expert-parallel sharded layers, folds expert weights into
the EP layout, and records universal-checkpoint metadata.

TPU translation: expert layout is declarative — expert tensors carry an
'expert' logical axis that the sharding policy maps onto the 'expert' mesh
axis (``parallel/partitioning.py``), and dispatch is the all-to-all MoE layer
(``moe/layer.py``). What AutoEP contributes here:

* **detection** (:func:`detect_moe`): recognizes MoE in an HF config or a
  zoo TransformerConfig (n_experts, top-k, per-arch attribute names);
* **planning** (:func:`plan_ep`): picks the expert-parallel width from the
  device count and expert count (largest divisor of both ≤ n_experts —
  the reference preset logic);
* **injection** (:func:`auto_ep`): imports the HF MoE model (or takes a zoo
  spec) and returns (spec, mesh_section) to pass straight into
  ``deepspeed_tpu.initialize`` with the 'expert' axis sized per plan.
"""
from __future__ import annotations

import dataclasses
from typing import Any, Dict, Optional, Tuple

from deepspeed_tpu.utils.logging import log_dist

# HF config attribute names that mark MoE archs (the detector table)
_MOE_ATTRS = (
    ("num_local_experts", "num_experts_per_tok"),      # mixtral
    ("num_experts", "num_experts_per_tok"),            # qwen2_moe, deepseek
    ("moe_num_experts", "moe_top_k"),                  # misc
)


@dataclasses.dataclass(frozen=True)
class EPPlan:
    enabled: bool
    n_experts: int = 0
    top_k: int = 0
    ep_size: int = 1
    reason: str = ""

    def describe(self) -> str:
        if not self.enabled:
            return f"AutoEP: disabled ({self.reason})"
        return (f"AutoEP: {self.n_experts} experts top-{self.top_k} over "
                f"ep={self.ep_size} ({self.reason})")


def detect_moe(config: Any) -> Tuple[int, int]:
    """→ (n_experts, top_k); (0, 0) when the model is dense.

    Accepts an HF config object or a zoo TransformerConfig."""
    n = getattr(config, "n_experts", 0)
    if n:
        return int(n), int(getattr(config, "moe_top_k", 2))
    for n_attr, k_attr in _MOE_ATTRS:
        n = getattr(config, n_attr, 0) or 0
        if n:
            return int(n), int(getattr(config, k_attr, 2) or 2)
    return 0, 0


def plan_ep(config: Any, n_devices: Optional[int] = None,
            max_ep: Optional[int] = None) -> EPPlan:
    """Pick the expert-parallel width: the largest divisor of the device
    count that also divides the expert count (capped by ``max_ep``)."""
    n_experts, top_k = detect_moe(config)
    if not n_experts:
        return EPPlan(False, reason="no MoE layers detected")
    if n_devices is None:
        import jax

        n_devices = jax.device_count()
    ep = 1
    for cand in range(1, min(n_experts, n_devices, max_ep or n_experts) + 1):
        if n_devices % cand == 0 and n_experts % cand == 0:
            ep = cand
    if ep == 1:
        return EPPlan(True, n_experts, top_k, 1,
                      "no common divisor > 1 of devices and experts; "
                      "experts replicated")
    return EPPlan(True, n_experts, top_k, ep,
                  f"{n_experts} experts over {n_devices} devices")


def auto_ep(model_or_spec, n_devices: Optional[int] = None,
            max_ep: Optional[int] = None,
            **spec_kwargs) -> Tuple[Any, Dict[str, int], EPPlan]:
    """Detect + plan + inject. Accepts an HF model (anything
    ``import_hf_model`` takes) or a zoo ModelSpec.

    → (model_spec, mesh_section, plan); pass ``config={'mesh': mesh_section,
    ...}`` to ``initialize`` (mesh_section = {'expert': ep_size})."""
    from deepspeed_tpu.models.api import ModelSpec, causal_lm_spec

    if isinstance(model_or_spec, ModelSpec):
        spec = model_or_spec
        cfg = spec.config
    else:
        from deepspeed_tpu.models.api import spec_from_hf

        spec = spec_from_hf(model_or_spec, **spec_kwargs)
        cfg = spec.config

    plan = plan_ep(cfg, n_devices=n_devices, max_ep=max_ep)
    log_dist(plan.describe())
    mesh_section = {"expert": plan.ep_size} if plan.enabled else {}
    return spec, mesh_section, plan
