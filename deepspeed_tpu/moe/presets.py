"""AutoEP model-family presets + EP topology math.

Parity: reference ``module_inject/auto_ep_presets/`` (``base.py``
``MoEModelPreset`` — per-family routing semantics, weight patterns, storage
layout; ``registry.py`` — model_type → preset resolution with unsupported
notes) and ``module_inject/auto_ep_folding.py`` (``ParallelFoldingSpec`` /
``FoldingGroupTables`` — pure topology math for EP×TP×DP group layouts).

TPU translation: a preset here describes (a) the routing math the zoo's
``moe_ffn`` must run (score_func / route_norm / route_scale / shared experts)
and (b) which importer understands the family's weight schema. "Folding" —
the reference's runtime surgery that re-groups per-rank expert modules — is
weight stacking at import time (``models/hf_import.py`` stacks ModuleList
experts into [L, E, in, out] arrays whose 'expert' logical axis the sharding
policy maps onto the 'expert' mesh axis). The group tables are still pure
math and are computed from the named mesh shape.
"""
from __future__ import annotations

import dataclasses
import itertools
from typing import Dict, Optional, Tuple

import numpy as np


@dataclasses.dataclass(frozen=True)
class MoEPreset:
    """Routing + schema description for one MoE model family.

    Reference ``MoEModelPreset`` (``auto_ep_presets/base.py:27``); fields the
    CUDA version needs for module surgery (regex patterns, storage flags)
    collapse into ``importable`` + the importer's schema knowledge.
    """
    name: str
    hf_model_types: Tuple[str, ...]
    num_experts_attr: str
    top_k_attr: str
    score_func: str = "softmax"           # softmax | sigmoid
    route_norm_attr: Optional[str] = "norm_topk_prob"
    route_norm_default: bool = True
    route_scale_attr: Optional[str] = None  # e.g. routed_scaling_factor
    moe_ffn_attr: Optional[str] = "moe_intermediate_size"
    shared_size_attr: Optional[str] = None
    shared_gate: bool = False
    first_dense_attr: Optional[str] = None  # first_k_dense_replace (DeepSeek)
    importable: bool = True
    unsupported_note: str = ""

    def describe_config(self, hf_config) -> Dict[str, object]:
        """Extract this family's MoE knobs from an HF config object."""
        def attr(name, default=None):
            return getattr(hf_config, name, default) if name else default

        return {
            "n_experts": int(attr(self.num_experts_attr, 0) or 0),
            "top_k": int(attr(self.top_k_attr, 2) or 2),
            "score_func": self.score_func,
            "route_norm": bool(attr(self.route_norm_attr,
                                    self.route_norm_default)),
            "route_scale": float(attr(self.route_scale_attr, 1.0) or 1.0),
            "moe_ffn_size": attr(self.moe_ffn_attr),
            "shared_size": int(attr(self.shared_size_attr, 0) or 0),
            "shared_gate": self.shared_gate,
            "first_dense": int(attr(self.first_dense_attr, 0) or 0),
        }


# Registry (reference ``auto_ep_presets/{mixtral,qwen3_moe,...}.py``).
PRESETS: Dict[str, MoEPreset] = {
    "mixtral": MoEPreset(
        name="mixtral", hf_model_types=("mixtral",),
        num_experts_attr="num_local_experts",
        top_k_attr="num_experts_per_tok",
        route_norm_attr=None, route_norm_default=True,
        moe_ffn_attr="intermediate_size"),
    "qwen2_moe": MoEPreset(
        name="qwen2_moe", hf_model_types=("qwen2_moe",),
        num_experts_attr="num_experts", top_k_attr="num_experts_per_tok",
        route_norm_default=False,
        shared_size_attr="shared_expert_intermediate_size", shared_gate=True),
    "qwen3_moe": MoEPreset(
        name="qwen3_moe", hf_model_types=("qwen3_moe", "qwen3_5_moe"),
        num_experts_attr="num_experts", top_k_attr="num_experts_per_tok"),
    "deepseek_v2": MoEPreset(
        name="deepseek_v2", hf_model_types=("deepseek_v2",),
        num_experts_attr="n_routed_experts", top_k_attr="num_experts_per_tok",
        score_func="softmax", route_scale_attr="routed_scaling_factor",
        shared_size_attr="n_shared_experts",  # count ×moe_intermediate_size
        first_dense_attr="first_k_dense_replace",
        importable=True,
        unsupported_note=(
            "importable with MLA attention (models/transformer.py _mla_qkv); "
            "constraints: first_k_dense_replace=0 and topk_method='greedy' "
            "(the importer raises otherwise)")),
    "deepseek_v3": MoEPreset(
        name="deepseek_v3", hf_model_types=("deepseek_v3",),
        num_experts_attr="n_routed_experts", top_k_attr="num_experts_per_tok",
        score_func="sigmoid", route_scale_attr="routed_scaling_factor",
        shared_size_attr="n_shared_experts",
        first_dense_attr="first_k_dense_replace",
        importable=True,
        unsupported_note=(
            "importable with MLA attention + sigmoid grouped routing with "
            "e_score_correction_bias; constraint: first_k_dense_replace=0 "
            "(the importer raises otherwise)")),
}


def preset_for_model_type(model_type: Optional[str]) -> Optional[MoEPreset]:
    """model_type → preset (reference ``preset_name_for_hf_model_type``)."""
    if not model_type:
        return None
    for preset in PRESETS.values():
        if model_type in preset.hf_model_types:
            return preset
    return None


def resolve_preset(hf_config) -> Optional[Tuple[MoEPreset, Dict[str, object]]]:
    """HF config → (preset, extracted knobs) when the family is known and the
    config actually carries experts; None for dense models."""
    preset = preset_for_model_type(getattr(hf_config, "model_type", None))
    if preset is None:
        return None
    knobs = preset.describe_config(hf_config)
    if knobs["n_experts"] <= 0:
        return None
    return preset, knobs


# --------------------------------------------------------------------------- #
# EP topology math (reference auto_ep_folding.py ParallelFoldingSpec /
# FoldingGroupTables — pure math, no runtime handles)
# --------------------------------------------------------------------------- #

@dataclasses.dataclass(frozen=True)
class EPTopology:
    """Resolved expert-parallel topology over a named mesh.

    world = data × expert × tensor (the MoE-relevant axes); edp (expert-data
    parallel — replicas of each expert shard) = data; etp (expert tensor
    parallel) = tensor. Mirrors reference ``ParallelFoldingSpec`` fields.
    """
    world_size: int
    ep_size: int
    edp_size: int
    etp_size: int

    def validate(self, n_experts: int) -> None:
        if self.ep_size > 1 and n_experts % self.ep_size != 0:
            raise ValueError(
                f"ep_size {self.ep_size} does not divide num_experts "
                f"{n_experts}; choose an 'expert' mesh axis that divides the "
                "expert count")
        if self.ep_size * self.edp_size * self.etp_size != self.world_size:
            raise ValueError(
                f"ep {self.ep_size} × edp {self.edp_size} × etp "
                f"{self.etp_size} != world {self.world_size}")


def ep_topology(mesh_shape: Dict[str, int]) -> EPTopology:
    """Mesh axis sizes → EPTopology. Axes default to 1."""
    data = int(mesh_shape.get("data", 1)) * int(mesh_shape.get("zshard", 1))
    ep = int(mesh_shape.get("expert", 1))
    tp = int(mesh_shape.get("tensor", 1))
    return EPTopology(world_size=data * ep * tp, ep_size=ep, edp_size=data,
                      etp_size=tp)


def fold_group_tables(mesh_shape: Dict[str, int]
                      ) -> Dict[str, Tuple[Tuple[int, ...], ...]]:
    """Rank groups for each parallel dimension, axis order (data, expert,
    tensor) — reference ``FoldingGroupTables`` (tp/dense-dp/ep/edp). On TPU
    these are implied by the mesh (XLA lowers collectives per axis); the
    explicit tables exist for checkpoint-layout tooling and tests.
    """
    topo = ep_topology(mesh_shape)
    d, e, t = topo.edp_size, topo.ep_size, topo.etp_size
    grid = np.arange(topo.world_size).reshape(d, e, t)
    tp_groups = tuple(tuple(grid[i, j, :].tolist())
                      for i, j in itertools.product(range(d), range(e)))
    ep_groups = tuple(tuple(grid[i, :, k].tolist())
                      for i, k in itertools.product(range(d), range(t)))
    edp_groups = tuple(tuple(grid[:, j, k].tolist())
                       for j, k in itertools.product(range(e), range(t)))
    dense_dp = tuple(tuple(grid[:, :, k].reshape(-1).tolist())
                     for k in range(t))
    return {"tp": tp_groups, "ep": ep_groups, "edp": edp_groups,
            "dense_dp": dense_dp}
