"""MoE gating: top-1 / top-2 / top-k with capacity and load-balancing loss.

Parity: reference ``deepspeed/moe/sharded_moe.py`` (``top1gating`` :184,
``top2gating`` :291, ``topkgating`` :375, ``TopKGate`` :452). The reference
builds the same GShard-style dense dispatch/combine tensors; here the whole
gate is a handful of jnp ops with **static capacity** (shape-stable under jit —
XLA requirement, SURVEY.md §7 "Dynamic shapes").

Conventions (GShard/Switch):
* capacity C = max(min_capacity, ceil(T * k * capacity_factor / E))
* choices beyond an expert's capacity are dropped (token falls through the
  residual connection — same semantics as the reference with drop_tokens=True)
* aux (load-balancing) loss = E * Σ_e mean_t(gate_prob_e) * mean_t(mask1_e),
  the Switch/GShard l_aux over the FIRST choice (reference :269).
"""
from __future__ import annotations

import math
from typing import NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp


class GateOutput(NamedTuple):
    combine: jax.Array    # [T, E, C] fp32 — combine weights
    dispatch: jax.Array   # [T, E, C] bool — dispatch mask
    aux_loss: jax.Array   # scalar fp32 — load-balancing loss
    probs: jax.Array      # [T, E] fp32 — softmax gate probabilities
    counts: jax.Array     # [E] int32 — tokens routed per expert (pre-capacity)


class IndexGateOutput(NamedTuple):
    """Index-form gate for the dropless (sort + ragged matmul) dispatch —
    no [T,E,C] one-hot tensors, just who-goes-where and with what weight."""
    weights: jax.Array    # [T, k] fp32 — combine weights per choice
    experts: jax.Array    # [T, k] int32 — selected expert per choice
    aux_loss: jax.Array   # scalar fp32 — load-balancing loss
    probs: jax.Array      # [T, E] fp32 — gate probabilities


def gate_capacity(num_tokens: int, num_experts: int, k: int,
                  capacity_factor: float, min_capacity: int = 4) -> int:
    cap = int(math.ceil(num_tokens * k * capacity_factor / num_experts))
    return max(min_capacity, cap)


def _group_limited_mask(sel: jax.Array, n_group: int, topk_group: int
                        ) -> jax.Array:
    """DeepSeek-V3 node-limited routing (HF ``DeepseekV3TopkRouter.
    get_topk_indices``): score each group by the sum of its top-2 selection
    scores, keep the best ``topk_group`` groups, zero the rest."""
    T, E = sel.shape
    g = sel.reshape(T, n_group, E // n_group)
    group_scores = jnp.sum(jax.lax.top_k(g, 2)[0], axis=-1)        # [T, G]
    thresh = jax.lax.top_k(group_scores, topk_group)[0][:, -1:]     # [T, 1]
    group_mask = (group_scores >= thresh).astype(sel.dtype)         # [T, G]
    return (g * group_mask[:, :, None]).reshape(T, E)


def _gate_scores(logits: jax.Array, score_func: str,
                 select_bias: Optional[jax.Array], n_group: int,
                 topk_group: int, rng: Optional[jax.Array],
                 noise_std: float) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """Shared gate math → (gate_source [T,E], probs [T,E], sel_logits [T,E]).

    ``gate_source`` feeds combine weights; ``sel_logits`` feeds SELECTION only
    (bias / group limitation / noise never leak into combine weights)."""
    logits = logits.astype(jnp.float32)
    if score_func == "sigmoid":
        scores = jax.nn.sigmoid(logits)
        probs = scores / jnp.maximum(
            jnp.sum(scores, axis=-1, keepdims=True), 1e-9)
        gate_source = scores
    elif score_func == "softmax":
        probs = jax.nn.softmax(logits, axis=-1)
        gate_source = probs
    else:
        raise ValueError(f"score_func must be softmax|sigmoid, got {score_func!r}")
    sel_logits = logits
    if select_bias is not None or n_group > 1:
        sel = gate_source
        if select_bias is not None:
            sel = sel + select_bias.astype(jnp.float32)[None, :]
        if n_group > 1:
            sel = _group_limited_mask(sel, n_group, topk_group)
        sel_logits = sel
    if noise_std > 0.0 and rng is not None:
        # reference top1gating noisy_gate_policy='RSample' analog
        sel_logits = sel_logits + jax.random.normal(rng, logits.shape) * noise_std
    return gate_source, probs, sel_logits


def _iter_topk(sel_logits: jax.Array, gate_source: jax.Array, k: int):
    """Iterative argmax top-k (k small + static — unrolled).
    Returns (gates_list: k×[T], idx_list: k×[T] int32, masks: k×[T,E])."""
    masked = sel_logits
    gates_list, idx_list, masks = [], [], []
    E = sel_logits.shape[-1]
    for _ in range(k):
        idx = jnp.argmax(masked, axis=-1)                    # [T]
        mask = jax.nn.one_hot(idx, E, dtype=jnp.float32)     # [T, E]
        gates_list.append(jnp.sum(gate_source * mask, axis=-1))  # [T]
        idx_list.append(idx.astype(jnp.int32))
        masks.append(mask)
        masked = jnp.where(mask.astype(bool), -jnp.inf, masked)
    return gates_list, idx_list, masks


def _aux_loss(probs: jax.Array, mask1: jax.Array) -> jax.Array:
    """Switch/GShard l_aux over the FIRST choice (reference :269)."""
    me = jnp.mean(probs, axis=0)
    ce = jnp.mean(mask1, axis=0)
    return jnp.sum(me * ce) * probs.shape[-1]


def topk_gating_indices(logits: jax.Array, k: int = 2,
                        rng: Optional[jax.Array] = None,
                        noise_std: float = 0.0,
                        normalize: bool = True,
                        score_func: str = "softmax",
                        select_bias: Optional[jax.Array] = None,
                        n_group: int = 1, topk_group: int = 1
                        ) -> IndexGateOutput:
    """Index-form top-k gate for DROPLESS dispatch — identical selection math
    to :func:`topk_gating` but no capacity and no [T,E,C] tensors.

    Since nothing is dropped, ``normalize`` renormalizes the k selected scores
    directly (same value the dense path produces when capacity is generous).
    """
    gate_source, probs, sel_logits = _gate_scores(
        logits, score_func, select_bias, n_group, topk_group, rng, noise_std)
    gates_list, idx_list, masks = _iter_topk(sel_logits, gate_source, k)
    aux = _aux_loss(probs, masks[0])
    gates = jnp.stack(gates_list, axis=1)                    # [T, k]
    experts = jnp.stack(idx_list, axis=1)                    # [T, k]
    if normalize:
        gates = gates / jnp.maximum(
            jnp.sum(gates, axis=1, keepdims=True), 1e-9)
    return IndexGateOutput(gates, experts, aux, probs)


def topk_gating(logits: jax.Array, k: int = 2, capacity_factor: float = 1.25,
                min_capacity: int = 4,
                rng: Optional[jax.Array] = None,
                noise_std: float = 0.0,
                normalize: bool = True,
                score_func: str = "softmax",
                select_bias: Optional[jax.Array] = None,
                n_group: int = 1, topk_group: int = 1) -> GateOutput:
    """Generic top-k gate (k=1 → top1gating, k=2 → top2gating semantics).

    ``score_func``: 'softmax' (GShard/Mixtral/Qwen-MoE) or 'sigmoid'
    (DeepSeek-V3-style: per-expert sigmoid affinities; ``normalize``
    renormalizes the selected scores to sum 1). The aux loss always uses a
    distribution over experts (sigmoid scores are sum-normalized for it).

    DeepSeek-V3 extras: ``select_bias`` [E] (e_score_correction_bias —
    biases expert SELECTION only; combine weights stay the raw scores) and
    ``n_group``/``topk_group`` node-limited routing (selection restricted to
    the best groups).
    """
    T, E = logits.shape
    C = gate_capacity(T, E, k, capacity_factor, min_capacity)
    gate_source, probs, sel_logits = _gate_scores(
        logits, score_func, select_bias, n_group, topk_group, rng, noise_std)

    combine = jnp.zeros((T, E, C), jnp.float32)
    counts_total = jnp.zeros((E,), jnp.int32)
    gates_list, idx_list, masks = _iter_topk(sel_logits, gate_source, k)
    aux = _aux_loss(probs, masks[0])

    # capacity assignment in choice-priority order (1st choices fill first)
    denom = jnp.zeros((T,), jnp.float32)
    per_choice = []
    for i in range(k):
        mask = masks[i]
        locations = jnp.cumsum(mask, axis=0) - 1 + counts_total[None, :].astype(jnp.float32)
        counts_total = counts_total + jnp.sum(mask, axis=0).astype(jnp.int32)
        keep = (locations < C) & (mask > 0)
        mask = jnp.where(keep, mask, 0.0)
        gate_i = gates_list[i] * jnp.sum(mask, axis=-1)      # zero if dropped
        denom = denom + gate_i
        per_choice.append((mask, locations, gates_list[i]))

    for mask, locations, gate_raw in per_choice:
        gate = gate_raw / jnp.maximum(denom, 1e-9) if normalize else gate_raw
        loc_oh = jax.nn.one_hot(locations.astype(jnp.int32), C, dtype=jnp.float32)
        combine = combine + gate[:, None, None] * mask[:, :, None] * loc_oh

    dispatch = combine > 0.0
    counts = jnp.sum(masks[0], axis=0).astype(jnp.int32)
    return GateOutput(combine, dispatch, aux, probs, counts)


def top1_gating(logits: jax.Array, capacity_factor: float = 1.0,
                min_capacity: int = 4, rng: Optional[jax.Array] = None,
                noise_std: float = 0.0) -> GateOutput:
    """Switch-transformer gate (reference ``top1gating`` :184)."""
    return topk_gating(logits, k=1, capacity_factor=capacity_factor,
                       min_capacity=min_capacity, rng=rng, noise_std=noise_std,
                       normalize=False)


def top2_gating(logits: jax.Array, capacity_factor: float = 1.0,
                min_capacity: int = 4) -> GateOutput:
    """GShard top-2 gate (reference ``top2gating`` :291)."""
    return topk_gating(logits, k=2, capacity_factor=capacity_factor,
                       min_capacity=min_capacity, normalize=True)
