"""Tolerant recovery of bench results from committed round artifacts.

The driver records each round as ``BENCH_rNN.json`` = ``{n, cmd, rc, tail,
parsed}`` where ``tail`` is the LAST ~2000 characters of the run's output
and ``parsed`` is the driver's attempt at reading the final JSON line.
When the bench line outgrew the tail window (r03) the line's FRONT was cut
off, ``json.loads`` failed, and three rounds of perf evidence became
``"parsed": null`` — write-only. r04 (rc=124) never printed a line at all.

This module re-ingests those blobs: a complete line upgrades to schema v2
via :func:`upgrade_legacy_result`; a truncated line goes through a
fragment scanner (:func:`scan_outermost`) that walks every ``"key":``
position, ``raw_decode``\\ s the value, and keeps the outermost decodable
fragments — recovering whole suite entries, per-phase tables, and trailing
top-level fields even when the headline itself is gone. Keys whose front
was truncated (``dam_bert_large_fp16`` for
``zero2_fusedadam_bert_large_fp16``) are resolved by unique suffix match.

Everything here is stdlib-only and never raises on malformed input — a
recovery parser that crashes on the garbage it exists to read would be
the original bug with extra steps.
"""
from __future__ import annotations

import json
import os
import re
from typing import Any, Dict, List, Optional, Tuple

from deepspeed_tpu.bench.schema import (
    RECORD_VERSION,
    SCHEMA_VERSION,
    SUPPORTED_SCHEMA_VERSIONS,
    normalize_entry_row,
    validate_result,
)

# top-level keys of the v1 flat result that belong to the HEADLINE row
HEADLINE_KEYS = (
    "metric", "value", "unit", "value_band", "vs_baseline",
    "baseline_tokens_per_sec", "baseline_citation",
    "model_tflops_per_sec_chip", "mfu", "peak_tflops",
    "matmul_ceiling_tflops", "vs_ceiling", "hardware_tflops_per_sec_chip",
    "vs_ceiling_hardware", "window_samples_tokens_per_sec", "loss",
    "n_chips", "tokens_per_sec_chip", "error",
)

#: every suite-entry name that has ever appeared in a committed round —
#: the resolver for exact and truncated-suffix matches. (Hardcoded rather
#: than imported from bench.py: bench.py imports THIS package, and the
#: committed history must stay readable even after entries are renamed.)
KNOWN_ENTRY_NAMES = (
    "headline",
    "zero3_llama_3b_adafactor",
    "fastgen_paged_splitfuse_gpt2",
    "fastgen_sla_poisson_gpt2",
    "moe_ulysses_moe_350m_bf16",
    "moe_1b_large_experts",
    "zero2_fusedadam_bert_large_fp16",
    "zero3_llama_750m_bf16",
    "autotp_inference_gpt2_generate",
    "offload_param_memory",
    "autotune_smoke",
    "comm_cpu_mesh_world8",
    "comm_bw_onchip",
    "comm_bw",
    "comm_busbw_cpu_mesh_world8",
    "pipeline_1f1b_cpu_mesh",
    "converge_real_text",
    "stability_2k_cpu_mesh",
)

_EXTRA_TOP_KEYS = ("budget_s", "total_runtime_s", "entry_elapsed_s",
                   "best_mfu_row", "gate", "schema_version")

_KEY_RE = re.compile(r'"((?:[^"\\]|\\.)*)"\s*:\s*')
_LEAD_KEY_RE = re.compile(r'\s*([A-Za-z0-9_.\-/]*)"\s*:\s*')

#: headline keys that ALSO appear inside train-entry rows — on a
#: front-truncated line these are only attributable to the headline once
#: an unambiguous headline key has anchored the region (otherwise they
#: are some cut-off entry's internals masquerading as top-level)
AMBIGUOUS_HEADLINE_KEYS = frozenset(
    {"tokens_per_sec_chip", "model_tflops_per_sec_chip",
     "hardware_tflops_per_sec_chip", "mfu", "loss", "error",
     "window_samples_tokens_per_sec"})


def scan_outermost(text: str) -> List[Tuple[str, Any, int, int]]:
    """All outermost decodable ``"key": <value>`` fragments in ``text`` as
    ``(key, value, start, end)``. A fragment nested inside an
    already-decoded value is skipped (its parent carries it); fragments
    whose value is itself truncated simply fail to decode, letting their
    complete CHILDREN surface as outermost instead.

    A front-truncated line usually starts mid-key (``dam_bert_large_fp16":
    {...`` in BENCH_r03) — the opening quote is gone so the normal pattern
    can't see it, but the VALUE is complete and recoverable; it surfaces
    as a first fragment with the truncated key."""
    dec = json.JSONDecoder()
    out: List[Tuple[str, Any, int, int]] = []
    covered = -1
    lead = _LEAD_KEY_RE.match(text)
    if lead and not text.lstrip().startswith("{"):
        try:
            val, end = dec.raw_decode(text, lead.end())
            out.append((lead.group(1), val, 0, end))
            covered = end
        except ValueError:
            pass
    for m in _KEY_RE.finditer(text):
        if m.start() < covered:
            continue
        try:
            val, end = dec.raw_decode(text, m.end())
        except ValueError:
            continue
        out.append((m.group(1), val, m.start(), end))
        covered = end
    return out


def _match_entry_name(key: str, val: Any) -> Optional[str]:
    """Resolve a (possibly front-truncated) fragment key to a known suite
    entry name. Rows are dicts/lists; scalars are never entries."""
    if not isinstance(val, (dict, list)):
        return None
    if key in KNOWN_ENTRY_NAMES:
        return key
    if len(key) < 6:
        return None
    hits = [n for n in KNOWN_ENTRY_NAMES if n.endswith(key)]
    return hits[0] if len(hits) == 1 else None


def _match_headline_key(key: str, val: Any) -> Optional[str]:
    if key in HEADLINE_KEYS:
        return key
    if len(key) < 4 or isinstance(val, (dict, list)):
        return None
    hits = [k for k in HEADLINE_KEYS if k.endswith(key)]
    return hits[0] if len(hits) == 1 else None


def upgrade_legacy_result(parsed: Dict[str, Any]) -> Dict[str, Any]:
    """Upgrade a complete v1 (flat) bench result to schema v2. v2 input is
    returned unchanged. Idempotent."""
    if parsed.get("schema_version") in SUPPORTED_SCHEMA_VERSIONS:
        return parsed
    rest = dict(parsed)
    headline: Dict[str, Any] = {}
    for key in HEADLINE_KEYS:
        if key in rest:
            headline[key] = rest.pop(key)
    # v1 embedded the headline row's telemetry context at top level
    for key in ("telemetry", "trace_phases", "memory"):
        if key in rest:
            headline[key] = rest.pop(key)
    entries: Dict[str, Any] = {}
    elapsed = rest.pop("entry_elapsed_s", None) or {}
    for name, row in (rest.pop("configs", None) or {}).items():
        entries[name] = normalize_entry_row(row, elapsed.get(name))
    if "comm_bw" in rest:
        entries["comm_bw"] = normalize_entry_row(rest.pop("comm_bw"))
    best = rest.pop("best_mfu_row", None)
    if best is not None:
        headline["best_row"] = best
    result: Dict[str, Any] = {"schema_version": SCHEMA_VERSION}
    for key in ("metric", "value", "unit", "vs_baseline"):
        if key in headline:
            result[key] = headline[key]
    result["headline"] = headline
    result["entries"] = entries
    for key in ("budget_s", "total_runtime_s"):
        if key in rest:
            result[key] = rest.pop(key)
    if rest:
        result["extras"] = rest
    return result


def recover_from_text(text: str) -> Tuple[Dict[str, Any], List[str]]:
    """Recover a (possibly partial) schema-v2 result from raw bench output
    — a full stdout log, or a driver tail blob with the line's front cut
    off. Returns ``(result, notes)``; ``notes`` records what had to be
    guessed or dropped."""
    notes: List[str] = []
    lines = [ln for ln in (text or "").splitlines() if ln.strip()]
    # complete line first: the last parseable JSON-object line wins
    for line in reversed(lines):
        line = line.strip()
        if not line.startswith("{"):
            continue
        try:
            obj = json.loads(line)
        except ValueError:
            continue
        if isinstance(obj, dict) and ("metric" in obj
                                      or "schema_version" in obj):
            return upgrade_legacy_result(obj), notes
    # truncated line: the most JSON-ish line carries the fragments
    candidate = max(lines, key=lambda ln: ln.count('":'), default="")
    frags = scan_outermost(candidate)
    front_truncated = not candidate.lstrip().startswith("{")
    # on a front-truncated line, the true top-level headline scalars lived
    # at the cut-off FRONT; ambiguous keys found mid-line belong to some
    # truncated entry until an unambiguous headline key anchors the region
    headline_anchored = not front_truncated
    seen_entry = False
    headline: Dict[str, Any] = {}
    entries: Dict[str, Any] = {}
    extras: Dict[str, Any] = {}
    for key, val, _start, _end in frags:
        if key == "configs" and isinstance(val, dict):
            for name, row in val.items():
                entries[name] = normalize_entry_row(row)
            seen_entry = True
            continue
        entry_name = _match_entry_name(key, val)
        if entry_name is not None:
            entries[entry_name] = normalize_entry_row(val)
            seen_entry = True
            if entry_name != key:
                notes.append(f"entry key {key!r} resolved to "
                             f"{entry_name!r} by suffix")
            continue
        if key in ("telemetry", "trace_phases") and isinstance(val, dict):
            headline[key] = val
            continue
        if key == "best_mfu_row" and isinstance(val, dict):
            headline["best_row"] = val
            continue
        if key in _EXTRA_TOP_KEYS:
            extras[key] = val
            continue
        head_key = _match_headline_key(key, val)
        if head_key is not None:
            if head_key in AMBIGUOUS_HEADLINE_KEYS \
                    and (not headline_anchored or seen_entry):
                notes.append(f"fragment {key!r} dropped: inside a "
                             "truncated entry, not attributable to the "
                             "headline")
                continue
            headline[head_key] = val
            if head_key not in AMBIGUOUS_HEADLINE_KEYS:
                headline_anchored = True
            if head_key != key:
                notes.append(f"headline key {key!r} resolved to "
                             f"{head_key!r} by suffix")
            continue
        notes.append(f"unrecognized fragment {key!r} dropped")
    if not frags:
        notes.append("no JSON fragments found in output")
    result: Dict[str, Any] = {"schema_version": SCHEMA_VERSION}
    for key in ("metric", "value", "unit", "vs_baseline"):
        if key in headline:
            result[key] = headline[key]
    result["headline"] = headline
    result["entries"] = entries
    elapsed = extras.pop("entry_elapsed_s", None) or {}
    for name, secs in elapsed.items() if isinstance(elapsed, dict) else ():
        if name in entries and "elapsed_s" not in entries[name]:
            entries[name]["elapsed_s"] = secs
    for key in ("budget_s", "total_runtime_s"):
        if key in extras:
            result[key] = extras.pop(key)
    if extras:
        result["extras"] = extras
    return result, notes


def round_id_from_path(path: str) -> str:
    m = re.search(r"(r\d+)", os.path.basename(path))
    return m.group(1) if m else os.path.basename(path)


def recover_round_file(path: str) -> Dict[str, Any]:
    """Re-ingest one committed ``BENCH_rNN.json`` driver artifact into a
    bench_history record. Uses ``parsed`` when the driver managed to read
    the line; otherwise recovers what the tail still holds. An artifact
    that is itself corrupt JSON (the damage class this parser exists
    for) degrades to raw-text recovery, never a raise."""
    with open(path, encoding="utf-8") as f:
        text = f.read()
    round_id = round_id_from_path(path)
    source = os.path.basename(path)
    try:
        data = json.loads(text)
    except ValueError:
        data = None
    if not isinstance(data, dict):
        result, notes = recover_from_text(text)
        notes.append("artifact not a JSON object; recovered from raw text")
        return {
            "record_version": RECORD_VERSION,
            "round": round_id,
            "source": source,
            "rc": None,
            "recovered": True,
            "complete": not validate_result(result),
            "result": result,
            "notes": notes,
        }
    return recover_round_data(data, round_id, source)


def recover_round_data(data: Dict[str, Any], round_id: str,
                       source: str) -> Dict[str, Any]:
    """Same as :func:`recover_round_file` for an already-loaded artifact
    dict (``{n, cmd, rc, tail, parsed}``)."""
    notes: List[str] = []
    rc = data.get("rc")
    parsed = data.get("parsed")
    if isinstance(parsed, dict):
        result = upgrade_legacy_result(parsed)
        recovered = False
    else:
        result, notes = recover_from_text(data.get("tail") or "")
        recovered = True
        if rc not in (0, None):
            notes.append(f"round exited rc={rc}")
    complete = not validate_result(result)
    return {
        "record_version": RECORD_VERSION,
        "round": round_id,
        "source": source,
        "rc": rc,
        "recovered": recovered,
        "complete": complete,
        "result": result,
        "notes": notes,
    }


def recover_rounds(root: str) -> List[Dict[str, Any]]:
    """Recover every ``BENCH_r*.json`` under ``root``, ordered by round."""
    paths = sorted(
        os.path.join(root, name) for name in os.listdir(root)
        if re.fullmatch(r"BENCH_r\d+\.json", name))
    return [recover_round_file(p) for p in paths]
