"""Append-only bench history store: ``bench_history/history.jsonl``.

One JSONL line per bench round. Records are written by ``bench.py`` after
a schema-valid run, and by ``python -m deepspeed_tpu.bench recover`` when
re-ingesting committed ``BENCH_rNN.json`` artifacts. The file is
append-only by convention AND by API — there is no rewrite call; a bad
record is superseded by appending a corrected one with the same round id
(the LAST record for a round wins on read).

Reading is tolerant: a corrupt line is skipped with a note, never a
crash — history must stay readable after a partial append (preempted
writer, merge damage).

``BENCH_HISTORY`` overrides the location (a directory containing
``history.jsonl``, or a file path ending in ``.jsonl``); the default is
``<repo root>/bench_history/history.jsonl``.
"""
from __future__ import annotations

import json
import os
import time
from typing import Any, Dict, List, Optional, Tuple

from deepspeed_tpu.bench.schema import RECORD_VERSION, is_number

HISTORY_DIRNAME = "bench_history"
HISTORY_FILENAME = "history.jsonl"


def default_repo_root() -> str:
    """The checkout root: parent of the ``deepspeed_tpu`` package dir."""
    pkg = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    return os.path.dirname(pkg)


def history_path(path: Optional[str] = None) -> str:
    """Resolve the history file path from an explicit argument, the
    ``BENCH_HISTORY`` env var, or the repo default — in that order. A
    directory argument means ``<dir>/history.jsonl``."""
    path = path or os.environ.get("BENCH_HISTORY") or os.path.join(
        default_repo_root(), HISTORY_DIRNAME, HISTORY_FILENAME)
    if path.endswith(".jsonl"):
        return path
    return os.path.join(path, HISTORY_FILENAME)


def load_history(path: Optional[str] = None
                 ) -> Tuple[List[Dict[str, Any]], List[str]]:
    """Read all records (file order) plus notes for skipped lines."""
    path = history_path(path)
    records: List[Dict[str, Any]] = []
    notes: List[str] = []
    if not os.path.exists(path):
        return records, notes
    with open(path, encoding="utf-8") as f:
        for i, line in enumerate(f, 1):
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except ValueError:
                notes.append(f"{path}:{i}: unparseable line skipped")
                continue
            if isinstance(rec, dict) and isinstance(rec.get("result"), dict):
                records.append(rec)
            else:
                notes.append(f"{path}:{i}: not a bench record, skipped")
    return records, notes


def append_record(record: Dict[str, Any],
                  path: Optional[str] = None) -> str:
    """Append one record as a single JSONL line; returns the path."""
    path = history_path(path)
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    with open(path, "a", encoding="utf-8") as f:
        f.write(json.dumps(record, sort_keys=False) + "\n")
    return path


def record_from_result(result: Dict[str, Any],
                       round_id: Optional[str] = None,
                       source: str = "bench.py",
                       rc: int = 0) -> Dict[str, Any]:
    """Wrap a fresh schema-v2 result in a history record."""
    return {
        "record_version": RECORD_VERSION,
        "round": round_id or os.environ.get("BENCH_ROUND") or "local",
        "source": source,
        "rc": rc,
        "recovered": False,
        "complete": True,
        # export timestamp for ordering fresh local records between
        # committed rounds (never used as an interval)
        "recorded_unix_s": round(time.time(), 3),  # dslint: disable=wall-clock
        "result": result,
        "notes": [],
    }


def _has_comparables(record: Dict[str, Any]) -> bool:
    result = record.get("result") or {}
    head = result.get("headline") or {}
    if is_number(head.get("value")) and head.get("value", 0) > 0:
        return True
    entries = result.get("entries") or {}
    return any(isinstance(e, dict) and e.get("metrics")
               for e in entries.values())


def record_platform(record: Dict[str, Any]) -> Optional[str]:
    head = (record.get("result") or {}).get("headline") or {}
    plat = head.get("platform")
    return plat if isinstance(plat, str) else None


def latest_record(records: Optional[List[Dict[str, Any]]] = None,
                  path: Optional[str] = None,
                  comparable_only: bool = True,
                  exclude_failed: bool = False,
                  platform: Optional[str] = None,
                  metric: Optional[str] = None,
                  predicate: Optional[Any] = None
                  ) -> Optional[Dict[str, Any]]:
    """The most recent record (file order; last line wins), optionally
    restricted to records that carry something diffable — a recovered
    r04-style husk (rc=124, nothing parsed) can't be a gate baseline.

    ``exclude_failed`` skips records whose run exited nonzero (its own
    gate regression or a driver timeout) — a failed round is evidence
    but not a baseline. ``platform`` / ``metric`` skip records that
    declare a DIFFERENT platform or headline metric — a recorded
    BENCH_MODEL=tiny what-if must not become the gpt2 trajectory's
    baseline (its incomparable headline would silently disarm the
    headline gate). Records without one — all legacy rounds — match
    anything. ``predicate`` is an extra per-record filter (e.g. the
    gate's gate-grade checks)."""
    if records is None:
        records, _ = load_history(path)
    for rec in reversed(records):
        if comparable_only and not _has_comparables(rec):
            continue
        if exclude_failed and rec.get("rc") not in (0, None):
            continue
        rec_plat = record_platform(rec)
        if platform and rec_plat and rec_plat != platform:
            continue
        rec_metric = ((rec.get("result") or {}).get("headline")
                      or {}).get("metric")
        if metric and isinstance(rec_metric, str) and rec_metric != metric:
            continue
        if predicate is not None and not predicate(rec):
            continue
        return rec
    return None


def record_for_round(round_id: str,
                     records: Optional[List[Dict[str, Any]]] = None,
                     path: Optional[str] = None
                     ) -> Optional[Dict[str, Any]]:
    """Last record carrying ``round_id`` (later appends supersede)."""
    if records is None:
        records, _ = load_history(path)
    for rec in reversed(records):
        if rec.get("round") == round_id:
            return rec
    return None
