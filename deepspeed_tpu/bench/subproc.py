"""One-JSON-line child processes: the bench entry isolation contract.

Every bench suite entry — and now every autotune confirmation window —
runs in its OWN child process so an XLA OOM/abort in a deliberately
HBM-tight config can't take the parent's JSON artifact down with it,
and a hung one costs its own timeout, not the whole run. The child's
contract: print exactly ONE JSON object as its LAST stdout line
(logging goes to stderr); the parent parses backwards from the tail so
stray stdout above it is harmless.

Extracted from bench.py's ``_run_entry_subprocess`` (PR 9) so the plan
engine's measured-confirmation windows reuse the identical machinery —
own session + process-group SIGKILL on timeout (children that spawn
grandchildren must not leave an orphan training run burning the chip
under later candidates).
"""
from __future__ import annotations

import json
import os
import signal
import subprocess
import sys
from typing import Dict, List, Optional


def run_json_subprocess(argv: List[str], timeout: float,
                        env: Optional[Dict[str, str]] = None) -> dict:
    """Run ``argv`` as a child; return its last stdout JSON line.

    On timeout the child's whole process GROUP is SIGKILLed and an
    ``{"error": ...}`` dict comes back — a slow child costs ITS row,
    never the caller's artifact. On a non-JSON exit the stderr tail
    rides in the error string (first 180 chars) for the artifact's
    forensics. Never raises on child failure.
    """
    proc = subprocess.Popen(
        argv, stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
        start_new_session=True,
        env=dict(os.environ, **env) if env else None)
    try:
        stdout, stderr = proc.communicate(timeout=timeout)
    except subprocess.TimeoutExpired:
        try:
            os.killpg(proc.pid, signal.SIGKILL)
        except (ProcessLookupError, PermissionError):
            pass
        proc.wait()
        return {"error": f"entry timed out after {int(timeout)}s"}
    for line in reversed((stdout or "").strip().splitlines()):
        try:
            return json.loads(line)
        except json.JSONDecodeError:
            continue
    tail = (stderr or "").strip().splitlines()[-1:] or ["no output"]
    return {"error": f"rc={proc.returncode}: {tail[0][:180]}"}


def run_entry_subprocess(script: str, name: str, timeout: float) -> dict:
    """bench.py's per-entry child: ``python <script> --entry <name>``."""
    return run_json_subprocess(
        [sys.executable, os.path.abspath(script), "--entry", name],
        timeout)
