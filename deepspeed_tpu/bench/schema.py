"""Versioned bench-result schema + stdlib validator.

BENCH_r03–r05 carried ``"parsed": null`` because the headline-metric
extractor silently broke when the bench JSON grew past the driver's
2000-char tail window. The fix is structural, not a regex: ``bench.py``
now emits ``schema_version`` 2 with a top-level ``headline`` block and
normalized per-entry rows, VALIDATES the result before printing (an
invalid result is a refusal, not a recorded artifact), and appends the
full record to ``bench_history/`` so no future truncation can eat the
trajectory again.

Schema v2 (what ``python bench.py`` prints as its one JSON line)::

    {
      "schema_version": 2,
      # driver contract — unchanged since r01, always top-level:
      "metric": str, "value": number, "unit": str, "vs_baseline": number,
      "headline": {
        "metric": str, "value": number, "unit": str,
        "vs_baseline": number, "mfu": number, ...,   # full headline row
        "trace_phases": {phase: {count, total_s, p50_s, p95_s, p99_s}},
        "memory": {"peak_host_rss_mb": number, "device": {...}},
        "best_row": {...},            # best-MFU row across the suite
        "error": str,                 # only when the headline run failed
      },
      "entries": {
        name: {
          "metrics": {...},           # the entry's measured row
          "trace_phases": {...},      # per-phase span percentiles
          "telemetry": {...},         # registry snapshot (optional)
          "memory": {...},            # peak host RSS + device stats
          "elapsed_s": number,
          "skipped_reason": str,      # e.g. "budget (90s left < 120s floor)"
          "error": str,
        }, ...
      },
      "gate": {...},                  # regression-gate verdict (optional)
      "budget_s": number, "total_runtime_s": number, ...
    }

Every entry must carry at least one of ``metrics`` / ``skipped_reason`` /
``error`` — a row can be measured, explicitly skipped, or failed, but it
can never be silently absent-but-present. ``validate_result`` returns a
list of human-readable errors (empty = valid); it never raises on weird
input.

Schema v2.1 adds two OPTIONAL per-entry (and headline) keys next to
``trace_phases`` — older v2 records, which simply don't carry them, load
and validate unchanged::

    "comms": {              # compiled-collective ledger totals
      "program": str, "total_bytes": int, "unparsed": int,
      "async_pairs": int,   # matched -start/-done pairs (0 = sync-only)
      "link_gbps": number,
      "by_kind": {kind: {"count": int, "bytes": int, "bus_bytes": number,
                         "predicted_busbw_gbps": number}},
    },
    "overlap_fraction": number in [0, 1],

``bench-diff`` compares ``comms`` byte totals lower-is-better (quantized
collectives shrink wire bytes) and ``overlap_fraction`` higher-is-better.

Schema v2.2 adds one more OPTIONAL per-entry (and headline) key — v2/v2.1
records load and validate unchanged::

    "guardian": {           # training-guardian fault accounting
      "skipped_steps": int, # device-side non-finite skip counter
      "anomalies": int, "rollbacks": int, "quarantined_batches": int,
    },

All guardian counts diff lower-is-better, so ``bench-diff`` flags an
anomaly-ridden round (a 0 → nonzero move surfaces as an explicit
zero-baseline row).

Schema v2.4 adds one more OPTIONAL per-entry key — earlier records load
and validate unchanged::

    "elastic": {            # world-elastic resume accounting
      "from_world": int, "to_world": int,   # source/destination dp world
      "convert_s": number,  # native → universal conversion wall time
      "reshard_s": number,  # load_universal_checkpoint wall time
    },

carried by the ``elastic_resume`` lane; ``bench-diff`` treats the wall
times lower-is-better.

Schema v2.5 adds one more OPTIONAL per-entry key — earlier records load
and validate unchanged::

    "tenants": {            # per-tenant QoS accounting (multi-tenant lanes)
      name: {
        "submitted": int,   # requests this tenant submitted to the fleet
        "outcomes": {state: int},   # terminal-outcome counts; their sum
                                    # must equal "submitted" exactly
        "ttft_p50_s": number, "ttft_p99_s": number,   # optional
      }, ...
    },

carried by the ``fleet_sla_multitenant_gpt2`` lane. The per-tenant
reconciliation (submitted == Σ outcomes) is validated structurally here —
a tenants block that doesn't reconcile is an invalid result.

Schema v2.6 adds one more OPTIONAL per-entry key — earlier records load
and validate unchanged::

    "slo": {                # fleet-observatory SLO + goodput accounting
      "objectives": [ {"name": str, "metric": str, ...}, ... ],
      "verdicts": {name: "ok"|"firing"|"fired_and_cleared"|"no_data"},
      "worst_burn_rate": number,        # >= 0
      "goodput_tokens": int,            # tokens computed AND delivered
      "wasted_tokens": {reason: int},   # reasons from WASTE_REASONS
      "computed_tokens": int,  # MUST equal goodput + Σ wasted exactly
      "goodput_fraction": number|null,
      "prefix_hit_rate": number|null,   # optional, in [0, 1]
    },

embedded by the fleet lanes (opt out with ``BENCH_SLO=0``). The goodput
reconciliation (goodput + Σ wasted == computed) is validated EXACTLY —
an slo block that doesn't reconcile is an invalid result, same contract
as the tenants block.
"""
from __future__ import annotations

from typing import Any, Dict, List, Optional

SCHEMA_VERSION = 2.6

#: versions validate_result accepts — v2 records predate the ``comms``
#: block, v2.1 the ``guardian`` block, v2.2 the ``plan`` block
#: (autotune plan-cache verdict per entry), v2.3 the ``elastic`` block
#: (world-elastic resume wall times), v2.4 the ``tenants`` block
#: (per-tenant QoS accounting), v2.5 the ``slo`` block (fleet-observatory
#: SLO verdicts + goodput reconciliation); otherwise shape-identical
SUPPORTED_SCHEMA_VERSIONS = (2, 2.1, 2.2, 2.3, 2.4, 2.5, 2.6)

#: history records (one JSONL line each) wrap a result with provenance
RECORD_VERSION = 1

# keys an entry row may carry besides the measured metrics; everything
# else inside an entry dict is treated as a metric
ENTRY_STRUCTURAL_KEYS = ("metrics", "trace_phases", "telemetry", "memory",
                         "elapsed_s", "skipped_reason", "error", "note",
                         "comms", "overlap_fraction", "guardian", "plan",
                         "elastic", "tenants", "slo")

_PHASE_STAT_KEYS = ("count", "total_s", "p50_s", "p95_s", "p99_s")


def is_number(x: Any) -> bool:
    """JSON number: int/float but NOT bool (bool subclasses int)."""
    return isinstance(x, (int, float)) and not isinstance(x, bool)


def _is_jsonable(x: Any, depth: int = 0) -> bool:
    if depth > 12:
        return False
    if x is None or isinstance(x, (str, bool, int, float)):
        return True
    if isinstance(x, (list, tuple)):
        return all(_is_jsonable(v, depth + 1) for v in x)
    if isinstance(x, dict):
        return all(isinstance(k, str) and _is_jsonable(v, depth + 1)
                   for k, v in x.items())
    return False


def validate_trace_phases(phases: Any, where: str) -> List[str]:
    errs: List[str] = []
    if not isinstance(phases, dict):
        return [f"{where}: trace_phases must be a dict, got "
                f"{type(phases).__name__}"]
    for name, stats in phases.items():
        if not isinstance(stats, dict):
            errs.append(f"{where}: trace_phases[{name!r}] must be a dict")
            continue
        for key in _PHASE_STAT_KEYS:
            if key not in stats:
                errs.append(f"{where}: trace_phases[{name!r}] missing "
                            f"{key!r}")
            elif not is_number(stats[key]):
                errs.append(f"{where}: trace_phases[{name!r}][{key!r}] "
                            "must be a number")
    return errs


def validate_memory(mem: Any, where: str) -> List[str]:
    errs: List[str] = []
    if not isinstance(mem, dict):
        return [f"{where}: memory must be a dict"]
    if "peak_host_rss_mb" in mem and not is_number(mem["peak_host_rss_mb"]):
        errs.append(f"{where}: memory.peak_host_rss_mb must be a number")
    for key in ("device_peak_bytes", "temp_bytes"):
        # compiled-program memory_analysis legs (memlint's bench
        # satellite): optional, but numbers when present
        if key in mem and not is_number(mem[key]):
            errs.append(f"{where}: memory.{key} must be a number")
    if "device" in mem and mem["device"] is not None \
            and not isinstance(mem["device"], dict):
        errs.append(f"{where}: memory.device must be a dict or null")
    return errs


def validate_comms(comms: Any, where: str) -> List[str]:
    """Validate a v2.1 ``comms`` block (ledger totals by collective kind)."""
    if not isinstance(comms, dict):
        return [f"{where}: comms must be a dict"]
    errs: List[str] = []
    for key in ("total_bytes", "unparsed", "async_pairs"):
        if key in comms and (not isinstance(comms[key], int)
                             or isinstance(comms[key], bool)
                             or comms[key] < 0):
            errs.append(f"{where}: comms.{key} must be a non-negative int")
    by_kind = comms.get("by_kind")
    if by_kind is None:
        errs.append(f"{where}: comms.by_kind must be present (may be {{}})")
    elif not isinstance(by_kind, dict):
        errs.append(f"{where}: comms.by_kind must be a dict")
    else:
        for kind, row in by_kind.items():
            if not isinstance(row, dict):
                errs.append(f"{where}: comms.by_kind[{kind!r}] must be a "
                            "dict")
                continue
            for key in ("count", "bytes"):
                if not isinstance(row.get(key), int) \
                        or isinstance(row.get(key), bool) \
                        or row[key] < 0:
                    errs.append(f"{where}: comms.by_kind[{kind!r}].{key} "
                                "must be a non-negative int")
            if "bus_bytes" in row and not is_number(row["bus_bytes"]):
                errs.append(f"{where}: comms.by_kind[{kind!r}].bus_bytes "
                            "must be a number")
    return errs


def validate_guardian(block: Any, where: str) -> List[str]:
    """Validate a v2.2 ``guardian`` block (fault accounting counters)."""
    if not isinstance(block, dict):
        return [f"{where}: guardian must be a dict"]
    errs: List[str] = []
    for key, val in block.items():
        if not isinstance(val, int) or isinstance(val, bool) or val < 0:
            errs.append(f"{where}: guardian.{key} must be a non-negative "
                        "int")
    return errs


#: engine plan-cache statuses a v2.3 ``plan`` block may carry
PLAN_STATUSES = ("disabled", "miss", "hit", "stale")


def validate_plan_block(block: Any, where: str) -> List[str]:
    """Validate a v2.3 ``plan`` block: the entry engine's autotune
    plan-cache verdict (``engine._plan_status``) plus the plan key it
    looked up — per ROW, so a history round shows which lanes ran under
    a cached plan and which planned from scratch."""
    if not isinstance(block, dict):
        return [f"{where}: plan must be a dict"]
    errs: List[str] = []
    status = block.get("status")
    if status not in PLAN_STATUSES:
        errs.append(f"{where}: plan.status must be one of "
                    f"{PLAN_STATUSES}, got {status!r}")
    key = block.get("key")
    if key is not None and not isinstance(key, str):
        errs.append(f"{where}: plan.key must be a string or absent")
    return errs


def validate_elastic_block(block: Any, where: str) -> List[str]:
    """Validate a v2.4 ``elastic`` block: world-elastic resume accounting
    (the ``elastic_resume`` lane) — source/destination worlds plus the
    conversion and reshard-load wall times."""
    if not isinstance(block, dict):
        return [f"{where}: elastic must be a dict"]
    errs: List[str] = []
    for key in ("from_world", "to_world"):
        val = block.get(key)
        if not isinstance(val, int) or isinstance(val, bool) or val <= 0:
            errs.append(f"{where}: elastic.{key} must be a positive int")
    for key in ("convert_s", "reshard_s"):
        if key in block and (not is_number(block[key]) or block[key] < 0):
            errs.append(f"{where}: elastic.{key} must be a non-negative "
                        "number")
    return errs


def validate_tenants_block(block: Any, where: str) -> List[str]:
    """Validate a v2.5 ``tenants`` block: per-tenant submitted/outcome
    counts (which must reconcile exactly — submitted == Σ outcomes) plus
    optional TTFT percentiles."""
    if not isinstance(block, dict):
        return [f"{where}: tenants must be a dict"]
    errs: List[str] = []
    for name, row in block.items():
        if not isinstance(row, dict):
            errs.append(f"{where}: tenants[{name!r}] must be a dict")
            continue
        sub = row.get("submitted")
        if not isinstance(sub, int) or isinstance(sub, bool) or sub < 0:
            errs.append(f"{where}: tenants[{name!r}].submitted must be a "
                        "non-negative int")
            continue
        outcomes = row.get("outcomes")
        if not isinstance(outcomes, dict):
            errs.append(f"{where}: tenants[{name!r}].outcomes must be a "
                        "dict")
            continue
        total = 0
        bad = False
        for state, n in outcomes.items():
            if not isinstance(n, int) or isinstance(n, bool) or n < 0:
                errs.append(f"{where}: tenants[{name!r}].outcomes"
                            f"[{state!r}] must be a non-negative int")
                bad = True
                continue
            total += n
        if not bad and total != sub:
            errs.append(f"{where}: tenants[{name!r}] does not reconcile: "
                        f"submitted={sub} but outcomes sum to {total}")
        for key in ("ttft_p50_s", "ttft_p99_s"):
            if key in row and row[key] is not None \
                    and (not is_number(row[key]) or row[key] < 0):
                errs.append(f"{where}: tenants[{name!r}].{key} must be a "
                            "non-negative number or null")
    return errs


#: waste attributions a v2.6 ``slo`` block may carry — mirrors
#: ``deepspeed_tpu.serving.observatory.WASTE_REASONS`` (kept literal
#: here so validating a result never imports the serving stack)
SLO_WASTE_REASONS = ("hedge_lost", "failover_replay", "evicted", "shed")

_SLO_VERDICTS = ("ok", "firing", "fired_and_cleared", "no_data")


def validate_slo_block(block: Any, where: str) -> List[str]:
    """Validate a v2.6 ``slo`` block. The goodput reconciliation is
    exact: goodput_tokens + Σ wasted_tokens == computed_tokens, same
    zero-tolerance contract as the tenants block."""
    if not isinstance(block, dict):
        return [f"{where}: slo must be a dict"]
    errs: List[str] = []
    objectives = block.get("objectives", [])
    if not isinstance(objectives, list):
        errs.append(f"{where}: slo.objectives must be a list")
    else:
        for i, obj in enumerate(objectives):
            if not isinstance(obj, dict) or not isinstance(
                    obj.get("name"), str) or not obj.get("name"):
                errs.append(f"{where}: slo.objectives[{i}] must be a dict "
                            "with a non-empty 'name'")
    verdicts = block.get("verdicts", {})
    if not isinstance(verdicts, dict):
        errs.append(f"{where}: slo.verdicts must be a dict")
    else:
        for name, v in verdicts.items():
            if v not in _SLO_VERDICTS:
                errs.append(f"{where}: slo.verdicts[{name!r}] must be one "
                            f"of {_SLO_VERDICTS}, got {v!r}")
    if "worst_burn_rate" in block and (
            not is_number(block["worst_burn_rate"])
            or block["worst_burn_rate"] < 0):
        errs.append(f"{where}: slo.worst_burn_rate must be a non-negative "
                    "number")
    counts: Dict[str, int] = {}
    for key in ("goodput_tokens", "computed_tokens"):
        val = block.get(key)
        if not isinstance(val, int) or isinstance(val, bool) or val < 0:
            errs.append(f"{where}: slo.{key} must be a non-negative int")
        else:
            counts[key] = val
    wasted = block.get("wasted_tokens")
    wasted_total: Optional[int] = None
    if not isinstance(wasted, dict):
        errs.append(f"{where}: slo.wasted_tokens must be a dict")
    else:
        wasted_total = 0
        for reason, n in wasted.items():
            if reason not in SLO_WASTE_REASONS:
                errs.append(f"{where}: slo.wasted_tokens[{reason!r}] is "
                            f"not a known reason {SLO_WASTE_REASONS}")
                wasted_total = None
                continue
            if not isinstance(n, int) or isinstance(n, bool) or n < 0:
                errs.append(f"{where}: slo.wasted_tokens[{reason!r}] must "
                            "be a non-negative int")
                wasted_total = None
                continue
            if wasted_total is not None:
                wasted_total += n
    if ("goodput_tokens" in counts and "computed_tokens" in counts
            and wasted_total is not None):
        total = counts["goodput_tokens"] + wasted_total
        if total != counts["computed_tokens"]:
            errs.append(
                f"{where}: slo does not reconcile: goodput + wasted = "
                f"{total} but computed_tokens={counts['computed_tokens']}")
    if "goodput_fraction" in block and block["goodput_fraction"] is not None:
        gf = block["goodput_fraction"]
        if not is_number(gf) or not (0.0 <= float(gf) <= 1.0):
            errs.append(f"{where}: slo.goodput_fraction must be a number "
                        "in [0, 1] or null")
    if "prefix_hit_rate" in block and block["prefix_hit_rate"] is not None:
        pr = block["prefix_hit_rate"]
        if not is_number(pr) or not (0.0 <= float(pr) <= 1.0):
            errs.append(f"{where}: slo.prefix_hit_rate must be a number "
                        "in [0, 1] or null")
    return errs


def validate_overlap_fraction(frac: Any, where: str) -> List[str]:
    if not is_number(frac) or not (0.0 <= float(frac) <= 1.0):
        return [f"{where}: overlap_fraction must be a number in [0, 1]"]
    return []


def validate_entry(entry: Any, name: str) -> List[str]:
    where = f"entries[{name!r}]"
    if not isinstance(entry, dict):
        return [f"{where}: must be a dict, got {type(entry).__name__}"]
    errs: List[str] = []
    if not any(k in entry for k in ("metrics", "skipped_reason", "error")):
        errs.append(f"{where}: needs at least one of metrics / "
                    "skipped_reason / error")
    for key in entry:
        if key not in ENTRY_STRUCTURAL_KEYS:
            errs.append(f"{where}: unexpected key {key!r} (metrics belong "
                        "under 'metrics')")
    if "metrics" in entry:
        if not isinstance(entry["metrics"], dict):
            errs.append(f"{where}: metrics must be a dict")
        elif not _is_jsonable(entry["metrics"]):
            errs.append(f"{where}: metrics must be JSON-serializable")
    if "trace_phases" in entry:
        errs += validate_trace_phases(entry["trace_phases"], where)
    if "memory" in entry:
        errs += validate_memory(entry["memory"], where)
    if "elapsed_s" in entry and not is_number(entry["elapsed_s"]):
        errs.append(f"{where}: elapsed_s must be a number")
    for key in ("skipped_reason", "error", "note"):
        if key in entry and not isinstance(entry[key], str):
            errs.append(f"{where}: {key} must be a string")
    if "telemetry" in entry and not isinstance(entry["telemetry"], dict):
        errs.append(f"{where}: telemetry must be a dict")
    if "comms" in entry:
        errs += validate_comms(entry["comms"], where)
    if "guardian" in entry:
        errs += validate_guardian(entry["guardian"], where)
    if "overlap_fraction" in entry:
        errs += validate_overlap_fraction(entry["overlap_fraction"], where)
    if "plan" in entry:
        errs += validate_plan_block(entry["plan"], where)
    if "elastic" in entry:
        errs += validate_elastic_block(entry["elastic"], where)
    if "tenants" in entry:
        errs += validate_tenants_block(entry["tenants"], where)
    if "slo" in entry:
        errs += validate_slo_block(entry["slo"], where)
    return errs


def validate_headline(head: Any) -> List[str]:
    if not isinstance(head, dict):
        return [f"headline: must be a dict, got {type(head).__name__}"]
    errs: List[str] = []
    for key, typ in (("metric", str), ("unit", str)):
        if not isinstance(head.get(key), typ):
            errs.append(f"headline: {key!r} must be a {typ.__name__}")
    if not is_number(head.get("value")):
        errs.append("headline: 'value' must be a number (a null/absent "
                    "headline value is exactly the r03–r05 failure mode)")
    elif head.get("value", 0) <= 0 and "error" not in head:
        errs.append("headline: value <= 0 without an 'error' field — a "
                    "dead headline must say why")
    if "error" in head and not isinstance(head["error"], str):
        errs.append("headline: 'error' must be a string")
    for key in ("vs_baseline", "mfu", "model_tflops_per_sec_chip",
                "peak_tflops", "matmul_ceiling_tflops", "vs_ceiling",
                "hardware_tflops_per_sec_chip", "vs_ceiling_hardware",
                "baseline_tokens_per_sec", "loss"):
        if key in head and head[key] is not None and not is_number(head[key]):
            errs.append(f"headline: {key!r} must be a number or null")
    if "trace_phases" in head:
        errs += validate_trace_phases(head["trace_phases"], "headline")
    if "memory" in head:
        errs += validate_memory(head["memory"], "headline")
    if "comms" in head:
        errs += validate_comms(head["comms"], "headline")
    if "guardian" in head:
        errs += validate_guardian(head["guardian"], "headline")
    if "overlap_fraction" in head and head["overlap_fraction"] is not None:
        errs += validate_overlap_fraction(head["overlap_fraction"],
                                          "headline")
    return errs


def validate_result(result: Any) -> List[str]:
    """Validate a full schema-v2 bench result. Returns a list of errors
    (empty list = valid). Never raises."""
    if not isinstance(result, dict):
        return [f"result must be a dict, got {type(result).__name__}"]
    errs: List[str] = []
    if result.get("schema_version") not in SUPPORTED_SCHEMA_VERSIONS:
        errs.append(f"schema_version must be one of "
                    f"{SUPPORTED_SCHEMA_VERSIONS}, got "
                    f"{result.get('schema_version')!r}")
    # driver contract: the four keys the round extractor has read since r01
    if not isinstance(result.get("metric"), str) or not result.get("metric"):
        errs.append("'metric' must be a non-empty string")
    if not is_number(result.get("value")):
        errs.append("'value' must be a number")
    if not isinstance(result.get("unit"), str):
        errs.append("'unit' must be a string")
    if "vs_baseline" in result and not is_number(result["vs_baseline"]):
        errs.append("'vs_baseline' must be a number")
    errs += validate_headline(result.get("headline"))
    # headline block and driver-contract fields must agree — two sources
    # of truth drifting apart is how extractors rot
    head = result.get("headline")
    if isinstance(head, dict) and not errs:
        for key in ("metric", "value", "unit"):
            if head.get(key) != result.get(key):
                errs.append(f"headline.{key} != top-level {key} "
                            f"({head.get(key)!r} vs {result.get(key)!r})")
    entries = result.get("entries")
    if entries is None:
        errs.append("'entries' must be present (may be {})")
    elif not isinstance(entries, dict):
        errs.append("'entries' must be a dict")
    else:
        for name, entry in entries.items():
            errs += validate_entry(entry, name)
    for key in ("budget_s", "total_runtime_s"):
        if key in result and not is_number(result[key]):
            errs.append(f"{key!r} must be a number")
    if "gate" in result and not isinstance(result["gate"], dict):
        errs.append("'gate' must be a dict")
    return errs


def validate_record(record: Any) -> List[str]:
    """Validate a bench_history record (one JSONL line). Recovered partial
    results validate structurally only — a truncated round keeps whatever
    it still has."""
    if not isinstance(record, dict):
        return ["record must be a dict"]
    errs: List[str] = []
    if record.get("record_version") != RECORD_VERSION:
        errs.append(f"record_version must be {RECORD_VERSION}")
    if not isinstance(record.get("round"), str) or not record.get("round"):
        errs.append("record 'round' must be a non-empty string")
    if not isinstance(record.get("source"), str):
        errs.append("record 'source' must be a string")
    for key in ("complete", "recovered"):
        if not isinstance(record.get(key), bool):
            errs.append(f"record {key!r} must be a bool")
    result = record.get("result")
    if not isinstance(result, dict):
        errs.append("record 'result' must be a dict")
        return errs
    if record.get("complete"):
        errs += validate_result(result)
    else:
        if not isinstance(result.get("headline"), dict):
            errs.append("partial record result.headline must be a dict "
                        "(may be {})")
        if not isinstance(result.get("entries"), dict):
            errs.append("partial record result.entries must be a dict "
                        "(may be {})")
        else:
            for name, entry in result["entries"].items():
                errs += validate_entry(entry, name)
    return errs


def normalize_entry_row(row: Any,
                        elapsed_s: Optional[float] = None) -> Dict[str, Any]:
    """Normalize a raw suite-entry row (what ``bench.py --entry`` prints, or
    a v1 ``configs`` value) into the schema-v2 entry shape.

    Raw rows are flat measured dicts with ``telemetry`` / ``trace_phases``
    mixed in, or ``{"skipped": reason}`` / ``{"error": msg}`` markers; some
    legacy entries are bare lists (comm tables).
    """
    out: Dict[str, Any] = {}
    if elapsed_s is not None:
        out["elapsed_s"] = round(float(elapsed_s), 1)
    if isinstance(row, list):
        out["metrics"] = {"rows": row}
        return out
    if not isinstance(row, dict):
        out["metrics"] = {"value": row}
        return out
    row = dict(row)
    if "skipped" in row:
        out["skipped_reason"] = str(row.pop("skipped"))
    if "skipped_reason" in row:
        out["skipped_reason"] = str(row.pop("skipped_reason"))
    if "error" in row:
        out["error"] = str(row.pop("error"))
    for key in ("trace_phases", "telemetry", "memory", "comms", "guardian",
                "plan", "elastic", "tenants", "slo"):
        if key in row:
            val = row.pop(key)
            if val:
                out[key] = val
    if "overlap_fraction" in row:
        # 0.0 (nothing hidden) is a real measurement — keep falsy numbers
        val = row.pop("overlap_fraction")
        if is_number(val):
            out["overlap_fraction"] = val
    if "note" in row:
        out["note"] = str(row.pop("note"))
    if "metrics" in row and isinstance(row["metrics"], dict):
        # already normalized (idempotent)
        out["metrics"] = row.pop("metrics")
        out.update({k: v for k, v in row.items()
                    if k in ENTRY_STRUCTURAL_KEYS and k not in out})
    elif row:
        out["metrics"] = row
    return out
