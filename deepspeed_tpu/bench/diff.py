"""Diff two bench rounds: headline, per-entry metrics, per-phase spans.

The comparison is direction-aware — ``tokens_per_sec`` falling is a
regression, ``ttft_p95_s`` falling is an improvement — and only metrics
with a known direction are compared at all (config echoes like ``batch``
or ``max_new`` and convergence losses are not perf trajectories).

When a throughput metric regresses past the threshold, the entry's
``trace_phases`` (per-phase p50/p95/p99 span percentiles, PR 5) are
diffed too and the regression is ATTRIBUTED: the phase whose per-
occurrence p50 grew the most, weighted by how often it ran, is named
with before/after numbers — "tokens/sec dropped 12%: 'train_window' p50
grew 15% (0.800s -> 0.920s)" instead of a bare red number.

Inputs are schema-v2 results (``deepspeed_tpu.bench.schema``) or the
partial results the legacy recovery produces — anything missing on one
side degrades to a status note, never a crash.
"""
from __future__ import annotations

from typing import Any, Dict, List, Optional, Tuple

from deepspeed_tpu.bench.schema import is_number

HIGHER_IS_BETTER = 1
LOWER_IS_BETTER = -1

_HIGHER_SUBSTR = ("tokens_per_sec", "tflops")
_HIGHER_EXACT = ("value", "mfu", "vs_baseline", "vs_ceiling",
                 "vs_ceiling_hardware", "wire_reduction", "speedup_vs_slot",
                 "baseline_tokens_per_sec")
# NOT compared: tuner_score (the autotuner's internal RANKING measure,
# explicitly uncalibrated — bench.py autotune_smoke), loss (convergence
# evidence, not a perf trajectory), config echoes (batch, max_new, ...)
_HIGHER_SUFFIX = ("gbps",)
_LOWER_PREFIX = ("ttft_", "tpot_", "e2e_")
_LOWER_EXACT = ("rel_err", "overhead_factor", "moe_dropped_frac",
                "peak_host_rss_mb", "peak_bytes_in_use",
                # compiled-program memory_analysis legs (memlint): the
                # lowered step's own peak/temp bytes are reproducible
                # per program, so they diff like perf numbers
                "device_peak_bytes", "temp_bytes")
# bytes_in_use is an END-OF-ENTRY allocator snapshot, not a peak — it
# moves with GC/donation timing run-to-run, so it is shown in rows but
# never direction-compared (peaks are; they're reproducible)
_LOWER_SUFFIX = ("_phase_s", "time_ms")


def metric_direction(name: str) -> Optional[int]:
    """+1 = higher is better, -1 = lower is better, None = not a perf
    metric (not compared). ``name`` is a flattened dotted path; most rules
    key on its LEAF, but ``comms.*`` byte totals are path-scoped (the leaf
    ``bytes`` is too generic to claim globally)."""
    leaf = name.rsplit(".", 1)[-1]
    if name.startswith("comms."):
        # compiled-collective ledger totals: wire bytes falling is the
        # quantized-collective win (ROADMAP item 1) — lower is better.
        # counts/link echoes carry no direction; predicted_busbw_gbps is
        # the link constant (leaf gbps rule would no-op compare it anyway)
        if leaf in ("bytes", "bus_bytes", "total_bytes"):
            return LOWER_IS_BETTER
        if leaf in ("count", "unparsed", "link_gbps",
                    "predicted_busbw_gbps", "async_pairs"):
            # async_pairs is a program-structure echo (how many
            # collectives lowered async), not a perf trajectory
            return None
    if name.startswith("guardian."):
        # training-guardian fault accounting: every count falling is
        # health improving — an anomaly-ridden round flags loudly (a
        # 0 -> nonzero move surfaces as the explicit zero-baseline row)
        return LOWER_IS_BETTER
    if name.startswith("slo."):
        # fleet-observatory accounting: delivering more of what was
        # computed is the win, burning budget / wasting compute is the
        # regression. prefix_hit_rate rising means more reuse headroom
        # was measured, not captured — no direction.
        if leaf in ("goodput_tokens", "goodput_fraction"):
            return HIGHER_IS_BETTER
        if leaf == "worst_burn_rate" or name.startswith(
                "slo.wasted_tokens."):
            return LOWER_IS_BETTER
        return None
    if leaf == "overlap_fraction":
        # fraction of collective time hidden under compute — the ROADMAP
        # item 2 before/after metric
        return HIGHER_IS_BETTER
    if leaf in _HIGHER_EXACT or any(s in leaf for s in _HIGHER_SUBSTR):
        return HIGHER_IS_BETTER
    if leaf.endswith(_HIGHER_SUFFIX):
        return HIGHER_IS_BETTER
    if leaf in _LOWER_EXACT or leaf.startswith(_LOWER_PREFIX) \
            or leaf.endswith(_LOWER_SUFFIX):
        return LOWER_IS_BETTER
    return None


def flatten_metrics(obj: Any, prefix: str = "",
                    out: Optional[Dict[str, float]] = None,
                    depth: int = 0) -> Dict[str, float]:
    """Flatten a metrics tree to ``dotted.path -> number``, keeping only
    leaves with a known direction. Lists of dicts keyed by an ``"op"``
    field (comm tables) flatten per-op; other lists are samples, skipped."""
    if out is None:
        out = {}
    if depth > 8:
        return out
    if isinstance(obj, dict):
        for key, val in obj.items():
            path = f"{prefix}.{key}" if prefix else str(key)
            flatten_metrics(val, path, out, depth + 1)
    elif isinstance(obj, list):
        for item in obj:
            if isinstance(item, dict) and isinstance(item.get("op"), str):
                flatten_metrics(
                    {k: v for k, v in item.items() if k != "op"},
                    f"{prefix}.{item['op']}" if prefix else item["op"],
                    out, depth + 1)
    elif is_number(obj) and prefix and metric_direction(prefix) is not None:
        out[prefix] = float(obj)
    return out


def comparables(result: Dict[str, Any]) -> Dict[str, Any]:
    """Extract the diffable view of a (possibly partial) v2 result."""
    head = result.get("headline") or {}
    head_metrics = flatten_metrics(
        {k: v for k, v in head.items()
         if k not in ("trace_phases", "telemetry", "best_row", "memory",
                      "comms", "guardian", "slo")})
    if "memory" in head:
        head_metrics.update(flatten_metrics(head["memory"], "memory"))
    if "comms" in head:
        head_metrics.update(flatten_metrics(head["comms"], "comms"))
    if "guardian" in head:
        head_metrics.update(flatten_metrics(head["guardian"], "guardian"))
    out = {
        "headline": {
            "metric_name": head.get("metric"),
            "metrics": head_metrics,
            "phases": head.get("trace_phases") or {},
            "error": head.get("error"),
        },
        "entries": {},
    }
    for name, entry in (result.get("entries") or {}).items():
        if not isinstance(entry, dict):
            continue
        metrics = flatten_metrics(entry.get("metrics") or {})
        if "memory" in entry:
            metrics.update(flatten_metrics(entry["memory"], "memory"))
        if "comms" in entry:
            metrics.update(flatten_metrics(entry["comms"], "comms"))
        if "guardian" in entry:
            metrics.update(flatten_metrics(entry["guardian"], "guardian"))
        if "slo" in entry:
            metrics.update(flatten_metrics(entry["slo"], "slo"))
        if is_number(entry.get("overlap_fraction")):
            metrics["overlap_fraction"] = float(entry["overlap_fraction"])
        out["entries"][name] = {
            "metrics": metrics,
            "phases": entry.get("trace_phases") or {},
            "skipped_reason": entry.get("skipped_reason"),
            "error": entry.get("error"),
        }
    return out


def _field_diffs(old: Dict[str, float], new: Dict[str, float],
                 threshold: float) -> List[Dict[str, Any]]:
    rows: List[Dict[str, Any]] = []
    for name in sorted(set(old) & set(new)):
        a, b = old[name], new[name]
        direction = metric_direction(name)
        if direction is None:
            continue
        if a == 0:
            # no relative delta exists, but dropping the row would hide
            # a 0 -> nonzero move (e.g. rel_err appearing); show it
            # un-verdicted instead
            rows.append({
                "name": name, "old": a, "new": b,
                "delta_frac": None,
                "direction": ("higher_is_better" if direction > 0
                              else "lower_is_better"),
                "regressed": False, "improved": False,
                "note": "zero baseline — no relative delta",
            })
            continue
        delta = (b - a) / abs(a)
        regressed = direction * delta < -threshold
        improved = direction * delta > threshold
        rows.append({
            "name": name, "old": a, "new": b,
            "delta_frac": round(delta, 4),
            "direction": ("higher_is_better" if direction > 0
                          else "lower_is_better"),
            "regressed": regressed, "improved": improved,
        })
    return rows


def _phase_diffs(old: Dict[str, Any],
                 new: Dict[str, Any]) -> List[Dict[str, Any]]:
    rows: List[Dict[str, Any]] = []
    for phase in sorted(set(old) & set(new)):
        a, b = old[phase], new[phase]
        if not (isinstance(a, dict) and isinstance(b, dict)):
            continue
        p50_a, p50_b = a.get("p50_s"), b.get("p50_s")
        if not (is_number(p50_a) and is_number(p50_b)) or p50_a <= 0:
            continue
        rows.append({
            "phase": phase,
            "p50_old_s": p50_a, "p50_new_s": p50_b,
            "p50_delta_frac": round((p50_b - p50_a) / p50_a, 4),
            "p95_old_s": a.get("p95_s"), "p95_new_s": b.get("p95_s"),
            "count_old": a.get("count"), "count_new": b.get("count"),
            "total_old_s": a.get("total_s"), "total_new_s": b.get("total_s"),
        })
    return rows


def _attribute(fields: List[Dict[str, Any]],
               phase_rows: List[Dict[str, Any]]) -> Optional[Dict[str, Any]]:
    """Name the phase responsible for a throughput regression. Only fires
    when a higher-is-better throughput-class metric regressed."""
    culprit_metric = None
    for row in fields:
        if row["regressed"] and row["direction"] == "higher_is_better" \
                and ("tokens_per_sec" in row["name"]
                     or row["name"] == "value"):
            if culprit_metric is None \
                    or row["delta_frac"] < culprit_metric["delta_frac"]:
                culprit_metric = row
    if culprit_metric is None:
        return None
    base = {
        "regressed_metric": culprit_metric["name"],
        "metric_delta_frac": culprit_metric["delta_frac"],
    }
    grown = [r for r in phase_rows if r["p50_delta_frac"] > 0]
    if not grown:
        base["phase"] = None
        base["summary"] = (
            f"{culprit_metric['name']} "
            f"{culprit_metric['delta_frac'] * 100:+.1f}% — no overlapping "
            "trace_phases grew; phase attribution unavailable")
        return base
    # weight per-occurrence p50 growth by how often the phase ran: the
    # phase contributing the most wall seconds to the slowdown wins
    def score(r: Dict[str, Any]) -> float:
        count = r.get("count_new") or r.get("count_old") or 1
        return (r["p50_new_s"] - r["p50_old_s"]) * float(count)

    top = max(grown, key=score)
    base.update({
        "phase": top["phase"],
        "p50_old_s": top["p50_old_s"], "p50_new_s": top["p50_new_s"],
        "p50_growth_frac": top["p50_delta_frac"],
        "est_growth_s": round(score(top), 6),
    })
    base["summary"] = (
        f"{culprit_metric['name']} "
        f"{culprit_metric['delta_frac'] * 100:+.1f}%: phase "
        f"'{top['phase']}' p50 grew {top['p50_delta_frac'] * 100:+.1f}% "
        f"({top['p50_old_s']:.4g}s -> {top['p50_new_s']:.4g}s)")
    return base


def diff_results(old_result: Dict[str, Any], new_result: Dict[str, Any],
                 threshold: float = 0.05,
                 old_label: str = "old",
                 new_label: str = "new") -> Dict[str, Any]:
    """Structured diff of two (possibly partial) schema-v2 results."""
    old_c, new_c = comparables(old_result), comparables(new_result)
    diff: Dict[str, Any] = {
        "old": old_label, "new": new_label,
        "threshold": threshold,
        "headline": {}, "entries": {},
        "regressions": [], "improvements": [], "notes": [],
    }

    def collect(where: str, fields: List[Dict[str, Any]]) -> None:
        for row in fields:
            bucket = (diff["regressions"] if row["regressed"] else
                      diff["improvements"] if row["improved"] else None)
            if bucket is not None:
                bucket.append({"where": where, "metric": row["name"],
                               "old": row["old"], "new": row["new"],
                               "delta_frac": row["delta_frac"]})

    old_name = old_c["headline"]["metric_name"]
    new_name = new_c["headline"]["metric_name"]
    old_plat = (old_result.get("headline") or {}).get("platform")
    new_plat = (new_result.get("headline") or {}).get("platform")
    old_err = old_c["headline"]["error"]
    new_err = new_c["headline"]["error"]
    if (old_name and new_name and old_name != new_name) or \
            (old_plat and new_plat and old_plat != new_plat):
        # different model/config headline (BENCH_MODEL override) or
        # different backend (CPU what-if vs TPU round): a cross
        # comparison of the headline would be a fake regression
        diff["notes"].append(
            f"headline not comparable ({old_name!r}@{old_plat or '?'} vs "
            f"{new_name!r}@{new_plat or '?'}) — entries still diff "
            "like-for-like")
        head_fields: List[Dict[str, Any]] = []
        head_phases: List[Dict[str, Any]] = []
    elif old_err or new_err:
        # an errored headline carries value=0 by schema contract —
        # numeric-comparing it would read as a fake -100%. Measured ->
        # error IS a regression (like entries), but an honest one —
        # UNLESS the error is budget starvation (the headline can't carry
        # skipped_reason, so bench.py folds budget skips into error):
        # budget skips are noted, never flagged, same as entries.
        head_fields = []
        head_phases = []
        fresh_budget = isinstance(new_err, str) \
            and new_err.startswith("budget")
        if new_err and not old_err and not fresh_budget \
                and old_c["headline"]["metrics"].get("value"):
            diff["regressions"].append({
                "where": "headline", "metric": "(headline)",
                "old": "measured", "new": "error",
                "delta_frac": None, "note": str(new_err)[:160]})
        diff["notes"].append(
            "headline errored in "
            + (" and ".join(lbl for lbl, err in ((old_label, old_err),
                                                 (new_label, new_err))
                            if err))
            + " — numeric headline not compared")
    else:
        head_fields = _field_diffs(old_c["headline"]["metrics"],
                                   new_c["headline"]["metrics"], threshold)
        head_phases = _phase_diffs(old_c["headline"]["phases"],
                                   new_c["headline"]["phases"])
    diff["headline"] = {
        "metric_name": (new_c["headline"]["metric_name"]
                        or old_c["headline"]["metric_name"]),
        "fields": head_fields, "phases": head_phases,
        "attribution": _attribute(head_fields, head_phases),
    }
    collect("headline", head_fields)
    if not old_c["headline"]["metrics"]:
        diff["notes"].append(f"{old_label}: headline not comparable "
                             "(missing or recovered without it)")
    if not new_c["headline"]["metrics"]:
        diff["notes"].append(f"{new_label}: headline not comparable")

    for name in sorted(set(old_c["entries"]) | set(new_c["entries"])):
        o = old_c["entries"].get(name)
        n = new_c["entries"].get(name)
        if o is None or n is None:
            diff["entries"][name] = {
                "status": "only_old" if n is None else "only_new"}
            continue
        old_state = ("skipped" if o["skipped_reason"] else
                     "error" if o["error"] else "ok")
        new_state = ("skipped" if n["skipped_reason"] else
                     "error" if n["error"] else "ok")
        if old_state == "ok" and new_state == "ok":
            status = "compared"
        elif old_state == new_state:
            # skipped/errored on BOTH sides is not a fresh breakage
            status = f"{old_state}_both"
        elif new_state != "ok":
            status = f"{new_state}_new"
        else:
            status = f"{old_state}_old"
        entry_diff: Dict[str, Any] = {"status": status}
        if status == "compared" or (o["metrics"] and n["metrics"]):
            fields = _field_diffs(o["metrics"], n["metrics"], threshold)
            phases = _phase_diffs(o["phases"], n["phases"])
            entry_diff.update({
                "fields": fields, "phases": phases,
                "attribution": _attribute(fields, phases),
            })
            collect(name, fields)
        if status == "error_new" and o["metrics"]:
            # a measured entry turning into an error row IS a regression
            diff["regressions"].append({
                "where": name, "metric": "(entry)",
                "old": "measured", "new": "error",
                "delta_frac": None,
                "note": (n["error"] or "")[:160]})
        elif status.startswith("skipped"):
            diff["notes"].append(
                f"{name}: {status.replace('_', ' in ')} — not compared")
        diff["entries"][name] = entry_diff
    diff["ok"] = not diff["regressions"]
    return diff


# --------------------------------------------------------------------- #
# renderers
# --------------------------------------------------------------------- #
def _fmt(x: Any) -> str:
    if is_number(x):
        # magnitude guard first: int(inf)/int(nan) raise
        if abs(x) < 1e15 and x == int(x):
            return str(int(x))
        return f"{x:.4g}"
    return str(x)


def _fmt_delta(delta_frac: Any) -> str:
    if delta_frac is None:
        return "    n/a "
    return f"{delta_frac * 100:+7.1f}%"


def _field_line(row: Dict[str, Any]) -> str:
    flag = ("REGRESSED" if row["regressed"]
            else "improved" if row["improved"] else row.get("note") or "")
    return (f"{row['name']:42s} {_fmt(row['old']):>12s} -> "
            f"{_fmt(row['new']):>12s}  {_fmt_delta(row['delta_frac'])}  "
            f"{flag}").rstrip()


def render_text(diff: Dict[str, Any], verbose: bool = False) -> str:
    lines: List[str] = []
    th = diff["threshold"]
    lines.append(f"bench-diff {diff['old']} -> {diff['new']}  "
                 f"(threshold {th * 100:g}%)")
    head = diff["headline"]
    if head.get("fields"):
        lines.append(f"headline: {head.get('metric_name')}")
        for row in head["fields"]:
            if verbose or row["regressed"] or row["improved"]:
                lines.append("  " + _field_line(row))
        if head.get("attribution"):
            lines.append(f"  attribution: {head['attribution']['summary']}")
    for name, entry in diff["entries"].items():
        fields = entry.get("fields") or []
        shown = [r for r in fields
                 if verbose or r["regressed"] or r["improved"]]
        if not shown and entry.get("status") == "compared" \
                and not entry.get("attribution"):
            continue
        lines.append(f"{name} [{entry['status']}]")
        for row in shown:
            lines.append("  " + _field_line(row))
        if entry.get("attribution"):
            lines.append(f"  attribution: {entry['attribution']['summary']}")
    for note in diff["notes"]:
        lines.append(f"note: {note}")
    lines.append(
        f"summary: {len(diff['regressions'])} regression(s), "
        f"{len(diff['improvements'])} improvement(s) past "
        f"{th * 100:g}%")
    return "\n".join(lines)


def render_markdown(diff: Dict[str, Any], verbose: bool = False) -> str:
    lines: List[str] = []
    lines.append(f"### bench-diff `{diff['old']}` → `{diff['new']}` "
                 f"(threshold {diff['threshold'] * 100:g}%)")
    lines.append("")
    lines.append("| where | metric | old | new | Δ | verdict |")
    lines.append("|---|---|---:|---:|---:|---|")

    def md_rows(where: str, fields: List[Dict[str, Any]]) -> None:
        for row in fields:
            if not (verbose or row["regressed"] or row["improved"]):
                continue
            verdict = ("**regressed**" if row["regressed"]
                       else "improved" if row["improved"] else "")
            lines.append(
                f"| {where} | `{row['name']}` | {_fmt(row['old'])} | "
                f"{_fmt(row['new'])} | {_fmt_delta(row['delta_frac']).strip()}"
                f" | {verdict} |")

    md_rows("headline", diff["headline"].get("fields") or [])
    for name, entry in diff["entries"].items():
        md_rows(name, entry.get("fields") or [])
    attributions = []
    if diff["headline"].get("attribution"):
        attributions.append(("headline", diff["headline"]["attribution"]))
    attributions += [(n, e["attribution"]) for n, e in
                     diff["entries"].items() if e.get("attribution")]
    if attributions:
        lines.append("")
        lines.append("**Attribution**")
        for where, attr in attributions:
            lines.append(f"- {where}: {attr['summary']}")
    if diff["notes"]:
        lines.append("")
        for note in diff["notes"]:
            lines.append(f"- note: {note}")
    lines.append("")
    lines.append(f"{len(diff['regressions'])} regression(s), "
                 f"{len(diff['improvements'])} improvement(s)")
    return "\n".join(lines)
