"""Perf-regression observatory: versioned bench schema, history store,
legacy-round recovery, round-to-round diffs, and the regression gate.

The modules in here import only the stdlib (no jax, no numpy) —
``bench.py`` pulls this in on every run and the parsers must keep
working on whatever is left of a broken round's output. The
``tools/bench-diff`` shim registers a stub parent package so even the
framework's own ``__init__`` (which DOES import jax) never runs when
you only need the observatory. Pieces:

* :mod:`~deepspeed_tpu.bench.schema`  — schema v2 + validator (``parsed``
  can never silently go null again)
* :mod:`~deepspeed_tpu.bench.history` — append-only
  ``bench_history/history.jsonl``
* :mod:`~deepspeed_tpu.bench.legacy`  — tolerant recovery of the
  committed BENCH_r01–r05 tail blobs (r03–r05 were ``"parsed": null``)
* :mod:`~deepspeed_tpu.bench.diff`    — direction-aware metric diffs +
  per-phase span diffs with regression attribution
* :mod:`~deepspeed_tpu.bench.gate`    — 0/1/2 exit-code regression gate
* :mod:`~deepspeed_tpu.bench.cli`     — the ``bench-diff`` console entry
* ``python -m deepspeed_tpu.bench``   — recover / validate / history

Docs: README "Perf trajectory", docs/tutorials/bench-diff.md.
"""
from deepspeed_tpu.bench.diff import (
    diff_results,
    flatten_metrics,
    metric_direction,
    render_markdown,
    render_text,
)
from deepspeed_tpu.bench.gate import (
    GATE_ERROR,
    GATE_OK,
    GATE_REGRESSED,
    gate_enabled,
    gate_threshold,
    run_gate,
)
from deepspeed_tpu.bench.history import (
    append_record,
    history_path,
    latest_record,
    load_history,
    record_for_round,
    record_from_result,
)
from deepspeed_tpu.bench.legacy import (
    recover_from_text,
    recover_round_file,
    recover_rounds,
    upgrade_legacy_result,
)
from deepspeed_tpu.bench.schema import (
    RECORD_VERSION,
    SCHEMA_VERSION,
    SUPPORTED_SCHEMA_VERSIONS,
    normalize_entry_row,
    validate_record,
    validate_result,
)

__all__ = [
    "SCHEMA_VERSION", "RECORD_VERSION", "SUPPORTED_SCHEMA_VERSIONS",
    "validate_result", "validate_record", "normalize_entry_row",
    "recover_from_text", "recover_round_file", "recover_rounds",
    "upgrade_legacy_result",
    "load_history", "append_record", "latest_record", "record_for_round",
    "record_from_result", "history_path",
    "diff_results", "render_text", "render_markdown", "flatten_metrics",
    "metric_direction",
    "run_gate", "gate_enabled", "gate_threshold",
    "GATE_OK", "GATE_REGRESSED", "GATE_ERROR",
]
