"""``bench-diff`` — diff two bench rounds with regression attribution.

Round specs (either positional argument):

* ``r05`` / ``r3``       — a round id: resolved from ``bench_history/``
  first, else recovered live from the committed ``BENCH_rNN.json``
* ``latest``             — the newest comparable ``bench_history`` record
* a file path            — a driver round artifact (``{rc, tail,
  parsed}``), a history record, a raw bench result (v1 or v2), or a
  plain log whose last JSON line / fragments are recovered tolerantly

Exit codes (dslint-shaped, see ``deepspeed_tpu.bench.gate``): 0 = no
past-threshold regressions, 1 = regressions found, 2 = usage/internal
error. ``--no-gate`` forces exit 0 on a successful diff. Unlike
``bench.py``'s automated self-gate, an explicit diff exits 1 on ANY
regression it shows — including the CPU-mesh noisy lanes the automated
gate ignores; you asked for this exact comparison, so you get all of it
(``--no-gate`` if you only want the report).

Examples::

    bench-diff r04 r05
    bench-diff r05 /tmp/fresh_bench.json --format markdown
    bench-diff latest /tmp/fresh_bench.json --threshold 0.10
"""
from __future__ import annotations

import argparse
import json
import os
import re
import sys
from typing import Any, Dict, List, Optional, Tuple

from deepspeed_tpu.bench import history as history_mod
from deepspeed_tpu.bench import legacy
from deepspeed_tpu.bench.diff import (
    diff_results,
    render_markdown,
    render_text,
)
from deepspeed_tpu.bench.gate import GATE_ERROR, GATE_OK, GATE_REGRESSED


class SpecError(ValueError):
    pass


def _from_loaded_json(obj: Any, label: str
                      ) -> Tuple[str, Dict[str, Any], List[str]]:
    if not isinstance(obj, dict):
        raise SpecError(f"{label}: not a JSON object")
    if "tail" in obj and "parsed" in obj:        # driver round artifact
        rec = legacy.recover_round_data(obj, legacy.round_id_from_path(
            label), label)
        return rec["round"], rec["result"], rec.get("notes", [])
    if "record_version" in obj and isinstance(obj.get("result"), dict):
        return obj.get("round", label), obj["result"], obj.get("notes", [])
    if "metric" in obj or "schema_version" in obj:
        return label, legacy.upgrade_legacy_result(obj), []
    raise SpecError(f"{label}: unrecognized JSON shape (neither a round "
                    "artifact, a history record, nor a bench result)")


def resolve_spec(spec: str, history_file: Optional[str],
                 repo_root: Optional[str]
                 ) -> Tuple[str, Dict[str, Any], List[str]]:
    """Resolve a round spec to ``(label, result, notes)``."""
    root = repo_root or history_mod.default_repo_root()
    if spec == "latest":
        rec = history_mod.latest_record(path=history_file)
        if rec is None:
            raise SpecError("no comparable record in bench_history")
        return rec.get("round", "latest"), rec["result"], \
            rec.get("notes", [])
    m = re.fullmatch(r"r?(\d+)", spec)
    if not os.path.exists(spec) and m:
        # canonical zero-padded id first ("r5" and "r05" are the same
        # round; history and artifacts store the padded form)
        candidates = [f"r{int(m.group(1)):02d}", f"r{m.group(1)}"]
        for round_id in dict.fromkeys(candidates):
            rec = history_mod.record_for_round(round_id, path=history_file)
            if rec is not None:
                return round_id, rec["result"], rec.get("notes", [])
        # not ingested yet — recover live from the committed artifact
        for round_id in dict.fromkeys(candidates):
            path = os.path.join(root, f"BENCH_{round_id}.json")
            if os.path.exists(path):
                rec = legacy.recover_round_file(path)
                return rec["round"], rec["result"], rec.get("notes", [])
        raise SpecError(f"round {candidates[0]!r} not in bench_history "
                        f"and no BENCH_{candidates[0]}.json under {root}")
    if os.path.exists(spec):
        with open(spec, encoding="utf-8") as f:
            text = f.read()
        label = os.path.basename(spec)
        try:
            obj = json.loads(text)
        except ValueError:
            result, notes = legacy.recover_from_text(text)
            return label, result, notes
        return _from_loaded_json(obj, label)
    raise SpecError(f"cannot resolve spec {spec!r}: not a round id, "
                    "'latest', or an existing file")


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="bench-diff",
        description="diff two bench rounds (headline, per-entry metrics, "
                    "per-phase trace spans) with regression attribution")
    p.add_argument("old", help="baseline round (rNN | latest | file)")
    p.add_argument("new", help="candidate round (rNN | latest | file)")
    p.add_argument("--format", choices=("text", "json", "markdown"),
                   default="text")
    p.add_argument("--threshold", type=float, default=0.05,
                   help="regression threshold as a fraction (default 0.05)")
    p.add_argument("--history", default=None, metavar="PATH",
                   help="bench_history dir or .jsonl file (default: the "
                        "checkout's bench_history/, or $BENCH_HISTORY)")
    p.add_argument("--repo", default=None, metavar="DIR",
                   help="checkout root holding BENCH_rNN.json artifacts")
    p.add_argument("--verbose", action="store_true",
                   help="show every compared metric, not just movers")
    p.add_argument("--no-gate", action="store_true",
                   help="always exit 0 on a successful diff")
    return p


def main(argv: Optional[List[str]] = None) -> int:
    args = build_parser().parse_args(argv)
    try:
        old_label, old_result, old_notes = resolve_spec(
            args.old, args.history, args.repo)
        new_label, new_result, new_notes = resolve_spec(
            args.new, args.history, args.repo)
    except (OSError, ValueError) as e:
        # SpecError subclasses ValueError; unreadable files / corrupt
        # artifacts are internal errors (2), never "regressions" (1)
        print(f"bench-diff: error: {e}", file=sys.stderr)
        return GATE_ERROR
    try:
        diff = diff_results(old_result, new_result,
                            threshold=args.threshold,
                            old_label=old_label, new_label=new_label)
        seen = set(diff["notes"])
        for label, notes in ((old_label, old_notes),
                             (new_label, new_notes)):
            for note in notes:
                line = f"{label}: {note}"
                if line not in seen:
                    seen.add(line)
                    diff["notes"].append(line)
        if args.format == "json":
            print(json.dumps(diff, indent=2))
        elif args.format == "markdown":
            print(render_markdown(diff, verbose=args.verbose))
        else:
            print(render_text(diff, verbose=args.verbose))
    except Exception as e:
        # exit 1 is reserved for "regressions found"; a diff/render
        # failure on degenerate input is the contract's 2
        print(f"bench-diff: internal error: {type(e).__name__}: {e}",
              file=sys.stderr)
        return GATE_ERROR
    if args.no_gate:
        return GATE_OK
    return GATE_OK if diff["ok"] else GATE_REGRESSED


if __name__ == "__main__":
    sys.exit(main())
