"""Regression gate: a fresh bench result vs the latest recorded round.

Exit-code contract (same shape as dslint's): **0** = no regressions (or
no usable baseline — a first run can't regress), **1** = at least one
past-threshold regression, **2** = internal error. ``bench.py`` runs the
gate after printing its JSON line, so each PR's bench run FAILS on a
>5% headline or per-entry drop instead of logging it; ``tools/bench-diff``
applies the same contract between any two explicit rounds (without the
noisy-lane filter — an explicit diff reports everything it shows).

Baseline selection skips records whose run FAILED its own gate
(``rc != 0``): a regressed round must not become the next round's
baseline, or the gate fires exactly once and the regression is
grandfathered. It also skips records with a different headline metric
when both sides declare one, and — when the fresh run declares a
``platform`` — records that don't declare the SAME platform: a CPU
what-if run or a ``BENCH_MODEL=tiny`` local record is not the same
trajectory, and the platform-less legacy rounds must not numeric-gate
a fresh run from an unknown-vs-recorded backend (a CPU box against a
TPU round reads as a fake -99%).

Environment knobs:

* ``BENCH_GATE=0``        — skip the gate entirely (bench.py exits 0)
* ``BENCH_GATE_THRESHOLD``— regression threshold as a fraction
  (default 0.05 = 5%), applied to headline and per-entry metrics alike.
* ``BENCH_GATE_NOISE``    — per-platform noise band as a fraction:
  a regression whose magnitude is INSIDE the band is reported under
  ``noise_within_band`` (a warning in the gate info) instead of failing
  the run. Unset = derived from committed same-platform history (2x the
  relative sample stddev of the last 5 clean headline rounds, capped at
  0.25); ``0`` disables the band (every past-threshold regression
  fails, the pre-PR-16 behavior). Rationale: the CPU lane's r08 fired
  on a ~5.5% headline drift with zero code changes — same-platform
  history says that lane's round-to-round noise floor is ~14%, and a
  gate that cries wolf inside its own noise floor trains people to
  ignore it.
"""
from __future__ import annotations

import os
from typing import Any, Dict, Optional, Tuple

from deepspeed_tpu.bench import history as history_mod
from deepspeed_tpu.bench.diff import diff_results, flatten_metrics
from deepspeed_tpu.bench.schema import is_number

GATE_OK = 0
GATE_REGRESSED = 1
GATE_ERROR = 2

DEFAULT_THRESHOLD = 0.05

#: entries the AUTOMATED gate never fails a run on: the CPU-mesh software
#: collectives time-slice 8 virtual devices on whatever cores the runner
#: has free, and their absolute numbers swing far past any real threshold
#: round-to-round (r03 vs r05 all_reduce busbw moved 36% with no code
#: change). ``bench-diff`` still SHOWS them (and still exits 1 on them —
#: it diffs exactly what you asked for); they are evidence, just not
#: gate-grade. On-chip lanes (``comm_bw_onchip``, ``comm_bw``) measure
#: real ICI and DO gate.
NOISY_ENTRIES = frozenset({
    "comm_cpu_mesh_world8", "comm_busbw_cpu_mesh_world8",
    "pipeline_1f1b_cpu_mesh", "stability_2k_cpu_mesh",
})


def _has_headline(record: Dict[str, Any]) -> bool:
    """Gate-grade tier 1: the record carries a numeric headline value, so
    the headline gate is armed against it."""
    head = (record.get("result") or {}).get("headline") or {}
    value = head.get("value")
    return is_number(value) and value > 0


def _has_gateable_entries(record: Dict[str, Any]) -> bool:
    """Gate-grade tier 2: at least one NON-noisy entry with direction-
    comparable metrics. A record whose only comparables are noisy
    CPU-mesh lanes would pass ``_has_comparables`` and then every one of
    its regressions would be filtered — a baseline that silently disarms
    the gate."""
    entries = (record.get("result") or {}).get("entries") or {}
    for name, entry in entries.items():
        if name in NOISY_ENTRIES or not isinstance(entry, dict):
            continue
        if flatten_metrics(entry.get("metrics") or {}):
            return True
    return False


#: noise-band derivation window and ceiling: the band is evidence from
#: recent history, not a licence — five clean rounds bound "recent", and
#: a lane so noisy its 2-sigma exceeds 25% shouldn't silently waive
#: quarter-sized regressions (cap it and let a human look)
NOISE_WINDOW = 5
NOISE_BAND_CAP = 0.25


def platform_noise_band(records, platform: Optional[str],
                        metric: Optional[str]) -> Optional[float]:
    """The fraction below which a same-platform regression is noise.

    ``BENCH_GATE_NOISE`` overrides (``0`` disables). Otherwise: 2x the
    relative sample stddev of the last ``NOISE_WINDOW`` clean
    (``rc == 0``) same-platform, same-headline-metric,
    headline-bearing records, capped at ``NOISE_BAND_CAP``; fewer than
    2 samples (or no declared platform) = no band (None).
    """
    env = os.environ.get("BENCH_GATE_NOISE")
    if env is not None:
        try:
            band = float(env)
        except ValueError:
            return None
        return band if band > 0 else None
    if not platform:
        return None
    vals = []
    for rec in records or []:
        if rec.get("rc") != 0:
            continue
        if history_mod.record_platform(rec) != platform:
            continue
        head = (rec.get("result") or {}).get("headline") or {}
        if metric and head.get("metric") and head["metric"] != metric:
            continue
        value = head.get("value")
        if is_number(value) and value > 0:
            vals.append(float(value))
    vals = vals[-NOISE_WINDOW:]
    if len(vals) < 2:
        return None
    mean = sum(vals) / len(vals)
    if not mean:
        return None
    var = sum((v - mean) ** 2 for v in vals) / (len(vals) - 1)
    rel = (var ** 0.5) / mean
    band = min(2.0 * rel, NOISE_BAND_CAP)
    return band or None


def gate_threshold() -> float:
    try:
        return float(os.environ.get("BENCH_GATE_THRESHOLD",
                                    DEFAULT_THRESHOLD))
    except ValueError:
        return DEFAULT_THRESHOLD


def gate_enabled() -> bool:
    return os.environ.get("BENCH_GATE", "1") != "0"


def run_gate(fresh_result: Dict[str, Any],
             history_path: Optional[str] = None,
             threshold: Optional[float] = None
             ) -> Tuple[int, Dict[str, Any]]:
    """Compare ``fresh_result`` against the latest comparable history
    record. Returns ``(exit_code, gate_info)`` where ``gate_info`` is the
    JSON-embeddable verdict (baseline id, threshold, regression list).
    Never raises — an unreadable history is a GATE_ERROR verdict, not a
    crash in the middle of a bench run."""
    threshold = gate_threshold() if threshold is None else threshold
    info: Dict[str, Any] = {"threshold": threshold, "ok": True,
                            "baseline": None, "regressions": []}
    if not gate_enabled():
        info["disabled"] = True
        return GATE_OK, info
    try:
        fresh_head = fresh_result.get("headline") or {}
        fresh_platform = fresh_head.get("platform")
        fresh_metric = fresh_head.get("metric")
        # two-tier gate-grade baseline selection: prefer the latest
        # HEADLINE-bearing record (arms the headline gate); only if none
        # exists fall back to the latest record with non-noisy comparable
        # entries. Without the tiers, a recovered entries-only round
        # (r05: headline unrecoverable, gateable lane = comm_bw_onchip)
        # shadows the last headline-bearing round and the headline gate
        # silently never fires again.
        #
        # Platform matching is STRICT when the fresh run declares one:
        # the legacy r01–r05 records predate the platform field, and a
        # fresh CPU-box run numeric-compared against a TPU-round headline
        # reads as a fake -99%. A platform-less record is evidence for an
        # explicit bench-diff, not an automated-gate baseline; the gate
        # re-arms one round after the first platform-stamped record.
        fresh_plat = (fresh_platform
                      if isinstance(fresh_platform, str) else None)

        def strict(pred):
            if not fresh_plat:
                return pred
            return lambda rec: (history_mod.record_platform(rec)
                                == fresh_plat and pred(rec))

        records, _ = history_mod.load_history(history_path)
        select = dict(
            records=records, exclude_failed=True,
            metric=fresh_metric
            if isinstance(fresh_metric, str) else None)
        baseline = history_mod.latest_record(
            predicate=strict(_has_headline), **select)
        if baseline is None:
            baseline = history_mod.latest_record(
                predicate=strict(_has_gateable_entries), **select)
        if baseline is None:
            info["note"] = "no comparable baseline in bench_history"
            return GATE_OK, info
        label = baseline.get("round") or baseline.get("source") or "baseline"
        diff = diff_results(baseline["result"], fresh_result,
                            threshold=threshold,
                            old_label=str(label), new_label="fresh")
        gated = [r for r in diff["regressions"]
                 if r.get("where") not in NOISY_ENTRIES]
        ignored = len(diff["regressions"]) - len(gated)
        # per-platform noise band: a numeric regression whose magnitude
        # sits inside the lane's own measured round-to-round noise floor
        # WARNS (noise_within_band) instead of failing the run; error
        # transitions (delta_frac None) always gate — an error is never
        # noise
        band = platform_noise_band(records, fresh_plat,
                                   fresh_metric
                                   if isinstance(fresh_metric, str)
                                   else None)
        if band:
            info["noise_band"] = round(band, 4)
            within = [r for r in gated
                      if r.get("delta_frac") is not None
                      and abs(r["delta_frac"]) <= band]
            if within:
                gated = [r for r in gated if r not in within]
                info["noise_within_band"] = within
        info.update({
            "baseline": label,
            "baseline_recovered": bool(baseline.get("recovered")),
            "regressions": gated,
            "improvements_count": len(diff["improvements"]),
            "ok": not gated,
        })
        if ignored:
            info["noisy_regressions_ignored"] = ignored
        attributions = []
        if diff["headline"].get("attribution"):
            attributions.append(diff["headline"]["attribution"]["summary"])
        # same filter as the verdict: a noisy lane's phase must not be
        # blamed for a gate failure it was excluded from
        attributions += [e["attribution"]["summary"]
                         for name, e in diff["entries"].items()
                         if e.get("attribution")
                         and name not in NOISY_ENTRIES]
        if attributions:
            info["attribution"] = attributions
        return (GATE_OK if not gated else GATE_REGRESSED), info
    except Exception as e:
        info.update({"ok": False, "error": f"{type(e).__name__}: {e}"})
        return GATE_ERROR, info
